#!/usr/bin/env python
"""Pod-scale streaming demo: converge N docs on carried device state.

One collaborative editing session (3 replicas, fuzz-generated) is streamed
to N independent documents as binary wire frames over two arrival rounds —
the config-5 shape of BASELINE.md.  Ingest takes the frame-native fast path
(C++ parse + one-call round scheduling); reads and the convergence digest
resolve the doc axis in memory-bounded blocks, so N scales to 100K docs on
a single chip (BASELINE.md row 5b: 22.6M ops converged on-device in ~2 minutes wall (see BASELINE.md row 5b for the recorded numbers),
zero fallbacks or overflows).

Run: python demos/scale_demo.py [--docs N]   (default 2000; try 100000 on TPU)
"""

import argparse
import os
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--docs", type=int, default=2000)
    parser.add_argument("--ops-per-doc", type=int, default=220)
    parser.add_argument("--seed", type=int, default=200)
    args = parser.parse_args()

    # Default to CPU: the harness PRESETS JAX_PLATFORMS to the TPU plugin,
    # so honoring it blindly hangs when the tunnel is down.  Opt into the
    # device platform explicitly with PT_DEMO_PLATFORM=tpu.  Env var AND
    # config must both be pinned (the plugin re-asserts at config level).
    platform = os.environ.get("PT_DEMO_PLATFORM") or "cpu"
    os.environ["JAX_PLATFORMS"] = platform
    import jax

    jax.config.update("jax_platforms", platform)

    from peritext_tpu.api.batch import _oracle_doc
    from peritext_tpu.parallel.codec import encode_frame
    from peritext_tpu.parallel.streaming import StreamingMerge
    from peritext_tpu.testing.fuzz import generate_workload

    d = args.docs
    w = generate_workload(seed=args.seed, num_docs=1, ops_per_doc=args.ops_per_doc)[0]
    changes = [ch for log in w.values() for ch in log]
    half = len(changes) // 2
    frames = [encode_frame(changes[:half]), encode_frame(changes[half:])]
    expected = _oracle_doc(w).get_text_with_formatting(["text"])
    total_ops = sum(len(c.ops) for c in changes) * d
    print(f"{d} docs x {sum(len(c.ops) for c in changes)} ops "
          f"({total_ops / 1e6:.1f}M total), 2 arrival rounds of wire frames\n")

    sess = StreamingMerge(
        num_docs=d, actors=("doc1", "doc2", "doc3"),
        slot_capacity=512, mark_capacity=160, tomb_capacity=192,
        round_insert_capacity=192, round_delete_capacity=96,
        round_mark_capacity=96,
    )
    t_all = time.perf_counter()
    pending = None
    for r, frame in enumerate(frames):
        if pending is not None:
            # fetch LAST round's digest BEFORE this round's ingest mutates
            # any change history (digest_async's documented precondition for
            # sessions that could hold fallback/overflow docs); the fetch is
            # scalar + overflow only, and the device computed it behind the
            # queue while round r-1's host work finished
            pending.wait()
        t0 = time.perf_counter()
        sess.ingest_frames((doc, frame) for doc in range(d))
        t_ing = time.perf_counter() - t0
        t0 = time.perf_counter()
        sess.drain()
        t_drain = time.perf_counter() - t0
        t0 = time.perf_counter()
        pending = sess.digest_async()  # per-round convergence sync point
        t_sched = time.perf_counter() - t0
        print(f"round {r}: ingest {t_ing:.1f}s, device rounds {t_drain:.1f}s, "
              f"digest scheduled in {t_sched * 1000:.0f}ms (async)")
    wall = time.perf_counter() - t_all

    t0 = time.perf_counter()
    digest = pending.wait()
    t_digest = time.perf_counter() - t0
    assert digest == sess.digest(), "async digest != sync digest"
    for doc in (0, d // 2, d - 1):
        assert sess.read(doc) == expected, f"doc {doc} diverged"
    assert not any(s.fallback for s in sess.docs), "docs demoted to scalar replay"
    # overflowed docs silently read via scalar replay and are masked from the
    # digest — the demo's claim is DEVICE convergence, so none may overflow
    assert sess.overflow_count() == 0, (
        f"{sess.overflow_count()} docs overflowed device capacities"
    )

    # full-sweep reads: every doc's spans and incremental patches in one
    # vectorized pass per block (decode_block_spans / block_char_states)
    t0 = time.perf_counter()
    all_spans = sess.read_all()
    t_read = time.perf_counter() - t0
    assert all(s == expected for s in all_spans), "full-sweep read diverged"
    t0 = time.perf_counter()
    n_patches = sum(len(p) for p in sess.read_patches_all())
    t_patches = time.perf_counter() - t0

    print(f"\nconverged ON DEVICE: digest {digest:#010x} "
          f"(final wait {t_digest:.2f}s; per-round sync is the async schedule above)")
    print(f"{total_ops / 1e6:.1f}M ops in {wall:.1f}s "
          f"({total_ops / wall / 1e3:.0f}K ops/s end-to-end incl. host ingest)")
    print(f"full span sweep {t_read:.1f}s, full patch sweep {t_patches:.1f}s "
          f"({n_patches} patches) across {d} docs")
    print("ALL docs verified against the scalar oracle; 0 fallbacks")


if __name__ == "__main__":
    main()
