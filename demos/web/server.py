#!/usr/bin/env python
"""Two live editors against the TPU merge backend, in a browser.

The reference ships its two-editor demo on ProseMirror in the browser
(``/root/reference/src/index.ts:122-126``, ``index.html:41``).  This is the
framework's equivalent: a dependency-free page (demos/web/index.html) with two
editable panes talking to this server, which hosts two ``bridge.Editor``
instances on the ``tpu`` backend sharing an in-memory ``Publisher`` — the
exact replication topology of the reference demo, including the manual Sync
button (changes queue locally until synced, then anti-entropy merges both
ways).

Run:  python demos/web/server.py [--port 8700] [--backend tpu|scalar]
then open http://localhost:8700/
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[2]))

from peritext_tpu.bridge.bridge import create_editor, initialize_docs
from peritext_tpu.parallel.pubsub import Publisher

_HERE = Path(__file__).parent


def describe_op(editor: str, op: dict) -> str:
    """One-line op description for the debug log panel (the reference
    renders the same log into the demo DOM — ``describeOp``,
    src/bridge.ts:96-110, ``outputDebugForChange`` :235-242)."""
    action = op.get("action")
    if action == "insert":
        return f'{editor}: insert {"".join(op.get("values", []))!r} at {op.get("index")}'
    if action == "delete":
        return f'{editor}: delete {op.get("count")} at {op.get("index")}'
    if action in ("addMark", "removeMark"):
        attrs = op.get("attrs")
        extra = f" {attrs}" if attrs else ""
        return (f'{editor}: {action} {op.get("markType")} '
                f'[{op.get("startIndex")}, {op.get("endIndex")}){extra}')
    return f"{editor}: {action}"


class Session:
    """The two editors plus a lock (bridge editors are single-threaded)."""

    def __init__(self, backend: str = "tpu") -> None:
        self.lock = threading.Lock()
        self.pub = Publisher()
        self.oplog: list = []
        actors = ("alice", "bob", "init")
        self.editors = {
            "alice": create_editor("alice", self.pub, backend=backend, actors=actors),
            "bob": create_editor("bob", self.pub, backend=backend, actors=actors),
        }
        initialize_docs(
            [self.editors["alice"], self.editors["bob"]],
            "The Peritext editor",
        )

    def state(self) -> dict:
        return {
            **{
                name: {
                    "spans": ed.view.spans(),
                    "pending": len(ed.queue) if hasattr(ed, "queue") else 0,
                }
                for name, ed in self.editors.items()
            },
            "oplog": list(self.oplog),
        }

    def _log(self, line: str) -> None:
        self.oplog.append(line)
        del self.oplog[:-12]

    def dispatch(self, editor: str, ops) -> None:
        self.editors[editor].dispatch_input_ops(ops)
        for op in ops:
            self._log(describe_op(editor, op))

    def sync(self) -> None:
        had_pending = any(len(ed.queue) for ed in self.editors.values())
        for ed in self.editors.values():
            ed.sync()
        if had_pending:  # auto-sync no-ops must not flush real ops out of the log
            self._log("-- sync: queues flushed both ways --")


SESSION: Session = None  # set in main()


class Handler(BaseHTTPRequestHandler):
    def _json(self, payload, status=200):
        body = json.dumps(payload).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        if self.path in ("/", "/index.html"):
            body = (_HERE / "index.html").read_bytes()
            self.send_response(200)
            self.send_header("Content-Type", "text/html; charset=utf-8")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        elif self.path == "/state":
            with SESSION.lock:
                self._json(SESSION.state())
        else:
            self._json({"error": "not found"}, 404)

    def do_POST(self):
        try:
            length = int(self.headers.get("Content-Length", 0))
            payload = json.loads(self.rfile.read(length) or b"{}")
            with SESSION.lock:
                if self.path == "/op":
                    SESSION.dispatch(payload["editor"], payload["ops"])
                elif self.path == "/sync":
                    SESSION.sync()
                else:
                    self._json({"error": "not found"}, 404)
                    return
                self._json(SESSION.state())
        except Exception as exc:  # surface editor errors to the page
            self._json({"error": repr(exc)}, 400)

    def log_message(self, fmt, *args):  # quiet
        pass


def main() -> None:
    global SESSION
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--port", type=int, default=8700)
    parser.add_argument("--backend", default="tpu", choices=("tpu", "scalar"))
    args = parser.parse_args()
    SESSION = Session(backend=args.backend)
    server = ThreadingHTTPServer(("127.0.0.1", args.port), Handler)
    print(f"two-editor demo ({args.backend} backend): http://127.0.0.1:{args.port}/")
    server.serve_forever()


if __name__ == "__main__":
    main()
