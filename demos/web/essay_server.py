#!/usr/bin/env python
"""The scripted essay, playing in a browser (reference ``src/essay-demo.ts``
+ ``essay-demo.html``).

The full-length authored two-author session (demos/essay_content.py, 740
per-keystroke events across 9 sections) plays into two live editor panes:
remote changes FLASH in the receiving pane the way the reference's essay
embed highlights them (``highlightRemoteChanges``, src/essay-demo.ts:47-75),
a play/pause control drives an endless loop (:97-132), and a debug panel
streams per-event op descriptions (the reference renders the same log into
the demo DOM — ``describeOp``, src/bridge.ts:96-110).

The browser owns the clock: it polls ``POST /step {"n": k}`` to advance k
trace events (so play/pause/speed are purely client-side), and the server
replies with both panes' spans, the highlight ranges, the section banner,
and the op log.  When the trace ends the session restarts from a blank doc,
as the reference's endless loop does.

Run:  python demos/web/essay_server.py [--port 8701] [--backend scalar|tpu]
then open http://127.0.0.1:8701/
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[2]))
sys.path.insert(0, str(Path(__file__).resolve().parents[1]))  # essay_content
sys.path.insert(0, str(Path(__file__).resolve().parent))  # sibling server.py

from essay_content import ESSAY_SECTIONS, build_essay_trace  # noqa: E402
from server import describe_op  # noqa: E402  (the shared op formatter)

from peritext_tpu.bridge.bridge import create_editor  # noqa: E402
from peritext_tpu.bridge.playback import execute_trace_event  # noqa: E402
from peritext_tpu.parallel.pubsub import Publisher  # noqa: E402

_HERE = Path(__file__).parent


def describe_event(event: dict) -> str:
    """One-line TRACE-event description for the debug log: the shared op
    formatter (server.describe_op, reference ``describeOp``
    src/bridge.ts:96-110) plus the trace-level sync/restart/makeList cases."""
    action = event.get("action")
    who = event.get("editorId", "")
    if action == "sync":
        return "-- sync: queues flushed both ways --"
    if action == "restart":
        return "-- restart --"
    if action == "makeList":
        return f'{who}: makeList {event.get("key")!r}'
    return describe_op(who, event)


class EssaySession:
    """Trace playback state: two editors, a cursor into the trace, the
    highlight ranges, and the rolling op log."""

    def __init__(self, backend: str = "scalar") -> None:
        self.lock = threading.Lock()
        self.backend = backend
        self.trace = build_essay_trace()
        self.loops = 0
        self._reset()

    def _reset(self) -> None:
        self.pub = Publisher()
        self.highlights: dict = {}
        self.oplog: list = []
        self.pos = 0
        self.sync_count = 0
        kw = {}
        if self.backend == "tpu":
            kw = {"backend": "tpu", "actors": ("alice", "bob")}

        def on_remote_patch(editor, patch):
            if patch["action"] == "insert":
                self.highlights[editor.actor_id] = (
                    patch["index"], patch["index"] + len(patch["values"]))
            elif "startIndex" in patch:
                self.highlights[editor.actor_id] = (
                    patch["startIndex"], patch["endIndex"])

        self.editors = {
            name: create_editor(name, self.pub, on_remote_patch=on_remote_patch, **kw)
            for name in ("alice", "bob")
        }

    def step(self, n: int) -> None:
        for _ in range(max(0, min(n, 200))):
            if self.pos >= len(self.trace):
                # endless loop: restart from a blank doc (reference
                # essay-demo.ts:97-132)
                self.loops += 1
                self._reset()
            event = self.trace[self.pos]
            self.pos += 1
            if event.get("action") == "sync":
                self.highlights.clear()  # flashes replaced by the new sync's
                self.sync_count += 1
            execute_trace_event(event, self.editors)
            self.oplog.append(describe_event(event))
        del self.oplog[:-12]

    def state(self) -> dict:
        section = ESSAY_SECTIONS[
            min(self.sync_count, len(ESSAY_SECTIONS)) - 1
        ] if self.sync_count else "warming up"
        return {
            "editors": {
                name: {"spans": ed.view.spans()} for name, ed in self.editors.items()
            },
            "highlights": dict(self.highlights),
            "section": section,
            "progress": {"event": self.pos, "total": len(self.trace),
                         "loops": self.loops},
            "oplog": list(self.oplog),
            "converged": self.editors["alice"].view == self.editors["bob"].view,
        }


SESSION: EssaySession = None  # set in main() / the test fixture


class Handler(BaseHTTPRequestHandler):
    def _json(self, payload, status=200):
        body = json.dumps(payload).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        if self.path in ("/", "/index.html", "/essay.html"):
            body = (_HERE / "essay.html").read_bytes()
            self.send_response(200)
            self.send_header("Content-Type", "text/html; charset=utf-8")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        elif self.path == "/state":
            with SESSION.lock:
                self._json(SESSION.state())
        else:
            self._json({"error": "not found"}, 404)

    def do_POST(self):
        try:
            length = int(self.headers.get("Content-Length", 0))
            payload = json.loads(self.rfile.read(length) or b"{}")
            with SESSION.lock:
                if self.path == "/step":
                    SESSION.step(int(payload.get("n", 1)))
                elif self.path == "/restart":
                    SESSION.loops += 1
                    SESSION._reset()
                else:
                    self._json({"error": "not found"}, 404)
                    return
                self._json(SESSION.state())
        except Exception as exc:  # surface playback errors to the page
            self._json({"error": repr(exc)}, 400)

    def log_message(self, fmt, *args):  # quiet
        pass


def main() -> None:
    global SESSION
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--port", type=int, default=8701)
    parser.add_argument(
        "--backend", default="scalar", choices=("scalar", "tpu"),
        help="merge backend for the two editors (identical semantics; "
             "scalar keeps per-keystroke playback snappy on CPU-only hosts)",
    )
    args = parser.parse_args()
    SESSION = EssaySession(backend=args.backend)
    server = ThreadingHTTPServer(("127.0.0.1", args.port), Handler)
    print(f"essay demo ({args.backend} backend): http://127.0.0.1:{args.port}/")
    server.serve_forever()


if __name__ == "__main__":
    main()
