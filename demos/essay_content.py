"""The scripted essay session (headless analog of the reference's
``src/essay-demo-content.ts`` — same SHAPE of content: a full-length
two-author writing session with per-keystroke typing, mid-session
corrections, concurrent formatting, conflicting links, coexisting comments
and a restart — with entirely original text).

The trace is built against a shadow copy of the document so every index is
computed, not hand-counted: between synced sections the shadow equals both
replicas; concurrent sections take their indices from the shadow as it stood
at the last sync, exactly the state both authors see when they type.
"""

from __future__ import annotations

from typing import List

from peritext_tpu.bridge.playback import simulate_typing_for_input_op
from peritext_tpu.core.doc import CONTENT_KEY


class _EssayBuilder:
    def __init__(self) -> None:
        self.trace: List[dict] = [
            {"editorId": "alice", "path": [], "action": "makeList",
             "key": CONTENT_KEY, "delay": 0},
            {"action": "sync", "delay": 0},
        ]
        self.text = ""

    # -- synced, shadow-tracked edits --------------------------------------

    def type(self, editor: str, index: int, s: str, delay: int = 24) -> None:
        events = simulate_typing_for_input_op(
            editor, {"action": "insert", "index": index, "values": list(s)}
        )
        for ev in events:
            ev.setdefault("delay", delay)
        self.trace += events
        self.text = self.text[:index] + s + self.text[index:]

    def append(self, editor: str, s: str) -> None:
        self.type(editor, len(self.text), s)

    def delete(self, editor: str, index: int, count: int) -> None:
        self.trace.append(
            {"editorId": editor, "path": [CONTENT_KEY], "action": "delete",
             "index": index, "count": count, "delay": 120}
        )
        self.text = self.text[:index] + self.text[index + count:]

    def mark(self, editor: str, action: str, start: int, end: int,
             mark_type: str, attrs: dict | None = None) -> None:
        ev = {"editorId": editor, "path": [CONTENT_KEY], "action": action,
              "startIndex": start, "endIndex": end, "markType": mark_type,
              "delay": 200}
        if attrs:
            ev["attrs"] = attrs
        self.trace.append(ev)

    def sync(self) -> None:
        self.trace.append({"action": "sync", "delay": 400})

    def find(self, phrase: str) -> tuple:
        """(start, end) of a phrase in the current shadow text."""
        start = self.text.index(phrase)
        return start, start + len(phrase)


def build_essay_trace() -> List[dict]:
    b = _EssayBuilder()

    # ---- alice drafts the opening; bob reads along ----
    b.append("alice",
             "Rich text is a pact among characters about their shared past. ")
    b.sync()
    b.append("alice",
             "Plain text only has to agree on an order; formatted text must "
             "also agree on where every intention begins and ends. ")
    b.sync()

    # ---- bob continues the argument while alice is away ----
    b.append("bob",
             "When two writers touch the same sentence at the same moment, "
             "the letters have to find a single order, and the bold has to "
             "decide whether it grows around the newcomer or lets it stand "
             "plain. ")
    b.sync()

    # ---- alice revises: deletes a hedge, retypes it sharper ----
    start, end = b.find("a pact among characters")
    b.delete("alice", start, end - start)
    b.type("alice", start, "a merge of independent histories")
    b.sync()

    # ---- a third paragraph, typed concurrently with bob's edits ----
    tail = len(b.text)
    b.append("alice",
             "A mark is a promise pinned between two anchors. Each replica "
             "keeps the promise on its own clock, and the anchors ride the "
             "characters wherever concurrent edits carry them. ")
    # bob, concurrently (indices computed against the synced shadow): bolds
    # the thesis and italicizes an overlapping stretch
    s1, e1 = b.find("a single order")
    b.mark("bob", "addMark", s1, e1, "strong")
    s2, e2 = b.find("order, and the bold")
    b.mark("bob", "addMark", s2, e2, "em")
    b.sync()

    # ---- conflicting links over the same phrase: LWW picks one ----
    s3, e3 = b.find("independent histories")
    b.mark("alice", "addMark", s3, e3, "link",
           {"url": "https://crdt.tech"})
    b.mark("bob", "addMark", s3 + 4, e3, "link",
           {"url": "https://www.inkandswitch.com/peritext/"})
    b.sync()

    # ---- comments coexist where links fight ----
    s4, e4 = b.find("promise pinned between two anchors")
    b.mark("alice", "addMark", s4, e4, "comment", {"id": "essay-alice-1"})
    b.mark("bob", "addMark", s4, s4 + 7, "comment", {"id": "essay-bob-1"})
    b.sync()

    # ---- closing paragraph; bob then withdraws his comment ----
    b.append("bob",
             "Convergence is not agreement about intent. It is the narrower, "
             "sturdier guarantee that after every message arrives, both "
             "writers read the same page. ")
    b.mark("bob", "removeMark", s4, s4 + 7, "comment", {"id": "essay-bob-1"})
    b.sync()

    # ---- a final flourish: emphasis over the close, then loop ----
    s5, e5 = b.find("both writers read the same page")
    b.mark("alice", "addMark", s5, e5, "em")
    b.sync()
    b.trace.append({"action": "restart", "delay": 1500})
    return b.trace


#: sections in sync order, for the demo's narration
ESSAY_SECTIONS = [
    "alice drafts the opening",
    "plain vs formatted text",
    "bob continues the argument",
    "alice revises a phrase",
    "concurrent typing + overlapping bold/italic",
    "conflicting links (LWW)",
    "comments coexist",
    "closing paragraph; a comment withdrawn",
    "final emphasis",
]
