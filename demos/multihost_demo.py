#!/usr/bin/env python
"""Multi-host convergence demo: three hosts, real sockets, one shared doc.

Each "host" owns one collaborating actor of a fuzz-generated editing session:
its own append-only ChangeStore, a TCP anti-entropy endpoint
(parallel/multihost.py) speaking binary codec frames, and its own device
merge session (parallel/streaming.py) fed raw wire bytes through the
server's on_frame hook (frame-native ingest — no Python Change objects on
the device path; on_changes only counts deliveries for the quiescence
check).  Gossip rounds around the ring converge all three stores, and each
host's device state converges to the same digest — the multi-host analog of
the reference's in-memory Publisher + getMissingChanges sync
(src/pubsub.ts, test/merge.ts), with DCN traffic carrying only change
frames while per-op CRDT work stays on each host's chips.

Run: python demos/multihost_demo.py
"""

import os
import sys
import threading
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

ACTORS = ("doc1", "doc2", "doc3")


class Host:
    """One simulated host: store + TCP endpoint + device merge session."""

    def __init__(self, name: str, actor: str, workload):
        from peritext_tpu.parallel import ChangeStore, ReplicaServer
        from peritext_tpu.parallel.codec import encode_frame
        from peritext_tpu.parallel.streaming import StreamingMerge

        self.name = name
        self.actor = actor
        self.store = ChangeStore()
        self.session = StreamingMerge(
            num_docs=1, actors=ACTORS, slot_capacity=512, mark_capacity=128
        )
        self._ingest_lock = threading.Lock()
        self._delivered = 0
        own = workload.get(actor, [])
        for change in own:
            self.store.append(change)
        if own:
            self._ingest_frame(encode_frame(own), len(own))
        # wire bytes flow straight into the device session (on_frame): no
        # Python Change objects on the hot ingest path; on_changes only
        # counts deliveries for the quiescence check
        self.server = ReplicaServer(
            self.store,
            on_changes=self._count,
            on_frame=lambda frame: self._ingest_frame(frame, 0),
        )
        self.address = self.server.start()

    def _count(self, changes):
        with self._ingest_lock:
            self._delivered += len(changes)

    def _ingest_frame(self, frame, count):
        with self._ingest_lock:
            self._delivered += count
            self.session.ingest_frame(0, frame)
            self.session.drain()

    def digest(self) -> int:
        with self._ingest_lock:
            return self.session.digest()

    def settled(self) -> bool:
        """True once every change in the store has been delivered to the
        device session (the server's on_changes hook runs on its handler
        thread, so ingestion trails sync_with returning).  Counts deliveries
        rather than comparing clocks: the session may legitimately hold back
        causally incomplete changes mid-gossip."""
        in_store = sum(len(self.store.log(a)) for a in self.store.actors())
        with self._ingest_lock:
            return self._delivered == in_store

    def text(self) -> str:
        with self._ingest_lock:
            return "".join(s["text"] for s in self.session.read(0))

    def stop(self):
        self.server.stop()


def _wait_settled(hosts, timeout: float = 10.0) -> None:
    deadline = time.monotonic() + timeout
    while not all(h.settled() for h in hosts):
        if time.monotonic() > deadline:  # pragma: no cover
            raise RuntimeError("hosts failed to ingest synced changes in time")
        time.sleep(0.01)


def main() -> None:
    # Pick the platform BEFORE any backend initializes (a default_backend()
    # probe would itself initialize backends, making the update a no-op).
    # Default to CPU: the harness environment PRESETS JAX_PLATFORMS to the
    # TPU plugin, so honoring it blindly would hang the demo whenever the
    # tunnel is down — opt into a device platform explicitly with
    # PT_DEMO_PLATFORM=tpu.  BOTH the env var and the config entry must be
    # pinned (the TPU plugin re-asserts itself at config level).
    platform = os.environ.get("PT_DEMO_PLATFORM") or "cpu"
    os.environ["JAX_PLATFORMS"] = platform
    import jax

    jax.config.update("jax_platforms", platform)

    from peritext_tpu.api.batch import _oracle_doc
    from peritext_tpu.testing.fuzz import generate_workload

    workload = generate_workload(seed=33, num_docs=1, ops_per_doc=150)[0]

    # Each actor additionally sets per-host MAP state (a metadata key under
    # the root map): the convergence digest is full-state, so the gossip
    # loop below provably synchronizes map registers, not just text+marks.
    from peritext_tpu.core.opids import ROOT
    from peritext_tpu.core.types import Change, Operation

    for actor in ACTORS:
        log = workload.setdefault(actor, [])
        next_op = max(
            [ch.start_op + len(ch.ops) for ch in log], default=1
        )
        log.append(Change(
            actor=actor, seq=len(log) + 1, deps={}, start_op=next_op,
            ops=[Operation(action="set", obj=ROOT, opid=(next_op, actor),
                           key=f"edited-by-{actor}", value=True)],
        ))

    total = sum(len(log) for log in workload.values())
    print(f"session: {total} changes by {len(ACTORS)} actors, one host each\n")

    hosts = [Host(f"host{i}", actor, workload) for i, actor in enumerate(ACTORS)]
    try:
        for h in hosts:
            print(f"{h.name} ({h.actor}) @ {h.address[0]}:{h.address[1]} "
                  f"digest={h.digest():#010x}")

        round_no = 0
        while len({h.digest() for h in hosts}) > 1:
            round_no += 1
            print(f"\n-- gossip round {round_no} (ring) --")
            for i, h in enumerate(hosts):
                peer = hosts[(i + 1) % len(hosts)]
                pulled, pushed = h.server.sync_with(*peer.address)
                print(f"{h.name} <-> {peer.name}: pulled {pulled}, pushed {pushed}")
            # pushed changes are ingested on the receiving server's handler
            # thread; wait for quiescence before reading digests
            _wait_settled(hosts)
            for h in hosts:
                print(f"{h.name} digest={h.digest():#010x} "
                      f"frontier={h.store.clock()}")
            if round_no > 5:
                raise RuntimeError("gossip failed to converge")

        digests = {h.digest() for h in hosts}
        assert len(digests) == 1, digests
        expected = _oracle_doc(workload).get_text_with_formatting(["text"])
        expected_text = "".join(s["text"] for s in expected)
        meta_keys = {f"edited-by-{a}" for a in ACTORS}
        for h in hosts:
            assert h.text() == expected_text, h.name
            # the full-state digest above already proves map convergence;
            # read back the registers as direct evidence too
            root = h.session.read_root(0)
            assert meta_keys <= set(root), (h.name, root)
        print(f"\nall hosts converged after {round_no} gossip rounds "
              f"(digest covers text+marks+map; every host sees {sorted(meta_keys)})")
        print(f"shared digest: {hosts[0].digest():#010x}")
        print(f"document ({len(expected_text)} chars): {expected_text[:70]!r}...")
    finally:
        for h in hosts:
            h.stop()


if __name__ == "__main__":
    main()
