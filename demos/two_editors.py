#!/usr/bin/env python
"""Two-editor demo (headless analog of the reference ``src/index.ts``).

Two collaborative editors, alice and bob, edit concurrently; changes buffer
in per-editor outbound queues and only cross when you sync — exactly the
reference demo's manual Sync button (src/index.ts:122-126).  This script
scripts a short session and prints each editor's text, span structure, and
the structured change log at every stage.

Run: python demos/two_editors.py
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from peritext_tpu.bridge import EditorEvent, create_editor, initialize_docs
from peritext_tpu.bridge.commands import (
    add_comment,
    set_link,
    toggle_bold,
    toggle_italic,
    type_text,
)
from peritext_tpu.parallel.pubsub import Publisher


def render(editor) -> str:
    parts = []
    for span in editor.view.spans():
        text, marks = span["text"], span["marks"]
        if not marks:
            parts.append(text)
        else:
            names = ",".join(sorted(marks))
            parts.append(f"[{text}]({names})")
    return "".join(parts)


def show(editors, label) -> None:
    print(f"\n== {label} ==")
    for editor in editors:
        print(f"  {editor.actor_id}: {render(editor)}")


def main() -> None:
    import argparse

    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "--backend", choices=("scalar", "tpu"), default="scalar",
        help="merge backend for the editor views: 'tpu' drives them from the "
             "batched device engine's incremental patch stream",
    )
    args = parser.parse_args()
    if args.backend == "tpu":
        import os

        # Default to CPU: the harness PRESETS JAX_PLATFORMS to the TPU
        # plugin, so honoring it blindly hangs when the tunnel is down.
        # Opt into the device platform with PT_DEMO_PLATFORM=tpu.
        platform = os.environ.get("PT_DEMO_PLATFORM") or "cpu"
        os.environ["JAX_PLATFORMS"] = platform
        import jax

        jax.config.update("jax_platforms", platform)

    events = []
    publisher = Publisher()
    kw = dict(on_event=events.append)
    if args.backend == "tpu":
        kw.update(backend="tpu", actors=("alice", "bob"))
    alice = create_editor("alice", publisher, **kw)
    bob = create_editor("bob", publisher, **kw)
    initialize_docs([alice, bob], "The Peritext editor")
    show([alice, bob], f"seeded (shared origin change; {args.backend} backend)")

    # concurrent edits: nothing crosses until a sync
    type_text(alice, 1, "Hey! ")
    toggle_bold(bob, 5, 13)
    show([alice, bob], "concurrent edits, not yet synced")

    alice.sync()
    bob.sync()
    show([alice, bob], "after sync")

    # overlapping formatting + a link + a comment, then partition bob
    toggle_italic(alice, 10, 24)
    set_link(bob, 14, 22, "https://www.inkandswitch.com/peritext/")
    bob.disconnect()
    type_text(bob, 1, "(offline) ")
    show([alice, bob], "bob offline with local edits")

    alice.sync()
    bob.sync()  # manual flush still works after drop()
    add_comment(alice, 1, 10, comment_id="c-demo")
    alice.sync()
    show([alice, bob], "after reconnect + comment")

    assert alice.view == bob.view, "editors diverged!"
    print("\nconverged: both editors show identical marked text")
    print(f"events logged: {len(events)}")
    for ev in events[-4:]:
        print(f"  {ev.actor}: {ev.kind} {ev.detail}")


if __name__ == "__main__":
    main()
