#!/usr/bin/env python
"""Scripted playback demo (headless analog of the reference
``src/essay-demo.ts`` + ``src/essay-demo-content.ts``).

Plays a scripted trace through two editors: simulated per-keystroke typing,
concurrent formatting that overlaps after sync, conflicting links resolved
last-writer-wins, and co-existing comments.  Remote changes are highlighted
the way the reference's essay embed flashes them (``highlightRemoteChanges``,
src/essay-demo.ts:47-75): the receiving editor records the affected range and
the renderer shows it underlined.

Run:  python demos/essay_demo.py [--realtime] [--loop N]
"""

import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from peritext_tpu.bridge import create_editor
from peritext_tpu.bridge.playback import (
    execute_trace_event,
    simulate_typing_for_input_op,
    trace_from_spec,
)
from peritext_tpu.core.doc import CONTENT_KEY
from peritext_tpu.parallel.pubsub import Publisher

ANSI = {
    "strong": "\x1b[1m",
    "em": "\x1b[3m",
    "link": "\x1b[36m",
    "comment": "\x1b[43m",
    "highlight": "\x1b[4m",
    "reset": "\x1b[0m",
}


def build_trace():
    """The demo script: each section exercises one Peritext behavior."""
    trace = [
        {"editorId": "alice", "path": [], "action": "makeList", "key": CONTENT_KEY, "delay": 0},
        {"action": "sync", "delay": 0},
    ]

    def typing(editor_id, index, text):
        return simulate_typing_for_input_op(
            editor_id, {"action": "insert", "index": index, "values": list(text)}
        )

    # 1. typing syncs live between the two editors
    trace += typing("alice", 0, "Formatting survives concurrent edits.")
    trace.append({"action": "sync"})
    # 2. concurrent bold and italic overlap cleanly after sync
    #     0123456789012345678901234567890123456
    trace += [
        {"editorId": "alice", "action": "addMark", "path": [CONTENT_KEY],
         "startIndex": 0, "endIndex": 10, "markType": "strong"},
        {"editorId": "bob", "action": "addMark", "path": [CONTENT_KEY],
         "startIndex": 5, "endIndex": 19, "markType": "em"},
        {"action": "sync"},
    ]
    # 3. concurrent overlapping links: one writer wins deterministically
    trace += [
        {"editorId": "alice", "action": "addMark", "path": [CONTENT_KEY],
         "startIndex": 20, "endIndex": 30, "markType": "link",
         "attrs": {"url": "https://crdt.tech"}},
        {"editorId": "bob", "action": "addMark", "path": [CONTENT_KEY],
         "startIndex": 25, "endIndex": 36, "markType": "link",
         "attrs": {"url": "https://inkandswitch.com"}},
        {"action": "sync"},
    ]
    # 4. comments co-exist where links conflict
    trace += [
        {"editorId": "alice", "action": "addMark", "path": [CONTENT_KEY],
         "startIndex": 0, "endIndex": 10, "markType": "comment",
         "attrs": {"id": "comment-alice"}},
        {"editorId": "bob", "action": "addMark", "path": [CONTENT_KEY],
         "startIndex": 5, "endIndex": 19, "markType": "comment",
         "attrs": {"id": "comment-bob"}},
        {"action": "sync"},
        {"action": "restart"},
    ]
    return trace


def make_editors(publisher, highlights):
    def on_remote_patch(editor, patch):
        # record flashed ranges like the essay embed's highlight marks
        if patch["action"] == "insert":
            highlights[editor.actor_id] = (patch["index"], patch["index"] + len(patch["values"]))
        elif "startIndex" in patch:
            highlights[editor.actor_id] = (patch["startIndex"], patch["endIndex"])

    return {
        name: create_editor(name, publisher, on_remote_patch=on_remote_patch)
        for name in ("alice", "bob")
    }


def render(editor, highlight=None) -> str:
    out, index = [], 0
    for span in editor.view.spans():
        codes = "".join(ANSI[m] for m in sorted(span["marks"]) if m in ANSI)
        for ch in span["text"]:
            h = ANSI["highlight"] if highlight and highlight[0] <= index < highlight[1] else ""
            out.append(f"{codes}{h}{ch}{ANSI['reset']}" if (codes or h) else ch)
            index += 1
    return "".join(out)


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--realtime", action="store_true", help="honor event delays")
    parser.add_argument("--loop", type=int, default=1, help="play the trace N times")
    parser.add_argument(
        "--short", action="store_true",
        help="play the short pedagogical trace instead of the full scripted "
             "essay session (demos/essay_content.py)",
    )
    args = parser.parse_args()

    publisher = Publisher()
    highlights = {}
    editors = make_editors(publisher, highlights)

    if args.short:
        section_names = ["typing", "concurrent bold+italic overlap",
                         "conflicting links (LWW)", "comments co-exist"]
    else:
        from essay_content import ESSAY_SECTIONS

        section_names = ESSAY_SECTIONS
    sections = iter(section_names)

    def on_sync():
        label = next(sections, "sync")
        print(f"\n-- sync: {label} --")
        # flush happens after this hook, so render post-event below

    if args.short:
        trace = build_trace()
    else:
        from essay_content import build_essay_trace

        trace = build_essay_trace()
    for _ in range(args.loop):
        for event in trace:
            execute_trace_event(event, editors, on_sync=on_sync, realtime=args.realtime)
            if event.get("action") == "sync":
                for name, editor in editors.items():
                    print(f"  {name}: {render(editor, highlights.get(name))}")

    alice, bob = editors["alice"], editors["bob"]
    assert alice.view == bob.view, "demo editors diverged"
    link_urls = {
        str(m.get("link", {}).get("url"))
        for m in alice.view.marks
        if "link" in m
    }
    print(f"\nconverged. winning link(s): {sorted(link_urls)}")
    print("spans:", alice.view.spans())


if __name__ == "__main__":
    main()
