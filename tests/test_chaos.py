"""Fault-domain supervisor tests: per-doc quarantine, guarded device rounds,
resilient transport, and the composed chaos harness (ISSUE 1).

The long soak (20+ seeds) is ``slow``; a one-campaign smoke rides tier-1.
"""

import random

import pytest

from peritext_tpu.api.batch import _oracle_doc, oracle_merge
from peritext_tpu.core.errors import (
    DecodeError,
    DeviceRoundError,
    TransportError,
)
from peritext_tpu.parallel.codec import decode_frame, encode_frame
from peritext_tpu.parallel.faults import (
    FaultSpec,
    corrupt_detectably,
    perturb_frame,
)
from peritext_tpu.parallel.streaming import REASON_DECODE, REASON_DEVICE_ROUND
from peritext_tpu.parallel.supervisor import GuardedSession
from peritext_tpu.testing.chaos import _StallingPeer, run_campaign, run_chaos
from peritext_tpu.testing.fuzz import _campaign_session, generate_workload

DOCS, OPS = 4, 25


def _frames_for(workload, rng, chunk=7):
    changes = [ch for log in workload.values() for ch in log]
    rng.shuffle(changes)
    return [encode_frame(changes[i:i + chunk]) for i in range(0, len(changes), chunk)]


# ---------------------------------------------------------------------------
# codec surface: corruption is typed, contained, and never hangs
# ---------------------------------------------------------------------------


class TestCorruptFrames:
    def test_decode_raises_only_decode_error(self):
        workload = generate_workload(seed=11, num_docs=1, ops_per_doc=40)[0]
        frame = encode_frame([c for log in workload.values() for c in log])
        rng = random.Random(7)
        spec = FaultSpec(truncate_p=0.5, bitflip_p=0.9)
        rejected = 0
        for _ in range(200):
            bad = perturb_frame(frame, rng, spec)
            try:
                decode_frame(bad)
            except DecodeError:
                rejected += 1  # the one documented failure mode
            # any other exception type fails the test by propagating
        assert rejected > 50  # the mutator really does corrupt frames

    def test_ingest_never_crashes_always_quarantines_with_reason(self):
        """Fuzz: corrupted frames through ``ingest_frame`` must never raise
        (quarantine mode), never hang, always tag the doc with a typed
        ``decode`` reason, and never block the session's device rounds."""
        rng = random.Random(13)
        workloads = generate_workload(seed=13, num_docs=DOCS, ops_per_doc=OPS)
        sess = _campaign_session(DOCS, OPS)
        spec = FaultSpec(truncate_p=0.4, bitflip_p=0.8)
        corrupted_docs = set()
        for d, w in enumerate(workloads):
            for frame in _frames_for(w, rng):
                bad = perturb_frame(frame, rng, spec)
                try:
                    decode_frame(bad)
                except ValueError:
                    corrupted_docs.add(d)
                sess.ingest_frame(d, bad, on_corrupt="quarantine")
                if rng.random() < 0.3:
                    sess.step()
        assert corrupted_docs, "mutator produced no corruption; test is vacuous"
        quarantined = sess.quarantined()
        # every doc that received a corrupt frame (and no clean one after)
        # is quarantined as decode; docs quarantine ONLY via typed reasons
        for d, record in quarantined.items():
            assert record.reason in (REASON_DECODE, "capacity", "schedule", "encode")
        assert any(r.reason == REASON_DECODE for r in quarantined.values())
        sess.drain()  # healthy docs' rounds proceed; no exception, no hang

    def test_decode_quarantine_auto_readmits_after_clean_redelivery(self):
        rng = random.Random(5)
        workload = generate_workload(seed=5, num_docs=1, ops_per_doc=OPS)[0]
        frames = _frames_for(workload, rng)
        sess = _campaign_session(1, OPS)
        sess.ingest_frame(0, frames[0][: len(frames[0]) // 2],
                          on_corrupt="quarantine")
        assert sess.quarantined()[0].reason == REASON_DECODE
        # anti-entropy repair: the full clean history re-admits + converges —
        # but only once the doc also DRAINS clean (a clean delivery alone is
        # not proof the gap closed while work is still pending)
        sess.ingest_frames([(0, f) for f in frames])
        assert sess.quarantined()[0].clean_delivery
        sess.drain()
        assert 0 not in sess.quarantined()
        expected = _oracle_doc(workload).get_text_with_formatting(["text"])
        assert sess.read(0) == expected

    def test_demotion_escalates_over_decode_quarantine(self):
        """A demotion-class fault overwrites a ``decode`` record, so a later
        clean delivery cannot lift the quarantine of a doc that is really
        sitting on the scalar path for a device-round reason."""
        sess = _campaign_session(1, OPS)
        sess.ingest_frame(0, b"junkjunkjunk", on_corrupt="quarantine")
        assert sess.quarantined()[0].reason == REASON_DECODE
        sess.force_fallback(0, REASON_DEVICE_ROUND, "supervisor demotion")
        assert sess.quarantined()[0].reason == REASON_DEVICE_ROUND
        workload = generate_workload(seed=43, num_docs=1, ops_per_doc=10)[0]
        frame = encode_frame([c for log in workload.values() for c in log])
        sess.ingest_frame(0, frame, on_corrupt="quarantine")
        assert sess.quarantined()[0].reason == REASON_DEVICE_ROUND
        sess.drain()
        expected = _oracle_doc(workload).get_text_with_formatting(["text"])
        assert sess.read(0) == expected  # degraded, still correct

    def test_faulty_publisher_exercises_codec_and_repairs(self):
        """Payload faults route every delivery through the real wire codec;
        detectably-corrupt messages are lost-and-recorded, and redelivery
        (the anti-entropy analog) reconverges the editors."""
        from peritext_tpu.bridge import create_editor, initialize_docs
        from peritext_tpu.bridge.commands import type_text
        from peritext_tpu.parallel.faults import FaultyPublisher

        spec = FaultSpec(reorder=False, truncate_p=0.5, bitflip_p=0.9)
        pub = FaultyPublisher(spec, seed=2)
        alice = create_editor("alice", pub)
        bob = create_editor("bob", pub)
        initialize_docs([alice, bob], "base")
        for _ in range(12):
            type_text(alice, 1, "x")
            alice.sync()
        assert pub.corrupt_count > 0, "payload faults never fired; vacuous"
        pub.redeliver_lost()
        assert alice.view == bob.view

    def test_raise_mode_still_queues_other_docs(self):
        """Pre-supervisor contract: on_corrupt="raise" raises a typed
        DecodeError naming the bad docs, AFTER queueing every clean frame —
        fault isolation holds on both surfaces."""
        rng = random.Random(3)
        workloads = generate_workload(seed=3, num_docs=2, ops_per_doc=OPS)
        sess = _campaign_session(2, OPS)
        good = _frames_for(workloads[0], rng)
        with pytest.raises(DecodeError):
            sess.ingest_frames([(0, f) for f in good] + [(1, b"junkjunkjunk")])
        sess.drain()
        expected = _oracle_doc(workloads[0]).get_text_with_formatting(["text"])
        assert sess.read(0) == expected
        assert sess.quarantined()[1].reason == REASON_DECODE


# ---------------------------------------------------------------------------
# transport: deadlines, retry, behind-frontier absorption
# ---------------------------------------------------------------------------


class TestResilientTransport:
    def test_stalled_peer_times_out_not_hangs(self):
        from peritext_tpu.parallel import ChangeStore, RetryPolicy, sync_with

        peer = _StallingPeer()
        try:
            with pytest.raises(TransportError):
                sync_with(
                    ChangeStore(), *peer.address,
                    retry=RetryPolicy(attempts=2, base_delay=0.01,
                                      max_delay=0.05, timeout=0.25),
                )
        finally:
            peer.close()

    def test_stalled_peer_surfaces_as_behind_outcome(self):
        from peritext_tpu.observability import GLOBAL_COUNTERS
        from peritext_tpu.parallel import ChangeStore, RetryPolicy, try_sync_with

        before = GLOBAL_COUNTERS.get("transport.retries")
        peer = _StallingPeer()
        try:
            outcome = try_sync_with(
                ChangeStore(), *peer.address,
                retry=RetryPolicy(attempts=3, base_delay=0.01,
                                  max_delay=0.05, timeout=0.2),
            )
        finally:
            peer.close()
        assert outcome.behind and not outcome.ok
        assert outcome.error is not None
        assert GLOBAL_COUNTERS.get("transport.retries") >= before + 2

    def test_corrupt_protocol_keeps_valueerror_surface(self):
        """Terminal protocol corruption keeps the typed DecodeError /
        ValueError surface (the pre-retry contract) instead of being
        rewrapped as TransportError (a ConnectionError), so pre-existing
        ``except ValueError`` corrupt-peer handlers still fire."""
        import socket as socketlib
        import struct
        import threading

        from peritext_tpu.parallel import ChangeStore, sync_with

        srv = socketlib.socket()
        srv.bind(("127.0.0.1", 0))
        srv.listen(1)

        def speak_garbage():
            conn, _ = srv.accept()
            with conn:
                conn.recv(65536)  # client frontier
                body = b"C" + b"\xde\xad\xbe\xef"  # MSG_CHANGES, junk frame
                conn.sendall(struct.pack(">I", len(body)) + body)

        threading.Thread(target=speak_garbage, daemon=True).start()
        try:
            with pytest.raises(ValueError) as ei:
                sync_with(ChangeStore(), *srv.getsockname(), timeout=2.0)
            assert not isinstance(ei.value, ConnectionError)
        finally:
            srv.close()

    def test_callback_decode_error_propagates_from_try_sync(self):
        """A DecodeError raised by the caller's OWN on_changes callback is a
        local delivery failure, not a corrupt peer: try_sync_with must let
        it propagate instead of absorbing it as a (false) behind outcome —
        the store already merged the pull, so no later round would repair."""
        from peritext_tpu.parallel import (
            ChangeStore, ReplicaServer, RetryPolicy, try_sync_with,
        )

        workload = generate_workload(seed=53, num_docs=1, ops_per_doc=30)[0]
        full = ChangeStore()
        for log in workload.values():
            for ch in log:
                full.append(ch)
        server = ReplicaServer(full, timeout=5.0)
        host, port = server.start()

        def sink(changes):
            raise DecodeError("downstream parser rejected the batch")

        try:
            with pytest.raises(DecodeError):
                try_sync_with(
                    ChangeStore(), host, port, on_changes=sink,
                    retry=RetryPolicy(attempts=1, timeout=2.0),
                )
        finally:
            server.stop()

    def test_callback_failure_not_swallowed_by_retry(self):
        """A failure in on_changes AFTER a successful pull propagates
        unwrapped and is not retried: a retry would pull only duplicates,
        skip the callbacks entirely, and report success."""
        from peritext_tpu.parallel import (
            ChangeStore, ReplicaServer, RetryPolicy, sync_with,
        )

        workload = generate_workload(seed=51, num_docs=1, ops_per_doc=30)[0]
        full = ChangeStore()
        for log in workload.values():
            for ch in log:
                full.append(ch)
        server = ReplicaServer(full, timeout=5.0)
        host, port = server.start()
        calls = []

        def sink(changes):
            calls.append(len(changes))
            raise OSError("downstream sink failed")

        try:
            with pytest.raises(OSError):
                sync_with(
                    ChangeStore(), host, port, on_changes=sink,
                    retry=RetryPolicy(attempts=3, base_delay=0.01, timeout=2.0),
                )
        finally:
            server.stop()
        assert len(calls) == 1 and calls[0] > 0

    def test_refused_connection_becomes_behind_then_repairs(self):
        from peritext_tpu.parallel import (
            ChangeStore, ReplicaServer, RetryPolicy, try_sync_with,
        )

        workload = generate_workload(seed=9, num_docs=1, ops_per_doc=60)[0]
        full = ChangeStore()
        for log in workload.values():
            for ch in log:
                full.append(ch)
        local = ChangeStore()
        # grab a port that refuses by binding without listening backlog use
        dead = _StallingPeer()
        dead_addr = dead.address
        dead.close()  # now actively refused
        policy = RetryPolicy(attempts=2, base_delay=0.01, max_delay=0.02,
                             timeout=0.2)
        outcome = try_sync_with(local, *dead_addr, retry=policy)
        assert outcome.behind
        # a later round against a live peer repairs the behind frontier
        server = ReplicaServer(full, timeout=5.0)
        host, port = server.start()
        try:
            repaired = try_sync_with(local, host, port, retry=policy)
        finally:
            server.stop()
        assert repaired.ok and repaired.pulled > 0
        assert local.clock() == full.clock()


# ---------------------------------------------------------------------------
# guarded device rounds: watchdog, rollback, scalar degradation
# ---------------------------------------------------------------------------


class TestGuardedSession:
    def _converged(self, guarded, workloads):
        for d, w in enumerate(workloads):
            expected = _oracle_doc(w).get_text_with_formatting(["text"])
            assert guarded.read(d) == expected, f"doc {d} diverged"

    def test_injected_failures_roll_back_and_recover(self, tmp_path):
        workloads = generate_workload(seed=17, num_docs=DOCS, ops_per_doc=OPS)
        clean = _campaign_session(DOCS, OPS)
        rng = random.Random(17)
        plans = [_frames_for(w, rng) for w in workloads]
        for d, frames in enumerate(plans):
            for f in frames:
                clean.ingest_frame(d, f)
        clean.drain()

        guarded = GuardedSession(
            lambda: _campaign_session(DOCS, OPS), tmp_path, deadline=120.0,
            checkpoint_every=2,
        )
        for d, frames in enumerate(plans):
            for f in frames:
                guarded.ingest_frame(d, f)
                if rng.random() < 0.4:
                    guarded.step()
        guarded.inject_failure(DeviceRoundError("injected device fault"))
        assert guarded.step() == 0  # absorbed, not raised
        guarded.inject_failure(RuntimeError("injected XLA error"))
        guarded.step()
        guarded.drain()
        assert guarded.rollbacks == 2
        assert guarded.digest() == clean.digest()
        self._converged(guarded, workloads)
        health = guarded.health()
        assert health["rollbacks"] == 2
        assert health["pending_changes"] == 0
        assert guarded.pending_count() == 0  # public pass-through surface

    def test_deadline_watchdog_fires_and_session_recovers(self, tmp_path):
        workloads = generate_workload(seed=23, num_docs=2, ops_per_doc=OPS)
        rng = random.Random(23)
        guarded = GuardedSession(
            lambda: _campaign_session(2, OPS), tmp_path, deadline=120.0,
            checkpoint_every=100,
        )
        for d, w in enumerate(workloads):
            for f in _frames_for(w, rng):
                guarded.ingest_frame(d, f)
        guarded.step()  # warm: compile outside the tight deadline
        guarded.deadline = 1.0
        guarded.inject_delay(3.0)
        assert guarded.step() == 0  # watchdog fired, round rolled back
        assert guarded.rollbacks == 1
        guarded.deadline = 120.0
        guarded.drain()
        self._converged(guarded, workloads)

    def test_object_ingest_is_journalled_and_survives_rollback(self, tmp_path):
        """The object-change ingest surface (editor/bridge path) journals
        like frames do: a rollback replays it, so accepted changes can never
        silently vanish from the restored session."""
        workloads = generate_workload(seed=47, num_docs=2, ops_per_doc=OPS)
        clean = _campaign_session(2, OPS)
        for d, w in enumerate(workloads):
            for log in w.values():
                clean.ingest(d, list(log))
        clean.drain()

        guarded = GuardedSession(
            lambda: _campaign_session(2, OPS), tmp_path, deadline=120.0,
            checkpoint_every=100,
        )
        for d, w in enumerate(workloads):
            for log in w.values():
                guarded.ingest(d, list(log))
        guarded.inject_failure(RuntimeError("injected device fault"))
        assert guarded.step() == 0  # rollback: replay includes object ingests
        guarded.drain()
        assert guarded.rollbacks == 1
        assert guarded.digest() == clean.digest()
        self._converged(guarded, workloads)

    def test_fused_drain_kill_recovers_byte_equal(self, tmp_path):
        """A device fault BETWEEN staged-batch commits of one fused
        multi-round drain (mid-fuse: earlier batches already advanced the
        donated state) must roll the WHOLE drain back to the pre-fuse
        checkpoint boundary and recover byte-equal via journal replay —
        never resume from a half-applied fused pipeline."""
        from peritext_tpu.testing.chaos import run_fused_drain_kill

        report = run_fused_drain_kill(seed=101, checkpoint_root=tmp_path)
        assert report["rollbacks"] == 1
        # the kill provably fired mid-fuse: at least one staged batch had
        # already committed inside the killed drain
        assert report["batches_before_kill"] >= 1
        assert report["pre_fuse_rounds"] > 0
        # incident-plane oracle: EXACTLY a quarantine-storm, resolved
        # post-recovery, detected within a round of the rollback
        assert report["incident_kinds"] == ["quarantine-storm"]
        assert report["incident_resolved"]
        assert report["incident_detection_rounds"] == 1

    def test_persistent_failure_degrades_to_scalar_replay(self, tmp_path, monkeypatch):
        workloads = generate_workload(seed=29, num_docs=2, ops_per_doc=OPS)
        rng = random.Random(29)
        guarded = GuardedSession(
            lambda: _campaign_session(2, OPS), tmp_path, deadline=120.0,
            checkpoint_every=100,
        )
        for d, w in enumerate(workloads):
            for f in _frames_for(w, rng):
                guarded.ingest_frame(d, f)

        def sick(self):
            raise RuntimeError("device still failing")

        monkeypatch.setattr(GuardedSession, "_drain_device", sick)
        guarded.inject_failure(DeviceRoundError("first failure"))
        assert guarded.step() == 0
        monkeypatch.undo()
        # the ladder's last rung: every pending doc demoted to scalar replay,
        # quarantined with the device-round reason — and still correct
        quarantined = guarded.quarantined()
        assert quarantined, "persistent failure must quarantine the pending docs"
        assert all(r.reason == REASON_DEVICE_ROUND for r in quarantined.values())
        assert all(s.fallback for s in guarded.session.docs)
        guarded.drain()
        self._converged(guarded, workloads)


# ---------------------------------------------------------------------------
# crash-restore under fault schedules
# ---------------------------------------------------------------------------


class TestCrashRestore:
    def test_mid_checkpoint_crash_staging_ignored(self, tmp_path):
        from peritext_tpu.checkpoint import CheckpointManager

        workloads = generate_workload(seed=31, num_docs=2, ops_per_doc=OPS)
        rng = random.Random(31)
        sess = _campaign_session(2, OPS)
        for d, w in enumerate(workloads):
            for f in _frames_for(w, rng):
                sess.ingest_frame(d, f)
        sess.drain()
        manager = CheckpointManager(tmp_path / "ckpt", keep=3)
        manager.save(step=1, session=sess)

        # crash mid-save: a STALE staging dir with partial content, plus a
        # torn (meta-less) step dir — neither may mask the good checkpoint.
        # A FRESH staging dir may belong to a live concurrent saver and must
        # survive the sweep.
        import os
        import time

        staging = tmp_path / "ckpt" / ".staging_killed"
        staging.mkdir()
        (staging / "changes.jsonl").write_text("{ truncated")
        old = time.time() - 7200
        os.utime(staging, (old, old))
        live = tmp_path / "ckpt" / ".staging_live"
        live.mkdir()
        torn = tmp_path / "ckpt" / "step_000000000002"
        torn.mkdir()
        (torn / "session").mkdir()

        reopened = CheckpointManager(tmp_path / "ckpt", keep=3)
        assert reopened.steps() == [1]
        assert not staging.exists(), "stale staging must be swept on reopen"
        assert live.exists(), "a live saver's fresh staging must survive"
        restored = reopened.latest().session()
        assert restored is not None
        expected = _oracle_doc(workloads[0]).get_text_with_formatting(["text"])
        assert restored.read(0) == expected
        assert restored.digest() == sess.digest()

    def test_crash_restore_under_corruption_schedule(self, tmp_path):
        """Kill a supervised session mid-run while some docs are decode-
        quarantined; restore; repair by clean redelivery; final digest must
        be byte-equal to a fault-free run's."""
        workloads = generate_workload(seed=37, num_docs=DOCS, ops_per_doc=OPS)
        rng = random.Random(37)
        plans = [_frames_for(w, rng) for w in workloads]
        clean = _campaign_session(DOCS, OPS)
        for d, frames in enumerate(plans):
            for f in frames:
                clean.ingest_frame(d, f)
        clean.drain()

        factory = lambda: _campaign_session(DOCS, OPS)  # noqa: E731
        guarded = GuardedSession(factory, tmp_path, deadline=120.0,
                                 checkpoint_every=3)
        spec = FaultSpec(truncate_p=0.5, bitflip_p=0.5)
        for d, frames in enumerate(plans):
            for f in frames[:-1]:  # hold back a suffix: lost in the crash
                if d == 0:
                    bad = corrupt_detectably(f, rng, spec)
                    if bad is not None:
                        f = bad
                guarded.ingest_frame(d, f)
                if rng.random() < 0.3:
                    guarded.step()
        guarded.checkpoint()
        del guarded  # crash

        revived = GuardedSession(factory, tmp_path, deadline=120.0,
                                 checkpoint_every=3)
        latest = revived.manager.latest()
        assert latest is not None
        revived.session = latest.session(drain=True)
        for d, frames in enumerate(plans):  # anti-entropy repair, clean
            revived.ingest_frames([(d, f) for f in frames])
        revived.drain()
        assert revived.session.pending_count() == 0
        assert not any(
            r.reason == REASON_DECODE for r in revived.quarantined().values()
        )
        assert revived.digest() == clean.digest()
        for d, w in enumerate(workloads):
            expected = _oracle_doc(w).get_text_with_formatting(["text"])
            assert revived.read(d) == expected


# ---------------------------------------------------------------------------
# guarded batch merge + health surface
# ---------------------------------------------------------------------------


class TestGuardedMergeAndHealth:
    def test_guarded_docbatch_degrades_to_oracle(self, monkeypatch):
        from peritext_tpu.api.batch import DocBatch

        workloads = generate_workload(seed=41, num_docs=3, ops_per_doc=20)
        batch = DocBatch(slot_capacity=256, mark_capacity=64, guard=True)

        def boom(encoded):
            raise RuntimeError("injected device failure")

        monkeypatch.setattr(batch, "apply_encoded", boom)
        report = batch.merge(workloads)
        assert report.spans == oracle_merge(workloads)
        assert report.fallback_docs == [0, 1, 2]
        assert report.stats.extras["guarded_fallback"] == 1.0
        # unguarded batches keep the loud-failure contract
        strict = DocBatch(guard=False)
        monkeypatch.setattr(strict, "apply_encoded", boom)
        with pytest.raises(RuntimeError):
            strict.merge(workloads)

    def test_health_snapshot_shape(self, tmp_path):
        from peritext_tpu.observability import health_snapshot

        guarded = GuardedSession(
            lambda: _campaign_session(1, OPS), tmp_path, deadline=120.0
        )
        guarded.ingest_frame(0, b"garbage", )
        snap = health_snapshot(session=guarded)
        assert "counters" in snap
        assert all(
            k.split(".")[0] in ("streaming", "transport", "supervisor",
                                "merge", "convergence", "serve", "fleet",
                                "jit")
            for k in snap["counters"]
        )
        q = snap["session"]["quarantined"]
        assert q[0]["reason"] == REASON_DECODE
        assert snap["session"]["rollbacks"] == 0


# ---------------------------------------------------------------------------
# the composed chaos harness
# ---------------------------------------------------------------------------


class TestChaosHarness:
    def test_chaos_smoke(self):
        """One composed campaign rides tier-1: delivery + corruption +
        injected device faults + peer stall + crash-restore, all oracles."""
        report = run_chaos(0, num_docs=DOCS, ops_per_doc=OPS)
        assert report.delivered_frames > 0
        assert report.transport_repaired
        assert report.crash_restores == 1

    def test_fleet_partition_heals_in_lag_order(self):
        """ISSUE 4 acceptance: a 4-host fleet under an asymmetric partition
        (host0 hears frontiers, every reply cut; one link flapping) with a
        slow link at heal converges to identical fleet-wide digests, host0's
        monitor watermarks equal the store-derived truth, the
        ``peritext_convergence_lag_ops`` gauge is live in ``/metrics``
        during the episode, and the first post-heal gossip round follows
        behind-ness priority.  All oracles assert inside the harness."""
        from peritext_tpu.testing.chaos import run_fleet_chaos

        report = run_fleet_chaos(0, hosts=4)
        assert report.converged
        assert report.lag_gauge_seen
        assert report.observed_lag == report.expected_lag
        # most-behind-first: the order is the lag sort, descending
        lags = [report.expected_lag[name] for name in report.heal_order]
        assert lags == sorted(lags, reverse=True) and len(lags) == 3
        assert report.ops_drained > 0
        assert report.divergence_incidents == 0

    def test_serve_tier_overload_plus_partition(self):
        """ISSUE 7 acceptance: under a 2x overload burst composed with an
        asymmetric partition, the serving tier sheds with TYPED verdicts
        only (zero silent drops — the accounting identity holds and every
        reason is in the typed vocabulary), the bounded ingest queue never
        exceeds its depth bound, the fleet heals to identical store
        digests, and after shed frames are redelivered the serving state
        equals the fault-free session byte-for-bit.  All oracles assert
        inside the harness."""
        from peritext_tpu.serve import SHED_REASONS
        from peritext_tpu.testing.chaos import run_serve_chaos

        report = run_serve_chaos(0, hosts=3)
        assert report.offered == (
            report.admitted + report.delayed + report.shed
        )
        assert report.shed > 0
        assert set(report.shed_reasons) <= set(SHED_REASONS)
        assert report.queue_peak <= report.queue_max_depth
        assert report.partition_lag_ops > 0
        assert report.fleet_converged
        assert report.serve_digest_matches_reference
        assert report.repaired_digest_matches_clean
        # incident-plane oracle: EXACTLY a shed-storm, resolved post-heal
        assert report.incident_kinds == ["shed-storm"]
        assert report.incident_resolved
        assert report.incident_detection_rounds >= 1
        # history-plane oracle (PR 20): the overload burst scored as an
        # anomaly on serve gauges no later than the incident opened
        assert report.anomaly_keys
        assert all(k.startswith("serve.") for k in report.anomaly_keys)
        assert report.anomaly_detection_rounds >= 0

    def test_reconnect_storm_drains_while_serving(self):
        """ROADMAP scenario item: a peer back from a long offline window
        drains its whole backlog through gossip while the serving tier
        stays under open-loop load — convergence is byte-exact, the tier
        stays live, and every verdict is accounted."""
        from peritext_tpu.testing.chaos import run_reconnect_storm

        report = run_reconnect_storm(0, backlog_ops=400,
                                     storm_duration_s=0.4)
        assert report.converged
        assert report.drain_ops_per_sec > 0
        assert report.offered == (
            report.admitted + report.delayed + report.shed
        )
        assert report.served_rounds > 0

    def test_host_kill_failover_acceptance(self, tmp_path):
        """ISSUE 10 acceptance: with traffic running against a 3-host
        fleet, killing one serving host yields only typed verdicts (zero
        silent drops, fleet-wide accounting identity), every acked op
        survives failover (checkpoint + journal redelivery), post-heal
        fleet-wide digests byte-equal a fault-free reference run, and the
        flight recorder dumps the failover timeline.  All oracles assert
        inside the harness; the CI fleet-serve-smoke job runs the larger
        TCP-transport episode."""
        from peritext_tpu.testing.chaos import run_host_kill_failover

        report = run_host_kill_failover(
            0, hosts=3, num_docs=4, ops_per_doc=16, transport=False,
            dump_dir=tmp_path,
        )
        assert report.acked_survived
        assert report.converged
        assert report.failovers == 1
        assert report.failover_docs == report.victim_docs >= 1
        assert report.offered == (
            report.admitted + report.delayed + report.shed
        )
        assert report.delayed + report.shed > 0
        assert report.flight_dumps >= 1
        # incident-plane oracle: EXACTLY a host-death, resolved once
        # failover re-homed the victim's docs, detected within the lease
        assert report.incident_kinds == ["host-death"]
        assert report.incident_resolved
        assert 1 <= report.incident_detection_rounds <= report.detection_rounds + 1
        # history-plane oracle (PR 20): the kill's delay/shed spike scored
        # as an anomaly no later than the host-death incident opened
        assert report.anomaly_keys
        assert set(report.anomaly_keys) <= {
            "fleet.verdicts.delayed", "fleet.verdicts.shed",
        }
        assert 0 <= report.anomaly_detection_rounds <= (
            report.incident_detection_rounds
        )

    def test_markheavy_chaos_smoke(self):
        """ROADMAP scenario diversity: the mark-heavy editorial-pass
        family (span-overlap explosion) through the full composed-fault
        campaign, byte-equality oracle and all."""
        from peritext_tpu.testing.chaos import run_markheavy_chaos

        report = run_markheavy_chaos(1, num_docs=4, ops_per_doc=30)
        assert report.delivered_frames > 0
        assert report.final_digest != 0

    @pytest.mark.slow
    def test_chaos_soak_twenty_seeds(self):
        """Acceptance criterion: >=20 seeded composed-fault campaigns all
        reach byte-equal digests vs the fault-free oracle with zero
        unhandled exceptions (any violation raises inside run_chaos)."""
        reports = run_campaign(range(20), num_docs=6, ops_per_doc=40)
        assert len(reports) == 20
        # the fault space was actually exercised across the soak
        assert sum(r.corrupt_frames for r in reports) > 0
        assert sum(r.rollbacks for r in reports) > 0
        assert sum(r.transport_behind for r in reports) == 20
        assert sum(r.crash_restores for r in reports) == 20
        assert any(r.isolation_checked for r in reports)
