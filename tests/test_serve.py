"""Serving-tier tests (ISSUE 7): admission verdicts, watermark
backpressure, session multiplexing, batching-window autotune, fleet
placement, the open-loop traffic generator, and the serve exporter
surfaces (golden shapes)."""

import json
import urllib.request

import pytest

from peritext_tpu.parallel.codec import encode_frame
from peritext_tpu.parallel.router import FleetRouter, PlacementError
from peritext_tpu.parallel.streaming import StreamingMerge
from peritext_tpu.serve import (
    ADMIT,
    AdmissionController,
    BatchWindowTuner,
    DELAY,
    SHED,
    SHED_OVERLOAD,
    SHED_QUEUE_FULL,
    SHED_REASONS,
    SHED_SESSION_QUOTA,
    SHED_UNKNOWN_SESSION,
    SessionMux,
    build_arrivals,
    run_open_loop,
    sustained_ladder,
)
from peritext_tpu.testing.fuzz import generate_workload

ACTORS = ("doc1", "doc2", "doc3")


def serve_session(num_docs=4, ops_per_doc=40, **kw):
    return StreamingMerge(
        num_docs=num_docs, actors=ACTORS,
        slot_capacity=max(256, 4 * ops_per_doc),
        mark_capacity=max(64, ops_per_doc),
        tomb_capacity=max(128, ops_per_doc),
        round_insert_capacity=128, round_delete_capacity=64,
        round_mark_capacity=64, static_rounds=True, **kw,
    )


def doc_frames(seed=21, num_docs=4, ops_per_doc=40, chunk=6):
    """Per-doc wire-frame plans from the fuzz generator."""
    plans = []
    for w in generate_workload(seed, num_docs=num_docs, ops_per_doc=ops_per_doc):
        changes = [ch for log in w.values() for ch in log]
        plans.append([
            encode_frame(changes[i:i + chunk])
            for i in range(0, len(changes), chunk)
        ])
    return plans


# ---------------------------------------------------------------------------
# admission control
# ---------------------------------------------------------------------------


class TestAdmission:
    def test_admits_below_watermark(self):
        ac = AdmissionController(max_depth=10, high_watermark=0.8,
                                 low_watermark=0.5, session_quota=None)
        for _ in range(8):
            v = ac.offer(0)
            assert v.kind == ADMIT
        assert ac.depth == 8
        assert ac.peak_depth == 8

    def test_delay_above_high_watermark_with_hint(self):
        ac = AdmissionController(max_depth=10, high_watermark=0.5,
                                 low_watermark=0.3, session_quota=None)
        for _ in range(5):
            assert ac.offer(0).kind == ADMIT
        v = ac.offer(0)
        assert v.kind == DELAY
        assert v.hint_seconds is not None and v.hint_seconds > 0
        assert ac.backpressure

    def test_hysteresis_clears_below_low_watermark_only(self):
        ac = AdmissionController(max_depth=10, high_watermark=0.5,
                                 low_watermark=0.2, session_quota=None)
        for _ in range(5):
            ac.offer(0)
        assert ac.offer(0).kind == DELAY
        # draining to between low and high keeps backpressure latched
        ac.mark_applied(0, 2)
        assert ac.offer(0).kind == DELAY
        # draining below low clears it
        ac.mark_applied(0, 2)
        assert ac.offer(0).kind == ADMIT

    def test_sustained_delay_escalates_to_typed_overload_shed(self):
        ac = AdmissionController(max_depth=10, high_watermark=0.5,
                                 low_watermark=0.2, shed_after=3,
                                 session_quota=None)
        for _ in range(5):
            ac.offer(0)
        kinds = [ac.offer(0).kind for _ in range(6)]
        assert kinds[:3] == [DELAY, DELAY, DELAY]
        assert set(kinds[3:]) == {SHED}
        v = ac.offer(0)
        assert v.reason == SHED_OVERLOAD

    def test_degraded_admits_do_not_reset_overload_escalation(self):
        """Interleaved degraded-session traffic (which bypasses
        backpressure) must not keep a delayed client below the shed_after
        escalation forever — only a NORMAL admit or a real drain says the
        queue is moving."""
        ac = AdmissionController(max_depth=10, high_watermark=0.5,
                                 low_watermark=0.2, shed_after=3,
                                 session_quota=None)
        for _ in range(5):
            ac.offer(0)
        kinds = []
        for _ in range(8):  # alternate: delayed client / degraded tenant
            kinds.append(ac.offer(0).kind)
            assert ac.offer(1, degraded=True).kind == ADMIT
            ac.mark_applied(1, 1)  # degraded work applies immediately
        assert SHED in kinds, (
            f"degraded interleave defeated the overload escalation: {kinds}"
        )

    def test_full_queue_sheds_typed(self):
        ac = AdmissionController(max_depth=4, high_watermark=1.0,
                                 low_watermark=0.5, session_quota=None)
        for _ in range(4):
            assert ac.offer(0).kind == ADMIT
        v = ac.offer(0)
        assert v.kind == SHED and v.reason == SHED_QUEUE_FULL

    def test_session_quota_sheds_typed(self):
        ac = AdmissionController(max_depth=10, session_quota=0.3)
        assert ac.offer(7).kind == ADMIT
        assert ac.offer(7).kind == ADMIT
        assert ac.offer(7).kind == ADMIT
        v = ac.offer(7)
        assert v.kind == SHED and v.reason == SHED_SESSION_QUOTA
        # other sessions are unaffected by one tenant's quota
        assert ac.offer(8).kind == ADMIT

    def test_accounting_identity_and_snapshot_shape(self):
        ac = AdmissionController(max_depth=4, high_watermark=1.0,
                                 low_watermark=0.5, session_quota=None)
        for _ in range(9):
            ac.offer(0)
        s = ac.stats
        assert s.submitted == s.admitted + s.delayed + s.shed == 9
        snap = ac.snapshot()
        assert set(snap) == {
            "depth", "peak", "max_depth", "high_watermark", "low_watermark",
            "shed_after", "backpressure", "drain_rate_per_s", "verdicts",
        }
        assert set(snap["verdicts"]) == {
            "submitted", "admitted", "delayed", "shed", "shed_reasons",
        }
        for reason in snap["verdicts"]["shed_reasons"]:
            assert reason in SHED_REASONS
        json.dumps(snap)  # exporter body must serialize

    def test_bad_config_rejected(self):
        with pytest.raises(ValueError):
            AdmissionController(max_depth=0)
        with pytest.raises(ValueError):
            AdmissionController(high_watermark=0.3, low_watermark=0.5)


# ---------------------------------------------------------------------------
# batching-window autotune
# ---------------------------------------------------------------------------


class TestWindowTuner:
    def test_empty_clamps_to_floor(self):
        t = BatchWindowTuner(floor=0.004, ceiling=0.5)
        assert t.window_seconds() == 0.004

    def test_window_tracks_round_latency_between_clamps(self):
        t = BatchWindowTuner(floor=0.001, ceiling=10.0, margin=1.0)
        for _ in range(20):
            t.observe(0.05)
        mid = t.window_seconds()
        assert mid == pytest.approx(0.05, rel=0.5)
        for _ in range(40):
            t.observe(0.4)
        assert t.window_seconds() > mid

    def test_clamps(self):
        t = BatchWindowTuner(floor=0.01, ceiling=0.1)
        t.observe(0.0001)
        assert t.window_seconds() == 0.01
        for _ in range(30):
            t.observe(5.0)
        assert t.window_seconds() == 0.1

    def test_rolling_window_forgets_old_rounds(self):
        t = BatchWindowTuner(floor=0.001, ceiling=10.0, window=8)
        for _ in range(8):
            t.observe(1.0)
        assert t.window_seconds() >= 1.0
        for _ in range(8):  # evicts every slow observation
            t.observe(0.01)
        assert t.window_seconds() < 0.1

    def test_snapshot_shape(self):
        t = BatchWindowTuner()
        snap = t.snapshot()
        assert set(snap) == {"seconds", "floor", "ceiling", "margin",
                             "quantile", "p99_round_seconds",
                             "rounds_observed"}
        json.dumps(snap)


# ---------------------------------------------------------------------------
# fleet placement (parallel/router.py — deterministic, merge scope)
# ---------------------------------------------------------------------------


class TestFleetRouter:
    def fleet(self, lag_weight=1):
        r = FleetRouter(lag_weight=lag_weight)
        r.add_host("hostA", capacity=4)
        r.add_host("hostB", capacity=4)
        r.add_host("hostC", capacity=4)
        return r

    def test_least_loaded_name_tiebreak_is_deterministic(self):
        a = self.fleet()
        b = self.fleet()
        seq_a = [a.place(f"d{i}", size=2) for i in range(6)]
        seq_b = [b.place(f"d{i}", size=2) for i in range(6)]
        assert seq_a == seq_b
        assert seq_a[:3] == ["hostA", "hostB", "hostC"]

    def test_place_is_idempotent_per_doc(self):
        r = self.fleet()
        assert r.place("d0") == r.place("d0")

    def test_lag_penalty_steers_placement_away(self):
        r = self.fleet(lag_weight=1)
        r.observe("hostA", lag_ops=100)
        assert r.place("d0") == "hostB"

    def test_host_bound_docs_balance_their_own_dimension(self):
        r = self.fleet()
        # hostA carries the fleet's scalar-replay load but little slot load
        r.observe("hostA", slot_load=1, host_bound_load=50)
        r.observe("hostB", slot_load=10)
        r.observe("hostC", slot_load=12)
        # a host-bound doc avoids the host-bound-loaded host...
        assert r.place("hb", host_bound=True) == "hostB"
        # ...while a device doc still picks by device load
        assert r.place("dev") == "hostA"

    def test_capacity_respected_and_typed_error_when_full(self):
        r = FleetRouter()
        r.add_host("only", capacity=2)
        r.place("d0")
        r.place("d1")
        with pytest.raises(PlacementError):
            r.place("d2")

    def test_evacuate_rolls_back_atomically_when_capacity_runs_out(self):
        """A mid-plan capacity failure must leave the router exactly as it
        was (minus the draining flag): the caller acts on the whole
        returned plan or none of it."""
        r = FleetRouter()
        r.add_host("big", capacity=5)
        for i in range(5):
            r.place(f"d{i}", size=1)
        r.add_host("small", capacity=2)  # can absorb only 2 of the 5
        before = r.placement()
        assert all(h == "big" for h in before.values())
        moves_before = r.moves
        with pytest.raises(PlacementError):
            r.evacuate("big")
        assert r.placement() == before, "partial evacuation leaked"
        assert r.moves == moves_before
        assert r.host("big").draining  # the intent is recorded, the state is whole

    def test_evacuate_moves_every_doc_off_a_draining_host(self):
        r = self.fleet()
        docs = [f"d{i}" for i in range(6)]
        for d in docs:
            r.place(d)
        victims = [d for d, h in r.placement().items() if h == "hostA"]
        moves = r.evacuate("hostA")
        assert sorted(d for d, _, _ in moves) == sorted(victims)
        assert all(h != "hostA" for h in r.placement().values())
        # a draining host accepts nothing new
        assert r.place("d9") != "hostA"

    def test_rebalance_shrinks_the_spread_and_terminates(self):
        r = FleetRouter()
        r.add_host("hot", capacity=8)
        r.add_host("cold", capacity=8)
        for i in range(4):
            r.place(f"d{i}", size=4)  # alternates hot/cold
        r.observe("hot", lag_ops=0)
        # skew it: all docs onto 'hot' via observations
        r2 = FleetRouter()
        r2.add_host("hot", capacity=8)
        r2.add_host("cold", capacity=8)
        r2._assign("a", r2.host("hot"), 6, False)
        r2._assign("b", r2.host("hot"), 4, False)
        r2._assign("c", r2.host("hot"), 2, False)
        moves = r2.rebalance()
        assert moves  # something moved
        loads = {n: r2.host(n).slot_load for n in r2.hosts()}
        assert abs(loads["hot"] - loads["cold"]) <= 6
        assert r2.rebalance() == [] or True  # terminates without oscillating

    def test_monitor_watermarks_fold_in(self):
        from peritext_tpu.obs import ConvergenceMonitor

        r = self.fleet()
        mon = ConvergenceMonitor(host="frontend")
        mon.observe_frontier("hostB", {"x": 0}, {"x": 500})
        r.observe_monitor(mon)
        assert r.host("hostB").lag_ops == 500
        assert r.place("d0") in ("hostA", "hostC")

    def test_snapshot_shape(self):
        r = self.fleet()
        r.place("d0")
        snap = r.snapshot()
        assert set(snap) == {"hosts", "docs", "placements", "moves",
                             "lag_weight"}
        assert set(snap["hosts"]["hostA"]) == {
            "capacity", "docs", "slot_load", "page_load", "paged",
            "host_bound_load", "lag_ops", "draining",
        }
        json.dumps(snap)


# ---------------------------------------------------------------------------
# session multiplexing
# ---------------------------------------------------------------------------


class FakeClock:
    def __init__(self):
        self.t = 100.0

    def __call__(self):
        return self.t

    def tick(self, dt):
        self.t += dt


class SteppingClock:
    """Monotonic fake that advances ``step`` per read: the mux's round
    wall (its pump reads the clock immediately before and after the
    drain) measures exactly ``step`` seconds per committed round."""

    def __init__(self):
        self.t = 100.0
        self.step = 0.0

    def __call__(self):
        v = self.t
        self.t += self.step
        return v


class TestSessionMux:
    def test_sessions_map_onto_doc_slots_and_patches_flow(self):
        plans = doc_frames(seed=33, num_docs=2)
        mux = SessionMux(serve_session(num_docs=2))
        sids = []
        for c in ("alice", "bob"):
            sid, v = mux.open_session(c)
            assert v.admitted
            sids.append(sid)
        for sid, plan in zip(sids, plans):
            for f in plan:
                assert mux.submit(sid, f).kind == ADMIT
        mux.flush()
        # per-session patch streams: same vocabulary as the direct session
        ref = serve_session(num_docs=2)
        for doc, plan in enumerate(plans):
            for f in plan:
                ref.ingest_frame(doc, f)
        ref.drain()
        for doc, sid in enumerate(sids):
            assert mux.patches(sid) == ref.read_patches(doc)
            assert mux.read(sid) == ref.read(doc)
        assert mux.session.digest() == ref.digest()

    def test_capacity_exhaustion_is_a_typed_shed(self):
        mux = SessionMux(serve_session(num_docs=1))
        sid, v = mux.open_session("a")
        assert v.admitted and sid is not None
        sid2, v2 = mux.open_session("b")
        assert sid2 is None and v2.kind == SHED and v2.reason == "capacity"

    def test_unknown_session_is_a_typed_shed(self):
        mux = SessionMux(serve_session(num_docs=1))
        v = mux.submit(99, b"junk")
        assert v.kind == SHED and v.reason == SHED_UNKNOWN_SESSION

    def test_corrupt_frame_quarantines_not_raises(self):
        plans = doc_frames(seed=33, num_docs=2)
        mux = SessionMux(serve_session(num_docs=2))
        sid, _ = mux.open_session("a")
        good = plans[0][0]
        assert mux.submit(sid, good[:-3] + b"\xff\xff\xff").kind == ADMIT
        mux.flush()  # must not raise out of the serving loop
        q = mux.session.quarantined()
        assert 0 in q and q[0].reason == "decode"

    def test_window_forces_round_close_on_expiry(self):
        plans = doc_frames(seed=33, num_docs=1)
        clock = FakeClock()
        tuner = BatchWindowTuner(floor=0.1, ceiling=0.1)
        mux = SessionMux(serve_session(num_docs=1), tuner=tuner, clock=clock)
        sid, _ = mux.open_session("a")
        mux.submit(sid, plans[0][0])
        assert mux.pump() == 0  # window still open
        clock.tick(0.2)
        assert mux.pump() == 1  # window expired: round committed

    def test_backpressure_forces_round_close_early(self):
        plans = doc_frames(seed=33, num_docs=1)
        clock = FakeClock()
        tuner = BatchWindowTuner(floor=100.0, ceiling=100.0)  # huge window
        mux = SessionMux(
            serve_session(num_docs=1), tuner=tuner, clock=clock,
            admission=AdmissionController(
                max_depth=4, high_watermark=0.5, low_watermark=0.25,
                session_quota=None,
            ),
        )
        sid, _ = mux.open_session("a")
        for f in plans[0][:3]:
            mux.submit(sid, f)
        # above the high watermark: the window must not wait out 100 s
        assert mux.window_expired()
        assert mux.pump() > 0

    def test_sustained_quota_shedding_degrades_through_fallback_ladder(self):
        plans = doc_frames(seed=33, num_docs=2, ops_per_doc=40)
        mux = SessionMux(
            serve_session(num_docs=2),
            admission=AdmissionController(max_depth=8, session_quota=0.25),
            degrade_after=3,
        )
        hot, _ = mux.open_session("hot")
        frames = plans[0]
        sheds = 0
        # keep submitting without pumping: quota sheds accumulate until the
        # degradation ladder demotes the doc to scalar fallback
        for i in range(16):
            v = mux.submit(hot, frames[i % len(frames)])
            if v.kind == SHED:
                assert v.reason in SHED_REASONS
                sheds += 1
            if mux.sessions()[hot].degraded:
                break
        assert mux.sessions()[hot].degraded
        assert mux.session.docs[0].fallback  # the PR-1 ladder rung engaged
        assert 0 in mux.session.quarantined()
        # degraded writes keep flowing (immediately, off the device budget)
        v = mux.submit(hot, frames[0])
        assert v.kind == ADMIT
        # the degraded doc still reads correctly via scalar replay: feed the
        # whole plan and compare against the scalar-path reference
        for f in frames:
            assert mux.submit(hot, f).kind == ADMIT
        mux.flush()
        ref = serve_session(num_docs=1)
        ref.force_fallback(0)
        for f in frames:
            ref.ingest_frame(0, f)
        ref.drain()
        assert mux.read(hot) == ref.read(0)

    def test_snapshot_golden_shape(self):
        mux = SessionMux(serve_session(num_docs=2), host="h9")
        mux.open_session("a")
        snap = mux.snapshot()
        assert set(snap) == {
            "host", "layout", "fused_pipeline", "sessions", "sessions_total",
            "docs", "doc_capacity", "degraded_docs", "fusion", "rounds",
            "applied_frames", "buffered_frames", "overloaded",
            "recent_sheds", "load", "queue", "window", "session_table",
        }
        # the fusion section: standalone identity report (a FusedMuxGroup
        # member reports the shared window's stats under the SAME keys)
        assert set(snap["fusion"]) == {
            "grouped", "tenants", "lanes", "windows", "dispatches",
            "docs_per_dispatch", "window_occupancy",
        }
        assert snap["fusion"]["grouped"] is False
        # the load section is FleetRouter.observe keyword-compatible (the
        # fleet frontend feeds placement straight from this surface)
        assert {"slot_load", "host_bound_load", "docs"} <= set(snap["load"])
        assert snap["layout"] == "padded"  # paged muxes add "page_pool"
        assert snap["fused_pipeline"] is True  # serving rides the fused path
        assert snap["host"] == "h9"
        assert set(snap["session_table"]["0"]) == {
            "client", "doc", "submitted", "admitted", "delayed", "shed",
            "degraded", "closed",
        }
        json.dumps(snap)


# ---------------------------------------------------------------------------
# window movement: the latency/occupancy dial demonstrably adapts
# ---------------------------------------------------------------------------


class TestWindowMovement:
    def test_window_moves_between_low_rate_and_saturating_load(self):
        """The acceptance pin: a low-rate workload's window sits at/near
        the floor, a saturating workload's window grows toward the
        ceiling.  Driven through the REAL mux pump path; the stepping
        clock makes each committed round's measured wall exactly the
        phase's per-read step."""
        plans = doc_frames(seed=33, num_docs=2)
        clock = SteppingClock()
        tuner = BatchWindowTuner(floor=0.002, ceiling=0.5, window=16)
        mux = SessionMux(serve_session(num_docs=2), tuner=tuner, clock=clock)
        sid, _ = mux.open_session("a")

        # low-rate phase: trickle rounds are cheap (0.5 ms each)
        clock.step = 0.0005
        for f in plans[0][:4]:
            mux.submit(sid, f)
            mux.flush()
        low_window = mux.window_seconds()
        assert low_window <= 0.01, "cheap rounds must keep the window small"

        # saturating phase: rounds cost 50 ms -> the window stretches
        clock.step = 0.05
        for i in range(20):
            mux.submit(sid, plans[0][i % len(plans[0])])
            mux.flush()
        high_window = mux.window_seconds()
        assert high_window >= 0.04, (
            f"saturating rounds must grow the window (got {high_window})"
        )
        assert high_window > 5 * low_window

    def test_window_movement_end_to_end_real_clock(self):
        """Real-clock smoke of the same dial: after cheap real rounds the
        tuned window is strictly below the ceiling; flooding the session
        with every plan's frames at once produces costlier rounds and a
        larger (or ceiling-clamped) window."""
        plans = doc_frames(seed=33, num_docs=4, ops_per_doc=60)
        tuner = BatchWindowTuner(floor=0.0005, ceiling=5.0, window=8)
        mux = SessionMux(serve_session(num_docs=4, ops_per_doc=60),
                         tuner=tuner)
        sids = [mux.open_session(f"c{i}")[0] for i in range(4)]
        # warm the compile cache so measured rounds are honest
        for sid, plan in zip(sids, plans):
            mux.submit(sid, plan[0])
        mux.flush()
        for sid, plan in zip(sids, plans):
            mux.submit(sid, plan[1])
        mux.flush()
        low_window = mux.window_seconds()
        # saturating: every remaining frame in a handful of fat rounds
        for k in range(2, max(len(p) for p in plans)):
            for sid, plan in zip(sids, plans):
                if k < len(plan):
                    mux.submit(sid, plan[k])
            mux.flush()
        assert mux.window_seconds() >= low_window
        assert tuner.round_seconds.count >= 3


# ---------------------------------------------------------------------------
# open-loop traffic
# ---------------------------------------------------------------------------


class TestTraffic:
    def test_build_arrivals_is_deterministic_and_open_loop(self):
        frames = {0: [b"a", b"b"], 1: [b"c"]}
        arr = build_arrivals(frames, rate_per_s=10, duration_s=1.0)
        assert arr == build_arrivals(frames, rate_per_s=10, duration_s=1.0)
        assert len(arr) == 10
        # arrival times fixed by the rate alone
        assert [t for t, _, _ in arr] == pytest.approx(
            [i / 10 for i in range(10)]
        )
        # sessions round-robin, frames cycle
        assert arr[0][1:] == (0, b"a") and arr[1][1:] == (1, b"c")
        assert arr[2][1:] == (0, b"b") and arr[4][1:] == (0, b"a")

    def test_open_loop_accounting_and_latency_readout(self):
        plans = doc_frames(seed=33, num_docs=2)
        mux = SessionMux(serve_session(num_docs=2))
        frames = {}
        for doc in range(2):
            sid, _ = mux.open_session(f"c{doc}")
            frames[sid] = plans[doc]
        arr = build_arrivals(frames, rate_per_s=400, duration_s=0.05)
        res = run_open_loop(mux, arr)
        assert res.accounted()
        assert res.offered == len(arr)
        assert res.applied == res.admitted  # drain=True applies everything
        assert res.p99_apply_s >= res.p50_apply_s >= 0
        json.dumps(res.to_json())

    def test_ladder_stops_at_first_unsustained_rung(self):
        """Drive the ladder against a mux whose queue is tiny: the high
        rate must break via typed verdicts and the sweep must stop."""
        plans = doc_frames(seed=33, num_docs=2)

        def factory():
            mux = SessionMux(
                serve_session(num_docs=2),
                admission=AdmissionController(
                    max_depth=4, high_watermark=0.5, low_watermark=0.25,
                    shed_after=2, session_quota=None,
                ),
            )
            frames = {}
            for doc in range(2):
                sid, _ = mux.open_session(f"c{doc}")
                frames[sid] = plans[doc]
            return mux, frames

        rungs, best = sustained_ladder(
            factory, rates=[20.0, 20000.0, 40000.0], slo_p99_s=30.0,
            duration_s=0.05,
        )
        # the saturating rung breaks (typed), and the sweep stops there
        assert len(rungs) == 2
        assert rungs[0].sustained
        assert not rungs[1].sustained
        assert rungs[1].result.shed + rungs[1].result.delayed > 0
        assert best is rungs[0]
        for rung in rungs:
            assert rung.result.accounted()
            for reason in rung.result.shed_reasons:
                assert reason in SHED_REASONS


# ---------------------------------------------------------------------------
# exporter surfaces (golden shapes)
# ---------------------------------------------------------------------------


class TestServeExporters:
    def make_mux(self):
        mux = SessionMux(serve_session(num_docs=2), host="hX")
        mux.open_session("a")
        return mux

    def test_serve_json_route_and_shape(self):
        from peritext_tpu.obs import MetricsServer

        mux = self.make_mux()
        server = MetricsServer(serve=mux)
        host, port = server.start()
        try:
            body = json.loads(urllib.request.urlopen(
                f"http://{host}:{port}/serve.json", timeout=5
            ).read())
        finally:
            server.stop()
        assert body["host"] == "hX"
        assert set(body["queue"]["verdicts"]) == {
            "submitted", "admitted", "delayed", "shed", "shed_reasons",
        }
        assert {"seconds", "floor", "ceiling"} <= set(body["window"])

    def test_prometheus_serve_gauges(self):
        from peritext_tpu.obs import prometheus_text

        mux = self.make_mux()
        mux.submit(99, b"x")  # one typed shed for the labelled series
        text = prometheus_text(serve=mux)
        for gauge in (
            "peritext_serve_sessions ",
            "peritext_serve_docs ",
            "peritext_serve_queue_depth ",
            "peritext_serve_queue_peak ",
            "peritext_serve_queue_max_depth ",
            "peritext_serve_backpressure ",
            "peritext_serve_overloaded ",
            "peritext_serve_window_seconds ",
            "peritext_serve_submitted_total ",
            "peritext_serve_admitted_total ",
            "peritext_serve_delayed_total ",
            "peritext_serve_shed_total ",
        ):
            assert any(line.startswith(gauge)
                       for line in text.splitlines()), gauge
        # the by-reason breakdown is a separate family so PromQL sum()
        # never double-counts the unlabelled total
        assert 'peritext_serve_shed_reason_total{reason="unknown-session"} 1' in text
        assert 'peritext_serve_shed_total{' not in text

    def test_health_snapshot_composition(self):
        from peritext_tpu.obs import health_snapshot

        mux = self.make_mux()
        snap = health_snapshot(serve=mux)
        assert snap["serve"]["host"] == "hX"
        assert "queue" in snap["serve"] and "window" in snap["serve"]
        json.dumps(snap, default=str)

    def test_replica_server_mounts_serve(self):
        from peritext_tpu.parallel.anti_entropy import ChangeStore
        from peritext_tpu.parallel.multihost import ReplicaServer

        mux = self.make_mux()
        server = ReplicaServer(ChangeStore(), metrics_port=0, serve=mux)
        server.start()
        try:
            mh, mp = server.metrics_address
            body = json.loads(urllib.request.urlopen(
                f"http://{mh}:{mp}/serve.json", timeout=5
            ).read())
            assert body["host"] == "hX"
        finally:
            server.stop()


# ---------------------------------------------------------------------------
# the obs serve CLI
# ---------------------------------------------------------------------------


class TestServeCLI:
    def write_snap(self, tmp_path, mux, name="h.json"):
        p = tmp_path / name
        p.write_text(json.dumps(mux.snapshot()))
        return str(p)

    def test_healthy_fleet_exits_zero(self, tmp_path, capsys):
        from peritext_tpu.obs.__main__ import main as obs_main

        mux = SessionMux(serve_session(num_docs=2), host="h0")
        mux.open_session("a")
        rc = obs_main(["serve", self.write_snap(tmp_path, mux)])
        assert rc == 0
        assert "h0" in capsys.readouterr().out

    def test_shedding_fleet_exits_one(self, tmp_path, capsys):
        from peritext_tpu.obs.__main__ import main as obs_main

        mux = SessionMux(serve_session(num_docs=2), host="h1")
        mux.submit(42, b"x")  # typed unknown-session shed
        rc = obs_main(["serve", self.write_snap(tmp_path, mux)])
        assert rc == 1
        out = capsys.readouterr().out
        assert "unknown-session" in out

    def test_overloaded_fleet_exits_one(self, tmp_path):
        from peritext_tpu.obs.__main__ import main as obs_main

        mux = SessionMux(
            serve_session(num_docs=2),
            admission=AdmissionController(
                max_depth=4, high_watermark=0.5, low_watermark=0.25,
                session_quota=None,
            ),
            host="h2",
        )
        sid, _ = mux.open_session("a")
        for f in doc_frames(seed=33, num_docs=1)[0][:3]:
            mux.submit(sid, f)
        assert mux.overloaded
        rc = obs_main(["serve", self.write_snap(tmp_path, mux)])
        assert rc == 1

    def test_recovered_host_stops_reporting_unhealthy(self, tmp_path):
        """Sheds are lifetime counters but health reads RECENCY: after the
        tier recovers (rounds commit with backpressure clear), the same
        host's scrape must exit 0 even though verdicts.shed stays > 0."""
        from peritext_tpu.obs.__main__ import main as obs_main

        plans = doc_frames(seed=33, num_docs=1)
        mux = SessionMux(serve_session(num_docs=1), host="h4")
        mux.submit(99, b"x")  # one historical typed shed
        rc = obs_main(["serve", self.write_snap(tmp_path, mux)])
        assert rc == 1  # unhealthy while the shed is recent
        sid, _ = mux.open_session("a")
        mux.submit(sid, plans[0][0])
        mux.flush()  # a clean committed round: the tier is keeping up
        snap = mux.snapshot()
        assert snap["queue"]["verdicts"]["shed"] == 1  # history intact
        assert snap["recent_sheds"] == 0
        rc = obs_main(["serve", self.write_snap(tmp_path, mux)])
        assert rc == 0

    def test_health_json_body_unwraps(self, tmp_path):
        from peritext_tpu.obs import health_snapshot
        from peritext_tpu.obs.__main__ import main as obs_main

        mux = SessionMux(serve_session(num_docs=2), host="h3")
        p = tmp_path / "health.json"
        p.write_text(json.dumps(health_snapshot(serve=mux), default=str))
        assert obs_main(["serve", str(p)]) == 0

    def test_unreadable_snapshot_exits_two(self, tmp_path):
        from peritext_tpu.obs.__main__ import main as obs_main

        p = tmp_path / "junk.json"
        p.write_text("{\"not\": \"a serve snapshot\"}")
        assert obs_main(["serve", str(p)]) == 2


# ---------------------------------------------------------------------------
# static_rounds shape discipline
# ---------------------------------------------------------------------------


class TestStaticRounds:
    def test_static_rounds_matches_adaptive_digest(self):
        plans = doc_frames(seed=44, num_docs=3, ops_per_doc=50)
        static = serve_session(num_docs=3, ops_per_doc=50)
        adaptive = StreamingMerge(
            num_docs=3, actors=ACTORS, slot_capacity=256,
            mark_capacity=64, tomb_capacity=160,
            round_insert_capacity=128, round_delete_capacity=64,
            round_mark_capacity=64,
        )
        for s in (static, adaptive):
            for doc, plan in enumerate(plans):
                for f in plan:
                    s.ingest_frame(doc, f)
                s.drain()
        assert static.digest() == adaptive.digest()
        for doc in range(3):
            assert static.read(doc) == adaptive.read(doc)

    def test_static_rounds_no_per_composition_apply_variants(self):
        """The shape-discipline claim: a DIFFERENT batch composition in a
        fresh static_rounds session never reaches the flat/compact apply
        paths (whose stream buckets mint per-composition XLA variants) —
        any residual compiles come only from the bounded pow-2 ladders
        (slot window, digest row gather)."""
        from peritext_tpu.obs import RecompileSentinel

        plans = doc_frames(seed=44, num_docs=3, ops_per_doc=50)
        warm = serve_session(num_docs=3, ops_per_doc=50)
        for doc, plan in enumerate(plans):
            for f in plan:
                warm.ingest_frame(doc, f)
            warm.drain()
        warm.digest()
        sentinel = RecompileSentinel()
        sentinel.start()
        try:
            replay = serve_session(num_docs=3, ops_per_doc=50)
            # a different composition: wave-interleaved instead of per-doc
            for k in range(max(len(p) for p in plans)):
                replay.ingest_frames([
                    (doc, plan[k]) for doc, plan in enumerate(plans)
                    if k < len(plan)
                ])
                replay.drain()
            assert replay.digest() == warm.digest()
            assert not any(
                "compact" in site for site in sentinel.counts
            ), f"static_rounds leaked a flat-path variant: {dict(sentinel.counts)}"
            assert sentinel.total <= 8, (
                f"compile count beyond the bounded ladders: "
                f"{dict(sentinel.counts)}"
            )
        finally:
            sentinel.stop()
