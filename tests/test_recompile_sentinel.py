"""Runtime recompile sentinel: the compile-shape discipline, enforced.

The streaming engine's whole throughput story rests on "one compiled
program per session" — every per-round tensor is padded to a static width,
so after warmup NO round may trigger XLA compilation (the hazard the width
buckets in parallel/streaming.py exist to prevent, and the runtime half of
graftlint's PTL004).  These tests pin that invariant with a live counter
instead of a comment.
"""

import random

import jax
import jax.numpy as jnp

from peritext_tpu.observability import health_snapshot
from peritext_tpu.parallel.streaming import StreamingMerge
from peritext_tpu.testing.fuzz import generate_workload

ACTORS = ("doc1", "doc2", "doc3")


def _arrival_rounds(workloads, rounds, rng):
    """Split each doc's change logs into ``rounds`` shuffled arrival
    batches (the steady-state shape: new changes every round, same static
    widths)."""
    arrival = []
    for workload in workloads:
        changes = [ch for log in workload.values() for ch in log]
        rng.shuffle(changes)
        size = -(-len(changes) // rounds)
        arrival.append(
            [changes[i : i + size] for i in range(0, len(changes), size)]
        )
    return arrival


def _run_schedule(session, arrival, rounds):
    for r in range(rounds):
        for d, batches in enumerate(arrival):
            if r < len(batches):
                session.ingest(d, batches[r])
        session.drain()
        session.digest()
    return session.read_all()


def test_sentinel_counts_per_site_compiles(recompile_sentinel):
    """The sentinel sees a fresh jit compile exactly once per signature."""
    recompile_sentinel.mark()

    @jax.jit
    def _sentinel_probe(x):
        return x * 2 + 1

    _sentinel_probe(jnp.ones(3))
    first = sum(recompile_sentinel.since_mark().values())
    assert first >= 1  # fresh function, fresh signature: compiled
    recompile_sentinel.mark()
    _sentinel_probe(jnp.ones(3))  # same signature: cache hit, no compile
    assert recompile_sentinel.since_mark() == {}
    recompile_sentinel.mark()
    _sentinel_probe(jnp.ones(7))  # new shape: recompiles, and we see it
    assert sum(recompile_sentinel.since_mark().values()) >= 1


def test_health_snapshot_exports_recompile_counters(recompile_sentinel):
    """Tier-1 smoke: compile counts surface through health_snapshot both as
    jit.* counters and as the per-site dict."""

    @jax.jit
    def _snapshot_probe(x):
        return x + 1

    _snapshot_probe(jnp.ones(2))
    snap = health_snapshot(sentinel=recompile_sentinel)
    assert snap["recompiles"]["total"] >= 1
    assert any(site for site in snap["recompiles"]["sites"])
    assert snap["counters"].get("jit.compiles_total", 0) >= 1
    assert any(k.startswith("jit.compiles.") for k in snap["counters"])


def test_steady_state_streaming_rounds_zero_recompiles(recompile_sentinel):
    """The fleet steady-state contract: once a workload shape has been seen,
    serving it again — a fresh session, same config, same arrival shapes —
    dispatches only already-compiled programs.  ZERO compiles.

    (Within a single cold session the width buckets intentionally mint a
    small logarithmic variant set as docs grow — that is the compile-cache
    design, not a hazard.  The hazard PTL004 and this sentinel guard is
    unbounded variant minting: any per-doc shape that escapes the padded
    tables makes the replay below recompile, and this test fail.)"""

    def fresh_session():
        return StreamingMerge(
            num_docs=4,
            actors=ACTORS,
            round_insert_capacity=32,
            round_delete_capacity=16,
            round_mark_capacity=16,
        )

    workloads = generate_workload(seed=21, num_docs=4, ops_per_doc=60)
    arrival = _arrival_rounds(workloads, rounds=6, rng=random.Random(5))
    # cold run: compiles every program variant this schedule needs
    cold = _run_schedule(fresh_session(), arrival, rounds=6)

    recompile_sentinel.mark()
    warm = _run_schedule(fresh_session(), arrival, rounds=6)
    recompile_sentinel.assert_steady_state("steady-state streaming rounds")
    assert warm == cold  # replay converges byte-equal, and compiled nothing


def test_mixed_size_drain_one_ragged_executable(recompile_sentinel):
    """The ragged layout's headline, pinned live: a tweet fleet + an essay
    + a book-scale doc drain through ONE compiled ragged apply — per-doc op
    and page counts are data, so the size mix cannot mint shapes.  The
    paged engine on the IDENTICAL schedule splits the same mix across its
    power-of-two bucket ladder and compiles several apply variants; that
    contrast is the point, so it is asserted too."""
    tweets = generate_workload(seed=31, num_docs=6, ops_per_doc=10)
    essay = generate_workload(seed=32, num_docs=1, ops_per_doc=120)
    book = generate_workload(seed=33, num_docs=1, ops_per_doc=400)
    workloads = tweets + essay + book
    rounds = 5
    arrival = _arrival_rounds(workloads, rounds=rounds, rng=random.Random(7))

    def session(layout):
        return StreamingMerge(
            num_docs=8,
            actors=ACTORS,
            slot_capacity=512,
            mark_capacity=64,
            tomb_capacity=64,
            round_insert_capacity=128,
            round_delete_capacity=32,
            round_mark_capacity=32,
            layout=layout,
            # pre-sized pool: growth mid-drain would change the pool
            # shape, which recompiles HONESTLY — sizing is the deployer's
            # lever, shape stability is the layout's
            pool_pages=64,
        )

    recompile_sentinel.mark()
    ragged_reads = _run_schedule(session("ragged"), arrival, rounds)
    ragged_compiles = recompile_sentinel.since_mark().get(
        "apply_batch_ragged", 0
    )
    assert ragged_compiles == 1, (
        f"mixed-size drain minted {ragged_compiles} ragged apply "
        "executables; the whole layout exists to make this 1"
    )

    recompile_sentinel.mark()
    paged_reads = _run_schedule(session("paged"), arrival, rounds)
    paged_compiles = sum(
        n for site, n in recompile_sentinel.since_mark().items()
        if "apply_batch_paged" in site
    )
    assert paged_compiles > 1  # the bucket ladder, observed
    assert ragged_reads == paged_reads  # same bytes, fewer programs


# ---------------------------------------------------------------------------
# log-record parsing regression (ISSUE 3 satellite): the sentinel must
# tolerate prefixed and multi-line jax log_compiles records
# ---------------------------------------------------------------------------

import logging

from peritext_tpu.obs.sentinel import _COMPILE_MSG_RE

#: VERBATIM record messages captured from the current jax pin (0.4.37,
#: CPU backend, jax_log_compiles=True) — see the emitting sites in
#: jax._src.interpreters.pxla / jax._src.dispatch.  If a jax upgrade
#: changes these shapes, re-capture and extend; the sentinel must keep
#: counting exactly the "Compiling <site>" records.
VERBATIM_JAX_0_4_37 = [
    ("Finished tracing + transforming convert_element_type for pjit "
     "in 0.000578880 sec", None),
    ("Compiling convert_element_type with global shapes and types "
     "[ShapedArray(float32[])]. Argument mapping: (UnspecifiedValue,).",
     "convert_element_type"),
    ("Finished jaxpr to MLIR module conversion jit(convert_element_type) "
     "in 0.026105642 sec", None),
    ("Finished XLA compilation of jit(convert_element_type) "
     "in 0.014521360 sec", None),
    ("Compiling f with global shapes and types [ShapedArray(float32[3])]. "
     "Argument mapping: (UnspecifiedValue,).", "f"),
    ("Finished tracing + transforming multiply for pjit in 0.001347542 sec",
     None),
]

#: shapes the regex must ALSO tolerate: a formatter-prefixed record and a
#: multi-line record with "Finished tracing" noise batched ahead of the
#: Compiling line (both observed from handlers downstream of other logging
#: layers)
HOSTILE_SHAPES = [
    ("WARNING:2026-08-03 23:17:59,392:jax._src.interpreters.pxla:1906: "
     "Compiling f with global shapes and types [ShapedArray(float32[3])].",
     "f"),
    ("Finished tracing + transforming f for pjit in 0.003565311 sec\n"
     "Compiling f with global shapes and types [ShapedArray(float32[3])]. "
     "Argument mapping: (UnspecifiedValue,).", "f"),
    # prose containing "compilation"/"Recompiling" must NOT count
    ("Finished XLA compilation of jit(f) in 0.081711054 sec", None),
    ("Recompiling is not what this says", None),
]


def test_compile_regex_on_verbatim_and_hostile_records():
    for message, site in VERBATIM_JAX_0_4_37 + HOSTILE_SHAPES:
        m = _COMPILE_MSG_RE.search(message)
        if site is None:
            assert m is None, f"false positive on: {message!r}"
        else:
            assert m is not None and m.group(1) == site, message


def test_sentinel_counts_prefixed_and_multiline_records():
    """End-to-end through logging.Handler.emit with hostile record shapes:
    the per-site counts must land exactly once per Compiling record."""
    from peritext_tpu.observability import Counters, RecompileSentinel

    counters = Counters()
    sentinel = RecompileSentinel(counters=counters)
    for message, _ in VERBATIM_JAX_0_4_37 + HOSTILE_SHAPES:
        record = logging.LogRecord(
            "jax._src.interpreters.pxla", logging.WARNING, __file__, 1,
            message, None, None,
        )
        sentinel.emit(record)
    expected_sites = [s for _, s in VERBATIM_JAX_0_4_37 + HOSTILE_SHAPES if s]
    assert sentinel.total == len(expected_sites)
    assert sentinel.counts == {"convert_element_type": 1, "f": 3}
    assert counters.get("jit.compiles_total") == len(expected_sites)
    assert counters.get("jit.compiles.f") == 3
