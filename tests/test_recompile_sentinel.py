"""Runtime recompile sentinel: the compile-shape discipline, enforced.

The streaming engine's whole throughput story rests on "one compiled
program per session" — every per-round tensor is padded to a static width,
so after warmup NO round may trigger XLA compilation (the hazard the width
buckets in parallel/streaming.py exist to prevent, and the runtime half of
graftlint's PTL004).  These tests pin that invariant with a live counter
instead of a comment.
"""

import random

import jax
import jax.numpy as jnp

from peritext_tpu.observability import health_snapshot
from peritext_tpu.parallel.streaming import StreamingMerge
from peritext_tpu.testing.fuzz import generate_workload

ACTORS = ("doc1", "doc2", "doc3")


def _arrival_rounds(workloads, rounds, rng):
    """Split each doc's change logs into ``rounds`` shuffled arrival
    batches (the steady-state shape: new changes every round, same static
    widths)."""
    arrival = []
    for workload in workloads:
        changes = [ch for log in workload.values() for ch in log]
        rng.shuffle(changes)
        size = -(-len(changes) // rounds)
        arrival.append(
            [changes[i : i + size] for i in range(0, len(changes), size)]
        )
    return arrival


def _run_schedule(session, arrival, rounds):
    for r in range(rounds):
        for d, batches in enumerate(arrival):
            if r < len(batches):
                session.ingest(d, batches[r])
        session.drain()
        session.digest()
    return session.read_all()


def test_sentinel_counts_per_site_compiles(recompile_sentinel):
    """The sentinel sees a fresh jit compile exactly once per signature."""
    recompile_sentinel.mark()

    @jax.jit
    def _sentinel_probe(x):
        return x * 2 + 1

    _sentinel_probe(jnp.ones(3))
    first = sum(recompile_sentinel.since_mark().values())
    assert first >= 1  # fresh function, fresh signature: compiled
    recompile_sentinel.mark()
    _sentinel_probe(jnp.ones(3))  # same signature: cache hit, no compile
    assert recompile_sentinel.since_mark() == {}
    recompile_sentinel.mark()
    _sentinel_probe(jnp.ones(7))  # new shape: recompiles, and we see it
    assert sum(recompile_sentinel.since_mark().values()) >= 1


def test_health_snapshot_exports_recompile_counters(recompile_sentinel):
    """Tier-1 smoke: compile counts surface through health_snapshot both as
    jit.* counters and as the per-site dict."""

    @jax.jit
    def _snapshot_probe(x):
        return x + 1

    _snapshot_probe(jnp.ones(2))
    snap = health_snapshot(sentinel=recompile_sentinel)
    assert snap["recompiles"]["total"] >= 1
    assert any(site for site in snap["recompiles"]["sites"])
    assert snap["counters"].get("jit.compiles_total", 0) >= 1
    assert any(k.startswith("jit.compiles.") for k in snap["counters"])


def test_steady_state_streaming_rounds_zero_recompiles(recompile_sentinel):
    """The fleet steady-state contract: once a workload shape has been seen,
    serving it again — a fresh session, same config, same arrival shapes —
    dispatches only already-compiled programs.  ZERO compiles.

    (Within a single cold session the width buckets intentionally mint a
    small logarithmic variant set as docs grow — that is the compile-cache
    design, not a hazard.  The hazard PTL004 and this sentinel guard is
    unbounded variant minting: any per-doc shape that escapes the padded
    tables makes the replay below recompile, and this test fail.)"""

    def fresh_session():
        return StreamingMerge(
            num_docs=4,
            actors=ACTORS,
            round_insert_capacity=32,
            round_delete_capacity=16,
            round_mark_capacity=16,
        )

    workloads = generate_workload(seed=21, num_docs=4, ops_per_doc=60)
    arrival = _arrival_rounds(workloads, rounds=6, rng=random.Random(5))
    # cold run: compiles every program variant this schedule needs
    cold = _run_schedule(fresh_session(), arrival, rounds=6)

    recompile_sentinel.mark()
    warm = _run_schedule(fresh_session(), arrival, rounds=6)
    recompile_sentinel.assert_steady_state("steady-state streaming rounds")
    assert warm == cold  # replay converges byte-equal, and compiled nothing
