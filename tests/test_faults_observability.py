"""Fault injection (SURVEY §5.3), permutation-invariance self-checks (§5.2),
and observability (§5.1/5.5) tests."""

import random

import pytest

from peritext_tpu.bridge import create_editor, initialize_docs
from peritext_tpu.bridge.commands import type_text
from peritext_tpu.core.doc import Doc
from peritext_tpu.observability import Counters, EventLog, MergeStats, profile_trace
from peritext_tpu.parallel.anti_entropy import apply_changes
from peritext_tpu.parallel.causal import causal_schedule
from peritext_tpu.parallel.faults import FaultSpec, FaultyPublisher, perturb_delivery
from peritext_tpu.testing.fuzz import FuzzState, full_sync, make_fuzz_state, fuzz_step, run_fuzz


class TestPerturbDelivery:
    def test_dropless_spec_preserves_set(self):
        state = run_fuzz(seed=1, iterations=15)
        changes = [ch for a in state.store.actors() for ch in state.store.log(a)]
        rng = random.Random(0)
        out = perturb_delivery(changes, rng, FaultSpec(reorder=True))
        assert sorted(id(c) for c in out) == sorted(id(c) for c in changes)

    def test_drops_and_dups(self):
        state = run_fuzz(seed=1, iterations=30)
        changes = [ch for a in state.store.actors() for ch in state.store.log(a)]
        rng = random.Random(0)
        out = perturb_delivery(changes, rng, FaultSpec(drop_p=0.5, dup_p=0.3))
        keys = [(c.actor, c.seq) for c in out]
        assert len(set(keys)) < len(changes)  # some dropped
        assert len(keys) != len(set(keys)) or len(keys) == 0 or True  # dups allowed


class TestFuzzUnderFaults:
    @pytest.mark.parametrize("seed", [0, 7])
    def test_faulty_session_converges_after_repair(self, seed):
        faults = FaultSpec(drop_p=0.25, dup_p=0.25, reorder=True)
        state = make_fuzz_state(seed)
        for _ in range(80):
            fuzz_step(state, check=True, faults=faults)
        # repair round: clean anti-entropy to the store frontier
        full_sync(state)
        spans = [d.get_text_with_formatting(["text"]) for d in state.docs]
        assert spans[0] == spans[1] == spans[2]
        clocks = [d.clock for d in state.docs]
        assert clocks[0] == clocks[1] == clocks[2]


class TestFaultyPublisher:
    def test_drops_diverge_then_redelivery_converges(self):
        pub = FaultyPublisher(FaultSpec(drop_p=1.0), seed=1)
        alice = create_editor("alice", pub)
        bob = create_editor("bob", pub)
        initialize_docs([alice, bob], "base")
        type_text(alice, 1, "lost ")
        alice.sync()
        assert bob.text == "base"  # dropped
        assert pub.dropped_count == 1
        redelivered = pub.redeliver_lost()
        assert redelivered == 1
        assert bob.text == "lost base"
        assert alice.view == bob.view

    def test_dup_reorder_tolerated(self):
        pub = FaultyPublisher(FaultSpec(drop_p=0.0, dup_p=0.6, reorder=True), seed=3)
        alice = create_editor("alice", pub)
        bob = create_editor("bob", pub)
        initialize_docs([alice, bob], "seed")
        for i in range(10):
            type_text(alice, 1, "a")
            type_text(bob, 1, "b")
            if i % 3 == 0:
                alice.sync()
                bob.sync()
        alice.sync()
        bob.sync()
        assert alice.view == bob.view


class TestPermutationInvariance:
    """The §5.2 race-detection analog: the merge fixpoint must be independent
    of any causally-admissible delivery order."""

    def test_scalar_fixpoint_under_20_permutations(self):
        state = run_fuzz(seed=13, iterations=50)
        changes = [ch for a in state.store.actors() for ch in state.store.log(a)]
        rng = random.Random(99)
        reference_spans = None
        for _ in range(20):
            rng.shuffle(changes)
            doc = Doc("perm")
            apply_changes(doc, list(changes))
            spans = doc.get_text_with_formatting(["text"])
            if reference_spans is None:
                reference_spans = spans
            assert spans == reference_spans

    def test_device_fixpoint_under_permutations(self):
        from peritext_tpu.api.batch import DocBatch
        from peritext_tpu.testing.fuzz import generate_workload

        workload = generate_workload(seed=21, num_docs=1, ops_per_doc=50)[0]
        batch = DocBatch(slot_capacity=192, mark_capacity=64, jit=False)
        rng = random.Random(5)
        baseline = None
        for _ in range(5):
            # shuffle the per-actor log dict ordering AND feed different doc
            # orderings; encode does its own causal scheduling
            actors = list(workload.items())
            rng.shuffle(actors)
            report = batch.merge([dict(actors)])
            if baseline is None:
                baseline = report.spans[0]
            assert report.spans[0] == baseline


class TestCausalSchedule:
    def test_stuck_changes_returned_not_raised(self):
        state = run_fuzz(seed=2, iterations=10)
        actor = state.store.actors()[0]
        log = state.store.log(actor)
        assert len(log) >= 2
        # deliver only the tail: its predecessor is missing -> stuck
        ordered, stuck = causal_schedule([log[-1]], base_clock={})
        assert ordered == [] and stuck == [log[-1]]


class TestObservability:
    def test_counters_and_timers(self):
        c = Counters()
        c.add("x")
        c.add("x", 2)
        with c.timed("t"):
            pass
        snap = c.snapshot()
        assert snap["x"] == 3 and snap["t"] >= 0
        c.reset()
        assert c.snapshot() == {}

    def test_event_log_sink_and_file(self, tmp_path):
        path = tmp_path / "events.jsonl"
        log = EventLog(path=path)
        pub_events = log.emit("custom", foo=1)
        assert pub_events["seq"] == 1

        from peritext_tpu.parallel.pubsub import Publisher

        pub = Publisher()
        alice = create_editor("alice", pub, on_event=log)
        bob = create_editor("bob", pub)
        initialize_docs([alice, bob])
        type_text(alice, 1, "hi")
        alice.sync()
        kinds = {e["kind"] for e in log.events()}
        assert "editor.local-change" in kinds and "editor.flush" in kinds
        assert path.read_text().count("\n") == len(log.events())
        log.close()

    def test_event_log_capacity_bounds_memory(self):
        log = EventLog(capacity=5)
        for i in range(12):
            log.emit("k", i=i)
        events = log.events()
        assert len(events) == 5 and events[-1]["i"] == 11

    def test_merge_stats_populated(self):
        from peritext_tpu.api.batch import DocBatch
        from peritext_tpu.testing.fuzz import generate_workload

        workloads = generate_workload(seed=1, num_docs=4, ops_per_doc=30)
        report = DocBatch(slot_capacity=192, mark_capacity=64, jit=False).merge(workloads)
        s = report.stats
        assert s.docs == 4
        assert s.device_docs + s.fallback_docs == 4
        assert s.device_ops == report.device_ops > 0
        assert 0 < s.padding_efficiency <= 1
        assert s.apply_seconds > 0
        d = s.to_json()
        assert d["device_ops_per_sec"] > 0

    def test_profile_trace_noop_safe(self, tmp_path):
        with profile_trace(tmp_path, enabled=False):
            pass
        # enabled path must not raise even if profiler unavailable
        with profile_trace(tmp_path / "t", enabled=True):
            pass
