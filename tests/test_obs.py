"""Telemetry subsystem unit tests (ISSUE 3): tracer spans, histograms,
flight recorder, EventLog hygiene, exporters, the health-snapshot golden
shape, and the ``python -m peritext_tpu.obs`` renderer."""

import builtins
import json
import urllib.request

import pytest

from peritext_tpu.obs import (
    EventLog,
    FlightRecorder,
    GLOBAL_HISTOGRAMS,
    Histogram,
    HistogramRegistry,
    MetricsServer,
    SIZE_BUCKETS,
    TraceContext,
    Tracer,
    health_snapshot,
    merge_traces,
    prometheus_text,
)
from peritext_tpu.obs.__main__ import load_spans, main as obs_main, summarize


# ---------------------------------------------------------------------------
# spans
# ---------------------------------------------------------------------------


class TestTracer:
    def test_nesting_and_monotonic_ids(self):
        t = Tracer(host="h", enabled=True, trace_id=0xABC)
        with t.span("outer") as outer:
            with t.span("inner") as inner:
                with t.span("leaf") as leaf:
                    pass
        assert outer.span_id < inner.span_id < leaf.span_id
        assert inner.parent_id == outer.span_id
        assert leaf.parent_id == inner.span_id
        assert outer.parent_id == 0
        assert {s.trace_id for s in (outer, inner, leaf)} == {0xABC}
        assert all(s.duration >= 0 for s in (outer, inner, leaf))

    def test_context_adoption_joins_remote_trace(self):
        t = Tracer(host="h", enabled=True, trace_id=0x1)
        with t.span("serve", ctx=TraceContext(0x99, 42)) as sp:
            with t.span("child") as child:
                pass
        assert sp.trace_id == 0x99 and sp.parent_id == 42
        # children inherit the adopted trace, not the tracer's own
        assert child.trace_id == 0x99 and child.parent_id == sp.span_id

    def test_disabled_tracer_measures_but_retains_nothing(self):
        t = Tracer(host="h", enabled=False)
        with t.span("x") as sp:
            pass
        assert sp.duration >= 0  # stats consumers still get a duration
        assert t.spans() == []

    def test_sink_receives_spans_without_enabling(self):
        t = Tracer(host="h", enabled=False)
        got = []
        t.add_sink(got.append)
        with t.span("x"):
            pass
        assert [s.name for s in got] == ["x"]
        assert t.spans() == []  # sink-only: nothing retained

    def test_error_is_recorded_and_reraised(self):
        t = Tracer(host="h", enabled=True)
        with pytest.raises(RuntimeError):
            with t.span("boom"):
                raise RuntimeError("nope")
        (sp,) = t.spans()
        assert "nope" in sp.args["error"]

    def test_span_ids_unique_across_tracers(self):
        """Two hosts' spans can share one trace id (wire-carried context),
        so their span ids must come from disjoint ranges or parent links in
        a merged trace are ambiguous."""
        a, b = Tracer(host="a", enabled=True), Tracer(host="b", enabled=True)
        for t in (a, b):
            for _ in range(50):
                with t.span("x"):
                    pass
        ids_a = {s.span_id for s in a.spans()}
        ids_b = {s.span_id for s in b.spans()}
        assert len(ids_a) == len(ids_b) == 50
        assert not ids_a & ids_b

    def test_ambient_parent_carries_span_across_threads(self):
        import threading

        from peritext_tpu.obs import ambient_parent

        t = Tracer(host="h", enabled=True)
        inner = []

        def worker(parent):
            with ambient_parent(parent):
                with t.span("child") as sp:
                    inner.append(sp)

        with t.span("outer") as outer:
            th = threading.Thread(target=worker, args=(outer,))
            th.start()
            th.join()
        assert inner[0].parent_id == outer.span_id
        assert inner[0].trace_id == outer.trace_id

    def test_chrome_trace_schema_and_merge(self):
        a = Tracer(host="hostA", enabled=True, trace_id=0x7)
        b = Tracer(host="hostB", enabled=True, trace_id=0x7)
        with a.span("stage"):
            pass
        with b.span("stage"):
            pass
        merged = merge_traces(a.chrome_trace(), b.chrome_trace())
        json.dumps(merged)  # Perfetto-loadable JSON
        events = [e for e in merged["traceEvents"] if e.get("ph") == "X"]
        assert len(events) == 2
        for e in events:
            assert {"name", "cat", "ph", "ts", "dur", "pid", "tid", "args"} <= set(e)
            assert e["dur"] >= 1
        assert {e["args"]["trace_id"] for e in events} == {f"{0x7:016x}"}
        # process_name metadata rows name both hosts
        metas = [e for e in merged["traceEvents"] if e.get("ph") == "M"]
        assert {m["args"]["name"] for m in metas} == {"hostA", "hostB"}


# ---------------------------------------------------------------------------
# histograms
# ---------------------------------------------------------------------------


class TestHistogram:
    def test_percentiles_read_bucket_upper_bounds(self):
        h = Histogram(buckets=(0.01, 0.1, 1.0))
        for _ in range(98):
            h.observe(0.005)  # bucket le=0.01
        h.observe(0.5)  # bucket le=1.0
        h.observe(5.0)  # overflow bucket
        assert h.p50 == 0.01
        assert h.percentile(0.99) == 1.0
        assert h.percentile(1.0) == 5.0  # overflow reads the observed max
        assert h.count == 100

    def test_rolling_window_evicts(self):
        h = Histogram(buckets=(0.01, 1.0), window=4)
        for _ in range(10):
            h.observe(5.0)  # slow history
        for _ in range(4):
            h.observe(0.005)  # fast recent window
        assert h.count == 4
        assert h.p99 == 0.01  # the slow history no longer dominates
        assert h.sum == pytest.approx(0.02)

    def test_empty_is_zero(self):
        h = Histogram()
        assert h.p50 == 0.0 and h.count == 0 and h.snapshot()["p99"] == 0.0

    def test_registry_timer_and_snapshot(self):
        reg = HistogramRegistry()
        with reg.timed("streaming.test_seconds"):
            pass
        reg.observe("streaming.test_sizes", 42, buckets=SIZE_BUCKETS)
        snap = reg.snapshot()
        assert snap["streaming.test_seconds"]["count"] == 1
        assert snap["streaming.test_sizes"]["p50"] == 50  # bucket upper bound
        json.dumps(snap)


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------


class TestFlightRecorder:
    def test_ring_is_bounded(self):
        r = FlightRecorder(capacity=4)
        for i in range(10):
            r.record("event", i=i)
        entries = r.entries()
        assert len(entries) == 4
        assert [e["i"] for e in entries] == [6, 7, 8, 9]

    def test_fault_auto_dumps_jsonl(self, tmp_path):
        r = FlightRecorder(capacity=16, dump_dir=tmp_path / "fl", fsync=True)
        t = Tracer(host="h", enabled=False)
        t.add_sink(r.record_span)
        with t.span("streaming.round"):
            pass
        r.fault("quarantine", doc=3, quarantine_reason="decode")
        dumps = list((tmp_path / "fl").glob("*.jsonl"))
        assert len(dumps) == 1
        records = [json.loads(line) for line in dumps[0].read_text().splitlines()]
        assert records[0]["kind"] == "dump" and records[0]["reason"] == "quarantine"
        kinds = {rec["kind"] for rec in records}
        assert {"span", "fault"} <= kinds
        (fault,) = [rec for rec in records if rec["kind"] == "fault"]
        assert fault["doc"] == 3 and fault["quarantine_reason"] == "decode"

    def test_default_dump_names_unique_across_instances(self, tmp_path):
        """Two recorders sharing a dump_dir (the crash-restore pattern)
        must never overwrite each other's post-mortems."""
        r1 = FlightRecorder(capacity=4, dump_dir=tmp_path,
                            min_dump_interval=0.0)
        r1.fault("quarantine", doc=0)
        r2 = FlightRecorder(capacity=4, dump_dir=tmp_path,
                            min_dump_interval=0.0)  # "restored" instance
        r2.fault("quarantine", doc=0)
        dumps = list(tmp_path.glob("*.jsonl"))
        assert len(dumps) == 2

    def test_dump_throttle(self, tmp_path):
        r = FlightRecorder(capacity=4, dump_dir=tmp_path, min_dump_interval=3600)
        r.fault("quarantine", doc=0)
        r.fault("quarantine", doc=1)  # inside the interval: no second dump
        assert r.dumps == 1 and r.faults == 2
        snap = r.snapshot()
        assert snap["dumps"] == 1 and snap["faults"] == 2
        assert snap["last_dump"] is not None


# ---------------------------------------------------------------------------
# EventLog hygiene (satellite)
# ---------------------------------------------------------------------------


class TestEventLog:
    def test_context_manager_closes_file(self, tmp_path):
        path = tmp_path / "events.jsonl"
        with EventLog(path, fsync=True) as log:
            log.emit("test", n=1)
            handle = log._file
        assert handle.closed and log._file is None
        assert json.loads(path.read_text().splitlines()[0])["kind"] == "test"

    def test_bad_capacity_mid_init_does_not_leak_handle(self, tmp_path, monkeypatch):
        opened = []
        real_open = builtins.open

        def tracking_open(*args, **kwargs):
            f = real_open(*args, **kwargs)
            opened.append(f)
            return f

        monkeypatch.setattr(builtins, "open", tracking_open)
        with pytest.raises(ValueError):
            EventLog(tmp_path / "leak.jsonl", capacity=-1)
        assert len(opened) == 1 and opened[0].closed

    def test_capacity_still_bounds_memory(self, tmp_path):
        log = EventLog(capacity=3)
        for i in range(9):
            log.emit("e", i=i)
        assert [e["i"] for e in log.events()] == [6, 7, 8]


# ---------------------------------------------------------------------------
# exporters + health snapshot golden shape (satellite)
# ---------------------------------------------------------------------------


#: exporter-schema pins: drift in these key sets breaks fleet scrapers, so
#: it must be a deliberate, test-visible change
GOLDEN_SNAPSHOT_KEYS = {"counters", "histograms", "session", "flight_recorder"}
GOLDEN_SESSION_KEYS = {
    # streaming session health
    "rounds", "num_docs", "pending_changes", "fallback_docs", "frame_docs",
    "round_padding_efficiency", "padding_efficiency_cum", "quarantined",
    # supervisor overlay
    "rollbacks", "checkpoints", "journal_frames", "deadline_seconds",
    "deadline_static", "deadline_floor", "deadline_ceiling",
    "deadline_autotuned", "round_latency", "flight_recorder",
}


class TestHealthSnapshotShape:
    def test_composed_snapshot_golden_shape(self, tmp_path):
        from peritext_tpu.obs import RecompileSentinel
        from peritext_tpu.parallel.supervisor import GuardedSession
        from peritext_tpu.testing.fuzz import _campaign_session

        guarded = GuardedSession(
            lambda: _campaign_session(1, 20), tmp_path, deadline=120.0
        )
        guarded.ingest_frame(0, b"garbage")  # one quarantine for the registry
        sentinel = RecompileSentinel()
        snap = health_snapshot(
            session=guarded, sentinel=sentinel, recorder=guarded.recorder
        )
        assert set(snap) == GOLDEN_SNAPSHOT_KEYS | {"recompiles"}
        assert set(snap["session"]) == GOLDEN_SESSION_KEYS
        assert set(snap["flight_recorder"]) == {
            "capacity", "size", "faults", "dumps", "last_dump",
        }
        assert set(snap["session"]["round_latency"]) == {
            "count", "sum", "max", "p50", "p95", "p99", "overflow",
        }
        # every histogram entry shares the percentile schema
        for entry in snap["histograms"].values():
            assert {"count", "p50", "p95", "p99"} <= set(entry)
        json.dumps(snap, default=str)  # one JSON document, end to end
        # fault-domain namespacing holds across every surface
        prefixes = ("streaming.", "transport.", "supervisor.", "merge.",
                    "jit.", "convergence.", "serve.", "fleet.")
        assert all(k.startswith(prefixes) for k in snap["counters"])
        assert all(k.startswith(prefixes) for k in snap["histograms"])

    def test_prometheus_text_format(self, tmp_path):
        GLOBAL_HISTOGRAMS.observe("streaming.prom_test_seconds", 0.02)
        text = prometheus_text()
        assert "# TYPE peritext_streaming_prom_test_seconds histogram" in text
        assert 'peritext_streaming_prom_test_seconds_bucket{le="+Inf"}' in text
        assert "peritext_streaming_prom_test_seconds_count" in text
        for line in text.splitlines():
            assert line.startswith("#") or len(line.split()) == 2

    def test_prometheus_ragged_gauges(self):
        from peritext_tpu.obs import DeviceProfiler

        prof = DeviceProfiler()
        # padded/paged-only profiles carry no section and emit no gauges
        assert prof.snapshot()["ragged"] is None
        assert "peritext_ragged_dispatches" not in prometheus_text(devprof=prof)
        with prof:
            prof.observe_ragged(docs_walked=7, pages_walked=19, real_ops=140)
            prof.observe_ragged(docs_walked=7, pages_walked=19, real_ops=60)
        snap = prof.snapshot()["ragged"]
        assert snap == {
            "dispatches": 2, "docs_walked": 14, "pages_walked": 38,
            "real_ops": 200, "padded_slot_waste": 0,
        }
        text = prometheus_text(devprof=prof)
        assert "peritext_ragged_dispatches 2" in text
        assert "peritext_ragged_docs_walked 14" in text
        assert "peritext_ragged_pages_walked 38" in text
        assert "peritext_ragged_real_ops 200" in text
        # the layout's headline: no padded slots ever dispatched
        assert "peritext_ragged_padded_slot_waste 0" in text
        for line in text.splitlines():
            assert line.startswith("#") or len(line.split()) == 2

    def test_metrics_server_endpoints(self):
        tracer = Tracer(host="metrics-test", enabled=True)
        with tracer.span("probe"):
            pass
        server = MetricsServer(tracer=tracer)
        host, port = server.start()
        try:
            with urllib.request.urlopen(f"http://{host}:{port}/metrics") as resp:
                assert resp.status == 200
                assert b"peritext_" in resp.read()
            with urllib.request.urlopen(f"http://{host}:{port}/health.json") as resp:
                snap = json.loads(resp.read())
                assert "counters" in snap and "histograms" in snap
            with urllib.request.urlopen(f"http://{host}:{port}/trace.json") as resp:
                trace = json.loads(resp.read())
                assert any(
                    e.get("name") == "probe" for e in trace["traceEvents"]
                )
            req = urllib.request.Request(f"http://{host}:{port}/nope")
            with pytest.raises(urllib.error.HTTPError):
                urllib.request.urlopen(req)
        finally:
            server.stop()

    def test_metrics_server_stop_without_start_returns(self):
        import threading

        server = MetricsServer()
        stopper = threading.Thread(target=server.stop)
        stopper.start()
        stopper.join(timeout=2)
        assert not stopper.is_alive(), "stop() before start() must not hang"


# ---------------------------------------------------------------------------
# the CLI renderer
# ---------------------------------------------------------------------------


class TestObsCli:
    def _trace_file(self, tmp_path):
        t = Tracer(host="cli-host", enabled=True, trace_id=0x5)
        for _ in range(3):
            with t.span("streaming.apply"):
                pass
        path = tmp_path / "trace.json"
        t.write_chrome_trace(path)
        return path

    def test_summary_table(self, tmp_path, capsys):
        path = self._trace_file(tmp_path)
        assert obs_main([str(path)]) == 0  # summary is the default command
        out = capsys.readouterr().out
        assert "streaming.apply" in out and "cli-host" in out
        assert "p95_ms" in out

    def test_summary_reads_flight_jsonl(self, tmp_path, capsys):
        r = FlightRecorder(capacity=8)
        t = Tracer(host="fl-host")
        t.add_sink(r.record_span)
        with t.span("supervisor.round"):
            pass
        dump = r.dump(tmp_path / "flight.jsonl")
        assert obs_main(["summary", str(dump), "--json"]) == 0
        rows = json.loads(capsys.readouterr().out)
        assert rows[0]["stage"] == "supervisor.round"
        assert rows[0]["host"] == "fl-host"

    def test_merge_command(self, tmp_path, capsys):
        a, b = self._trace_file(tmp_path), tmp_path / "b.json"
        t = Tracer(host="other", enabled=True)
        with t.span("batch.merge"):
            pass
        t.write_chrome_trace(b)
        out = tmp_path / "merged.json"
        assert obs_main(["merge", "-o", str(out), str(a), str(b)]) == 0
        merged = json.loads(out.read_text())
        names = {e["name"] for e in merged["traceEvents"]}
        assert {"streaming.apply", "batch.merge"} <= names
        spans = load_spans(out)
        assert {row["stage"] for row in summarize(spans)} == {
            "streaming.apply", "batch.merge",
        }

    def test_unreadable_and_empty_exit_codes(self, tmp_path, capsys):
        assert obs_main([str(tmp_path / "missing.json")]) == 2
        empty = tmp_path / "empty.json"
        empty.write_text(json.dumps({"traceEvents": []}))
        assert obs_main([str(empty)]) == 1
