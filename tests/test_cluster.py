"""Capstone integration: the whole stack in one scenario.

Two "hosts", each with a mesh-sharded StreamingMerge session over 4 virtual
devices, replicate a set of collaborative documents over real TCP sockets
(binary codec frames, frame-native ingest).  Midway, one host checkpoints,
"crashes", restores from the checkpoint, and catches up via anti-entropy.
Everything must converge to the scalar oracle: spans, digests, and the
surviving host's accumulated patch streams.
"""

import numpy as np
import pytest

import jax
from jax.sharding import Mesh

from peritext_tpu.api.batch import _oracle_doc
from peritext_tpu.checkpoint import restore_session, save_session
from peritext_tpu.core.types import Change
from peritext_tpu.parallel import ChangeStore, ReplicaServer, sync_with
from peritext_tpu.parallel.codec import encode_frame
from peritext_tpu.parallel.streaming import StreamingMerge
from peritext_tpu.testing.accumulate import accumulate_patches
from peritext_tpu.testing.fuzz import generate_workload

NUM_DOCS = 4
ACTORS = ("doc1", "doc2", "doc3")


@pytest.fixture()
def namespaced_workloads():
    """Per-doc fuzz workloads with actors renamed per doc so one ChangeStore
    can hold every doc's logs (actor = 'd{doc}.{replica}')."""
    raw = generate_workload(seed=130, num_docs=NUM_DOCS, ops_per_doc=80)
    out = []
    for d, w in enumerate(raw):
        mapping = {a: f"d{d}.{a}" for a in ACTORS}

        def rename_id(v):
            if isinstance(v, str) and "@" in v:
                ctr, a = v.split("@")
                return f"{ctr}@{mapping.get(a, a)}"
            return v

        renamed = {}
        for actor, log in w.items():
            new_log = []
            for ch in log:
                j = ch.to_json()
                j["actor"] = mapping[j["actor"]]
                j["deps"] = {mapping.get(a, a): s for a, s in j["deps"].items()}
                for op in j["ops"]:
                    for key in ("opId", "obj", "elemId"):
                        if key in op:
                            op[key] = rename_id(op[key])
                    for bkey in ("start", "end"):
                        b = op.get(bkey)
                        if isinstance(b, dict) and "elemId" in b:
                            b["elemId"] = rename_id(b["elemId"])
                new_log.append(Change.from_json(j))
            renamed[mapping[actor]] = new_log
        out.append(renamed)
    return out


class HostSim:
    """One simulated host: durable change log + TCP endpoint + a device
    session sharded over the virtual mesh, fed frame-natively.  Remote
    pushes are ingested on the server's handler thread, so readers must
    ``wait_settled`` first (same pattern as demos/multihost_demo.py)."""

    def __init__(self, mesh, actors, doc_of_actor):
        import threading

        self.store = ChangeStore()
        self.session = StreamingMerge(
            num_docs=NUM_DOCS, actors=actors, slot_capacity=512,
            mark_capacity=128, round_insert_capacity=128,
            round_delete_capacity=64, round_mark_capacity=64, mesh=mesh,
        )
        self.doc_of_actor = doc_of_actor
        self._lock = threading.Lock()
        self._delivered = 0
        self.server = ReplicaServer(self.store, on_changes=self._on_changes)
        self.address = self.server.start()

    def _on_changes(self, fresh):
        with self._lock:
            by_doc = {}
            for ch in fresh:
                by_doc.setdefault(self.doc_of_actor[ch.actor], []).append(ch)
            for d, changes in by_doc.items():
                self.session.ingest_frame(d, encode_frame(changes))
            self.session.drain()
            self._delivered += len(fresh)

    def author(self, d, changes):
        for ch in changes:
            self.store.append(ch)
        self._on_changes(changes)

    def settled(self):
        in_store = sum(len(self.store.log(a)) for a in self.store.actors())
        with self._lock:
            return self._delivered == in_store

    def stop(self):
        self.server.stop()


def wait_settled(*hosts, timeout=30.0):
    import time

    deadline = time.monotonic() + timeout
    while not all(h.settled() for h in hosts):
        if time.monotonic() > deadline:  # pragma: no cover
            raise RuntimeError("hosts failed to ingest synced changes in time")
        time.sleep(0.01)


def test_cluster_end_to_end(namespaced_workloads, tmp_path):
    workloads = namespaced_workloads
    all_actors = sorted({a for w in workloads for a in w})
    doc_of_actor = {a: d for d, w in enumerate(workloads) for a in w}
    mesh = Mesh(np.asarray(jax.devices("cpu")[:4]), ("docs",))

    h0 = HostSim(mesh, all_actors, doc_of_actor)
    h1 = HostSim(mesh, all_actors, doc_of_actor)
    try:
        # each doc's replicas are split between the hosts: doc1+doc2 edits
        # originate on h0, doc3 edits on h1
        for d, w in enumerate(workloads):
            for actor, log in w.items():
                owner = h1 if actor.endswith(".doc3") else h0
                if log:
                    owner.author(d, log)

        # gossip round converges the stores AND both device sessions; the
        # push side lands on h1's handler thread, so wait for quiescence
        h0.server.sync_with(*h1.address)
        wait_settled(h0, h1)
        assert h0.store.clock() == h1.store.clock()

        # checkpoint h0's session, crash the host, restore, catch up
        save_session(h0.session, tmp_path / "h0")
        h0.stop()
        restored = restore_session(tmp_path / "h0", mesh=mesh)

        # redelivery from the durable store (dups are tolerated everywhere)
        for d, w in enumerate(workloads):
            changes = [
                ch for a in h0.store.actors() if doc_of_actor[a] == d
                for ch in h0.store.log(a)
            ]
            if changes:
                restored.ingest_frame(d, encode_frame(changes))
        restored.drain()

        # convergence: restored h0 session == h1 session == oracle
        assert restored.digest() == h1.session.digest()
        for d, w in enumerate(workloads):
            expected = _oracle_doc(w).get_text_with_formatting(["text"])
            assert restored.read(d) == expected, f"doc {d} (restored)"
            assert h1.session.read(d) == expected, f"doc {d} (h1)"
        # the surviving host's patch streams replay to the oracle
        for d, w in enumerate(workloads):
            expected = _oracle_doc(w).get_text_with_formatting(["text"])
            assert accumulate_patches(h1.session.read_patches(d)) == expected
    finally:
        h1.stop()
