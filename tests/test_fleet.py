"""Live fleet failover tests (ISSUE 10): deterministic heartbeat leases,
real doc-state migration (checkpoint ship + anti-entropy catch-up +
digest-checked cutover with atomic rollback), host-death failover with
acked-op survival, per-session wire auth, and the fleet exporter surfaces
(golden shapes)."""

import json
import urllib.request

import pytest

from peritext_tpu.checkpoint import pack_doc_frames, unpack_doc_frames
from peritext_tpu.parallel.codec import encode_frame
from peritext_tpu.parallel.lease import DEAD, HeartbeatLedger, LIVE, SUSPECT
from peritext_tpu.parallel.router import FleetRouter, PlacementError
from peritext_tpu.serve import (
    AdmissionController,
    AuthError,
    CutoverError,
    FleetFrontend,
    SHED_FAILOVER,
    SHED_REASONS,
    SHED_UNAUTHORIZED,
    SessionKeyring,
    SessionMux,
)
from peritext_tpu.testing.chaos import _serve_session
from peritext_tpu.testing.fuzz import generate_workload

DOCS, OPS = 4, 16


def make_mux(num_docs=8, max_depth=64):
    return SessionMux(
        _serve_session(num_docs, OPS),
        admission=AdmissionController(max_depth=max_depth,
                                      session_quota=None),
    )


def doc_plans(seed=31, num_docs=DOCS, ops_per_doc=OPS, chunk=5):
    plans = {}
    for d, w in enumerate(generate_workload(seed, num_docs=num_docs,
                                            ops_per_doc=ops_per_doc)):
        changes = [ch for log in sorted(w) for ch in w[log]]
        plans[f"doc{d}"] = [
            encode_frame(changes[i:i + chunk])
            for i in range(0, len(changes), chunk)
        ]
    return plans


def make_fleet(hosts=3, lease_rounds=2, transport=False, **kw):
    fe = FleetFrontend(lease_rounds=lease_rounds, checkpoint_every=2, **kw)
    for i in range(hosts):
        fe.add_host(f"h{i}", make_mux(), transport=transport)
    return fe


def feed(fe, plans, keep_last=0):
    for k in sorted(plans):
        assert fe.open_doc(k, f"client-{k}").admitted
    for k, frames in sorted(plans.items()):
        for f in frames[:len(frames) - keep_last]:
            assert fe.submit(k, f).admitted
    fe.round()
    fe.flush()


def clean_reference(plans):
    clean = _serve_session(len(plans), OPS)
    for d, k in enumerate(sorted(plans)):
        for f in plans[k]:
            clean.ingest_frame(d, f)
    clean.drain()
    return clean, {k: d for d, k in enumerate(sorted(plans))}


def assert_fleet_equals_clean(fe, plans):
    clean, index = clean_reference(plans)
    total = 0
    for k in sorted(plans):
        got = fe.doc_digest(k)
        assert got == clean.doc_digest(index[k]), k
        total = (total + got) & 0xFFFFFFFF
    assert total == clean.digest()


# ---------------------------------------------------------------------------
# heartbeat leases: deterministic round-counted death verdicts
# ---------------------------------------------------------------------------


class TestHeartbeatLedger:
    def test_same_observation_sequence_same_verdicts(self):
        """The split-brain guard: two independently-fed ledgers must agree
        on every verdict at every tick."""
        seq = [
            {"a": True, "b": True},
            {"a": False, "b": True},
            {"a": False, "b": False},
            {"a": False, "b": True},
            {"a": True, "b": True},  # a is latched dead; beat ignored
        ]
        l1, l2 = HeartbeatLedger(3), HeartbeatLedger(3)
        for ledger in (l1, l2):
            ledger.track("a")
            ledger.track("b")
        trace1 = [l1.tick(beats) for beats in seq]
        trace2 = [l2.tick(beats) for beats in seq]
        assert trace1 == trace2
        assert l1.snapshot() == l2.snapshot()

    def test_verdict_ladder_and_latch(self):
        ledger = HeartbeatLedger(2)
        ledger.track("h")
        assert ledger.tick({"h": True})["h"] == LIVE
        assert ledger.tick({"h": False})["h"] == SUSPECT
        assert ledger.newly_dead() == []
        assert ledger.tick({"h": False})["h"] == DEAD
        assert ledger.newly_dead() == ["h"]
        # latched: a zombie beat does not revive, and newly_dead fires once
        assert ledger.tick({"h": True})["h"] == DEAD
        assert ledger.newly_dead() == []
        assert ledger.dead_hosts() == ["h"]

    def test_single_missed_round_is_not_death(self):
        ledger = HeartbeatLedger(3)
        ledger.track("h")
        ledger.tick({"h": False})
        assert ledger.tick({"h": True})["h"] == LIVE
        assert ledger.lease("h").missed == 0

    def test_absent_from_beats_counts_as_miss(self):
        ledger = HeartbeatLedger(1)
        ledger.track("h")
        assert ledger.tick({})["h"] == DEAD

    def test_reset_is_the_only_way_back(self):
        ledger = HeartbeatLedger(1)
        ledger.track("h")
        ledger.tick({"h": False})
        assert ledger.verdict("h") == DEAD
        ledger.reset("h")
        assert ledger.tick({"h": True})["h"] == LIVE

    def test_snapshot_golden_shape(self):
        ledger = HeartbeatLedger(2)
        ledger.track("h")
        ledger.tick({"h": False})
        snap = ledger.snapshot()
        assert set(snap) == {"lease_rounds", "ticks", "leases"}
        assert set(snap["leases"]["h"]) == {
            "missed", "rounds", "dead_at_round", "verdict",
        }
        json.dumps(snap)


# ---------------------------------------------------------------------------
# per-doc digest: the cutover oracle's foundation
# ---------------------------------------------------------------------------


class TestDocDigest:
    def test_doc_digest_sums_to_session_digest(self):
        plans = doc_plans()
        sess = _serve_session(DOCS, OPS)
        for d, k in enumerate(sorted(plans)):
            for f in plans[k]:
                sess.ingest_frame(d, f)
        sess.drain()
        total = sum(sess.doc_digest(d) for d in range(DOCS)) & 0xFFFFFFFF
        assert total == sess.digest()

    def test_doc_digest_comparable_across_sessions(self):
        """Two sessions holding the same doc at DIFFERENT indices (and with
        different other docs, so intern orders differ) hash it equal — the
        migration cutover's exact requirement."""
        plans = doc_plans()
        a = _serve_session(DOCS, OPS)
        b = _serve_session(DOCS, OPS)
        keys = sorted(plans)
        for d, k in enumerate(keys):
            for f in plans[k]:
                a.ingest_frame(d, f)
        for d, k in enumerate(reversed(keys)):
            for f in plans[k]:
                b.ingest_frame(d, f)
        a.drain()
        b.drain()
        for d, k in enumerate(keys):
            assert a.doc_digest(d) == b.doc_digest(DOCS - 1 - d), k

    def test_doc_digest_fallback_parity(self):
        from peritext_tpu.parallel.streaming import REASON_CAPACITY

        plans = doc_plans()
        a = _serve_session(DOCS, OPS)
        b = _serve_session(DOCS, OPS)
        for d, k in enumerate(sorted(plans)):
            for f in plans[k]:
                a.ingest_frame(d, f)
                b.ingest_frame(d, f)
        a.drain()
        b.drain()
        b.force_fallback(1, REASON_CAPACITY, "test: scalar replay rung")
        assert a.doc_digest(1) == b.doc_digest(1)


# ---------------------------------------------------------------------------
# checkpoint ship transport
# ---------------------------------------------------------------------------


class TestShipTransport:
    def test_pack_unpack_roundtrip(self):
        frames = [b"", b"abc", b"\x00" * 100]
        assert unpack_doc_frames(pack_doc_frames(frames)) == frames

    def test_truncated_blob_raises(self):
        blob = pack_doc_frames([b"abcdef"])
        with pytest.raises(ValueError):
            unpack_doc_frames(blob[:-2])
        with pytest.raises(ValueError):
            unpack_doc_frames(blob + b"\xff\xff\xff")

    def test_ship_frames_roundtrip_and_catch_up(self):
        from peritext_tpu.parallel.anti_entropy import ChangeStore
        from peritext_tpu.parallel.multihost import (
            ReplicaServer, RetryPolicy, ship_frames,
        )

        received = {}

        def on_ship(doc_key, frames, base):
            received.setdefault(doc_key, [])
            have = len(received[doc_key])
            received[doc_key].extend(frames[max(0, have - base):])
            return len(received[doc_key])

        server = ReplicaServer(ChangeStore(), on_ship=on_ship)
        host, port = server.start()
        policy = RetryPolicy(attempts=2, base_delay=0.01, timeout=2.0)
        try:
            have = ship_frames(host, port, "docA", [b"f0", b"f1"],
                               retry=policy)
            assert have == 2
            # catch-up leg: only the tail ships, with base = prior have
            have = ship_frames(host, port, "docA", [b"f2"], base=have,
                               retry=policy)
            assert have == 3
            # a retried/overlapping ship is idempotent
            have = ship_frames(host, port, "docA", [b"f1", b"f2"], base=1,
                               retry=policy)
            assert have == 3
            assert received["docA"] == [b"f0", b"f1", b"f2"]
        finally:
            server.stop()

    def test_ship_to_no_handler_endpoint_fails_loudly(self):
        from peritext_tpu.core.errors import TransportError
        from peritext_tpu.parallel.anti_entropy import ChangeStore
        from peritext_tpu.parallel.multihost import (
            ReplicaServer, RetryPolicy, ship_frames,
        )

        server = ReplicaServer(ChangeStore())  # no on_ship
        host, port = server.start()
        try:
            with pytest.raises(TransportError):
                ship_frames(host, port, "docA", [b"f0"],
                            retry=RetryPolicy(attempts=1, timeout=1.0))
        finally:
            server.stop()

    def test_malformed_ship_counted_not_fatal(self):
        """A buggy/malicious peer's malformed MSG_SHIP body (short body,
        non-dict header, missing "doc", bad frame blob) must die inside
        the bad-peer guard — counted and swallowed — and the endpoint
        must keep serving well-formed ships."""
        import socket
        import struct as _struct

        from peritext_tpu.parallel.anti_entropy import ChangeStore
        from peritext_tpu.parallel.multihost import (
            _send_message, MSG_SHIP, ReplicaServer, ship_frames,
        )

        server = ReplicaServer(ChangeStore(), on_ship=lambda d, f, b: len(f))
        host, port = server.start()
        hdr = lambda s: _struct.pack("<I", len(s)) + s  # noqa: E731
        bad_bodies = [
            b"",                                   # short: struct.error
            b"\x01",                               # short: struct.error
            hdr(b"[1, 2]"),                        # header not a dict
            hdr(b"{}"),                            # header missing "doc"
            hdr(b"not json"),                      # json ValueError
            hdr(b'{"doc": "d"}') + b"\xff\xff",    # truncated frame blob
        ]
        try:
            for body in bad_bodies:
                with socket.create_connection((host, port),
                                              timeout=5) as sock:
                    _send_message(sock, MSG_SHIP, body)
                    sock.settimeout(2)
                    assert sock.recv(4096) == b"", body  # closed, no ack
            # the endpoint survived every malformed peer
            assert ship_frames(host, port, "docZ", [b"frame"]) == 1
        finally:
            server.stop()

    def test_anti_entropy_exchange_unaffected(self):
        """The ship message kind must not disturb the frontier/changes
        protocol on the same endpoint."""
        from peritext_tpu.parallel.anti_entropy import ChangeStore
        from peritext_tpu.parallel.multihost import ReplicaServer, sync_with
        from peritext_tpu.testing.chaos import _append_changes

        full, local = ChangeStore(), ChangeStore()
        _append_changes(full, "actor", 5)
        server = ReplicaServer(full, on_ship=lambda *a: 0)
        host, port = server.start()
        try:
            pulled, pushed = sync_with(local, host, port)
        finally:
            server.stop()
        assert pulled == 5 and local.clock() == full.clock()


# ---------------------------------------------------------------------------
# router execution hooks
# ---------------------------------------------------------------------------


class TestRouterHooks:
    def make_router(self):
        r = FleetRouter()
        for name in ("h0", "h1", "h2"):
            r.add_host(name, capacity=4)
        for i in range(4):
            r.place(f"doc{i}", size=i + 1)
        return r

    def test_fail_host_forgets_placements_and_latches(self):
        r = self.make_router()
        victim = r.host_of("doc0")
        held = [dk for dk, h in r.placement().items() if h == victim]
        lost = r.fail_host(victim)
        assert sorted(dk for dk, _, _ in lost) == sorted(held)
        assert all(r.host_of(dk) is None for dk in held)
        assert r.host(victim).draining
        # a dead host receives no placements
        r.place("fresh", size=1)
        assert r.host_of("fresh") != victim

    def test_rollback_moves_restores_pre_plan_placement(self):
        r = self.make_router()
        before = r.placement()
        moves_before = r.moves
        plan = r.evacuate("h0")
        assert plan
        r.rollback_moves(plan)
        r.set_draining("h0", False)
        assert r.placement() == before
        assert r.moves == moves_before

    def test_release_and_directed_move(self):
        r = self.make_router()
        r.release("doc0")
        assert r.host_of("doc0") is None
        r.release("doc0")  # idempotent
        target = "h2" if r.host_of("doc1") != "h2" else "h1"
        r.move("doc1", target)
        assert r.host_of("doc1") == target

    def test_directed_move_refuses_full_or_draining(self):
        r = self.make_router()
        r.set_draining("h2", True)
        src = r.host_of("doc1")
        with pytest.raises(PlacementError):
            r.move("doc1", "h2")
        assert r.host_of("doc1") == src


# ---------------------------------------------------------------------------
# migration: real state movement with digest-checked cutover
# ---------------------------------------------------------------------------


class TestMigration:
    def test_evacuate_moves_real_state(self):
        plans = doc_plans()
        fe = make_fleet(hosts=3)
        try:
            feed(fe, plans)
            victim = fe.router.host_of("doc0")
            plan = fe.evacuate(victim)
            assert plan
            assert all(fe._serving[dk] != victim for dk in plans)
            # source slots were released only after the plan committed
            assert all(
                fe.hosts[victim].session_of(dk) is None for dk in plans
            )
            assert_fleet_equals_clean(fe, plans)
        finally:
            fe.stop()

    def test_mid_move_op_race_catches_up(self, monkeypatch):
        """Ops landing between the checkpoint snapshot and cutover keep
        hitting the SOURCE (the serving map flips only at cutover) and the
        catch-up legs ship them — the moved doc must be byte-equal to a
        reference fed everything."""
        plans = doc_plans()
        fe = make_fleet(hosts=2)
        try:
            feed(fe, plans, keep_last=1)
            key = "doc1"
            late = plans[key][-1]
            src = fe.router.host_of(key)
            dst = next(n for n in fe.hosts if n != src)
            real_ship = fe._ship
            raced = {"done": False}

            def racing_ship(target, doc_key, frames, base):
                have = real_ship(target, doc_key, frames, base)
                if doc_key == key and not raced["done"]:
                    raced["done"] = True
                    # the race: a client op lands mid-move, on the source
                    verdict = fe.submit(key, late)
                    assert verdict.admitted
                    assert fe._serving[key] == src
                return have

            monkeypatch.setattr(fe, "_ship", racing_ship)
            fe.migrate(key, dst)
            assert raced["done"], "the race never fired"
            assert fe._serving[key] == dst
            # deliver the held-back frames of the OTHER docs for the
            # reference comparison
            for k, frames in sorted(plans.items()):
                if k != key:
                    assert fe.submit(k, frames[-1]).admitted
            fe.round()
            fe.flush()
            assert_fleet_equals_clean(fe, plans)
        finally:
            fe.stop()

    def test_fallback_doc_migration_with_mid_move_race(self, monkeypatch):
        """A degraded doc re-encodes its whole log as ONE frame, so the
        frame-count frontier never advances — catch-up must diff CONTENT
        and re-ship in full (the receiver's merge is idempotent), or a
        mid-move op is silently dropped and the cutover digest check can
        never pass."""
        from peritext_tpu.parallel.streaming import REASON_CAPACITY

        plans = doc_plans()
        fe = make_fleet(hosts=2)
        try:
            feed(fe, plans, keep_last=1)
            key = "doc1"
            late = plans[key][-1]
            src = fe.router.host_of(key)
            dst = next(n for n in fe.hosts if n != src)
            host = fe.hosts[src]
            doc = host.mux.sessions()[host.session_of(key)].doc_index
            host.mux.session.force_fallback(
                doc, REASON_CAPACITY, "test: scalar replay rung")
            real_ship = fe._ship
            raced = {"done": False}

            def racing_ship(target, doc_key, frames, base):
                have = real_ship(target, doc_key, frames, base)
                if doc_key == key and not raced["done"]:
                    raced["done"] = True
                    assert fe.submit(key, late).admitted
                    assert fe._serving[key] == src
                return have

            monkeypatch.setattr(fe, "_ship", racing_ship)
            fe.migrate(key, dst)
            assert raced["done"], "the race never fired"
            assert fe._serving[key] == dst
            for k, frames in sorted(plans.items()):
                if k != key:
                    assert fe.submit(k, frames[-1]).admitted
            fe.round()
            fe.flush()
            assert_fleet_equals_clean(fe, plans)
        finally:
            fe.stop()

    def test_failed_move_reuses_target_slot(self, monkeypatch):
        """A ship that fails AFTER the target slot was claimed keeps the
        doc→slot reservation — mux slots are append-only, so releasing
        could never reclaim capacity; retries must RESUME into the same
        slot, not burn a fresh one per attempt.  Repeated failures (more
        than the mux has slots) must not drain the target, and a clean
        migrate afterwards lands byte-equal."""
        plans = doc_plans()
        fe = make_fleet(hosts=2)
        try:
            feed(fe, plans)
            key = "doc0"
            src = fe.router.host_of(key)
            dst = next(n for n in fe.hosts if n != src)
            real_ship = fe._ship

            def failing_ship(target, doc_key, frames, base):
                # deliver one frame (claiming the slot), then die mid-ship
                real_ship(target, doc_key, frames[:1], base=base)
                raise OSError("injected ship failure")

            monkeypatch.setattr(fe, "_ship", failing_ship)
            before = fe.hosts[dst].mux.load_report()["docs"]
            failures = 0
            # the broken transport dies mid-ship every time; each retry
            # must RESUME where the last died, so the move eventually
            # completes through the fault — and claims ONE slot, ever
            for _ in range(40):
                try:
                    fe.migrate(key, dst)
                    break
                except OSError:
                    failures += 1
                    assert fe._serving[key] == src
            else:
                pytest.fail("migration never completed through resume")
            assert failures >= 1, "the fault never fired"
            assert fe._serving[key] == dst
            assert fe.hosts[dst].mux.load_report()["docs"] == before + 1
            fe.round()
            fe.flush()
            assert_fleet_equals_clean(fe, plans)
        finally:
            fe.stop()

    def test_cutover_mismatch_rolls_back_atomically(self, monkeypatch):
        from peritext_tpu.serve.fleet import FleetHost

        plans = doc_plans()
        fe = make_fleet(hosts=2)
        try:
            feed(fe, plans)
            key = "doc0"
            src = fe.router.host_of(key)
            dst = next(n for n in fe.hosts if n != src)
            before_serving = dict(fe._serving)
            before_placement = fe.router.placement()
            orig = FleetHost.doc_digest

            def corrupt(self, doc_key):
                value = orig(self, doc_key)
                return value ^ 1 if (self.name == dst and doc_key == key) \
                    else value

            monkeypatch.setattr(FleetHost, "doc_digest", corrupt)
            with pytest.raises(CutoverError):
                fe.migrate(key, dst)
            monkeypatch.setattr(FleetHost, "doc_digest", orig)
            # atomic: serving map, router placement, and the doc's state
            # are all exactly pre-plan; the doc still serves
            assert fe._serving == before_serving
            assert fe.router.placement() == before_placement
            assert fe.migration_rollbacks == 1
            assert fe.submit(key, plans[key][0]).admitted
            fe.round()
            fe.flush()
            assert fe.doc_digest(key) is not None
        finally:
            fe.stop()

    def test_evacuate_rollback_spans_whole_plan(self, monkeypatch):
        """A digest mismatch on the LAST doc of an evacuation plan must
        revert every earlier (already cut over) doc too."""
        from peritext_tpu.serve.fleet import FleetHost

        plans = doc_plans()
        fe = make_fleet(hosts=3)
        try:
            feed(fe, plans)
            victim = fe.router.host_of("doc0")
            victim_docs = sorted(
                dk for dk, h in fe._serving.items() if h == victim
            )
            assert len(victim_docs) >= 1
            before_serving = dict(fe._serving)
            before_placement = fe.router.placement()
            orig = FleetHost.doc_digest
            last = victim_docs[-1]

            def corrupt(self, doc_key):
                value = orig(self, doc_key)
                return value ^ 1 if (doc_key == last
                                     and self.name != victim) else value

            monkeypatch.setattr(FleetHost, "doc_digest", corrupt)
            with pytest.raises(CutoverError):
                fe.evacuate(victim)
            monkeypatch.setattr(FleetHost, "doc_digest", orig)
            fe.router.set_draining(victim, False)
            assert fe._serving == before_serving
            assert fe.router.placement() == before_placement
            assert_fleet_equals_clean(fe, plans)
        finally:
            fe.stop()

    def test_tcp_ship_migration(self):
        """The same migration over the real retrying transport (TCP ship
        endpoints on both hosts)."""
        plans = doc_plans(num_docs=2)
        fe = make_fleet(hosts=2, transport=True)
        try:
            feed(fe, plans)
            key = "doc0"
            src = fe.router.host_of(key)
            dst = next(n for n in fe.hosts if n != src)
            assert fe.hosts[dst].address is not None
            fe.migrate(key, dst)
            assert fe._serving[key] == dst
            assert_fleet_equals_clean(fe, plans)
        finally:
            fe.stop()


# ---------------------------------------------------------------------------
# failover: host death mid-traffic
# ---------------------------------------------------------------------------


class TestFailover:
    def test_kill_failover_typed_verdicts_and_survival(self):
        plans = doc_plans()
        fe = make_fleet(hosts=3, lease_rounds=2)
        try:
            feed(fe, plans, keep_last=1)
            victim = fe.router.host_of("doc0")
            victim_docs = sorted(
                dk for dk, h in fe._serving.items() if h == victim
            )
            acked = {k: plans[k][:-1] for k in victim_docs}
            fe.hosts[victim].kill()
            # pre-detection submissions answer TYPED delay, never raise
            verdict = fe.submit(victim_docs[0], plans[victim_docs[0]][-1])
            assert verdict.kind == "delay"
            for _ in range(2):
                fe.round()
            assert fe.failovers == 1
            assert fe.failover_docs == len(victim_docs)
            # acked-op survival BEFORE any retry
            for k in victim_docs:
                ref = _serve_session(1, OPS)
                for f in acked[k]:
                    ref.ingest_frame(0, f)
                ref.drain()
                assert fe.doc_digest(k) == ref.doc_digest(0), k
            # retries redeliver the held-back tail fleet-wide
            for k, frames in sorted(plans.items()):
                while not fe.submit(k, frames[-1]).admitted:
                    fe.round()
            fe.round()
            fe.flush()
            assert_fleet_equals_clean(fe, plans)
            assert fe.stats.accounted()
            for reason in fe.stats.shed_reasons:
                assert reason in SHED_REASONS
        finally:
            fe.stop()

    def test_failover_without_capacity_sheds_typed_then_heals(self):
        plans = doc_plans(num_docs=2)
        fe = FleetFrontend(lease_rounds=1, checkpoint_every=1)
        # two hosts with capacity exactly 1 each: no spare room anywhere
        fe.add_host("h0", make_mux(), capacity=1)
        fe.add_host("h1", make_mux(), capacity=1)
        try:
            feed(fe, plans, keep_last=1)
            victim = fe.router.host_of("doc0")
            doomed = [dk for dk, h in fe._serving.items() if h == victim]
            fe.hosts[victim].kill()
            fe.round()
            assert fe.failovers == 1 and fe.failover_docs == 0
            verdict = fe.submit(doomed[0], plans[doomed[0]][-1])
            assert verdict.kind == "shed"
            assert verdict.reason == SHED_FAILOVER
            # capacity returns: a fresh host registers, retry heals
            fe.add_host("h2", make_mux(), capacity=2)
            assert fe.retry_failed() == len(doomed)
            for k in doomed:
                assert fe._serving[k] == "h2"
                assert fe.submit(k, plans[k][-1]).admitted
            fe.round()
            fe.flush()
            assert fe.stats.accounted()
        finally:
            fe.stop()

    def test_failed_replacement_reuses_target_slot(self, monkeypatch):
        """A failover redelivery that dies after claiming the target slot
        keeps the reservation: the doc sheds ``failover`` typed, repeated
        retries resume into the SAME slot (never burning fresh ones), and
        once the fault clears retry_failed() re-homes byte-equal."""
        plans = doc_plans()
        fe = make_fleet(hosts=2, lease_rounds=1)
        try:
            feed(fe, plans, keep_last=1)
            victim = fe.router.host_of("doc0")
            survivor = next(n for n in fe.hosts if n != victim)
            doomed = sorted(dk for dk, h in fe._serving.items()
                            if h == victim)
            real_ship = fe._ship

            def failing_ship(target, doc_key, frames, base):
                real_ship(target, doc_key, frames, base)
                raise OSError("injected redelivery failure")

            monkeypatch.setattr(fe, "_ship", failing_ship)
            fe.hosts[victim].kill()
            fe.round()
            assert fe.failovers == 1 and fe.failover_docs == 0
            slots_used = fe.hosts[survivor].mux.load_report()["docs"]
            for k in doomed:
                verdict = fe.submit(k, plans[k][-1])
                assert verdict.kind == "shed"
                assert verdict.reason == SHED_FAILOVER
            # failed retries must not burn fresh slots
            assert fe.retry_failed() == 0
            assert (fe.hosts[survivor].mux.load_report()["docs"]
                    == slots_used)
            monkeypatch.setattr(fe, "_ship", real_ship)
            assert fe.retry_failed() == len(doomed)
            assert (fe.hosts[survivor].mux.load_report()["docs"]
                    == slots_used)
            for k in doomed:
                assert fe._serving[k] == survivor
                assert fe.submit(k, plans[k][-1]).admitted
            for k in sorted(plans):
                if k not in doomed:
                    assert fe.submit(k, plans[k][-1]).admitted
            fe.round()
            fe.flush()
            assert fe.stats.accounted()
            assert_fleet_equals_clean(fe, plans)
        finally:
            fe.stop()

    def test_dead_host_readmission_via_add_host(self):
        """Re-registering a DEAD host's name is the re-admission path:
        the zombie's remnants tear down, the lease restarts fresh (the
        only way out of the latch), and the new host takes placements
        again.  A LIVE name re-registering raises before any state
        mutates."""
        plans = doc_plans(num_docs=2)
        fe = make_fleet(hosts=2, lease_rounds=1)
        try:
            feed(fe, plans, keep_last=1)
            with pytest.raises(ValueError):
                fe.add_host("h0", make_mux())
            victim = fe.router.host_of("doc0")
            fe.hosts[victim].kill()
            fe.round()
            assert fe.ledger.verdict(victim) == DEAD
            assert fe.failovers == 1
            # the operator restarts the machine and re-registers the name
            fe.add_host(victim, make_mux())
            assert fe.ledger.verdict(victim) == LIVE
            fe.round()
            assert fe.ledger.verdict(victim) == LIVE
            # the reborn host is placeable again
            assert fe.open_doc("doc-new", "client-new").admitted
            for k, frames in sorted(plans.items()):
                assert fe.submit(k, frames[-1]).admitted
            fe.round()
            fe.flush()
            assert fe.stats.accounted()
            assert_fleet_equals_clean(fe, plans)
        finally:
            fe.stop()

    def test_retried_plan_redelivery_does_not_grow_standby_store(self):
        """A client retrying its whole plan after a failover re-admits
        byte-identical frames; the journal dedups them, so the standby
        store (checkpoint ∪ journal) holds each acked frame ONCE no
        matter how many retry passes run."""
        plans = doc_plans(num_docs=2)
        fe = make_fleet(hosts=2, lease_rounds=1)
        try:
            feed(fe, plans)
            fe.checkpoint_ship()
            size = sum(len(v) for v in fe._checkpoint.values()) + sum(
                len(v) for v in fe._journal.values())
            for _ in range(3):  # three full retry passes
                for k, frames in sorted(plans.items()):
                    for f in frames:
                        assert fe.submit(k, f).admitted
                fe.round()
                fe.flush()
            fe.checkpoint_ship()
            grown = sum(len(v) for v in fe._checkpoint.values()) + sum(
                len(v) for v in fe._journal.values())
            assert grown == size, "retry passes multiplied the standby store"
            assert_fleet_equals_clean(fe, plans)
        finally:
            fe.stop()

    def test_flight_recorder_dumps_failover_timeline(self, tmp_path):
        from peritext_tpu.obs import FlightRecorder

        plans = doc_plans(num_docs=2)
        recorder = FlightRecorder(capacity=128, dump_dir=tmp_path,
                                  min_dump_interval=0.0)
        fe = make_fleet(hosts=3, lease_rounds=1, recorder=recorder)
        try:
            feed(fe, plans)
            victim = fe.router.host_of("doc0")
            fe.hosts[victim].kill()
            fe.round()
            assert fe.failovers == 1
            dumps = sorted(tmp_path.glob("*.jsonl"))
            assert dumps
            records = [
                json.loads(line)
                for dump in dumps
                for line in dump.read_text().splitlines() if line
            ]
            reasons = {r.get("reason") for r in records
                       if r.get("kind") == "fault"}
            assert {"host-death", "failover-complete"} <= reasons
        finally:
            fe.stop()


# ---------------------------------------------------------------------------
# per-session wire auth
# ---------------------------------------------------------------------------


class TestAuth:
    def keyring(self):
        return SessionKeyring({"k1": b"secret-one"})

    def test_mint_verify_and_reject(self):
        kr = self.keyring()
        token = kr.mint("alice")
        assert kr.verify("alice", token)
        assert not kr.verify("bob", token)  # bound to the client
        assert not kr.verify("alice", None)
        assert not kr.verify("alice", "garbage")
        assert not kr.verify("alice", "nokey." + token.split(".", 1)[1])
        snap = kr.snapshot()
        assert set(snap) == {"keys", "minting", "verified", "rejected",
                             "rotations"}
        assert snap["verified"] == 1 and snap["rejected"] == 4

    def test_rotation_keeps_live_tokens_retire_ends_them(self):
        kr = self.keyring()
        old_token = kr.mint("alice")
        kr.rotate("k2", b"secret-two")
        assert kr.minting_key_id == "k2"
        # rotation does NOT drop live sessions: old tokens still verify
        assert kr.verify("alice", old_token)
        new_token = kr.mint("alice")
        assert new_token.startswith("k2.")
        assert kr.verify("alice", new_token)
        kr.retire("k1")
        assert not kr.verify("alice", old_token)
        assert kr.verify("alice", new_token)
        with pytest.raises(AuthError):
            kr.retire("k2")  # the minting key cannot be retired

    def test_mux_sheds_unauthorized_at_admission(self):
        kr = self.keyring()
        mux = SessionMux(_serve_session(2, OPS), auth=kr)
        sid, verdict = mux.open_session("alice")  # no token
        assert sid is None and verdict.reason == SHED_UNAUTHORIZED
        sid, verdict = mux.open_session("alice", token=kr.mint("bob"))
        assert sid is None and verdict.reason == SHED_UNAUTHORIZED
        sid, verdict = mux.open_session("alice", token=kr.mint("alice"))
        assert sid is not None and verdict.admitted
        # identity holds and the reason is counted
        stats = mux.admission.stats
        assert stats.submitted == stats.admitted + stats.delayed + stats.shed
        assert stats.shed_reasons[SHED_UNAUTHORIZED] == 2
        assert "auth" in mux.snapshot()

    def test_per_frame_auth_and_rotation_mid_session(self):
        kr = self.keyring()
        mux = SessionMux(_serve_session(2, OPS), auth=kr,
                         auth_per_frame=True)
        token = kr.mint("alice")
        sid, verdict = mux.open_session("alice", token=token)
        assert verdict.admitted
        plans = doc_plans(num_docs=1)
        frame = plans["doc0"][0]
        assert mux.submit(sid, frame, token=token).admitted
        verdict = mux.submit(sid, frame)  # missing token
        assert verdict.kind == "shed"
        assert verdict.reason == SHED_UNAUTHORIZED
        # rotation mid-session: the cached token keeps working
        kr.rotate("k2", b"secret-two")
        assert mux.submit(sid, frame, token=token).admitted

    def test_unauthorized_counted_in_shed_reason_gauges(self):
        from peritext_tpu.obs import prometheus_text

        kr = self.keyring()
        mux = SessionMux(_serve_session(2, OPS), auth=kr)
        mux.open_session("alice")
        text = prometheus_text(serve=mux)
        assert ('peritext_serve_shed_reason_total{reason="unauthorized"} 1'
                in text)

    def test_fleet_frontend_auth_edge(self):
        """doc_key is a PUBLIC name, not a bearer: an auth-enabled fleet
        must verify every submit and bind re-opens to the registered
        owner, or any tenant could write into any doc it can name."""
        kr = self.keyring()
        fe = FleetFrontend(auth=kr)
        fe.add_host("h0", make_mux())
        try:
            verdict = fe.open_doc("docA", "alice")
            assert verdict.kind == "shed"
            assert verdict.reason == SHED_UNAUTHORIZED
            token = kr.mint("alice")
            assert fe.open_doc("docA", "alice", token=token).admitted
            frame = doc_plans(num_docs=1)["doc0"][0]
            # knowing the doc name is not a credential
            verdict = fe.submit("docA", frame)
            assert verdict.kind == "shed"
            assert verdict.reason == SHED_UNAUTHORIZED
            # a DIFFERENT tenant's valid token opens nothing of alice's
            verdict = fe.open_doc("docA", "mallory",
                                  token=kr.mint("mallory"))
            assert verdict.kind == "shed"
            assert verdict.reason == SHED_UNAUTHORIZED
            assert fe.submit("docA", frame, token=token).admitted
            assert fe.stats.accounted()
        finally:
            fe.stop()

    def test_host_mux_with_own_keyring_refused(self):
        fe = FleetFrontend()
        mux = SessionMux(_serve_session(2, OPS),
                         auth=SessionKeyring({"k": b"s"}))
        with pytest.raises(AuthError):
            fe.add_host("h0", mux)
        assert not fe.hosts and fe.router.hosts() == []


# ---------------------------------------------------------------------------
# exporter surfaces: /fleet.json + peritext_fleet_* gauges
# ---------------------------------------------------------------------------


class TestFleetExporters:
    def make_frontend(self):
        plans = doc_plans(num_docs=2)
        fe = make_fleet(hosts=2)
        feed(fe, plans)
        return fe

    def test_snapshot_golden_shape(self):
        fe = self.make_frontend()
        try:
            snap = fe.snapshot()
            assert set(snap) == {
                "rounds", "hosts", "leases", "router", "serving", "moving",
                "failed_docs", "failovers", "failover_docs", "migrations",
                "migration_rollbacks", "checkpoint_ships", "journal_frames",
                "checkpoint_docs", "verdicts", "auth",
            }
            assert set(snap["verdicts"]) == {
                "submitted", "admitted", "delayed", "shed", "shed_reasons",
            }
            host_snap = snap["hosts"]["h0"]
            assert set(host_snap) == {"alive", "docs", "address", "serve"}
            json.dumps(snap)
        finally:
            fe.stop()

    def test_fleet_json_route(self):
        from peritext_tpu.obs import MetricsServer

        fe = self.make_frontend()
        server = MetricsServer(fleet=fe)
        host, port = server.start()
        try:
            body = json.loads(urllib.request.urlopen(
                f"http://{host}:{port}/fleet.json", timeout=5
            ).read())
        finally:
            server.stop()
            fe.stop()
        assert body["router"]["docs"] == 2
        assert set(body["serving"]) == {"doc0", "doc1"}

    def test_prometheus_fleet_gauges(self):
        from peritext_tpu.obs import prometheus_text

        fe = self.make_frontend()
        try:
            fe.submit("nonexistent", b"x")  # one typed shed for the family
            text = prometheus_text(fleet=fe)
            for line in (
                "peritext_fleet_hosts ",
                "peritext_fleet_live_hosts ",
                "peritext_fleet_dead_hosts ",
                "peritext_fleet_docs ",
                "peritext_fleet_failed_docs ",
                "peritext_fleet_journal_frames ",
                "peritext_fleet_failovers_total ",
                "peritext_fleet_migrations_total ",
                "peritext_fleet_migration_rollbacks_total ",
                "peritext_fleet_checkpoint_ships_total ",
                "peritext_fleet_submitted_total ",
                "peritext_fleet_admitted_total ",
                "peritext_fleet_delayed_total ",
                "peritext_fleet_shed_total ",
            ):
                assert any(ln.startswith(line)
                           for ln in text.splitlines()), line
            assert ('peritext_fleet_shed_reason_total'
                    '{reason="unknown-session"} 1') in text
        finally:
            fe.stop()

    def test_health_snapshot_composition(self):
        from peritext_tpu.obs import health_snapshot

        fe = self.make_frontend()
        try:
            snap = health_snapshot(fleet=fe)
            assert "fleet" in snap and snap["fleet"]["router"]["docs"] == 2
            json.dumps(snap, default=str)
        finally:
            fe.stop()

    def test_replica_server_mounts_fleet(self):
        from peritext_tpu.parallel.anti_entropy import ChangeStore
        from peritext_tpu.parallel.multihost import ReplicaServer

        fe = self.make_frontend()
        server = ReplicaServer(ChangeStore(), metrics_port=0, fleet=fe)
        server.start()
        try:
            mh, mp = server.metrics_address
            body = json.loads(urllib.request.urlopen(
                f"http://{mh}:{mp}/fleet.json", timeout=5
            ).read())
            assert body["router"]["docs"] == 2
        finally:
            server.stop()
            fe.stop()


# ---------------------------------------------------------------------------
# load ingestion: the router learns from the serve exporter surface
# ---------------------------------------------------------------------------


class TestLoadIngestion:
    def test_round_feeds_measured_loads_into_router(self):
        plans = doc_plans(num_docs=2)
        fe = make_fleet(hosts=2)
        try:
            feed(fe, plans)
            fe.observe_loads()  # re-observe after the flush landed frames
            for name in fe.hosts:
                rec = fe.router.host(name)
                expected = fe.hosts[name].mux.load_report()
                assert rec.slot_load == expected["slot_load"]
                assert rec.host_bound_load == expected["host_bound_load"]
            assert sum(fe.router.host(n).slot_load
                       for n in fe.hosts) > 0
        finally:
            fe.stop()
