"""Convergence observability (ISSUE 4): lag-watermark arithmetic, the
divergence-vs-lag classifier, store frontier digests, the gossip scheduler's
behind-ness priority + backoff, wire v6 CRC frames, the exporter surfaces
(``/convergence.json`` + ``peritext_convergence_*`` gauges, golden shape),
and the fleet CLI view."""

import json
import urllib.request

import pytest

from peritext_tpu.core.errors import DecodeError
from peritext_tpu.core.opids import ROOT
from peritext_tpu.core.types import Change, Operation
from peritext_tpu.obs import (
    ConvergenceMonitor,
    FlightRecorder,
    GLOBAL_COUNTERS,
    MetricsServer,
    health_snapshot,
    prometheus_text,
)
from peritext_tpu.obs.convergence import (
    CONVERGED,
    DIVERGENCE,
    LAG,
    clock_delta_ops,
    clocks_equal,
)
from peritext_tpu.parallel.anti_entropy import ChangeStore, change_digest
from peritext_tpu.parallel.gossip import GossipScheduler
from peritext_tpu.parallel.multihost import ReplicaServer, RetryPolicy


def _change(actor, seq, value=None):
    return Change(
        actor=actor, seq=seq, deps={actor: seq - 1} if seq > 1 else {},
        start_op=seq,
        ops=[Operation(action="set", obj=ROOT, opid=(seq, actor), key="n",
                       value=seq if value is None else value)],
    )


def _fill(store, actor, n):
    for seq in range(1, n + 1):
        store.append(_change(actor, seq))


# ---------------------------------------------------------------------------
# lag-watermark arithmetic
# ---------------------------------------------------------------------------


class TestWatermarkArithmetic:
    def test_clock_delta_ops_sums_only_deficits(self):
        local = {"a": 5, "b": 2}
        peer = {"a": 3, "b": 9, "c": 4}
        # behind on b by 7 and c by 4; a is AHEAD and contributes nothing
        assert clock_delta_ops(local, peer) == 11
        assert clock_delta_ops(peer, local) == 2
        assert clock_delta_ops(local, local) == 0

    def test_clocks_equal_ignores_zero_entries(self):
        assert clocks_equal({"a": 3, "b": 0}, {"a": 3})
        assert not clocks_equal({"a": 3}, {"a": 4})

    def test_observe_frontier_classifies_lag(self):
        m = ConvergenceMonitor(host="t")
        got = m.observe_frontier("p", {"a": 1}, {"a": 4, "b": 2})
        assert got == LAG
        rec = m.peer("p")
        assert rec.ops_behind == 5 and rec.ops_ahead == 0
        assert rec.peak_ops_behind == 5 and not rec.divergent

    def test_observe_success_drains_and_resets_staleness(self):
        m = ConvergenceMonitor(host="t")
        m.observe_frontier("p", {"a": 1}, {"a": 4})
        for _ in range(3):
            m.advance_round()
        assert m.peer("p").staleness(m.rounds) == 3
        m.observe_success("p", pulled=3)
        rec = m.peer("p")
        assert rec.ops_behind == 0 and rec.staleness(m.rounds) == 0
        assert m.total_lag_ops() == 0

    def test_failures_accumulate_and_staleness_grows(self):
        m = ConvergenceMonitor(host="t")
        m.observe_frontier("p", {"a": 1}, {"a": 4})
        for _ in range(4):
            m.advance_round()
            m.observe_failure("p", error="refused")
        rec = m.peer("p")
        assert rec.failures == 4
        assert rec.ops_behind == 3  # the estimate survives the failures
        assert rec.last_error == "refused"  # the WHY rides the watermarks
        assert rec.staleness(m.rounds) == m.rounds  # never cleanly exchanged
        assert m.behindness("p") == (3, 4)
        m.observe_success("p")
        assert m.peer("p").last_error is None  # a clean exchange clears it

    def test_never_seen_peer_is_maximally_stale(self):
        m = ConvergenceMonitor(host="t")
        for _ in range(7):
            m.advance_round()
        assert m.behindness("ghost") == (0, 7)


# ---------------------------------------------------------------------------
# divergence vs lag
# ---------------------------------------------------------------------------


class TestDivergenceProbe:
    def test_same_frontier_same_digest_is_converged(self):
        m = ConvergenceMonitor(host="t")
        got = m.observe_frontier(
            "p", {"a": 3}, {"a": 3}, local_digest=7, peer_digest=7
        )
        assert got == CONVERGED and not m.peer("p").divergent

    def test_same_frontier_different_digest_is_divergence_not_lag(self):
        rec = FlightRecorder(capacity=16)
        m = ConvergenceMonitor(host="t", recorder=rec)
        before = GLOBAL_COUNTERS.get("convergence.divergence_incidents")
        got = m.observe_frontier(
            "p", {"a": 3}, {"a": 3}, local_digest=7, peer_digest=8
        )
        assert got == DIVERGENCE
        assert m.peer("p").divergent and m.peer("p").last_outcome == DIVERGENCE
        assert m.divergent_peers() == ["p"]
        assert GLOBAL_COUNTERS.get("convergence.divergence_incidents") == before + 1
        (incident,) = m.divergence_incidents
        assert (incident.local_digest, incident.peer_digest) == (7, 8)
        # the recorder saw the fault record (ring; no dump_dir configured)
        assert any(
            e["kind"] == "fault" and e["reason"] == "divergence"
            for e in rec.entries()
        )

    def test_different_frontiers_never_probe_divergent(self):
        m = ConvergenceMonitor(host="t")
        got = m.observe_frontier(
            "p", {"a": 1}, {"a": 3}, local_digest=7, peer_digest=8
        )
        assert got == LAG and not m.peer("p").divergent

    def test_missing_digest_downgrades_to_frontier_compare(self):
        m = ConvergenceMonitor(host="t")
        assert m.observe_frontier("p", {"a": 3}, {"a": 3}) == CONVERGED

    def test_end_to_end_injection_counter_and_flight_dump(self, tmp_path):
        from peritext_tpu.testing.chaos import run_divergence_injection

        evidence = run_divergence_injection(3, dump_dir=tmp_path)
        assert evidence["counter_incremented"]
        assert evidence["dump"] is not None
        # incident-plane oracle: EXACTLY a divergence incident, resolved
        # once the monitor stops observing new divergent probes
        assert evidence["incident_kinds"] == ["divergence"]
        assert evidence["incident_resolved"]
        assert evidence["incident_detection_rounds"] == 1


# ---------------------------------------------------------------------------
# store frontier digests
# ---------------------------------------------------------------------------


class TestStoreDigest:
    def test_digest_is_merge_order_independent(self):
        a, b = ChangeStore(), ChangeStore()
        for actor in ("x", "y", "z"):
            _fill(a, actor, 5)
        for actor in ("z", "x", "y"):  # different arrival order
            _fill(b, actor, 5)
        assert a.clock() == b.clock()
        assert a.digest() == b.digest()

    def test_digest_at_frontier_prefixes(self):
        a = ChangeStore()
        _fill(a, "x", 6)
        partial = ChangeStore()
        _fill(partial, "x", 3)
        assert a.digest({"x": 3}) == partial.digest()
        assert a.digest({"x": 3}) != a.digest()
        assert a.digest({}) == 0
        # a frontier past the log clamps to what the store holds
        assert a.digest({"x": 99}) == a.digest()

    def test_content_difference_changes_digest(self):
        a, b = ChangeStore(), ChangeStore()
        a.append(_change("x", 1, value=1))
        b.append(_change("x", 1, value=2))
        assert a.clock() == b.clock()
        assert a.digest() != b.digest()
        assert change_digest(a.log("x")[0]) != change_digest(b.log("x")[0])


# ---------------------------------------------------------------------------
# gossip scheduler: priority + backoff
# ---------------------------------------------------------------------------


class _StubServer:
    """Scripted try_sync_with outcomes, no sockets."""

    def __init__(self, monitor, fail=()):
        from peritext_tpu.parallel.multihost import SyncOutcome

        self.monitor = monitor
        self.fail = set(fail)
        self.calls = []
        self._outcome = SyncOutcome

    def try_sync_with(self, host, port, retry=None, peer_name=None):
        name = peer_name or f"{host}:{port}"
        self.calls.append(name)
        if name in self.fail:
            self.monitor.observe_failure(name, "scripted failure")
            return self._outcome(ok=False, error="scripted failure")
        self.monitor.observe_success(name)
        return self._outcome(pulled=1, pushed=1)


class TestGossipScheduler:
    def test_round_order_is_most_behind_first(self):
        m = ConvergenceMonitor(host="t")
        m.observe_frontier("a", {}, {"x": 5})    # 5 behind
        m.observe_frontier("b", {}, {"x": 50})   # 50 behind
        m.observe_frontier("c", {}, {"x": 20})   # 20 behind
        server = _StubServer(m)
        sched = GossipScheduler(server, monitor=m)
        for name in ("a", "b", "c"):
            sched.add_peer("127.0.0.1", 1, name=name)
        sched.round()
        assert sched.last_round_order == ["b", "c", "a"]
        assert server.calls == ["b", "c", "a"]

    def test_staleness_breaks_lag_ties(self):
        m = ConvergenceMonitor(host="t")
        m.observe_frontier("young", {}, {"x": 5})
        m.observe_success("young")  # clean now; staleness 0 afterwards
        m.observe_frontier("old", {}, {"x": 5})
        for _ in range(3):
            m.advance_round()
        m.observe_frontier("young", {}, {"x": 5})
        m.observe_frontier("old", {}, {"x": 5})
        server = _StubServer(m, fail={"young", "old"})
        sched = GossipScheduler(server, monitor=m)
        sched.add_peer("127.0.0.1", 1, name="young")
        sched.add_peer("127.0.0.1", 2, name="old")
        assert sched.plan() == ["old", "young"]  # equal lag: staler first

    def test_failed_peers_back_off_exponentially_and_wake_clears(self):
        m = ConvergenceMonitor(host="t")
        server = _StubServer(m, fail={"dead"})
        sched = GossipScheduler(server, monitor=m)
        sched.add_peer("127.0.0.1", 1, name="dead")
        sched.add_peer("127.0.0.1", 2, name="live")
        sched.round()  # r1: dead fails -> 2-round skip window
        sched.round()  # r2: dead skipped
        assert server.calls.count("dead") == 1
        sched.round()  # r3: retried, fails again -> 4-round window
        assert server.calls.count("dead") == 2
        for _ in range(3):
            sched.round()  # r4-r6: inside the wider window
        assert server.calls.count("dead") == 2
        assert server.calls.count("live") == 6  # full cadence throughout
        sched.wake()  # the heal signal skips the rest of the window
        sched.round()
        assert server.calls.count("dead") == 3
        snap = sched.snapshot()
        assert snap["peers"]["dead"]["backed_off"] is True
        json.dumps(snap)

    def test_drain_stops_when_fleet_is_clean(self):
        m = ConvergenceMonitor(host="t")
        server = _StubServer(m)
        sched = GossipScheduler(server, monitor=m)
        sched.add_peer("127.0.0.1", 1, name="a")
        assert sched.drain(max_rounds=10) == 1

    def test_real_servers_converge_through_scheduler(self):
        a, b = ChangeStore(), ChangeStore()
        _fill(a, "hostA", 10)
        _fill(b, "hostB", 30)
        sa, sb = ReplicaServer(a), ReplicaServer(b)
        sa.start()
        hb, pb = sb.start()
        try:
            sched = GossipScheduler(
                sa, retry=RetryPolicy(attempts=1, timeout=2.0)
            )
            sched.add_peer(hb, pb)
            rounds = sched.drain(max_rounds=4)
        finally:
            sa.stop()
            sb.stop()
        assert rounds <= 2
        assert a.clock() == b.clock() and a.digest() == b.digest()


# ---------------------------------------------------------------------------
# in-process transports feed the same surface
# ---------------------------------------------------------------------------


class TestInProcessHooks:
    def test_local_sync_observes_frontiers_and_success(self):
        from peritext_tpu.core.doc import Doc
        from peritext_tpu.parallel.anti_entropy import sync

        store = ChangeStore()
        left, right = Doc("L"), Doc("R")
        change, _ = left.change([
            {"path": [], "action": "makeList", "key": "text"},
        ])
        store.append(change)
        m = ConvergenceMonitor(host="local")
        sync(left, right, store, monitor=m)
        assert m.peer("right").exchanges == 1
        assert m.peer("right").ops_behind == 0  # success drained it
        assert right.clock == left.clock

    def test_faulty_publisher_records_drops_and_repair(self):
        from peritext_tpu.parallel.faults import FaultSpec, FaultyPublisher

        m = ConvergenceMonitor(host="pubsub")
        pub = FaultyPublisher(FaultSpec(drop_p=1.0, reorder=False),
                              seed=3, monitor=m)
        seen = []
        pub.subscribe("sub", seen.extend)
        pub.publish("writer", [_change("writer", 1)])
        assert not seen
        assert m.peer("sub").failures == 1
        pub.redeliver_lost()
        assert seen and m.peer("sub").failures == 0

    def test_clean_publisher_records_success(self):
        from peritext_tpu.parallel.pubsub import Publisher

        m = ConvergenceMonitor(host="pubsub")
        pub = Publisher(monitor=m)
        pub.subscribe("a", lambda _: None)
        pub.publish("writer", [_change("writer", 1)])
        assert m.peer("a").exchanges == 0  # success-only path: no frontier
        assert m.peer("a").last_outcome == "converged"


# ---------------------------------------------------------------------------
# wire v6: CRC32 trailer
# ---------------------------------------------------------------------------


class TestWireV6:
    def _changes(self):
        return [_change("actor", seq) for seq in range(1, 9)]

    def test_checked_roundtrip_and_strip(self):
        from peritext_tpu.parallel.codec import (
            decode_frame, encode_frame, encode_frame_checked,
            strip_trace_context,
        )

        chs = self._changes()
        plain = encode_frame(chs)
        checked = encode_frame_checked(chs)
        assert checked[4] == 6 and len(checked) == len(plain) + 16 + 4
        assert decode_frame(checked) == chs
        ctx, stripped = strip_trace_context(checked)
        assert ctx is None and stripped == plain

    def test_checked_carries_trace_context(self):
        from peritext_tpu.parallel.codec import (
            decode_frame_traced, encode_frame_checked, strip_trace_context,
        )

        checked = encode_frame_checked(self._changes(), 0xFEED, 21)
        assert decode_frame_traced(checked)[1] == (0xFEED, 21)
        ctx, _ = strip_trace_context(checked)
        assert ctx == (0xFEED, 21)

    def test_every_bitflip_is_detected(self):
        """The satellite's point: with the CRC trailer there is no longer
        such a thing as an undetectable bit flip — every mutation raises
        the typed DecodeError, so quarantine attributes payload corruption
        precisely."""
        import random

        from peritext_tpu.parallel.codec import (
            decode_frame, encode_frame_checked,
        )
        from peritext_tpu.parallel.faults import FaultSpec, perturb_frame

        frame = encode_frame_checked(self._changes())
        rng = random.Random(11)
        spec = FaultSpec(truncate_p=0.3, bitflip_p=0.9)
        mutated = 0
        for _ in range(300):
            bad = perturb_frame(frame, rng, spec)
            if bad is frame:
                continue
            mutated += 1
            with pytest.raises(DecodeError):
                decode_frame(bad)
        assert mutated > 100, "mutator produced no corruption; vacuous"

    def test_corrupt_checked_frame_quarantines_with_decode_reason(self):
        from peritext_tpu.parallel.codec import encode_frame_checked
        from peritext_tpu.parallel.streaming import REASON_DECODE
        from peritext_tpu.testing.fuzz import _campaign_session, generate_workload

        workload = generate_workload(seed=19, num_docs=1, ops_per_doc=20)[0]
        changes = [ch for log in workload.values() for ch in log]
        frame = bytearray(encode_frame_checked(changes))
        frame[len(frame) // 2] ^= 0x10  # one flipped bit mid-body
        sess = _campaign_session(1, 20)
        sess.ingest_frame(0, bytes(frame), on_corrupt="quarantine")
        assert sess.quarantined()[0].reason == REASON_DECODE
        # clean redelivery (checked wire) repairs and re-admits
        sess.ingest_frame(0, encode_frame_checked(changes))
        sess.drain()
        assert 0 not in sess.quarantined()

    def test_caps_negotiation_sends_v6_to_new_v5_to_traced_old(self):
        import socket as socketlib

        from peritext_tpu.obs import TraceContext
        from peritext_tpu.parallel.codec import decode_frame
        from peritext_tpu.parallel.multihost import _recv_message, _send_changes

        chs = self._changes()
        ctx = TraceContext(0x123, 9)
        for caps, ctx_in, version in (
            (0, ctx, 2), (4, ctx, 2), (5, ctx, 5), (5, None, 2),
            (6, ctx, 6), (6, None, 6),
        ):
            a, b = socketlib.socketpair()
            try:
                _send_changes(a, chs, peer_caps=caps, ctx=ctx_in)
                _, body = _recv_message(b)
                assert body[4] == version, f"caps={caps} ctx={ctx_in}"
                assert decode_frame(body) == chs
            finally:
                a.close()
                b.close()


# ---------------------------------------------------------------------------
# exporter surfaces: gauges, /convergence.json, health composition, CLI
# ---------------------------------------------------------------------------


#: exporter-schema pins — drift breaks fleet scrapers, so it must be a
#: deliberate, test-visible change
GOLDEN_CONVERGENCE_KEYS = {
    "host", "rounds", "peers", "total_lag_ops", "divergence_incidents",
    "divergent_peers",
}
GOLDEN_PEER_KEYS = {
    "ops_behind", "ops_ahead", "peak_ops_behind", "staleness_rounds",
    "exchanges", "failures", "divergent", "last_outcome", "last_error",
}


class TestConvergenceExporters:
    def _monitor(self):
        m = ConvergenceMonitor(host="exp-test")
        m.observe_frontier("peer-1", {"a": 1}, {"a": 4})
        m.observe_frontier("peer-2", {"a": 1}, {"a": 1},
                           local_digest=1, peer_digest=2)
        m.advance_round()
        m.observe_failure("peer-1", "refused")
        return m

    def test_snapshot_golden_shape(self):
        snap = self._monitor().snapshot()
        assert set(snap) == GOLDEN_CONVERGENCE_KEYS
        for peer_rec in snap["peers"].values():
            assert set(peer_rec) == GOLDEN_PEER_KEYS
        assert snap["total_lag_ops"] == 3
        assert snap["divergence_incidents"] == 1
        assert snap["divergent_peers"] == ["peer-2"]
        json.dumps(snap)

    def test_health_snapshot_composes_convergence(self):
        snap = health_snapshot(convergence=self._monitor())
        assert set(snap["convergence"]) == GOLDEN_CONVERGENCE_KEYS
        assert any(
            k.startswith("convergence.") for k in snap["counters"]
        ), "convergence counters missing from the health namespace"
        json.dumps(snap)

    def test_prometheus_gauges(self):
        text = prometheus_text(convergence=self._monitor())
        assert '# TYPE peritext_convergence_lag_ops gauge' in text
        assert 'peritext_convergence_lag_ops{peer="peer-1"} 3' in text
        assert 'peritext_convergence_staleness_rounds{peer="peer-1"} 1' in text
        assert 'peritext_convergence_divergence_incidents_total 1' in text
        assert 'peritext_convergence_total_lag_ops 3' in text
        for line in text.splitlines():
            assert line.startswith("#") or len(line.split(" ")) == 2

    def test_metrics_server_convergence_endpoint(self):
        server = MetricsServer(convergence=self._monitor())
        host, port = server.start()
        try:
            with urllib.request.urlopen(
                f"http://{host}:{port}/convergence.json"
            ) as resp:
                snap = json.loads(resp.read())
                assert set(snap) == GOLDEN_CONVERGENCE_KEYS
            with urllib.request.urlopen(
                f"http://{host}:{port}/metrics"
            ) as resp:
                assert b"peritext_convergence_lag_ops" in resp.read()
        finally:
            server.stop()

    def test_fleet_cli_renders_and_flags_lag(self, tmp_path, capsys):
        from peritext_tpu.obs.__main__ import main as obs_main

        path = tmp_path / "conv.json"
        path.write_text(json.dumps(self._monitor().snapshot()))
        # nested form (a health.json scrape) parses too
        nested = tmp_path / "health.json"
        nested.write_text(json.dumps(
            {"convergence": self._monitor().snapshot()}
        ))
        assert obs_main(["fleet", str(path), str(nested)]) == 1  # lag: exit 1
        out = capsys.readouterr().out
        assert "peer-1" in out and "lag_ops" in out and "YES" in out
        assert obs_main(["fleet", str(path), "--json"]) == 1
        rows = json.loads(capsys.readouterr().out)
        assert rows["rows"][0]["peer"] == "peer-1"
        assert rows["divergence_incidents"] == 1

    def test_fleet_cli_converged_exit_zero(self, tmp_path, capsys):
        from peritext_tpu.obs.__main__ import main as obs_main

        m = ConvergenceMonitor(host="clean")
        m.observe_frontier("p", {"a": 1}, {"a": 1})
        path = tmp_path / "conv.json"
        path.write_text(json.dumps(m.snapshot()))
        assert obs_main(["fleet", str(path)]) == 0
        assert obs_main(["fleet", str(tmp_path / "missing.json")]) == 2
