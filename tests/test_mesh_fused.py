"""Mesh-sharded fused commits (ISSUE 14): byte equality with the
single-device fused path across every storage layout, the one-staged-
program-per-drain dispatch discipline, zero steady-state compiles, and
the bounded per-mesh program cache.

Runs on the 8 virtual CPU devices conftest.py forces, so every shard
count up to 8 is exercised without hardware."""

import jax
import numpy as np
import pytest
from jax.sharding import Mesh

from peritext_tpu.obs import GLOBAL_COUNTERS
from peritext_tpu.parallel.streaming import StreamingMerge
from peritext_tpu.testing.fuzz import (
    generate_markheavy_workload,
    generate_workload,
)

LAYOUTS = ("padded", "paged", "ragged")


def _mesh(n):
    return Mesh(np.asarray(jax.devices()[:n]), ("docs",))


def _changes(workloads):
    return [[ch for log in w.values() for ch in log] for w in workloads]


def _replay(layout, mesh, changes, **kw):
    kw.setdefault("slot_capacity", 256)
    kw.setdefault("mark_capacity", 128)
    kw.setdefault("tomb_capacity", 128)
    sess = StreamingMerge(
        num_docs=len(changes), actors=("doc1", "doc2", "doc3"),
        layout=layout, mesh=mesh, **kw,
    )
    for doc, log in enumerate(changes):
        sess.ingest(doc, log)
    sess.drain()
    return sess


def _snapshot(sess):
    # read_patches_all consumes the patch stream, so capture each
    # session's triple exactly once and compare the captures
    return sess.digest(), sess.read_all(), sess.read_patches_all()


def _assert_equal(sess, ref_snap, label):
    digest, spans, patches = ref_snap
    assert sess.digest() == digest, f"{label}: digest diverged"
    assert sess.read_all() == spans, f"{label}: read_all diverged"
    assert sess.read_patches_all() == patches, f"{label}: patches diverged"


# ---------------------------------------------------------------------------
# byte equality: sharded fused commit == single-device, every layout
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("layout", LAYOUTS)
@pytest.mark.parametrize("seed", (3, 21, 77))
def test_mesh_drain_matches_single_device(layout, seed):
    changes = _changes(generate_workload(seed, num_docs=16, ops_per_doc=40))
    ref = _snapshot(_replay(layout, None, changes))
    for n in (2, 8):
        sess = _replay(layout, _mesh(n), changes)
        _assert_equal(sess, ref, f"{layout} seed={seed} shards={n}")


@pytest.mark.slow
@pytest.mark.parametrize("layout", LAYOUTS)
def test_mesh_drain_markheavy_family(layout):
    # same session shape as the seed sweep above so the compiled-program
    # ladder is shared — only the op mix (span-overlap explosion) changes
    changes = _changes(
        generate_markheavy_workload(seed=5, num_docs=16, ops_per_doc=40)
    )
    ref = _snapshot(_replay(layout, None, changes))
    sess = _replay(layout, _mesh(8), changes)
    _assert_equal(sess, ref, f"{layout} markheavy shards=8")


@pytest.mark.slow
@pytest.mark.parametrize("layout", LAYOUTS)
def test_mesh_drain_longdoc_family(layout):
    # one essay among a fleet of tweets: the per-shard page loads (and the
    # ragged walk lengths) skew hard across the mesh (session shape kept
    # on the shared compile ladder — the skew is the point, not the size)
    long = _changes(generate_workload(seed=9, num_docs=1, ops_per_doc=96))
    short = _changes(generate_workload(seed=1009, num_docs=15, ops_per_doc=16))
    changes = long + short
    ref = _snapshot(_replay(layout, None, changes))
    sess = _replay(layout, _mesh(8), changes)
    _assert_equal(sess, ref, f"{layout} longdoc shards=8")


# ---------------------------------------------------------------------------
# dispatch + compile discipline
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("layout", LAYOUTS)
def test_mesh_drain_is_one_fused_dispatch(layout):
    changes = _changes(generate_workload(seed=31, num_docs=16, ops_per_doc=40))
    sess = StreamingMerge(
        num_docs=16, actors=("doc1", "doc2", "doc3"),
        layout=layout, mesh=_mesh(8),
        slot_capacity=256, mark_capacity=128, tomb_capacity=128,
    )
    for doc, log in enumerate(changes):
        sess.ingest(doc, log)
    d0 = GLOBAL_COUNTERS.get("streaming.fused_dispatches")
    sess.drain()
    assert GLOBAL_COUNTERS.get("streaming.fused_dispatches") - d0 == 1, (
        f"{layout}: a mesh drain batch must be ONE staged program"
    )


@pytest.mark.parametrize("layout", LAYOUTS)
def test_mesh_repeat_drain_compiles_nothing(layout, recompile_sentinel):
    changes = _changes(generate_workload(seed=45, num_docs=16, ops_per_doc=32))
    _replay(layout, _mesh(8), changes)  # cold: pays the compile ladder
    recompile_sentinel.mark()
    warm = _replay(layout, _mesh(8), changes)
    recompile_sentinel.assert_steady_state(
        f"fresh-session {layout} mesh replay"
    )
    cold = _snapshot(_replay(layout, None, changes))
    _assert_equal(warm, cold, f"{layout} steady-state shards=8")


# ---------------------------------------------------------------------------
# the sharded page pool's collective reshard
# ---------------------------------------------------------------------------


def test_paged_reshard_preserves_bytes_and_counts_moves():
    changes = _changes(generate_workload(seed=77, num_docs=16, ops_per_doc=40))
    ref = _snapshot(_replay("paged", None, changes))
    sess = _replay("paged", _mesh(8), changes)
    before = GLOBAL_COUNTERS.get("store.ici_page_moves")
    out = sess.reshard()
    _assert_equal(sess, ref, "paged post-reshard shards=8")
    moved = sess._store.ici_page_moves
    assert GLOBAL_COUNTERS.get("store.ici_page_moves") - before == moved
    stats = sess._store.shard_stats()
    assert stats["shards"] == 8
    assert len(stats["shard_load"]) == 8
    assert stats["imbalance_ratio"] >= 1.0
    assert out is not None


# ---------------------------------------------------------------------------
# per-mesh program caches: fingerprint-keyed, bounded
# ---------------------------------------------------------------------------


def test_gather_rows_cache_keyed_by_mesh_fingerprint():
    from peritext_tpu.parallel import mesh_fused
    from peritext_tpu.parallel.streaming import gather_rows_fn

    # re-requesting the gather for an equivalent mesh must hit the shared
    # bounded cache (fingerprint-keyed), never build a second executable
    mesh = Mesh(np.asarray(jax.devices()), ("docs",))
    first = gather_rows_fn(mesh)
    size = mesh_fused.mesh_fn_cache_size()
    assert gather_rows_fn(Mesh(np.asarray(jax.devices()), ("docs",))) is first
    assert mesh_fused.mesh_fn_cache_size() == size
    # the cache key is the mesh FINGERPRINT, not the live object: a
    # fingerprint-equal key probe lands on the same entry
    key = (mesh_fused.mesh_fingerprint(mesh), "gather_rows")
    assert any(k == key for k in mesh_fused._MESH_FN_CACHE)


def test_mesh_fn_cache_is_bounded():
    from peritext_tpu.parallel import mesh_fused

    for i in range(mesh_fused.MESH_FN_CACHE_BOUND + 16):
        mesh_fused.mesh_fn(None, ("bound_probe", i), lambda: object())
    assert mesh_fused.mesh_fn_cache_size() <= mesh_fused.MESH_FN_CACHE_BOUND
    # and re-requesting a live key returns the cached object, not a rebuild
    probe = mesh_fused.mesh_fn(None, ("bound_probe_live",), lambda: object())
    again = mesh_fused.mesh_fn(None, ("bound_probe_live",),
                               lambda: object())
    assert probe is again
