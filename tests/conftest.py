"""Test configuration.

Tests run on CPU with 8 virtual XLA host devices so the multi-chip sharding
paths (jax.sharding.Mesh over the doc axis) are exercised without TPU
hardware.  The environment preselects the TPU platform (JAX_PLATFORMS=axon,
and the plugin re-asserts itself at config level), so we must both set the
env vars *and* update jax.config before any backend initializes.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture
def recompile_sentinel():
    """Per-jit-site XLA compile counter (jax_log_compiles-backed).

    Active for the whole test: run warmup, ``mark()``, run the steady-state
    rounds, then ``assert_steady_state()`` to require zero recompiles.
    """
    from peritext_tpu.observability import RecompileSentinel

    with RecompileSentinel() as sentinel:
        yield sentinel
