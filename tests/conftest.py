"""Test configuration.

Tests run on CPU with 8 virtual XLA host devices so the multi-chip sharding
paths (jax.sharding.Mesh over the doc axis) are exercised without TPU
hardware.  The environment preselects the TPU platform (JAX_PLATFORMS=axon,
and the plugin re-asserts itself at config level), so we must both set the
env vars *and* update jax.config before any backend initializes.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
