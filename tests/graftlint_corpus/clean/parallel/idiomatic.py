"""graftlint fixture corpus: CLEAN NEGATIVES.

Each block is the idiomatic fix for the matching violation in
bad/parallel/violations.py; the suite asserts this file scans clean (and
the suppression forms are honored).
"""

import random
import time

import jax
import jax.numpy as jnp
from functools import partial

WIDTH_TABLE = (8, 16, 32, 64)


def _width_bucket(n):
    for w in WIDTH_TABLE:
        if n <= w:
            return w
    return n


class Registry:
    def __init__(self):
        self._subscribers = {}
        self._lost = {}

    # PTL001-clean: sorted iteration over instance state
    def fanout(self, update):
        for key, callback in sorted(self._subscribers.items()):
            callback(update)

    # PTL001-clean: sorted set iteration; local dicts iterate freely
    def drop_all(self, doc_ids):
        for doc in sorted(set(doc_ids)):
            self._lost.pop(doc, None)
        local = {d: 1 for d in sorted(doc_ids)}
        return [v for _, v in local.items()]

    # PTL001-clean: order-insensitive consumers
    def stats(self):
        total = sum(v for v in self._lost.values())
        worst = max(self._lost.keys(), default=None)
        return total, worst

    # PTL001-clean: bare attribute iteration is fine for LIST state (order
    # is code-determined, not arrival hashing) and sorted() for dict state
    def walk(self):
        self._log = []
        for entry in self._log:
            yield entry
        for key in sorted(self._subscribers):
            yield key


# PTL002-clean: static reads and device-side branching
@partial(jax.jit, static_argnames=("flag",))
def traced_branch(x, flag):
    if flag:  # static argument: trace-time branch is fine
        return x + 1
    if x.shape[0] > 4:  # structural read: static at trace time
        return jnp.where(x > 0, x, -x)
    return jax.lax.fori_loop(0, x.shape[0], lambda i, acc: acc * 2, x)


# PTL003-clean: syncs live OUTSIDE the jit boundary
@jax.jit
def pure_program(x):
    return (x * 2).sum()


def read_result(x):
    return float(pure_program(x))  # host sync at the boundary, not inside


# PTL004-clean: shapes routed through the width bucket
def dispatch(docs):
    padded = jnp.zeros(_width_bucket(len(docs)))
    return pure_program(padded)


# PTL005-clean: typed error, and an annotated boundary
class MergeError(ValueError):
    pass


def guarded(op):
    try:
        return op()
    except MergeError:
        return None


def boundary(op):
    try:
        return op()
    except Exception:  # graftlint: boundary(fixture: any failure degrades to None by contract)
        return None


# PTL006-clean: seeded RNG threaded through; suppression honored
def deterministic_merge(items, seed):
    rng = random.Random(seed)
    rng.shuffle(items)
    t0 = time.perf_counter()  # graftlint: disable=PTL006
    return items, t0
