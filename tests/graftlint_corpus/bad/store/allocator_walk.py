"""Corpus case: a paged-store allocator that violates the determinism
contract two ways.  ``store/`` is merge scope ON PURPOSE — page placement
is replicated state (two replicas ingesting the same frames must build
identical page tables), so PTL001 must fire on the unsorted free-SET walk
and PTL006 on the wall-clock allocation stamp."""

import time


class SloppyPageAllocator:
    def __init__(self, total_pages):
        self.free = set(range(1, total_pages))
        self.pages = {}
        self.stamps = {}

    def alloc(self, doc, n):
        grabbed = []
        for page in self.free:  # PTL001: set iteration orders the page table
            grabbed.append(page)
            if len(grabbed) == n:
                break
        for page in grabbed:
            self.free.discard(page)
        self.pages.setdefault(doc, []).extend(grabbed)
        # PTL006: wall clock in a merge region — allocation stamps diverge
        # across replicas and make page-table fuzz failures unreproducible
        self.stamps[doc] = time.time()
        return grabbed
