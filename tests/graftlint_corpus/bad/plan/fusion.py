"""graftlint fixture: the cross-tenant fusion mistake PTL006 exists for.

Fusion-group assembly (plan/fusion.py) decides which tenants' drain
batches ride the SAME staged device program and in which doc-row order —
merge scope, even though it lives outside the merge directories (the
``merge_scope_files`` entry pins it in).  The tempting bug is ordering or
admitting tenants into a window by a wall-clock read ("who arrived
first"), which makes the fused dispatch order replica-local: two hosts
replaying the same committed windows would assemble different programs
and the byte-equality oracle (fused vs per-session drains) dies.  This
file is the TRUE POSITIVE proving the rule fires on exactly that; never
"fix" it.
"""

import time


class WallClockFusionGroup:
    def __init__(self):
        self._arrivals = {}

    def admit(self, tenant):
        # PTL006: wall-clock stamp deciding fusion-window membership
        self._arrivals[tenant] = time.monotonic()

    def window_order(self, window_opened, window_seconds):
        # the assembled doc-row order now depends on WHEN this replica
        # observed each tenant, not on the committed window contents
        return sorted(
            t for t, at in self._arrivals.items()
            if at - window_opened < window_seconds
        )
