"""Corpus case: a ragged module that smuggles the bucket ladder back in.
The basename is ``ragged.py`` ON PURPOSE — PTL007 scopes by module name,
because the one-shape contract attaches to the module, not a directory.
Both spellings must fire: the import line (the cheap catch) and the call
sites (the actual regression)."""

from peritext_tpu.utils.shapes import next_pow2  # PTL007: bucket import


def _pow2(n):
    k = 1
    while k < n:
        k *= 2
    return k


def plan_ragged_groups(ins_counts, page_size):
    groups = {}
    for doc, count in enumerate(ins_counts):
        pages = -(-max(1, count) // page_size)
        # PTL007: pow-2 rounding of a per-doc count IS the bucket ladder —
        # every distinct bucket mints a compiled shape again
        groups.setdefault(_pow2(pages), []).append(doc)
    return groups


def staged_width(counts):
    # PTL007: the canonical helper is just as banned here as the private one
    return next_pow2(max(counts, default=1))
