"""graftlint fixture: the history-plane mistake PTL006 exists for.

The anomaly scorer in ``obs/timeseries.py`` is merge scope even though
it lives in obs/ (the ``merge_scope_files`` entry pins it in, the same
plan-scope split that pins ``plan/fusion.py``): its findings feed the
incident monitor and its retained ring must replay byte-identically from
persisted segments.  The tempting bug is stamping frames — or ageing the
anomaly baseline — by a wall-clock read, which makes every replayed ring
diverge from the live one (replay happens at a different wall time) and
the byte-equality oracle (``frames_json()`` after ``replay_segments``)
dies.  Overhead is measured by CALLERS and fed in as data
(``note_overhead``), never read here.  This file is the TRUE POSITIVE
proving the rule fires on exactly that; never "fix" it.
"""

import time


class WallClockAnomalyScorer:
    def __init__(self, window_seconds):
        self.window_seconds = window_seconds
        self._baseline = []

    def score(self, value):
        # PTL006: wall-clock stamp deciding the anomaly baseline window —
        # a replayed ring ages its baseline by replay-time, not by the
        # rounds the frames were committed at
        now = time.time()
        self._baseline = [
            (at, v) for at, v in self._baseline
            if now - at < self.window_seconds
        ]
        self._baseline.append((now, value))
        vals = sorted(v for _, v in self._baseline)
        med = vals[len(vals) // 2]
        return abs(value - med)
