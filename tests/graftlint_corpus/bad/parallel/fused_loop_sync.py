"""graftlint fixture: the fused-pipeline mistake PTL003 exists for.

The fused round pipeline (parallel/streaming.py drain) chains K rounds
inside ONE device program precisely so the device never waits on the host
between rounds.  The tempting "just checking" move is a
``block_until_ready`` between chained rounds — a host sync INSIDE the
fused loop, which re-serializes exactly the async dispatch pipeline the
fusion removed (the FusionStitching defect class: a host boundary stitched
back into the middle of a device program).  This file is the TRUE POSITIVE
proving PTL003 fires on that; never "fix" it.
"""

import jax


def _chained_round(state, stream):
    state = state + stream
    # PTL003: host sync inside the fused round loop, reachable from the
    # jit root below through the file-local call graph
    jax.block_until_ready(state)
    return state


@jax.jit
def fused_round_pipeline(state, streams):
    for k in range(4):
        state = _chained_round(state, streams[k])
    return state
