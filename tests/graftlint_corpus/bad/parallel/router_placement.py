"""graftlint fixture: the serving-tier placement mistake PTL006 exists for.

Doc placement (parallel/router.py) must be a deterministic function of the
observed fleet state — two frontends placing the same doc have to agree
without coordination.  The tempting bug is breaking placement ties (or
"freshness-weighting" load) with a wall-clock read, which silently makes
placement replica-local.  This file is the TRUE POSITIVE proving the rule
fires on exactly that; never "fix" it.
"""

import time


class LeakyRouter:
    def __init__(self):
        self._load = {}

    def place(self, doc_key, size):
        # PTL006: wall-clock read inside the (merge-scope) placement path
        stamp = time.monotonic()
        best = None
        for name in sorted(self._load):
            score = self._load[name] + size
            if best is None or score < best[0]:
                best = (score, name, stamp)
        return best
