"""graftlint fixture: the host-death-detection mistake PTL006 exists for.

A heartbeat lease (parallel/lease.py) must be ROUND-counted: the death
verdict is a deterministic function of the observed beat sequence, so two
frontends that saw the same beats agree on the same verdict at the same
tick — otherwise they re-place the same doc onto different hosts
(split-brain placement).  The tempting bug is stamping the lease with a
wall-clock read ("expired if now - last_beat > ttl"), which makes the
verdict replica-local.  This file is the TRUE POSITIVE proving the rule
fires on exactly that; never "fix" it.
"""

import time


class WallClockLease:
    def __init__(self, ttl):
        self.ttl = ttl
        self._last_beat = {}

    def beat(self, host):
        # PTL006: wall-clock lease stamp inside a merge-scope verdict path
        self._last_beat[host] = time.monotonic()

    def dead(self, host):
        # the verdict now depends on WHICH replica asks, and WHEN
        return time.monotonic() - self._last_beat[host] > self.ttl
