"""graftlint fixture: the mesh-region mistake PTL003 exists for.

The mesh-sharded commit path wraps the staged K-round body in
``jax.jit(shard_map(body, ...))`` so a drain batch is ONE dispatch for
the whole mesh.  The body executes under the enclosing trace, so a
"quick peek" ``.item()`` inside a helper the shard-mapped body calls is
a host sync from INSIDE the mesh region — it stalls every shard on the
doc axis, not just one device, and re-serializes the single staged
program the mesh path exists to keep async.  This file is the TRUE
POSITIVE proving PTL003 sees through the ``shard_map`` wrapper; never
"fix" it.
"""

import jax
from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P


def _shard_debug_total(rows):
    total = rows.sum()
    # PTL003: host sync inside the shard_map region, reachable from the
    # jit root below through the mapped body's file-local call graph
    return total.item()


def _mesh_round_body(rows, stream):
    rows = rows + stream
    _shard_debug_total(rows)
    return rows


mesh_fused_commit = jax.jit(
    shard_map(
        _mesh_round_body,
        in_specs=(P("docs"), P("docs")),
        out_specs=P("docs"),
    )
)
