"""graftlint fixture corpus: TRUE POSITIVES, one block per rule.

Every construct here must be flagged; test_graftlint.py asserts the exact
set of (rule, line-context) hits, and the acceptance criterion runs the CLI
over this tree expecting a nonzero exit.  Never "fix" this file.
"""

import random
import time

import jax
import jax.numpy as jnp
import numpy as np
from functools import partial


class Registry:
    def __init__(self):
        self._subscribers = {}
        self._lost = {}

    # PTL001: dict view of long-lived instance state
    def fanout(self, update):
        for key, callback in list(self._subscribers.items()):
            callback(update)

    # PTL001: set iteration
    def drop_all(self, doc_ids):
        for doc in set(doc_ids):
            self._lost.pop(doc, None)

    # PTL001: set-typed local name
    def sweep(self):
        pending = set(self._lost)
        return [self._lost[d] for d in pending]

    # PTL001: bare iteration over dict-typed instance state
    def keys_walk(self):
        return [key for key in self._subscribers]


class PendingSet:
    def __init__(self):
        self._pending = set()

    # PTL001: bare iteration over set-typed instance state
    def drain(self):
        for doc in self._pending:
            yield doc


# PTL002: Python control flow on a traced value
@jax.jit
def traced_branch(x, flag):
    if flag:
        return x + 1
    while x:
        x = x - 1
    return jnp.where(x > 0, x, -x)


# PTL002 (via partial form) + PTL003 (.item() host sync)
@partial(jax.jit, static_argnums=1)
def traced_loop(x, width):
    total = x.sum()
    sign = 1 if total else -1  # PTL002: ternary on a traced value
    for _ in range(total):
        x = x * sign * 2
    return x.item()


# PTL003: host sync reachable through a file-local helper
def _helper_sync(x):
    return np.asarray(x) + jax.device_get(x)


@jax.jit
def calls_helper(x):
    return _helper_sync(x)


# PTL004: shape-derived static arg at a jit callsite
def dispatch(docs):
    padded = jnp.zeros(len(docs))  # PTL004: unbucketed len() shape
    return traced_loop(padded, len(docs))


# PTL003: devprof-style cost/memory probe sneaking INSIDE a merge-scope jit
# root — device-cost introspection belongs in obs/devprof.py, OUTSIDE every
# jit boundary; in traced code it is a fusion-breaking host sync
def _cost_probe(state):
    return jax.block_until_ready(state)


@jax.jit
def apply_with_probe(state):
    _cost_probe(state)
    return state + 1


# PTL005: broad except without a boundary annotation
def swallow(op):
    try:
        return op()
    except Exception:
        return None


# PTL006: wall clock + unseeded/global RNG in a merge region
def jittery_merge(items):
    deadline = time.time() + 1.0
    random.shuffle(items)
    rng = random.Random()
    return items, rng.random(), deadline
