"""Trace playback + comment model tests (reference ``src/playback.ts``,
``src/comment.ts``) and demo smoke runs."""

import subprocess
import sys
from pathlib import Path

import pytest

from peritext_tpu.bridge import create_editor, editor_doc_from_crdt
from peritext_tpu.bridge.playback import (
    endless_loop,
    execute_trace_event,
    play_trace,
    simulate_typing_for_input_op,
    trace_from_spec,
)
from peritext_tpu.core.comment import (
    Comment,
    get_comment,
    list_comments,
    put_comment,
    remove_comment,
)
from peritext_tpu.core.doc import Doc
from peritext_tpu.core.types import span
from peritext_tpu.parallel.pubsub import Publisher

REPO = Path(__file__).resolve().parent.parent


def make_editors():
    pub = Publisher()
    return {name: create_editor(name, pub) for name in ("alice", "bob")}


class TestSimulateTyping:
    def test_insert_expands_per_keystroke(self):
        events = simulate_typing_for_input_op(
            "alice", {"action": "insert", "index": 3, "values": list("hi!")}
        )
        assert [(e["index"], e["values"]) for e in events] == [
            (3, ["h"]),
            (4, ["i"]),
            (5, ["!"]),
        ]
        assert all(e["editorId"] == "alice" and e["delay"] > 0 for e in events)

    def test_non_insert_passthrough(self):
        events = simulate_typing_for_input_op(
            "bob", {"action": "addMark", "startIndex": 0, "endIndex": 2, "markType": "em"}
        )
        assert len(events) == 1 and events[0]["action"] == "addMark"


class TestTracePlayback:
    def test_trace_from_spec_plays_to_expected_result(self):
        # The reference's built-in demo trace spec (src/playback.ts:53-80):
        # concurrent bold over [0,12) and em over [4,19) on the seed text.
        trace = trace_from_spec(
            {
                "initialText": "The Peritext editor",
                "inputOps1": [
                    {"action": "addMark", "startIndex": 0, "endIndex": 12, "markType": "strong"}
                ],
                "inputOps2": [
                    {"action": "addMark", "startIndex": 4, "endIndex": 19, "markType": "em"}
                ],
            }
        )
        editors = make_editors()
        play_trace(trace, editors)
        expected = [
            span("The ", {"strong": {"active": True}}),
            span("Peritext", {"strong": {"active": True}, "em": {"active": True}}),
            span(" editor", {"em": {"active": True}}),
        ]
        for editor in editors.values():
            assert editor.view.spans() == expected
            assert editor.view == editor_doc_from_crdt(editor.doc)

    def test_missing_editor_raises(self):
        with pytest.raises(KeyError):
            execute_trace_event(
                {"editorId": "ghost", "action": "insert", "path": ["text"],
                 "index": 0, "values": ["x"]},
                make_editors(),
            )

    def test_sync_hook_called_and_restart_noop(self):
        calls = []
        editors = make_editors()
        play_trace(
            [{"action": "restart"}, {"action": "sync"}],
            editors,
            on_sync=lambda: calls.append(1),
        )
        assert calls == [1]

    def test_endless_loop_cycles(self):
        gen = endless_loop([{"action": "restart"}, {"action": "sync"}])
        kinds = [next(gen)["action"] for _ in range(5)]
        assert kinds == ["restart", "sync", "restart", "sync", "restart"]


class TestCommentModel:
    def test_put_get_list_remove(self):
        doc = Doc("alice")
        put_comment(doc, Comment(id="c1", actor="alice", content="first!"))
        put_comment(doc, Comment(id="c0", actor="bob", content="second"))
        assert get_comment(doc, "c1") == Comment("c1", "alice", "first!")
        assert [c.id for c in list_comments(doc)] == ["c0", "c1"]
        remove_comment(doc, "c1")
        assert get_comment(doc, "c1") is None
        assert [c.id for c in list_comments(doc)] == ["c0"]

    def test_comments_replicate(self):
        alice, bob = Doc("alice"), Doc("bob")
        ch1, _ = put_comment(alice, Comment(id="c1", actor="alice", content="hello"))
        bob.apply_change(ch1)
        assert get_comment(bob, "c1") == Comment("c1", "alice", "hello")
        # concurrent field edit converges by op-id LWW
        ch2, _ = put_comment(alice, Comment(id="c1", actor="alice", content="edited"))
        ch3, _ = bob.change(
            [{"path": ["comments", "c1"], "action": "set", "key": "content", "value": "bobbed"}]
        )
        alice.apply_change(ch3)
        bob.apply_change(ch2)
        assert get_comment(alice, "c1") == get_comment(bob, "c1")


class TestDemoScripts:
    @pytest.mark.parametrize(
        "script",
        ["demos/two_editors.py", "demos/essay_demo.py", "demos/multihost_demo.py",
         # the scale demo's DEFAULT config targets a real chip; the CPU test
         # checks the demo's correctness flow at a size the suite can afford
         ["demos/scale_demo.py", "--docs", "300", "--ops-per-doc", "120"]],
        ids=lambda s: s if isinstance(s, str) else s[0],
    )
    def test_demo_runs_clean(self, script):
        argv = [script] if isinstance(script, str) else script
        proc = subprocess.run(
            [sys.executable, str(REPO / argv[0]), *argv[1:]],
            capture_output=True,
            text=True,
            timeout=240,
            cwd=REPO,
        )
        assert proc.returncode == 0, proc.stderr
        assert "converged" in proc.stdout
