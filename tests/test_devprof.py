"""Device-cost observability tests (ISSUE 5): the DeviceProfiler's shape
buckets / occupancy / memory watermarks, the cross-check against the
RecompileSentinel on a fresh-session replay, the new exporter surfaces'
golden shapes (``/devprof.json``, ``peritext_device_*`` gauges,
``health_snapshot(devprof=)``, the ledger record schema), and the perf
ledger's rolling-reference regression gate."""

import json
import random
import urllib.request
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from peritext_tpu.obs import (
    DeviceProfiler,
    GLOBAL_DEVPROF,
    MetricsServer,
    health_snapshot,
    prometheus_text,
)
from peritext_tpu.obs import ledger as perf_ledger
from peritext_tpu.obs.devprof import note_jit_dispatch
from peritext_tpu.obs.__main__ import main as obs_main

REPO_ROOT = Path(__file__).resolve().parents[1]
REFERENCE_LEDGER = REPO_ROOT / "perf" / "reference_ledger.jsonl"


@pytest.fixture
def global_devprof():
    """The process profiler, armed for one test and always disarmed after —
    devprof is off by default and other tests must see it that way."""
    GLOBAL_DEVPROF.reset()
    GLOBAL_DEVPROF.enable(capture_costs=False)
    try:
        yield GLOBAL_DEVPROF
    finally:
        GLOBAL_DEVPROF.disable()
        GLOBAL_DEVPROF.reset()


# ---------------------------------------------------------------------------
# DeviceProfiler unit behavior
# ---------------------------------------------------------------------------


class TestDeviceProfiler:
    def test_off_by_default(self):
        assert DeviceProfiler().enabled is False

    def test_shape_signature_matches_compile_granularity(self):
        p = DeviceProfiler()
        a32 = np.zeros((4, 8), np.int32)
        b32 = np.zeros((4, 8), np.int32)
        key_a, sig = p.shape_signature((a32,), static=(("w", 16),))
        key_b, _ = p.shape_signature((b32,), static=(("w", 16),))
        assert key_a == key_b  # same shapes+statics: one bucket
        assert "int32(4, 8)" in sig
        # a different shape, dtype, static, or an absent optional stream
        # each mint a distinct bucket — exactly what recompiles
        others = [
            ((np.zeros((4, 16), np.int32),), (("w", 16),)),
            ((np.zeros((4, 8), np.int64),), (("w", 16),)),
            ((a32,), (("w", 32),)),
            ((a32, None), (("w", 16),)),
            (({"m": a32},), (("w", 16),)),
        ]
        keys = {key_a} | {p.shape_signature(t, static=s)[0] for t, s in others}
        assert len(keys) == 1 + len(others)

    def test_occupancy_table_generalizes_padding_efficiency(self):
        p = DeviceProfiler().enable()
        p.observe_round("D8.ki16.kd8.km8.kp8", real_ops=60, padded_capacity=320)
        p.observe_round("D8.ki16.kd8.km8.kp8", real_ops=20, padded_capacity=320)
        p.observe_round("D8.ki8.kd8.km8.kp8", real_ops=64, padded_capacity=256,
                        origin="batch.merge")
        snap = p.snapshot()
        bucket = snap["occupancy"]["D8.ki16.kd8.km8.kp8"]
        assert bucket["rounds"] == 2
        assert bucket["real_ops"] == 80
        assert bucket["padded_capacity"] == 640
        assert bucket["padding_waste"] == pytest.approx(1 - 80 / 640)
        assert snap["occupancy"]["D8.ki8.kd8.km8.kp8"]["origin"] == "batch.merge"
        totals = snap["occupancy_totals"]
        assert totals["rounds"] == 3
        assert totals["real_ops"] == 144
        assert totals["padded_capacity"] == 896
        assert totals["padding_waste"] == pytest.approx(1 - 144 / 896, abs=1e-4)

    def test_cost_and_memory_capture_on_compiled_executable(self):
        p = DeviceProfiler(capture_costs=True).enable()

        @jax.jit
        def _devprof_probe(x):
            return (x * 2 + 1).sum()

        x = jnp.ones((16, 16), jnp.float32)
        _devprof_probe(x)
        note_jit_dispatch("_devprof_probe", _devprof_probe, (x,), profiler=p)
        note_jit_dispatch("_devprof_probe", _devprof_probe, (x,), profiler=p)
        site = p.snapshot()["sites"]["_devprof_probe"]
        assert site["distinct_shapes"] == 1
        assert site["dispatches"] == 2
        (bucket,) = site["buckets"].values()
        assert bucket["cost"] is not None and bucket["cost"]["flops"] > 0
        assert bucket["memory"] is not None
        assert bucket["memory"]["peak_bytes"] >= bucket["memory"]["argument_size_in_bytes"]

    def test_memory_watermark_degrades_gracefully_without_stats(self):
        # CPU backends expose no memory_stats: the snapshot must say so
        # instead of exporting zeros a dashboard would trust
        p = DeviceProfiler().enable()
        p.sample_memory()
        mem = p.snapshot()["memory"]
        assert mem["samples"] == 1
        if jax.devices()[0].platform == "cpu":
            assert mem["available"] is False
            assert mem["bytes_in_use"] is None

    def test_disabled_hooks_record_nothing(self):
        p = DeviceProfiler()  # never enabled

        @jax.jit
        def _noop_probe(x):
            return x

        note_jit_dispatch("x", _noop_probe, (jnp.ones(2),), profiler=p)
        assert p.snapshot()["sites"] == {}


# ---------------------------------------------------------------------------
# the sentinel cross-check (satellite): on a fresh-session replay of a known
# workload, the bucket table's distinct compiled-shape count per jit site
# equals the RecompileSentinel's per-site compile count
# ---------------------------------------------------------------------------


ACTORS = ("doc1", "doc2", "doc3")
#: distinctive capacities so these sessions' compiled shapes cannot collide
#: with (= be pre-compiled by) any other test's in this process
_XCHECK_CONFIG = dict(
    num_docs=5, actors=ACTORS, slot_capacity=112, mark_capacity=48,
    tomb_capacity=56, round_insert_capacity=24, round_delete_capacity=12,
    round_mark_capacity=12, round_map_capacity=8,
)


def _arrival_rounds(workloads, rounds, rng):
    arrival = []
    for workload in workloads:
        changes = [ch for log in workload.values() for ch in log]
        rng.shuffle(changes)
        size = -(-len(changes) // rounds)
        arrival.append(
            [changes[i: i + size] for i in range(0, len(changes), size)]
        )
    return arrival


def _run_schedule(session, arrival, rounds):
    for r in range(rounds):
        for d, batches in enumerate(arrival):
            if r < len(batches):
                session.ingest(d, batches[r])
        session.drain()
        session.digest()
    return session.read_all()


def test_bucket_table_distinct_shapes_match_sentinel(recompile_sentinel,
                                                     global_devprof):
    """THE acceptance cross-check: devprof's shape-bucket keys are derived
    from the actual dispatch arguments + statics, i.e. jax's own compile
    granularity — so on a fresh-session replay every instrumented site's
    distinct-shape count equals the sentinel's compile count, and a warm
    replay adds neither a shape nor a compile."""
    from peritext_tpu.parallel.streaming import StreamingMerge
    from peritext_tpu.testing.fuzz import generate_workload

    workloads = generate_workload(seed=33, num_docs=5, ops_per_doc=36)
    arrival = _arrival_rounds(workloads, rounds=3, rng=random.Random(9))
    recompile_sentinel.mark()

    cold = _run_schedule(StreamingMerge(**_XCHECK_CONFIG), arrival, rounds=3)

    compiles = recompile_sentinel.since_mark()
    distinct = global_devprof.distinct_shapes()
    # the workload hit the fused kernel (the round-13 staged multi-round
    # program is the streaming commit path now)
    assert "apply_batch_staged_rounds" in distinct
    for site, shapes in distinct.items():
        assert shapes == compiles.get(site, 0), (
            f"site {site}: {shapes} distinct shape bucket(s) vs "
            f"{compiles.get(site, 0)} sentinel compile(s) — the bucket key "
            "has drifted from jax's compile-cache granularity"
        )

    # fresh session, same workload: zero compiles AND zero new buckets
    recompile_sentinel.mark()
    warm = _run_schedule(StreamingMerge(**_XCHECK_CONFIG), arrival, rounds=3)
    recompile_sentinel.assert_steady_state("fresh-session devprof replay")
    assert global_devprof.distinct_shapes() == distinct
    assert warm == cold
    # and the occupancy table saw every committed round of both sessions
    totals = global_devprof.snapshot()["occupancy_totals"]
    assert totals["rounds"] > 0 and totals["real_ops"] > 0
    assert 0.0 <= totals["padding_waste"] < 1.0


# ---------------------------------------------------------------------------
# exporter golden shapes (satellite): downstream scrapers are pinned
# ---------------------------------------------------------------------------


GOLDEN_DEVPROF_KEYS = {
    "enabled", "capture_costs", "sites", "occupancy", "occupancy_totals",
    "memory", "page_pool", "ragged", "mesh",
}
GOLDEN_SITE_KEYS = {"distinct_shapes", "dispatches", "buckets"}
GOLDEN_BUCKET_KEYS = {"dispatches", "sig", "cost", "memory"}
GOLDEN_OCCUPANCY_KEYS = {
    "origin", "rounds", "real_ops", "padded_capacity", "padding_waste",
}
GOLDEN_TOTALS_KEYS = {"rounds", "real_ops", "padded_capacity", "padding_waste"}
GOLDEN_MEMORY_KEYS = {"available", "samples", "bytes_in_use",
                      "peak_bytes_in_use"}
GOLDEN_LEDGER_RECORD_KEYS = {"schema", "sha", "device", "config", "rows",
                             "devprof"}
GOLDEN_LEDGER_ROW_KEYS = {"row", "metric", "value", "unit", "key"}
GOLDEN_DEVICE_GAUGES = (
    "peritext_device_distinct_shapes",
    "peritext_device_dispatches",
    "peritext_device_flops_total",
    "peritext_device_bytes_accessed_total",
    "peritext_device_peak_bytes",
    "peritext_device_rounds_total",
    "peritext_device_real_ops_total",
    "peritext_device_padded_ops_total",
    "peritext_device_padding_waste_ratio",
)


def _profiled_probe() -> DeviceProfiler:
    p = DeviceProfiler(capture_costs=True).enable()

    @jax.jit
    def _golden_probe(x):
        return x + 1

    x = jnp.ones((8, 8))
    _golden_probe(x)
    note_jit_dispatch("_golden_probe", _golden_probe, (x,), profiler=p)
    p.observe_round("D8.ki8.kd8.km8.kp8", real_ops=10, padded_capacity=256)
    p.sample_memory()
    return p


class TestDevprofExporterGoldenShapes:
    def test_snapshot_golden_shape(self):
        snap = _profiled_probe().snapshot()
        assert set(snap) == GOLDEN_DEVPROF_KEYS
        for site in snap["sites"].values():
            assert set(site) == GOLDEN_SITE_KEYS
            for bucket in site["buckets"].values():
                assert set(bucket) == GOLDEN_BUCKET_KEYS
        for occ in snap["occupancy"].values():
            assert set(occ) == GOLDEN_OCCUPANCY_KEYS
        assert set(snap["occupancy_totals"]) == GOLDEN_TOTALS_KEYS
        assert set(snap["memory"]) == GOLDEN_MEMORY_KEYS
        json.dumps(snap)  # one JSON document, end to end

    def test_health_snapshot_composition(self):
        p = _profiled_probe()
        snap = health_snapshot(devprof=p)
        assert set(snap) == {"counters", "histograms", "devprof"}
        assert set(snap["devprof"]) == GOLDEN_DEVPROF_KEYS
        json.dumps(snap, default=str)

    def test_prometheus_device_gauges(self):
        text = prometheus_text(devprof=_profiled_probe())
        for gauge in GOLDEN_DEVICE_GAUGES:
            assert f"# TYPE {gauge} gauge" in text, gauge
        assert 'peritext_device_distinct_shapes{site="_golden_probe"} 1' in text
        for line in text.splitlines():
            assert line.startswith("#") or len(line.split()) == 2

    def test_mesh_section_and_gauges(self):
        p = _profiled_probe()
        snap = p.snapshot()
        assert snap["mesh"] is None  # meshless processes export no section
        stats = {
            "shards": 4, "rows_per_shard": 4,
            "shard_load": [3, 4, 3, 2],
            "shard_utilization": [0.5, 0.75, 0.5, 0.25],
            "imbalance_ratio": 1.33, "ici_page_moves": 12,
        }
        p.observe_mesh(stats)
        p.observe_mesh(dict(stats, imbalance_ratio=1.1))
        mesh = p.snapshot()["mesh"]
        assert mesh["imbalance_ratio"] == 1.1
        assert mesh["peak_imbalance"] == 1.33  # watermark survives the dip
        text = prometheus_text(devprof=p)
        for gauge in (
            "peritext_mesh_shards",
            "peritext_mesh_rows_per_shard",
            "peritext_mesh_shard_imbalance_ratio",
            "peritext_mesh_peak_imbalance_ratio",
            "peritext_mesh_ici_page_moves",
            "peritext_mesh_shard_load",
            "peritext_mesh_shard_pool_utilization",
        ):
            assert f"# TYPE {gauge} gauge" in text, gauge
        assert 'peritext_mesh_shard_load{shard="1"} 4' in text
        assert 'peritext_mesh_shard_pool_utilization{shard="3"} 0.25' in text
        health = health_snapshot(mesh=stats)
        assert health["mesh"]["shards"] == 4
        json.dumps(health, default=str)

    def test_devprof_json_endpoint(self):
        server = MetricsServer(devprof=_profiled_probe())
        host, port = server.start()
        try:
            with urllib.request.urlopen(
                f"http://{host}:{port}/devprof.json"
            ) as resp:
                assert resp.status == 200
                snap = json.loads(resp.read())
                assert set(snap) == GOLDEN_DEVPROF_KEYS
                assert "_golden_probe" in snap["sites"]
            with urllib.request.urlopen(
                f"http://{host}:{port}/metrics"
            ) as resp:
                assert b"peritext_device_distinct_shapes" in resp.read()
        finally:
            server.stop()

    def test_ledger_record_schema(self):
        record = perf_ledger.ledger_record(
            [{"row": "streaming", "metric": "m", "value": 1.0, "unit": "ops/s",
              "docs": 64, "ops_per_doc": 96}],
            config="test", devprof=_profiled_probe().snapshot(),
        )
        assert set(record) == GOLDEN_LEDGER_RECORD_KEYS
        assert record["schema"] == perf_ledger.SCHEMA_VERSION
        (row,) = record["rows"]
        assert set(row) == GOLDEN_LEDGER_ROW_KEYS
        assert row["key"] == "docs=64,ops_per_doc=96"
        assert set(record["device"]) == {"platform", "kind", "cpus"}
        json.dumps(record)


# ---------------------------------------------------------------------------
# the perf-regression gate
# ---------------------------------------------------------------------------


def _record(value=1000.0, unit="ops/s", row="streaming", device=None,
            failed=False, extra_rows=()):
    rows = [{"row": row, "metric": "m", "value": value, "unit": unit,
             "key": "docs=64", **({"failed": True} if failed else {})}]
    rows.extend(extra_rows)
    return {
        "schema": 1, "sha": "abc", "config": "test",
        "device": device or {"platform": "cpu", "kind": "cpu", "cpus": 8},
        "rows": rows, "devprof": None,
    }


class TestPerfGate:
    def test_single_record_is_a_vacuous_pass(self):
        report = perf_ledger.evaluate([_record()])
        assert report["regressed"] is False
        assert [v["status"] for v in report["rows"]] == ["new"]

    def test_throughput_drop_beyond_band_regresses(self):
        records = [_record(1000.0), _record(1000.0), _record(400.0)]
        report = perf_ledger.evaluate(records)  # ops/s band: 50%
        (v,) = report["rows"]
        assert v["status"] == "regressed" and report["regressed"]
        assert v["ref"] == 1000.0 and v["delta_pct"] == -60.0
        # within the band: jitter, not a regression
        ok = perf_ledger.evaluate([_record(1000.0), _record(700.0)])
        assert ok["regressed"] is False

    def test_direction_comes_from_the_unit(self):
        # B/op is lower-better with a tight band: growing 20% regresses,
        # shrinking 20% is an improvement
        up = perf_ledger.evaluate([_record(5.0, "B/op"), _record(6.0, "B/op")])
        assert up["rows"][0]["status"] == "regressed"
        down = perf_ledger.evaluate([_record(5.0, "B/op"), _record(4.0, "B/op")])
        assert down["rows"][0]["status"] == "improved"
        assert down["regressed"] is False

    def test_rolling_reference_is_the_median(self):
        records = [_record(100.0), _record(1000.0), _record(1100.0),
                   _record(1000.0)]
        (v,) = perf_ledger.evaluate(records)["rows"]
        assert v["ref"] == 1000.0  # the 100.0 outlier does not drag the ref

    def test_device_mismatch_is_vacuous_unless_relaxed(self):
        other = {"platform": "tpu", "kind": "TPU v5", "cpus": 8}
        records = [_record(1000.0, device=other), _record(100.0)]
        assert perf_ledger.evaluate(records)["rows"][0]["status"] == "new"
        relaxed = perf_ledger.evaluate(records, match="any")
        assert relaxed["rows"][0]["status"] == "regressed"

    def test_deterministic_rows_gate_across_core_counts(self):
        """B/op is a function of (workload, codec), not clock speed: a
        same-platform machine with a different core count (the CI-runner
        case) must still gate it — that is what keeps the committed
        reference non-vacuous on ephemeral runners."""
        two_cores = {"platform": "cpu", "kind": "cpu", "cpus": 2}
        records = [_record(5.0, "B/op", device=two_cores), _record(7.0, "B/op")]
        report = perf_ledger.evaluate(records)
        assert report["rows"][0]["status"] == "regressed"
        # ...while the wall-clock row on the same fingerprints stays vacuous
        records = [_record(1000.0, device=two_cores), _record(100.0)]
        assert perf_ledger.evaluate(records)["rows"][0]["status"] == "new"

    def test_dropped_reference_row_fails_the_gate(self):
        """Renaming/dropping a gated bench row must be loud, never a
        silent pass: the reference row surfaces as a `missing` verdict."""
        wire = {"row": "wire", "metric": "w", "value": 5.0, "unit": "B/op",
                "key": ""}
        records = [_record(1000.0, extra_rows=[wire]), _record(1000.0)]
        report = perf_ledger.evaluate(records)
        assert report["regressed"]
        missing = [v for v in report["rows"] if v["status"] == "missing"]
        assert [v["row"] for v in missing] == ["wire"]
        assert missing[0]["ref"] == 5.0 and missing[0]["value"] is None

    def test_other_config_records_cannot_evict_references(self):
        """The rolling window applies per row identity, NOT to the record
        stream: interleaved records of another config must neither evict a
        row's true references (vacuous gate) nor suppress the missing
        check."""
        ref = _record(5.0, "B/op", row="wire")
        ref["config"] = "ladder-smoke"
        others = []
        for _ in range(perf_ledger.DEFAULT_WINDOW + 1):
            other = _record(100.0)
            other["config"] = "streaming-smoke"
            others.append(other)
        bad = _record(50.0, "B/op", row="wire")  # 10x B/op regression
        bad["config"] = "ladder-smoke"
        report = perf_ledger.evaluate([ref, *others, bad])
        (v,) = report["rows"]
        assert v["status"] == "regressed" and report["regressed"]
        # and a candidate that DROPPED the row still fails as missing
        empty = _record(1.0, row="unrelated")
        empty["config"] = "ladder-smoke"
        report = perf_ledger.evaluate([ref, *others, empty])
        assert any(v["status"] == "missing" and v["row"] == "wire"
                   for v in report["rows"])

    def test_different_config_is_a_separate_history_not_a_drop(self):
        """A single-mode record appended to a ladder ledger is a NEW
        config: no cross-config reference, and no spurious `missing`."""
        ladder = _record(1000.0)
        ladder["config"] = "ladder-smoke"
        ladder["rows"].append({"row": "wire", "metric": "w", "value": 5.0,
                               "unit": "B/op", "key": ""})
        single = _record(100.0)
        single["config"] = "streaming-smoke"
        report = perf_ledger.evaluate([ladder, single])
        assert report["regressed"] is False
        assert [v["status"] for v in report["rows"]] == ["new"]

    def test_failed_row_with_reference_regresses(self):
        records = [_record(1000.0), _record(None, failed=True)]
        report = perf_ledger.evaluate(records)
        assert report["rows"][0]["status"] == "failed"
        assert report["regressed"]

    def test_cli_gate_exit_codes(self, tmp_path, capsys):
        path = tmp_path / "ledger.jsonl"
        for rec in (_record(1000.0), _record(950.0)):
            perf_ledger.append_record(path, rec)
        assert obs_main(["perf", str(path), "--gate"]) == 0
        out = capsys.readouterr().out
        assert "streaming" in out and "ok" in out
        perf_ledger.append_record(path, _record(10.0))
        assert obs_main(["perf", str(path)]) == 0  # render-only never gates
        assert obs_main(["perf", str(path), "--gate"]) == 1
        capsys.readouterr()  # drain the table renders before parsing JSON
        assert obs_main(["perf", str(path), "--gate", "--json"]) == 1
        payload = json.loads(capsys.readouterr().out)
        assert payload["regressed"] is True

    def test_cli_unreadable_ledger_exits_2(self, tmp_path, capsys):
        assert obs_main(["perf", str(tmp_path / "missing.jsonl")]) == 2
        bad = tmp_path / "bad.jsonl"
        bad.write_text("{not json}\n")
        assert obs_main(["perf", str(bad)]) == 2

    def test_committed_reference_gates_clean_and_catches_regression(
        self, tmp_path, capsys
    ):
        """THE acceptance criterion: exit 0 on the committed reference
        ledger, exit 1 once a synthetically regressed record lands."""
        assert REFERENCE_LEDGER.is_file(), "committed reference ledger missing"
        assert obs_main(["perf", str(REFERENCE_LEDGER), "--gate"]) == 0

        records = perf_ledger.load_ledger(REFERENCE_LEDGER)
        assert records, "reference ledger is empty"
        regressed = json.loads(json.dumps(records[-1]))  # deep copy
        for row in regressed["rows"]:
            if isinstance(row.get("value"), (int, float)):
                # regress every row in its OWN bad direction
                direction = perf_ledger.DIRECTION_BY_UNIT.get(
                    row.get("unit"), +1
                )
                row["value"] = (row["value"] * 0.2 if direction > 0
                                else row["value"] * 5.0)
        work = tmp_path / "gate.jsonl"
        work.write_text(REFERENCE_LEDGER.read_text())
        perf_ledger.append_record(work, regressed)
        assert obs_main(["perf", str(work), "--gate"]) == 1
        out = capsys.readouterr().out
        assert "regressed" in out
