"""Replication layer: pubsub, change queue, anti-entropy, causal scheduling,
and recorded-trace replay."""

import random

import pytest

from peritext_tpu import Doc, PeritextError
from peritext_tpu.core.types import Change
from peritext_tpu.parallel import (
    ChangeQueue,
    ChangeStore,
    Publisher,
    apply_changes,
    causal_sort,
    causal_waves,
    sync,
)
from peritext_tpu.testing import generate_docs
from peritext_tpu.testing.fuzz import run_fuzz
from peritext_tpu.testing.traces import (
    available_traces,
    load_trace_queues,
    replay_queues,
)


def test_publisher_skips_sender():
    pub = Publisher()
    seen = {}
    pub.subscribe("a", lambda u: seen.setdefault("a", []).append(u))
    pub.subscribe("b", lambda u: seen.setdefault("b", []).append(u))
    pub.publish("a", "hello")
    assert seen == {"b": ["hello"]}
    pub.unsubscribe("b")
    with pytest.raises(ValueError):
        pub.unsubscribe("b")


def test_change_queue_flush_and_requeue_on_failure():
    flushed = []
    fail = {"on": True}

    def handler(batch):
        if fail["on"]:
            raise RuntimeError("network down")
        flushed.extend(batch)

    q = ChangeQueue(handler)
    q.enqueue("c1", "c2")
    with pytest.raises(RuntimeError):
        q.flush()
    assert len(q) == 2  # nothing dropped
    fail["on"] = False
    q.enqueue("c3")
    q.flush()
    assert flushed == ["c1", "c2", "c3"]


def test_anti_entropy_sync_converges():
    docs, _, initial = generate_docs("hello", 3)
    store = ChangeStore()
    store.append(initial)
    d1, d2, d3 = docs

    for doc, ops in (
        (d1, [{"path": ["text"], "action": "insert", "index": 5, "values": [" world"]}]),
        (d2, [{"path": ["text"], "action": "addMark", "startIndex": 0, "endIndex": 5, "markType": "strong"}]),
        (d3, [{"path": ["text"], "action": "delete", "index": 0, "count": 1}]),
    ):
        change, _ = doc.change(ops)
        store.append(change)

    sync(d1, d2, store)
    sync(d2, d3, store)
    sync(d1, d3, store)
    sync(d1, d2, store)

    spans = [d.get_text_with_formatting(["text"]) for d in docs]
    assert spans[0] == spans[1] == spans[2]
    assert d1.clock == d2.clock == d3.clock


def test_apply_changes_tolerates_reordering_and_duplicates():
    docs, _, initial = generate_docs("abc", 2)
    d1, d2 = docs
    changes = [initial]
    for ch in "xyz":
        change, _ = d1.change(
            [{"path": ["text"], "action": "insert", "index": 0, "values": [ch]}]
        )
        changes.append(change)

    fresh = Doc("fresh")
    shuffled = changes[::-1] + changes  # reversed order plus full duplicates
    apply_changes(fresh, shuffled)
    assert fresh.root["text"] == d1.root["text"]


def test_causal_sort_orders_any_shuffle():
    docs, _, initial = generate_docs("abc", 3)
    store = ChangeStore()
    store.append(initial)
    rng = random.Random(7)
    # build an entangled history: random edits + syncs
    for i in range(30):
        doc = docs[rng.randrange(3)]
        change, _ = doc.change(
            [{"path": ["text"], "action": "insert", "index": 0, "values": [str(i % 10)]}]
        )
        store.append(change)
        if i % 3 == 0:
            a, b = rng.sample(range(3), 2)
            sync(docs[a], docs[b], store)

    all_changes = [ch for actor in store.actors() for ch in store.log(actor)]
    rng.shuffle(all_changes)
    ordered = causal_sort(all_changes)
    # replaying the sorted order must never raise CausalityError
    fresh = Doc("fresh")
    for ch in ordered:
        fresh.apply_change(ch)

    # waves partition the same set and each wave is admissible
    rng.shuffle(all_changes)
    waves = causal_waves(all_changes)
    assert sum(len(w) for w in waves) == len(ordered)
    fresh2 = Doc("fresh2")
    for wave in waves:
        for ch in wave:
            fresh2.apply_change(ch)
    assert fresh2.root["text"] == fresh.root["text"]


def test_causal_sort_detects_gap():
    docs, _, initial = generate_docs("a", 2)
    d1 = docs[0]
    c2, _ = d1.change([{"path": ["text"], "action": "insert", "index": 0, "values": ["x"]}])
    c3, _ = d1.change([{"path": ["text"], "action": "insert", "index": 0, "values": ["y"]}])
    with pytest.raises(PeritextError, match="Causal gap"):
        causal_sort([initial, c3])  # c2 missing


def test_fuzz_convergence_short():
    state = run_fuzz(seed=42, iterations=120)
    assert state.syncs > 10


@pytest.mark.parametrize("seed", [1, 2, 3])
def test_fuzz_convergence_seeds(seed):
    run_fuzz(seed=seed, iterations=60)


@pytest.mark.parametrize("path", available_traces())
def test_reference_trace_replay_converges(path):
    """Replay recorded reference fuzz-failure traces: our implementation must
    converge on them (the reference's replicas famously did not)."""
    queues = load_trace_queues(path)
    doc_a = replay_queues(queues, "a")

    # Replay again with a different causal-compatible delivery schedule:
    # per-actor round-robin with the retry loop.
    doc_b = Doc("b")
    interleaved = []
    logs = [list(log) for log in queues.values()]
    while any(logs):
        for log in logs:
            if log:
                interleaved.append(log.pop(0))
    apply_changes(doc_b, interleaved)

    assert doc_a.get_text_with_formatting(["text"]) == doc_b.get_text_with_formatting(
        ["text"]
    )
    assert doc_a.clock == doc_b.clock


def test_apply_changes_reversed_large_batch():
    """Regression: reversed delivery of a large batch must not hit any
    iteration cap (the old retry loop died at ~141 changes)."""
    docs, _, initial = generate_docs("a", 1)
    d1 = docs[0]
    changes = [initial]
    for i in range(200):
        ch, _ = d1.change(
            [{"path": ["text"], "action": "insert", "index": 0, "values": ["x"]}]
        )
        changes.append(ch)
    fresh = Doc("fresh")
    apply_changes(fresh, changes[::-1])
    assert len(fresh.root["text"]) == 201


def test_causal_waves_dedup_duplicates():
    docs, _, initial = generate_docs("a", 1)
    ch, _ = docs[0].change(
        [{"path": ["text"], "action": "insert", "index": 0, "values": ["y"]}]
    )
    waves = causal_waves([initial, initial, ch, ch])
    assert sum(len(w) for w in waves) == 2
    fresh = Doc("f")
    for wave in waves:
        for c in wave:
            fresh.apply_change(c)
    assert fresh.root["text"] == ["y", "a"]
