"""Shared test helper: hand-assemble wire frames from raw parts.

One copy of the frame framing (header struct + varint-length string table +
zigzag-varint payload) for every crafted-frame test; the per-test int
payloads stay inline where the scenario lives."""

from peritext_tpu.parallel.codec import _HEADER, _MAGIC, _py_varint_encode


def craft_frame(strings, ints, n_changes, version=1) -> bytes:
    """Build a wire frame (codec layout) from raw strings + int payload."""
    payload = _py_varint_encode(ints)
    parts = [
        _HEADER.pack(_MAGIC, version, n_changes, len(strings), len(ints),
                     len(payload))
    ]
    for s in strings:
        raw = s if isinstance(s, bytes) else s.encode("utf-8")
        parts.append(_py_varint_encode([len(raw)]))
        parts.append(raw)
    parts.append(payload)
    return b"".join(parts)
