"""Time-to-visibility latency plane tests (round 20): stage-watermark
records (telescoping sum consistency, sampling decimation, visibility
finalization), the exporter golden shapes (``/latency.json``,
``peritext_latency_*``, ``health_snapshot(latency=)``), the serve-tier
integration across the padded/paged/ragged layouts, the zero-compile pin
when arming the plane, and the ``obs why`` attribution engine's
deterministic dominant-stage naming + CLI exit contract."""

import json
import urllib.request

import pytest

from peritext_tpu.obs import MetricsServer, health_snapshot, prometheus_text
from peritext_tpu.obs.__main__ import main as obs_main
from peritext_tpu.obs.latency import (
    CLOSE_BACKPRESSURE,
    CLOSE_CAUSES,
    CLOSE_FLUSH,
    CLOSE_WINDOW,
    LatencyPlane,
    SERVER_STAGES,
    STAGES,
    attribute,
    check_sum_consistency,
)
from peritext_tpu.parallel.codec import encode_frame
from peritext_tpu.parallel.streaming import StreamingMerge
from peritext_tpu.serve import SessionMux, build_arrivals, run_open_loop
from peritext_tpu.testing.fuzz import generate_workload

ACTORS = ("doc1", "doc2", "doc3")

#: the pinned ``/latency.json`` body shape (snapshot() keys)
GOLDEN_LATENCY_KEYS = {
    "enabled", "sample_every", "windows", "records", "pending_visibility",
    "never_read", "shards", "force_close", "stages", "total",
    "time_to_visibility", "slo", "last",
}

#: the pinned bench-row decomposition shape (decomposition() keys)
GOLDEN_DECOMPOSITION_KEYS = {
    "stages_ms", "total_ms", "time_to_visibility_ms", "records",
    "never_read", "shards", "force_close", "slo_burn_rate",
    "sum_consistent",
}


def serve_session(num_docs=2, ops_per_doc=30, layout="padded", **kw):
    # static_rounds is the PADDED serving shape discipline; the paged and
    # ragged layouts run adaptive rounds (and reject static_rounds).
    # Resident shapes mirror the variants the rest of tier-1 already
    # compiles (test_serve's padded mux sessions, test_store/test_ragged's
    # paged/ragged _build sessions) so this module pre-warms the shared
    # XLA cache instead of minting cold per-file program variants.
    if layout == "padded":
        kw.setdefault("static_rounds", True)
        return StreamingMerge(
            num_docs=num_docs, actors=ACTORS, layout=layout,
            slot_capacity=max(256, 4 * ops_per_doc),
            mark_capacity=max(64, ops_per_doc),
            tomb_capacity=max(128, ops_per_doc),
            round_insert_capacity=128, round_delete_capacity=64,
            round_mark_capacity=64, **kw,
        )
    return StreamingMerge(
        num_docs=num_docs, actors=ACTORS, layout=layout,
        slot_capacity=256, mark_capacity=64, tomb_capacity=64, **kw,
    )


def doc_frames(seed=31, num_docs=2, ops_per_doc=30, chunk=6):
    plans = []
    for w in generate_workload(seed, num_docs=num_docs,
                               ops_per_doc=ops_per_doc):
        changes = [ch for log in w.values() for ch in log]
        plans.append([
            encode_frame(changes[i:i + chunk])
            for i in range(0, len(changes), chunk)
        ])
    return plans


def observe(plane, *, submit=0.0, admit=0.001, close=0.003, staged=0.004,
            commit=0.010, **kw):
    return plane.observe_batch(submit=submit, admit=admit, close=close,
                               staged=staged, commit=commit, **kw)


# ---------------------------------------------------------------------------
# the plane itself
# ---------------------------------------------------------------------------


class TestLatencyPlane:
    def test_off_by_default_and_arming(self):
        plane = LatencyPlane()
        assert not plane.enabled
        assert plane.enable() is plane and plane.enabled
        plane.disable()
        assert not plane.enabled
        with LatencyPlane() as armed:
            assert armed.enabled
        assert not armed.enabled

    def test_record_telescopes_to_total(self):
        plane = LatencyPlane().enable()
        rec = observe(plane, marks={"apply_seconds": 0.004, "rounds": 2})
        assert rec is not None
        assert set(rec["stages"]) == set(SERVER_STAGES)
        assert all(v >= 0 for v in rec["stages"].values())
        # the telescoping identity: server stages sum EXACTLY to total
        assert rec["total"] == pytest.approx(
            sum(rec["stages"].values()), abs=1e-12
        )
        assert rec["total"] == pytest.approx(0.010, abs=1e-9)
        assert check_sum_consistency(rec)
        assert rec["rounds"] == 2

    def test_commit_split_honours_span_bound(self):
        # apply_seconds longer than the staged→commit span cannot drive
        # dispatch negative: commit is clamped to the span
        plane = LatencyPlane().enable()
        rec = observe(plane, marks={"apply_seconds": 99.0})
        assert rec["stages"]["dispatch"] == 0.0
        assert rec["stages"]["commit"] == pytest.approx(
            rec["total"] - rec["stages"]["admit"] - rec["stages"]["window"]
            - rec["stages"]["stage"], abs=1e-12,
        )
        assert check_sum_consistency(rec)

    def test_sampling_decimates_but_counts_windows(self):
        plane = LatencyPlane(sample_every=4).enable()
        sampled = [observe(plane) is not None for _ in range(8)]
        assert sampled == [True, False, False, False,
                           True, False, False, False]
        snap = plane.snapshot()
        assert snap["windows"] == 8 and snap["records"] == 2

    def test_mark_visible_finalizes_pending(self):
        plane = LatencyPlane().enable()
        rec = observe(plane, commit=0.010)
        assert rec["visible"] is None
        n = plane.mark_visible(0.015)
        assert n == 1
        assert rec["stages"]["visibility"] == pytest.approx(0.005)
        # visibility sits ON TOP of the commit total
        assert rec["time_to_visibility"] == pytest.approx(
            rec["total"] + 0.005
        )
        assert check_sum_consistency(rec)
        # repeat reads between commits are free
        assert plane.mark_visible(0.016) == 0

    def test_unread_backlog_bounded(self):
        plane = LatencyPlane(pending_cap=4).enable()
        for _ in range(7):
            observe(plane)
        snap = plane.snapshot()
        assert snap["pending_visibility"] == 4
        assert snap["never_read"] == 3

    def test_force_close_causes_typed(self):
        plane = LatencyPlane().enable()
        observe(plane, cause=CLOSE_WINDOW)
        observe(plane, cause=CLOSE_BACKPRESSURE)
        observe(plane, cause=CLOSE_FLUSH)
        assert plane.force_close == {c: 1 for c in CLOSE_CAUSES}

    def test_slo_burn_rate(self):
        plane = LatencyPlane(slo_seconds=0.005, slo_target=0.9).enable()
        observe(plane, commit=0.010)  # violates the 5ms SLO
        observe(plane, commit=0.002)  # holds it
        slo = plane.slo()
        assert slo["violations"] == 1 and slo["window"] == 2
        assert slo["burn_rate"] == pytest.approx(0.5 / 0.1, abs=1e-6)

    def test_decomposition_golden_shape(self):
        plane = LatencyPlane().enable()
        observe(plane)
        plane.mark_visible(0.012)
        dec = plane.decomposition()
        assert set(dec) == GOLDEN_DECOMPOSITION_KEYS
        assert dec["sum_consistent"] is True
        assert set(dec["stages_ms"]) == set(STAGES)
        assert all(v >= 0 for v in dec["stages_ms"].values())

    def test_check_sum_consistency_rejects(self):
        bad = {"stages": {"admit": -0.001, "window": 0.0, "stage": 0.0,
                          "dispatch": 0.0, "commit": 0.0}, "total": -0.001}
        assert not check_sum_consistency(bad)
        leaky = {"stages": {s: 0.001 for s in SERVER_STAGES}, "total": 0.5}
        assert not check_sum_consistency(leaky)
        # the client-wall bound: server stages (past admission) cannot
        # exceed what the client observed
        plane = LatencyPlane().enable()
        rec = observe(plane)
        client = rec["total"] - rec["stages"]["admit"]
        assert check_sum_consistency(rec, client_wall=client + 0.001)
        assert not check_sum_consistency(rec, client_wall=client / 2)


# ---------------------------------------------------------------------------
# exporter golden shapes
# ---------------------------------------------------------------------------


class TestLatencyExporters:
    def make_plane(self):
        plane = LatencyPlane().enable()
        observe(plane, cause=CLOSE_FLUSH)
        plane.mark_visible(0.013)
        return plane

    def test_latency_json_route_golden_shape(self):
        plane = self.make_plane()
        server = MetricsServer(latency=plane)
        host, port = server.start()
        try:
            body = json.loads(urllib.request.urlopen(
                f"http://{host}:{port}/latency.json", timeout=5
            ).read())
        finally:
            server.stop()
        assert set(body) == GOLDEN_LATENCY_KEYS
        assert body["enabled"] is True
        assert body["records"] == 1 and body["pending_visibility"] == 0
        assert set(body["stages"]) == set(STAGES)
        for entry in body["stages"].values():
            assert {"count", "sum", "max", "p50", "p95", "p99",
                    "overflow"} == set(entry)
        assert set(body["force_close"]) == set(CLOSE_CAUSES)

    def test_prometheus_latency_families(self):
        text = prometheus_text(latency=self.make_plane())
        for name in (
            "peritext_latency_admit_seconds_count 1",
            "peritext_latency_commit_seconds_count 1",
            "peritext_latency_visibility_seconds_count 1",
            "peritext_latency_total_seconds_count 1",
            "peritext_latency_time_to_visibility_seconds_count 1",
            "peritext_latency_admit_seconds_overflow 0",
            "peritext_latency_enabled 1",
            "peritext_latency_records 1",
            "peritext_latency_pending_visibility 0",
            "peritext_latency_slo_burn_rate",
            'peritext_latency_force_close_total{cause="flush"} 1',
        ):
            assert name in text, f"missing {name!r}"
        # exposition discipline: every sample line is `name value`
        for line in text.splitlines():
            if line and not line.startswith("#"):
                assert len(line.split()) == 2, line

    def test_health_snapshot_latency_opt_in(self):
        snap = health_snapshot(latency=self.make_plane())
        assert set(snap["latency"]) == GOLDEN_LATENCY_KEYS
        json.dumps(snap)  # one JSON document, end to end
        assert "latency" not in health_snapshot()  # strictly opt-in


# ---------------------------------------------------------------------------
# serve-tier integration
# ---------------------------------------------------------------------------


class TestServeIntegration:
    def drive(self, layout, read_every=2):
        # num_docs=8 on the non-padded layouts: the doc axis is a compiled
        # shape dimension, and D=8 is the rung test_store/test_ragged's
        # _build sessions already pay the paged/ragged compiles for
        num_docs = 2 if layout == "padded" else 8
        plans = doc_frames(seed=37 + len(layout), num_docs=num_docs)
        mux = SessionMux(serve_session(num_docs=num_docs, layout=layout),
                         host="hL")
        mux.latency_plane = LatencyPlane().enable()
        frames = {}
        for doc, plan in enumerate(plans):
            sid, verdict = mux.open_session(f"c{doc}")
            assert verdict.admitted
            frames[sid] = plan
        res = run_open_loop(
            mux, build_arrivals(frames, 400.0, 0.05),
            deadline_s=10.0, read_every=read_every,
        )
        return mux, res

    @pytest.mark.parametrize("layout", ["padded", "paged", "ragged"])
    def test_sum_consistency_across_layouts(self, layout):
        mux, res = self.drive(layout)
        plane = mux.latency_plane
        assert plane.records > 0, "armed plane sampled nothing"
        rec = plane.last
        assert all(v >= 0 for v in rec["stages"].values())
        # stage sum ≤ the client-observed wall: the server's decomposition
        # cannot claim more time than the slowest admitted frame saw
        assert check_sum_consistency(rec, client_wall=res.max_apply_s)
        assert res.latency is not None
        assert res.latency["sum_consistent"] is True
        assert "latency" in res.to_json()

    def test_visibility_marked_by_reads(self):
        mux, _ = self.drive("padded")
        snap = mux.latency_plane.snapshot()
        # the tail flush's read finalized everything pending
        assert snap["pending_visibility"] == 0
        assert snap["time_to_visibility"]["count"] > 0
        last = snap["last"]
        assert last["time_to_visibility"] >= last["total"]

    def test_disabled_plane_records_nothing(self):
        plans = doc_frames()
        mux = SessionMux(serve_session(), host="h0")
        sid, _ = mux.open_session("c0")
        for f in plans[0][:4]:
            mux.submit(sid, f)
        mux.flush()
        mux.patches(sid)
        from peritext_tpu.obs.latency import GLOBAL_LATENCY
        assert mux.latency_plane is GLOBAL_LATENCY
        assert not mux.latency_plane.enabled

    def test_arming_plane_compiles_nothing(self):
        """The devprof-grade overhead pin: arming the plane on a repeat
        workload must mint ZERO new XLA programs — watermarks are host
        clock reads, never traced values."""
        from peritext_tpu.obs import RecompileSentinel

        plans = doc_frames(seed=41)

        def drive(armed):
            mux = SessionMux(serve_session(), host="hS")
            if armed:
                mux.latency_plane = LatencyPlane().enable()
            sids = []
            for doc, _ in enumerate(plans):
                sid, _ = mux.open_session(f"c{doc}")
                sids.append(sid)
            for k in range(4):
                for doc, plan in enumerate(plans):
                    mux.submit(sids[doc], plan[k % len(plan)])
                mux.flush()
            return [mux.patches(s) for s in sids]

        cold = drive(armed=False)
        with RecompileSentinel() as sentinel:
            sentinel.mark()
            warm = drive(armed=True)
            sentinel.assert_steady_state("arming the latency plane")
        assert warm == cold

    def test_admission_verdict_tail_and_fault_context(self, tmp_path):
        """Satellite: quarantine/rollback dumps carry the affected doc's
        admission-verdict tail via the recorder's context providers."""
        from peritext_tpu.obs import FlightRecorder

        plans = doc_frames()
        mux = SessionMux(serve_session(), host="hF")
        sid, _ = mux.open_session("c0")
        for f in plans[0][:3]:
            mux.submit(sid, f)
        mux.flush()
        tail = mux.admission.verdict_tail(sid)
        assert len(tail) == 3
        assert all(t["kind"] == "admit" and "seq" in t for t in tail)
        ctx = mux._fault_context({"doc": 0})
        assert ctx and all(c["session"] == sid for c in ctx)
        assert all(c["verdict"] == "admit" for c in ctx)

        rec = FlightRecorder(capacity=16, dump_dir=tmp_path)
        rec.add_context_provider(
            "admission-verdicts", mux._fault_context,
        )
        rec.fault("quarantine", doc=0)
        path = rec.last_dump_path
        assert path is not None
        lines = [json.loads(l) for l in
                 path.read_text().splitlines() if l.strip()]
        ctx_lines = [l for l in lines if l.get("kind") == "context"]
        assert len(ctx_lines) == 3
        assert all(l["provider"] == "admission-verdicts" for l in ctx_lines)
        assert all(l["doc"] == 0 and l["verdict"] == "admit"
                   for l in ctx_lines)


# ---------------------------------------------------------------------------
# attribution: obs why
# ---------------------------------------------------------------------------


def ledger_rec(sha, value, stages_ms, row="serve_sustained",
               unit="docs/s", devprof=None):
    lat = {"stages_ms": dict(stages_ms),
           "total_ms": round(sum(v for s, v in stages_ms.items()
                                 if s != "visibility"), 4)}
    rec = {
        "sha": sha, "config": "c1",
        "device": {"platform": "cpu", "kind": "cpu0"},
        "rows": [{"row": row, "unit": unit, "value": value, "latency": lat}],
    }
    if devprof is not None:
        rec["devprof"] = devprof
    return rec


BASE_STAGES = {"admit": 0.1, "window": 2.0, "stage": 0.2,
               "dispatch": 0.5, "commit": 1.0, "visibility": 0.3}


class TestAttribution:
    def regressed_ledger(self, moved="window", by=7.0):
        records = [ledger_rec(f"r{i}", 100.0, BASE_STAGES)
                   for i in range(5)]
        stages = dict(BASE_STAGES)
        stages[moved] += by
        records.append(ledger_rec("bad", 50.0, stages))
        return records

    def test_names_dominant_stage_deterministically(self):
        out = attribute(self.regressed_ledger(), tolerance=0.1)
        assert out["verdict"] == "regression-attributed"
        assert out["dominant_stage"] == "window"
        assert out["row"] == "serve_sustained"
        assert out["delta"] == -50.0
        assert out["stage_deltas_ms"]["window"] == pytest.approx(7.0)
        # same inputs, same verdict — always
        again = attribute(self.regressed_ledger(), tolerance=0.1)
        assert again["dominant_stage"] == out["dominant_stage"]
        assert again["stage_deltas_ms"] == out["stage_deltas_ms"]

    def test_tie_breaks_to_earliest_stage(self):
        records = [ledger_rec(f"r{i}", 100.0, BASE_STAGES)
                   for i in range(5)]
        stages = dict(BASE_STAGES)
        stages["stage"] += 3.0
        stages["commit"] += 3.0  # identical delta, later in the taxonomy
        records.append(ledger_rec("bad", 50.0, stages))
        out = attribute(records, tolerance=0.1)
        assert out["dominant_stage"] == "stage"

    def test_clean_gate_attributes_nothing(self):
        records = [ledger_rec(f"r{i}", 100.0, BASE_STAGES)
                   for i in range(6)]
        out = attribute(records, tolerance=0.1)
        assert out["verdict"] == "clean" and out["row"] is None

    def test_regression_without_decomposition(self):
        records = [ledger_rec(f"r{i}", 100.0, BASE_STAGES)
                   for i in range(5)]
        records.append({
            "sha": "bad", "config": "c1",
            "device": {"platform": "cpu", "kind": "cpu0"},
            "rows": [{"row": "serve_sustained", "unit": "docs/s",
                      "value": 50.0}],
        })
        out = attribute(records, tolerance=0.1)
        assert out["verdict"] == "no-decomposition"
        assert out["dominant_stage"] is None

    def test_unmoved_stages_is_unattributed(self):
        records = [ledger_rec(f"r{i}", 100.0, BASE_STAGES)
                   for i in range(5)]
        records.append(ledger_rec("bad", 50.0, BASE_STAGES))
        out = attribute(records, tolerance=0.1)
        assert out["verdict"] == "regression-unattributed"
        assert out["dominant_stage"] is None

    def test_devprof_shape_deltas_attached(self):
        def dp(shapes, dispatches, waste):
            return {"sites": {"apply": {"distinct_shapes": shapes,
                                        "dispatches": dispatches}},
                    "occupancy_totals": {"padding_waste": waste}}
        records = [ledger_rec(f"r{i}", 100.0, BASE_STAGES,
                              devprof=dp(3, 40, 0.1)) for i in range(5)]
        stages = dict(BASE_STAGES)
        stages["stage"] += 4.0
        records.append(ledger_rec("bad", 50.0, stages,
                                  devprof=dp(5, 70, 0.4)))
        out = attribute(records, tolerance=0.1)
        assert out["devprof"]["delta"] == {
            "distinct_shapes": 2, "dispatches": 30,
            "padding_waste": pytest.approx(0.3),
        }

    def test_explicit_row_selection(self):
        out = attribute(self.regressed_ledger(), row="serve_sustained",
                        tolerance=0.1)
        assert out["row"] == "serve_sustained"
        with pytest.raises(ValueError):
            attribute(self.regressed_ledger(), row="nonexistent")


class TestWhyCommand:
    def write_ledger(self, tmp_path, records):
        p = tmp_path / "ledger.jsonl"
        p.write_text("".join(json.dumps(r) + "\n" for r in records))
        return str(p)

    def test_exit_contract(self, tmp_path, capsys):
        bad = TestAttribution().regressed_ledger()
        clean = [ledger_rec(f"r{i}", 100.0, BASE_STAGES) for i in range(6)]
        assert obs_main(["why", self.write_ledger(tmp_path, bad),
                         "--tolerance", "10"]) == 1
        out = capsys.readouterr()
        assert "dominant moved stage is 'window'" in out.err
        assert obs_main(["why", self.write_ledger(tmp_path, clean),
                         "--tolerance", "10"]) == 0
        assert obs_main(["why", str(tmp_path / "missing.jsonl")]) == 2
        empty = tmp_path / "empty.jsonl"
        empty.write_text("")
        assert obs_main(["why", str(empty)]) == 2

    def test_json_body(self, tmp_path, capsys):
        bad = TestAttribution().regressed_ledger()
        rc = obs_main(["why", self.write_ledger(tmp_path, bad),
                       "--tolerance", "10", "--json"])
        body = json.loads(capsys.readouterr().out)
        assert rc == 1
        assert body["verdict"] == "regression-attributed"
        assert body["dominant_stage"] == "window"
        assert body["candidate_stages_ms"]["window"] == pytest.approx(9.0)
        assert body["reference_stages_ms"]["window"] == pytest.approx(2.0)


# ---------------------------------------------------------------------------
# satellite: perf verdicts carry the signed delta
# ---------------------------------------------------------------------------


class TestPerfDelta:
    def test_verdicts_include_reference_and_signed_delta(self):
        from peritext_tpu.obs import ledger as _ledger

        records = [ledger_rec(f"r{i}", 100.0, BASE_STAGES)
                   for i in range(5)]
        records.append(ledger_rec("bad", 60.0, BASE_STAGES))
        report = _ledger.evaluate(records)
        v = report["rows"][0]
        assert v["ref"] == pytest.approx(100.0)
        assert v["delta"] == pytest.approx(-40.0)

    def test_perf_json_carries_delta(self, tmp_path, capsys):
        records = [ledger_rec(f"r{i}", 100.0, BASE_STAGES)
                   for i in range(3)]
        p = tmp_path / "ledger.jsonl"
        p.write_text("".join(json.dumps(r) + "\n" for r in records))
        assert obs_main(["perf", str(p), "--json"]) == 0
        body = json.loads(capsys.readouterr().out)
        assert all("delta" in row and "ref" in row for row in body["rows"])
