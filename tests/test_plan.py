"""Device-as-OS planner tests (ISSUE 13): cross-tenant fusion planning
(tenant -> lane -> doc-row assignment), the FusedMuxGroup serving wiring
(fused-vs-unfused byte equality, per-tenant verdict isolation, zero
steady-state compiles), and the closed-loop cost-model planner
(PlanProposal golden schema + determinism on the committed smoke
snapshot, CLI exit codes, exporter surfaces)."""

import json
from pathlib import Path

import pytest

from peritext_tpu.parallel.codec import encode_frame
from peritext_tpu.parallel.streaming import StreamingMerge
from peritext_tpu.plan import (
    CostModel,
    FusionGroup,
    LanePlan,
    PlanProposal,
    TenantSpec,
    load_devprof,
    propose,
)
from peritext_tpu.serve import (
    ADMIT,
    AdmissionController,
    FusedMuxGroup,
    SessionMux,
    default_lane_factory,
)
from peritext_tpu.testing.fuzz import generate_workload

ACTORS = ("doc1", "doc2", "doc3")

#: the committed plan-smoke devprof capture the golden tests read
SNAPSHOT = Path(__file__).resolve().parents[1] / "perf" / "plan_devprof.json"

SESSION_KW = dict(
    slot_capacity=128, mark_capacity=64, tomb_capacity=96,
    round_insert_capacity=32, round_delete_capacity=16,
    round_mark_capacity=16,
)


def frame_plans(names, windows, seed, ops_per_doc=24):
    """One causally-ordered workload per tenant, striped across windows."""
    workloads = generate_workload(seed=seed, num_docs=len(names),
                                  ops_per_doc=ops_per_doc)
    plans = {}
    for name, w in zip(names, workloads):
        changes = sorted((ch for log in w.values() for ch in log),
                         key=lambda c: (c.actor, c.seq))
        plans[name] = [
            encode_frame(changes[i::windows]) for i in range(windows)
        ]
    return plans


def window_plan(names, plans, windows):
    """Alternating full/sparse windows + a tail that drains leftovers."""
    out, cursor = [], {n: 0 for n in names}
    for w in range(windows):
        active = list(names) if w % 2 == 0 else names[(w // 2) % 4::4]
        step = []
        for n in active:
            if cursor[n] < windows:
                step.append((n, plans[n][cursor[n]]))
                cursor[n] += 1
        out.append(step)
    tail = [(n, plans[n][c]) for n in names for c in range(cursor[n], windows)]
    if tail:
        out.append(tail)
    return out


def build_group(specs, admission_factory=None):
    group = FusedMuxGroup(
        specs, default_lane_factory(ACTORS, **SESSION_KW),
        admission_factory=admission_factory, host="test",
    )
    sids = {}
    for spec in specs:
        sid, verdict = group.open_session(spec.tenant, "client")
        assert verdict.admitted
        sids[spec.tenant] = sid
    return group, sids


def build_solo(specs, admission_factory=None):
    muxes, sids = {}, {}
    for spec in specs:
        mux = SessionMux(
            StreamingMerge(num_docs=1, actors=ACTORS,
                           static_rounds=(spec.layout == "padded"),
                           layout=spec.layout, **SESSION_KW),
            admission=(admission_factory() if admission_factory else None),
            host="test-solo",
        )
        sid, verdict = mux.open_session("client")
        assert verdict.admitted
        muxes[spec.tenant], sids[spec.tenant] = mux, sid
    return muxes, sids


def drive_group(group, sids, plan):
    for step in plan:
        for n, frame in step:
            assert group.submit(n, sids[n], frame).admitted
        group.flush()


def drive_solo(muxes, sids, plan):
    for step in plan:
        touched = []
        for n, frame in step:
            assert muxes[n].submit(sids[n], frame).admitted
            touched.append(n)
        for n in dict.fromkeys(touched):
            muxes[n].flush()


# ---------------------------------------------------------------------------
# fusion planning (pure assignment, no device)
# ---------------------------------------------------------------------------


class TestFusionGroup:
    def test_assignment_is_deterministic_and_disjoint(self):
        specs = [TenantSpec(tenant=f"t{i}", docs=1 + i % 3) for i in range(9)]
        a = FusionGroup(specs, lane_capacity=64)
        b = FusionGroup(list(reversed(specs)), lane_capacity=64)
        assert a.to_json() == b.to_json()
        rows = []
        for slot in a.slots.values():
            rows.append((slot.lane, slot.doc_base, slot.doc_base + slot.docs))
        rows.sort()
        for (lane1, _, end1), (lane2, base2, _) in zip(rows, rows[1:]):
            if lane1 == lane2:
                assert end1 <= base2, "tenant doc ranges alias"

    def test_lane_capacity_opens_new_lanes(self):
        specs = [TenantSpec(tenant=f"t{i}", docs=4) for i in range(6)]
        g = FusionGroup(specs, lane_capacity=8)
        assert len(g.lanes) == 3
        for plan in g.lanes:
            assert plan.docs <= 8
            assert isinstance(plan, LanePlan)

    def test_layouts_never_share_a_lane(self):
        specs = [TenantSpec(tenant="p0", docs=2),
                 TenantSpec(tenant="p1", docs=2),
                 TenantSpec(tenant="q0", docs=2, layout="paged")]
        g = FusionGroup(specs)
        assert len(g.lanes) == 2
        assert {p.layout for p in g.lanes} == {"padded", "paged"}

    def test_window_rows_uniform_subset(self):
        specs = [TenantSpec(tenant=f"t{i}", docs=2) for i in range(4)]
        g = FusionGroup(specs)
        rows = g.window_rows(0, ["t1", "t3"])
        assert rows == ((2, 6), 2)

    def test_window_rows_full_lane_and_ragged_mix_fall_back(self):
        specs = [TenantSpec(tenant="a", docs=2), TenantSpec(tenant="b", docs=2),
                 TenantSpec(tenant="c", docs=4)]
        g = FusionGroup(specs)
        # ragged active mix (2-doc + 4-doc blocks) -> full-lane staging
        assert g.window_rows(0, ["a", "c"]) is None
        # every tenant active -> full-lane staging is strictly cheaper
        assert g.window_rows(0, ["a", "b", "c"]) is None

    def test_window_occupancy(self):
        specs = [TenantSpec(tenant=f"t{i}", docs=1) for i in range(8)]
        g = FusionGroup(specs)
        assert g.window_occupancy(0, ["t0", "t1"]) == pytest.approx(0.25)
        assert g.window_occupancy(0, [s.tenant for s in specs]) == 1.0

    def test_spec_validation(self):
        with pytest.raises(ValueError):
            TenantSpec(tenant="", docs=1)
        with pytest.raises(ValueError):
            TenantSpec(tenant="t", docs=0)
        with pytest.raises(ValueError):
            TenantSpec(tenant="t", docs=1, layout="columnar")
        with pytest.raises(ValueError):
            FusionGroup([TenantSpec(tenant="t", docs=1)] * 2)
        with pytest.raises(ValueError):
            FusionGroup([TenantSpec(tenant="t", docs=9)], lane_capacity=8)

    def test_wrong_lane_rejected(self):
        specs = [TenantSpec(tenant="p", docs=1),
                 TenantSpec(tenant="q", docs=1, layout="paged")]
        g = FusionGroup(specs)
        with pytest.raises(ValueError):
            g.window_rows(g.slots["p"].lane, ["q"])


# ---------------------------------------------------------------------------
# fused serving: byte equality, isolation, steady state
# ---------------------------------------------------------------------------


class TestFusedServing:
    @pytest.mark.parametrize("seed", [3, 11, 27])
    def test_fused_byte_equal_to_standalone(self, seed):
        names = [f"t{i:02d}" for i in range(6)]
        specs = [TenantSpec(tenant=n, docs=1) for n in names]
        plans = frame_plans(names, 4, seed)
        plan = window_plan(names, plans, 4)
        group, gsids = build_group(specs)
        drive_group(group, gsids, plan)
        muxes, ssids = build_solo(specs)
        drive_solo(muxes, ssids, plan)
        for n in names:
            assert group.patches(n, gsids[n]) == muxes[n].patches(ssids[n])
            assert group.read(n, gsids[n]) == muxes[n].read(ssids[n])
        fusion = group.fusion_snapshot()
        assert fusion["grouped"] is True
        assert fusion["lanes"] == 1
        assert fusion["windows"] == len(plan)

    def test_mixed_layout_window_stays_byte_equal(self):
        """Padded, paged, and ragged tenants in ONE window: one lane per
        layout (padded static_rounds, paged/ragged fused pipeline), one
        shared drain per touched lane, every tenant byte-equal to its
        standalone twin."""
        specs = ([TenantSpec(tenant=f"p{i}", docs=1) for i in range(2)]
                 + [TenantSpec(tenant=f"q{i}", docs=1, layout="paged")
                    for i in range(2)]
                 + [TenantSpec(tenant=f"r{i}", docs=1, layout="ragged")
                    for i in range(2)])
        names = [s.tenant for s in specs]
        plans = frame_plans(names, 3, seed=41)
        plan = window_plan(names, plans, 3)
        group, gsids = build_group(specs)
        assert len(group.group.lanes) == 3
        drive_group(group, gsids, plan)
        muxes, ssids = build_solo(specs)
        drive_solo(muxes, ssids, plan)
        for n in names:
            assert group.patches(n, gsids[n]) == muxes[n].patches(ssids[n])
            assert group.read(n, gsids[n]) == muxes[n].read(ssids[n])

    def test_verdict_identity_and_isolation_under_overload(self):
        """Each tenant's admission verdicts under overload are IDENTICAL
        to its standalone twin's, and one tenant's burst never leaks into
        another tenant's verdicts — isolation is per-controller, not a
        shared-queue side effect."""
        tight = dict(max_depth=4, high_watermark=0.5, low_watermark=0.25,
                     shed_after=2, session_quota=None)
        names = ["busy", "idle"]
        specs = [TenantSpec(tenant=n, docs=1) for n in names]
        plans = frame_plans(names, 2, seed=7)
        group, gsids = build_group(
            specs, admission_factory=lambda: AdmissionController(**tight))
        muxes, ssids = build_solo(
            specs, admission_factory=lambda: AdmissionController(**tight))
        burst = plans["busy"] * 6
        fused_verdicts = [group.submit("busy", gsids["busy"], f) for f in burst]
        solo_verdicts = [muxes["busy"].submit(ssids["busy"], f) for f in burst]
        assert ([(v.kind, v.reason) for v in fused_verdicts]
                == [(v.kind, v.reason) for v in solo_verdicts])
        kinds = {v.kind for v in fused_verdicts}
        assert kinds != {ADMIT}, "burst never tripped admission"
        # the idle tenant is untouched by its neighbor's overload —
        # mirrored into both arms so the accounting stays comparable
        assert group.submit("idle", gsids["idle"], plans["idle"][0]).kind \
            == ADMIT
        assert muxes["idle"].submit(ssids["idle"], plans["idle"][0]).kind \
            == ADMIT
        group.flush()
        for n in names:
            muxes[n].flush()
        for n in names:
            fused = group.muxes[n].admission.stats
            solo = muxes[n].admission.stats
            assert (fused.submitted, fused.admitted, fused.delayed,
                    fused.shed) == (solo.submitted, solo.admitted,
                                    solo.delayed, solo.shed)

    def test_repeat_window_plan_compiles_nothing(self):
        from peritext_tpu.observability import RecompileSentinel

        names = [f"t{i}" for i in range(4)]
        specs = [TenantSpec(tenant=n, docs=1) for n in names]
        plans = frame_plans(names, 3, seed=13)
        plan = window_plan(names, plans, 3)
        cold, csids = build_group(specs)
        drive_group(cold, csids, plan)
        with RecompileSentinel() as sentinel:
            sentinel.mark()
            warm, wsids = build_group(specs)
            drive_group(warm, wsids, plan)
            sentinel.assert_steady_state("fused multi-tenant repeat plan")
        for n in names:
            assert warm.read(n, wsids[n]) == cold.read(n, csids[n])

    def test_one_dispatch_per_window_per_lane(self):
        from peritext_tpu.obs import GLOBAL_COUNTERS

        names = [f"t{i}" for i in range(8)]
        specs = [TenantSpec(tenant=n, docs=1) for n in names]
        plans = frame_plans(names, 4, seed=19)
        plan = window_plan(names, plans, 4)
        group, gsids = build_group(specs)
        d0 = GLOBAL_COUNTERS.get("streaming.fused_dispatches")
        drive_group(group, gsids, plan)
        delta = int(GLOBAL_COUNTERS.get("streaming.fused_dispatches") - d0)
        assert delta == len(plan), (
            f"{delta} staged programs over {len(plan)} windows")
        assert group.fusion_snapshot()["dispatches"] == len(plan)


# ---------------------------------------------------------------------------
# the closed-loop planner
# ---------------------------------------------------------------------------


class TestPlanProposal:
    def test_golden_schema_on_committed_snapshot(self):
        proposal = propose(SNAPSHOT)
        body = proposal.to_json()
        assert set(body) == {"proposal", "current", "modeled"}
        assert set(body["proposal"]) == {
            "insert_width", "delete_width", "mark_width", "map_width",
            "slot_capacity", "page_size", "fused_depth", "window_seconds",
        }
        for key in ("current_score", "proposed_score", "savings_frac",
                    "padded_flops_current", "padded_flops_proposed",
                    "recompiles_current", "recompiles_proposed",
                    "dispatches_current", "dispatches_proposed",
                    "executable_bytes", "budget_bytes", "utilization",
                    "tolerance"):
            assert key in body["modeled"], key
        assert isinstance(proposal, PlanProposal)

    def test_proposal_is_deterministic(self):
        snap = load_devprof(SNAPSHOT)
        assert propose(snap).to_json() == propose(snap).to_json()

    def test_beats_current_matches_modeled_scores(self):
        proposal = propose(SNAPSHOT)
        cur = proposal.modeled["current_score"]
        new = proposal.modeled["proposed_score"]
        assert proposal.beats_current() == ((cur - new) / cur > 0.10)
        # an infinite tolerance band can never be beaten
        assert not proposal.beats_current(tolerance=float("inf"))

    def test_load_devprof_contract(self, tmp_path):
        snap = load_devprof(SNAPSHOT)
        # the /health.json-style wrapper is unwrapped
        assert load_devprof({"devprof": snap}) == snap
        with pytest.raises(ValueError):
            load_devprof({"not": "a snapshot"})
        bad = tmp_path / "garbage.json"
        bad.write_text("{not json")
        with pytest.raises(json.JSONDecodeError):
            load_devprof(bad)

    def test_cost_model_scores_proposed_no_worse(self):
        model = CostModel(load_devprof(SNAPSHOT))
        proposal = propose(SNAPSHOT)
        cand = {k: getattr(proposal, k)
                for k in ("insert_width", "delete_width", "mark_width",
                          "map_width", "slot_capacity", "page_size",
                          "fused_depth")}
        assert model.score(cand) <= model.score(model.observed_config())

    def test_cli_exit_codes(self, capsys, tmp_path):
        from peritext_tpu.obs.__main__ import main as obs_main

        proposal = propose(SNAPSHOT)
        rc = obs_main(["plan", str(SNAPSHOT), "--json"])
        assert rc == (1 if proposal.beats_current() else 0)
        body = json.loads(capsys.readouterr().out)
        assert body["proposal"] == proposal.to_json()["proposal"]
        assert body["beats_current"] == proposal.beats_current()
        # an unbeatable tolerance band is exit 0 ("statics are fine")
        assert obs_main(["plan", str(SNAPSHOT), "--json",
                         "--tolerance", "1000000"]) == 0
        bad = tmp_path / "garbage.json"
        bad.write_text("{not json")
        assert obs_main(["plan", str(bad)]) == 2


# ---------------------------------------------------------------------------
# surfaces: lint scope, health, gauges
# ---------------------------------------------------------------------------


class TestPlanSurfaces:
    def test_fusion_assembly_is_merge_scope_for_graftlint(self):
        from peritext_tpu.analysis.engine import LintConfig

        scope = LintConfig().merge_scope_files
        assert "plan/fusion.py" in scope
        assert "plan/model.py" not in scope  # observability: clocks legal

    def test_health_snapshot_carries_plan_verdict(self):
        from peritext_tpu.obs import health_snapshot

        proposal = propose(SNAPSHOT)
        snap = health_snapshot(plan=proposal)
        assert snap["plan"] == proposal.to_json()
        assert json.loads(json.dumps(snap))["plan"] == proposal.to_json()

    def test_prometheus_plan_gauges(self):
        from peritext_tpu.obs import prometheus_text

        proposal = propose(SNAPSHOT)
        text = prometheus_text(plan=proposal)
        for metric in ("peritext_plan_current_score",
                       "peritext_plan_proposed_score",
                       "peritext_plan_savings_frac",
                       "peritext_plan_proposed_fused_depth"):
            assert metric in text, metric

    def test_prometheus_fusion_gauges_from_mux(self):
        from peritext_tpu.obs import prometheus_text

        names = ["t0", "t1"]
        specs = [TenantSpec(tenant=n, docs=1) for n in names]
        group, _ = build_group(specs)
        text = prometheus_text(serve=group.muxes["t0"])
        assert "peritext_plan_fusion_grouped 1" in text
        assert "peritext_plan_fusion_tenants 2" in text
        assert "peritext_plan_fusion_lanes 1" in text
