"""Differential tests: batched device path vs scalar oracle.

One module-scoped DocBatch config keeps shapes stable so XLA compiles the
kernels once for the whole module.
"""

import numpy as np
import pytest

from peritext_tpu.api import DocBatch, oracle_merge
from peritext_tpu.ops.encode import encode_workloads
from peritext_tpu.testing.fuzz import generate_workload
from peritext_tpu.testing.generate import generate_docs
from peritext_tpu.testing.traces import available_traces, load_trace_queues

SLOTS, MARKS, COMMENTS, OPS = 192, 96, 32, 256


@pytest.fixture(scope="module")
def batch():
    return DocBatch(
        slot_capacity=SLOTS,
        mark_capacity=MARKS,
        comment_capacity=COMMENTS,
        op_capacity=OPS,
    )


def _assert_matches_oracle(batch, workloads, expect_fallback=()):
    report = batch.merge(workloads)
    oracle = oracle_merge(workloads)
    assert list(report.fallback_docs) == list(expect_fallback)
    for d, (dev, orc) in enumerate(zip(report.spans, oracle)):
        assert dev == orc, f"doc {d}: device {dev} != oracle {orc}"
    return report


def test_fuzz_differential(batch):
    workloads = generate_workload(seed=7, num_docs=12, ops_per_doc=60)
    report = _assert_matches_oracle(batch, workloads)
    assert report.device_ops > 0


def test_fuzz_differential_more_seeds(batch):
    workloads = generate_workload(seed=1234, num_docs=8, ops_per_doc=80)
    _assert_matches_oracle(batch, workloads)


def test_reference_traces_differential(batch):
    traces = [load_trace_queues(p) for p in available_traces()]
    _assert_matches_oracle(batch, traces)


def test_insert_delete_only(batch):
    docs, _, initial = generate_docs("hello world", 2)
    d1, d2 = docs
    store = [initial]
    c, _ = d1.change([{"path": ["text"], "action": "insert", "index": 5, "values": list(", big")}])
    store.append(c)
    c, _ = d2.change([{"path": ["text"], "action": "delete", "index": 0, "count": 2}])
    store.append(c)
    workload = {"doc1": [s for s in store if s.actor == "doc1"],
                "doc2": [s for s in store if s.actor == "doc2"]}
    _assert_matches_oracle(batch, [workload])


def test_slot_overflow_falls_back_to_oracle():
    tiny = DocBatch(slot_capacity=8, mark_capacity=8, comment_capacity=4, op_capacity=64)
    docs, _, initial = generate_docs("0123456789ABCDEF", 1)  # 16 > 8 slots
    workload = {"doc1": [initial]}
    report = tiny.merge([workload])
    assert report.fallback_docs == [0]
    assert report.spans == oracle_merge([workload])


def test_mark_table_overflow_falls_back():
    tiny = DocBatch(slot_capacity=64, mark_capacity=2, comment_capacity=4, op_capacity=64)
    docs, _, initial = generate_docs("abcdef", 1)
    d1 = docs[0]
    store = [initial]
    for _ in range(4):  # 4 marks > capacity 2
        c, _ = d1.change(
            [{"path": ["text"], "action": "addMark", "startIndex": 0, "endIndex": 3, "markType": "strong"}]
        )
        store.append(c)
    workload = {"doc1": store}
    report = tiny.merge([workload])
    assert report.fallback_docs == [0]
    assert report.spans == oracle_merge([workload])


def test_device_convergence_under_causal_reorder(batch):
    """The same change set encoded under different (admissible) linear orders
    must produce identical spans: device-path commutativity."""
    workloads = generate_workload(seed=99, num_docs=4, ops_per_doc=50)
    report_fwd = batch.merge(workloads)

    # Re-encode with actors' logs presented in a different order; causal_sort
    # tie-breaks identically, so shuffle *changes across actors* by reversing
    # the actor dict order, then also verify against the oracle.
    reversed_workloads = [
        {actor: log for actor, log in reversed(list(w.items()))} for w in workloads
    ]
    report_rev = batch.merge(reversed_workloads)
    assert report_fwd.spans == report_rev.spans


def test_encode_expresses_map_ops_on_device():
    """makeMap / map set / del encode into the map-register stream (no
    fallback); reference map LWW src/micromerge.ts:1151-1175."""
    docs, _, initial = generate_docs("ab", 1)
    d1 = docs[0]
    c, _ = d1.change([
        {"path": [], "action": "makeMap", "key": "meta"},
        {"path": ["meta"], "action": "set", "key": "title", "value": "hi"},
        {"path": ["meta"], "action": "del", "key": "title"},
    ])
    enc = encode_workloads([{"doc1": [initial, c]}])
    assert enc.fallback_docs == []
    assert int(enc.map_count[0]) == 4  # text makeList register + 3 map ops


def test_encode_inexpressible_map_value_falls_back():
    """Nested-container / float values stay oracle-only."""
    docs, _, initial = generate_docs("ab", 1)
    d1 = docs[0]
    c, _ = d1.change([
        {"path": [], "action": "set", "key": "ratio", "value": 0.5},
    ])
    enc = encode_workloads([{"doc1": [initial, c]}])
    assert enc.fallback_docs == [0]


def test_op_capacity_overflow_falls_back():
    tiny = DocBatch(slot_capacity=64, mark_capacity=16, comment_capacity=8, op_capacity=8)
    docs, _, initial = generate_docs("abcdefghij", 1)  # 11 ops > capacity 8
    workload = {"doc1": [initial]}
    report = tiny.merge([workload])
    assert report.fallback_docs == [0]
    assert report.spans == oracle_merge([workload])


def test_change_queue_backoff_on_persistent_failure():
    import time
    from peritext_tpu.parallel import ChangeQueue

    errors = []
    q = ChangeQueue(
        lambda batch: (_ for _ in ()).throw(RuntimeError("down")),
        interval=0.005,
        on_error=errors.append,
        max_backoff=0.02,
    )
    q.enqueue("c1")
    q.start()
    time.sleep(0.15)
    q.drop()
    assert errors  # reported, not leaked into the timer thread
    assert len(q) == 1  # change retained for when the network returns


# -- device-side cursor resolution (reference getCursor/resolveCursor,
# src/micromerge.ts:859-870; stability tests test/micromerge.ts:1291-1418) --


def test_cursor_resolution_matches_oracle(batch):
    from peritext_tpu.testing.fuzz import run_differential

    # run_differential itself asserts span AND cursor equality per doc
    assert run_differential(seed=42, num_docs=10, ops_per_doc=60, batch=batch) > 0
    assert run_differential(seed=99, num_docs=6, ops_per_doc=100, batch=batch) > 0


def test_cursor_collapses_left_over_deleted_anchor(batch):
    from peritext_tpu.api.batch import _oracle_doc

    docs, _, initial = generate_docs("abcdef", 2)
    d1, d2 = docs
    cursor = d1.get_cursor(["text"], 3)  # anchored on 'd'
    # concurrently: d1 deletes the cursor char itself, d2 deletes before it
    c1, _ = d1.change([{"path": ["text"], "action": "delete", "index": 3, "count": 1}])
    c2, _ = d2.change([{"path": ["text"], "action": "delete", "index": 0, "count": 2}])
    workload = {"doc1": [initial, c1], "doc2": [c2]}
    report = batch.merge([workload], cursors=[[cursor]])
    assert report.fallback_docs == []
    expected = _oracle_doc(workload).resolve_cursor(cursor)
    assert report.cursor_positions == [[expected]]
    assert expected == 1  # "cf" remains; cursor collapsed onto 'f' index 1


def test_cursor_moves_with_concurrent_insert_before(batch):
    from peritext_tpu.api.batch import _oracle_doc

    docs, _, initial = generate_docs("abc", 2)
    d1, d2 = docs
    cursor = d1.get_cursor(["text"], 2)  # anchored on 'c'
    c2, _ = d2.change(
        [{"path": ["text"], "action": "insert", "index": 0, "values": list("xy")}]
    )
    workload = {"doc1": [initial], "doc2": [c2]}
    report = batch.merge([workload], cursors=[[cursor]])
    expected = _oracle_doc(workload).resolve_cursor(cursor)
    assert report.cursor_positions == [[expected]]
    assert expected == 4


def test_cursor_on_fallback_doc_resolves_via_oracle():
    from peritext_tpu.api.batch import _oracle_doc

    tiny = DocBatch(slot_capacity=8, mark_capacity=8, comment_capacity=4, op_capacity=64)
    docs, _, initial = generate_docs("overflowing text", 1)  # > 8 slots
    (d1,) = docs
    cursor = d1.get_cursor(["text"], 5)
    workload = {"doc1": [initial]}
    report = tiny.merge([workload], cursors=[[cursor]])
    assert report.fallback_docs == [0]
    assert report.cursor_positions == [[_oracle_doc(workload).resolve_cursor(cursor)]]


def test_cursor_for_unknown_element_is_minus_one(batch):
    docs, _, initial = generate_docs("abc", 1)
    workload = {"doc1": [initial]}
    bogus = {"objectId": (1, "doc1"), "elemId": (999, "nowhere")}
    report = batch.merge([workload], cursors=[[bogus]])
    assert report.cursor_positions == [[-1]]


def test_apply_batch_compact_empty_stream():
    """A round with zero ops of one kind (unpadded empty flat array) applies
    cleanly — kernel._pad_from_flat's empty-stream contract."""
    import jax.numpy as jnp

    from peritext_tpu.ops.kernel import apply_batch_compact_jit
    from peritext_tpu.ops.packed import empty_docs

    state = empty_docs(4, 32, 16, tomb_capacity=8)
    zero4 = jnp.zeros((4,), jnp.int32)
    counts = (jnp.asarray([1, 0, 0, 0], jnp.int32), zero4, zero4)
    out = apply_batch_compact_jit(
        state,
        counts,
        (jnp.asarray([0], jnp.int32),  # ref HEAD
         jnp.asarray([1 << 10 | 1], jnp.int32),  # op 1@actor1
         jnp.asarray([ord("a")], jnp.int32)),
        jnp.zeros((0,), jnp.int32),  # no deletes at all this round
        {col: jnp.zeros((0,), jnp.int32) for col in (
            "m_action", "m_type", "m_start_kind", "m_start_elem",
            "m_end_kind", "m_end_elem", "m_op", "m_attr")},
        widths=(8, 8, 8),
    )
    import numpy as np

    assert int(np.asarray(out.num_slots)[0]) == 1
    assert not bool(np.asarray(out.overflow).any())


# -- device map registers (kernel._apply_map_doc) ---------------------------


def test_device_map_lww_concurrent_set():
    """Two replicas set the same key concurrently: larger op id wins on
    device exactly as in the oracle (reference src/micromerge.ts:1151-1175)."""
    from peritext_tpu.api.batch import _oracle_doc

    docs, _, initial = generate_docs("ab", 2)
    d1, d2 = docs
    c1, _ = d1.change([{"path": [], "action": "set", "key": "title", "value": "one"}])
    c2, _ = d2.change([{"path": [], "action": "set", "key": "title", "value": "two"}])
    w = {"doc1": [initial, c1], "doc2": [c2]}
    report = DocBatch(slot_capacity=64, mark_capacity=16).merge([w])
    assert report.fallback_docs == []
    assert report.roots[0] == _oracle_doc(w).root


def test_device_map_del_vs_set_and_nested():
    from peritext_tpu.api.batch import _oracle_doc
    from peritext_tpu.core.comment import Comment, put_comment, remove_comment

    docs, _, initial = generate_docs("ab", 2)
    d1, d2 = docs
    ca, _ = put_comment(d1, Comment(id="c1", actor="doc1", content="first"))
    d2.apply_change(ca)
    # concurrent: doc1 deletes the comment while doc2 edits its content
    cdel, _ = remove_comment(d1, "c1")
    cset, _ = d2.change(
        [{"path": ["comments", "c1"], "action": "set", "key": "content", "value": "edited"}]
    )
    w = {"doc1": [initial, ca, cdel], "doc2": [cset]}
    report = DocBatch(slot_capacity=64, mark_capacity=16).merge([w])
    assert report.fallback_docs == []
    assert report.roots[0] == _oracle_doc(w).root


def test_device_map_register_overflow_falls_back():
    docs, _, initial = generate_docs("ab", 1)
    d1 = docs[0]
    ops = [
        {"path": [], "action": "set", "key": f"k{i}", "value": i} for i in range(40)
    ]
    c, _ = d1.change(ops)
    w = {"doc1": [initial, c]}
    report = DocBatch(slot_capacity=64, mark_capacity=16, map_capacity=8).merge([w])
    assert report.fallback_docs == [0]
    from peritext_tpu.api.batch import _oracle_doc

    assert report.roots[0] == _oracle_doc(w).root  # served by the oracle


def test_comment_capacity_beyond_one_bitmask_word():
    """comment_capacity > 32 packs into multiple uint32 words (W=2); ids in
    the second word must round-trip through resolve + decode exactly."""
    from peritext_tpu.api.batch import _oracle_doc

    docs, _, initial = generate_docs("abcdef", 1)
    d1 = docs[0]
    store = [initial]
    for i in range(40):  # 40 distinct ids -> word 0 and word 1 both used
        c, _ = d1.change([
            {"path": ["text"], "action": "addMark", "startIndex": i % 3,
             "endIndex": 3 + (i % 3), "markType": "comment",
             "attrs": {"id": f"many-{i:02d}"}},
        ])
        store.append(c)
    # remove a second-word id again (winner must flip back off)
    c, _ = d1.change([
        {"path": ["text"], "action": "removeMark", "startIndex": 0,
         "endIndex": 6, "markType": "comment", "attrs": {"id": "many-37"}},
    ])
    store.append(c)
    w = {"doc1": store}
    report = DocBatch(
        slot_capacity=64, mark_capacity=64, comment_capacity=64
    ).merge([w])
    assert report.fallback_docs == []
    assert report.spans[0] == _oracle_doc(w).get_text_with_formatting(["text"])


def test_compact_block_decode_matches_full_planes():
    """The compact visible-prefix decoders are pinned against their
    full-plane twins on the SAME resolved block (the full path is the
    oracle the compact path's docstrings promise)."""
    from peritext_tpu.ops.decode import (
        block_char_states,
        block_char_states_compact,
        decode_block_spans,
        decode_block_spans_compact,
    )
    from peritext_tpu.parallel.codec import encode_frame
    from peritext_tpu.parallel.streaming import StreamingMerge

    d = 12
    workloads = generate_workload(seed=33, num_docs=d, ops_per_doc=64)
    s = StreamingMerge(num_docs=d, actors=("doc1", "doc2", "doc3"),
                       slot_capacity=192)
    for doc, w in enumerate(workloads):
        s.ingest_frame(doc, encode_frame([c for log in w.values() for c in log]))
    s.drain()

    full = s._resolved_block(0)
    compact = s._compact_block(0)
    lo, hi = s._block_bounds(0)
    mask = s._block_device_mask(full, lo, hi)
    attr_of, comment_of = s._block_tables(lo)

    assert decode_block_spans_compact(compact, attr_of, comment_of, mask) == \
        decode_block_spans(full, attr_of, comment_of, mask)
    elem_block = np.asarray(s.state.elem_id[lo:hi])
    assert block_char_states_compact(
        compact, s._actor_table, attr_of, comment_of, mask
    ) == block_char_states(
        full, elem_block, s._actor_table, attr_of, comment_of, mask
    )
