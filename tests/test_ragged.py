"""Ragged paged apply (ops/ragged.py): byte-equality against the padded
oracle, at every tier.

The ragged layout's contract is the paged layout's, sharpened: IDENTICAL
final docs, patches, digests, spans, roots and cursors to the padded
backend on every workload family — while dispatching exactly ONE compiled
apply shape for the whole pool (the recompile sentinel pins the
one-executable half; this file pins the bytes).  Both implementations are
exercised: the lax pool walk (the CPU production path) and the Pallas
kernel under ``interpret=True`` (the TPU path's semantics, minus Mosaic).
"""

import random

import numpy as np
import pytest

import jax.numpy as jnp

from peritext_tpu.api.batch import DocBatch
from peritext_tpu.ops.encode import encode_doc_streams, pad_doc_streams
from peritext_tpu.ops.kernel import apply_batch_jit, encoded_arrays_of
from peritext_tpu.ops.packed import empty_docs
from peritext_tpu.ops.ragged import (
    apply_batch_ragged_jit,
    plan_arrays,
    stream_counts,
)
from peritext_tpu.parallel.codec import encode_frame
from peritext_tpu.parallel.streaming import StreamingMerge
from peritext_tpu.store.paged import PagedDocStore, group_stream_arrays
from peritext_tpu.store.ragged import ragged_plan
from peritext_tpu.testing.fuzz import (
    generate_markheavy_workload,
    generate_workload,
)

ACTORS = ("doc1", "doc2", "doc3")

IMPLS = ("lax", "pallas_interpret")


# ---------------------------------------------------------------------------
# kernel differential: apply_batch_ragged vs the padded apply, field by field
# ---------------------------------------------------------------------------


def _ragged_vs_padded(workloads, slot_capacity, mark_capacity, page_size, impl):
    """Apply one batch both ways; assert every PackedDocs field byte-equal."""
    per_doc, fallback, actor_tables, attr_tables, map_tables = (
        encode_doc_streams(workloads)
    )
    enc = pad_doc_streams(
        per_doc, fallback, actor_tables, attr_tables, map_tables
    )
    d = enc.ins_ref.shape[0]
    ins_counts, del_counts = stream_counts(enc)

    ref = apply_batch_jit(
        empty_docs(d, slot_capacity, mark_capacity), encoded_arrays_of(enc)
    )

    store = PagedDocStore(
        d, slot_capacity, mark_capacity, page_size=page_size
    )
    rows = np.arange(d, dtype=np.int64)
    store.ensure_rows(rows, np.asarray(ins_counts, np.int64))
    plan = ragged_plan(store)
    store.pool_elem, store.pool_char, store.aux = apply_batch_ragged_jit(
        store.pool_elem, store.pool_char, store.aux,
        *plan_arrays(plan),
        group_stream_arrays(enc, None, d),
        jnp.asarray(ins_counts), jnp.asarray(del_counts),
        ragged_impl=impl,
    )
    got = store.materialize_rows(rows, bucket_pages=store.max_doc_pages)
    for f in ref._fields:
        a = np.asarray(getattr(ref, f))
        b = np.asarray(getattr(got, f))
        if f in ("elem_id", "char"):
            b = b[:, : a.shape[1]]
        assert np.array_equal(a, b), f"ragged/{impl} diverges on {f}"
    # the null page is never owned, so no dispatch may dirty it
    assert np.all(np.asarray(store.pool_elem[0]) == 0)
    assert np.all(np.asarray(store.pool_char[0]) == 0)


@pytest.mark.parametrize("impl", IMPLS)
def test_ragged_apply_uniform(impl):
    _ragged_vs_padded(
        generate_workload(3, num_docs=6, ops_per_doc=40), 512, 128, 64, impl
    )


@pytest.mark.parametrize("impl", IMPLS)
def test_ragged_apply_markheavy(impl):
    _ragged_vs_padded(
        generate_markheavy_workload(5, num_docs=4, ops_per_doc=50),
        512, 128, 64, impl,
    )


@pytest.mark.parametrize("impl", IMPLS)
def test_ragged_apply_longdoc_mix(impl):
    # the motivating shape: a book-scale doc among tweets — the paged
    # engine would split these across a bucket ladder; ragged runs ONE
    # program whose per-doc trip counts absorb the skew
    w = generate_workload(11, num_docs=5, ops_per_doc=12)
    w += generate_workload(12, num_docs=1, ops_per_doc=300)
    _ragged_vs_padded(w, 512, 128, 64, impl)


@pytest.mark.parametrize("impl", IMPLS)
def test_ragged_apply_overflow(impl):
    # docs larger than the slot capacity: the overflow flag must trip at
    # the SAME op as the padded path (cap = page_count * P == S)
    _ragged_vs_padded(
        generate_workload(7, num_docs=3, ops_per_doc=90), 64, 64, 32, impl
    )


@pytest.mark.parametrize("seed", range(4))
def test_ragged_apply_fuzz(seed):
    w = generate_workload(seed * 101 + 17, num_docs=4, ops_per_doc=30 + seed * 25)
    _ragged_vs_padded(w, 512, 128, 64, "lax")


# ---------------------------------------------------------------------------
# batch API: DocBatch(layout="ragged") vs the padded oracle
# ---------------------------------------------------------------------------


def test_docbatch_ragged_matches_padded():
    wl = generate_workload(seed=3, num_docs=6, ops_per_doc=40)
    wl += generate_workload(seed=13, num_docs=2, ops_per_doc=150)
    wl += generate_markheavy_workload(seed=7, num_docs=2, ops_per_doc=30)
    rp = DocBatch(layout="padded").merge(wl)
    rb = DocBatch(layout="ragged")
    rr = rb.merge(wl)
    assert rr.spans == rp.spans
    assert rr.roots == rp.roots
    assert rr.fallback_docs == rp.fallback_docs
    assert rr.device_ops == rp.device_ops
    # no bucket pad anywhere: occupancy is definitionally perfect
    assert rr.stats.padding_efficiency == 1.0
    assert rr.stats.extras["layout_ragged"] == 1.0
    assert rb.last_store is not None


def test_docbatch_ragged_cursors_match_padded():
    from peritext_tpu.api.batch import _oracle_doc

    wl = generate_workload(seed=29, num_docs=4, ops_per_doc=35)
    cursors = []
    for w in wl:
        doc = _oracle_doc(w)
        lids = [o for o, m in doc._metadata.items() if isinstance(m, list)]
        row = []
        if lids and doc._metadata[lids[0]]:
            meta = doc._metadata[lids[0]]
            for el in (meta[0].elem_id, meta[len(meta) // 2].elem_id):
                row.append({"objectId": lids[0], "elemId": el})
        cursors.append(row)
    rp = DocBatch(layout="padded").merge(wl, cursors=cursors)
    rr = DocBatch(layout="ragged").merge(wl, cursors=cursors)
    assert rr.cursor_positions == rp.cursor_positions


def test_docbatch_ragged_overflow_fallback_parity():
    big = generate_workload(seed=21, num_docs=4, ops_per_doc=90)
    rp = DocBatch(layout="padded", slot_capacity=64, mark_capacity=16).merge(big)
    rr = DocBatch(
        layout="ragged", slot_capacity=64, mark_capacity=16, page_size=32
    ).merge(big)
    assert rr.spans == rp.spans
    assert rr.fallback_docs == rp.fallback_docs


def test_docbatch_ragged_validation():
    with pytest.raises(ValueError):
        DocBatch(layout="bogus")
    with pytest.raises(ValueError):
        DocBatch(layout="ragged", slot_capacity=100)  # not page-aligned
    import jax

    mesh_like = object.__new__(jax.sharding.Mesh) if hasattr(
        jax.sharding, "Mesh"
    ) else object()
    with pytest.raises(ValueError):
        DocBatch(layout="ragged", mesh=mesh_like)


# ---------------------------------------------------------------------------
# streaming: RaggedStreamingMerge vs the padded session
# ---------------------------------------------------------------------------


def _arrival(workloads, rounds=3, seed=1):
    rng = random.Random(seed)
    out = []
    for w in workloads:
        chs = [ch for log in w.values() for ch in log]
        rng.shuffle(chs)
        size = -(-len(chs) // rounds)
        out.append(
            [
                encode_frame(
                    sorted(chs[i : i + size], key=lambda c: (c.actor, c.seq))
                )
                for i in range(0, len(chs), size)
            ]
        )
    return out


def _build(arrival, layout, num_docs, rounds=3, fused=True, **kw):
    s = StreamingMerge(
        num_docs=num_docs, actors=ACTORS, slot_capacity=256,
        mark_capacity=64, tomb_capacity=64, layout=layout, **kw
    )
    s.fused_pipeline = fused
    for r in range(rounds):
        s.ingest_frames(
            (d, b[r]) for d, b in enumerate(arrival) if r < len(b)
        )
        s.drain()
    return s


def test_streaming_ragged_factory_and_validation():
    s = StreamingMerge(
        num_docs=2, actors=ACTORS, slot_capacity=256, mark_capacity=16,
        tomb_capacity=16, layout="ragged",
    )
    assert type(s).__name__ == "RaggedStreamingMerge"
    assert s.layout == "ragged"
    assert s.health()["layout"] == "ragged"
    with pytest.raises(ValueError):
        StreamingMerge(
            num_docs=2, actors=ACTORS, slot_capacity=100, mark_capacity=16,
            tomb_capacity=16, layout="ragged",
        )


def test_streaming_ragged_matches_padded():
    wl = generate_workload(seed=5, num_docs=8, ops_per_doc=70)
    arr = _arrival(wl)
    sp = _build(arr, "padded", 8)
    sr = _build(arr, "ragged", 8)
    assert sr.read_all() == sp.read_all()
    assert sr.read_patches_all() == sp.read_patches_all()
    assert sr.digest() == sp.digest()
    assert sr.digest(full=False) == sp.digest(full=False)
    assert sr.digest(refresh=True) == sp.digest(refresh=True)
    assert sr.frontier() == sp.frontier()
    assert sr.overflow_count() == sp.overflow_count()


def test_streaming_ragged_serial_drain_matches():
    wl = generate_workload(seed=5, num_docs=8, ops_per_doc=70)
    arr = _arrival(wl)
    sp = _build(arr, "padded", 8)
    sr = _build(arr, "ragged", 8, fused=False)
    assert sr.digest() == sp.digest()
    assert sr.read_all() == sp.read_all()


def test_streaming_ragged_mixed_sizes_match():
    # tweet fleet + essay docs over uneven rounds: the exact mix the
    # bucket ladder fragments; digests must stay bit-equal regardless
    wl = generate_workload(seed=9, num_docs=6, ops_per_doc=12)
    wl += generate_workload(seed=11, num_docs=2, ops_per_doc=160)
    arr = _arrival(wl, rounds=4, seed=2)
    mp = _build(arr, "padded", 8, rounds=4)
    mr = _build(arr, "ragged", 8, rounds=4)
    assert mr.digest() == mp.digest()
    assert mr.read_all() == mp.read_all()


def test_streaming_ragged_overflow_parity():
    wl = generate_workload(seed=17, num_docs=3, ops_per_doc=80)
    arr = _arrival(wl, rounds=1)

    def tiny(layout):
        s = StreamingMerge(
            num_docs=3, actors=ACTORS, slot_capacity=64, mark_capacity=16,
            tomb_capacity=16, layout=layout,
        )
        s.ingest_frames((d, arr[d][0]) for d in range(3))
        s.drain()
        return s

    tp, tr = tiny("padded"), tiny("ragged")
    assert tr.overflow_count() == tp.overflow_count()
    assert tr.digest() == tp.digest()
    assert tr.read_all() == tp.read_all()
