"""Editor bridge tests (reference behaviors from ``src/bridge.ts``).

The core invariant throughout: the editor view is driven *only* by patches
(incremental path), and must equal a full ``get_text_with_formatting`` render
(batch path) after every operation — the same dual-oracle the reference's
``accumulatePatches`` tests enforce.
"""

import pytest

from peritext_tpu.bridge import (
    Editor,
    EditorDoc,
    Transaction,
    create_editor,
    editor_doc_from_crdt,
    initialize_docs,
    patch_to_steps,
    transaction_to_input_ops,
)
from peritext_tpu.bridge.commands import (
    add_comment,
    delete_range,
    set_link,
    toggle_bold,
    toggle_italic,
    type_text,
)
from peritext_tpu.core.types import span
from peritext_tpu.parallel.pubsub import Publisher


def make_pair(text="The Peritext editor"):
    pub = Publisher()
    alice = create_editor("alice", pub)
    bob = create_editor("bob", pub)
    initialize_docs([alice, bob], text)
    return pub, alice, bob


def assert_view_consistent(editor: Editor):
    """Incremental patch-driven view == full CRDT render."""
    assert editor.view == editor_doc_from_crdt(editor.doc)


class TestTransforms:
    def test_insert_step_position_shift(self):
        # Editor position p addresses CRDT index p-1 (reference :360-371).
        ops = transaction_to_input_ops(Transaction().insert_text(1, "hi"))
        assert ops == [
            {"path": ["text"], "action": "insert", "index": 0, "values": ["h", "i"]}
        ]

    def test_replace_becomes_delete_then_insert(self):
        # Reference translates content-bearing ReplaceStep as delete+insert
        # (src/bridge.ts:428-444).
        ops = transaction_to_input_ops(Transaction().replace(2, 5, "xyz"))
        assert ops == [
            {"path": ["text"], "action": "delete", "index": 1, "count": 3},
            {"path": ["text"], "action": "insert", "index": 1, "values": ["x", "y", "z"]},
        ]

    def test_mark_steps(self):
        ops = transaction_to_input_ops(
            Transaction()
            .add_mark(1, 4, "strong")
            .remove_mark(2, 3, "comment", {"id": "c1"})
        )
        assert ops == [
            {
                "path": ["text"],
                "action": "addMark",
                "startIndex": 0,
                "endIndex": 3,
                "markType": "strong",
            },
            {
                "path": ["text"],
                "action": "removeMark",
                "startIndex": 1,
                "endIndex": 2,
                "markType": "comment",
                "attrs": {"id": "c1"},
            },
        ]

    def test_patch_to_steps_roundtrip_indices(self):
        view = EditorDoc(list("abc"), [{}, {}, {}])
        for step in patch_to_steps(
            {"path": ["text"], "action": "insert", "index": 1, "values": ["X"], "marks": {}}
        ):
            step.apply(view)
        assert view.text == "aXbc"
        for step in patch_to_steps(
            {"path": ["text"], "action": "delete", "index": 0, "count": 2}
        ):
            step.apply(view)
        assert view.text == "bc"


class TestLocalDispatch:
    def test_typing_updates_view_via_patches(self):
        _, alice, bob = make_pair()
        type_text(alice, 1, "Hey! ")
        assert alice.text == "Hey! The Peritext editor"
        assert_view_consistent(alice)

    def test_bold_then_unbold(self):
        _, alice, _ = make_pair()
        toggle_bold(alice, 5, 13)
        assert {"strong": {"active": True}} in [m for m in alice.view.marks]
        assert_view_consistent(alice)
        toggle_bold(alice, 5, 13)  # toggle off
        assert all("strong" not in m for m in alice.view.marks)
        assert_view_consistent(alice)

    def test_replace_range(self):
        _, alice, _ = make_pair("hello world")
        alice.dispatch(Transaction().replace(1, 6, "goodbye"))
        assert alice.text == "goodbye world"
        assert_view_consistent(alice)

    def test_comment_and_link(self):
        _, alice, _ = make_pair("hello world")
        add_comment(alice, 1, 6, comment_id="c-1")
        set_link(alice, 7, 12, "https://example.com")
        spans = alice.doc.get_text_with_formatting(["text"])
        assert spans == [
            span("hello", {"comment": [{"id": "c-1"}]}),
            span(" "),
            span("world", {"link": {"active": True, "url": "https://example.com"}}),
        ]
        assert_view_consistent(alice)


class TestSync:
    def test_two_editor_convergence_via_pubsub(self):
        _, alice, bob = make_pair()
        type_text(alice, 1, "A")
        type_text(bob, 1, "B")
        # nothing flushed yet: views diverge
        assert alice.text != bob.text
        alice.sync()
        bob.sync()
        assert alice.text == bob.text
        assert alice.view == bob.view
        assert_view_consistent(alice)
        assert_view_consistent(bob)

    def test_concurrent_format_and_edit(self):
        _, alice, bob = make_pair("The quick fox")
        toggle_bold(alice, 1, 10)
        type_text(bob, 5, "very ")
        alice.sync()
        bob.sync()
        assert alice.text == bob.text == "The very quick fox"
        assert alice.view == bob.view
        assert_view_consistent(alice)

    def test_out_of_order_delivery_holdback(self):
        pub, alice, bob = make_pair()
        ch1 = type_text(alice, 1, "one ")
        ch2 = type_text(alice, 1, "two ")
        # deliver newest first: bob must hold it back until ch1 arrives
        bob.apply_remote(ch2)
        assert bob.text == "The Peritext editor"
        bob.apply_remote(ch1)
        assert bob.text == "two one The Peritext editor"
        assert_view_consistent(bob)

    def test_duplicate_delivery_is_idempotent(self):
        _, alice, bob = make_pair()
        ch = type_text(alice, 1, "x")
        bob.apply_remote(ch)
        bob.apply_remote(ch)
        assert bob.text == "xThe Peritext editor"
        assert_view_consistent(bob)

    def test_disconnect_drops_sync(self):
        _, alice, bob = make_pair()
        alice.disconnect()
        type_text(alice, 1, "offline ")
        # queue still accumulates; manual sync after "reconnect" delivers
        assert bob.text == "The Peritext editor"
        alice.sync()
        assert bob.text == "offline The Peritext editor"


class TestRemoteHighlightHook:
    def test_on_remote_patch_called(self):
        pub = Publisher()
        seen = []
        alice = create_editor("alice", pub)
        bob = create_editor(
            "bob", pub, on_remote_patch=lambda ed, p: seen.append(p["action"])
        )
        initialize_docs([alice, bob])
        type_text(alice, 1, "hi")
        alice.sync()
        assert "insert" in seen


class TestFuzzBridge:
    def test_random_editing_session_converges(self):
        import random

        rng = random.Random(42)
        _, alice, bob = make_pair("seed text")
        editors = [alice, bob]
        for i in range(120):
            ed = rng.choice(editors)
            n = len(ed.view)
            action = rng.randrange(4)
            if action == 0 or n == 0:
                pos = rng.randint(1, n + 1)
                type_text(ed, pos, rng.choice("abcdefgh"))
            elif action == 1 and n >= 1:
                start = rng.randint(1, n)
                end = min(n + 1, start + rng.randint(1, 3))
                delete_range(ed, start, end)
            elif action == 2 and n >= 2:
                start = rng.randint(1, n - 1)
                end = rng.randint(start + 1, n)
                toggle_bold(ed, start, end)
            elif n >= 2:
                start = rng.randint(1, n - 1)
                end = rng.randint(start + 1, n)
                toggle_italic(ed, start, end)
            if i % 10 == 0:
                alice.sync()
                bob.sync()
        alice.sync()
        bob.sync()
        assert alice.view == bob.view
        assert_view_consistent(alice)
        assert_view_consistent(bob)
