"""Checkpoint / resume tests (SURVEY §5.4): change-log round-trip, replica
restore by replay, packed-state snapshots, manager retention/atomicity, and a
mid-fuzz checkpoint-restart that must converge identically."""

import json

import numpy as np
import pytest

from peritext_tpu.checkpoint import (
    CheckpointManager,
    doc_from_store,
    load_change_log,
    load_packed,
    save_change_log,
    save_failed_trace,
    save_packed,
)
from peritext_tpu.ops.kernel import apply_batch, encoded_arrays_of
from peritext_tpu.ops.packed import empty_docs, to_numpy
from peritext_tpu.testing.fuzz import fuzz_step, make_fuzz_state, run_fuzz
from peritext_tpu.testing.traces import replay_queues


class TestChangeLogRoundTrip:
    def test_save_load_restore(self, tmp_path):
        state = run_fuzz(seed=11, iterations=40)
        path = tmp_path / "changes.jsonl"
        count = save_change_log(state.store, path)
        assert count == sum(len(state.store.log(a)) for a in state.store.actors())

        restored_store = load_change_log(path)
        assert restored_store.clock() == state.store.clock()

        restored = doc_from_store(restored_store)
        original = doc_from_store(state.store)
        assert restored.get_text_with_formatting(["text"]) == original.get_text_with_formatting(
            ["text"]
        )

    def test_wire_format_lines(self, tmp_path):
        state = run_fuzz(seed=5, iterations=10)
        path = tmp_path / "changes.jsonl"
        save_change_log(state.store, path)
        for line in path.read_text().splitlines():
            d = json.loads(line)
            assert {"actor", "seq", "deps", "startOp", "ops"} <= set(d)


class TestPackedSnapshot:
    def test_npz_round_trip(self, tmp_path):
        from peritext_tpu.ops.encode import encode_workloads
        from peritext_tpu.testing.fuzz import generate_workload

        workloads = generate_workload(seed=2, num_docs=4, ops_per_doc=30)
        encoded = encode_workloads(workloads)
        state0 = empty_docs(4, 128, 64, tomb_capacity=encoded.del_target.shape[1])
        state = to_numpy(apply_batch(state0, encoded_arrays_of(encoded)))

        path = tmp_path / "packed.npz"
        save_packed(state, path)
        restored = load_packed(path)
        for a, b in zip(state, restored):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


class TestCheckpointManager:
    def test_save_restore_latest(self, tmp_path):
        state = run_fuzz(seed=3, iterations=20)
        mgr = CheckpointManager(tmp_path / "ckpt", keep=2)
        mgr.save(1, store=state.store, meta={"phase": "early"})
        state2 = run_fuzz(seed=3, iterations=40)
        mgr.save(2, store=state2.store)

        latest = mgr.latest()
        assert latest.step == 2
        assert latest.meta["changes"] == sum(
            len(state2.store.log(a)) for a in state2.store.actors()
        )
        doc = doc_from_store(latest.store)
        assert doc.get_text_with_formatting(["text"]) == doc_from_store(
            state2.store
        ).get_text_with_formatting(["text"])

    def test_retention_prunes_oldest(self, tmp_path):
        state = run_fuzz(seed=3, iterations=5)
        mgr = CheckpointManager(tmp_path / "ckpt", keep=2)
        for step in (1, 2, 3, 4):
            mgr.save(step, store=state.store)
        assert mgr.steps() == [3, 4]

    def test_empty_save_rejected(self, tmp_path):
        with pytest.raises(ValueError):
            CheckpointManager(tmp_path).save(1)

    def test_no_staging_left_behind(self, tmp_path):
        state = run_fuzz(seed=3, iterations=5)
        mgr = CheckpointManager(tmp_path / "ckpt")
        mgr.save(7, store=state.store)
        leftovers = [p for p in (tmp_path / "ckpt").iterdir() if p.name.startswith(".staging")]
        assert leftovers == []


class TestCheckpointRestartConvergence:
    def test_mid_fuzz_restart_converges_identically(self, tmp_path):
        # Run A: 60 uninterrupted fuzz steps.
        run_a = make_fuzz_state(seed=9)
        for _ in range(60):
            fuzz_step(run_a)

        # Run B: 30 steps, checkpoint, "crash", restore the log, rebuild every
        # replica by replay, resume the remaining 30 steps with the same rng
        # stream state.
        run_b = make_fuzz_state(seed=9)
        for _ in range(30):
            fuzz_step(run_b)
        mgr = CheckpointManager(tmp_path / "ckpt")
        mgr.save(30, store=run_b.store)

        restored_store = mgr.latest().store
        # rebuild replicas at the checkpointed frontier
        for i, doc in enumerate(run_b.docs):
            rebuilt = doc_from_store(restored_store, actor_id=doc.actor_id)
            # bring the rebuilt replica to the same clock as the live one by
            # replaying exactly what that replica had seen
            assert rebuilt.clock == restored_store.clock()

        # The store after restore is byte-equivalent: resuming the SAME fuzz
        # object (whose docs already match the log frontier) must converge to
        # run A's final state.
        for _ in range(30):
            fuzz_step(run_b)

        final_a = doc_from_store(run_a.store)
        final_b = doc_from_store(run_b.store)
        assert final_a.get_text_with_formatting(["text"]) == final_b.get_text_with_formatting(
            ["text"]
        )


class TestFailedTrace:
    def test_failed_trace_replayable(self, tmp_path):
        state = run_fuzz(seed=4, iterations=30)
        path = tmp_path / "failure.json"
        save_failed_trace(
            path, state.store, evidence={"leftText": "x", "rightText": "y"}
        )
        payload = json.loads(path.read_text())
        assert "queues" in payload and payload["leftText"] == "x"

        from peritext_tpu.core.types import Change

        queues = {
            actor: [Change.from_json(c) for c in changes]
            for actor, changes in payload["queues"].items()
        }
        doc = replay_queues(queues)
        assert doc.get_text_with_formatting(["text"]) == doc_from_store(
            state.store
        ).get_text_with_formatting(["text"])


class TestSessionCheckpoint:
    """Event-sourced streaming-session checkpoints: the frame log IS the
    state; restore re-ingests and must reproduce digests/spans exactly."""

    def _session(self, workloads, mix=True):
        from peritext_tpu.parallel.codec import encode_frame
        from peritext_tpu.parallel.streaming import StreamingMerge

        sess = StreamingMerge(
            num_docs=len(workloads), actors=("doc1", "doc2", "doc3"),
            slot_capacity=512, mark_capacity=128,
            round_insert_capacity=128, round_delete_capacity=64,
            round_mark_capacity=64,
        )
        for d, w in enumerate(workloads):
            changes = [ch for log in w.values() for ch in log]
            if mix and d % 2:
                sess.ingest(d, changes)  # object path
            else:
                sess.ingest_frame(d, encode_frame(changes))  # frame path
        sess.drain()
        return sess

    def test_save_restore_roundtrip(self, tmp_path):
        from peritext_tpu.checkpoint import restore_session, save_session
        from peritext_tpu.testing.fuzz import generate_workload

        workloads = generate_workload(seed=61, num_docs=4, ops_per_doc=90)
        sess = self._session(workloads)
        meta = save_session(sess, tmp_path / "ckpt")
        assert meta["frames"] > 0

        restored = restore_session(tmp_path / "ckpt")
        assert restored.digest() == sess.digest()
        assert restored.read_all() == sess.read_all()
        assert restored.frontier() == sess.frontier()

    def test_restore_then_continue_ingesting(self, tmp_path):
        from peritext_tpu.api.batch import _oracle_doc
        from peritext_tpu.checkpoint import restore_session, save_session
        from peritext_tpu.parallel.codec import encode_frame
        from peritext_tpu.testing.fuzz import generate_workload

        workloads = generate_workload(seed=62, num_docs=2, ops_per_doc=120)
        half_workloads = []
        rest = []
        for w in workloads:
            changes = [ch for log in w.values() for ch in log]
            half = len(changes) // 2
            half_workloads.append(changes[:half])
            rest.append(changes[half:])

        from peritext_tpu.parallel.streaming import StreamingMerge

        sess = StreamingMerge(
            num_docs=2, actors=("doc1", "doc2", "doc3"), slot_capacity=512,
            mark_capacity=128, round_insert_capacity=128,
            round_delete_capacity=64, round_mark_capacity=64,
        )
        for d, changes in enumerate(half_workloads):
            sess.ingest_frame(d, encode_frame(changes))
        sess.drain()
        save_session(sess, tmp_path / "mid")

        restored = restore_session(tmp_path / "mid")
        for d, changes in enumerate(rest):
            restored.ingest_frame(d, encode_frame(changes))
        restored.drain()
        for d, w in enumerate(workloads):
            expected = _oracle_doc(w).get_text_with_formatting(["text"])
            assert restored.read(d) == expected, f"doc {d}"

    def test_manager_session_checkpoint(self, tmp_path):
        from peritext_tpu.checkpoint import CheckpointManager
        from peritext_tpu.testing.fuzz import generate_workload

        workloads = generate_workload(seed=63, num_docs=2, ops_per_doc=60)
        sess = self._session(workloads, mix=False)
        mgr = CheckpointManager(tmp_path / "root", keep=2)
        mgr.save(1, session=sess)
        ckpt = mgr.latest()
        restored = ckpt.session()
        assert restored is not None
        assert restored.digest() == sess.digest()

    def test_digest_stable_across_demotion_and_restore(self, tmp_path):
        """A doc demoted AFTER earlier device rounds leaves residue in its
        device row; digest() must mask fallback docs so a session and its
        restored checkpoint agree (the restored session demotes the same doc
        without ever touching the device)."""
        from peritext_tpu.checkpoint import restore_session, save_session
        from peritext_tpu.parallel.codec import encode_frame
        from peritext_tpu.parallel.streaming import StreamingMerge
        from peritext_tpu.testing.generate import generate_docs

        docs, _, initial = generate_docs("seed text", 1)
        (d1,) = docs
        sess = StreamingMerge(
            num_docs=1, actors=("doc1",), slot_capacity=256,
            round_insert_capacity=32,
        )
        sess.ingest_frame(0, encode_frame([initial]))
        sess.drain()  # round applied on device
        big, _ = d1.change(
            [{"path": ["text"], "action": "insert", "index": 1,
              "values": list("y" * 100)}]
        )
        sess.ingest_frame(0, encode_frame([big]))
        sess.drain()  # oversized: demotes, device row keeps residue
        assert sess.docs[0].fallback

        save_session(sess, tmp_path / "demoted")
        restored = restore_session(tmp_path / "demoted")
        assert restored.docs[0].fallback
        assert restored.digest() == sess.digest()
        assert restored.read_all() == sess.read_all()


def test_crash_restore_campaign():
    """Kill + checkpoint-restore + anti-entropy repair reaches the clean
    session's digest and the oracle's spans/roots (fuzz.run_crash_restore;
    the mesh variant restores MESHLESS, exercising digest mesh-invariance)."""
    from peritext_tpu.parallel.mesh import make_mesh
    from peritext_tpu.testing.fuzz import run_crash_restore

    assert run_crash_restore(seed=11, num_docs=6, ops_per_doc=60) > 0
    assert run_crash_restore(seed=12, num_docs=6, ops_per_doc=60, mesh=make_mesh(4)) > 0
