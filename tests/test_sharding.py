"""Multi-device sharding: the doc axis partitioned over an 8-device CPU mesh
(conftest forces XLA_FLAGS=--xla_force_host_platform_device_count=8)."""

import jax
import numpy as np
import pytest

from peritext_tpu.api import DocBatch, oracle_merge
from peritext_tpu.ops.resolve import resolve_jit
from peritext_tpu.parallel.mesh import (
    convergence_digest,
    doc_sharding,
    make_mesh,
    pad_doc_axis,
    shard_docs,
)
from peritext_tpu.testing.fuzz import generate_workload


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) == 8, "conftest should provide 8 virtual devices"
    return make_mesh()


def test_sharded_merge_matches_oracle(mesh):
    workloads = generate_workload(seed=5, num_docs=12, ops_per_doc=40)  # 12 -> pad 16
    batch = DocBatch(
        slot_capacity=128, mark_capacity=64, comment_capacity=16, op_capacity=128,
        mesh=mesh,
    )
    report = batch.merge(workloads)
    assert report.fallback_docs == []
    assert report.spans == oracle_merge(workloads)


def test_state_is_actually_sharded(mesh):
    workloads = generate_workload(seed=5, num_docs=16, ops_per_doc=30)
    batch = DocBatch(
        slot_capacity=128, mark_capacity=64, comment_capacity=16, op_capacity=128,
        mesh=mesh,
    )
    encoded = batch.encode(workloads)
    state = batch.apply_encoded(encoded)
    # each of the 8 devices should hold a (2, ...) shard of the 16-doc batch
    shards = state.elem_id.addressable_shards
    assert len(shards) == 8
    assert all(s.data.shape[0] == 2 for s in shards)


def test_convergence_digest_allreduce(mesh):
    workloads = generate_workload(seed=11, num_docs=8, ops_per_doc=30)
    batch = DocBatch(
        slot_capacity=128, mark_capacity=64, comment_capacity=16, op_capacity=128,
        mesh=mesh,
    )
    encoded = batch.encode(workloads)
    state = batch.apply_encoded(encoded)
    resolved = resolve_jit(state, 16)

    digest_fn = jax.jit(convergence_digest)
    d1 = digest_fn(resolved.char, resolved.visible)
    # replica 2: same changes, different host ordering of the logs
    reordered = [
        {actor: log for actor, log in reversed(list(w.items()))} for w in workloads
    ]
    encoded2 = batch.encode(reordered)
    state2 = batch.apply_encoded(encoded2)
    resolved2 = resolve_jit(state2, 16)
    d2 = digest_fn(resolved2.char, resolved2.visible)
    assert int(d1) == int(d2)

    # and a genuinely different batch digests differently
    other = generate_workload(seed=12, num_docs=8, ops_per_doc=30)
    encoded3 = batch.encode(other)
    state3 = batch.apply_encoded(encoded3)
    resolved3 = resolve_jit(state3, 16)
    d3 = digest_fn(resolved3.char, resolved3.visible)
    assert int(d1) != int(d3)


def test_pad_doc_axis():
    x = np.ones((5, 3), np.int32)
    padded = pad_doc_axis(x, 8)
    assert padded.shape == (8, 3)
    assert padded[5:].sum() == 0
    assert pad_doc_axis(x, 5).shape == (5, 3)


def test_cpu_platform_helper_yields_devices_and_restores():
    """utils.platform.cpu_platform: >= n CPU devices inside, env restored after."""
    import os

    from peritext_tpu.utils.platform import cpu_platform

    before_env = os.environ.get("JAX_PLATFORMS")
    before_flags = os.environ.get("XLA_FLAGS")
    with cpu_platform(8) as devices:
        assert len(devices) >= 8
        assert all(d.platform == "cpu" for d in devices[:8])
        assert os.environ.get("JAX_PLATFORMS") == "cpu"
        # eager arrays inside the block land on a CPU device
        x = jax.numpy.zeros((2,))
        assert next(iter(x.devices())).platform == "cpu"
    assert os.environ.get("JAX_PLATFORMS") == before_env
    assert os.environ.get("XLA_FLAGS") == before_flags


def test_pin_cpu_platform_raises_small_flag_count(monkeypatch):
    """A pre-existing too-small forced count is raised, not silently kept."""
    import os

    from peritext_tpu.utils import platform as plat

    monkeypatch.setenv("XLA_FLAGS", "--xla_force_host_platform_device_count=4")
    # conftest already created the 8-device CPU client, so the flag rewrite
    # cannot change live device count — but the env must reflect the request.
    try:
        plat.pin_cpu_platform(8)
    except RuntimeError:
        pass  # acceptable iff the client predates the flag; env still checked
    assert "device_count=8" in os.environ["XLA_FLAGS"]


def test_digest_and_shards_invariant_across_mesh_sizes():
    """The same workload merged on 1/2/4/8-device meshes must (a) actually
    shard the doc axis across all devices and (b) produce identical
    convergence digests — re-sharding never changes content (the committed
    weak-scaling evidence, scripts/weak_scaling.py, asserts the same)."""
    import numpy as np
    from jax.sharding import Mesh

    from peritext_tpu.parallel.streaming import StreamingMerge
    from peritext_tpu.testing.fuzz import generate_workload

    workloads = generate_workload(seed=77, num_docs=16, ops_per_doc=40)
    devices = jax.devices()
    digests = {}
    for n in (1, 2, 4, 8):
        mesh_n = Mesh(np.asarray(devices[:n]), ("docs",))
        s = StreamingMerge(
            num_docs=16, actors=("doc1", "doc2", "doc3"), mesh=mesh_n,
            slot_capacity=256, mark_capacity=128, tomb_capacity=128,
        )
        for d, w in enumerate(workloads):
            s.ingest(d, [ch for log in w.values() for ch in log])
        s.drain()
        assert len(s.state.elem_id.sharding.device_set) == n
        digests[n] = s.digest()
    assert len(set(digests.values())) == 1, digests


def test_touched_rows_gather_lowered_without_all_gather():
    """The touched-rows digest gather (streaming._gather_rows, mesh path)
    must move K x row-bytes per device, independent of session size D: its
    compiled HLO may all-reduce the (K, ...) gathered shapes (the psum
    merge) but must contain NO all-gather — the SPMD partitioner's lowering
    of a dynamic gather from a doc-sharded operand, which made a 16-doc
    round's digest scale with D (VERDICT r4 task 6; bound in DESIGN.md
    SS10)."""
    import re

    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from peritext_tpu.ops.packed import empty_docs
    from peritext_tpu.parallel.mesh import DOC_AXIS
    from peritext_tpu.parallel.streaming import gather_rows_fn

    devices = jax.devices()
    mesh = Mesh(np.asarray(devices), (DOC_AXIS,))
    D, K = 512, 16  # D >> K so a full-batch collective is unmistakable
    state = empty_docs(D, 128, 32, tomb_capacity=64)
    sharded = jax.device_put(
        tuple(state), NamedSharding(mesh, P(DOC_AXIS)))
    rows_idx = jax.device_put(
        np.arange(K, dtype=np.int32), NamedSharding(mesh, P()))
    txt = gather_rows_fn(mesh).lower(sharded, rows_idx) \
        .compile().as_text()
    assert "all-gather" not in txt, "full-batch all-gather in gather_rows"
    # the psum merges run on gathered (K, ...) shapes; none may carry the
    # session doc axis (D or its 64-per-device shard).  All-reduces may be
    # fused into one tuple-shaped op, so check EVERY element of each op's
    # result type (the text between '=' and 'all-reduce(').
    seen = 0
    for m in re.finditer(r"=\s*([^=]*?)\s*all-reduce\(", txt):
        for dims_txt in re.findall(r"\[([\d,]*)\]", m.group(1)):
            dims = [int(x) for x in dims_txt.split(",") if x]
            seen += 1
            assert not dims or dims[0] <= K, \
                f"all-reduce over doc axis: {m.group(1)}"
    assert seen > 0, "no all-reduce found: the psum merge disappeared?"
