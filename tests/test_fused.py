"""Fused device-resident round pipeline (ISSUE 9): byte-equality of the
fused vs per-round dispatch disciplines across BOTH storage layouts, the
zero-recompile steady-state contract for the fused programs, donation
semantics, the drain-end digest prefetch, and the staging lane itself.

The equivalence oracle is the compat switch ``fused_pipeline=False``: it
restores the pre-fusion per-round dispatch (one compact apply per round,
per-round device_put staging, unpipelined drain) — fusion is the same apply
sequence staged and traced together, so every observable (spans, incremental
patches, full-state digests, round counts) must be indistinguishable."""

import random

import numpy as np
import pytest

import jax

from peritext_tpu.parallel.codec import encode_frame
from peritext_tpu.parallel.staging import FrameStager
from peritext_tpu.parallel.streaming import StreamingMerge
from peritext_tpu.testing.fuzz import generate_workload

ACTORS = ("doc1", "doc2", "doc3")


def _session(layout="padded", static_rounds=False, num_docs=6, fused=True,
             caps=(8, 8, 8, 8)):
    # one shared config across this module ON PURPOSE: the width buckets
    # collapse to a single signature (caps == the bucket floor), so every
    # test reuses the same compiled fused programs — the module stays
    # seconds, not minutes, and the zero-recompile test still proves the
    # steady state (its assertion is on the WARM run only)
    # small resident shapes: per-variant XLA compile time scales with the
    # program (slot window x mark table), and this module's cost is almost
    # entirely first-compiles of the per-seed (K, lens) signatures
    s = StreamingMerge(
        num_docs=num_docs,
        actors=ACTORS,
        slot_capacity=64,
        mark_capacity=48,
        tomb_capacity=48,
        round_insert_capacity=caps[0],
        round_delete_capacity=caps[1],
        round_mark_capacity=caps[2],
        round_map_capacity=caps[3],
        static_rounds=static_rounds,
        layout=layout,
    )
    s.fused_pipeline = fused
    # narrow fuse window: drains split into SEVERAL staged batches (more
    # pipeline coverage per op) while the chained program bodies stay
    # small — the XLA compile bill is per (K, lens) signature and K <= 2
    # keeps the variant set tiny
    s.FUSE_MAX_ROUNDS = 2
    return s


def _feed(s, workloads, rng, chunks=3, per_round_steps=False,
          prefetch=False):
    """Ingest each doc's log as ``chunks`` wire frames with interleaved
    drains — fused sessions drain pipelined, oracle sessions step per
    round."""
    s.prefetch_digest = prefetch
    plans = []
    for w in workloads:
        ch = [c for a in sorted(w) for c in w[a]]
        rng.shuffle(ch)
        size = -(-len(ch) // chunks)
        plans.append([ch[i:i + size] for i in range(0, len(ch), size)])
    for r in range(chunks):
        s.ingest_frames(
            (d, encode_frame(sorted(p[r], key=lambda c: (c.actor, c.seq))))
            for d, p in enumerate(plans) if r < len(p)
        )
        if per_round_steps:
            while s.step() > 0:
                pass
        else:
            s.drain()
    return s


@pytest.mark.parametrize("layout", ["padded", "paged"])
@pytest.mark.parametrize("seed", [
    11,
    # extra fuzz seeds ride the slow tier (each seed's arrival shapes mint
    # their own XLA variants — ~10 s/seed of pure compile); the CI
    # fused-smoke job sweeps two more seeds on every push, and the bench
    # row asserts equality on three per run
    pytest.param(203, marks=pytest.mark.slow),
    pytest.param(47, marks=pytest.mark.slow),
])
def test_fused_equals_per_round_across_layouts(layout, seed):
    """Fuzz-seed byte-equality of the fused pipeline vs the per-round
    dispatch oracle, padded AND paged: digests (full state), spans,
    incremental patch streams, committed round counts."""
    workloads = generate_workload(seed=seed, num_docs=6, ops_per_doc=40)
    fused = _feed(_session(layout), workloads, random.Random(seed),
                  prefetch=True)
    oracle = _feed(_session(layout, fused=False), workloads,
                   random.Random(seed), per_round_steps=True)
    assert fused.rounds == oracle.rounds
    assert fused.digest() == oracle.digest()
    assert fused.read_all() == oracle.read_all()
    assert fused.read_patches_all() == oracle.read_patches_all()
    assert fused.rounds > 1  # low caps force real multi-round fusion


def test_static_rounds_fused_parity():
    """The serving shape discipline rides the fused pipeline through the
    STACKED fixed-width program: byte equality with the per-round static
    path, and the committed apply keeps the session's configured widths
    (the one-shape contract)."""
    workloads = generate_workload(seed=7, num_docs=6, ops_per_doc=40)
    fused = _feed(_session(static_rounds=True, caps=(24, 12, 12, 8)),
                  workloads, random.Random(7))
    oracle = _feed(_session(static_rounds=True, caps=(24, 12, 12, 8),
                            fused=False),
                   workloads, random.Random(7), per_round_steps=True)
    assert fused.digest() == oracle.digest()
    assert fused.read_all() == oracle.read_all()
    assert fused.rounds == oracle.rounds


def test_fused_pipeline_zero_recompiles_on_repeat_workload(recompile_sentinel):
    """The fused pipeline adds ZERO compiles on a repeat workload: a fresh
    session serving the same arrival shapes again dispatches only
    already-compiled fused programs (staged multi-round apply, fused
    resolve+digest prefetch included)."""

    def fresh():
        return _session()

    workloads = generate_workload(seed=31, num_docs=6, ops_per_doc=36)
    cold = _feed(fresh(), workloads, random.Random(3), prefetch=True)
    cold_spans = cold.read_all()
    cold_digest = cold.digest()

    recompile_sentinel.mark()
    warm = _feed(fresh(), workloads, random.Random(3), prefetch=True)
    warm_digest = warm.digest()
    recompile_sentinel.assert_steady_state("fused pipeline repeat workload")
    assert warm.read_all() == cold_spans
    assert warm_digest == cold_digest


def test_prefetch_digest_matches_plain_digest():
    """The drain-end fused resolve+digest pre-dispatch is an overlap
    optimization, not a semantics change: digest() after a prefetching
    drain equals a non-prefetching twin bit-for-bit, including after
    further ingest+drain cycles."""
    workloads = generate_workload(seed=91, num_docs=6, ops_per_doc=32)
    a = _feed(_session(), workloads, random.Random(1), prefetch=True)
    b = _feed(_session(), workloads, random.Random(1), prefetch=False)
    assert a.digest() == b.digest()
    assert a.digest(refresh=True) == b.digest()


def test_drain_end_digest_chains_into_final_staged_batch():
    """Round-14 rung: with the prefetch armed, a multi-round drain's FINAL
    staged batch carries the resolve+digest in ITS OWN program — no
    separate prefetch dispatch.  Pinned three ways: the chained counter
    moves, the per-round block cache is already seeded when drain()
    returns (so digest() is a pure cache hit), and the digest stays
    byte-equal to the unchained per-round oracle."""
    from peritext_tpu.obs import GLOBAL_COUNTERS

    workloads = generate_workload(seed=55, num_docs=6, ops_per_doc=36)
    before = GLOBAL_COUNTERS.get("streaming.digest_chained")
    fused = _feed(_session(), workloads, random.Random(9), prefetch=True)
    assert GLOBAL_COUNTERS.get("streaming.digest_chained") > before
    # the final batch's dispatch seeded the resolution cache at the
    # current round stamp: the block program need not run again
    stamp, cache = fused._resolved_cache
    assert stamp == fused.rounds and 0 in cache
    entry = cache[0]
    digest = fused.digest()
    # digest() consumed the SEEDED entry (same object — no re-dispatch)
    assert fused._resolved_cache[1][0] is entry
    oracle = _feed(_session(fused=False), workloads, random.Random(9),
                   per_round_steps=True)
    assert digest == oracle.digest()
    assert fused.read_all() == oracle.read_all()


def test_drain_end_digest_chains_on_stacked_serving_form():
    """The static-rounds serving discipline chains too (the stacked
    fixed-width program grows a digest tail), with the same byte
    equality."""
    from peritext_tpu.obs import GLOBAL_COUNTERS

    workloads = generate_workload(seed=23, num_docs=6, ops_per_doc=36)
    before = GLOBAL_COUNTERS.get("streaming.digest_chained")
    fused = _feed(_session(static_rounds=True, caps=(24, 12, 12, 8)),
                  workloads, random.Random(4), prefetch=True)
    assert GLOBAL_COUNTERS.get("streaming.digest_chained") > before
    oracle = _feed(_session(static_rounds=True, caps=(24, 12, 12, 8),
                            fused=False),
                   workloads, random.Random(4), per_round_steps=True)
    assert fused.digest() == oracle.digest()
    assert fused.read_all() == oracle.read_all()


def test_staged_rounds_donation_consumes_input_state():
    """Donation semantics of the fused apply program: with donate=True the
    input state buffer is consumed (further reads raise), and the result is
    bit-identical to the undonated twin."""
    from peritext_tpu.ops.encode import MAP_STREAM_COLS, MARK_COLS
    from peritext_tpu.ops.kernel import apply_batch_staged_rounds_jit
    from peritext_tpu.ops.packed import empty_docs

    d = 4
    counts_all = np.zeros((1, 4, d), np.int32)
    counts_all[0, 0] = 2
    ins = [np.zeros(8, np.int32) for _ in range(3)]
    # two head inserts per doc: ref=0, ascending op ids, char payloads
    ops = np.arange(1, 2 * d + 1, dtype=np.int32)
    ins[1][: 2 * d] = ops
    ins[2][: 2 * d] = 65 + (ops % 26)
    dev = jax.device_put((
        counts_all, tuple(ins), np.zeros(8, np.int32),
        {c: np.zeros(8, np.int32) for c in MARK_COLS},
        {c: np.zeros(8, np.int32) for c in MAP_STREAM_COLS},
    ))
    statics = dict(widths_seq=((8, 8, 8, 8),), loop_slots_seq=(8,),
                   ins_lens=(8,), del_lens=(8,), mark_lens=(8,),
                   map_lens=(8,))

    plain_in = jax.device_put(empty_docs(d, 16, 8, tomb_capacity=8))
    plain = apply_batch_staged_rounds_jit(plain_in, *dev, donate=False,
                                          **statics)
    donated_in = jax.device_put(empty_docs(d, 16, 8, tomb_capacity=8))
    donated = apply_batch_staged_rounds_jit(donated_in, *dev, donate=True,
                                            **statics)
    for a, b in zip(plain, donated):
        assert (np.asarray(a) == np.asarray(b)).all()
    with pytest.raises(RuntimeError):
        np.asarray(donated_in.elem_id)  # the donated buffer is dead


def test_cpu_resolves_to_undonated_dispatch():
    """On a CPU backend the fused programs must NOT donate: a donated
    dispatch blocks on the donated input's pending producer there,
    serializing the host/device overlap the pipeline exists for."""
    from peritext_tpu.ops.kernel import resolve_state_donation

    assert resolve_state_donation(platform="cpu") is False
    assert resolve_state_donation(platform="tpu") is True


# ---------------------------------------------------------------------------
# the staging lane itself
# ---------------------------------------------------------------------------


class TestFrameStager:
    def test_fifo_results(self):
        st = FrameStager()
        try:
            handles = [st.submit(lambda i=i: i * i) for i in range(8)]
            assert [h.wait() for h in handles] == [i * i for i in range(8)]
            assert st.stats()["staged"] == 8
        finally:
            st.close()

    def test_error_propagates_to_waiter(self):
        st = FrameStager()
        try:
            def boom():
                raise ValueError("staging failed")

            ok = st.submit(lambda: 1)
            bad = st.submit(boom)
            after = st.submit(lambda: 2)
            assert ok.wait() == 1
            with pytest.raises(ValueError, match="staging failed"):
                bad.wait()
            # one failed job must not kill the lane
            assert after.wait() == 2
            assert st.stats()["errors"] == 1
        finally:
            st.close()

    def test_close_is_idempotent_and_rejects_new_jobs(self):
        st = FrameStager()
        h = st.submit(lambda: 42)
        assert h.wait() == 42
        st.close()
        st.close()
        with pytest.raises(RuntimeError):
            st.submit(lambda: 0)

    def test_depth_validation(self):
        with pytest.raises(ValueError):
            FrameStager(depth=0)

    def test_session_respawns_closed_stager(self):
        s = _session(num_docs=2)
        lane = s._ensure_stager()
        lane.close()
        assert s._ensure_stager() is not lane

    def test_idle_retired_worker_respawns_on_submit(self, monkeypatch):
        # the worker self-reaps after IDLE_TIMEOUT_SECONDS; a later submit
        # must respawn it and resolve — submit publishes the job BEFORE
        # the worker check, so the retire/submit race can never strand a
        # staged job on a worker-less lane
        import time

        from peritext_tpu.parallel import staging

        monkeypatch.setattr(staging, "IDLE_TIMEOUT_SECONDS", 0.05)
        lane = FrameStager()
        assert lane.submit(lambda: 1).wait() == 1
        deadline = time.time() + 5.0
        while lane._thread is not None and time.time() < deadline:
            time.sleep(0.01)
        assert lane._thread is None  # worker retired while idle
        assert lane.submit(lambda: 2).wait() == 2
        assert lane.stats()["staged"] == 2


class TestDrainDeadlineScaling:
    """The guarded fused drain's watchdog budget scales with the backlog:
    deadline_ceiling per staged batch, batches estimated from the deepest
    per-doc pending queue — a deep healthy drain is not a hung device."""

    def _frames(self, seed=31, num_docs=4, ops_per_doc=24):
        workloads = generate_workload(seed=seed, num_docs=num_docs,
                                      ops_per_doc=ops_per_doc)
        out = []
        for d, w in enumerate(workloads):
            ch = sorted((c for a in sorted(w) for c in w[a]),
                        key=lambda c: (c.actor, c.seq))
            out.append((d, encode_frame(ch)))
        return out

    def test_pending_rounds_estimate_tracks_deepest_queue(self):
        s = _session(num_docs=4)
        assert s.pending_rounds_estimate() == 0
        s.ingest_frames(self._frames())
        assert s.pending_rounds_estimate() >= 1
        s.drain()
        assert s.pending_rounds_estimate() == 0

    def test_guarded_drain_budget_scales_with_backlog(self, tmp_path):
        from peritext_tpu.parallel.supervisor import GuardedSession

        guarded = GuardedSession(lambda: _session(num_docs=4), tmp_path,
                                 deadline=30.0)
        # empty backlog: exactly one ceiling
        assert guarded._drain_deadline(1000) == guarded.deadline_ceiling
        for d, frame in self._frames():
            guarded.ingest_frame(d, frame)
        est = guarded.session.pending_rounds_estimate()
        assert est > guarded.session.FUSE_MAX_ROUNDS  # deep enough to scale
        batches = -(-min(est, 1000) // guarded.session.FUSE_MAX_ROUNDS)
        assert guarded._drain_deadline(1000) == pytest.approx(
            guarded.deadline_ceiling * batches)
        # max_rounds clamps the budget back to one batch
        assert guarded._drain_deadline(1) == guarded.deadline_ceiling
        assert guarded.drain() > 0  # and the scaled drain commits cleanly
