"""Surface-mount audit (PR 20): every MetricsServer JSON endpoint has an
``obs status`` roll-up row and a ``prometheus_text`` gauge family — a new
endpoint that forgets either breaks set equality here, not in production.
Plus the error-body contract: a raising plane snapshot answers a TYPED
500 JSON body naming the plane, never a stack-trace HTML page or a dead
serving thread."""

import json
import urllib.error
import urllib.request
from pathlib import Path

from peritext_tpu.obs import (
    ConvergenceMonitor,
    DeviceProfiler,
    IncidentMonitor,
    MetricsServer,
    TimeSeriesPlane,
    prometheus_text,
)
from peritext_tpu.obs.__main__ import _STATUS_PLANES, main as obs_main
from peritext_tpu.obs.latency import LatencyPlane

SNAPSHOT = Path(__file__).resolve().parents[1] / "perf" / "plan_devprof.json"

#: the /metrics family each JSON endpoint's plane must emit.  ``trace``
#: is the one documented exemption: spans export as Chrome trace JSON
#: (``/trace.json`` -> Perfetto), not as Prometheus gauges, and
#: ``prometheus_text`` takes no tracer; ``health`` is the roll-up body
#: itself, pinned via the always-present build-info gauge.
PROMETHEUS_NEEDLES = {
    "health": "peritext_build_info{",
    "convergence": "peritext_convergence_",
    "devprof": "peritext_device_",
    "serve": "peritext_serve_",
    "fleet": "peritext_fleet_",
    "plan": "peritext_plan_",
    "latency": "peritext_latency_",
    "incidents": "peritext_incident_",
    "timeseries": "peritext_history_",
}


def _all_planes_server(**overrides):
    """A MetricsServer with EVERY optional plane mounted.  Placeholder
    objects suffice for route-mount auditing: routes mount on presence
    and snapshot lazily."""
    kwargs = dict(
        tracer=object(), convergence=object(), devprof=object(),
        serve=object(), fleet=object(), plan={}, latency=object(),
        incidents=object(), history=object(),
    )
    kwargs.update(overrides)
    return MetricsServer(**kwargs)


class TestSurfaceMountAudit:
    def test_every_json_endpoint_has_a_status_row(self):
        """The golden set equality: ``{route stems}`` == ``{obs status
        planes}``.  Mounting a new /<plane>.json without teaching the
        roll-up about it (or vice versa) fails HERE."""
        server = _all_planes_server()
        try:
            routes = server._httpd._routes
            assert "/metrics" in routes  # the non-JSON exposition
            stems = {path[1:-len(".json")] for path in routes
                     if path.endswith(".json")}
        finally:
            server.stop()
        status_stems = {name for name, _ in _STATUS_PLANES}
        assert stems == status_stems
        assert "timeseries" in stems  # the PR 20 endpoint rides the audit

    def test_every_json_endpoint_has_a_prometheus_family(self):
        """Live-plane audit: prometheus_text fed one real plane per
        endpoint emits that endpoint's gauge family."""
        history = TimeSeriesPlane(min_frames=4).enable()
        history.sample(serve={"shed": 1.0})
        text = prometheus_text(
            convergence=ConvergenceMonitor(host="audit"),
            devprof=DeviceProfiler(),
            serve=_SnapStub(_SERVE_SNAP),
            fleet=_SnapStub(_FLEET_SNAP),
            plan=_plan_doc(),
            latency=LatencyPlane(),
            incidents=IncidentMonitor(host="audit"),
            history=history,
        )
        exempt = {"trace"}
        audited = {name for name, _ in _STATUS_PLANES} - exempt
        assert audited == set(PROMETHEUS_NEEDLES)
        for plane, needle in sorted(PROMETHEUS_NEEDLES.items()):
            assert needle in text, f"{plane}: no {needle} family emitted"


def _plan_doc():
    from peritext_tpu.plan import propose

    return propose(json.loads(Path(SNAPSHOT).read_text())).to_json()


class _SnapStub:
    """A snapshot-shaped stand-in for the heavyweight serve/fleet planes:
    the exporter contract is 'reads the snapshot dict', so the audit pins
    the SNAPSHOT SCHEMA the real planes already golden-test elsewhere
    (test_serve.py / test_fleet.py)."""

    def __init__(self, body):
        self._body = body

    def snapshot(self):
        return json.loads(json.dumps(self._body))


_SERVE_SNAP = {
    "host": "audit", "sessions": 1, "docs": 1, "doc_capacity": 4,
    "degraded_docs": 0, "rounds": 3, "applied_frames": 3,
    "buffered_frames": 0, "overloaded": False,
    "queue": {"depth": 0, "peak": 2, "max_depth": 64, "backpressure": False,
              "verdicts": {"submitted": 3, "admitted": 3, "delayed": 0,
                           "shed": 0, "shed_reasons": {}}},
    "window": {"seconds": 0.01, "p99_round_seconds": 0.001,
               "floor": 0.005, "ceiling": 0.1},
}

_FLEET_SNAP = {
    "rounds": 2, "hosts": {}, "leases": {"leases": {}},
    "router": {"docs": 0}, "serving": {}, "moving": {}, "failed_docs": [],
    "failovers": 0, "failover_docs": 0, "migrations": 0,
    "migration_rollbacks": 0, "checkpoint_ships": 0, "journal_frames": 0,
    "checkpoint_docs": 0,
    "verdicts": {"submitted": 0, "admitted": 0, "delayed": 0, "shed": 0,
                 "shed_reasons": {}},
    "auth": {"keys": 0, "rejected": 0},
}


class TestStatusRollupLive:
    def test_status_rolls_up_every_mounted_plane(self, capsys):
        history = TimeSeriesPlane(min_frames=4).enable()
        for i in range(6):
            history.sample(serve={"admitted": float(i)})
        server = MetricsServer(
            convergence=ConvergenceMonitor(host="roll"),
            devprof=DeviceProfiler(),
            incidents=IncidentMonitor(host="roll"),
            latency=LatencyPlane(),
            plan=_plan_doc(),
            history=history,
        )
        host, port = server.start()
        try:
            code = obs_main(["status", f"http://{host}:{port}", "--json"])
            body = json.loads(capsys.readouterr().out)
        finally:
            server.stop()
        rows = {row["plane"]: row for row in body["planes"]}
        assert {"health", "convergence", "devprof", "incidents", "latency",
                "plan", "timeseries"} <= set(rows)
        assert rows["timeseries"]["status"] == "ok"
        assert code == body["exit"]


class _Boom:
    """A plane whose snapshot raises — the exporter must answer a typed
    500, not die."""

    def __init__(self, msg):
        self._msg = msg

    def snapshot(self):
        raise RuntimeError(self._msg)

    def chrome_trace(self):
        raise RuntimeError(self._msg)


class TestTypedErrorBodies:
    def test_raising_planes_answer_typed_500_json(self):
        """Satellite pin (>=2 planes): the body is ``{"error", "plane"}``
        with the plane stem, and the server stays alive to answer the
        next request."""
        history = TimeSeriesPlane(min_frames=4).enable()
        history.sample(serve={"ok": 1.0})
        server = MetricsServer(
            convergence=_Boom("lag ledger corrupt"),
            incidents=_Boom("monitor detached"),
            history=history,
        )
        host, port = server.start()
        base = f"http://{host}:{port}"
        try:
            for stem, msg in (("convergence", "lag ledger corrupt"),
                              ("incidents", "monitor detached")):
                try:
                    urllib.request.urlopen(f"{base}/{stem}.json", timeout=5)
                    raise AssertionError(f"/{stem}.json did not 500")
                except urllib.error.HTTPError as exc:
                    assert exc.code == 500
                    body = json.loads(exc.read())
                    assert body["plane"] == stem
                    assert msg in body["error"]
            # the serving thread survived both faults
            healthy = json.loads(urllib.request.urlopen(
                f"{base}/timeseries.json", timeout=5).read())
            assert healthy["rounds"] == history.rounds
        finally:
            server.stop()

    def test_raising_history_plane_names_timeseries(self):
        server = MetricsServer(history=_Boom("ring poisoned"))
        host, port = server.start()
        try:
            try:
                urllib.request.urlopen(
                    f"http://{host}:{port}/timeseries.json?key=x", timeout=5)
                raise AssertionError("/timeseries.json did not 500")
            except urllib.error.HTTPError as exc:
                assert exc.code == 500
                body = json.loads(exc.read())
                assert body == {"error": "ring poisoned",
                                "plane": "timeseries"}
        finally:
            server.stop()
