"""Differential tests for the Pallas insert kernel (ops/pallas_insert.py).

The Pallas path must be bit-identical to the lax path (kernel._insert_loop),
which is itself differentially tested against the scalar oracle.  These run
the kernel in interpreter mode on CPU; the same comparison runs compiled on
real TPU hardware in the bench/driver environment.
"""

import jax
import numpy as np
import pytest

from peritext_tpu.ops.kernel import (
    _insert_loop,
    apply_batch,
    apply_batch_jit,
    encoded_arrays_of,
)
from peritext_tpu.ops.packed import empty_docs
from peritext_tpu.ops.pallas_insert import insert_batch_pallas
from peritext_tpu.testing.synth import synth_streams


def _insert_args(docs, slots, inserts, seed, tomb=8):
    state = empty_docs(docs, slots, 32, tomb_capacity=tomb)
    streams = synth_streams(
        docs, inserts_per_doc=inserts, deletes_per_doc=0, marks_per_doc=0, seed=seed
    )
    return state, streams[:3]


def _assert_same(lax_out, pallas_out):
    for a, b, name in zip(lax_out, pallas_out, ["elem", "char", "n", "ov"]):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b), err_msg=name)


@pytest.mark.parametrize("docs,slots,inserts", [(4, 32, 12), (8, 64, 40)])
def test_pallas_insert_matches_lax(docs, slots, inserts):
    state, (ins_ref, ins_op, ins_char) = _insert_args(docs, slots, inserts, seed=3)
    args = (state.elem_id, state.char, state.num_slots, state.overflow,
            ins_ref, ins_op, ins_char)
    _assert_same(
        jax.vmap(_insert_loop)(*args),
        insert_batch_pallas(*args, interpret=True),
    )


def test_pallas_insert_loop_slots_window():
    # With empty docs the loop window can shrink to the stream width and the
    # untouched tail must be preserved verbatim.
    state, (ins_ref, ins_op, ins_char) = _insert_args(8, 96, 24, seed=5)
    args = (state.elem_id, state.char, state.num_slots, state.overflow,
            ins_ref, ins_op, ins_char)
    _assert_same(
        jax.vmap(_insert_loop)(*args),
        insert_batch_pallas(*args, interpret=True, loop_slots=24),
    )


def test_pallas_insert_carried_state():
    # Second round applied on top of a populated doc: exercises n0 > 0.
    state, (r1, o1, c1) = _insert_args(8, 96, 20, seed=7)
    elem, char, n, ov = jax.vmap(_insert_loop)(
        state.elem_id, state.char, state.num_slots, state.overflow, r1, o1, c1
    )
    streams2 = synth_streams(
        8, inserts_per_doc=16, deletes_per_doc=0, marks_per_doc=0, seed=11,
        ctr_offset=20,
    )
    args = (elem, char, n, ov, *streams2[:3])
    _assert_same(
        jax.vmap(_insert_loop)(*args),
        insert_batch_pallas(*args, interpret=True, loop_slots=40),
    )


def test_pallas_insert_overflow_flag():
    # Capacity exhaustion must set overflow, identically to the lax path.
    state, (ins_ref, ins_op, ins_char) = _insert_args(4, 8, 16, seed=9)
    args = (state.elem_id, state.char, state.num_slots, state.overflow,
            ins_ref, ins_op, ins_char)
    lax_out = jax.vmap(_insert_loop)(*args)
    pallas_out = insert_batch_pallas(*args, interpret=True)
    _assert_same(lax_out, pallas_out)
    assert np.asarray(lax_out[3]).any()


def test_apply_batch_pallas_interpret_end_to_end():
    # Full three-phase apply through the pallas insert phase.
    docs, slots = 8, 64
    state = empty_docs(docs, slots, 32, tomb_capacity=16)
    streams = synth_streams(
        docs, inserts_per_doc=24, deletes_per_doc=8, marks_per_doc=8, seed=1
    )
    ref = apply_batch(state, streams, insert_impl="lax")
    out = apply_batch_jit(state, streams, insert_impl="pallas_interpret")
    for a, b, name in zip(ref, out, ref._fields):
        if isinstance(a, dict):
            continue
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b), err_msg=name)


def test_apply_batch_rejects_unknown_impl():
    docs, slots = 4, 32
    state = empty_docs(docs, slots, 16, tomb_capacity=8)
    streams = synth_streams(
        docs, inserts_per_doc=4, deletes_per_doc=0, marks_per_doc=0, seed=2
    )
    with pytest.raises(ValueError):
        apply_batch(state, streams, insert_impl="cuda")


def test_pallas_chunked_stream_matches_lax(monkeypatch):
    """Force the stream-chunked kernel (the long-doc VMEM path) by shrinking
    the VMEM budget so the op stream spans several chunks."""
    from peritext_tpu.ops import pallas_insert

    docs, slots, inserts = 8, 96, 80
    state, (ins_ref, ins_op, ins_char) = _insert_args(docs, slots, inserts, seed=9)
    args = (state.elem_id, state.char, state.num_slots, state.overflow,
            ins_ref, ins_op, ins_char)
    lax_out = jax.vmap(_insert_loop)(*args)

    budget = pallas_insert._state_bytes(slots) + pallas_insert._stream_bytes(24)
    monkeypatch.setattr(pallas_insert, "_VMEM_BUDGET", budget)
    assert pallas_insert._stream_chunk(slots, inserts) < inserts  # really chunked
    # cache-bust: jit would replay the old trace for identical arg shapes
    pallas_out = pallas_insert.insert_batch_pallas.__wrapped__(
        *args, interpret=True, loop_slots=None
    )
    _assert_same(lax_out, pallas_out)


def test_vmem_guard_routes_oversized_shapes_to_lax():
    from peritext_tpu.ops.kernel import resolve_insert_impl
    from peritext_tpu.ops.pallas_insert import pallas_vmem_ok

    assert pallas_vmem_ok(384)                # the bench config
    assert pallas_vmem_ok(6144)               # BASELINE config-4 long docs
    assert not pallas_vmem_ok(32768)          # state alone exceeds VMEM
    # apply_batch falls back to lax for such shapes (no pallas lowering)
    docs, slots = 4, 32768
    state = empty_docs(docs, slots, 16, tomb_capacity=8)
    streams = synth_streams(
        docs, inserts_per_doc=8, deletes_per_doc=0, marks_per_doc=0, seed=4
    )
    out = apply_batch(state, streams, insert_impl="pallas")  # guard: lax used
    ref = apply_batch(state, streams, insert_impl="lax")
    for a, b in zip(out, ref):
        if not isinstance(a, dict):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_zero_width_insert_stream_is_noop():
    import jax.numpy as jnp

    state = empty_docs(4, 32, 16, tomb_capacity=8)
    z = jnp.zeros((4, 0), jnp.int32)
    elem, char, n, ov = insert_batch_pallas(
        state.elem_id, state.char, state.num_slots, state.overflow, z, z, z,
        interpret=True,
    )
    np.testing.assert_array_equal(np.asarray(elem), np.asarray(state.elem_id))
    np.testing.assert_array_equal(np.asarray(n), np.asarray(state.num_slots))
