"""Frame-native ingest: wire bytes -> C++ parse -> vectorized schedule/split.

Differential against both the object ingest path and the scalar oracle.
"""

import numpy as np
import pytest

from peritext_tpu import native
from peritext_tpu.api.batch import _oracle_doc
from peritext_tpu.parallel.codec import encode_frame
from peritext_tpu.parallel.streaming import StreamingMerge
from peritext_tpu.testing.fuzz import generate_workload
from peritext_tpu.testing.generate import generate_docs

ACTORS = ("doc1", "doc2", "doc3")


def _session(num_docs=4, **kw):
    defaults = dict(
        num_docs=num_docs,
        actors=ACTORS,
        slot_capacity=512,
        mark_capacity=128,
        tomb_capacity=256,
        round_insert_capacity=128,
        round_delete_capacity=64,
        round_mark_capacity=64,
    )
    defaults.update(kw)
    return StreamingMerge(**defaults)


def _changes_of(workload):
    return [ch for log in workload.values() for ch in log]


def _oracle_spans(workload):
    return _oracle_doc(workload).get_text_with_formatting(["text"])


@pytest.fixture(scope="module")
def workloads():
    return generate_workload(seed=55, num_docs=4, ops_per_doc=120)


def test_native_parse_available():
    assert native.available(), "native core should build in this image"


def test_frame_ingest_matches_object_ingest_and_oracle(workloads):
    frames_sess = _session()
    object_sess = _session()
    for d, w in enumerate(workloads):
        frames_sess.ingest_frame(d, encode_frame(_changes_of(w)))
        object_sess.ingest(d, _changes_of(w))
    frames_sess.drain()
    object_sess.drain()
    assert not any(s.fallback for s in frames_sess.docs)
    assert frames_sess.digest() == object_sess.digest()
    fr = frames_sess.read_all()
    ob = object_sess.read_all()
    for d, w in enumerate(workloads):
        assert fr[d] == ob[d] == _oracle_spans(w), f"doc {d}"


def test_frame_ingest_multi_round_shuffled_duplicated(workloads):
    import random

    rng = random.Random(7)
    sess = _session()
    # deliver each doc's changes as several shuffled frames, with one frame
    # duplicated — per-actor suffix contiguity is not required by ingest
    for d, w in enumerate(workloads):
        changes = _changes_of(w)
        rng.shuffle(changes)
        chunks = [changes[i : i + 7] for i in range(0, len(changes), 7)]
        frames = [encode_frame(c) for c in chunks]
        frames.append(frames[0])  # duplicate delivery
        for f in frames:
            sess.ingest_frame(d, f)
            sess.step()
    sess.drain()
    assert not any(s.fallback for s in sess.docs)
    out = sess.read_all()
    for d, w in enumerate(workloads):
        assert out[d] == _oracle_spans(w), f"doc {d}"
    assert sess.pending_count() == 0


def test_mixed_object_then_frame_ingest(workloads):
    w = workloads[0]
    changes = _changes_of(w)
    half = len(changes) // 2
    sess = _session(num_docs=1)
    sess.ingest(0, changes[:half])  # doc becomes object-bound
    sess.ingest_frame(0, encode_frame(changes[half:]))  # routed to object path
    sess.drain()
    assert sess.read(0) == _oracle_spans(w)


def test_mixed_frame_then_object_ingest(workloads):
    w = workloads[1]
    changes = _changes_of(w)
    half = len(changes) // 2
    sess = _session(num_docs=1)
    sess.ingest_frame(0, encode_frame(changes[:half]))
    sess.ingest(0, changes[half:])  # converted to a frame internally
    sess.drain()
    assert sess.docs[0].frame_mode
    assert sess.read(0) == _oracle_spans(w)


def test_map_ops_stay_on_frame_fast_path():
    """makeMap / map set / del ride the wire fast path into the device map
    registers (no demotion); the materialized root equals the oracle's."""
    docs, _, initial = generate_docs("hello", 2)
    d1, _ = docs
    c, _ = d1.change([
        {"path": [], "action": "makeMap", "key": "comments"},
        {"path": ["comments"], "action": "set", "key": "note", "value": "hi"},
    ])
    sess = _session(num_docs=1)
    sess.ingest_frame(0, encode_frame([initial, c]))
    sess.drain()
    assert not sess.docs[0].fallback and sess.docs[0].frame_mode
    w = {"doc1": [initial, c]}
    assert sess.read(0) == _oracle_spans(w)
    assert sess.read_root(0) == _oracle_doc(w).root


def test_inexpressible_map_value_demotes_to_oracle_replay():
    docs, _, initial = generate_docs("hello", 2)
    d1, _ = docs
    c, _ = d1.change(
        [{"path": [], "action": "set", "key": "ratio", "value": 0.5}]
    )
    sess = _session(num_docs=1)
    sess.ingest_frame(0, encode_frame([initial, c]))
    sess.drain()
    assert sess.docs[0].fallback
    w = {"doc1": [initial, c]}
    assert sess.read(0) == _oracle_spans(w)
    assert sess.read_root(0) == _oracle_doc(w).root


def test_undeclared_actor_demotes_not_crashes(workloads):
    w = workloads[2]
    sess = _session(num_docs=1, actors=("doc1", "doc2"))  # doc3 undeclared
    sess.ingest_frame(0, encode_frame(_changes_of(w)))
    sess.drain()
    assert sess.docs[0].fallback
    assert sess.read(0) == _oracle_spans(w)


def test_oversized_change_demotes_not_wedges():
    docs, _, initial = generate_docs("x", 1)
    (d1,) = docs
    big, _ = d1.change(
        [{"path": ["text"], "action": "insert", "index": 1, "values": list("y" * 200)}]
    )
    sess = _session(num_docs=1, round_insert_capacity=64)
    sess.ingest_frame(0, encode_frame([initial, big]))
    rounds = sess.drain()
    assert rounds < 10  # never wedges
    w = {"doc1": [initial, big]}
    assert sess.read(0) == _oracle_spans(w)


def test_corrupt_frame_raises_and_queues_nothing(workloads):
    sess = _session(num_docs=1)
    good = encode_frame(_changes_of(workloads[0]))
    with pytest.raises(ValueError):
        sess.ingest_frame(0, good[:-3])  # truncated
    assert sess.pending_count() == 0


def test_frame_ingest_without_native_uses_object_path(monkeypatch, workloads):
    monkeypatch.setattr(native, "available", lambda: False)
    sess = _session(num_docs=1)
    sess.ingest_frame(0, encode_frame(_changes_of(workloads[3])))
    assert not sess.docs[0].frame_mode  # took the object path
    sess.drain()
    assert sess.read(0) == _oracle_spans(workloads[3])


def test_frontier_and_digest_frame_mode(workloads):
    sess = _session()
    for d, w in enumerate(workloads):
        sess.ingest_frame(d, encode_frame(_changes_of(w)))
    sess.drain()
    frontier = sess.frontier()
    expect = {}
    for w in workloads:
        for actor, log in w.items():
            if log:
                expect[actor] = max(expect.get(actor, 0), max(c.seq for c in log))
    assert frontier == expect


def test_marks_and_comments_through_frames():
    docs, _, initial = generate_docs("hello world", 2)
    d1, d2 = docs
    c1, _ = d1.change(
        [{"path": ["text"], "action": "addMark", "startIndex": 0, "endIndex": 5,
          "markType": "strong"}]
    )
    c2, _ = d2.change(
        [{"path": ["text"], "action": "addMark", "startIndex": 3, "endIndex": 9,
          "markType": "comment", "attrs": {"id": "abc-1"}},
         {"path": ["text"], "action": "addMark", "startIndex": 2, "endIndex": 7,
          "markType": "link", "attrs": {"url": "https://x.test"}}]
    )
    w = {"doc1": [initial, c1], "doc2": [c2]}
    sess = _session(num_docs=1)
    sess.ingest_frame(0, encode_frame(_changes_of(w)))
    sess.drain()
    assert not sess.docs[0].fallback
    assert sess.read(0) == _oracle_spans(w)


def test_python_schedule_fallback_matches(monkeypatch, workloads):
    """Frames parsed with the native core, but the round scheduled by the
    pure-python twins (_step_frame_docs_python + _py_schedule_order)."""
    sess = _session()
    for d, w in enumerate(workloads):
        sess.ingest_frame(d, encode_frame(_changes_of(w)))
    monkeypatch.setattr(native, "available", lambda: False)
    # causal_schedule_indices loads the library directly; force the pure-
    # python scheduler too so _py_schedule_order is actually exercised
    monkeypatch.setattr(native, "causal_schedule_indices", lambda *a, **k: None)
    sess.drain()
    assert not any(s.fallback for s in sess.docs)
    out = sess.read_all()
    for d, w in enumerate(workloads):
        assert out[d] == _oracle_spans(w), f"doc {d}"


def test_makelist_frame_redelivery_stays_fast_path(workloads):
    """Duplicate delivery of the frame holding the doc's makeList is a
    routine anti-entropy event and must not demote the doc."""
    w = workloads[0]
    frame = encode_frame(_changes_of(w))
    sess = _session(num_docs=1)
    sess.ingest_frame(0, frame)
    sess.step()
    sess.ingest_frame(0, frame)  # full retransmission
    sess.drain()
    assert sess.docs[0].frame_mode and not sess.docs[0].fallback
    assert sess.read(0) == _oracle_spans(w)


def test_wrong_shape_spillover_json_raises_valueerror():
    """A frame whose JSON-spillover string is valid JSON of the wrong shape
    must raise the documented ValueError, matching decode_frame's contract."""
    from peritext_tpu.core.types import Change, Operation
    from peritext_tpu.core.opids import ROOT

    bogus = Change(
        actor="doc1", seq=1, deps={}, start_op=1,
        # a float value spills to JSON (makeMap no longer does)
        ops=[Operation(action="set", obj=ROOT, opid=(1, "doc1"), key="m",
                       value=0.5)],
    )
    frame = bytearray(encode_frame([bogus]))
    # corrupt the spillover string table entry into valid-but-wrong JSON: we
    # can't easily patch bytes, so instead simulate via a frame whose op JSON
    # round-trips to a dict missing required fields
    import json as jsonlib

    from peritext_tpu.ops.frames import parse_frame
    from peritext_tpu.utils.interning import Interner, OrderedActorTable

    good = jsonlib.dumps(bogus.ops[0].to_json()).encode()
    # same-length substitution keeps the string-table length prefix valid
    # (trailing spaces are legal JSON whitespace)
    raw = b"[1,2,3]" + b" " * (len(good) - 7)
    patched = bytes(frame).replace(good, raw)
    if patched == bytes(frame):  # string table stores the op JSON verbatim
        pytest.skip("frame layout changed; spillover not found")
    with pytest.raises(ValueError):
        parse_frame(
            patched, OrderedActorTable(["doc1"]), Interner(), 0, Interner()
        )


def test_out_of_range_codepoint_rejected_at_ingest(workloads):
    """A frame whose insert codepoint exceeds chr() range must raise
    ValueError at the door, not poison device state (object path parity)."""
    import struct

    from peritext_tpu.ops.frames import parse_frame
    from peritext_tpu.parallel.codec import _CHAR_BIAS, _py_varint_encode
    from peritext_tpu.utils.interning import Interner, OrderedActorTable

    docs, _, initial = generate_docs("a", 1)
    frame = bytearray(encode_frame([initial]))
    # the single insert 'a' is the frame's LAST varint (wire v2 stores the
    # biased codepoint); swap in the biased encoding of a codepoint beyond
    # chr() range and fix the header payload length
    old = _py_varint_encode([ord("a") - _CHAR_BIAS])
    new = _py_varint_encode([0x200000 - _CHAR_BIAS])
    assert bytes(frame[-len(old):]) == old, "frame layout changed"
    patched = bytes(frame[: -len(old)]) + new
    hdr = struct.Struct("<4sBIIQQ")
    magic, ver, nc, ns, ni, pl = hdr.unpack_from(patched)
    patched = hdr.pack(magic, ver, nc, ns, ni, pl + len(new) - len(old)) + patched[hdr.size:]
    with pytest.raises(ValueError, match="codepoint"):
        parse_frame(patched, OrderedActorTable(["doc1"]), Interner(), 0, Interner())


# -- bulk-ingest edge cases (parse_frames_bulk contracts) -------------------


def _craft_frame(strings, ints, n_changes):
    """Hand-build a v1 wire frame (shared framing lives in tests/wire.py)."""
    from wire import craft_frame

    return craft_frame(strings, ints, n_changes, version=1)


@pytest.mark.skipif(not native.available(), reason="needs native core")
def test_bulk_demote_frame_undecodable_is_corrupt_not_lossy():
    """A frame that parses natively (byte-compared actors) but cannot be
    object-decoded (invalid UTF-8 actor) must report corrupt without
    aborting the bulk call — other docs' frames stay queued."""
    docs, _, origin = generate_docs()
    good = encode_frame([origin])
    # actor string "zz" -> invalid UTF-8 bytes: undeclared actor (demote path)
    # whose decode_frame fallback raises ValueError
    bad_actor = _craft_frame(
        [b"\xff\xfe"],
        [0, 1, 1, 0, 1, 0, 1, 1, 0, 2, 0, 0, 0, 0, ord("x")],
        1,
    )
    s = _session()
    with pytest.raises(ValueError):
        s.ingest_frames([(1, good), (0, bad_actor)])
    # doc 0 contributed nothing; doc 1's frame is fully queued
    assert s.docs[0].frames == [] and not s.docs[0].fallback
    s.drain()
    assert "".join(sp["text"] for sp in s.read(1)) == "The Peritext editor"


@pytest.mark.skipif(not native.available(), reason="needs native core")
def test_bulk_corrupt_frame_does_not_adopt_makelist():
    """A corrupt frame's makeList must not leak into session text_obj state
    (same-wire-input convergence must not depend on call batching)."""
    import json as _json

    # a makeList whose opid differs from the legitimate doc history's, so a
    # leak is distinguishable from follow's own (valid) makeList adoption
    make_list = _json.dumps(
        {"action": "makeList", "obj": "_root", "key": "text", "opId": "5@doc2"}
    )
    # change: [actor=0 seq=1 startOp=1 ndeps=0 nops=2,
    #          JSON makeList, insert with out-of-range codepoint]
    corrupt = _craft_frame(
        ["doc1", make_list],
        [0, 1, 1, 0, 2, 4, 1, 0, 1, 1, 0, 2, 0, 0, 0, 0, 0x110000],
        1,
    )
    docs, _, origin = generate_docs()
    follow = encode_frame([origin])  # valid ops (incl. makeList 1@doc1)
    s = _session()
    with pytest.raises(ValueError):
        s.ingest_frames([(0, corrupt), (0, follow)])
    # the corrupt frame contributed nothing: follow's own makeList governs,
    # the doc stays on the fast path, and its content reads back intact
    from peritext_tpu.ops.packed import pack_id

    assert s.docs[0].text_obj == pack_id(1, 1)
    assert s.docs[0].frames == [follow] and not s.docs[0].fallback
    s.drain()
    assert "".join(sp["text"] for sp in s.read(0)) == "The Peritext editor"


@pytest.mark.skipif(not native.available(), reason="needs native core")
def test_bulk_undecodable_attr_does_not_adopt_makelist():
    """A frame flagged corrupt by STRING INTERNING (undecodable UTF-8 mark
    attr) must not commit its makeList either: interning runs after value
    validation but used to run after the adoption loop, letting a crafted
    frame poison text_obj_by_doc and demote the doc's later valid frames
    (advisor r2 medium finding)."""
    import json as _json

    make_list = _json.dumps(
        {"action": "makeList", "obj": "_root", "key": "text", "opId": "5@doc2"}
    )
    # change header [actor=0 seq=1 startOp=1 ndeps=0 nops=2], then:
    #   op1: JSON spillover makeList (strid 1)
    #   op2: addMark comment over [startOfText, endOfText) with attr strid 2
    #        (invalid UTF-8 bytes) -> attr_idx = 2 + 1
    corrupt = _craft_frame(
        ["doc1", make_list, b"\xff\xfe"],
        [0, 1, 1, 0, 2,
         4, 1,
         2, 1, 5, 0, 6, 0, 2, 2, 0, 0, 3, 0, 0, 3],
        1,
    )
    docs, _, origin = generate_docs()
    follow = encode_frame([origin])  # valid ops (incl. makeList 1@doc1)
    s = _session()
    with pytest.raises(ValueError):
        s.ingest_frames([(0, corrupt), (0, follow)])
    from peritext_tpu.ops.packed import pack_id

    # the corrupt frame contributed nothing: no poisoned adoption, no
    # spurious demotion of the valid follow frame
    assert s.docs[0].text_obj == pack_id(1, 1)
    assert s.docs[0].frames == [follow] and not s.docs[0].fallback
    s.drain()
    assert "".join(sp["text"] for sp in s.read(0)) == "The Peritext editor"


@pytest.mark.skipif(not native.available(), reason="needs native core")
def test_bulk_corrupt_frames_do_not_intern_comment_ids():
    """Comment ids reaching the per-doc dense remap must come only from
    frames that passed every corrupt check: an adversarial peer spamming
    corrupt frames with distinct comment ids could otherwise exhaust the
    doc's comment capacity and force its reads to scalar replay forever
    (advisor r2 finding)."""
    import json as _json

    docs, _, origin = generate_docs()
    s = _session()
    s.ingest_frames([(0, encode_frame([origin]))])

    make_list = _json.dumps(
        {"action": "makeList", "obj": "_root", "key": "text", "opId": "9@doc2"}
    )
    for i in range(6):
        # each corrupt frame: a comment addMark with a FRESH id (strid 0)
        # plus a second makeList (spurious) and an undecodable attr marker
        # making the frame corrupt via interning
        frame = _craft_frame(
            [f"spam-{i}", "doc1", make_list, b"\xff"],
            [1, 2 + i, 1, 0, 3,
             2, 1, 1, 1, 10 + i, 1, 2, 2, 0, 0, 3, 0, 0, 1,
             4, 2,
             2, 1, 1, 1, 11 + i, 1, 2, 2, 0, 0, 3, 0, 0, 4],
            1,
        )
        with pytest.raises(ValueError):
            s.ingest_frames([(0, frame)])
    # no corrupt frame interned anything into the doc's dense comment table
    # (len 1 == only the Interner's reserved none slot)
    table = s._doc_comment_ids.get(0)
    assert table is None or len(table) == 1
    s.drain()
    assert "".join(sp["text"] for sp in s.read(0)) == "The Peritext editor"


def test_bulk_dedup_broadcast_frames_match_oracle():
    """Byte-identical frames fan-out (the scale demo ships one session to
    every doc): the parse dedups to unique frames and replicates the raw
    arrays (round 5).  Content, digest and per-doc comment interning must
    be indistinguishable from parsing every copy."""
    from peritext_tpu.api import oracle_merge
    from peritext_tpu.parallel.codec import encode_frame
    from peritext_tpu.parallel.streaming import StreamingMerge
    from peritext_tpu.testing.fuzz import generate_workload

    w = generate_workload(seed=31, num_docs=1, ops_per_doc=80)[0]
    changes = [ch for log in w.values() for ch in log]
    half = len(changes) // 2
    frames = [encode_frame(changes[:half]), encode_frame(changes[half:])]

    def build(docs):
        s = StreamingMerge(num_docs=docs, actors=("doc1", "doc2", "doc3"),
                           slot_capacity=256, mark_capacity=96,
                           tomb_capacity=96)
        for f in frames:
            s.ingest_frames((d, f) for d in range(docs))
            s.drain()
        return s

    many = build(12)  # 12 copies of 2 unique frames -> dedup path
    one = build(1)    # single doc -> non-dedup path
    assert not any(ds.fallback for ds in many.docs)
    expected = oracle_merge([w])[0]
    spans = many.read_all()
    assert all(sp == expected for sp in spans)
    assert many.read(5) == one.read(0)
    # digest is a doc-sum; every replica hashes identically
    many._digest_row_valid[:] = False
    many._refresh_digest_rows()
    assert (many._digest_plane[:12] == many._digest_plane[0]).all()


def test_bulk_dedup_replicates_corrupt_status():
    """A corrupt frame broadcast to many docs must surface EVERY replica
    through the dedup replication's normal corrupt-frame handling — a
    HEADER-corrupt unique frame parses to zero changes, which is exactly
    the empty-selection replication case (review r5: the first dedup cut
    crashed here with a numpy broadcast error instead of raising the
    documented ValueError)."""
    import pytest

    from peritext_tpu.parallel.codec import encode_frame
    from peritext_tpu.parallel.streaming import StreamingMerge
    from peritext_tpu.testing.fuzz import generate_workload

    w = generate_workload(seed=32, num_docs=1, ops_per_doc=40)[0]
    changes = [ch for log in w.values() for ch in log]
    good = encode_frame(changes)
    bad = b"XXXF" + good[4:]  # corrupt magic: header-invalid, 0 changes
    s = StreamingMerge(num_docs=8, actors=("doc1", "doc2", "doc3"),
                       slot_capacity=256, mark_capacity=96, tomb_capacity=96)
    with pytest.raises(ValueError) as exc:
        s.ingest_frames([(d, bad) for d in range(8)])
    assert "[0, 1, 2, 3, 4, 5, 6, 7]" in str(exc.value)
    # good frames after the corrupt batch still ingest everywhere
    s.ingest_frames([(d, good) for d in range(8)])
    s.drain()
    assert s.pending_count() == 0
    assert not any(ds.fallback for ds in s.docs)
    assert s.read(3) == s.read(7)
