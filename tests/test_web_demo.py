"""The browser two-editor demo's HTTP contract (demos/web/server.py):
edits dispatch through the TPU bridge backend, queue until Sync, and
anti-entropy converges both panes — the reference's index.ts experience."""

import json
import threading
import urllib.request

import pytest


@pytest.fixture(scope="module")
def demo_url():
    import importlib.util
    from http.server import ThreadingHTTPServer
    from pathlib import Path

    path = Path(__file__).parents[1] / "demos" / "web" / "server.py"
    spec = importlib.util.spec_from_file_location("web_demo_server", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    mod.SESSION = mod.Session(backend="tpu")
    server = ThreadingHTTPServer(("127.0.0.1", 0), mod.Handler)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    yield f"http://127.0.0.1:{server.server_port}"
    server.shutdown()


def _post(url, path, payload):
    req = urllib.request.Request(url + path, data=json.dumps(payload).encode())
    with urllib.request.urlopen(req) as res:
        return json.loads(res.read())


def _get(url, path):
    with urllib.request.urlopen(url + path) as res:
        return json.loads(res.read())


def _text(spans):
    return "".join(s["text"] for s in spans)


def test_page_and_state(demo_url):
    with urllib.request.urlopen(demo_url + "/") as res:
        page = res.read()
    assert b"contenteditable" in page
    # live mark-span sidebars (reference demo's Marks panel, index.html:19-25)
    assert b'id="marks-alice"' in page and b'id="marks-bob"' in page
    assert b"renderMarkPanel" in page
    state = _get(demo_url, "/state")
    assert _text(state["alice"]["spans"]) == _text(state["bob"]["spans"])
    # the state payload carries everything the panel renders: per-span marks
    assert all("marks" in sp for sp in state["alice"]["spans"])


def test_edit_queue_sync_converges(demo_url):
    state = _post(demo_url, "/op", {
        "editor": "alice",
        "ops": [{"path": ["text"], "action": "insert", "index": 0,
                 "values": list("Yo ")}],
    })
    assert _text(state["alice"]["spans"]).startswith("Yo ")
    assert state["alice"]["pending"] == 1  # queued until Sync
    assert not _text(state["bob"]["spans"]).startswith("Yo ")

    _post(demo_url, "/op", {
        "editor": "bob",
        "ops": [{"path": ["text"], "action": "addMark", "startIndex": 0,
                 "endIndex": 3, "markType": "strong"}],
    })
    state = _post(demo_url, "/sync", {})
    assert state["alice"]["spans"] == state["bob"]["spans"]
    assert state["alice"]["pending"] == state["bob"]["pending"] == 0
    assert any(
        s["marks"].get("strong", {}).get("active") for s in state["alice"]["spans"]
    )


def test_bad_op_reports_error_not_500(demo_url):
    req = urllib.request.Request(
        demo_url + "/op",
        data=json.dumps({"editor": "alice", "ops": [{"bogus": 1}]}).encode(),
    )
    try:
        urllib.request.urlopen(req)
        raise AssertionError("expected HTTP 400")
    except urllib.error.HTTPError as err:
        assert err.code == 400
        assert "error" in json.loads(err.read())
