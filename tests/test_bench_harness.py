"""Evidence-capture resilience of the bench orchestrator (bench.py).

Round 2 lost its TPU perf record to a tunnel flake: backend init raised /
hung and BENCH_r02.json recorded rc=1, parsed=null.  These tests pin the
round-3 contract — whatever the tunnel does, ``python bench.py`` prints one
parseable JSON line and exits 0 (nonzero only when even the CPU path is
broken, and still with a JSON line).

The dead-tunnel modes are simulated with PT_BENCH_SIMULATE_TPU=fail|hang,
which the probe child honours before importing jax (there is no tunnel to
kill in this CPU-only test image).  Reference analog: the reference CI's
"every job always reports a signal" discipline (.github/workflows/ci.yml).
"""

import json
import os
import subprocess
import sys

import pytest

BENCH = os.path.join(os.path.dirname(__file__), os.pardir, "bench.py")


def _run_bench(extra_args=(), env_extra=(), timeout=600):
    env = dict(os.environ)
    # the orchestrator's probe child must see the plain environment (tests
    # pin JAX_PLATFORMS=cpu via conftest, which doubles as "no TPU plugin")
    env.update(dict(env_extra))
    return subprocess.run(
        [sys.executable, BENCH, "--smoke", "--iters", "2", *extra_args],
        capture_output=True,
        text=True,
        env=env,
        timeout=timeout,
    )


def _json_line(stdout):
    lines = [ln for ln in stdout.splitlines() if ln.strip().startswith("{")]
    assert lines, f"no JSON line in stdout: {stdout!r}"
    return json.loads(lines[-1])


@pytest.mark.slow
def test_explicit_cpu_platform_still_one_json_line():
    """--platform cpu skips the probe and behaves exactly as round 2 did."""
    proc = _run_bench(["--platform", "cpu"])
    assert proc.returncode == 0, proc.stderr[-2000:]
    result = _json_line(proc.stdout)
    assert result["metric"] == "crdt_ops_per_sec_per_chip"
    assert result["value"] > 0
    assert "tpu_unavailable" not in result  # user chose cpu; not a fallback


@pytest.mark.slow
def test_probe_failure_falls_back_to_cpu_exit_zero():
    """A TPU backend that errors at init → CPU fallback, rc 0, flagged JSON."""
    proc = _run_bench(env_extra={"PT_BENCH_SIMULATE_TPU": "fail",
                                 "PT_BENCH_PROBE_ATTEMPTS": "2",
                                 "PT_BENCH_PROBE_BACKOFF": "0"}.items())
    assert proc.returncode == 0, proc.stderr[-2000:]
    result = _json_line(proc.stdout)
    assert result["tpu_unavailable"] is True
    assert "simulated TPU backend failure" in result["tpu_error"]
    assert result["value"] > 0
    assert result["platform"] == "cpu"


@pytest.mark.slow
def test_probe_hang_is_bounded_and_falls_back():
    """A TPU backend that hangs forever (round 2's observed mode) → the
    probe is killed at the timeout, retried, then CPU fallback with rc 0."""
    proc = _run_bench(
        env_extra={"PT_BENCH_SIMULATE_TPU": "hang",
                   "PT_BENCH_PROBE_TIMEOUT": "3",
                   "PT_BENCH_PROBE_ATTEMPTS": "2",
                   "PT_BENCH_PROBE_BACKOFF": "0"}.items(),
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    result = _json_line(proc.stdout)
    assert result["tpu_unavailable"] is True
    assert "timed out" in result["tpu_error"]
    assert result["value"] > 0
    # the probe phase must have been bounded: 2 attempts x 3s + slack
    assert result["probe_seconds"] < 60


@pytest.mark.slow
def test_engine_mode_reports_engine_and_end_to_end():
    """--mode engine replays captured device-ready rounds (digest-verified
    against the real session) and reports both the engine-limit rate and the
    end-to-end reference it is decoupled from."""
    proc = _run_bench(["--mode", "engine", "--platform", "cpu"])
    assert proc.returncode == 0, proc.stderr[-2000:]
    result = _json_line(proc.stdout)
    assert result["metric"] == "engine_limit_streaming_ops_per_sec_per_chip"
    assert result["value"] > 0 and result["end_to_end_ops_per_sec"] > 0
    # the replay syncs once; it can never be slower than end-to-end by much
    assert result["vs_baseline"] > 0.8


@pytest.mark.slow
def test_ladder_smoke_emits_rows():
    """--mode ladder runs every selected row as its own bounded worker and
    prints ONE JSON line with a rows array (VERDICT r3 task 1).  The
    headline fields mirror the best batch row so the driver contract is
    unchanged."""
    import tempfile

    with tempfile.TemporaryDirectory() as td:
        sidecar = os.path.join(td, "BENCH_self.json")
        proc = _run_bench(
            ["--mode", "ladder", "--platform", "cpu"],
            env_extra={"PT_BENCH_LADDER_ROWS": "baselines,batch_8k,wire",
                       "PT_BENCH_SIDECAR": sidecar}.items(),
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        # the LAST stdout line is the driver-parsed compact summary: within
        # the hard byte budget no matter what (VERDICT r4 task 1)
        last = proc.stdout.strip().splitlines()[-1]
        assert len(last) <= 1536, f"final line {len(last)} B over budget"
        result = json.loads(last)
        assert result["metric"] == "crdt_ops_per_sec_per_chip"
        assert result["value"] > 0
        assert result["headline_row"] == "batch_8k"
        assert result["sidecar"] == "BENCH_self.json"
        crows = {r["row"]: r for r in result["rows"]}
        assert set(crows) == {"baselines", "batch_8k", "wire"}
        assert crows["batch_8k"]["platform"] == "cpu"
        assert crows["batch_8k"]["value"] > 0
        # the FULL rows live in the sidecar (and in an earlier stdout line)
        full = json.load(open(sidecar))
        rows = {r["row"]: r for r in full["rows"]}
        assert rows["baselines"]["scalar_python_ops_per_sec"] > 0
        assert rows["wire"]["shapes"]["typing"]["bytes_per_op"] < 4
        # the batch row REUSED the baselines row's python-oracle measurement
        # (shape-independent; the native one re-measures when ops/doc differ)
        assert rows["batch_8k"]["python_oracle_ops_per_sec"] == \
            rows["baselines"]["scalar_python_ops_per_sec"]
        # the earlier stdout line carries the same full record
        full_line = json.loads(
            [ln for ln in proc.stdout.splitlines()
             if ln.strip().startswith("{")][-2])
        assert full_line["rows"] == full["rows"]


@pytest.mark.slow
def test_ladder_dead_tunnel_still_records_full_rows():
    """A dead TPU backend must never shrink the record to the smoke config
    alone: the SAME ladder reruns on CPU, flagged tpu_unavailable (VERDICT
    r3 weak #2)."""
    import tempfile

    with tempfile.TemporaryDirectory() as td:
        env = {
            "PT_BENCH_SIMULATE_TPU": "fail",
            "PT_BENCH_PROBE_ATTEMPTS": "1",
            "PT_BENCH_PROBE_BACKOFF": "0",
            "PT_BENCH_LADDER_ROWS": "wire,batch_128_cpu",
            "PT_BENCH_SIDECAR": os.path.join(td, "BENCH_self.json"),
        }
        proc = subprocess.run(
            [sys.executable, BENCH, "--mode", "ladder", "--iters", "2",
             "--smoke"],
            capture_output=True, text=True,
            env={**os.environ, **env}, timeout=600,
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        last = proc.stdout.strip().splitlines()[-1]
        assert len(last) <= 1536
        result = json.loads(last)
        assert result["tpu_unavailable"] is True
        rows = {r["row"]: r for r in result["rows"]}
        assert set(rows) == {"wire", "batch_128_cpu"}
        assert not any(r.get("failed") for r in rows.values())


def test_compact_record_fits_budget_on_round4_shape():
    """Regression for BENCH_r04.json parsed=null: the round-4 full ladder
    record (~5 KB, committed as BENCH_self_r04_tpu.json) must compact to
    within the driver's tail budget with every row retained."""
    import bench

    full = json.load(open(os.path.join(os.path.dirname(BENCH),
                                       "BENCH_self_r04_tpu.json")))
    compact = bench.compact_record(full)
    blob = json.dumps(compact)
    assert len(blob) <= 1536, f"{len(blob)} B over budget"
    assert compact["value"] == full["value"]
    assert [r["row"] for r in compact["rows"]] == \
        [r["row"] for r in full["rows"]]
    assert all("value" in r for r in compact["rows"]
               if not r.get("failed") and not r.get("skipped"))


def test_compact_record_degrades_but_never_overflows():
    """Pathological rows (huge error strings, many rows) still compact to
    within the budget — by dropping optional fields, then trailing rows."""
    import bench

    record = {
        "metric": "m", "value": 1.0, "unit": "ops/s", "vs_baseline": 2.0,
        "headline_row": "r0", "tpu_error": "x" * 5000,
        "rows": [{"row": f"r{i}", "value": float(i), "unit": "ops/s",
                  "platform": "tpu", "config": str(i), "vs_baseline": 1.0,
                  "error": "y" * 2000}
                 for i in range(40)],
    }
    compact = bench.compact_record(record)
    assert len(json.dumps(compact)) <= 1536
    assert compact["value"] == 1.0
    assert len(compact["tpu_error"]) <= 160
    # tiny budget: rows degrade away entirely but the headline survives
    tiny = bench.compact_record(record, budget=200)
    assert len(json.dumps(tiny)) <= 200
    assert tiny["value"] == 1.0


def test_probe_ok_on_cpu_only_env_flags_unavailability(monkeypatch):
    """No TPU plugin (default backend = cpu) is recorded as tpu_unavailable
    so a driver run on a chip-less host can't masquerade as a TPU number.
    (This image does ship the axon plugin, so the plugin-less default is
    simulated — PT_BENCH_SIMULATE_TPU=cpu pins the probe child to cpu.)"""
    import bench

    monkeypatch.setenv("PT_BENCH_SIMULATE_TPU", "cpu")
    platform, tail = bench.probe_device(timeout=120, attempts=1)
    assert platform == "cpu"
    assert tail == ""


def test_parse_json_tail_skips_warnings():
    import bench

    out = "WARNING: platform axon is experimental\nnot json {\n" + json.dumps(
        {"metric": "m", "value": 1}
    )
    assert bench._parse_json_tail(out) == {"metric": "m", "value": 1}
    assert bench._parse_json_tail("no json here") is None


def test_worker_crash_yields_structured_failure_line():
    """If even the CPU worker dies, the orchestrator still prints a JSON
    line carrying the error tail (rc 1 is then honest)."""
    import bench

    class _Args:
        platform = "cpu"
        smoke = True
        docs = None
        ops_per_doc = None
        mode = "batch"

    real = bench._run_bounded
    calls = []

    def fake_run_bounded(argv, timeout):
        calls.append(argv)
        return 1, "", "boom: synthetic worker crash"

    bench._run_bounded = fake_run_bounded
    try:
        import io
        from contextlib import redirect_stdout

        buf = io.StringIO()
        with redirect_stdout(buf):
            rc = bench.orchestrate(_Args(), ["--smoke"])
    finally:
        bench._run_bounded = real
    assert rc == 1
    result = json.loads(buf.getvalue().strip().splitlines()[-1])
    assert result["failed"] is True
    assert "synthetic worker crash" in result["error"]
    assert result["value"] is None
    assert len(calls) >= 1
