"""Native C++ host-runtime tests: scheduler equivalence with the Python
implementation, varint byte-compatibility, and binary frame codec round-trips."""

import json
import random

import numpy as np
import pytest

from peritext_tpu import native
from peritext_tpu.core.types import Change
from peritext_tpu.parallel import causal
from peritext_tpu.parallel.codec import (
    _py_varint_decode,
    _py_varint_encode,
    decode_frame,
    encode_frame,
)
from peritext_tpu.testing.fuzz import run_fuzz


def fuzz_changes(seed, iterations=60):
    state = run_fuzz(seed=seed, iterations=iterations)
    return [ch for a in state.store.actors() for ch in state.store.log(a)]


def python_schedule(changes, base_clock=None):
    """Force the pure-Python scheduler path."""
    old = causal._NATIVE_THRESHOLD
    causal._NATIVE_THRESHOLD = 10**9
    try:
        return causal.causal_schedule(changes, base_clock)
    finally:
        causal._NATIVE_THRESHOLD = old


@pytest.fixture(scope="module")
def native_lib():
    lib = native.load()
    if lib is None:
        pytest.skip("native library unavailable")
    return lib


class TestNativeBuild:
    def test_builds_and_loads(self, native_lib):
        assert native.available()


class TestSchedulerEquivalence:
    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_full_set_matches_python(self, native_lib, seed):
        changes = fuzz_changes(seed)
        rng = random.Random(seed)
        for _ in range(5):
            rng.shuffle(changes)
            py_ordered, py_stuck = python_schedule(list(changes))
            nat = causal._native_schedule(list(changes), None)
            assert nat is not None
            nat_ordered, nat_stuck = nat
            assert [(c.actor, c.seq) for c in nat_ordered] == [
                (c.actor, c.seq) for c in py_ordered
            ]
            assert nat_stuck == py_stuck == []

    def test_with_base_clock_and_duplicates(self, native_lib):
        changes = fuzz_changes(3)
        base = {"doc1": 2}  # pretend doc1's first two changes are applied
        doubled = changes + list(changes)
        py_ordered, py_stuck = python_schedule(list(doubled), dict(base))
        nat_ordered, nat_stuck = causal._native_schedule(list(doubled), dict(base))
        assert [(c.actor, c.seq) for c in nat_ordered] == [
            (c.actor, c.seq) for c in py_ordered
        ]
        assert [(c.actor, c.seq) for c in nat_stuck] == [
            (c.actor, c.seq) for c in py_stuck
        ]

    def test_gaps_leave_identical_stuck_sets(self, native_lib):
        changes = fuzz_changes(5)
        rng = random.Random(7)
        # drop 30%: later changes of the same actor become stuck
        kept = [ch for ch in changes if rng.random() > 0.3]
        py_ordered, py_stuck = python_schedule(list(kept))
        nat_ordered, nat_stuck = causal._native_schedule(list(kept), None)
        assert [(c.actor, c.seq) for c in nat_ordered] == [
            (c.actor, c.seq) for c in py_ordered
        ]
        assert [(c.actor, c.seq) for c in nat_stuck] == [
            (c.actor, c.seq) for c in py_stuck
        ]

    def test_dep_on_unknown_actor_is_stuck(self, native_lib):
        ch = Change(actor="a", seq=1, deps={"ghost": 4}, start_op=1, ops=[])
        filler = fuzz_changes(1)  # push past the native threshold
        ordered, stuck = causal._native_schedule(filler + [ch], None)
        assert ch in stuck


class TestVarint:
    def test_native_and_python_bytes_identical(self, native_lib):
        rng = np.random.default_rng(0)
        values = rng.integers(-(2**31), 2**31 - 1, size=5000, dtype=np.int32)
        nat = native.varint_encode(values)
        py = _py_varint_encode(values.tolist())
        assert nat == py
        assert native.varint_decode(nat, len(values)).tolist() == values.tolist()
        assert _py_varint_decode(py, len(values)) == values.tolist()

    def test_malformed_rejected(self, native_lib):
        with pytest.raises(ValueError):
            native.varint_decode(b"\xff\xff\xff\xff\xff\xff", 1)
        with pytest.raises(ValueError):
            _py_varint_decode(b"\xff\xff\xff\xff\xff\xff", 1)


class TestFrameCodec:
    @pytest.mark.parametrize("seed", [0, 4])
    def test_round_trip_equals_input(self, seed):
        changes = fuzz_changes(seed)
        frame = encode_frame(changes)
        decoded = decode_frame(frame)
        assert decoded == changes

    def test_round_trip_matches_json_wire(self):
        changes = fuzz_changes(2)
        decoded = decode_frame(encode_frame(changes))
        assert [c.to_json() for c in decoded] == [c.to_json() for c in changes]

    def test_smaller_than_json(self):
        changes = fuzz_changes(6, iterations=150)
        frame = encode_frame(changes)
        as_json = json.dumps([c.to_json() for c in changes]).encode()
        assert len(frame) < len(as_json) / 2  # at least 2x denser

    def test_map_ops_spill_to_json_path(self):
        from peritext_tpu.core.comment import Comment, put_comment
        from peritext_tpu.core.doc import Doc

        doc = Doc("alice")
        change, _ = put_comment(doc, Comment(id="c1", actor="alice", content="hey"))
        decoded = decode_frame(encode_frame([change]))
        assert decoded == [change]

    def test_corrupt_frames_raise(self):
        changes = fuzz_changes(1, iterations=20)
        frame = encode_frame(changes)
        with pytest.raises(ValueError):
            decode_frame(frame[: len(frame) // 2])
        with pytest.raises(ValueError):
            decode_frame(b"XXXX" + frame[4:])
        with pytest.raises(ValueError):
            decode_frame(frame[:-3])

    def test_python_fallback_bytes_compatible(self, monkeypatch):
        changes = fuzz_changes(3, iterations=30)
        with_native = encode_frame(changes)
        monkeypatch.setattr(native, "available", lambda: False)
        without = encode_frame(changes)
        assert with_native == without
        assert decode_frame(with_native) == changes


class TestCodecRobustness:
    """Regression tests for lossless attrs and the corrupt-frame contract."""

    def _mark_change(self, mark_type, attrs):
        from peritext_tpu.core.types import Boundary, Operation
        from peritext_tpu.core.types import BEFORE, END_OF_TEXT

        op = Operation(
            action="addMark",
            obj=(1, "alice"),
            opid=(7, "alice"),
            start=Boundary(BEFORE, (2, "alice")),
            end=Boundary(END_OF_TEXT),
            mark_type=mark_type,
            attrs=attrs,
        )
        return Change(actor="alice", seq=1, deps={}, start_op=7, ops=[op])

    @pytest.mark.parametrize(
        "mark_type,attrs",
        [
            ("link", {"url": "http://x", "title": "extra"}),  # extra key
            ("strong", {"url": "http://x"}),  # attrs on attr-less type
            ("link", {}),  # empty dict must stay {}
            ("comment", {"id": "c1", "resolved": True}),
            ("link", {"url": 42}),  # non-string value
        ],
    )
    def test_attr_shapes_round_trip_lossless(self, mark_type, attrs):
        changes = [self._mark_change(mark_type, attrs)]
        decoded = decode_frame(encode_frame(changes))
        assert decoded == changes
        assert decoded[0].ops[0].attrs == attrs

    def test_fast_path_attrs_round_trip(self):
        for mark_type, attrs in [("link", {"url": "http://x"}), ("comment", {"id": "c9"})]:
            changes = [self._mark_change(mark_type, attrs)]
            decoded = decode_frame(encode_frame(changes))
            assert decoded == changes

    def test_byte_flip_fuzz_raises_valueerror_only(self):
        changes = fuzz_changes(4, iterations=40)
        frame = bytearray(encode_frame(changes))
        rng = random.Random(0)
        flips = 0
        for _ in range(400):
            i = rng.randrange(len(frame))
            old = frame[i]
            frame[i] ^= 1 << rng.randrange(8)
            try:
                out = decode_frame(bytes(frame))
                assert isinstance(out, list)
            except ValueError:
                flips += 1
            finally:
                frame[i] = old
        assert flips > 0  # most flips must be detected

    def test_truncated_and_giant_headers_rejected(self):
        frame = encode_frame(fuzz_changes(5, iterations=10))
        import struct as _struct

        # blow up n_ints to something that would drive a giant allocation
        hdr = list(_struct.Struct("<4sBIIQQ").unpack_from(frame))
        hdr[4] = 1 << 40
        bad = _struct.Struct("<4sBIIQQ").pack(*hdr) + frame[_struct.Struct("<4sBIIQQ").size:]
        with pytest.raises(ValueError):
            decode_frame(bad)


def test_scalar_apply_matches_oracle():
    """The C++ single-core baseline (pt_scalar_apply) must replay a fuzz
    workload to the oracle's exact visible text (BASELINE config 1)."""
    import pytest

    from peritext_tpu import native
    from peritext_tpu.testing.baseline import (
        check_scalar_apply_matches_oracle,
        workload_op_matrices,
    )
    from peritext_tpu.testing.fuzz import generate_workload

    if not native.available():
        pytest.skip("native core unavailable")
    workloads = generate_workload(seed=77, num_docs=3, ops_per_doc=120)
    matrices, total = workload_op_matrices(workloads)
    assert total > 0
    check_scalar_apply_matches_oracle(workloads, matrices)


class TestWireV2Efficiency:
    """Wire v2 delta encoding (VERDICT r2 weak #4): the frame layout elides
    ids the frame context predicts, roughly halving bytes/op vs v1's ~12.
    These are regression guards on the measured rates, not exact pins."""

    def _fuzz_frames(self, order):
        from peritext_tpu.parallel.causal import causal_sort
        from peritext_tpu.testing.fuzz import generate_workload

        out = []
        for wl in generate_workload(seed=21, num_docs=3, ops_per_doc=140):
            chs = [ch for log in wl.values() for ch in log]
            if order == "causal":
                chs = causal_sort(chs)
            out.append(chs)
        return out

    def test_fuzz_shaped_bytes_per_op(self):
        from peritext_tpu.parallel.codec import decode_frame, encode_frame

        tot_b = tot_o = 0
        for chs in self._fuzz_frames("causal"):
            f = encode_frame(chs)
            assert decode_frame(f) == chs
            tot_b += len(f)
            tot_o += sum(len(c.ops) for c in chs)
        # v1 measured 12.9 on this shape; v2 lands ~7.3 (the rest is the
        # per-change causal metadata at ~2 ops/change + mark anchors)
        assert tot_b / tot_o < 8.5, tot_b / tot_o

    def test_typing_shaped_bytes_per_op(self):
        """Multi-char inserts (the reference's own hot path: per-char chained
        ops, src/micromerge.ts:604-613) amortize to a few bytes per op."""
        from peritext_tpu.core.doc import Doc
        from peritext_tpu.parallel.codec import decode_frame, encode_frame

        d = Doc("alice")
        chs = []
        ch, _ = d.change([{"path": [], "action": "makeList", "key": "text"}])
        chs.append(ch)
        text = "The quick brown fox jumps over the lazy dog. " * 20
        pos = 0
        for i in range(20):
            seg = text[i * 45:(i + 1) * 45]
            ch, _ = d.change([{"path": ["text"], "action": "insert",
                              "index": pos, "values": list(seg)}])
            pos += len(seg)
            chs.append(ch)
        f = encode_frame(chs)
        assert decode_frame(f) == chs
        n = sum(len(c.ops) for c in chs)
        assert len(f) / n < 3.0, len(f) / n

    def test_mixed_session_round_trip_shuffled(self):
        import random

        from peritext_tpu.parallel.codec import decode_frame, encode_frame

        rng = random.Random(3)
        for chs in self._fuzz_frames("grouped"):
            rng.shuffle(chs)
            assert decode_frame(encode_frame(chs)) == chs

    def test_per_keystroke_changes_round_trip_and_ingest(self):
        """One insert per change (the classic interactive typing shape) is
        v2's most-elided form — 3 ints/change, under v1's 5-int minimum.
        The header sanity checks must be version-aware or valid frames are
        rejected as corrupt (review finding r3)."""
        from peritext_tpu.api.batch import _oracle_doc
        from peritext_tpu.core.doc import Doc
        from peritext_tpu.parallel.codec import decode_frame, encode_frame
        from peritext_tpu.parallel.streaming import StreamingMerge

        d = Doc("alice")
        chs = []
        ch, _ = d.change([{"path": [], "action": "makeList", "key": "text"}])
        chs.append(ch)
        for i, c in enumerate("hello world"):
            ch, _ = d.change([{"path": ["text"], "action": "insert",
                              "index": i, "values": [c]}])
            chs.append(ch)
        f = encode_frame(chs)
        assert decode_frame(f) == chs
        s = StreamingMerge(num_docs=1, actors=("alice",), slot_capacity=64,
                           round_insert_capacity=32, round_delete_capacity=8,
                           round_mark_capacity=8)
        s.ingest_frames([(0, f)])
        s.drain()
        assert "".join(sp["text"] for sp in s.read(0)) == "hello world"
        assert not s.docs[0].fallback

    def test_dep_expansion_budget_rejects_crafted_blowup(self):
        """A sub-MB crafted frame must not expand to unbounded dep dicts:
        DEPS_SAME headers re-materialize the stored dep set from zero wire
        ints, so both decoders bound the expansion (native demotes the doc
        off the fast path at n_declared+64; the Python decoder enforces a
        total decode budget)."""
        import pytest
        from wire import craft_frame

        from peritext_tpu.parallel.codec import decode_frame

        n_actors = 200
        strings = [f"actor-{i:03d}" for i in range(n_actors)]
        ints = []
        # change 1 (combo: actor 0, no flags): dseq=0, dstart=0, then a FULL
        # dep set naming every actor (establishes the stored dep_set), one
        # makeList op (kind 5 + REF_HEAD, opid/obj elided): [first, key=0]
        ints += [0 << 4, 0, 0, (n_actors << 2) | 0]
        for i in range(n_actors):
            ints += [i, 1]
        # first op carries an explicit ROOT obj (no previous op to elide to)
        ints += [1, 5 | ((1 | 8) << 3), 0, 0, 0, 0]
        # thousands of fully-elided single-op changes with DEPS_SAME: 3 ints
        # each, each re-materializing the 200-entry dep set at decode time
        n_spam = 5000
        for _ in range(n_spam):
            ints += [(0 << 4) | (1 | 2 | 4 | 8), 5 | ((1 | 2 | 8) << 3), 0]
        frame = craft_frame(strings, ints, 1 + n_spam, version=2)
        assert len(frame) < 100_000  # small wire...
        with pytest.raises(ValueError, match="decode budget"):
            decode_frame(frame)  # ...must NOT decode to ~1M dep entries

    def test_wire_v1_frames_still_ingest(self):
        """v1 frames (old checkpoints, old peers) must keep decoding and
        taking the native fast path: the reader negotiates the version per
        frame.  The inline v1 writer below emits every op as a JSON-spill
        row — the simplest valid v1 layout (kind _OP_JSON + string id)."""
        from wire import craft_frame

        from peritext_tpu.api.batch import _oracle_doc
        from peritext_tpu.parallel.codec import _OP_JSON, decode_frame, encode_frame
        from peritext_tpu.parallel.streaming import StreamingMerge
        from peritext_tpu.testing.fuzz import generate_workload

        (wl,) = generate_workload(seed=31, num_docs=1, ops_per_doc=80)
        chs = [ch for log in wl.values() for ch in log]

        # v1 writer: the pre-delta layout (explicit obj/opid/ref per op)
        def v1_encode(changes):
            table = {}
            strings = []

            def intern(s):
                if s not in table:
                    table[s] = len(strings)
                    strings.append(s)
                return table[s]

            ints = []
            for c in changes:
                ints += [intern(c.actor), c.seq, c.start_op]
                deps = sorted((c.deps or {}).items())
                ints.append(len(deps))
                for a, s in deps:
                    ints += [intern(a), s]
                ints.append(len(c.ops))
                for op in c.ops:
                    ints += [_OP_JSON, intern(json.dumps(op.to_json()))]
            return craft_frame(strings, ints, len(changes), version=1)

        v1_frame = v1_encode(chs)
        assert decode_frame(v1_frame) == chs  # reader accepts v1

        expected = _oracle_doc(wl).get_text_with_formatting(["text"])
        for frame in (v1_frame, encode_frame(chs)):
            s = StreamingMerge(num_docs=1, actors=("doc1", "doc2", "doc3"),
                               slot_capacity=512, mark_capacity=128,
                               tomb_capacity=256, round_insert_capacity=128,
                               round_delete_capacity=64, round_mark_capacity=64)
            s.ingest_frames([(0, frame)])
            s.drain()
            assert s.read(0) == expected
