"""Native C++ host-runtime tests: scheduler equivalence with the Python
implementation, varint byte-compatibility, and binary frame codec round-trips."""

import json
import random

import numpy as np
import pytest

from peritext_tpu import native
from peritext_tpu.core.types import Change
from peritext_tpu.parallel import causal
from peritext_tpu.parallel.codec import (
    _py_varint_decode,
    _py_varint_encode,
    decode_frame,
    encode_frame,
)
from peritext_tpu.testing.fuzz import run_fuzz


def fuzz_changes(seed, iterations=60):
    state = run_fuzz(seed=seed, iterations=iterations)
    return [ch for a in state.store.actors() for ch in state.store.log(a)]


def python_schedule(changes, base_clock=None):
    """Force the pure-Python scheduler path."""
    old = causal._NATIVE_THRESHOLD
    causal._NATIVE_THRESHOLD = 10**9
    try:
        return causal.causal_schedule(changes, base_clock)
    finally:
        causal._NATIVE_THRESHOLD = old


@pytest.fixture(scope="module")
def native_lib():
    lib = native.load()
    if lib is None:
        pytest.skip("native library unavailable")
    return lib


class TestNativeBuild:
    def test_builds_and_loads(self, native_lib):
        assert native.available()


class TestSchedulerEquivalence:
    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_full_set_matches_python(self, native_lib, seed):
        changes = fuzz_changes(seed)
        rng = random.Random(seed)
        for _ in range(5):
            rng.shuffle(changes)
            py_ordered, py_stuck = python_schedule(list(changes))
            nat = causal._native_schedule(list(changes), None)
            assert nat is not None
            nat_ordered, nat_stuck = nat
            assert [(c.actor, c.seq) for c in nat_ordered] == [
                (c.actor, c.seq) for c in py_ordered
            ]
            assert nat_stuck == py_stuck == []

    def test_with_base_clock_and_duplicates(self, native_lib):
        changes = fuzz_changes(3)
        base = {"doc1": 2}  # pretend doc1's first two changes are applied
        doubled = changes + list(changes)
        py_ordered, py_stuck = python_schedule(list(doubled), dict(base))
        nat_ordered, nat_stuck = causal._native_schedule(list(doubled), dict(base))
        assert [(c.actor, c.seq) for c in nat_ordered] == [
            (c.actor, c.seq) for c in py_ordered
        ]
        assert [(c.actor, c.seq) for c in nat_stuck] == [
            (c.actor, c.seq) for c in py_stuck
        ]

    def test_gaps_leave_identical_stuck_sets(self, native_lib):
        changes = fuzz_changes(5)
        rng = random.Random(7)
        # drop 30%: later changes of the same actor become stuck
        kept = [ch for ch in changes if rng.random() > 0.3]
        py_ordered, py_stuck = python_schedule(list(kept))
        nat_ordered, nat_stuck = causal._native_schedule(list(kept), None)
        assert [(c.actor, c.seq) for c in nat_ordered] == [
            (c.actor, c.seq) for c in py_ordered
        ]
        assert [(c.actor, c.seq) for c in nat_stuck] == [
            (c.actor, c.seq) for c in py_stuck
        ]

    def test_dep_on_unknown_actor_is_stuck(self, native_lib):
        ch = Change(actor="a", seq=1, deps={"ghost": 4}, start_op=1, ops=[])
        filler = fuzz_changes(1)  # push past the native threshold
        ordered, stuck = causal._native_schedule(filler + [ch], None)
        assert ch in stuck


class TestVarint:
    def test_native_and_python_bytes_identical(self, native_lib):
        rng = np.random.default_rng(0)
        values = rng.integers(-(2**31), 2**31 - 1, size=5000, dtype=np.int32)
        nat = native.varint_encode(values)
        py = _py_varint_encode(values.tolist())
        assert nat == py
        assert native.varint_decode(nat, len(values)).tolist() == values.tolist()
        assert _py_varint_decode(py, len(values)) == values.tolist()

    def test_malformed_rejected(self, native_lib):
        with pytest.raises(ValueError):
            native.varint_decode(b"\xff\xff\xff\xff\xff\xff", 1)
        with pytest.raises(ValueError):
            _py_varint_decode(b"\xff\xff\xff\xff\xff\xff", 1)


class TestFrameCodec:
    @pytest.mark.parametrize("seed", [0, 4])
    def test_round_trip_equals_input(self, seed):
        changes = fuzz_changes(seed)
        frame = encode_frame(changes)
        decoded = decode_frame(frame)
        assert decoded == changes

    def test_round_trip_matches_json_wire(self):
        changes = fuzz_changes(2)
        decoded = decode_frame(encode_frame(changes))
        assert [c.to_json() for c in decoded] == [c.to_json() for c in changes]

    def test_smaller_than_json(self):
        changes = fuzz_changes(6, iterations=150)
        frame = encode_frame(changes)
        as_json = json.dumps([c.to_json() for c in changes]).encode()
        assert len(frame) < len(as_json) / 2  # at least 2x denser

    def test_map_ops_spill_to_json_path(self):
        from peritext_tpu.core.comment import Comment, put_comment
        from peritext_tpu.core.doc import Doc

        doc = Doc("alice")
        change, _ = put_comment(doc, Comment(id="c1", actor="alice", content="hey"))
        decoded = decode_frame(encode_frame([change]))
        assert decoded == [change]

    def test_corrupt_frames_raise(self):
        changes = fuzz_changes(1, iterations=20)
        frame = encode_frame(changes)
        with pytest.raises(ValueError):
            decode_frame(frame[: len(frame) // 2])
        with pytest.raises(ValueError):
            decode_frame(b"XXXX" + frame[4:])
        with pytest.raises(ValueError):
            decode_frame(frame[:-3])

    def test_python_fallback_bytes_compatible(self, monkeypatch):
        changes = fuzz_changes(3, iterations=30)
        with_native = encode_frame(changes)
        monkeypatch.setattr(native, "available", lambda: False)
        without = encode_frame(changes)
        assert with_native == without
        assert decode_frame(with_native) == changes


class TestCodecRobustness:
    """Regression tests for lossless attrs and the corrupt-frame contract."""

    def _mark_change(self, mark_type, attrs):
        from peritext_tpu.core.types import Boundary, Operation
        from peritext_tpu.core.types import BEFORE, END_OF_TEXT

        op = Operation(
            action="addMark",
            obj=(1, "alice"),
            opid=(7, "alice"),
            start=Boundary(BEFORE, (2, "alice")),
            end=Boundary(END_OF_TEXT),
            mark_type=mark_type,
            attrs=attrs,
        )
        return Change(actor="alice", seq=1, deps={}, start_op=7, ops=[op])

    @pytest.mark.parametrize(
        "mark_type,attrs",
        [
            ("link", {"url": "http://x", "title": "extra"}),  # extra key
            ("strong", {"url": "http://x"}),  # attrs on attr-less type
            ("link", {}),  # empty dict must stay {}
            ("comment", {"id": "c1", "resolved": True}),
            ("link", {"url": 42}),  # non-string value
        ],
    )
    def test_attr_shapes_round_trip_lossless(self, mark_type, attrs):
        changes = [self._mark_change(mark_type, attrs)]
        decoded = decode_frame(encode_frame(changes))
        assert decoded == changes
        assert decoded[0].ops[0].attrs == attrs

    def test_fast_path_attrs_round_trip(self):
        for mark_type, attrs in [("link", {"url": "http://x"}), ("comment", {"id": "c9"})]:
            changes = [self._mark_change(mark_type, attrs)]
            decoded = decode_frame(encode_frame(changes))
            assert decoded == changes

    def test_byte_flip_fuzz_raises_valueerror_only(self):
        changes = fuzz_changes(4, iterations=40)
        frame = bytearray(encode_frame(changes))
        rng = random.Random(0)
        flips = 0
        for _ in range(400):
            i = rng.randrange(len(frame))
            old = frame[i]
            frame[i] ^= 1 << rng.randrange(8)
            try:
                out = decode_frame(bytes(frame))
                assert isinstance(out, list)
            except ValueError:
                flips += 1
            finally:
                frame[i] = old
        assert flips > 0  # most flips must be detected

    def test_truncated_and_giant_headers_rejected(self):
        frame = encode_frame(fuzz_changes(5, iterations=10))
        import struct as _struct

        # blow up n_ints to something that would drive a giant allocation
        hdr = list(_struct.Struct("<4sBIIQQ").unpack_from(frame))
        hdr[4] = 1 << 40
        bad = _struct.Struct("<4sBIIQQ").pack(*hdr) + frame[_struct.Struct("<4sBIIQQ").size:]
        with pytest.raises(ValueError):
            decode_frame(bad)


def test_scalar_apply_matches_oracle():
    """The C++ single-core baseline (pt_scalar_apply) must replay a fuzz
    workload to the oracle's exact visible text (BASELINE config 1)."""
    import pytest

    from peritext_tpu import native
    from peritext_tpu.testing.baseline import (
        check_scalar_apply_matches_oracle,
        workload_op_matrices,
    )
    from peritext_tpu.testing.fuzz import generate_workload

    if not native.available():
        pytest.skip("native core unavailable")
    workloads = generate_workload(seed=77, num_docs=3, ops_per_doc=120)
    matrices, total = workload_op_matrices(workloads)
    assert total > 0
    check_scalar_apply_matches_oracle(workloads, matrices)


class TestWireV2Efficiency:
    """Wire v2 delta encoding (VERDICT r2 weak #4): the frame layout elides
    ids the frame context predicts, roughly halving bytes/op vs v1's ~12.
    These are regression guards on the measured rates, not exact pins."""

    def _fuzz_frames(self, order):
        from peritext_tpu.parallel.causal import causal_sort
        from peritext_tpu.testing.fuzz import generate_workload

        out = []
        for wl in generate_workload(seed=21, num_docs=3, ops_per_doc=140):
            chs = [ch for log in wl.values() for ch in log]
            if order == "causal":
                chs = causal_sort(chs)
            out.append(chs)
        return out

    def test_fuzz_shaped_bytes_per_op(self):
        from peritext_tpu.parallel.codec import decode_frame, encode_frame

        tot_b = tot_o = 0
        for chs in self._fuzz_frames("causal"):
            f = encode_frame(chs)
            assert decode_frame(f) == chs
            tot_b += len(f)
            tot_o += sum(len(c.ops) for c in chs)
        # v1 measured 12.9 on this shape; v2 lands ~7.3 (the rest is the
        # per-change causal metadata at ~2 ops/change + mark anchors)
        assert tot_b / tot_o < 8.5, tot_b / tot_o

    def test_typing_shaped_bytes_per_op(self):
        """Multi-char inserts (the reference's own hot path: per-char chained
        ops, src/micromerge.ts:604-613) amortize to a few bytes per op."""
        from peritext_tpu.core.doc import Doc
        from peritext_tpu.parallel.codec import decode_frame, encode_frame

        d = Doc("alice")
        chs = []
        ch, _ = d.change([{"path": [], "action": "makeList", "key": "text"}])
        chs.append(ch)
        text = "The quick brown fox jumps over the lazy dog. " * 20
        pos = 0
        for i in range(20):
            seg = text[i * 45:(i + 1) * 45]
            ch, _ = d.change([{"path": ["text"], "action": "insert",
                              "index": pos, "values": list(seg)}])
            pos += len(seg)
            chs.append(ch)
        f = encode_frame(chs)
        assert decode_frame(f) == chs
        n = sum(len(c.ops) for c in chs)
        assert len(f) / n < 3.0, len(f) / n

    def test_mixed_session_round_trip_shuffled(self):
        import random

        from peritext_tpu.parallel.codec import decode_frame, encode_frame

        rng = random.Random(3)
        for chs in self._fuzz_frames("grouped"):
            rng.shuffle(chs)
            assert decode_frame(encode_frame(chs)) == chs

    def test_per_keystroke_changes_round_trip_and_ingest(self):
        """One insert per change (the classic interactive typing shape) is
        v2's most-elided form — 3 ints/change, under v1's 5-int minimum.
        The header sanity checks must be version-aware or valid frames are
        rejected as corrupt (review finding r3)."""
        from peritext_tpu.api.batch import _oracle_doc
        from peritext_tpu.core.doc import Doc
        from peritext_tpu.parallel.codec import decode_frame, encode_frame
        from peritext_tpu.parallel.streaming import StreamingMerge

        d = Doc("alice")
        chs = []
        ch, _ = d.change([{"path": [], "action": "makeList", "key": "text"}])
        chs.append(ch)
        for i, c in enumerate("hello world"):
            ch, _ = d.change([{"path": ["text"], "action": "insert",
                              "index": i, "values": [c]}])
            chs.append(ch)
        f = encode_frame(chs)
        assert decode_frame(f) == chs
        s = StreamingMerge(num_docs=1, actors=("alice",), slot_capacity=64,
                           round_insert_capacity=32, round_delete_capacity=8,
                           round_mark_capacity=8)
        s.ingest_frames([(0, f)])
        s.drain()
        assert "".join(sp["text"] for sp in s.read(0)) == "hello world"
        assert not s.docs[0].fallback

    def test_deps_same_run_decodes_with_shared_mapping(self):
        """A sub-MB frame of DEPS_SAME headers over a 200-actor clock is
        VALID data (a big session's anti-entropy run, ADVICE r3 high) — it
        must decode, and in O(1) memory per change: the whole run shares one
        materialized dep mapping instead of 5000 copies of a 200-entry
        dict."""
        from wire import craft_frame

        from peritext_tpu.parallel.codec import decode_frame

        n_actors = 200
        strings = [f"actor-{i:03d}" for i in range(n_actors)]
        ints = []
        # change 1 (combo: actor 0, no flags): dseq=0, dstart=0, then a FULL
        # dep set naming every actor (establishes the stored dep_set), one
        # makeList op (kind 5 + REF_HEAD, opid/obj elided): [first, key=0]
        ints += [0 << 4, 0, 0, (n_actors << 2) | 0]
        for i in range(n_actors):
            ints += [i, 1]
        # first op carries an explicit ROOT obj (no previous op to elide to)
        ints += [1, 5 | ((1 | 8) << 3), 0, 0, 0, 0]
        # thousands of fully-elided single-op changes with DEPS_SAME: 3 ints
        # each, each reusing the 200-entry dep set at decode time
        n_spam = 5000
        for _ in range(n_spam):
            ints += [(0 << 4) | (1 | 2 | 4 | 8), 5 | ((1 | 2 | 8) << 3), 0]
        frame = craft_frame(strings, ints, 1 + n_spam, version=2)
        assert len(frame) < 100_000  # small wire decodes to 5001 changes
        decoded = decode_frame(frame)
        assert len(decoded) == 1 + n_spam
        expected = {f"actor-{i:03d}": 1 for i in range(n_actors)}
        assert dict(decoded[0].deps) == expected
        assert dict(decoded[-1].deps) == expected
        # the run shares ONE materialized mapping (no per-change copies)
        assert decoded[1].deps is decoded[2].deps is decoded[-1].deps

    def test_many_actor_deps_same_run_round_trips(self):
        """ADVICE r3 (high) repro: 120 actors, one actor emitting a 6000-
        change run with an unchanged clock.  Each clock encodes as DEPS_SAME
        (~0 wire ints) but legitimately materializes 120 dep entries — the
        decoder must accept its own encoder's output instead of calling it
        a budget attack."""
        from peritext_tpu.core.opids import ROOT
        from peritext_tpu.core.types import Operation

        actors = [f"peer-{i:03d}" for i in range(120)]
        clock = {a: 1 for a in actors}
        changes = []
        for k in range(1, 6001):
            deps = dict(clock)
            deps["writer"] = k - 1  # own dep: elided on the wire
            changes.append(Change(
                actor="writer", seq=k, deps=deps, start_op=k,
                ops=[Operation(action="set", obj=ROOT, opid=(k, "writer"),
                               key="m", value=k)],
            ))
        decoded = decode_frame(encode_frame(changes))
        assert decoded == changes

    def test_dep_hard_ceiling_still_rejects_quadratic_blowup(self, monkeypatch):
        """The scaled budget follows the frame's own actor table, so the
        absolute ceiling is what stops a many-strings × many-changes frame
        from quadratic expansion.  The charge lands BEFORE materialization:
        decode must raise without allocating the claimed entries.  (Ceiling
        patched down so the test stays fast; the mechanism is identical.)"""
        import pytest
        from wire import craft_frame

        from peritext_tpu.parallel import codec

        monkeypatch.setattr(codec, "_DEP_HARD_CEILING", 50_000)
        n_actors = 400
        strings = [f"actor-{i:03d}" for i in range(n_actors)]
        ints = [0 << 4, 0, 0, (n_actors << 2) | 0]
        for i in range(n_actors):
            ints += [i, 1]
        ints += [1, 5 | ((1 | 8) << 3), 0, 0, 0, 0]
        # delta-mode headers (count=0) force a fresh 400-entry materialization
        # per change — 300 of them claim 120K entries from ~1.5K wire ints
        n_spam = 300
        for _ in range(n_spam):
            ints += [(0 << 4) | (1 | 2 | 8), (0 << 2) | 2,
                     5 | ((1 | 2 | 8) << 3), 0]
        frame = craft_frame(strings, ints, 1 + n_spam, version=2)
        with pytest.raises(ValueError, match="decode budget"):
            decode_frame(frame)

    def test_encode_frame_chunks_round_trip(self, monkeypatch):
        """Sender-side guard (review r4): a backlog whose dep charge would
        approach the decode ceiling must split into multiple frames — a peer
        must never reject its counterpart's own legitimate encoder output.
        Each chunk stands alone, and the concatenation (the anti-entropy
        wire shape) round-trips via decode_frame_multi."""
        from peritext_tpu.core.opids import ROOT
        from peritext_tpu.core.types import Operation
        from peritext_tpu.parallel import codec

        monkeypatch.setattr(codec, "_ENCODE_CHUNK_CHARGE", 500)
        actors = [f"peer-{i:02d}" for i in range(40)]
        changes = []
        clock = {a: 1 for a in actors}
        for k in range(1, 101):
            clock = dict(clock)
            clock[f"peer-{k % 40:02d}"] = k  # drifting clock: no DEPS_SAME
            changes.append(Change(
                actor="writer", seq=k, deps=dict(clock), start_op=k,
                ops=[Operation(action="set", obj=ROOT, opid=(k, "writer"),
                               key="m", value=k)],
            ))
        chunks = codec.encode_frame_chunks(changes)
        assert len(chunks) > 1
        for c in chunks:
            codec.decode_frame(c)  # every chunk is a complete valid frame
        blob = b"".join(chunks)
        assert codec.decode_frame_multi(blob) == changes
        assert [len(f) for f in codec.iter_frames(blob)] == [len(c) for c in chunks]
        # single-frame payloads keep decoding through the multi entry point
        assert codec.decode_frame_multi(chunks[0]) == codec.decode_frame(chunks[0])
        with pytest.raises(ValueError):
            codec.decode_frame_multi(blob[:-3])  # truncated tail frame

    def test_native_walk_demotes_over_emission_budget(self, native_lib):
        """Native twin of the blowup guard (ADVICE r3 medium): walk_v2
        re-emits each change's stored dep set into flat output, so a frame
        of tiny DEPS_SAME headers otherwise forces ~n_declared entries per
        payload int through the host's capacity doubling.  Over-budget
        changes are demoted (ch_actor = -1 -> object path), the dep output
        stays payload-proportional, and the same frame still decodes fully
        on the object path."""
        from peritext_tpu.core.opids import ROOT
        from peritext_tpu.core.types import Operation
        from peritext_tpu.ops.packed import ACTOR_BITS, MAX_CTR
        from peritext_tpu.parallel.codec import frame_parts

        actors = [f"peer-{i:03d}" for i in range(400)]
        clock = {a: 1 for a in actors}
        changes = [Change(
            actor="writer", seq=k, deps=dict(clock), start_op=k,
            ops=[Operation(action="set", obj=ROOT, opid=(k, "writer"),
                           key="m", value=k)],
        ) for k in range(1, 3001)]
        frame = encode_frame(changes)
        strings, values, n_changes, version = frame_parts(frame)
        vals = np.asarray(values, np.int32)
        parsed = native.parse_changes(
            vals, n_changes,
            np.arange(len(strings), dtype=np.int32),  # all actors declared
            ACTOR_BITS, MAX_CTR, version=version,
        )
        ch_actor, _, dep_off, dep_actor = parsed[0], parsed[1], parsed[2], parsed[3]
        assert (ch_actor == -1).any()  # over-budget changes demoted
        assert len(dep_actor) <= 64 * len(vals) + 4096  # emission bounded
        # the data itself is valid: the object path decodes all of it
        decoded = decode_frame(frame)
        assert len(decoded) == 3000
        assert dict(decoded[-1].deps) == clock

    def test_wire_v1_frames_still_ingest(self):
        """v1 frames (old checkpoints, old peers) must keep decoding and
        taking the native fast path: the reader negotiates the version per
        frame.  The inline v1 writer below emits every op as a JSON-spill
        row — the simplest valid v1 layout (kind _OP_JSON + string id)."""
        from wire import craft_frame

        from peritext_tpu.api.batch import _oracle_doc
        from peritext_tpu.parallel.codec import _OP_JSON, decode_frame, encode_frame
        from peritext_tpu.parallel.streaming import StreamingMerge
        from peritext_tpu.testing.fuzz import generate_workload

        (wl,) = generate_workload(seed=31, num_docs=1, ops_per_doc=80)
        chs = [ch for log in wl.values() for ch in log]

        # v1 writer: the pre-delta layout (explicit obj/opid/ref per op)
        def v1_encode(changes):
            table = {}
            strings = []

            def intern(s):
                if s not in table:
                    table[s] = len(strings)
                    strings.append(s)
                return table[s]

            ints = []
            for c in changes:
                ints += [intern(c.actor), c.seq, c.start_op]
                deps = sorted((c.deps or {}).items())
                ints.append(len(deps))
                for a, s in deps:
                    ints += [intern(a), s]
                ints.append(len(c.ops))
                for op in c.ops:
                    ints += [_OP_JSON, intern(json.dumps(op.to_json()))]
            return craft_frame(strings, ints, len(changes), version=1)

        v1_frame = v1_encode(chs)
        assert decode_frame(v1_frame) == chs  # reader accepts v1

        expected = _oracle_doc(wl).get_text_with_formatting(["text"])
        for frame in (v1_frame, encode_frame(chs)):
            s = StreamingMerge(num_docs=1, actors=("doc1", "doc2", "doc3"),
                               slot_capacity=512, mark_capacity=128,
                               tomb_capacity=256, round_insert_capacity=128,
                               round_delete_capacity=64, round_mark_capacity=64)
            s.ingest_frames([(0, frame)])
            s.drain()
            assert s.read(0) == expected


class TestWireSession:
    """Session-scoped wire (v3/v4, VERDICT r3 task 3): persistent string
    dictionary + streaming deflate per peer link."""

    def _changes(self, lo, hi, url="https://example.com/a"):
        from peritext_tpu.core.opids import ROOT
        from peritext_tpu.core.types import Operation

        return [Change(
            actor="writer", seq=k, deps={"writer": k - 1, "peer": 1},
            start_op=k,
            ops=[Operation(action="set", obj=ROOT, opid=(k, "writer"),
                           key="m", value=url if k % 3 else k)],
        ) for k in range(lo, hi)]

    @pytest.mark.parametrize("compress", [False, True])
    def test_round_trip_and_string_reuse(self, compress):
        from peritext_tpu.parallel.codec import WireSession, encode_frame

        enc = WireSession(compress=compress)
        dec = WireSession(compress=compress)
        f1 = enc.encode_frame(self._changes(1, 40))
        f2 = enc.encode_frame(self._changes(40, 80))
        assert dec.decode_frame(f1) == self._changes(1, 40)
        assert dec.decode_frame(f2) == self._changes(40, 80)
        # second frame re-advertises nothing: strictly smaller than the
        # self-contained v2 encoding of the same changes
        assert len(f2) < len(encode_frame(self._changes(40, 80)))

    def test_normalized_frames_are_self_contained_v2(self):
        from peritext_tpu.parallel.codec import WireSession, decode_frame

        enc, dec = WireSession(compress=True), WireSession(compress=True)
        f1 = enc.encode_frame(self._changes(1, 20))
        f2 = enc.encode_frame(self._changes(20, 40))
        c1, v2a = dec.decode_frame_normalized(f1)
        c2, v2b = dec.decode_frame_normalized(f2)
        assert c1 == self._changes(1, 20) and c2 == self._changes(20, 40)
        # plain stateless decoder reads the normalized bytes
        assert decode_frame(v2a) == c1
        assert decode_frame(v2b) == c2

    def test_skipped_frame_detected_not_misresolved(self):
        from peritext_tpu.parallel.codec import WireSession

        enc, dec = WireSession(), WireSession()
        enc.encode_frame(self._changes(1, 20))        # frame 1 never delivered
        f2 = enc.encode_frame(self._changes(20, 40))
        with pytest.raises(ValueError, match="out of sync"):
            dec.decode_frame(f2)

    def test_epoch_reset_resyncs_decoder(self):
        from peritext_tpu.parallel.codec import WireSession

        enc = WireSession(reset_at=2)  # every frame overflows the dictionary
        dec = WireSession()
        for lo in (1, 30, 60):
            f = enc.encode_frame(self._changes(lo, lo + 20))
            assert dec.decode_frame(f) == self._changes(lo, lo + 20)

    def test_session_frames_rejected_outside_sessions(self):
        from peritext_tpu.parallel.codec import WireSession, decode_frame
        from peritext_tpu.parallel.streaming import StreamingMerge

        f = WireSession().encode_frame(self._changes(1, 10))
        with pytest.raises(ValueError, match="WireSession"):
            decode_frame(f)
        # the ingest path (storage format) rejects them identically
        s = StreamingMerge(num_docs=1, actors=("writer", "peer"))
        with pytest.raises(ValueError):
            s.ingest_frames([(0, f)])

    def test_inflate_bomb_bounded(self):
        import zlib

        from peritext_tpu.parallel.codec import _HEADER, _MAGIC, WireSession

        comp = zlib.compress(b"\x00" * (32 << 20), 6)  # 32MB of zeros
        frame = _HEADER.pack(_MAGIC, 4, 1, 0, 2, len(comp)) + comp
        dec = WireSession(compress=True)
        with pytest.raises(ValueError):
            dec.decode_frame(frame)

    def test_byte_flip_fuzz_raises_valueerror_only(self):
        import random

        from peritext_tpu.parallel.codec import WireSession

        rng = random.Random(9)
        base = self._changes(1, 30)
        for compress in (False, True):
            for _ in range(120):
                enc = WireSession(compress=compress)
                f = bytearray(enc.encode_frame(base))
                i = rng.randrange(len(f))
                f[i] ^= 1 << rng.randrange(8)
                dec = WireSession(compress=compress)
                try:
                    dec.decode_frame(bytes(f))
                except ValueError:
                    pass  # the only permitted failure mode

    def test_chunk_train_decodes_with_one_session(self, monkeypatch):
        from peritext_tpu.parallel import codec

        monkeypatch.setattr(codec, "_ENCODE_CHUNK_CHARGE", 100)
        changes = self._changes(1, 200)
        chunks = codec.encode_frame_chunks(
            changes, session=codec.WireSession(compress=True))
        assert len(chunks) > 2
        blob = b"".join(chunks)
        assert codec.decode_frame_multi(blob) == changes
        # chunks after the first carry no string table (dictionary reuse)
        assert codec._HEADER.unpack_from(chunks[1])[3] == 0

    def test_failed_decode_cannot_desync_session(self):
        """A decode error must roll the string table back — and with a
        deflate stream (whose consumed bytes cannot be un-fed) latch the
        session broken — so a retry can never silently misresolve ids
        (review r4)."""
        from peritext_tpu.parallel.codec import WireSession

        # plain v3: error rolls back, session stays usable
        enc, dec = WireSession(), WireSession()
        f1 = enc.encode_frame(self._changes(1, 20))
        with pytest.raises(ValueError):
            dec.decode_frame(f1 + b"JUNKJUNK")  # trailing garbage
        assert dec.decode_frame(f1) == self._changes(1, 20)  # recovered

        # v4: the inflate stream consumed bytes — session latches broken
        enc, dec = WireSession(compress=True), WireSession(compress=True)
        f1 = enc.encode_frame(self._changes(1, 20))
        f2 = enc.encode_frame(self._changes(20, 40))
        with pytest.raises(ValueError):
            dec.decode_frame(f1 + f2)  # a 2-frame train fed to decode_frame
        with pytest.raises(ValueError, match="broken"):
            dec.decode_frame(f1)

    def test_preset_dictionary_round_trip_and_saves_bytes(self):
        """Wire option ``preset`` (round 5, VERDICT r4 task 8): a fresh
        per-doc link primes its deflate window with the protocol dictionary
        (wire_preset.bin), recovering most of the shared-window advantage a
        host-link mux gets for free.  First-frame bytes must shrink vs a
        cold v4 link on session-shaped traffic."""
        from peritext_tpu.parallel.codec import WireSession

        from peritext_tpu.parallel.causal import causal_sort
        from peritext_tpu.testing.fuzz import generate_workload

        # session-shaped traffic (what the dictionary was trained for; the
        # synthetic map-set changes above share almost no byte patterns
        # with editing sessions and measure ~0 gain)
        wl = generate_workload(seed=5, num_docs=1, ops_per_doc=120)[0]
        chs = causal_sort([ch for log in wl.values() for ch in log])
        half = len(chs) // 2
        enc_p = WireSession(compress=True, preset=True)
        dec_p = WireSession(compress=True, preset=True)
        f_preset = enc_p.encode_frame(chs[:half])
        assert dec_p.decode_frame(f_preset) == chs[:half]
        f_cold = WireSession(compress=True).encode_frame(chs[:half])
        assert len(f_preset) < len(f_cold)
        # the link stays a normal v4 session afterwards
        f2 = enc_p.encode_frame(chs[half:])
        assert dec_p.decode_frame(f2) == chs[half:]

    def test_preset_mismatch_fails_closed(self):
        """preset is negotiated out-of-band like ``compress``; a mismatch
        must raise the corrupt-frame ValueError, never decode garbage."""
        from peritext_tpu.parallel.codec import WireSession

        chs = self._changes(1, 30)
        f = WireSession(compress=True, preset=True).encode_frame(chs)
        plain = WireSession(compress=True)
        with pytest.raises(ValueError, match="corrupt frame"):
            plain.decode_frame(f)
        # the reverse direction: preset decoder on a non-preset stream is
        # tolerated by zlib only if no dictionary was demanded — decode
        # must either succeed with identical changes or fail closed
        f2 = WireSession(compress=True).encode_frame(chs)
        dec = WireSession(compress=True, preset=True)
        try:
            assert dec.decode_frame(f2) == chs
        except ValueError:
            pass

    def test_preset_ignored_without_compress(self):
        from peritext_tpu.parallel.codec import WireSession

        s = WireSession(preset=True)
        assert s.preset is False  # preset is a deflate-window option
        chs = self._changes(1, 10)
        assert WireSession().decode_frame(s.encode_frame(chs)) == chs
