"""ProseMirror conformance suite (VERDICT r3 task 4).

The reference's L2 is a live ProseMirror plugin (src/bridge.ts:204-347); a
real PM bundle cannot run in this image (no node runtime, no network egress
to vendor one), so conformance is pinned at the WIRE level instead: the
fixtures in ``tests/pm_fixtures/`` are collaborative sessions whose edits
are authored byte-for-byte in the JSON ``prosemirror-transform`` emits
(``Step.toJSON()``: replace/addMark/removeMark with slices, marks and
1-based positions) and whose expected documents are ``Node.toJSON()`` of
the reference schema (src/schema.ts:45-96).  A real ProseMirror client
producing these exact payloads drives the bridge unchanged — these tests
replay them from JSON alone, against both the scalar and the tpu backend,
and assert the byte-equal converged document plus schema-valid outbound
patches (what the bridge would hand back to ``Step.fromJSON``)."""

import json
from pathlib import Path

import pytest

from peritext_tpu.bridge.bridge import create_editor, initialize_docs, patch_to_steps
from peritext_tpu.bridge.model import (
    AddMarkStep,
    EditorDoc,
    RemoveMarkStep,
    ReplaceStep,
    ResetStep,
    Transaction,
)
from peritext_tpu.bridge.pm import (
    PMFormatError,
    editor_doc_from_pm,
    editor_doc_to_pm,
    marks_from_pm,
    marks_to_pm,
    step_from_pm,
    step_to_pm,
    transaction_from_pm,
)
from peritext_tpu.parallel.pubsub import Publisher

FIXTURES = sorted((Path(__file__).parent / "pm_fixtures").glob("*.json"))
ACTORS = ("alice", "bob")


def validate_pm_step_json(step):
    """Structural validation against prosemirror-transform's wire schema."""
    assert isinstance(step, dict)
    assert step["stepType"] in ("replace", "addMark", "removeMark")
    assert isinstance(step["from"], int) and isinstance(step["to"], int)
    assert 0 < step["from"] <= step["to"]
    if step["stepType"] == "replace":
        assert set(step) <= {"stepType", "from", "to", "slice"}
        for node in step.get("slice", {}).get("content", []):
            assert node["type"] == "text" and isinstance(node["text"], str)
            for mark in node.get("marks", []):
                assert isinstance(mark["type"], str)
    else:
        assert set(step) <= {"stepType", "from", "to", "mark"}
        assert isinstance(step["mark"]["type"], str)


class TestStepJson:
    CASES = [
        ReplaceStep(3, 3, "hi"),
        ReplaceStep(1, 9),
        ReplaceStep(2, 5, "bold", {"strong": {"active": True}}),
        ReplaceStep(4, 4, "x", {"link": {"active": True, "url": "https://a"}}),
        AddMarkStep(1, 7, "strong"),
        AddMarkStep(2, 9, "link", {"url": "https://a"}),
        AddMarkStep(1, 4, "comment", {"id": "c1"}),
        RemoveMarkStep(3, 6, "em"),
        RemoveMarkStep(1, 4, "comment", {"id": "c1"}),
    ]

    @pytest.mark.parametrize("step", CASES, ids=lambda s: type(s).__name__)
    def test_round_trip_and_schema(self, step):
        pm = step_to_pm(step)
        validate_pm_step_json(pm)
        back = step_from_pm(pm)
        # attrs normalize to None <-> {} equivalently; compare via re-encode
        assert step_to_pm(back) == pm
        doc_a, doc_b = EditorDoc(), EditorDoc()
        doc_a.insert_at(0, "hello world brave")
        doc_b.insert_at(0, "hello world brave")
        step.apply(doc_a)
        back.apply(doc_b)
        assert doc_a == doc_b

    def test_reset_step_has_no_pm_form(self):
        with pytest.raises(PMFormatError):
            step_to_pm(ResetStep())

    @pytest.mark.parametrize("bad", [
        {"stepType": "replaceAround", "from": 1, "to": 2},
        {"stepType": "replace", "from": 0, "to": 2},      # pos 0 = doc token
        {"stepType": "replace", "from": 3, "to": 1},
        {"stepType": "replace", "from": 1, "to": 1,
         "slice": {"content": [{"type": "paragraph"}]}},  # block content
        {"stepType": "replace", "from": 1, "to": 1,
         "slice": {"content": [{"type": "text", "text": "x"}], "openStart": 1}},
        {"stepType": "addMark", "from": 1, "to": 2, "mark": {"attrs": {}}},
        {"stepType": "addMark", "from": 1, "to": 2, "mark": {"type": "blink"}},
    ])
    def test_malformed_rejected(self, bad):
        with pytest.raises(PMFormatError):
            step_from_pm(bad)


class TestMarkSetJson:
    def test_mark_map_round_trip(self):
        marks = {
            "strong": {"active": True},
            "link": {"active": True, "url": "https://a"},
            "comment": [{"id": "c1"}, {"id": "c2"}],
        }
        pm = marks_to_pm(marks)
        assert {m["type"] for m in pm} == {"strong", "link", "comment"}
        assert marks_from_pm(pm) == marks

    def test_add_to_set_semantics(self):
        # same-type mark replaces (PM Mark.addToSet); comments key by id
        pm = [{"type": "link", "attrs": {"url": "https://old"}},
              {"type": "link", "attrs": {"url": "https://new"}}]
        assert marks_from_pm(pm)["link"]["url"] == "https://new"
        pm = [{"type": "comment", "attrs": {"id": "c1"}},
              {"type": "comment", "attrs": {"id": "c1"}},
              {"type": "comment", "attrs": {"id": "c0"}}]
        assert marks_from_pm(pm)["comment"] == [{"id": "c0"}, {"id": "c1"}]


class TestDocJson:
    def test_doc_round_trip(self):
        doc = EditorDoc()
        doc.insert_at(0, "hello")
        doc.add_mark_at(0, 3, "strong", None)
        doc.add_mark_at(2, 5, "link", {"url": "https://a"})
        pm = editor_doc_to_pm(doc)
        assert pm["type"] == "doc" and pm["content"][0]["type"] == "paragraph"
        assert editor_doc_from_pm(pm) == doc

    def test_multi_paragraph_rejected(self):
        with pytest.raises(PMFormatError):
            editor_doc_from_pm({"type": "doc", "content": [
                {"type": "paragraph"}, {"type": "paragraph"}]})


def replay_fixture(spec, backend):
    pub = Publisher()
    kwargs = {"backend": backend, "actors": ACTORS} if backend == "tpu" else {}
    editors = {name: create_editor(name, pub, **kwargs) for name in ACTORS}
    initialize_docs(list(editors.values()), spec["initial"])
    outbound = []  # every patch-derived step the bridge would hand to PM
    for event in spec["events"]:
        if event.get("sync"):
            for ed in editors.values():
                ed.sync()
            continue
        ed = editors[event["editor"]]
        ed.dispatch(transaction_from_pm(event["steps"]))
    for ed in editors.values():
        ed.sync()
    return editors, outbound


@pytest.mark.parametrize("path", FIXTURES, ids=lambda p: p.stem)
def test_fixture_names_external_source(path):
    """Every fixture header documents its provenance (VERDICT r4 task 5):
    which published prosemirror-transform step construct its wire JSON
    follows, and which reference/Peritext-paper scenario it mirrors.  The
    expected documents remain pinned by this repo's own bridge replay —
    scripts/gen_pm_fixtures.py states why (no node runtime or egress to
    vendor upstream test files), and README "ProseMirror conformance"
    records exactly what a browser run would add."""
    spec = json.loads(path.read_text())
    src = spec.get("source", "")
    assert len(src) > 20, f"{path.stem}: missing provenance header"
    assert "prosemirror" in src.lower() or "Step" in src


@pytest.mark.parametrize("path", FIXTURES, ids=lambda p: p.stem)
@pytest.mark.parametrize("backend", ["scalar", "tpu"])
def test_fixture_sessions_converge(path, backend):
    """Replaying the recorded PM-wire transactions converges both editors to
    the fixture's expected ``Node.toJSON()`` document on BOTH backends."""
    spec = json.loads(path.read_text())
    editors, _ = replay_fixture(spec, backend)
    views = {n: editor_doc_to_pm(ed.view) for n, ed in editors.items()}
    assert views["alice"] == views["bob"]
    assert views["alice"] == spec["expected_doc"]
    assert editors["alice"].text == spec["expected_text"]


@pytest.mark.parametrize("path", FIXTURES, ids=lambda p: p.stem)
def test_fixture_outbound_patches_serialize_to_pm(path):
    """Every patch a replica emits while receiving the session translates
    into schema-valid PM step JSON — the ``Step.fromJSON`` feed a real PM
    client would apply for remote edits."""
    from peritext_tpu.core.doc import Doc
    from peritext_tpu.parallel.causal import causal_sort

    spec = json.loads(path.read_text())
    pub = Publisher()
    editors = {name: create_editor(name, pub) for name in ACTORS}
    changes = [initialize_docs(list(editors.values()), spec["initial"])]
    for event in spec["events"]:
        if event.get("sync"):
            for ed in editors.values():
                ed.sync()
            continue
        ed = editors[event["editor"]]
        changes.append(ed.dispatch(transaction_from_pm(event["steps"])))
    for ed in editors.values():
        ed.sync()

    captured = []
    observer = Doc("observer")
    for ch in causal_sort(changes):
        for patch in observer.apply_change(ch):
            for step in patch_to_steps(patch):
                if not isinstance(step, ResetStep):
                    captured.append(step_to_pm(step))
    assert captured, "no outbound patches captured"
    for pm_step in captured:
        validate_pm_step_json(pm_step)
    # and the observer's document serializes to the same expected PM doc
    from peritext_tpu.bridge.bridge import editor_doc_from_crdt

    assert editor_doc_to_pm(editor_doc_from_crdt(observer)) == spec["expected_doc"]


class TestPresentationSchema:
    """The presentation half of the reference markSpec (src/schema.ts:45-96):
    excludes and toDOM, modeled so a real PM schema can be built from
    peritext_tpu.schema."""

    def test_excludes_defaults_and_comment_override(self):
        from peritext_tpu.schema import excludes_of

        assert excludes_of("strong") == ("strong",)  # PM default: own type
        assert excludes_of("link") == ("link",)
        assert excludes_of("comment") == ()  # schema.ts:77 excludes: ""

    def test_mark_to_dom_shapes(self):
        from peritext_tpu.schema import mark_to_dom

        assert mark_to_dom("strong") == ["strong"]
        assert mark_to_dom("em") == ["em"]
        a = mark_to_dom("link", {"url": "https://a"})
        assert a[0] == "a" and a[1]["href"] == "https://a"
        assert a[1]["style"].startswith("color: #")
        # per-url color is deterministic and url-dependent
        assert mark_to_dom("link", {"url": "https://a"}) == a
        assert mark_to_dom("link", {"url": "https://b"}) != a
        c = mark_to_dom("comment", {"id": "c1"})
        assert c == ["span", {"data-mark": "comment", "data-comment-id": "c1"}]

    def test_add_to_set_honors_excludes(self):
        from peritext_tpu.bridge.model import _add_mark_to_map

        # same-type add replaces (default excludes), other types coexist
        m = _add_mark_to_map({}, "link", {"url": "https://old"})
        m = _add_mark_to_map(m, "strong", None)
        m = _add_mark_to_map(m, "link", {"url": "https://new"})
        assert m["link"]["url"] == "https://new" and "strong" in m
        # comments exclude nothing: they stack with themselves and others
        m = _add_mark_to_map(m, "comment", {"id": "c1"})
        m = _add_mark_to_map(m, "comment", {"id": "c2"})
        assert [e["id"] for e in m["comment"]] == ["c1", "c2"]
        assert "link" in m and "strong" in m

    def test_cross_type_excludes_both_directions(self, monkeypatch):
        """A custom spec whose excludes names ANOTHER type follows PM
        Mark.addToSet in both directions: the new mark evicts types it
        excludes, and an existing mark that excludes the new type rejects
        the add."""
        from peritext_tpu import schema
        from peritext_tpu.bridge.model import _add_mark_to_map

        spec = dict(schema.MARK_SPEC)
        spec["strong"] = schema.MarkSchema(
            inclusive=True, allow_multiple=False, excludes=("strong", "em"))
        monkeypatch.setattr(schema, "MARK_SPEC", spec)

        # adding strong evicts an existing em...
        m = _add_mark_to_map({}, "em", None)
        m = _add_mark_to_map(m, "strong", None)
        assert "em" not in m and "strong" in m
        # ...and an existing strong rejects a later em add
        m2 = _add_mark_to_map({}, "strong", None)
        m2 = _add_mark_to_map(m2, "em", None)
        assert "em" not in m2 and "strong" in m2
