"""Patch emission from the batched path: identity-keyed host diff.

Oracle: accumulate_patches (the reference's naive patch-replay model) over
the emitted stream must reproduce the target state's spans exactly.
"""

import pytest

from peritext_tpu.api.batch import _oracle_doc
from peritext_tpu.ops.patches import (
    as_insert_patches,
    diff_patches,
    doc_chars_scalar,
)
from peritext_tpu.parallel.codec import encode_frame
from peritext_tpu.parallel.streaming import StreamingMerge
from peritext_tpu.testing.accumulate import accumulate_patches
from peritext_tpu.testing.fuzz import generate_workload
from peritext_tpu.testing.generate import generate_docs

ACTORS = ("doc1", "doc2", "doc3")


def _spans_of(chars):
    """Span form of a CharState list via the accumulate oracle."""
    return accumulate_patches(as_insert_patches(chars))


def _assert_diff_replays(before, after):
    patches = as_insert_patches(before) + diff_patches(before, after)
    assert accumulate_patches(patches) == _spans_of(after)
    return diff_patches(before, after)


def test_pure_insert_and_delete():
    a = [((1, "a"), "h", {}), ((2, "a"), "i", {})]
    b = [((1, "a"), "h", {}), ((3, "b"), "e", {}), ((2, "a"), "i", {})]
    patches = _assert_diff_replays(a, b)
    assert patches == [
        {"action": "insert", "path": ["text"], "index": 1, "values": ["e"], "marks": {}}
    ]
    patches = _assert_diff_replays(b, a)
    assert patches == [{"action": "delete", "path": ["text"], "index": 1, "count": 1}]


def test_replace_and_mark_changes():
    strong = {"strong": {"active": True}}
    a = [((1, "a"), "x", {}), ((2, "a"), "y", {}), ((3, "a"), "z", {})]
    b = [((1, "a"), "x", strong), ((4, "b"), "q", strong), ((3, "a"), "z", {})]
    patches = _assert_diff_replays(a, b)
    actions = [p["action"] for p in patches]
    assert actions == ["delete", "insert", "addMark"]
    assert patches[2] == {
        "action": "addMark", "path": ["text"],
        "startIndex": 0, "endIndex": 1, "markType": "strong",
    }


def test_mark_runs_merge_contiguously():
    strong = {"strong": {"active": True}}
    a = [((i, "a"), "x", {}) for i in range(1, 6)]
    b = [(cid, ch, strong) for cid, ch, _ in a]
    patches = _assert_diff_replays(a, b)
    assert patches == [
        {"action": "addMark", "path": ["text"],
         "startIndex": 0, "endIndex": 5, "markType": "strong"}
    ]


def test_link_value_change_and_comment_sets():
    l1 = {"link": {"active": True, "url": "https://a"}}
    l2 = {"link": {"active": True, "url": "https://b"}}
    c1 = {"comment": [{"id": "c1"}]}
    c12 = {"comment": [{"id": "c1"}, {"id": "c2"}]}
    a = [((1, "a"), "x", l1), ((2, "a"), "y", c1)]
    b = [((1, "a"), "x", l2), ((2, "a"), "y", c12)]
    patches = _assert_diff_replays(a, b)
    assert {"action": "addMark", "path": ["text"], "startIndex": 0, "endIndex": 1,
            "markType": "link", "attrs": {"url": "https://b"}} in patches
    assert {"action": "addMark", "path": ["text"], "startIndex": 1, "endIndex": 2,
            "markType": "comment", "attrs": {"id": "c2"}} in patches
    # and removal
    patches = _assert_diff_replays(b, a)
    assert {"action": "addMark", "path": ["text"], "startIndex": 0, "endIndex": 1,
            "markType": "link", "attrs": {"url": "https://a"}} in patches
    assert {"action": "removeMark", "path": ["text"], "startIndex": 1, "endIndex": 2,
            "markType": "comment", "attrs": {"id": "c2"}} in patches


def test_scalar_chars_roundtrip():
    docs, _, initial = generate_docs("hello world", 2)
    d1, _ = docs
    d1.change([{"path": ["text"], "action": "addMark", "startIndex": 0,
                "endIndex": 5, "markType": "strong"}])
    chars = doc_chars_scalar(d1)
    assert _spans_of(chars) == d1.get_text_with_formatting(["text"])


@pytest.fixture(scope="module")
def workloads():
    return generate_workload(seed=91, num_docs=3, ops_per_doc=110)


def _session(num_docs):
    return StreamingMerge(
        num_docs=num_docs, actors=ACTORS, slot_capacity=512, mark_capacity=128,
        round_insert_capacity=128, round_delete_capacity=64, round_mark_capacity=64,
    )


def test_streaming_incremental_patches_accumulate_to_final(workloads):
    import random

    rng = random.Random(5)
    sess = _session(len(workloads))
    streams = {d: [] for d in range(len(workloads))}
    arrivals = []
    for d, w in enumerate(workloads):
        changes = [ch for log in w.values() for ch in log]
        rng.shuffle(changes)
        arrivals.append([changes[i : i + 13] for i in range(0, len(changes), 13)])
    rounds = max(len(a) for a in arrivals)
    for r in range(rounds):
        for d, batches in enumerate(arrivals):
            if r < len(batches):
                sess.ingest_frame(d, encode_frame(batches[r]))
        sess.drain()
        for d in range(len(workloads)):
            streams[d].extend(sess.read_patches(d))

    for d, w in enumerate(workloads):
        expected = _oracle_doc(w).get_text_with_formatting(["text"])
        assert accumulate_patches(streams[d]) == expected, f"doc {d}"
        assert sess.read_patches(d) == []  # quiescent: no spurious patches


def test_streaming_patches_across_fallback_demotion():
    """A doc that demotes mid-session keeps emitting consistent patches:
    identities are (ctr, actor) on both the device and scalar paths, so the
    post-demotion diff is incremental, not a delete-all/re-insert."""
    docs, _, initial = generate_docs("hello world", 1)
    (d1,) = docs
    sess = _session(1)
    sess.ingest_frame(0, encode_frame([initial]))
    sess.drain()
    stream = sess.read_patches(0)  # device path
    assert not sess.docs[0].fallback

    c1, _ = d1.change(
        [{"path": ["text"], "action": "insert", "index": 11, "values": list("!")},
         {"path": ["text"], "action": "addMark", "startIndex": 0, "endIndex": 5,
          "markType": "em"}]
    )
    # a float value is inexpressible on device: the demotion trigger
    # (makeMap itself now rides the device map-register path)
    c2, _ = d1.change([{"path": [], "action": "set", "key": "r", "value": 0.5}])
    sess.ingest_frame(0, encode_frame([c1, c2]))  # inexpressible op: demotes
    sess.drain()
    assert sess.docs[0].fallback
    increment = sess.read_patches(0)  # scalar path
    # incremental, not a rebuild: no delete of the surviving prefix
    assert not any(p["action"] == "delete" for p in increment)
    assert accumulate_patches(stream + increment) == d1.get_text_with_formatting(
        ["text"]
    )
