"""Telemetry integration tests (ISSUE 3 acceptance): deadline autotuning
from the rolling round-latency percentile, cross-host trace propagation via
the wire-carried context (frame v5 + frontier sentinels with old-peer
compatibility), and streaming per-round MergeStats."""

import json
import socket
import time

import pytest

from peritext_tpu.obs import TraceContext, Tracer, merge_traces
from peritext_tpu.parallel.anti_entropy import ChangeStore
from peritext_tpu.parallel.codec import (
    decode_frame,
    decode_frame_traced,
    encode_frame,
    encode_frame_traced,
    strip_trace_context,
)
from peritext_tpu.parallel.multihost import (
    ReplicaServer,
    _meta_ctx,
    _parse_frontier,
    _recv_message,
    _send_changes,
    sync_with,
)
from peritext_tpu.parallel.supervisor import GuardedSession
from peritext_tpu.testing.fuzz import _campaign_session, generate_workload

DOCS, OPS = 3, 25


def _changes(seed=11, doc=0):
    workload = generate_workload(seed, num_docs=DOCS, ops_per_doc=OPS)[doc]
    return [ch for log in workload.values() for ch in log]


# ---------------------------------------------------------------------------
# deadline autotuning (closes ROADMAP "supervisor deadline autotuning")
# ---------------------------------------------------------------------------


class TestDeadlineAutotune:
    def _guarded(self, tmp_path, **kw):
        kw.setdefault("deadline", 30.0)
        kw.setdefault("deadline_floor", 1.0)
        kw.setdefault("deadline_ceiling", 8.0)
        kw.setdefault("deadline_margin", 2.0)
        kw.setdefault("deadline_window", 8)
        kw.setdefault("checkpoint_every", 10_000)
        return GuardedSession(lambda: _campaign_session(1, OPS), tmp_path, **kw)

    def test_first_round_compile_exempt(self, tmp_path):
        guarded = self._guarded(tmp_path)
        assert guarded.effective_deadline() == 8.0  # no data: ceiling
        guarded.inject_delay(0.3)  # a "slow compile" first round
        guarded.step()
        # warmup-exempt: the slow first round never enters the window
        assert guarded.round_latency.count == 0
        assert guarded.effective_deadline() == 8.0

    def test_deadline_adapts_within_floor_and_ceiling(self, tmp_path):
        guarded = self._guarded(tmp_path)
        guarded.step()  # warmup (exempt)
        for _ in range(6):
            guarded.step()  # fast empty rounds
        assert guarded.round_latency.count == 6
        fast = guarded.effective_deadline()
        # fast rounds clamp at (or near) the floor, well under the ceiling
        assert guarded.deadline_floor <= fast < guarded.deadline_ceiling
        # slow rounds (under the current deadline, so they complete and are
        # observed) push the rolling percentile — the deadline rises
        for _ in range(4):
            guarded.inject_delay(0.6)
            guarded.step()
        tuned = guarded.effective_deadline()
        assert tuned >= 2.0  # 2x margin on the 0.6s rounds' bucket
        assert tuned > fast
        assert guarded.deadline_floor <= tuned <= guarded.deadline_ceiling
        health = guarded.health()
        assert health["deadline_autotuned"] is True
        assert health["deadline_seconds"] == pytest.approx(tuned)
        assert health["deadline_static"] == 30.0
        assert health["round_latency"]["count"] == guarded.round_latency.count

    def test_watchdog_fires_at_the_tuned_deadline(self, tmp_path):
        """The acceptance oracle: the watchdog trips at the DERIVED deadline
        — far below the static constant — and the ladder still recovers."""
        guarded = self._guarded(tmp_path, deadline_floor=0.5,
                                deadline_ceiling=8.0)
        guarded.step()  # warmup
        for _ in range(5):
            guarded.step()  # fast rounds: effective ~= floor
        tuned = guarded.effective_deadline()
        assert tuned < 3.0  # comfortably under both ceiling and static 30s
        from peritext_tpu.obs import GLOBAL_HISTOGRAMS

        exported = GLOBAL_HISTOGRAMS.get("supervisor.round_seconds")
        count_before = exported.count
        guarded.inject_delay(3.2)  # over the tuned deadline, under static
        assert guarded.step() == 0  # watchdog fired -> rollback, contained
        assert guarded.rollbacks == 1
        # the failed round was not observed by AUTOTUNE (window unchanged)…
        assert guarded.effective_deadline() == tuned
        # …but the exported fleet histogram saw it: deadline-hit rounds are
        # the worst case operators size the static ceiling from
        assert exported.count == count_before + 1
        assert exported.snapshot()["max"] >= tuned

    def test_stage_spans_nest_under_guarded_round(self, tmp_path):
        """The watchdog runs the round body on a worker thread; the
        session's stage spans must still parent under supervisor.round so
        flight-recorder dumps reconstruct a NESTED stage timeline."""
        from peritext_tpu.parallel.codec import encode_frame

        tracer = Tracer(host="nesting", enabled=True)
        guarded = self._guarded(tmp_path, tracer=tracer)
        guarded.ingest_frame(0, encode_frame(_changes()))
        guarded.step()
        spans = {s.name: s for s in tracer.spans()}
        round_sp = spans["supervisor.round"]
        assert spans["streaming.round"].parent_id == round_sp.span_id
        assert spans["streaming.round"].trace_id == round_sp.trace_id
        assert spans["streaming.schedule"].parent_id == spans[
            "streaming.round"
        ].span_id

    def test_autotune_off_keeps_static_behavior(self, tmp_path):
        guarded = self._guarded(tmp_path, autotune=False)
        for _ in range(8):
            guarded.step()
        assert guarded.effective_deadline() == guarded.deadline_ceiling

    def test_warmup_rounds_still_export_to_global_histogram(self, tmp_path):
        """The warmup exemption scopes the AUTOTUNE window only: the fleet
        histogram must see every round, compile-dominated first ones
        included (operators size the static ceiling from the true max)."""
        from peritext_tpu.obs import GLOBAL_HISTOGRAMS

        hist = GLOBAL_HISTOGRAMS.get("supervisor.round_seconds")
        before = hist.count
        guarded = self._guarded(tmp_path)
        guarded.step()  # warmup round: autotune-exempt, still exported
        assert hist.count == before + 1
        assert guarded.round_latency.count == 0

    def test_close_detaches_recorder_sink_from_shared_tracer(self, tmp_path):
        tracer = Tracer(host="shared")
        guarded = self._guarded(tmp_path, tracer=tracer)
        guarded.step()
        size_before = guarded.recorder.snapshot()["size"]
        assert size_before > 0  # the sink was live
        guarded.close()
        with tracer.span("after-close"):
            pass
        assert guarded.recorder.snapshot()["size"] == size_before


# ---------------------------------------------------------------------------
# cross-host trace propagation
# ---------------------------------------------------------------------------


class TestCrossHostTrace:
    def test_two_hosts_share_one_trace_id(self):
        """Acceptance: a two-ReplicaServer sync produces a single merged
        Perfetto trace where both hosts' spans share one trace id via the
        wire-carried context."""
        store_a, store_b = ChangeStore(), ChangeStore()
        for ch in _changes():
            store_a.append(ch)
        tracer_a = Tracer(host="hostA", enabled=True, trace_id=0xA11CE)
        tracer_b = Tracer(host="hostB", enabled=True, trace_id=0xB0B)
        server_a = ReplicaServer(store_a, tracer=tracer_a)
        server_b = ReplicaServer(store_b, tracer=tracer_b)
        server_a.start()
        host, port = server_b.start()
        try:
            pulled, pushed = server_a.sync_with(host, port)
            assert pushed > 0
            deadline = time.time() + 5
            while time.time() < deadline:  # the handler thread finishes async
                if any(s.name == "anti-entropy.serve" for s in tracer_b.spans()):
                    break
                time.sleep(0.02)
        finally:
            server_a.stop()
            server_b.stop()
        (sync_span,) = [
            s for s in tracer_a.spans() if s.name == "anti-entropy.sync"
        ]
        (serve_span,) = [
            s for s in tracer_b.spans() if s.name == "anti-entropy.serve"
        ]
        # hostB's handler joined hostA's trace, as a child of the sync span
        assert serve_span.trace_id == sync_span.trace_id == 0xA11CE
        assert serve_span.parent_id == sync_span.span_id
        assert serve_span.args["pulled"] == len(_changes())
        merged = merge_traces(tracer_a.chrome_trace(), tracer_b.chrome_trace())
        exchange = [
            e for e in merged["traceEvents"]
            if e.get("ph") == "X" and e["name"].startswith("anti-entropy.")
        ]
        assert {e["args"]["host"] for e in exchange} == {"hostA", "hostB"}
        assert {e["args"]["trace_id"] for e in exchange} == {f"{0xA11CE:016x}"}
        json.dumps(merged)

    def test_client_delivery_joins_trace_via_frame_context(self):
        """The v5 frame field is load-bearing on the CLIENT side: delivery
        runs after the sync span closed, so the consumer's spans link into
        the exchange's trace through the frame-carried context — the
        delivery span parents under the SERVER's handler span."""
        store_a, store_b = ChangeStore(), ChangeStore()
        for ch in _changes():  # server has the backlog; client pulls
            store_b.append(ch)
        tracer_a = Tracer(host="hostA", enabled=True, trace_id=0xA11CE)
        tracer_b = Tracer(host="hostB", enabled=True, trace_id=0xB0B)
        server_b = ReplicaServer(store_b, tracer=tracer_b)
        host, port = server_b.start()
        delivered = []
        try:
            pulled, _ = sync_with(
                store_a, host, port, tracer=tracer_a,
                on_changes=delivered.extend,
            )
            assert pulled > 0 and delivered
            deadline = time.time() + 5
            while time.time() < deadline:
                if any(s.name == "anti-entropy.serve" for s in tracer_b.spans()):
                    break
                time.sleep(0.02)
        finally:
            server_b.stop()
        (serve,) = [s for s in tracer_b.spans() if s.name == "anti-entropy.serve"]
        (deliver,) = [
            s for s in tracer_a.spans() if s.name == "anti-entropy.deliver"
        ]
        assert deliver.trace_id == 0xA11CE  # the whole exchange: one trace
        assert deliver.parent_id == serve.span_id  # linked by the v5 field

    def test_store_clocks_stay_clean_of_metadata(self):
        """The frontier sentinels are transport metadata: after a traced
        sync both stores' clocks hold actors only."""
        store_a, store_b = ChangeStore(), ChangeStore()
        for ch in _changes():
            store_a.append(ch)
        server = ReplicaServer(store_b, tracer=Tracer(host="b", enabled=True))
        host, port = server.start()
        try:
            sync_with(store_a, host, port, tracer=Tracer(host="a", enabled=True))
            deadline = time.time() + 5
            while time.time() < deadline and store_b.clock() != store_a.clock():
                time.sleep(0.02)
        finally:
            server.stop()
        assert store_b.clock() == store_a.clock()
        assert all(not a.startswith("\x00") for a in store_a.clock())
        assert all(not a.startswith("\x00") for a in store_b.clock())


# ---------------------------------------------------------------------------
# wire negotiation + v5 frames
# ---------------------------------------------------------------------------


class TestWireNegotiation:
    def test_frontier_metadata_roundtrip_and_old_form(self):
        clock, meta = _parse_frontier(json.dumps({"actor": 3}).encode())
        assert clock == {"actor": 3} and meta == {}  # pre-caps peers
        body = json.dumps({
            "actor": 3, "\x00caps": 5, "\x00trace": 0xA, "\x00span": 7,
        }).encode()
        clock, meta = _parse_frontier(body)
        assert clock == {"actor": 3}
        assert meta == {"caps": 5, "trace": 0xA, "span": 7}
        assert _meta_ctx(meta) == TraceContext(0xA, 7)
        assert _meta_ctx({"caps": 5}) is None

    def test_v5_sent_only_to_capable_peers(self):
        changes = _changes()[:5]
        ctx = TraceContext(0x123, 9)
        for caps, version in ((0, 2), (4, 2), (5, 5)):
            a, b = socket.socketpair()
            try:
                _send_changes(a, changes, peer_caps=caps, ctx=ctx)
                _, body = _recv_message(b)
                assert body[4] == version, f"caps={caps}"
                assert decode_frame(body) == changes
            finally:
                a.close()
                b.close()

    def test_traced_frame_roundtrip_and_strip(self):
        changes = _changes()[:8]
        plain = encode_frame(changes)
        traced = encode_frame_traced(changes, 0xFEED, 21)
        assert decode_frame(traced) == changes
        got, ctx = decode_frame_traced(traced)
        assert got == changes and ctx == (0xFEED, 21)
        ctx, stripped = strip_trace_context(traced)
        assert stripped == plain and ctx == (0xFEED, 21)
        assert strip_trace_context(plain) == (None, plain)

    def test_streaming_ingest_adopts_frame_context(self):
        """A traced frame arriving at a session links that session's ingest
        span into the sender's trace, and the doc converges identically."""
        from peritext_tpu.api.batch import _oracle_doc

        workload = generate_workload(11, num_docs=DOCS, ops_per_doc=OPS)[0]
        changes = [ch for log in workload.values() for ch in log]
        sess = _campaign_session(1, OPS)
        tracer = Tracer(host="ingestor", enabled=True)
        sess.tracer = tracer
        sess.ingest_frame(0, encode_frame_traced(changes, 0x77, 9))
        sess.drain()
        (ingest,) = [s for s in tracer.spans() if s.name == "streaming.ingest"]
        assert ingest.trace_id == 0x77 and ingest.parent_id == 9
        assert sess.read(0) == _oracle_doc(workload).get_text_with_formatting(
            ["text"]
        )


# ---------------------------------------------------------------------------
# streaming per-round MergeStats (satellite)
# ---------------------------------------------------------------------------


class TestStreamingRoundStats:
    def test_round_stats_and_padding_surface(self):
        sess = _campaign_session(DOCS, OPS)
        assert sess.last_round_stats is None
        assert sess.health()["round_padding_efficiency"] is None
        for d in range(DOCS):
            sess.ingest_frame(d, encode_frame(_changes(doc=d)))
        sess.drain()
        stats = sess.last_round_stats
        assert stats is not None
        assert stats.device_ops > 0
        assert 0.0 < stats.padding_efficiency <= 1.0
        assert stats.extras["rounds"] >= 1
        assert stats.encode_seconds > 0 and stats.apply_seconds > 0
        health = sess.health()
        assert health["round_padding_efficiency"] == pytest.approx(
            stats.padding_efficiency, abs=1e-4
        )
        assert 0.0 < health["padding_efficiency_cum"] <= 1.0
        json.dumps(health)
