"""Streaming merge tests (BASELINE config 5): incremental rounds on carried
device state must equal one-shot oracle replay; static round widths defer
excess; fallbacks replay; sharded sessions agree via the digest collective."""

import random

import numpy as np
import pytest

from peritext_tpu.api.batch import oracle_merge
from peritext_tpu.parallel.mesh import make_mesh
from peritext_tpu.parallel.streaming import StreamingMerge, rebalance
from peritext_tpu.testing.fuzz import generate_workload

ACTORS = ("doc1", "doc2", "doc3")


def interleave_rounds(workload, rounds, rng):
    """Split one doc's change logs into `rounds` arrival batches (shuffled
    within a batch — delivery order must not matter)."""
    changes = [ch for log in workload.values() for ch in log]
    rng.shuffle(changes)
    size = -(-len(changes) // rounds)
    return [changes[i : i + size] for i in range(0, len(changes), size)]


class TestIncrementalEqualsOracle:
    @pytest.mark.parametrize("rounds", [1, 4])
    def test_multi_round_convergence(self, rounds):
        rng = random.Random(0)
        workloads = generate_workload(seed=31, num_docs=8, ops_per_doc=40)
        session = StreamingMerge(
            num_docs=8,
            actors=ACTORS,
            round_insert_capacity=256,
            round_delete_capacity=128,
            round_mark_capacity=128,
        )
        arrival = [interleave_rounds(w, rounds, rng) for w in workloads]
        for r in range(rounds):
            for d, batches in enumerate(arrival):
                if r < len(batches):
                    session.ingest(d, batches[r])
            session.drain()
        assert session.pending_count() == 0
        assert session.read_all() == oracle_merge(workloads)

    def test_tiny_round_widths_defer_and_still_converge(self):
        rng = random.Random(1)
        workloads = generate_workload(seed=7, num_docs=4, ops_per_doc=30)
        session = StreamingMerge(
            num_docs=4,
            actors=ACTORS,
            round_insert_capacity=8,
            round_delete_capacity=8,
            round_mark_capacity=8,
        )
        for d, w in enumerate(workloads):
            batches = interleave_rounds(w, 1, rng)
            session.ingest(d, batches[0])
        rounds = session.drain()
        assert rounds > 1  # the narrow widths forced multiple rounds
        assert session.read_all() == oracle_merge(workloads)

    def test_duplicate_ingestion_idempotent(self):
        rng = random.Random(2)
        workloads = generate_workload(seed=3, num_docs=2, ops_per_doc=25)
        session = StreamingMerge(num_docs=2, actors=ACTORS)
        for d, w in enumerate(workloads):
            changes = [ch for log in w.values() for ch in log]
            session.ingest(d, changes)
            session.ingest(d, list(changes))  # full duplicate delivery
        session.drain()
        assert session.read_all() == oracle_merge(workloads)


class TestFallbacks:
    def test_undeclared_actor_falls_back_to_replay(self):
        workloads = generate_workload(seed=5, num_docs=2, ops_per_doc=25)
        session = StreamingMerge(num_docs=2, actors=("doc1",))  # missing doc2/3
        for d, w in enumerate(workloads):
            session.ingest(d, [ch for log in w.values() for ch in log])
        session.drain()
        assert all(s.fallback for s in session.docs)
        assert session.read_all() == oracle_merge(workloads)

    def test_device_overflow_falls_back_to_replay(self):
        workloads = generate_workload(seed=6, num_docs=2, ops_per_doc=60)
        session = StreamingMerge(
            num_docs=2, actors=ACTORS, slot_capacity=16, tomb_capacity=8, mark_capacity=8
        )
        for d, w in enumerate(workloads):
            session.ingest(d, [ch for log in w.values() for ch in log])
        session.drain()
        assert bool(np.asarray(session.state.overflow).any())
        assert session.read_all() == oracle_merge(workloads)


class TestShardedStreaming:
    def test_mesh_session_matches_oracle_and_digest_agrees(self):
        workloads = generate_workload(seed=8, num_docs=16, ops_per_doc=30)
        mesh = make_mesh(8)
        rng = random.Random(3)

        def run_session(order_seed):
            r = random.Random(order_seed)
            s = StreamingMerge(num_docs=16, actors=ACTORS, mesh=mesh)
            for d, w in enumerate(workloads):
                batches = interleave_rounds(w, 3, r)
                for b in batches:
                    s.ingest(d, b)
                    s.drain()
            return s

        s1, s2 = run_session(1), run_session(2)
        assert s1.read_all() == oracle_merge(workloads)
        # different ingestion orders, same fixpoint: digests agree (with the
        # mesh this reduction is an XLA all-reduce across the 8 shards)
        assert s1.digest() == s2.digest()

    def test_frontier_merged(self):
        workloads = generate_workload(seed=9, num_docs=2, ops_per_doc=20)
        session = StreamingMerge(num_docs=2, actors=ACTORS)
        for d, w in enumerate(workloads):
            session.ingest(d, [ch for log in w.values() for ch in log])
        session.drain()
        frontier = session.frontier()
        assert set(frontier) <= set(ACTORS) and max(frontier.values()) > 0


class TestRebalance:
    def test_greedy_balance(self):
        sizes = [100, 1, 1, 1, 97, 1, 1, 1]
        shards = rebalance(sizes, 2)
        loads = [sum(sizes[i] for i in s) for s in shards]
        assert abs(loads[0] - loads[1]) <= 4
        assert sorted(i for s in shards for i in s) == list(range(8))


def test_object_path_oversized_change_demotes_not_wedges():
    """A single change exceeding a round width can never be admitted; the
    object path must demote to scalar replay like the frame path does."""
    from peritext_tpu.api.batch import _oracle_doc
    from peritext_tpu.testing.generate import generate_docs

    docs, _, initial = generate_docs("x", 1)
    (d1,) = docs
    big, _ = d1.change(
        [{"path": ["text"], "action": "insert", "index": 1, "values": list("y" * 100)}]
    )
    sess = StreamingMerge(
        num_docs=1, actors=("doc1",), slot_capacity=256, round_insert_capacity=32
    )
    sess.ingest(0, [initial, big])
    rounds = sess.drain()
    assert rounds < 10
    assert sess.docs[0].fallback
    assert sess.pending_count() == 0
    w = {"doc1": [initial, big]}
    assert sess.read(0) == _oracle_doc(w).get_text_with_formatting(["text"])


def test_streaming_cursor_resolution_matches_oracle():
    import random

    from peritext_tpu.api.batch import _oracle_doc
    from peritext_tpu.parallel.codec import encode_frame
    from peritext_tpu.testing.fuzz import generate_workload

    rng = random.Random(4)
    workloads = generate_workload(seed=140, num_docs=3, ops_per_doc=100)
    sess = StreamingMerge(
        num_docs=3, actors=("doc1", "doc2", "doc3"), slot_capacity=512,
        mark_capacity=128, round_insert_capacity=128,
        round_delete_capacity=64, round_mark_capacity=64,
    )
    for d, w in enumerate(workloads):
        sess.ingest_frame(d, encode_frame([ch for log in w.values() for ch in log]))
    sess.drain()
    for d, w in enumerate(workloads):
        doc = _oracle_doc(w)
        n = sum(len(s["text"]) for s in doc.get_text_with_formatting(["text"]))
        if not n:
            continue
        cursors = [doc.get_cursor(["text"], rng.randrange(n)) for _ in range(5)]
        expected = [doc.resolve_cursor(c) for c in cursors]
        assert sess.resolve_cursors(d, cursors) == expected, f"doc {d}"
    # unknown element -> -1
    bogus = {"objectId": (1, "doc1"), "elemId": (99999, "nowhere")}
    assert sess.resolve_cursors(0, [bogus]) == [-1]


def test_streaming_cursor_resolution_on_fallback_doc():
    from peritext_tpu.api.batch import _oracle_doc
    from peritext_tpu.core.comment import Comment, put_comment
    from peritext_tpu.parallel.codec import encode_frame
    from peritext_tpu.testing.generate import generate_docs

    docs, _, initial = generate_docs("fallback text", 1)
    (d1,) = docs
    # a float value is device-inexpressible: forces the fallback path
    # (comment-body maps themselves now ride the device registers)
    fall_change, _ = d1.change(
        [{"path": [], "action": "set", "key": "ratio", "value": 0.25}]
    )
    sess = StreamingMerge(
        num_docs=1, actors=("doc1",), slot_capacity=128,
        round_insert_capacity=64, round_delete_capacity=32, round_mark_capacity=32,
    )
    sess.ingest_frame(0, encode_frame([initial, fall_change]))
    sess.drain()
    assert sess.docs[0].fallback
    w = {"doc1": [initial, fall_change]}
    doc = _oracle_doc(w)
    cursor = doc.get_cursor(["text"], 4)
    assert sess.resolve_cursors(0, [cursor]) == [doc.resolve_cursor(cursor)]


def test_block_chunked_reads_match_single_block():
    """read_chunk smaller than num_docs: reads/digest/cursors/patches must be
    identical to the whole-batch path (the 100K-doc memory-bounding mode)."""
    from peritext_tpu.parallel.codec import encode_frame
    from peritext_tpu.testing.fuzz import generate_workload

    workloads = generate_workload(seed=150, num_docs=5, ops_per_doc=80)

    def build(read_chunk):
        sess = StreamingMerge(
            num_docs=5, actors=("doc1", "doc2", "doc3"), slot_capacity=512,
            mark_capacity=128, round_insert_capacity=128,
            round_delete_capacity=64, round_mark_capacity=64,
            read_chunk=read_chunk,
        )
        for d, w in enumerate(workloads):
            sess.ingest_frame(d, encode_frame([c for log in w.values() for c in log]))
        sess.drain()
        return sess

    whole = build(read_chunk=8192)
    chunked = build(read_chunk=2)  # 3 blocks, last one partial
    assert chunked.digest() == whole.digest()
    assert chunked.read_all() == whole.read_all()
    for d in range(5):
        assert chunked.read(d) == whole.read(d)
        assert chunked.read_patches(d) == whole.read_patches(d)
    # cursors across block boundaries in one batched call
    from peritext_tpu.api.batch import _oracle_doc

    cursor_map = {}
    for d, w in enumerate(workloads):
        doc = _oracle_doc(w)
        n = sum(len(s["text"]) for s in doc.get_text_with_formatting(["text"]))
        if n:
            cursor_map[d] = [doc.get_cursor(["text"], n // 2)]
    assert chunked.resolve_cursors_batch(cursor_map) == whole.resolve_cursors_batch(
        cursor_map
    )


def test_digest_equal_across_different_demotion_sets():
    """Two converged peers whose demotion histories differ must report EQUAL
    digests: fallback docs hash host-side with the device-identical per-doc
    formula (mesh.doc_digest_host) instead of being masked away."""
    from peritext_tpu.parallel.codec import encode_frame
    from peritext_tpu.testing.generate import generate_docs

    docs, _, initial = generate_docs("converged text", 1)
    (d1,) = docs
    c1, _ = d1.change(
        [{"path": ["text"], "action": "insert", "index": 4, "values": list("XY")},
         {"path": ["text"], "action": "delete", "index": 0, "count": 2}]
    )
    mk = lambda: StreamingMerge(  # noqa: E731
        num_docs=1, actors=("doc1",), slot_capacity=64,
        round_insert_capacity=32, round_delete_capacity=16, round_mark_capacity=16,
    )
    on_device = mk()
    on_device.ingest_frame(0, encode_frame([initial, c1]))
    on_device.drain()
    assert not on_device.docs[0].fallback

    # demotion WITHOUT state divergence (capacity-style): full digests agree —
    # the fallback doc's host-side formatting/register hashes are
    # bit-identical to the device sums
    same_state = mk()
    same_state.ingest_frame(0, encode_frame([initial, c1]))
    same_state.drain()
    same_state.docs[0].fallback = True
    assert on_device.digest() == same_state.digest()

    demoted = mk()
    demoted.ingest_frame(0, encode_frame([initial, c1]))
    demoted.drain()
    # demote AFTER convergence via a device-inexpressible op
    fl, _ = d1.change([{"path": [], "action": "set", "key": "r", "value": 0.5}])
    demoted.ingest_frame(0, encode_frame([fl]))
    demoted.drain()
    assert demoted.docs[0].fallback
    # the float map entry does not touch the text, so the TEXT digests agree…
    assert on_device.digest(full=False) == demoted.digest(full=False)
    # …but the full-state digest correctly sees the extra map register
    assert on_device.digest() != demoted.digest()


def test_span_marks_are_isolated_copies():
    """Mark dicts are memoized inside the vectorized span decode — but the
    copies handed out must be isolated ALL the way down: mutating one span's
    nested mark values (link url, comment list) must not reformat any other
    span or doc sharing the same formatting (ADVICE r3 + review r4)."""
    from peritext_tpu.testing.generate import generate_docs

    docs, _, initial = generate_docs("hello world", 1)
    (d1,) = docs
    link, _ = d1.change([{
        "path": ["text"], "action": "addMark", "startIndex": 0, "endIndex": 5,
        "markType": "link", "attrs": {"url": "https://a.example"},
    }])
    comment, _ = d1.change([{
        "path": ["text"], "action": "addMark", "startIndex": 0, "endIndex": 5,
        "markType": "comment", "attrs": {"id": "c-1"},
    }])
    sess = StreamingMerge(num_docs=2, actors=("doc1",))
    for d in range(2):
        sess.ingest(d, [initial, link, comment])
    sess.drain()
    spans = sess.read_all()
    assert spans[0] == spans[1]
    marked = next(sp for sp in spans[0] if "link" in sp["marks"])
    # nested mutation on doc 0's span...
    marked["marks"]["link"]["url"] = "https://evil.example"
    marked["marks"]["comment"].append({"id": "c-2"})
    marked["marks"]["strong"] = {"active": True}
    # ...must leave doc 1's identically-formatted span untouched
    twin = next(sp for sp in spans[1] if "link" in sp["marks"])
    assert twin["marks"]["link"]["url"] == "https://a.example"
    assert twin["marks"]["comment"] == [{"id": "c-1"}]
    assert "strong" not in twin["marks"]


class TestReshard:
    """Live doc re-sharding (SURVEY §5.8(c)): move packed doc rows across
    shards, digest-invariant, with ingest continuing afterwards."""

    def _skewed(self, seed=5):
        workloads = generate_workload(seed=seed, num_docs=8, ops_per_doc=30)
        big = generate_workload(seed=seed + 1, num_docs=2, ops_per_doc=150)
        workloads[0], workloads[1] = big[0], big[1]
        return workloads

    def _split(self, w):
        chs = [ch for log in w.values() for ch in log]
        half = len(chs) // 2
        return chs[:half], chs[half:]

    def test_reshard_preserves_state_and_keeps_ingesting(self):
        workloads = self._skewed()
        halves = [self._split(w) for w in workloads]
        s = StreamingMerge(
            num_docs=8, actors=ACTORS, read_chunk=2,
            round_insert_capacity=256, round_delete_capacity=128,
            round_mark_capacity=128,
        )
        for d, (first, _) in enumerate(halves):
            s.ingest(d, first)
        s.drain()
        before_digest, before_reads = s.digest(), s.read_all()

        r = s.reshard()
        assert r["moved"] > 0
        # skew is balanced: worst shard no longer dominates
        assert max(r["shard_load"]) < 0.7 * sum(r["shard_load"])
        # placement is invisible: digests and reads are bit-identical
        assert s.digest() == before_digest == s.digest(refresh=True)
        assert s.read_all() == before_reads

        # the session keeps running on the new placement
        for d, (_, second) in enumerate(halves):
            s.ingest(d, second)
        s.drain()
        assert s.read_all() == oracle_merge(workloads)
        assert s.digest() == s.digest(refresh=True)

    def test_reshard_mesh_all_to_all_digest_invariant(self):
        from peritext_tpu.parallel.mesh import make_mesh

        workloads = self._skewed(seed=11)
        halves = [self._split(w) for w in workloads]
        s = StreamingMerge(num_docs=8, actors=ACTORS, mesh=make_mesh(4),
                           round_insert_capacity=256,
                           round_delete_capacity=128, round_mark_capacity=128)
        for d, (first, _) in enumerate(halves):
            s.ingest(d, first)
        s.drain()
        before = s.digest()
        r = s.reshard()
        assert s.digest() == before
        for d, (_, second) in enumerate(halves):
            s.ingest(d, second)
        s.drain()
        assert s.read_all() == oracle_merge(workloads)
        # meshless session with same data agrees (cross-topology invariance)
        flat = StreamingMerge(num_docs=8, actors=ACTORS,
                              round_insert_capacity=256,
                              round_delete_capacity=128,
                              round_mark_capacity=128)
        for d, w in enumerate(workloads):
            flat.ingest(d, [ch for log in w.values() for ch in log])
        flat.drain()
        assert flat.digest() == s.digest()

    def test_reshard_spreads_quarantined_docs_across_hosts(self):
        """Quarantine-aware placement (ROADMAP): scalar-replay (host-bound)
        docs must not crowd one shard's host — the default assignment
        balances their load as its own dimension."""
        workloads = self._skewed(seed=31)
        s = StreamingMerge(num_docs=8, actors=ACTORS, read_chunk=2,
                           round_insert_capacity=256,
                           round_delete_capacity=128, round_mark_capacity=128)
        for d, w in enumerate(workloads):
            s.ingest(d, [ch for log in w.values() for ch in log])
        s.drain()
        for d in (0, 1, 2, 3):  # a burst of demotions, biggest docs included
            s.force_fallback(d, detail="test demotion")
        before_digest, before_reads = s.digest(), s.read_all()
        r = s.reshard()
        # 4 host-bound docs over 4 shards: every shard carries exactly one
        # (no host runs two scalar replays while another runs none)
        assert all(load > 0 for load in r["host_bound_load"]), r
        assert sum(r["host_bound_load"]) <= sum(r["shard_load"])
        # placement stays invisible to reads and digests
        assert s.digest() == before_digest == s.digest(refresh=True)
        assert s.read_all() == before_reads

    def test_reshard_explicit_assignment_and_validation(self):
        workloads = self._skewed(seed=21)
        s = StreamingMerge(num_docs=8, actors=ACTORS, read_chunk=2,
                           round_insert_capacity=256,
                           round_delete_capacity=128, round_mark_capacity=128)
        for d, w in enumerate(workloads):
            s.ingest(d, [ch for log in w.values() for ch in log])
        s.drain()
        before = s.digest()
        # explicit: reverse the blocks
        s.reshard([3, 3, 2, 2, 1, 1, 0, 0])
        assert s.digest() == before
        assert s.read_all() == oracle_merge(workloads)
        with pytest.raises(ValueError, match="capacity"):
            s.reshard([0] * 8)  # 8 docs into a 2-row shard
        with pytest.raises(ValueError, match="cover"):
            s.reshard([0, 1])

    def test_reshard_between_async_digest_and_wait(self):
        """A reshard between digest_async() and wait() must neither corrupt
        the returned value (the scalars describe schedule-time rows) nor
        write stale pre-reshard digests into the carry (review r4)."""
        workloads = self._skewed(seed=31)
        s = StreamingMerge(num_docs=8, actors=ACTORS, read_chunk=2,
                           round_insert_capacity=256,
                           round_delete_capacity=128, round_mark_capacity=128)
        for d, w in enumerate(workloads):
            s.ingest(d, [ch for log in w.values() for ch in log])
        s.drain()
        s.docs[3].fallback = True  # a replay doc exercises the row->doc map
        expected = s.digest(refresh=True)
        pending = s.digest_async()
        assert s.reshard()["moved"] > 0
        assert pending.wait() == expected
        # the carry was not polluted by the pre-reshard scalars
        assert s.digest() == s.digest(refresh=True) == expected


def test_compact_width_prior_too_small_widens_not_truncates():
    """The sweep's packed transfer trusts a session-wide width prior and
    must RE-FETCH wider — never silently truncate — when a live doc's
    visible count exceeds it (streaming._finish_compact)."""
    from peritext_tpu.parallel.codec import encode_frame

    d = 6
    workloads = generate_workload(seed=91, num_docs=d, ops_per_doc=96)
    s = StreamingMerge(num_docs=d, actors=("doc1", "doc2", "doc3"),
                       slot_capacity=256)
    for doc, w in enumerate(workloads):
        s.ingest_frame(doc, encode_frame([c for log in w.values() for c in log]))
    s.drain()
    oracle = oracle_merge(workloads)
    assert any(
        sum(len(sp["text"]) for sp in spans) > 8 for spans in oracle
    ), "workload too small to exercise the widen path"

    # poison the width cache with a floor-small prior, as a session whose
    # first block held only tiny docs would have recorded
    s._compact_width = {-1: 8}
    for bi in range(-(-s._padded_docs // s._read_chunk)):
        s._compact_width[bi] = 8
    assert s.read_all() == oracle
    # the refetch recorded honest widths for the next sweep
    assert s._compact_width[-1] > 8


def test_block_chunked_apply_matches_whole_batch():
    """The block-chunked round apply (sessions larger than a read block,
    incl. the padded doc axis, shared stream buckets and carried block
    states) must produce bit-identical state to the whole-batch apply."""
    from peritext_tpu.parallel.codec import encode_frame

    d = 26  # deliberately NOT a block multiple: exercises meshless padding
    workloads = generate_workload(seed=77, num_docs=d, ops_per_doc=72)
    sessions = [
        StreamingMerge(num_docs=d, actors=("doc1", "doc2", "doc3"),
                       slot_capacity=256, read_chunk=rc)
        for rc in (8, 1024)  # chunked (4 blocks, padded to 32) vs single
    ]
    for s in sessions:
        for doc, w in enumerate(workloads):
            ch = [c for log in w.values() for c in log]
            s.ingest_frame(doc, encode_frame(ch[: len(ch) // 2]))
        s.drain()
        # second round exercises the carried-block fast path
        for doc, w in enumerate(workloads):
            ch = [c for log in w.values() for c in log]
            s.ingest_frame(doc, encode_frame(ch[len(ch) // 2:]))
        s.drain()
    chunked, single = sessions
    # the comparison is vacuous if docs silently demoted to scalar replay —
    # the native block path must actually have run
    for s in sessions:
        assert not any(ds.fallback for ds in s.docs)
        assert s.pending_count() == 0
        assert s.overflow_count() == 0
    assert chunked.digest() == single.digest()
    assert chunked.read_all() == single.read_all()
    oracle = oracle_merge(workloads)
    assert single.read_all() == oracle


def test_cum_ins_upper_bounds_device_occupancy():
    """The host-side cumulative-insert plane must upper-bound every row's
    device slot occupancy after any mix of rounds, duplicates and a
    reshard — it feeds the pallas insert loop's static slot window
    (kernel insert_loop_slots), where an under-bound would corrupt
    inserts on TPU (round 5; CPU uses the lax path, so this pins the
    INVARIANT, not the kernel)."""
    import numpy as np

    from peritext_tpu.parallel.codec import encode_frame
    from peritext_tpu.parallel.streaming import StreamingMerge
    from peritext_tpu.testing.fuzz import generate_workload

    workloads = generate_workload(seed=13, num_docs=12, ops_per_doc=60)
    s = StreamingMerge(
        num_docs=12, actors=("doc1", "doc2", "doc3"),
        slot_capacity=256, mark_capacity=96, tomb_capacity=96,
        round_insert_capacity=32, round_delete_capacity=16,
        round_mark_capacity=16,
    )
    for doc, w in enumerate(workloads):
        ch = [c for log in w.values() for c in log]
        s.ingest_frame(doc, encode_frame(ch[: len(ch) // 2]))
        # duplicate delivery: dedup happens device-side, the bound may
        # only over-count
        s.ingest_frame(doc, encode_frame(ch[: len(ch) // 2]))
    s.drain()
    for doc, w in enumerate(workloads):
        ch = [c for log in w.values() for c in log]
        s.ingest_frame(doc, encode_frame(ch[len(ch) // 2:]))
    s.drain()
    slots = np.asarray(s.state.num_slots)
    assert (s._cum_ins >= slots).all(), (s._cum_ins, slots)
    s.reshard()
    slots = np.asarray(s.state.num_slots)
    assert (s._cum_ins >= slots).all(), "bound must ride the reshard permute"
    assert s.pending_count() == 0


def test_fused_drain_equals_stepwise_application():
    """drain() commits queued rounds as ONE fused device program
    (kernel.apply_batch_compact_rounds); public step() commits per round.
    The two must be indistinguishable — state digest, spans, patches —
    since fusion is the same apply sequence traced together (round 5)."""
    from peritext_tpu.parallel.codec import encode_frame
    from peritext_tpu.parallel.streaming import StreamingMerge
    from peritext_tpu.testing.fuzz import generate_workload

    workloads = generate_workload(seed=23, num_docs=16, ops_per_doc=96)

    def build(use_drain):
        s = StreamingMerge(
            num_docs=16, actors=("doc1", "doc2", "doc3"),
            slot_capacity=256, mark_capacity=96, tomb_capacity=128,
            round_insert_capacity=32, round_delete_capacity=16,
            round_mark_capacity=16,
        )
        for doc, w in enumerate(workloads):
            ch = [c for log in w.values() for c in log]
            half = len(ch) // 2
            s.ingest_frame(doc, encode_frame(ch[:half]))
            s.ingest_frame(doc, encode_frame(ch[half:]))
        if use_drain:
            s.drain()  # fused: multiple rounds per dispatch
        else:
            while s.step() > 0:  # per-round dispatch
                pass
        return s

    fused, stepwise = build(True), build(False)
    assert fused.rounds == stepwise.rounds
    assert fused.digest() == stepwise.digest()
    assert fused.read_all() == stepwise.read_all()
    assert fused.read_patches_all() == stepwise.read_patches_all()
    # low caps force several rounds, so the fused path actually fused
    assert fused.rounds > 1
