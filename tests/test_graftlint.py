"""graftlint suite tests: every rule has true positives (the bad corpus)
and clean negatives (the clean corpus), the attributed baseline round-trips,
the CLI exit codes hold, and the repo self-scan is clean modulo the
checked-in baseline — the acceptance criteria of the determinism contract
(DESIGN.md "Determinism contract")."""

from pathlib import Path

import pytest

from peritext_tpu.analysis import (
    all_rule_ids,
    apply_baseline,
    find_default_baseline,
    load_baseline,
    rule_table,
    scan_paths,
    update_baseline,
)
from peritext_tpu.analysis.__main__ import main as graftlint_main
from peritext_tpu.analysis.baseline import save_baseline

REPO_ROOT = Path(__file__).resolve().parents[1]
CORPUS = Path(__file__).resolve().parent / "graftlint_corpus"


def _scan(path):
    return scan_paths([path], root=REPO_ROOT)


class TestRules:
    @pytest.fixture(scope="class")
    def bad_findings(self):
        return _scan(CORPUS / "bad")

    @pytest.mark.parametrize("rule", all_rule_ids())
    def test_every_rule_has_a_true_positive(self, bad_findings, rule):
        assert any(f.rule == rule for f in bad_findings), (
            f"{rule} found nothing in the bad corpus"
        )

    def test_clean_corpus_scans_clean(self):
        assert _scan(CORPUS / "clean") == []

    def test_findings_carry_stable_contexts(self, bad_findings):
        for f in bad_findings:
            assert f.context, f  # fingerprint basis must never be empty
            assert f.path.startswith("tests/graftlint_corpus/bad")

    def test_expected_positive_spot_checks(self, bad_findings):
        hits = {(f.rule, f.context) for f in bad_findings}
        assert ("PTL001", "for key, callback in list(self._subscribers.items()):") in hits
        # bare iteration over dict/set-typed instance state — the most
        # common spelling of the arrival-order hazard
        assert ("PTL001", "return [key for key in self._subscribers]") in hits
        assert ("PTL001", "for doc in self._pending:") in hits
        assert ("PTL002", "if flag:") in hits
        assert ("PTL002", "while x:") in hits
        assert ("PTL003", "return x.item()") in hits
        # the devprof pattern: a cost/memory probe reachable from a
        # merge-scope jit root is a host sync, obs/-scoping or not
        assert ("PTL003", "return jax.block_until_ready(state)") in hits
        # the fused-pipeline mistake: a host sync INSIDE the fused round
        # loop (reachable from the jit root through a chained helper)
        # re-serializes the dispatch pipeline the fusion exists to remove
        assert ("PTL003", "jax.block_until_ready(state)") in hits
        # the mesh-region mistake: a host sync in a helper the
        # shard-mapped body calls — jit(shard_map(body)) roots body, so
        # the sync stalls every shard of the one staged mesh program
        assert ("PTL003", "return total.item()") in hits
        assert ("PTL005", "except Exception:") in hits
        assert ("PTL006", "rng = random.Random()") in hits
        # the serving-tier placement mistake: a wall-clock read sneaking
        # into the FleetRouter's (merge-scope) placement path must fire —
        # placement determinism is what lets two frontends agree
        assert ("PTL006", "stamp = time.monotonic()") in hits
        assert any(r == "PTL004" and "len(docs)" in c for r, c in hits)

    def test_merge_scope_rules_skip_unscoped_files(self, tmp_path):
        src = "import time\n\ndef f():\n    return time.time()\n"
        (tmp_path / "util.py").write_text(src)
        assert scan_paths([tmp_path / "util.py"], root=tmp_path) == []
        scoped = tmp_path / "parallel"
        scoped.mkdir()
        (scoped / "util.py").write_text(src)
        findings = scan_paths([scoped / "util.py"], root=tmp_path)
        assert [f.rule for f in findings] == ["PTL006"]

    def test_nonexistent_path_is_an_error_not_a_clean_scan(self, tmp_path):
        with pytest.raises(FileNotFoundError):
            scan_paths([tmp_path / "no_such_pkg"], root=tmp_path)
        (tmp_path / "notes.txt").write_text("not python")
        with pytest.raises(ValueError):
            scan_paths([tmp_path / "notes.txt"], root=tmp_path)
        assert graftlint_main([str(tmp_path / "no_such_pkg")]) == 2

    def test_unparseable_file_reports_ptl000(self, tmp_path):
        bad = tmp_path / "broken.py"
        bad.write_text("def f(:\n")
        findings = scan_paths([bad], root=tmp_path)
        assert [f.rule for f in findings] == ["PTL000"]

    def test_rule_table_is_complete(self):
        assert [row["id"] for row in rule_table()] == all_rule_ids()
        assert all(row["summary"] and row["rationale"] for row in rule_table())
        assert len(all_rule_ids()) >= 6  # registry-derived, never hardcoded

    def test_assignment_ternary_on_tracer_is_flagged(self, bad_findings):
        assert ("PTL002", "sign = 1 if total else -1  # PTL002: ternary on a traced value") in {
            (f.rule, f.context) for f in bad_findings
        }


class TestBaseline:
    def test_round_trip_suppresses_then_catches_new(self, tmp_path):
        findings = _scan(CORPUS / "bad")
        assert findings
        baseline_path = tmp_path / "baseline.json"
        save_baseline(baseline_path, update_baseline(findings, {}))
        entries = load_baseline(baseline_path)

        new, stale = apply_baseline(findings, entries)
        assert new == [] and stale == []  # full suppression round-trip

        # a brand-new violation is NOT absorbed by the old baseline
        extra = tmp_path / "parallel"
        extra.mkdir()
        (extra / "fresh.py").write_text(
            "import random\n\ndef f(xs):\n    random.shuffle(xs)\n"
        )
        grown = findings + scan_paths([extra], root=tmp_path)
        new, stale = apply_baseline(grown, entries)
        assert [f.rule for f in new] == ["PTL006"] and stale == []

    def test_update_with_no_prior_baseline_anchors_at_cwd(self, tmp_path, monkeypatch, capsys):
        """--update-baseline must write the ledger at the scan root (cwd),
        never inside the scanned tree, so default discovery finds it with
        matching relative paths."""
        scoped = tmp_path / "parallel"
        scoped.mkdir()
        (scoped / "v.py").write_text(
            "import random\n\ndef f(xs):\n    random.shuffle(xs)\n"
        )
        monkeypatch.chdir(tmp_path)
        assert graftlint_main(["parallel/v.py", "--update-baseline"]) == 0
        ledger = tmp_path / "graftlint_baseline.json"
        assert ledger.is_file()
        assert not (scoped / "graftlint_baseline.json").exists()
        entries = load_baseline(ledger)
        assert {e.path for e in entries.values()} == {"parallel/v.py"}
        # and the default-discovery scan is now clean against it
        assert graftlint_main(["parallel"]) == 0

    def test_stale_entries_are_reported_not_fatal(self):
        findings = _scan(CORPUS / "bad")
        entries = update_baseline(findings, {})
        by_key = {(e.rule, e.path, e.context): e for e in entries}
        new, stale = apply_baseline(findings[1:], by_key)
        assert new == []
        assert len(stale) == 1  # the dropped finding's entry went stale

    def test_update_preserves_justifications(self):
        findings = _scan(CORPUS / "bad")
        first = update_baseline(findings, {})
        first[0].justification = "because physics"
        old = {(e.rule, e.path, e.context): e for e in first}
        second = update_baseline(findings, old)
        assert second[0].justification == "because physics"
        assert all(
            e.justification.startswith("TODO") for e in second[1:]
        ) or len(second) == 1


class TestCli:
    def test_bad_corpus_exits_nonzero(self, capsys):
        rc = graftlint_main([str(CORPUS / "bad"), "--no-baseline"])
        assert rc == 1
        out = capsys.readouterr().out
        assert "PTL001" in out and "PTL006" in out

    def test_clean_corpus_exits_zero(self, capsys):
        assert graftlint_main([str(CORPUS / "clean"), "--no-baseline"]) == 0

    def test_rule_subset_and_unknown_rule(self, capsys):
        rc = graftlint_main(
            [str(CORPUS / "bad"), "--no-baseline", "--rules", "PTL005"]
        )
        assert rc == 1
        out = capsys.readouterr().out
        assert "PTL005" in out and "PTL001" not in out
        assert graftlint_main([str(CORPUS / "bad"), "--rules", "PTL999"]) == 2

    def test_json_format(self, capsys):
        import json

        rc = graftlint_main(
            [str(CORPUS / "bad"), "--no-baseline", "--format", "json"]
        )
        assert rc == 1
        payload = json.loads(capsys.readouterr().out)
        assert {f["rule"] for f in payload["findings"]} == set(all_rule_ids())

    def test_rules_scoped_update_preserves_other_entries(self, tmp_path, monkeypatch):
        """--rules + --update-baseline must not delete other rules' ledger
        entries (or their justifications)."""
        scoped = tmp_path / "parallel"
        scoped.mkdir()
        (scoped / "v.py").write_text(
            "import random, time\n\ndef f(xs):\n"
            "    random.shuffle(xs)\n"
            "    for x in set(xs):\n        pass\n"
        )
        monkeypatch.chdir(tmp_path)
        assert graftlint_main(["parallel", "--update-baseline"]) == 0
        ledger = tmp_path / "graftlint_baseline.json"
        full = load_baseline(ledger)
        assert {e.rule for e in full.values()} == {"PTL001", "PTL006"}
        for e in full.values():
            e.justification = "kept"
        from peritext_tpu.analysis.baseline import save_baseline as _save

        _save(ledger, full.values())
        assert graftlint_main(["parallel", "--rules", "PTL001", "--update-baseline"]) == 0
        after = load_baseline(ledger)
        assert {e.rule for e in after.values()} == {"PTL001", "PTL006"}
        assert all(e.justification == "kept" for e in after.values())


class TestRepoSelfScan:
    def test_checked_in_baseline_is_found(self):
        found = find_default_baseline([REPO_ROOT / "peritext_tpu"])
        assert found == REPO_ROOT / "graftlint_baseline.json"

    def test_repo_scan_is_clean_modulo_baseline(self):
        """THE acceptance criterion: zero unbaselined findings in the
        package, and every baseline entry both live and justified."""
        findings = scan_paths([REPO_ROOT / "peritext_tpu"], root=REPO_ROOT)
        entries = load_baseline(REPO_ROOT / "graftlint_baseline.json")
        new, stale = apply_baseline(findings, entries)
        assert new == [], "unbaselined graftlint findings:\n" + "\n".join(
            f.render() for f in new
        )
        assert stale == [], "stale baseline entries: " + ", ".join(
            f"{e.rule} {e.path}" for e in stale
        )
        assert all(
            e.justification and not e.justification.startswith("TODO")
            for e in entries.values()
        ), "baseline entries must carry real justifications"
