"""Paged document storage (peritext_tpu/store/): the byte-equality oracle
and the subsystem invariants.

The paged layout's correctness contract is blunt: for every fuzz seed and
recorded trace, the paged backend must produce IDENTICAL final docs,
patches and store digests to the padded backend — the padded path stays
resident as the oracle.  On top of that: allocator determinism (page
tables are replicated state), typed pool exhaustion, checkpoint round-trip
of a paged session, a recompile-sentinel replay proving paged dispatch
mints no per-round compiles, and the page-pool telemetry surfaces.
"""

import random
import tempfile

import numpy as np
import pytest

from peritext_tpu.api.batch import DocBatch, _oracle_doc
from peritext_tpu.parallel.codec import encode_frame
from peritext_tpu.parallel.streaming import StreamingMerge
from peritext_tpu.store import PageAllocator, PagedDocStore, PoolExhausted
from peritext_tpu.testing.fuzz import generate_workload

ACTORS = ("doc1", "doc2", "doc3")


# ---------------------------------------------------------------------------
# allocator: deterministic, typed exhaustion, compact/evacuate/reseat
# ---------------------------------------------------------------------------


def test_allocator_is_deterministic_lowest_first():
    a = PageAllocator(10)
    assert a.ensure(0, 3) == [1, 2, 3]
    assert a.ensure(1, 2) == [4, 5]
    assert a.ensure(0, 3) == []  # already satisfied: no-op
    a.free_doc(0)
    # freed pages come back lowest-id-first, ahead of never-used ones
    assert a.ensure(2, 4) == [1, 2, 3, 6]
    # two allocators fed the same request sequence agree exactly
    b = PageAllocator(10)
    for doc, n in ((0, 3), (1, 2)):
        b.ensure(doc, n)
    b.free_doc(0)
    assert b.ensure(2, 4) == [1, 2, 3, 6]
    assert a.pages_of(2) == b.pages_of(2)


def test_allocator_exhaustion_is_typed_and_atomic():
    a = PageAllocator(6)
    a.ensure(0, 3)
    with pytest.raises(PoolExhausted) as exc:
        a.ensure(1, 5)
    assert exc.value.requested == 5
    assert exc.value.free == 2
    assert exc.value.total == 6
    assert a.pages_of(1) == []  # failed ensure assigned nothing
    a.grow(12)
    assert a.ensure(1, 5) == [4, 5, 6, 7, 8]


def test_allocator_compact_plan_packs_sorted():
    a = PageAllocator(12)
    a.ensure(3, 2)
    a.ensure(1, 2)
    a.free_doc(3)
    a.ensure(5, 1)
    plan = a.compact_plan()
    a.apply_compact(plan)
    # docs walk in sorted row order: doc 1 first, then doc 5
    assert a.pages_of(1) == [1, 2]
    assert a.pages_of(5) == [3]
    assert a.free_pages == 12 - 1 - 3


def test_store_compact_and_evacuate_preserve_content():
    s = PagedDocStore(4, slot_capacity=256, mark_capacity=8,
                      tomb_capacity=8, page_size=64, initial_pages=16)
    s.ensure_rows([0, 1, 2], [100, 30, 64])
    s.pool_elem = s.pool_elem.at[s.alloc.pages_of(1)[0], 0].set(42)
    before = np.asarray(s.materialize_rows([1], 1).elem_id)
    s.evacuate_row(0)
    moved = s.compact()
    assert moved > 0
    after = np.asarray(s.materialize_rows([1], 1).elem_id)
    assert (before == after).all()
    # freed pages and the null page read as zeros
    assert int(np.asarray(s.pool_elem[0]).sum()) == 0
    free_page = s.alloc._free[0]
    assert int(np.asarray(s.pool_elem[free_page]).sum()) == 0


def test_store_pool_grows_and_caps():
    s = PagedDocStore(2, slot_capacity=512, mark_capacity=8,
                      page_size=64, initial_pages=4, max_pool_pages=8)
    s.ensure_rows([0], [300])  # 5 pages: forces one doubling
    assert s.growths == 1
    assert s.pool_elem.shape[0] == 8
    with pytest.raises(PoolExhausted):
        s.ensure_rows([1], [512])  # 8 more pages would exceed the cap
    assert s.pool_stats()["growths"] == 1


def test_store_default_tomb_capacity_matches_padded_layout():
    """An omitted tomb_capacity must default to the slot capacity (the
    padded layout's empty_docs default), not to the width-1 aux proto."""
    s = PagedDocStore(2, slot_capacity=256, mark_capacity=64, page_size=64)
    assert s.aux_capacities["tomb_capacity"] == 256


def test_store_rejects_unaligned_slot_capacity():
    with pytest.raises(ValueError):
        PagedDocStore(2, slot_capacity=100, mark_capacity=8, page_size=64)


# ---------------------------------------------------------------------------
# DocBatch: paged vs padded byte equality (the oracle)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("seed", [3, 11, 42])
def test_batch_paged_matches_padded_on_fuzz_seeds(seed):
    wl = generate_workload(seed=seed, num_docs=8, ops_per_doc=60)
    curs = [[] for _ in wl]
    p = DocBatch(slot_capacity=256, mark_capacity=64).merge(wl, cursors=curs)
    q = DocBatch(slot_capacity=256, mark_capacity=64,
                 layout="paged").merge(wl, cursors=curs)
    assert p.spans == q.spans
    assert p.roots == q.roots
    assert p.fallback_docs == q.fallback_docs
    assert p.device_ops == q.device_ops
    assert p.cursor_positions == q.cursor_positions


def test_batch_paged_matches_padded_under_capacity_fallbacks():
    """The configured capacities act as fallback thresholds identically
    under both layouts — tiny caps route the same docs to the oracle."""
    wl = generate_workload(seed=7, num_docs=6, ops_per_doc=70)
    for kw in (
        dict(slot_capacity=256, mark_capacity=8),   # mark-capacity fallback
        dict(slot_capacity=64, mark_capacity=64),   # slot overflow
        dict(slot_capacity=256, mark_capacity=64, op_capacity=32),
    ):
        p = DocBatch(**kw).merge(wl)
        q = DocBatch(layout="paged", **kw).merge(wl)
        assert p.spans == q.spans, kw
        assert p.fallback_docs == q.fallback_docs, kw
        assert p.roots == q.roots, kw


def test_batch_paged_cursor_parity():
    wl = generate_workload(seed=2, num_docs=4, ops_per_doc=50)
    curs = []
    for w in wl:
        doc = _oracle_doc(w)
        lids = [oid for oid, m in doc._metadata.items() if isinstance(m, list)]
        row = []
        if lids and doc._metadata[lids[0]]:
            el = doc._metadata[lids[0]][0].elem_id
            row = [{"objectId": lids[0], "elemId": el}]
        curs.append(row)
    p = DocBatch().merge(wl, cursors=curs)
    q = DocBatch(layout="paged").merge(wl, cursors=curs)
    assert p.cursor_positions == q.cursor_positions


def test_batch_paged_matches_padded_on_recorded_traces():
    from peritext_tpu.testing.traces import available_traces, load_trace_queues

    traces = available_traces()
    if not traces:
        pytest.skip("no recorded reference traces in this image")
    wl = [load_trace_queues(t) for t in traces[:4]]
    p = DocBatch(slot_capacity=1024, mark_capacity=256).merge(wl)
    q = DocBatch(slot_capacity=1024, mark_capacity=256,
                 layout="paged").merge(wl)
    assert p.spans == q.spans
    assert p.fallback_docs == q.fallback_docs


def test_batch_paged_occupancy_beats_padded_on_longtail():
    """One essay among tweets: the paged layout must burn strictly less
    padded stream capacity (the acceptance direction bench longdoc gates
    at >= 5x on the full row; the unit test pins the direction)."""
    wl = generate_workload(seed=5, num_docs=12, ops_per_doc=8)
    wl += generate_workload(seed=501, num_docs=1, ops_per_doc=300)
    p = DocBatch(slot_capacity=512, mark_capacity=128).merge(wl)
    q = DocBatch(slot_capacity=512, mark_capacity=128,
                 layout="paged").merge(wl)
    assert p.spans == q.spans
    assert q.stats.padding_efficiency > p.stats.padding_efficiency

    def wasted(r):
        real = r.stats.device_ops + r.stats.fallback_ops
        eff = r.stats.padding_efficiency
        return real / eff - real if eff else 0.0

    assert wasted(p) >= 5.0 * wasted(q)


def test_batch_paged_rejects_mesh_and_bad_page_size():
    with pytest.raises(ValueError):
        DocBatch(layout="paged", slot_capacity=100)
    with pytest.raises(ValueError):
        DocBatch(layout="nonsense")


# ---------------------------------------------------------------------------
# streaming: paged vs padded byte equality, blocks, digests, checkpoints
# ---------------------------------------------------------------------------


def _arrival(workloads, rounds=3, seed=1):
    rng = random.Random(seed)
    out = []
    for w in workloads:
        chs = [ch for log in w.values() for ch in log]
        rng.shuffle(chs)
        size = -(-len(chs) // rounds)
        out.append([
            encode_frame(sorted(chs[i:i + size], key=lambda c: (c.actor, c.seq)))
            for i in range(0, len(chs), size)
        ])
    return out


def _build(arrival, layout, num_docs, rounds=3, read_chunk=8192, **kw):
    s = StreamingMerge(
        num_docs=num_docs, actors=ACTORS, slot_capacity=256,
        mark_capacity=64, tomb_capacity=64, read_chunk=read_chunk,
        layout=layout, **kw,
    )
    for r in range(rounds):
        s.ingest_frames(
            (d, b[r]) for d, b in enumerate(arrival) if r < len(b)
        )
        s.drain()
    return s


def test_streaming_paged_factory_and_validation():
    s = StreamingMerge(num_docs=2, actors=ACTORS, layout="paged")
    assert type(s).__name__ == "PagedStreamingMerge"
    assert s.layout == "paged"
    assert StreamingMerge(num_docs=2, actors=ACTORS).layout == "padded"
    with pytest.raises(ValueError):
        StreamingMerge(num_docs=2, actors=ACTORS, layout="paged",
                       static_rounds=True)
    with pytest.raises(ValueError):
        StreamingMerge(num_docs=2, actors=ACTORS, layout="bogus")
    with pytest.raises(ValueError):
        StreamingMerge(num_docs=2, actors=ACTORS, layout="paged",
                       slot_capacity=100)


@pytest.mark.parametrize("seed", [5, 23])
def test_streaming_paged_matches_padded(seed):
    wl = generate_workload(seed=seed, num_docs=8, ops_per_doc=70)
    arr = _arrival(wl)
    sp = _build(arr, "padded", 8)
    sq = _build(arr, "paged", 8)
    assert sp.read_all() == sq.read_all()
    assert sp.read_patches_all() == sq.read_patches_all()
    assert sp.digest() == sq.digest()
    assert sp.digest(full=False) == sq.digest(full=False)
    assert sp.digest(refresh=True) == sq.digest(refresh=True)
    assert sp.frontier() == sq.frontier()
    assert sp.overflow_count() == sq.overflow_count()


def test_streaming_paged_block_chunked_reads_match():
    """read_chunk smaller than the batch: the paged backend materializes
    per block at page-bucketed widths — reads and digests must still be
    bit-equal to the padded session."""
    wl = generate_workload(seed=9, num_docs=10, ops_per_doc=50)
    arr = _arrival(wl, rounds=2)
    sp = _build(arr, "padded", 10, rounds=2, read_chunk=4)
    sq = _build(arr, "paged", 10, rounds=2, read_chunk=4)
    assert sp.read_all() == sq.read_all()
    assert sp.digest() == sq.digest()
    assert [sp.read(d) for d in range(10)] == [sq.read(d) for d in range(10)]
    assert [sp.read_root(d) for d in range(10)] == [sq.read_root(d) for d in range(10)]


def test_streaming_paged_async_digest_and_fallback_parity():
    wl = generate_workload(seed=13, num_docs=6, ops_per_doc=50)
    arr = _arrival(wl, rounds=2)
    sp = _build(arr, "padded", 6, rounds=2)
    sq = _build(arr, "paged", 6, rounds=2)
    assert sp.digest_async().wait() == sq.digest_async().wait()
    # corrupt-frame quarantine + forced demotion behave identically
    bad = arr[2][0][:12] + b"\xffgarbage"
    sp.ingest_frame(2, bad, on_corrupt="quarantine")
    sq.ingest_frame(2, bad, on_corrupt="quarantine")
    assert sorted(sp.quarantined()) == sorted(sq.quarantined())
    sp.force_fallback(4)
    sq.force_fallback(4)
    assert sp.digest() == sq.digest()
    assert sp.read(4) == sq.read(4)
    assert sp.health()["fallback_docs"] == sq.health()["fallback_docs"]


def test_streaming_paged_overflow_routes_to_replay_like_padded():
    wl = generate_workload(seed=17, num_docs=3, ops_per_doc=80)
    arr = _arrival(wl, rounds=1)

    def tiny(layout):
        s = StreamingMerge(num_docs=3, actors=ACTORS, slot_capacity=64,
                           mark_capacity=16, tomb_capacity=16, layout=layout)
        s.ingest_frames((d, arr[d][0]) for d in range(3))
        s.drain()
        return s

    tp, tq = tiny("padded"), tiny("paged")
    assert tp.overflow_count() == tq.overflow_count()
    assert tp.digest() == tq.digest()
    assert tp.read_all() == tq.read_all()


def test_streaming_paged_pool_exhaustion_is_typed():
    wl = generate_workload(seed=19, num_docs=4, ops_per_doc=60)
    arr = _arrival(wl, rounds=1)
    s = StreamingMerge(num_docs=4, actors=ACTORS, slot_capacity=256,
                       mark_capacity=64, layout="paged",
                       pool_pages=2, max_pool_pages=3)
    s.ingest_frames((d, arr[d][0]) for d in range(4))
    with pytest.raises(PoolExhausted):
        s.drain()


def test_streaming_paged_reshard_pages_and_digest_invariance():
    wl = generate_workload(seed=21, num_docs=9, ops_per_doc=40)
    arr = _arrival(wl, rounds=1)
    sq = _build(arr, "paged", 9, rounds=1, read_chunk=3)
    before = sq.digest()
    spans_before = sq.read_all()
    out = sq.reshard()
    assert "page_load" in out
    assert sum(out["page_load"]) == int(sq.store.page_loads().sum())
    assert sq.digest() == before
    assert sq.read_all() == spans_before
    # ingest keeps working after the permutation
    sq.ingest_frames([(0, arr[0][0])])  # duplicate frames are idempotent
    sq.drain()
    assert sq.digest() == before


def test_paged_checkpoint_round_trip():
    from peritext_tpu import checkpoint as ckpt

    wl = generate_workload(seed=25, num_docs=5, ops_per_doc=40)
    arr = _arrival(wl, rounds=2)
    sq = _build(arr, "paged", 5, rounds=2)
    with tempfile.TemporaryDirectory() as td:
        meta = ckpt.save_session(sq, td)
        assert meta["config"]["layout"] == "paged"
        assert meta["config"]["page_size"] == sq.page_size
        restored = ckpt.restore_session(td)
        assert type(restored).__name__ == "PagedStreamingMerge"
        assert restored.digest() == sq.digest()
        assert restored.read_all() == sq.read_all()


def test_paged_replay_mints_no_per_round_compiles(recompile_sentinel):
    """Shape discipline: a fresh paged session replaying a known workload
    reuses every compiled program (apply groups, materialization, fused
    digest) — zero XLA compiles after the warmup session."""
    wl = generate_workload(seed=31, num_docs=6, ops_per_doc=50)
    arr = _arrival(wl, rounds=2)

    def run():
        s = _build(arr, "paged", 6, rounds=2)
        s.digest()
        return s.read_all()

    first = run()  # warmup: compiles everything
    recompile_sentinel.mark()
    second = run()
    assert second == first
    assert recompile_sentinel.since_mark() == {}, (
        f"paged replay recompiled: {recompile_sentinel.since_mark()}"
    )


# ---------------------------------------------------------------------------
# telemetry: page-pool gauges, devprof section, mux snapshot, router loads
# ---------------------------------------------------------------------------


def test_devprof_page_pool_section_and_gauges():
    from peritext_tpu.obs import DeviceProfiler, prometheus_text

    prof = DeviceProfiler()
    assert prof.snapshot()["page_pool"] is None  # padded-only: no section
    wl = generate_workload(seed=33, num_docs=5, ops_per_doc=40)
    arr = _arrival(wl, rounds=1)
    with prof:
        import peritext_tpu.obs.devprof as devprof_mod
        old = devprof_mod.GLOBAL_DEVPROF
        devprof_mod.GLOBAL_DEVPROF = prof
        try:
            # module-level GLOBAL_DEVPROF references were imported by value
            # in the session module via ..obs; drive the store's stats in
            # directly instead of monkeypatching every site
            s = _build(arr, "paged", 5, rounds=1)
            prof.observe_page_pool(s.store.pool_stats())
        finally:
            devprof_mod.GLOBAL_DEVPROF = old
    snap = prof.snapshot()
    pp = snap["page_pool"]
    assert pp is not None
    for key in ("page_size", "pool_pages", "pages_in_use", "pool_utilization",
                "internal_frag_slots", "internal_frag_ratio",
                "frag_by_decile", "peak_utilization"):
        assert key in pp, key
    text = prometheus_text(devprof=prof)
    assert "peritext_page_pool_pages" in text
    assert "peritext_page_pool_utilization" in text
    assert 'peritext_page_frag_ratio{decile="d0"}' in text
    # health_snapshot composition carries the section through devprof
    from peritext_tpu.obs import health_snapshot

    snap = health_snapshot(devprof=prof)
    assert snap["devprof"]["page_pool"]["pool_pages"] == pp["pool_pages"]


def test_streaming_paged_health_and_occupancy_accounting():
    wl = generate_workload(seed=35, num_docs=6, ops_per_doc=40)
    arr = _arrival(wl, rounds=2)
    from peritext_tpu.obs import GLOBAL_DEVPROF

    GLOBAL_DEVPROF.reset()
    with GLOBAL_DEVPROF:
        sq = _build(arr, "paged", 6, rounds=2)
    h = sq.health()
    assert h["layout"] == "paged"
    assert h["page_pool"]["pages_in_use"] > 0
    assert sq.last_round_stats.extras["layout_paged"] == 1.0
    assert 0.0 < sq.last_round_stats.padding_efficiency <= 1.0
    snap = GLOBAL_DEVPROF.snapshot()
    assert snap["page_pool"] is not None
    assert any(
        o["origin"].startswith("streaming.paged")
        for o in snap["occupancy"].values()
    )
    assert any(site.startswith("apply_batch_paged") for site in snap["sites"])


def test_mux_snapshot_reports_layout_and_pool():
    from peritext_tpu.serve import SessionMux

    sq = StreamingMerge(num_docs=4, actors=ACTORS, slot_capacity=256,
                        mark_capacity=64, layout="paged")
    mux = SessionMux(sq, host="t")
    sid, verdict = mux.open_session("client0")
    assert verdict.admitted
    wl = generate_workload(seed=37, num_docs=1, ops_per_doc=30)
    frame = encode_frame(sorted(
        [ch for log in wl[0].values() for ch in log],
        key=lambda c: (c.actor, c.seq),
    ))
    mux.submit(sid, frame)
    mux.flush()
    snap = mux.snapshot()
    assert snap["layout"] == "paged"
    assert snap["page_pool"]["pages_in_use"] >= 1
    # the mux serves byte-identical patches off a paged session
    sp = StreamingMerge(num_docs=4, actors=ACTORS, slot_capacity=256,
                        mark_capacity=64)
    sp.ingest_frame(0, frame)
    sp.drain()
    assert sq.read(0) == sp.read(0)


def test_router_page_load_dimension():
    from peritext_tpu.parallel.router import FleetRouter

    r = FleetRouter()
    r.add_host("a", capacity=8)
    r.add_host("b", capacity=8)
    # paged fleet: hosts report pages; the loaded host loses placement
    r.observe("a", page_load=100)
    r.observe("b", page_load=10)
    assert r.place("doc-1", size=2) == "b"
    assert r.host("b").page_load == 12  # estimate drifts in pages
    assert r.host("b").to_json()["page_load"] == 12
    # a fresh paged host with an EMPTY pool stays in the page dimension
    r.add_host("c", capacity=8)
    r.observe("c", page_load=0, slot_load=999)
    assert r.host("c").paged and r.host("c").device_load() == 0
    assert r.place("doc-2", size=1) == "c"
    # a doc placed BEFORE the paged latch must not wipe the page estimate
    # on eviction: its slot-unit size was never added to page_load
    r3 = FleetRouter()
    r3.add_host("a", capacity=8)
    r3.add_host("b", capacity=8)
    r3.place("pre-latch", size=512)  # slot units, host assumed padded
    host = r3.host_of("pre-latch")
    r3.observe(host, page_load=40)
    r3.evacuate(host)
    other = "b" if host == "a" else "a"
    assert r3.host(host).page_load == 40  # untouched by the slot-unit doc
    assert r3.host_of("pre-latch") == other
    # slot-unit host: page_load stays 0 and slot placement is unchanged
    r2 = FleetRouter()
    r2.add_host("a", capacity=8)
    r2.observe("a", slot_load=5)
    assert r2.host("a").device_load() == 5


# ---------------------------------------------------------------------------
# graftlint: store/ is merge scope; the corpus case must keep failing
# ---------------------------------------------------------------------------


_REPO_ROOT = __import__("pathlib").Path(__file__).resolve().parents[1]


def test_graftlint_store_is_merge_scope_and_corpus_fires():
    from peritext_tpu.analysis.engine import scan_paths

    findings = scan_paths(
        [_REPO_ROOT / "tests/graftlint_corpus/bad/store/allocator_walk.py"],
        root=_REPO_ROOT,
    )
    ids = {f.rule for f in findings}
    assert "PTL001" in ids, "unsorted free-set walk must fire PTL001"
    assert "PTL006" in ids, "wall-clock allocation stamp must fire PTL006"


def test_graftlint_store_package_scans_clean():
    from peritext_tpu.analysis.engine import scan_paths

    findings = scan_paths(
        [_REPO_ROOT / "peritext_tpu" / "store"], root=_REPO_ROOT
    )
    assert findings == [], [str(f) for f in findings]
