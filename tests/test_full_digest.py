"""Full-state convergence digest (VERDICT r2 weak #3).

The digest must cover the COMPLETE document state — visible text, resolved
formatting (LWW winner bits, link urls, comment-id sets) and map registers —
matching the reference's convergence oracles, which compare full formatted
text (reference test/fuzz.ts:245-278), and be comparable across sessions
that interned strings in different orders (content-hash tables, not
session-local ids).
"""

import pytest

from peritext_tpu.core.doc import Doc
from peritext_tpu.parallel.codec import encode_frame
from peritext_tpu.parallel.streaming import StreamingMerge


def mk(n=2, **kw):
    defaults = dict(
        num_docs=n, actors=("a1", "a2"), slot_capacity=128, mark_capacity=64,
        tomb_capacity=64, round_insert_capacity=64, round_delete_capacity=32,
        round_mark_capacity=32,
    )
    defaults.update(kw)
    return StreamingMerge(**defaults)


def rich_changes(urls=("https://one", "https://two")):
    """A doc with text, strong/em/link/comment marks and nested map state."""
    d = Doc("a1")
    chs = []
    ch, _ = d.change(
        [{"path": [], "action": "makeList", "key": "text"},
         {"path": ["text"], "action": "insert", "index": 0,
          "values": list("hello world")}]
    )
    chs.append(ch)
    for i, u in enumerate(urls):
        ch, _ = d.change(
            [{"path": ["text"], "action": "addMark", "startIndex": i,
              "endIndex": i + 4, "markType": "link", "attrs": {"url": u}},
             {"path": ["text"], "action": "addMark", "startIndex": i + 1,
              "endIndex": i + 5, "markType": "comment",
              "attrs": {"id": f"cm-{u}"}}]
        )
        chs.append(ch)
    ch, _ = d.change(
        [{"path": ["text"], "action": "addMark", "startIndex": 0,
          "endIndex": 5, "markType": "strong"},
         {"path": [], "action": "makeMap", "key": "meta"},
         {"path": ["meta"], "action": "set", "key": "title", "value": "T"},
         {"path": ["meta"], "action": "set", "key": "n", "value": -7},
         {"path": [], "action": "set", "key": "flag", "value": True}]
    )
    chs.append(ch)
    return chs, d


def extend(base_changes, actor, ops):
    d = Doc(actor)
    for ch in base_changes:
        d.apply_change(ch)
    ch, _ = d.change(ops)
    return ch


def test_intern_order_independence_across_sessions():
    """Two sessions ingesting the same changes in different orders intern
    attrs/keys/values under different ids, yet their digests match: interned
    identities are folded as content hashes, never raw ids."""
    a, _ = rich_changes(("https://one", "https://two"))
    b, _ = rich_changes(("https://two", "https://one"))
    sx = mk()
    sx.ingest_frames([(0, encode_frame(a)), (1, encode_frame(b))])
    sx.drain()
    sy = mk()
    sy.ingest_frames([(1, encode_frame(b))])  # opposite arrival order
    sy.ingest_frames([(0, encode_frame(a))])
    sy.drain()
    assert sx.digest() == sy.digest()


def test_object_path_matches_frame_path():
    """Per-doc encoder interners (object ingest) and session interners
    (frame ingest) produce the same digest for the same state."""
    a, _ = rich_changes()
    b, _ = rich_changes(("https://x",))
    sf = mk()
    sf.ingest_frames([(0, encode_frame(a)), (1, encode_frame(b))])
    sf.drain()
    so = mk()
    so.ingest(0, a)
    so.ingest(1, b)
    so.drain()
    assert sf.digest() == so.digest()


def test_fallback_doc_full_digest_parity():
    """A demoted doc (host scalar replay) hashes formatting + map registers
    bit-identically to a device-resident peer holding the same state."""
    chs, _ = rich_changes()
    on_device = mk(1)
    on_device.ingest_frames([(0, encode_frame(chs))])
    on_device.drain()
    assert not on_device.docs[0].fallback
    replayed = mk(1)
    replayed.ingest_frames([(0, encode_frame(chs))])
    replayed.drain()
    replayed.docs[0].fallback = True
    assert on_device.digest() == replayed.digest()


@pytest.mark.parametrize(
    "ops",
    [
        # formatting-only: one extra em mark, text unchanged
        [{"path": ["text"], "action": "addMark", "startIndex": 6,
          "endIndex": 9, "markType": "em"}],
        # link attr only: same span, different url
        [{"path": ["text"], "action": "addMark", "startIndex": 0,
          "endIndex": 4, "markType": "link",
          "attrs": {"url": "https://other"}}],
        # comment set only
        [{"path": ["text"], "action": "addMark", "startIndex": 2,
          "endIndex": 6, "markType": "comment", "attrs": {"id": "cm-new"}}],
        # map register only: overwrite one value
        [{"path": ["meta"], "action": "set", "key": "n", "value": -8}],
        # map register only: delete a key
        [{"path": ["meta"], "action": "del", "key": "title"}],
        # nested map creation only
        [{"path": [], "action": "makeMap", "key": "sub"}],
    ],
    ids=["em-mark", "link-url", "comment-id", "map-set", "map-del", "make-map"],
)
def test_single_non_text_divergence_flips_digest(ops):
    """Each formatting-/map-only divergence (text identical) flips the full
    digest; the text-only digest stays blind to it — the r2 gap."""
    chs, _ = rich_changes()
    base = mk(1)
    base.ingest_frames([(0, encode_frame(chs))])
    base.drain()
    diverged = mk(1)
    diverged.ingest_frames([(0, encode_frame(chs))])
    extra = extend(chs, "a2", ops)
    diverged.ingest_frames([(0, encode_frame([extra]))])
    diverged.drain()
    assert base.digest(full=False) == diverged.digest(full=False)
    assert base.digest() != diverged.digest()


def test_fallback_parity_with_empty_link_url():
    """An EMPTY link url is interned device-side (link_attr > 0) and must be
    hashed by the host mirror too — a truthiness check there made converged
    fallback/device peers diverge (review finding r3)."""
    chs, _ = rich_changes()
    extra = extend(chs, "a2", [
        {"path": ["text"], "action": "addMark", "startIndex": 7,
         "endIndex": 10, "markType": "link", "attrs": {"url": ""}},
    ])
    on_device = mk(1)
    on_device.ingest_frames([(0, encode_frame([*chs, extra]))])
    on_device.drain()
    assert not on_device.docs[0].fallback
    replayed = mk(1)
    replayed.ingest_frames([(0, encode_frame([*chs, extra]))])
    replayed.drain()
    replayed.docs[0].fallback = True
    assert on_device.digest() == replayed.digest()


def test_digest_async_matches_sync():
    """digest_async schedules the fused program without synchronizing;
    wait() must return exactly digest(), including host-replay fallbacks."""
    a, _ = rich_changes()
    b, _ = rich_changes(("https://x",))
    s = mk()
    s.ingest_frames([(0, encode_frame(a)), (1, encode_frame(b))])
    s.drain()
    pending = s.digest_async()
    assert pending.wait() == s.digest()
    assert pending.wait() == pending.wait()  # idempotent fetch

    # with a fallback doc: wait() folds the host-replay hash
    sf = mk()
    sf.ingest_frames([(0, encode_frame(a)), (1, encode_frame(b))])
    sf.drain()
    sf.docs[1].fallback = True
    assert sf.digest_async().wait() == s.digest()


def test_full_digest_mesh_invariance():
    """The full digest is a doc-sum, so mesh size must not change it."""
    import jax
    from peritext_tpu.parallel.mesh import make_mesh

    a, _ = rich_changes()
    b, _ = rich_changes(("https://x",))
    digests = {}
    for n in (1, 2, 4):
        mesh = make_mesh(n) if n > 1 else None
        s = mk(mesh=mesh)
        s.ingest_frames([(0, encode_frame(a)), (1, encode_frame(b))])
        s.drain()
        digests[n] = s.digest()
    assert len(set(digests.values())) == 1, digests


# -- incremental (touched-doc) digest: VERDICT r3 task 2 ---------------------


def test_incremental_digest_equals_refresh_across_rounds():
    """After every round of a multi-round, multi-block session the carried
    incremental digest must equal a from-scratch recompute (the verification
    path)."""
    import random

    from peritext_tpu.testing.fuzz import generate_workload

    workloads = generate_workload(seed=11, num_docs=12, ops_per_doc=48)
    rng = random.Random(4)
    arrival = []
    for w in workloads:
        chs = [ch for log in w.values() for ch in log]
        rng.shuffle(chs)
        size = -(-len(chs) // 3)
        arrival.append([chs[i:i + size] for i in range(0, len(chs), size)])
    s = StreamingMerge(
        num_docs=12, actors=("doc1", "doc2", "doc3"), read_chunk=4,
        round_insert_capacity=256, round_delete_capacity=128,
        round_mark_capacity=128,
    )
    for r in range(3):
        for d, batches in enumerate(arrival):
            if r < len(batches):
                s.ingest(d, batches[r])
        s.drain()
        assert s.digest() == s.digest(refresh=True)


def test_incremental_digest_survives_fallback_and_overflow_transitions():
    """Carried block digests must invalidate when docs demote (fallback) or
    overflow out of the device sum — the transitions that re-route hashing
    to host-side replay."""
    a, da = rich_changes()
    s = mk(n=6, read_chunk=2, slot_capacity=128)
    for d in range(6):
        s.ingest_frames([(d, encode_frame(a))])
    s.drain()
    assert s.digest() == s.digest(refresh=True)

    # fallback transition WITHOUT a round bump: flip a doc by hand (the
    # read-time demotion shape) — the carried mask check must catch it
    s.docs[3].fallback = True
    assert s.digest() == s.digest(refresh=True)

    # fallback transition via a device-inexpressible op (float map value)
    fl = extend(a, "a2", [{"path": [], "action": "set", "key": "r", "value": 0.5}])
    s.ingest_frames([(1, encode_frame([fl]))])
    s.drain()
    assert s.docs[1].fallback
    assert s.digest() == s.digest(refresh=True)

    # overflow transition: a doc outgrows its slot capacity mid-session
    big = extend(a, "a2", [{"path": ["text"], "action": "insert", "index": 1,
                            "values": list("x" * 200)}])
    s.ingest_frames([(2, encode_frame([big]))])
    s.drain()
    assert s.digest() == s.digest(refresh=True)


def test_clean_blocks_skip_resolution_entirely():
    """The point of the carry: a digest after an idle round (or a round that
    touched one block) re-resolves only the touched blocks."""
    a, _ = rich_changes()
    b, _ = rich_changes(("https://x",))
    s = mk(n=8, read_chunk=2)  # 4 blocks of 2 docs
    for d in range(8):
        s.ingest_frames([(d, encode_frame(a))])
    s.drain()
    baseline = s.digest()

    calls = []
    orig = StreamingMerge._digest_resolution

    def counting(self, bi):
        calls.append(bi)
        return orig(self, bi)

    StreamingMerge._digest_resolution = counting
    try:
        # no rounds in between: every block rides the carry
        assert s.digest() == baseline
        assert calls == []

        # touch ONLY doc 5 (block 2): exactly that block re-resolves
        extra = extend(a, "a2", [{"path": ["text"], "action": "insert",
                                  "index": 2, "values": ["z"]}])
        s.ingest_frames([(5, encode_frame([extra]))])
        s.drain()
        changed = s.digest()
        assert calls == [2]
        assert changed != baseline

        # async path rides the carry the same way
        calls.clear()
        pending = s.digest_async()
        assert pending.wait() == changed
        assert calls == []
    finally:
        StreamingMerge._digest_resolution = orig
    assert s.digest(refresh=True) == changed


def test_touched_rows_digest_row0_comment_doc_with_padding():
    """Regression: the gathered-rows digest pads its row-index vector with
    zeros; the padding must never shadow the REAL row 0's comment-id
    tables (a frame-mode comment doc at row 0, touched alone, once made
    digest() != digest(refresh=True))."""
    from peritext_tpu.core.doc import Doc
    from peritext_tpu.parallel.codec import encode_frame
    from peritext_tpu.parallel.streaming import StreamingMerge

    d = 12
    frames_a, frames_b = [], []
    for i in range(d):
        doc = Doc(actor_id="doc1")
        c1, _ = doc.change([
            {"path": [], "action": "makeList", "key": "text"},
            {"path": ["text"], "action": "insert", "index": 0,
             "values": list(f"hello world {i}")},
            {"path": ["text"], "action": "addMark", "startIndex": 0,
             "endIndex": 5, "markType": "comment",
             "attrs": {"id": f"c-{i}"}},
        ])
        c2, _ = doc.change([
            {"path": ["text"], "action": "addMark", "startIndex": 6,
             "endIndex": 11, "markType": "strong"},
        ])
        frames_a.append(encode_frame([c1]))
        frames_b.append(encode_frame([c2]))

    s = StreamingMerge(num_docs=d, actors=("doc1",), slot_capacity=64)
    s.ingest_frames(list(enumerate(frames_a)))
    s.drain()
    s.digest()  # carried plane now covers every row
    # touch ONLY doc 0 (physical row 0) -> sub-batch path, K=8 bucket pads
    # seven zero entries that all alias row 0
    s.ingest_frame(0, frames_b[0])
    s.drain()
    incremental = s.digest()
    assert incremental == s.digest(refresh=True)
