"""The bridge's ``tpu`` merge backend (the BASELINE boundary contract):
same ``InputOperation`` in, same ``Patch`` vocabulary out, but the editor
view is driven by the device engine's incremental patch stream instead of
the scalar CRDT's patches.  Scalar-backend editors are the oracle.
"""

import pytest

from peritext_tpu.bridge import Editor, create_editor, editor_doc_from_crdt, initialize_docs
from peritext_tpu.bridge.commands import set_link, toggle_bold, type_text
from peritext_tpu.bridge.model import Transaction
from peritext_tpu.parallel.pubsub import Publisher

ACTORS = ("alice", "bob")


def make_pair(backends=("tpu", "tpu"), text="The Peritext editor"):
    pub = Publisher()
    alice = create_editor("alice", pub, backend=backends[0], actors=ACTORS)
    bob = create_editor("bob", pub, backend=backends[1], actors=ACTORS)
    initialize_docs([alice, bob], text)
    return pub, alice, bob


def assert_views_match_scalar_render(*editors):
    """The session-fed view must equal the full scalar CRDT render — the
    cross-backend version of the bridge's dual-oracle invariant."""
    for editor in editors:
        assert editor.view == editor_doc_from_crdt(editor.doc), editor.actor_id


def test_local_typing_updates_view_immediately():
    _, alice, bob = make_pair()
    type_text(alice, 1, "Hey! ")
    assert alice.text == "Hey! The Peritext editor"
    assert_views_match_scalar_render(alice)


def test_concurrent_edits_converge_via_tpu_backend():
    _, alice, bob = make_pair()
    type_text(alice, 1, "A")
    toggle_bold(bob, 2, 10)
    set_link(bob, 5, 13, "https://x.test")
    alice.sync()
    bob.sync()
    assert alice.view == bob.view
    assert_views_match_scalar_render(alice, bob)


def test_mixed_backends_converge():
    _, alice, bob = make_pair(backends=("scalar", "tpu"))
    type_text(alice, 1, "Hello ")
    toggle_bold(bob, 1, 6)
    alice.sync()
    bob.sync()
    assert alice.view == bob.view
    assert_views_match_scalar_render(alice, bob)


def test_out_of_order_delivery_with_tpu_backend():
    alice = Editor("alice", backend="tpu", actors=ACTORS)
    bob = Editor("bob", backend="tpu", actors=ACTORS)
    initialize_docs([alice, bob], "abc")
    c1 = alice.dispatch(Transaction().insert_text(1, "x"))
    c2 = alice.dispatch(Transaction().insert_text(2, "y"))
    c3 = alice.dispatch(Transaction().insert_text(3, "z"))
    bob.apply_remote(c3)   # held back (causal gap)
    bob.apply_remote(c2)   # still held back
    assert bob.text == "abc"
    bob.apply_remote(c1)   # releases all three
    assert bob.text == alice.text == "xyzabc"
    assert_views_match_scalar_render(alice, bob)


def test_map_ops_stay_on_device_and_views_stay_correct():
    _, alice, bob = make_pair()
    # comment bodies live in a nested map: the device map-register path
    # (ops/kernel._apply_map_doc) expresses makeMap/set/del, so the backend
    # session must NOT demote, and the root map must materialize correctly
    alice.dispatch_input_ops([{"path": [], "action": "makeMap", "key": "comments"}])
    type_text(alice, 1, "Q")
    alice.sync()
    bob.sync()
    assert not alice.session.docs[0].fallback
    assert alice.view == bob.view
    assert_views_match_scalar_render(alice, bob)
    assert alice.session.read_root(0).get("comments") == {}


def test_unknown_backend_rejected():
    with pytest.raises(ValueError):
        Editor("zoe", backend="gpu")


def test_fuzz_session_through_tpu_editors():
    import random

    rng = random.Random(11)
    pub, alice, bob = make_pair()
    editors = [alice, bob]
    for step in range(40):
        ed = editors[rng.randrange(2)]
        n = len(ed.view)
        roll = rng.random()
        if roll < 0.5 or n < 4:
            pos = rng.randrange(1, n + 2 - 1) if n else 1
            type_text(ed, pos, rng.choice("abcdef "))
        elif roll < 0.75:
            a = rng.randrange(1, n)
            b = rng.randrange(a + 1, n + 1)
            toggle_bold(ed, a, b)
        else:
            a = rng.randrange(1, n)
            b = rng.randrange(a + 1, n + 1)
            ed.dispatch(Transaction().delete(a, b))
        if rng.random() < 0.3:
            alice.sync()
            bob.sync()
    alice.sync()
    bob.sync()
    alice.sync()
    assert alice.view == bob.view
    assert_views_match_scalar_render(alice, bob)
