"""Port of the reference's example-based suite (reference test/micromerge.ts,
49 cases).  Each case seeds two replicas with shared history, applies
concurrent changes, cross-merges, and asserts that both the batch read path
(get_text_with_formatting) and the incremental patch path (accumulate_patches)
converge to the expected span list."""

import pytest

from peritext_tpu import Doc, span
from peritext_tpu.testing import accumulate_patches, generate_docs

DEFAULT_TEXT = "The Peritext editor"


def run_trace_spec(
    initial_text=DEFAULT_TEXT,
    pre_ops=None,
    input_ops1=(),
    input_ops2=(),
    expected_result=None,
):
    """Reference testConcurrentWrites (test/micromerge.ts:45-85)."""
    docs, patches, _ = generate_docs(initial_text)
    doc1, doc2 = docs
    patches1, patches2 = patches

    if pre_ops:
        change0, patches0 = doc1.change([{**op, "path": ["text"]} for op in pre_ops])
        patches1 = patches1 + patches0
        patches2 = patches2 + doc2.apply_change(change0)

    change1, p1 = doc1.change([{**op, "path": ["text"]} for op in input_ops1])
    patches1 = patches1 + p1
    change2, p2 = doc2.change([{**op, "path": ["text"]} for op in input_ops2])
    patches2 = patches2 + p2

    patches2 = patches2 + doc2.apply_change(change1)
    patches1 = patches1 + doc1.apply_change(change2)

    # Batch read path
    assert doc1.get_text_with_formatting(["text"]) == expected_result
    assert doc2.get_text_with_formatting(["text"]) == expected_result
    # Incremental patch path
    assert accumulate_patches(patches1) == expected_result
    assert accumulate_patches(patches2) == expected_result


STRONG = {"strong": {"active": True}}
EM = {"em": {"active": True}}


def test_insert_and_delete_text():
    docs, _, _ = generate_docs("abcde")
    doc1 = docs[0]
    doc1.change([{"path": ["text"], "action": "delete", "index": 0, "count": 3}])
    assert "".join(doc1.root["text"]) == "de"


def test_records_local_changes_in_deps_clock():
    docs, _, _ = generate_docs("a")
    doc1, doc2 = docs
    change2, _ = doc2.change(
        [{"path": ["text"], "action": "insert", "index": 1, "values": ["b"]}]
    )
    doc1.apply_change(change2)  # must not raise
    assert doc1.root["text"] == ["a", "b"]
    assert doc2.root["text"] == ["a", "b"]


def test_concurrent_deletion_and_insertion():
    run_trace_spec(
        initial_text="abrxabra",
        input_ops1=[
            {"action": "delete", "index": 3, "count": 1},
            {"action": "insert", "index": 4, "values": ["c", "a"]},
        ],
        input_ops2=[{"action": "insert", "index": 5, "values": ["d", "a"]}],
        expected_result=[span("abracadabra")],
    )


def test_flattens_local_formatting_into_spans():
    run_trace_spec(
        input_ops1=[
            {"action": "addMark", "startIndex": 4, "endIndex": 12, "markType": "strong"}
        ],
        expected_result=[
            span("The "),
            span("Peritext", dict(STRONG)),
            span(" editor"),
        ],
    )


def test_merges_concurrent_overlapping_bold_and_italic():
    run_trace_spec(
        input_ops1=[
            {"action": "addMark", "startIndex": 0, "endIndex": 12, "markType": "strong"}
        ],
        input_ops2=[
            {"action": "addMark", "startIndex": 4, "endIndex": 19, "markType": "em"}
        ],
        expected_result=[
            span("The ", dict(STRONG)),
            span("Peritext", {**STRONG, **EM}),
            span(" editor", dict(EM)),
        ],
    )


def test_merges_insert_at_end_and_italic_to_end():
    run_trace_spec(
        input_ops1=[
            {"action": "addMark", "startIndex": 0, "endIndex": 12, "markType": "strong"},
            {"action": "insert", "index": 19, "values": list(" is great!")},
        ],
        input_ops2=[
            {"action": "addMark", "startIndex": 4, "endIndex": 19, "markType": "em"}
        ],
        expected_result=[
            span("The ", dict(STRONG)),
            span("Peritext", {**STRONG, **EM}),
            span(" editor is great!", dict(EM)),
        ],
    )


def test_merges_concurrent_bold_and_unbold():
    run_trace_spec(
        input_ops1=[
            {"action": "addMark", "startIndex": 0, "endIndex": 12, "markType": "strong"}
        ],
        input_ops2=[
            {"action": "removeMark", "startIndex": 4, "endIndex": 19, "markType": "strong"}
        ],
        expected_result=[span("The ", dict(STRONG)), span("Peritext editor")],
    )


def test_unbold_inside_bold():
    run_trace_spec(
        input_ops1=[
            {"action": "addMark", "startIndex": 0, "endIndex": 19, "markType": "strong"}
        ],
        input_ops2=[
            {"action": "removeMark", "startIndex": 4, "endIndex": 12, "markType": "strong"}
        ],
        expected_result=[
            span("The ", dict(STRONG)),
            span("Peritext"),
            span(" editor", dict(STRONG)),
        ],
    )


def test_unbold_one_character():
    run_trace_spec(
        input_ops1=[
            {"action": "addMark", "startIndex": 0, "endIndex": 19, "markType": "strong"}
        ],
        input_ops2=[
            {"action": "removeMark", "startIndex": 4, "endIndex": 5, "markType": "strong"}
        ],
        expected_result=[
            span("The ", dict(STRONG)),
            span("P"),
            span("eritext editor", dict(STRONG)),
        ],
    )


def test_spans_collapsed_to_zero_width():
    run_trace_spec(
        pre_ops=[
            {"action": "addMark", "startIndex": 4, "endIndex": 12, "markType": "strong"},
            {"action": "delete", "index": 4, "count": 8},
        ],
        input_ops1=[{"action": "insert", "index": 4, "values": ["x"]}],
        expected_result=[span("The x editor")],
    )


# --- span growing behavior on a single actor (reference :322) ---


def test_grows_bold_span_to_the_right():
    run_trace_spec(
        input_ops2=[
            {"action": "addMark", "startIndex": 4, "endIndex": 12, "markType": "strong"},
            {"action": "insert", "index": 12, "values": ["!"]},
        ],
        expected_result=[
            span("The "),
            span("Peritext!", dict(STRONG)),
            span(" editor"),
        ],
    )


def test_does_not_grow_bold_span_to_the_left():
    run_trace_spec(
        input_ops2=[
            {"action": "addMark", "startIndex": 4, "endIndex": 12, "markType": "strong"},
            {"action": "insert", "index": 4, "values": ["!"]},
        ],
        expected_result=[
            span("The !"),
            span("Peritext", dict(STRONG)),
            span(" editor"),
        ],
    )


def test_does_not_grow_link_to_the_right():
    run_trace_spec(
        input_ops2=[
            {
                "action": "addMark",
                "startIndex": 4,
                "endIndex": 12,
                "markType": "link",
                "attrs": {"url": "inkandswitch.com"},
            },
            {"action": "insert", "index": 12, "values": ["!"]},
        ],
        expected_result=[
            span("The "),
            span("Peritext", {"link": {"active": True, "url": "inkandswitch.com"}}),
            span("! editor"),
        ],
    )


def test_does_not_grow_link_to_the_left():
    run_trace_spec(
        input_ops2=[
            {
                "action": "addMark",
                "startIndex": 4,
                "endIndex": 12,
                "markType": "link",
                "attrs": {"url": "inkandswitch.com"},
            },
            {"action": "insert", "index": 4, "values": ["!"]},
        ],
        expected_result=[
            span("The !"),
            span("Peritext", {"link": {"active": True, "url": "inkandswitch.com"}}),
            span(" editor"),
        ],
    )


def test_grows_only_bold_when_bold_and_link_end_together():
    run_trace_spec(
        input_ops2=[
            {
                "action": "addMark",
                "startIndex": 4,
                "endIndex": 12,
                "markType": "link",
                "attrs": {"url": "inkandswitch.com"},
            },
            {"action": "addMark", "startIndex": 4, "endIndex": 12, "markType": "strong"},
            {"action": "insert", "index": 12, "values": ["!"]},
        ],
        expected_result=[
            span("The "),
            span(
                "Peritext",
                {"link": {"active": True, "url": "inkandswitch.com"}, **STRONG},
            ),
            span("!", dict(STRONG)),
            span(" editor"),
        ],
    )


def test_grows_adjacent_bold_and_unbold_spans():
    run_trace_spec(
        initial_text="ABCDE",
        input_ops1=[
            {"action": "addMark", "startIndex": 0, "endIndex": 5, "markType": "strong"},
            {"action": "removeMark", "startIndex": 1, "endIndex": 4, "markType": "strong"},
            {"action": "insert", "index": 1, "values": ["F"]},
            {"action": "insert", "index": 5, "values": ["G"]},
        ],
        expected_result=[
            span("AF", dict(STRONG)),
            span("BCDG"),
            span("E", dict(STRONG)),
        ],
    )


def test_growth_behavior_when_boundary_is_tombstone():
    run_trace_spec(
        initial_text="ABCDE",
        input_ops1=[
            {
                "action": "addMark",
                "startIndex": 1,
                "endIndex": 4,
                "markType": "link",
                "attrs": {"url": "inkandswitch.com"},
            },
            {"action": "delete", "index": 1, "count": 1},
            {"action": "delete", "index": 2, "count": 1},
            {"action": "insert", "index": 2, "values": ["F"]},
        ],
        expected_result=[
            span("A"),
            span("C", {"link": {"active": True, "url": "inkandswitch.com"}}),
            span("FE"),
        ],
    )


# --- span growing behavior with concurrent edits (reference :568) ---


def test_concurrent_bold_and_insertion_at_boundary():
    run_trace_spec(
        input_ops1=[
            {"action": "addMark", "startIndex": 4, "endIndex": 12, "markType": "strong"}
        ],
        input_ops2=[
            {"action": "insert", "index": 4, "values": ["*"]},
            {"action": "insert", "index": 13, "values": ["*"]},
        ],
        expected_result=[
            span("The *"),
            span("Peritext*", dict(STRONG)),
            span(" editor"),
        ],
    )


def test_insertion_where_one_mark_ends_and_another_begins():
    run_trace_spec(
        input_ops1=[
            {"action": "addMark", "startIndex": 4, "endIndex": 12, "markType": "strong"},
            {"action": "addMark", "startIndex": 12, "endIndex": 19, "markType": "em"},
        ],
        input_ops2=[{"action": "insert", "index": 12, "values": list("[1]")}],
        expected_result=[
            span("The "),
            span("Peritext[1]", dict(STRONG)),
            span(" editor", dict(EM)),
        ],
    )


def test_insertion_at_boundary_between_bold_and_unbolded():
    run_trace_spec(
        initial_text="AC",
        input_ops1=[
            {"action": "addMark", "startIndex": 0, "endIndex": 2, "markType": "strong"},
            {"action": "removeMark", "startIndex": 1, "endIndex": 2, "markType": "strong"},
        ],
        input_ops2=[{"action": "insert", "index": 1, "values": ["B"]}],
        expected_result=[span("AB", dict(STRONG)), span("C")],
    )


def test_insertion_at_boundary_between_unbolded_and_bold():
    run_trace_spec(
        initial_text="AC",
        input_ops1=[
            {"action": "addMark", "startIndex": 0, "endIndex": 2, "markType": "strong"},
            {"action": "removeMark", "startIndex": 0, "endIndex": 1, "markType": "strong"},
        ],
        input_ops2=[{"action": "insert", "index": 1, "values": ["B"]}],
        expected_result=[span("AB"), span("C", dict(STRONG))],
    )


def test_concurrent_adjacent_formatting_ops():
    run_trace_spec(
        initial_text="ABCDE",
        input_ops1=[
            {"action": "addMark", "startIndex": 1, "endIndex": 2, "markType": "strong"}
        ],
        input_ops2=[
            {"action": "addMark", "startIndex": 2, "endIndex": 3, "markType": "strong"}
        ],
        expected_result=[span("A"), span("BC", dict(STRONG)), span("DE")],
    )


def test_addmark_boundary_that_is_tombstone():
    run_trace_spec(
        initial_text="The *Peritext* editor",
        input_ops1=[
            {"action": "addMark", "startIndex": 4, "endIndex": 14, "markType": "strong"},
            {"action": "delete", "index": 4, "count": 1},
            {"action": "delete", "index": 12, "count": 1},
        ],
        input_ops2=[
            {"action": "insert", "index": 5, "values": ["_"]},
            {"action": "insert", "index": 14, "values": ["_"]},
        ],
        expected_result=[
            span("The "),
            span("_Peritext_", dict(STRONG)),
            span(" editor"),
        ],
    )


def test_insertion_into_deleted_span_with_mark():
    run_trace_spec(
        pre_ops=[
            {"action": "addMark", "startIndex": 4, "endIndex": 12, "markType": "strong"}
        ],
        input_ops1=[{"action": "delete", "index": 4, "count": 8}],
        input_ops2=[
            {"action": "delete", "index": 5, "count": 3},
            {"action": "insert", "index": 5, "values": list("ara")},
        ],
        expected_result=[
            span("The "),
            span("ara", dict(STRONG)),
            span(" editor"),
        ],
    )


def test_formatting_on_deleted_span():
    run_trace_spec(
        input_ops1=[{"action": "delete", "index": 4, "count": 9}],
        input_ops2=[
            {"action": "addMark", "startIndex": 5, "endIndex": 11, "markType": "strong"}
        ],
        expected_result=[span("The editor")],
    )


def test_formatting_on_single_character():
    run_trace_spec(
        input_ops2=[
            {"action": "addMark", "startIndex": 4, "endIndex": 5, "markType": "strong"}
        ],
        expected_result=[
            span("The "),
            span("P", dict(STRONG)),
            span("eritext editor"),
        ],
    )


def test_formatting_on_single_deleted_character():
    run_trace_spec(
        initial_text="ABCDE",
        input_ops1=[{"action": "delete", "index": 2, "count": 1}],
        input_ops2=[
            {
                "action": "addMark",
                "startIndex": 2,
                "endIndex": 3,
                "markType": "link",
                "attrs": {"url": "inkandswitch.com"},
            }
        ],
        expected_result=[span("ABDE")],
    )


def test_mark_starting_and_ending_after_visible_sequence():
    run_trace_spec(
        initial_text="ABCDE",
        input_ops1=[
            {
                "action": "addMark",
                "startIndex": 2,
                "endIndex": 4,
                "markType": "link",
                "attrs": {"url": "A.com"},
            },
            {"action": "delete", "index": 1, "count": 2},
            {"action": "delete", "index": 2, "count": 1},
        ],
        input_ops2=[
            {
                "action": "addMark",
                "startIndex": 3,
                "endIndex": 5,
                "markType": "link",
                "attrs": {"url": "A.com"},
            }
        ],
        expected_result=[
            span("A"),
            span("D", {"link": {"active": True, "url": "A.com"}}),
        ],
    )


def test_mark_ending_after_visible_sequence():
    run_trace_spec(
        initial_text="ABCDE",
        input_ops1=[{"action": "delete", "index": 4, "count": 1}],
        input_ops2=[
            {
                "action": "addMark",
                "startIndex": 3,
                "endIndex": 5,
                "markType": "link",
                "attrs": {"url": "A.com"},
            }
        ],
        expected_result=[
            span("ABC"),
            span("D", {"link": {"active": True, "url": "A.com"}}),
        ],
    )


# --- patches (reference :911-1029) ---


def test_patch_for_simple_insertion():
    docs, _, _ = generate_docs()
    doc1, doc2 = docs
    input_ops = [{"path": ["text"], "action": "insert", "index": 7, "values": ["a"]}]
    change, _ = doc1.change(input_ops)
    patch = doc2.apply_change(change)
    assert patch == [{**input_ops[0], "marks": {}}]


def test_patch_with_adjusted_insertion_index():
    docs, _, _ = generate_docs()
    doc1, doc2 = docs
    doc1.change(
        [{"path": ["text"], "action": "insert", "index": 1, "values": ["a", "b", "c"]}]
    )
    change2, _ = doc2.change(
        [{"path": ["text"], "action": "insert", "index": 2, "values": ["b"]}]
    )
    patch = doc1.apply_change(change2)
    assert patch == [
        {"path": ["text"], "action": "insert", "index": 5, "values": ["b"], "marks": {}}
    ]


def test_patch_for_simple_deletion():
    docs, _, _ = generate_docs()
    doc1, doc2 = docs
    input_ops = [{"path": ["text"], "action": "delete", "index": 5, "count": 1}]
    change, _ = doc1.change(input_ops)
    patch = doc2.apply_change(change)
    assert patch == input_ops


def test_multichar_deletion_becomes_single_char_deletions():
    docs, _, _ = generate_docs()
    doc1, doc2 = docs
    change, _ = doc1.change(
        [{"path": ["text"], "action": "delete", "index": 5, "count": 2}]
    )
    patch = doc2.apply_change(change)
    assert patch == [
        {"path": ["text"], "action": "delete", "index": 5, "count": 1},
        {"path": ["text"], "action": "delete", "index": 5, "count": 1},
    ]


# --- comments (reference :1031-1142) ---


def test_single_comment_in_flattened_spans():
    docs, _, _ = generate_docs()
    doc1 = docs[0]
    doc1.change(
        [
            {
                "path": ["text"],
                "action": "addMark",
                "startIndex": 4,
                "endIndex": 12,
                "markType": "comment",
                "attrs": {"id": "abc-123"},
            }
        ]
    )
    assert doc1.root["text"] == list(DEFAULT_TEXT)
    assert doc1.get_text_with_formatting(["text"]) == [
        span("The "),
        span("Peritext", {"comment": [{"id": "abc-123"}]}),
        span(" editor"),
    ]


def test_two_comments_same_user():
    docs, _, _ = generate_docs()
    doc1 = docs[0]
    doc1.change(
        [
            {
                "path": ["text"],
                "action": "addMark",
                "startIndex": 0,
                "endIndex": 12,
                "markType": "comment",
                "attrs": {"id": "abc-123"},
            },
            {
                "path": ["text"],
                "action": "addMark",
                "startIndex": 4,
                "endIndex": 19,
                "markType": "comment",
                "attrs": {"id": "def-789"},
            },
        ]
    )
    assert doc1.get_text_with_formatting(["text"]) == [
        span("The ", {"comment": [{"id": "abc-123"}]}),
        span("Peritext", {"comment": [{"id": "abc-123"}, {"id": "def-789"}]}),
        span(" editor", {"comment": [{"id": "def-789"}]}),
    ]


def test_overlapping_comments_from_different_users():
    run_trace_spec(
        input_ops1=[
            {
                "action": "addMark",
                "startIndex": 0,
                "endIndex": 12,
                "markType": "comment",
                "attrs": {"id": "abc-123"},
            }
        ],
        input_ops2=[
            {
                "action": "addMark",
                "startIndex": 4,
                "endIndex": 19,
                "markType": "comment",
                "attrs": {"id": "def-789"},
            }
        ],
        expected_result=[
            span("The ", {"comment": [{"id": "abc-123"}]}),
            span("Peritext", {"comment": [{"id": "abc-123"}, {"id": "def-789"}]}),
            span(" editor", {"comment": [{"id": "def-789"}]}),
        ],
    )


# --- links (reference :1144-1289) ---


def test_single_link_in_flattened_spans():
    docs, _, _ = generate_docs()
    doc1 = docs[0]
    doc1.change(
        [
            {
                "path": ["text"],
                "action": "addMark",
                "startIndex": 4,
                "endIndex": 12,
                "markType": "link",
                "attrs": {"url": "https://inkandswitch.com"},
            }
        ]
    )
    assert doc1.get_text_with_formatting(["text"]) == [
        span("The "),
        span("Peritext", {"link": {"active": True, "url": "https://inkandswitch.com"}}),
        span(" editor"),
    ]


def test_link_lww_fully_overlapping():
    run_trace_spec(
        input_ops1=[
            {
                "action": "addMark",
                "startIndex": 4,
                "endIndex": 12,
                "markType": "link",
                "attrs": {"url": "https://inkandswitch.com"},
            }
        ],
        input_ops2=[
            {
                "action": "addMark",
                "startIndex": 4,
                "endIndex": 12,
                "markType": "link",
                "attrs": {"url": "https://google.com"},
            }
        ],
        expected_result=[
            span("The "),
            span("Peritext", {"link": {"active": True, "url": "https://google.com"}}),
            span(" editor"),
        ],
    )


def test_link_lww_partially_overlapping():
    run_trace_spec(
        input_ops1=[
            {
                "action": "addMark",
                "startIndex": 0,
                "endIndex": 12,
                "markType": "link",
                "attrs": {"url": "https://inkandswitch.com"},
            }
        ],
        input_ops2=[
            {
                "action": "addMark",
                "startIndex": 4,
                "endIndex": 19,
                "markType": "link",
                "attrs": {"url": "https://google.com"},
            }
        ],
        expected_result=[
            span("The ", {"link": {"active": True, "url": "https://inkandswitch.com"}}),
            span(
                "Peritext editor", {"link": {"active": True, "url": "https://google.com"}}
            ),
        ],
    )


def test_links_ending_at_same_place_converge():
    run_trace_spec(
        input_ops1=[
            {
                "action": "addMark",
                "startIndex": 11,
                "endIndex": 12,
                "markType": "link",
                "attrs": {"url": "https://inkandswitch.com"},
            }
        ],
        input_ops2=[
            {
                "action": "addMark",
                "startIndex": 4,
                "endIndex": 12,
                "markType": "link",
                "attrs": {"url": "https://google.com"},
            }
        ],
        expected_result=[
            span("The "),
            span("Peritext", {"link": {"active": True, "url": "https://google.com"}}),
            span(" editor"),
        ],
    )


# --- cursors (reference :1291-1418) ---


def _cursor_doc():
    docs, _, _ = generate_docs()
    return docs[0]


def test_resolve_cursor_position():
    doc1 = _cursor_doc()
    cursor = doc1.get_cursor(["text"], 5)
    assert doc1.resolve_cursor(cursor) == 5


def test_cursor_increments_on_insert_before():
    doc1 = _cursor_doc()
    cursor = doc1.get_cursor(["text"], 5)
    doc1.change(
        [{"path": ["text"], "action": "insert", "index": 0, "values": ["a", "b", "c"]}]
    )
    assert doc1.resolve_cursor(cursor) == 8


def test_cursor_stays_on_insert_after():
    doc1 = _cursor_doc()
    cursor = doc1.get_cursor(["text"], 5)
    doc1.change(
        [{"path": ["text"], "action": "insert", "index": 7, "values": ["a", "b", "c"]}]
    )
    assert doc1.resolve_cursor(cursor) == 5


def test_cursor_moves_left_on_delete_before():
    doc1 = _cursor_doc()
    cursor = doc1.get_cursor(["text"], 5)
    doc1.change([{"path": ["text"], "action": "delete", "index": 0, "count": 3}])
    assert doc1.resolve_cursor(cursor) == 2


def test_cursor_stays_on_delete_after():
    doc1 = _cursor_doc()
    cursor = doc1.get_cursor(["text"], 5)
    doc1.change([{"path": ["text"], "action": "delete", "index": 7, "count": 3}])
    assert doc1.resolve_cursor(cursor) == 5


def test_cursor_returns_zero_when_prefix_deleted():
    doc1 = _cursor_doc()
    cursor = doc1.get_cursor(["text"], 5)
    doc1.change([{"path": ["text"], "action": "delete", "index": 0, "count": 7}])
    assert doc1.resolve_cursor(cursor) == 0
