"""The browser essay demo's HTTP contract (demos/web/essay_server.py):
the full-length authored trace plays through two editors with remote-change
highlights, section banners, an op log, and endless-loop restart — the
reference's essay embed experience (src/essay-demo.ts:47-132)."""

import json
import threading
import urllib.request

import pytest


@pytest.fixture(scope="module")
def essay_url():
    import importlib.util
    from http.server import ThreadingHTTPServer
    from pathlib import Path

    path = Path(__file__).parents[1] / "demos" / "web" / "essay_server.py"
    spec = importlib.util.spec_from_file_location("essay_demo_server", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    mod.SESSION = mod.EssaySession(backend="scalar")
    server = ThreadingHTTPServer(("127.0.0.1", 0), mod.Handler)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    yield f"http://127.0.0.1:{server.server_port}", mod
    server.shutdown()


def _post(url, path, payload):
    req = urllib.request.Request(url + path, data=json.dumps(payload).encode())
    with urllib.request.urlopen(req) as res:
        return json.loads(res.read())


def _get(url, path):
    with urllib.request.urlopen(url + path) as res:
        return json.loads(res.read())


def _text(state, editor):
    return "".join(s["text"] for s in state["editors"][editor]["spans"])


def test_page_serves_player(essay_url):
    url, _ = essay_url
    with urllib.request.urlopen(url + "/") as res:
        page = res.read()
    assert b"Play" in page and b"oplog" in page and b"flash" in page
    # live mark-span sidebars (reference demo's Marks panel, index.html:19-25)
    assert b'id="marks-alice"' in page and b'id="marks-bob"' in page
    assert b"renderMarkPanel" in page


def test_stepping_advances_sections_highlights_and_oplog(essay_url):
    url, _ = essay_url
    state = _post(url, "/restart", {})
    assert state["progress"]["event"] == 0
    # first sync establishes the doc; keep stepping into the typing section
    while state["progress"]["event"] < 40:
        state = _post(url, "/step", {"n": 20})
    assert state["section"] != "warming up"
    assert state["oplog"], "op descriptions must stream to the debug panel"
    assert any("insert" in line for line in state["oplog"])
    # after a sync, the receiving editor records a highlight range
    assert _text(state, "alice")  # content is flowing


def test_full_essay_converges_and_loops(essay_url):
    url, mod = essay_url
    state = _post(url, "/restart", {})
    total = state["progress"]["total"]
    steps = 0
    while state["progress"]["event"] < total and state["progress"]["loops"] == \
            mod.SESSION.loops and steps < total * 2:
        before = state["progress"]["event"]
        state = _post(url, "/step", {"n": 200})
        steps += 200
        if state["progress"]["event"] <= before:  # wrapped
            break
    # play to the exact end of a loop by stepping one event at a time
    while state["progress"]["event"] % total != 0 or state["progress"]["event"] == 0:
        state = _post(url, "/step", {"n": 1})
        if state["progress"]["event"] == total:
            break
    assert state["converged"]
    final_text = _text(state, "alice")
    assert len(final_text) > 400  # the full authored essay, not a stub
    assert _text(state, "bob") == final_text
    # stepping past the end restarts the endless loop from a blank doc
    wrapped = _post(url, "/step", {"n": 3})
    assert wrapped["progress"]["loops"] >= 1
    assert wrapped["progress"]["event"] <= 3


def test_highlight_ranges_are_emitted_on_remote_changes(essay_url):
    url, _ = essay_url
    _post(url, "/restart", {})
    saw_highlight = False
    for _ in range(80):
        state = _post(url, "/step", {"n": 10})
        if state["highlights"]:
            ranges = list(state["highlights"].values())
            assert all(len(r) == 2 and r[0] <= r[1] for r in ranges)
            saw_highlight = True
            break
    assert saw_highlight, "remote changes must flash in the receiving pane"
