"""Input validation: bad input must raise before any replica state mutates
(fixes found in review; the reference poisons its replication stream here)."""

import pytest

from peritext_tpu import Doc, PeritextError
from peritext_tpu.core.errors import IndexOutOfBounds
from peritext_tpu.testing import generate_docs


def test_failed_change_does_not_advance_seq():
    docs, _, _ = generate_docs("ab")
    doc1, doc2 = docs
    with pytest.raises(IndexOutOfBounds):
        doc1.change([{"path": ["text"], "action": "insert", "index": 99, "values": ["x"]}])
    change, _ = doc1.change(
        [{"path": ["text"], "action": "insert", "index": 0, "values": ["y"]}]
    )
    assert change.seq == 2  # initial change was 1; failed attempt consumed nothing
    doc2.apply_change(change)  # peer still in sync
    assert doc2.root["text"] == ["y", "a", "b"]


def test_missing_mark_attrs_rejected_cleanly():
    docs, _, _ = generate_docs("ab")
    doc1 = docs[0]
    with pytest.raises(PeritextError, match="requires attr"):
        doc1.change(
            [
                {
                    "path": ["text"],
                    "action": "addMark",
                    "startIndex": 0,
                    "endIndex": 2,
                    "markType": "link",
                }
            ]
        )
    # Document must remain fully readable (no half-applied mark op).
    assert doc1.get_text_with_formatting(["text"]) == [{"marks": {}, "text": "ab"}]


def test_delete_out_of_bounds_rejected():
    docs, _, _ = generate_docs("abc")
    with pytest.raises(IndexOutOfBounds):
        docs[0].change([{"path": ["text"], "action": "delete", "index": 1, "count": 5}])


def test_mark_range_out_of_bounds_rejected():
    docs, _, _ = generate_docs("abc")
    with pytest.raises(IndexOutOfBounds):
        docs[0].change(
            [
                {
                    "path": ["text"],
                    "action": "addMark",
                    "startIndex": 2,
                    "endIndex": 7,
                    "markType": "strong",
                }
            ]
        )


def test_batch_local_makelist_then_insert_validates():
    doc = Doc("a")
    change, _ = doc.change(
        [
            {"path": [], "action": "makeList", "key": "text"},
            {"path": ["text"], "action": "insert", "index": 0, "values": ["h", "i"]},
        ]
    )
    assert doc.root["text"] == ["h", "i"]
    assert len(change.ops) == 3
