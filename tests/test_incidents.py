"""The fleet incident plane: typed lifecycle, causal correlation, wire
determinism, the merged black-box timeline, and the CLI/exporter surfaces.

Everything here is round-counted and wall-clock-free by construction, so
the pins are exact: two monitors fed the same observations must be
byte-identical, a flapping signal must never mint a second incident, and
arming the plane must compile nothing.
"""

import json
import urllib.error
import urllib.request

import pytest

from peritext_tpu.obs import (
    IncidentMonitor, MetricsServer, TAXONOMY, health_snapshot,
    merge_flight_dumps,
)
from peritext_tpu.obs.__main__ import main as obs_main
from peritext_tpu.obs.exporters import build_info, prometheus_text
from peritext_tpu.obs.incidents import Incident
from peritext_tpu.obs.recorder import FlightRecorder


# ---------------------------------------------------------------------------
# lifecycle: open -> ack -> resolve with two-watermark hysteresis
# ---------------------------------------------------------------------------


class TestLifecycle:
    def test_open_resolve_and_time_to_detection(self):
        m = IncidentMonitor(host="h", open_after=1, clear_after=2)
        fault_round = m.rounds
        m.raise_signal("shed-storm", host="h0", value=4)
        opened = m.advance_round()
        assert [i.kind for i in opened] == ["shed-storm"]
        assert m.open_incidents()[0].status == "open"
        m.advance_round()  # quiet 1
        assert m.open_incidents(), "one quiet round must not resolve yet"
        m.advance_round()  # quiet 2 == clear_after
        assert not m.open_incidents()
        assert m.time_to_detection("shed-storm", fault_round) == 1
        assert m.incident_kinds() == ["shed-storm"]

    def test_open_after_high_watermark(self):
        m = IncidentMonitor(host="h", open_after=3, clear_after=2)
        for n in range(2):
            m.raise_signal("slo-burn", value=2.0)
            assert m.advance_round() == [], f"round {n} is below the streak"
        m.raise_signal("slo-burn", value=2.0)
        assert [i.kind for i in m.advance_round()] == ["slo-burn"]
        # a break in the streak resets it
        m2 = IncidentMonitor(host="h", open_after=2, clear_after=1)
        m2.raise_signal("slo-burn", value=2.0)
        m2.advance_round()
        m2.advance_round()  # gap
        m2.raise_signal("slo-burn", value=2.0)
        assert m2.advance_round() == [], "the gap must reset the streak"

    def test_flap_suppression_re_arms_open_incident(self):
        # the low watermark counts ANY re-fire of an open incident's keys
        # (even sub-threshold flaps) as activity: a flapping signal must
        # re-arm the ONE open incident, never resolve-then-remint
        m = IncidentMonitor(host="h", open_after=2, clear_after=2)
        for _ in range(2):
            m.raise_signal("shed-storm", host="h0")
            m.advance_round()
        assert len(m.open_incidents()) == 1
        for _ in range(6):  # flap: fire every other round, below open_after
            m.raise_signal("shed-storm", host="h0")
            m.advance_round()
            m.advance_round()
        assert len(m.incidents()) == 1, "flapping minted a second incident"
        assert len(m.open_incidents()) == 1
        m.advance_round()
        m.advance_round()
        assert not m.open_incidents(), "true quiet must still resolve"

    def test_ack_is_open_only_and_resolve_is_terminal(self):
        m = IncidentMonitor(host="h", clear_after=1)
        m.raise_signal("divergence", host="p")
        inc = m.advance_round()[0]
        inc.ack(m.rounds)
        assert inc.status == "ack"
        m.advance_round()
        assert inc.status == "resolved"
        inc.ack(m.rounds)
        assert inc.status == "resolved", "ack must not reopen resolved"

    def test_unknown_kind_rejected(self):
        m = IncidentMonitor()
        with pytest.raises(ValueError):
            m.raise_signal("made-up-kind")


# ---------------------------------------------------------------------------
# causal correlation + root-cause ordering
# ---------------------------------------------------------------------------


class TestCorrelation:
    def test_shared_host_window_collapses_to_one_incident(self):
        m = IncidentMonitor(host="h", clear_after=8, correlation_window=4)
        m.raise_signal("shed-storm", host="h0", doc="d1", value=5)
        m.advance_round()
        m.raise_signal("slo-burn", host="h0", value=9)
        m.advance_round()
        assert len(m.incidents()) == 1, "same-host signals must correlate"
        inc = m.incidents()[0]
        # largest delta wins the root-cause slot regardless of taxonomy
        assert inc.kind == "slo-burn"
        kinds = [c.kind for c in inc.candidates()]
        assert kinds == ["slo-burn", "shed-storm"]

    def test_tie_breaks_to_earliest_taxonomy_entry(self):
        m = IncidentMonitor(host="h", clear_after=8)
        m.raise_signal("slo-burn", host="h0", value=5)
        m.raise_signal("shed-storm", host="h0", value=5)
        m.advance_round()
        inc = m.incidents()[0]
        # equal magnitudes: the earlier TAXONOMY entry is the root cause
        assert TAXONOMY.index("shed-storm") < TAXONOMY.index("slo-burn")
        assert inc.kind == "shed-storm"

    def test_outside_window_opens_a_fresh_incident(self):
        m = IncidentMonitor(host="h", clear_after=1, correlation_window=2)
        m.raise_signal("shed-storm", host="h0")
        m.advance_round()
        for _ in range(4):  # resolve + age past the window
            m.advance_round()
        m.raise_signal("slo-burn", host="h0")
        m.advance_round()
        assert len(m.incidents()) == 2

    def test_disjoint_hosts_do_not_correlate(self):
        m = IncidentMonitor(host="h", clear_after=8)
        m.raise_signal("shed-storm", host="h0")
        m.advance_round()
        m.raise_signal("slo-burn", host="h1")
        m.advance_round()
        assert len(m.incidents()) == 2

    def test_shared_trace_correlates_across_hosts(self):
        m = IncidentMonitor(host="h", clear_after=8)
        m.raise_signal("shed-storm", host="h0", trace="t1")
        m.advance_round()
        m.raise_signal("slo-burn", host="h1", trace="t1")
        m.advance_round()
        assert len(m.incidents()) == 1
        assert m.incidents()[0].hosts == ["h0", "h1"]


# ---------------------------------------------------------------------------
# determinism: two monitors, one truth
# ---------------------------------------------------------------------------


def _feed(m: IncidentMonitor, quiet: int = 3) -> None:
    m.observe_leases({"leases": {"h1": {"verdict": "dead", "missed": 3}}})
    m.observe_serve({"host": "h0", "recent_sheds": 7, "overloaded": True})
    m.advance_round()
    m.observe_latency({"slo": {"burn_rate": 2.5, "breaches": 4}})
    m.advance_round()
    m.observe_sentinel({"total": 9})
    m.observe_supervisor({"rollbacks": 2, "quarantined": {"3": {}}})
    m.advance_round()
    for _ in range(quiet):
        m.advance_round()


class TestDeterminism:
    def test_two_monitors_byte_identical(self):
        a, b = IncidentMonitor(host="h"), IncidentMonitor(host="h")
        _feed(a)
        _feed(b)
        assert a.incidents_json() == b.incidents_json()
        assert a.digest() == b.digest()
        assert a.wire_summary() == b.wire_summary()

    def test_ack_is_local_and_digest_normalizes_it(self):
        a, b = IncidentMonitor(host="h"), IncidentMonitor(host="h")
        _feed(a, quiet=0)
        _feed(b, quiet=0)
        open_a = a.open_incidents()
        assert open_a, "the feed must leave something open to ack"
        open_a[0].ack(a.rounds)
        assert a.digest() == b.digest(), "an operator ack must not fork views"

    def test_wire_summary_roundtrip_and_peer_agreement(self):
        # the SAME host label: observation-derived digests only agree when
        # the monitors were fed identical signals (host rides the signals)
        a, b = IncidentMonitor(host="h"), IncidentMonitor(host="h")
        _feed(a)
        _feed(b)
        parsed = b.parse_wire_summary(a.wire_summary())
        assert parsed["open"] == len(a.open_incidents())
        assert parsed["digest"] == a.digest() & 0xFFFFFFFF
        b.observe_peer_summary("a", a.wire_summary())
        snap = b.snapshot()
        assert snap["peers"]["a"]["agree"] is True

    def test_summary_rides_the_frontier_nul_sentinel(self):
        from peritext_tpu.parallel.multihost import (
            _frontier_meta, _parse_frontier,
        )

        m = IncidentMonitor(host="h")
        _feed(m)
        meta = _frontier_meta(None, None, incidents=m.wire_summary())
        body = json.dumps({"actor": 3, **meta}).encode("utf-8")
        clock, parsed = _parse_frontier(body)
        assert clock == {"actor": 3}, "sentinels must never pollute the clock"
        assert parsed["incidents"] == m.wire_summary()


# ---------------------------------------------------------------------------
# feeds
# ---------------------------------------------------------------------------


class TestFeeds:
    def test_fleet_feed_resolves_post_heal_not_post_reset(self):
        m = IncidentMonitor(host="h", clear_after=2)
        dead = {
            "leases": {"leases": {"h1": {"verdict": "dead", "missed": 2}}},
            "serving": {"d0": "h1"},
            "failed_docs": [],
        }
        m.observe_fleet(dead)
        m.advance_round()
        assert [i.kind for i in m.open_incidents()] == ["host-death"]
        healed = {  # lease still latched dead, docs re-homed by failover
            "leases": {"leases": {"h1": {"verdict": "dead", "missed": 2}}},
            "serving": {"d0": "h2"},
            "failed_docs": [],
        }
        for _ in range(3):
            m.observe_fleet(healed)
            m.advance_round()
        assert not m.open_incidents(), "failover completing IS the heal"

    def test_fleet_feed_migration_failure(self):
        m = IncidentMonitor(host="h", clear_after=1)
        m.observe_fleet({"leases": {"leases": {}}, "serving": {},
                         "failed_docs": [], "migration_rollbacks": 2})
        m.advance_round()
        assert m.incident_kinds() == ["migration-failure"]

    def test_sentinel_feed_needs_a_storm_not_a_compile(self):
        m = IncidentMonitor(host="h", compile_storm_threshold=3)
        m.observe_sentinel({"total": 2})
        assert m.advance_round() == []
        m.observe_sentinel({"total": 7})  # +5 in one observation window
        assert [i.kind for i in m.advance_round()] == ["recompile-storm"]

    def test_convergence_feed_is_delta_triggered(self):
        m = IncidentMonitor(host="h", clear_after=1)
        snap = {"divergence_incidents": 1, "divergent_peers": ["p1"]}
        m.observe_convergence(snap)
        m.advance_round()
        assert m.incident_kinds() == ["divergence"]
        for _ in range(2):  # the latched flag must not re-raise
            m.observe_convergence(snap)
            m.advance_round()
        assert not m.open_incidents()

    def test_perf_feed_magnitude_is_worst_regression(self):
        m = IncidentMonitor(host="h")
        m.observe_perf({"regressed": True, "rows": [
            {"name": "a", "status": "regressed", "delta_pct": -4.0},
            {"name": "b", "status": "regressed", "delta_pct": 11.5},
            {"name": "c", "status": "ok", "delta_pct": 0.1},
        ]})
        inc = m.advance_round()[0]
        assert inc.kind == "perf-regression"
        assert inc.candidates()[0].value == 11.5

    def test_arming_compiles_nothing(self):
        from peritext_tpu.obs.sentinel import RecompileSentinel

        with RecompileSentinel() as sentinel:
            before = sentinel.total
            m = IncidentMonitor(host="h")
            _feed(m)
            m.snapshot()
            m.incidents_json()
            assert sentinel.total == before, (
                "arming/feeding the incident plane dispatched XLA compiles"
            )


# ---------------------------------------------------------------------------
# surfaces: /incidents.json, gauges, health_snapshot, build info
# ---------------------------------------------------------------------------


class TestSurfaces:
    def test_incidents_json_golden_shape(self):
        m = IncidentMonitor(host="h")
        _feed(m)
        snap = m.snapshot()
        for key in ("host", "rounds", "open", "acked", "resolved", "total",
                    "by_kind", "digest", "open_after", "clear_after",
                    "correlation_window", "peers", "incidents"):
            assert key in snap, f"/incidents.json lost its {key!r} key"
        assert set(snap["by_kind"]) == set(TAXONOMY)
        inc = snap["incidents"][0]
        for key in ("id", "kind", "status", "hosts", "docs", "opened_round",
                    "resolved_round", "signals", "candidates"):
            assert key in inc
        json.dumps(snap)  # the body must be JSON-serializable as-is

    def test_prometheus_incident_gauges(self):
        m = IncidentMonitor(host="h")
        _feed(m)
        text = prometheus_text(incidents=m)
        assert "peritext_incident_open " in text
        assert "peritext_incident_resolved " in text
        assert "peritext_incident_total " in text
        assert "peritext_incident_digest " in text
        # the by-kind family covers the FULL taxonomy (zeros included) so
        # alert rules never reference a gauge that vanishes when quiet
        for kind in TAXONOMY:
            assert f'peritext_incident_open_by_kind{{kind="{kind}"}}' in text
        for line in text.splitlines():
            assert line.startswith("#") or len(line.split()) == 2

    def test_build_info_gauge_in_every_exposition(self):
        text = prometheus_text()
        assert "peritext_build_info{" in text
        info = build_info()
        for key in ("sha", "wire_caps", "jax", "device"):
            assert key in info

    def test_health_snapshot_carries_incidents(self):
        m = IncidentMonitor(host="h")
        _feed(m)
        snap = health_snapshot(incidents=m)
        assert snap["incidents"]["total"] == m.snapshot()["total"]

    def test_metrics_server_incidents_route(self):
        m = IncidentMonitor(host="h")
        _feed(m)
        server = MetricsServer(incidents=m)
        host, port = server.start()
        try:
            url = f"http://{host}:{port}/incidents.json"
            with urllib.request.urlopen(url) as resp:
                body = json.loads(resp.read())
            assert body["host"] == "h" and body["total"] >= 1
            with urllib.request.urlopen(
                f"http://{host}:{port}/metrics"
            ) as resp:
                text = resp.read().decode()
            assert "peritext_incident_open " in text
            assert "peritext_build_info{" in text
        finally:
            server.stop()


# ---------------------------------------------------------------------------
# the merged black-box timeline
# ---------------------------------------------------------------------------


class TestMergeFlightDumps:
    def _dump(self, tmp_path, host, records, reason="boom"):
        rec = FlightRecorder(dump_dir=tmp_path, host=host,
                             min_dump_interval=0.0)
        for kind, fields in records:
            rec.record(kind, **fields)
        return rec.dump(reason=reason)

    def test_host_attribution_and_trace_grouping(self, tmp_path):
        self._dump(tmp_path, "hostA",
                   [("span", {"name": "commit", "trace_id": "t9"})])
        self._dump(tmp_path, "hostB",
                   [("fault", {"reason": "rollback", "trace_id": "t9"})])
        merged = merge_flight_dumps(tmp_path.glob("flight-*.jsonl"))
        assert merged["hosts"] == ["hostA", "hostB"]
        assert merged["records"] == 2
        hosts_in_trace = {r["host"] for r in merged["traces"]["t9"]}
        assert hosts_in_trace == {"hostA", "hostB"}

    def test_overlapping_dumps_deduplicate_by_seq(self, tmp_path):
        rec = FlightRecorder(dump_dir=tmp_path, host="hostA",
                             min_dump_interval=0.0)
        rec.record("span", name="a")
        rec.dump(reason="first")
        rec.record("span", name="b")
        rec.dump(reason="second")  # carries the whole ring again
        merged = merge_flight_dumps(tmp_path.glob("flight-*.jsonl"))
        assert merged["records"] == 2, "ring overlap must dedup, not double"

    def test_legacy_hostless_filenames_still_merge(self, tmp_path):
        path = tmp_path / "flight-123-000001-crash.jsonl"
        path.write_text(
            json.dumps({"kind": "dump", "reason": "crash", "records": 1})
            + "\n" + json.dumps({"seq": 1, "ts": 1.0, "kind": "fault"})
            + "\n"
        )
        merged = merge_flight_dumps([path])
        assert merged["hosts"] == ["?"]
        assert merged["records"] == 1

    def test_unreadable_lines_counted_not_fatal(self, tmp_path):
        path = tmp_path / "flight-hostA-1-000001-x.jsonl"
        path.write_text("not json\n" + json.dumps(
            {"seq": 1, "ts": 1.0, "kind": "span"}) + "\n")
        merged = merge_flight_dumps([path])
        assert merged["skipped"] == 1 and merged["records"] == 1

    def test_incident_open_triggers_dump(self, tmp_path):
        rec = FlightRecorder(dump_dir=tmp_path, host="h0",
                             min_dump_interval=0.0)
        m = IncidentMonitor(host="h0", recorder=rec)
        m.raise_signal("shed-storm", host="h0", value=2)
        m.advance_round()
        dumps = list(tmp_path.glob("flight-h0-*-incident-shed-storm.jsonl"))
        assert dumps, "an incident open must dump the black box"
        m.raise_signal("shed-storm", host="h0", value=2)
        m.advance_round()
        assert len(list(tmp_path.glob("flight-*.jsonl"))) == len(dumps), (
            "re-fires of an open incident must not dump again"
        )


# ---------------------------------------------------------------------------
# the CLI: incidents / status / flight exit contracts
# ---------------------------------------------------------------------------


class TestCli:
    def _snap_file(self, tmp_path, name="incidents.json", feed=True):
        m = IncidentMonitor(host="h")
        if feed:
            _feed(m)
        else:
            m.advance_round()
        path = tmp_path / name
        path.write_text(json.dumps(m.snapshot()))
        return path, m

    def test_incidents_exit_codes(self, tmp_path, capsys):
        path, m = self._snap_file(tmp_path)
        expect = 1 if m.open_incidents() else 0
        assert obs_main(["incidents", str(path)]) == expect
        out = capsys.readouterr().out
        assert "monitor(s)" in out
        clean, _ = self._snap_file(tmp_path, "clean.json", feed=False)
        assert obs_main(["incidents", str(clean)]) == 0
        assert obs_main(["incidents", str(tmp_path / "missing.json")]) == 2
        bad = tmp_path / "bad.json"
        bad.write_text("{}")
        assert obs_main(["incidents", str(bad)]) == 2

    def test_incidents_reads_health_bodies(self, tmp_path):
        m = IncidentMonitor(host="h")
        _feed(m)
        path = tmp_path / "health.json"
        path.write_text(json.dumps(health_snapshot(incidents=m)))
        expect = 1 if m.open_incidents() else 0
        assert obs_main(["incidents", str(path)]) == expect

    def test_status_composite_over_snapshot_dir(self, tmp_path, capsys):
        m = IncidentMonitor(host="h")
        _feed(m)
        (tmp_path / "incidents.json").write_text(json.dumps(m.snapshot()))
        (tmp_path / "serve.json").write_text(json.dumps({
            "sessions": 1, "overloaded": False, "recent_sheds": 0,
            "queue": {"depth": 0, "max_depth": 8, "backpressure": False,
                      "verdicts": {"shed": 0}},
        }))
        code = obs_main(["status", str(tmp_path)])
        out = capsys.readouterr().out
        assert code == (1 if m.open_incidents() else 0)
        assert "serve" in out and "incidents" in out
        empty = tmp_path / "nothing"
        assert obs_main(["status", str(empty)]) == 2

    def test_status_against_live_metrics_server(self, tmp_path, capsys):
        m = IncidentMonitor(host="h")
        m.advance_round()  # clean monitor -> clean plane
        server = MetricsServer(incidents=m)
        host, port = server.start()
        try:
            code = obs_main(["status", f"http://{host}:{port}"])
        finally:
            server.stop()
        out = capsys.readouterr().out
        assert code == 0, out
        assert "incidents" in out and "health" in out

    def test_flight_merged_timeline(self, tmp_path, capsys):
        rec = FlightRecorder(dump_dir=tmp_path, host="hostA",
                             min_dump_interval=0.0)
        rec.record("span", name="commit", trace_id="t1")
        rec.dump(reason="probe")
        assert obs_main(["flight", str(tmp_path)]) == 0
        out = capsys.readouterr().out
        assert "hostA" in out and "commit" in out
        assert obs_main(["flight", str(tmp_path / "nope")]) == 2
        empty = tmp_path / "empty"
        empty.mkdir()
        assert obs_main(["flight", str(empty)]) == 2


# ---------------------------------------------------------------------------
# incident primitives
# ---------------------------------------------------------------------------


class TestIncidentPrimitives:
    def test_candidate_ordering_rest_sorted_by_magnitude(self):
        inc = Incident("INC-0001", 1)
        inc.attach("slo-burn", "h0", None, None, 3.0, {}, 1)
        inc.attach("shed-storm", "h0", None, None, 9.0, {}, 1)
        inc.attach("recompile-storm", "h1", None, None, 5.0, {}, 2)
        kinds = [c.kind for c in inc.candidates()]
        assert kinds[0] == "shed-storm"  # largest delta
        assert kinds[1:] == ["recompile-storm", "slo-burn"]

    def test_to_json_is_stable(self):
        inc = Incident("INC-0001", 1)
        inc.attach("divergence", "p", "d", "t", 2.0, {"x": 1}, 1)
        assert json.loads(json.dumps(inc.to_json())) == inc.to_json()
