"""Multi-host replication transport: TCP anti-entropy with codec frames.

"Multi-node without a cluster" in the reference's style (SURVEY §4): N
logical hosts are N ReplicaServers on localhost, each with its own
ChangeStore, exchanging real bytes over real sockets.
"""

import threading

import pytest

from peritext_tpu.api.batch import _oracle_doc
from peritext_tpu.parallel import ChangeStore, ReplicaServer, merge_changes, sync_with
from peritext_tpu.testing.fuzz import generate_workload


def _store_from(workload, actors):
    """Split one fuzz workload's logs across hosts: each host starts with
    only the changes its actors authored."""
    store = ChangeStore()
    for actor in actors:
        for change in workload.get(actor, []):
            store.append(change)
    return store


def _workload_of(store):
    return {actor: list(store.log(actor)) for actor in store.actors()}


@pytest.fixture()
def workload():
    return generate_workload(seed=21, num_docs=1, ops_per_doc=120)[0]


def test_two_hosts_converge(workload):
    a = _store_from(workload, ["doc1", "doc2"])
    b = _store_from(workload, ["doc3"])
    server = ReplicaServer(a)
    host, port = server.start()
    try:
        pulled, pushed = sync_with(b, host, port)
        assert pulled > 0 and pushed > 0
    finally:
        server.stop()
    assert a.clock() == b.clock()
    # both sides converge to the same document as a single-process replay
    expected = _oracle_doc(workload).get_text_with_formatting(["text"])
    assert _oracle_doc(_workload_of(a)).get_text_with_formatting(["text"]) == expected
    assert _oracle_doc(_workload_of(b)).get_text_with_formatting(["text"]) == expected


def test_sync_is_idempotent(workload):
    a = _store_from(workload, ["doc1"])
    b = _store_from(workload, ["doc2", "doc3"])
    server = ReplicaServer(a)
    host, port = server.start()
    try:
        sync_with(b, host, port)
        pulled, pushed = sync_with(b, host, port)  # second round: nothing new
        assert (pulled, pushed) == (0, 0)
    finally:
        server.stop()


def test_three_hosts_pairwise_gossip(workload):
    stores = [
        _store_from(workload, ["doc1"]),
        _store_from(workload, ["doc2"]),
        _store_from(workload, ["doc3"]),
    ]
    servers = [ReplicaServer(s) for s in stores]
    addrs = [s.start() for s in servers]
    try:
        # gossip ring: 0<->1, 1<->2, 0<->1 closes the gap
        sync_with(stores[0], *addrs[1])
        sync_with(stores[1], *addrs[2])
        sync_with(stores[0], *addrs[1])
    finally:
        for s in servers:
            s.stop()
    clocks = [s.clock() for s in stores]
    assert clocks[0] == clocks[1] == clocks[2]


def test_on_changes_hook_receives_fresh_changes(workload):
    a = _store_from(workload, ["doc1", "doc2", "doc3"])
    b = ChangeStore()
    received = []
    server = ReplicaServer(a)
    host, port = server.start()
    try:
        sync_with(b, host, port, on_changes=received.extend)
    finally:
        server.stop()
    assert sorted((c.actor, c.seq) for c in received) == sorted(
        (c.actor, c.seq) for log in workload.values() for c in log
    )


def test_concurrent_syncs_against_one_server(workload):
    """Many clients pulling from one server concurrently: the server lock
    keeps its store consistent and every client converges."""
    full = _store_from(workload, ["doc1", "doc2", "doc3"])
    server = ReplicaServer(full)
    host, port = server.start()
    clients = [ChangeStore() for _ in range(8)]
    errors = []

    def pull(store):
        try:
            sync_with(store, host, port)
        except Exception as exc:  # surface into the main thread
            errors.append(exc)

    threads = [threading.Thread(target=pull, args=(c,)) for c in clients]
    try:
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
    finally:
        server.stop()
    assert not errors
    assert all(c.clock() == full.clock() for c in clients)


def test_merge_changes_skips_duplicates_and_restores_order(workload):
    changes = [c for log in workload.values() for c in log]
    store = ChangeStore()
    # deliver in reverse order with duplicates: per-actor seq sort restores it
    fresh = merge_changes(store, list(reversed(changes)) + changes[:3])
    assert len(fresh) == len(changes)
    assert store.clock() == {a: len(l) for a, l in workload.items() if l}


def test_server_survives_garbage_peer(workload):
    import socket as socketlib

    a = _store_from(workload, ["doc1"])
    server = ReplicaServer(a)
    host, port = server.start()
    try:
        with socketlib.create_connection((host, port), timeout=5) as sock:
            sock.sendall(b"\x00\x00\x00\x05Xjunk")  # unknown message type
        # server should still answer a well-formed sync afterwards
        b = ChangeStore()
        sync_with(b, host, port)
        assert b.clock() == a.clock()
    finally:
        server.stop()


def test_on_frame_hook_feeds_device_session(workload):
    """The raw-frame hook: wire bytes flow into a StreamingMerge without
    object conversion on the device path."""
    from peritext_tpu.api.batch import _oracle_doc
    from peritext_tpu.parallel.streaming import StreamingMerge

    a = _store_from(workload, ["doc1", "doc2", "doc3"])
    b = ChangeStore()
    dev = StreamingMerge(
        num_docs=1, actors=("doc1", "doc2", "doc3"), slot_capacity=512,
        mark_capacity=128, round_insert_capacity=128,
        round_delete_capacity=64, round_mark_capacity=64,
    )

    def on_frame(frame):
        dev.ingest_frame(0, frame)
        dev.drain()

    server = ReplicaServer(a)
    host, port = server.start()
    try:
        sync_with(b, host, port, on_frame=on_frame)
    finally:
        server.stop()
    assert dev.docs[0].frame_mode and not dev.docs[0].fallback
    assert dev.read(0) == _oracle_doc(workload).get_text_with_formatting(["text"])


def test_large_backlog_syncs_chunked_via_multi_frame_message(monkeypatch):
    """A many-actor backlog whose dep charge would approach the decode
    ceiling ships as MSG_CHANGES_MULTI (multiple concatenated frames), each
    chunk an independently valid frame — never one giant frame the peer's
    own decoder must reject (review r4).  Small backlogs keep the
    wire-identical single MSG_CHANGES."""
    from peritext_tpu.core.opids import ROOT
    from peritext_tpu.core.types import Change, Operation
    from peritext_tpu.parallel import codec

    monkeypatch.setattr(codec, "_ENCODE_CHUNK_CHARGE", 500)
    actors = [f"peer-{i:03d}" for i in range(60)]
    clock = {a: 1 for a in actors}
    a_store = ChangeStore()
    for k in range(1, 301):
        clock = dict(clock)
        clock[f"peer-{k % 60:03d}"] = k  # drifting clock: no DEPS_SAME runs
        deps = dict(clock)
        deps["writer"] = k - 1
        a_store.append(Change(
            actor="writer", seq=k, deps=deps, start_op=k,
            ops=[Operation(action="set", obj=ROOT, opid=(k, "writer"),
                           key="m", value=k)],
        ))
    b_store = ChangeStore()
    frames = []
    server = ReplicaServer(a_store)
    host, port = server.start()
    try:
        pulled, _ = sync_with(b_store, host, port, on_frame=frames.append)
    finally:
        server.stop()
    assert pulled == 300
    assert b_store.clock() == a_store.clock()
    assert b_store.log("writer")[-1].deps == a_store.log("writer")[-1].deps
    assert len(frames) > 1  # chunked delivery, fanned out per frame
    for f in frames:
        codec.decode_frame(f)
