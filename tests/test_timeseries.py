"""History-plane tests (PR 20): retention tiers (a one-frame spike
survives every downsampling level), byte-identical JSONL segment replay,
the rolling-median + MAD anomaly detector and its incident-taxonomy
mapping (the IncidentMonitor's ninth signal source), the query helpers
behind ``/timeseries.json`` and ``obs history``, the closed planner loop
(fused occupancy rows -> ``propose(history=...)``), and the off-by-default
arming contract (zero new XLA compiles, bounded sampling overhead)."""

import json
import time
from pathlib import Path

import pytest

from peritext_tpu.obs import (
    GLOBAL_HISTORY,
    IncidentMonitor,
    RecompileSentinel,
    TAXONOMY,
    TimeSeriesPlane,
    anomaly_kind,
    health_snapshot,
    prometheus_text,
    replay_segments,
)
from peritext_tpu.obs.timeseries import (
    ANOMALY_KIND_PREFIXES,
    chronological_frames,
    flatten_gauges,
    key_summary,
    mad_z,
    occupancy_distribution,
    query_snapshot,
    series_points,
    series_rate,
    snapshot_keys,
)
from peritext_tpu.plan import history_values, propose

#: the committed plan-smoke devprof capture the planner tests read
SNAPSHOT = Path(__file__).resolve().parents[1] / "perf" / "plan_devprof.json"

#: bimodal occupancy: mostly-sparse windows with a dense burst — p90
#: lands on the dense mode (0.9) while the devprof point estimate on the
#: committed snapshot is ~0.07, so the width-shrink gate flips
BIMODAL = [0.05] * 12 + [0.9] * 4


def _plane(**kw):
    kw.setdefault("sample_every", 1)
    kw.setdefault("min_frames", 4)
    return TimeSeriesPlane(**kw).enable()


def _feed_flat(plane, n, value=0.0, key="shed"):
    for _ in range(n):
        plane.sample(serve={key: value})


# ---------------------------------------------------------------------------
# retention: the tier cascade and the spike-survival envelope
# ---------------------------------------------------------------------------


class TestRetention:
    def test_spike_survives_every_tier(self):
        """The retention headline: one spiked frame, then enough flat
        frames to merge it down into the DEEPEST tier — the min/max
        envelope must still carry the spike even though every
        intermediate tier downsampled it away."""
        plane = _plane(tier_capacity=4, merge_factor=4, tiers=3,
                       anomaly_window=4)
        plane.sample(serve={"shed": 100.0})  # the one-frame spike
        _feed_flat(plane, 80)  # tier 0 (cap 4) overflows through tier 1
        snap = plane.snapshot()
        frames = chronological_frames(snap)
        # the spike frame merged all the way down: the OLDEST retained
        # frame is a deep-tier merge whose envelope still holds 100
        assert frames[0]["frames"] > 1, "spike frame never downsampled"
        assert frames[0]["gauges"]["serve.shed"]["max"] == 100.0
        # and the plane-wide summary sees it through the envelopes
        assert key_summary(snap, "serve.shed")["max"] == 100.0
        # while last-value percentiles reflect the flat steady state
        assert key_summary(snap, "serve.shed")["p50"] == 0.0

    def test_tier_cascade_is_bounded(self):
        plane = _plane(tier_capacity=4, merge_factor=2, tiers=3)
        for i in range(500):
            plane.sample(serve={"shed": float(i)})
        snap = plane.snapshot()
        assert snap["frames_sampled"] == 500
        # every tier within capacity (+merge slack on interior tiers)
        for count in snap["tier_frames"]:
            assert count <= plane.tier_capacity + plane.merge_factor
        assert snap["frames_retained"] == sum(snap["tier_frames"])
        # the last tier dropped oldest frames: history is bounded
        assert snap["frames_retained"] < 500
        oldest = chronological_frames(snap)[0]
        assert oldest["round"] > 1

    def test_segment_replay_reconstructs_ring_byte_identically(self, tmp_path):
        """The persistence pin: JSONL segments re-fed through retention
        rebuild the EXACT ring (frames_json() equality), across a
        segment rotation."""
        plane = _plane(tier_capacity=8, merge_factor=2, tiers=3,
                       segment_frames=16, dir=tmp_path)
        for i in range(50):
            plane.sample(serve={"shed": float(i % 7)},
                         fleet={"hosts": 3.0, "dead": float(i == 31)})
        assert plane.segments() > 1, "rotation never exercised"
        replayed = replay_segments(tmp_path, tier_capacity=8,
                                   merge_factor=2, tiers=3)
        assert replayed.frames_json() == plane.frames_json()
        assert replayed.rounds == plane.rounds

    def test_disarmed_plane_costs_and_records_nothing(self):
        plane = TimeSeriesPlane()
        assert not plane.enabled
        assert plane.advance_round(serve={"x": 1}) is None
        plane.record_occupancy(0, 0.5)
        assert plane.frames_sampled == 0
        assert plane.occupancy_rows() == []
        # arming is enable(): the round counter kept counting throughout
        assert plane.rounds == 1

    def test_sample_every_decimates_advance_round(self):
        plane = TimeSeriesPlane(sample_every=4).enable()
        for _ in range(16):
            plane.advance_round(serve={"x": 1.0})
        assert plane.rounds == 16
        assert plane.frames_sampled == 4  # rounds 1, 5, 9, 13

    def test_constructor_validation(self):
        with pytest.raises(ValueError):
            TimeSeriesPlane(sample_every=0)
        with pytest.raises(ValueError):
            TimeSeriesPlane(merge_factor=1)
        with pytest.raises(ValueError):
            TimeSeriesPlane(tier_capacity=2, merge_factor=4)


# ---------------------------------------------------------------------------
# flattening
# ---------------------------------------------------------------------------


class TestFlatten:
    def test_flatten_rules(self):
        gauges = flatten_gauges("serve", {
            "depth": 3,
            "overloaded": True,
            "ratio": 0.5,
            "label": "ignored",
            "items": [1, 2],
            "bad": float("nan"),
            "nested": {"b": 2, "a": 1},
        })
        assert gauges == {
            "serve.depth": 3.0,
            "serve.overloaded": 1.0,
            "serve.ratio": 0.5,
            "serve.nested.a": 1.0,
            "serve.nested.b": 2.0,
        }

    def test_live_plane_source_uses_snapshot(self):
        class _Plane:
            def snapshot(self):
                return {"x": 2}

        assert flatten_gauges("p", _Plane()) == {"p.x": 2.0}
        with pytest.raises(TypeError):
            flatten_gauges("p", object())


# ---------------------------------------------------------------------------
# the anomaly detector
# ---------------------------------------------------------------------------


class TestAnomalies:
    def test_flat_baseline_spike_fires(self):
        plane = _plane(threshold=6.0)
        _feed_flat(plane, plane.min_frames + 2)
        assert plane.active_anomalies() == []
        plane.sample(serve={"shed": 50.0})
        active = plane.active_anomalies()
        assert [a["key"] for a in active] == ["serve.shed"]
        a = active[0]
        assert a["kind"] == "shed-storm"
        assert a["value"] == 50.0 and a["median"] == 0.0
        assert a["z"] > plane.threshold
        assert plane.anomaly_first_round("serve.shed") == a["round"]
        # recovery: the next flat frame scores against a window that
        # still holds the spike, but the VALUE is back at the median
        plane.sample(serve={"shed": 0.0})
        assert plane.active_anomalies() == []
        assert plane.anomalies_total == 1

    def test_linear_drift_stays_quiet(self):
        """A steadily-ramping counter has a healthy MAD — the robust z
        never crosses the threshold, so growth is not an anomaly."""
        plane = _plane()
        for i in range(40):
            plane.sample(serve={"admitted": float(i * 3)})
        assert plane.active_anomalies() == []
        assert plane.anomalies_total == 0

    def test_zero_mad_floor_tolerates_float_jitter(self):
        """The floor is RELATIVE: epsilon wobble around a large flat
        value stays quiet while a genuine step change fires."""
        plane = _plane()
        for _ in range(plane.min_frames + 2):
            plane.sample(latency={"p99": 100.0})
        plane.sample(latency={"p99": 100.0 + 1e-9})
        assert plane.active_anomalies() == []
        plane.sample(latency={"p99": 200.0})
        assert [a["kind"] for a in plane.active_anomalies()] == ["slo-burn"]

    def test_mad_z_is_pure_and_capped(self):
        flat = [0.0] * 8
        assert mad_z(0.0, flat) == 0.0
        assert mad_z(1e30, flat) == pytest.approx(1e9)  # Z_CAP
        assert mad_z(5.0, [1.0, 2.0, 3.0, 4.0, 5.0]) < 6.0

    def test_short_history_never_scores(self):
        plane = _plane(min_frames=8)
        for i in range(6):
            plane.sample(serve={"shed": 0.0 if i < 5 else 9999.0})
        assert plane.active_anomalies() == []

    def test_anomaly_kind_covers_the_existing_taxonomy_only(self):
        assert anomaly_kind("serve.queue.depth") == "shed-storm"
        assert anomaly_kind("fleet.verdicts.shed") == "host-death"
        assert anomaly_kind("convergence.lag") == "divergence"
        assert anomaly_kind("jit.compiles_total") == "recompile-storm"
        assert anomaly_kind("recompiles.site") == "recompile-storm"
        assert anomaly_kind("latency.slo.burn") == "slo-burn"
        assert anomaly_kind("session.quarantined") == "quarantine-storm"
        assert anomaly_kind("plan.savings") == "perf-regression"
        assert anomaly_kind("whatever.else") == "perf-regression"
        # every mapped kind is an EXISTING taxonomy member — anomalies
        # are root-cause candidates, never a new incident latch
        kinds = {kind for _, kind in ANOMALY_KIND_PREFIXES}
        kinds.add("perf-regression")
        assert kinds <= set(TAXONOMY)

    def test_incident_monitor_ninth_feed(self):
        """observe_timeseries raises signals on EXISTING kinds: a serve
        anomaly opens a shed-storm incident carrying the anomaly key."""
        plane = _plane()
        _feed_flat(plane, plane.min_frames + 2)
        plane.sample(serve={"shed": 50.0})
        imon = IncidentMonitor(host="front", open_after=2)
        for _ in range(2):
            imon.observe_timeseries(plane)
            imon.advance_round()
        assert imon.incident_kinds() == ["shed-storm"]
        inc = imon.open_incidents()[0]
        cause = inc.candidates()[0]
        assert cause.kind == "shed-storm"
        assert cause.detail.get("anomaly") is True
        assert cause.detail.get("anomaly_key") == "serve.shed"

    def test_ninth_feed_unknown_kind_folds_to_perf_regression(self):
        imon = IncidentMonitor(host="front", open_after=1)
        imon.observe_timeseries({
            "host": "front",
            "anomaly": {"active": [
                {"key": "mystery.gauge", "kind": "not-a-kind",
                 "round": 3, "z": 9.0},
            ]},
        })
        imon.advance_round()
        assert imon.incident_kinds() == ["perf-regression"]


# ---------------------------------------------------------------------------
# the query API (shared by /timeseries.json and obs history)
# ---------------------------------------------------------------------------


class TestQueries:
    def _snap(self, n=10):
        plane = _plane()
        for i in range(n):
            plane.sample(serve={"admitted": float(i * 2)},
                         fleet={"hosts": 3.0})
        return plane, plane.snapshot()

    def test_series_points_and_rate(self):
        plane, snap = self._snap()
        points = series_points(snap, "serve.admitted")
        assert len(points) == 10
        assert points[0] == [1, 0.0] and points[-1] == [10, 18.0]
        assert plane.series("serve.admitted", window=3) == points[-3:]
        rates = series_rate(points)
        assert all(r == 2.0 for _, r in rates)
        assert plane.rate("serve.admitted")[-1] == [10, 2.0]

    def test_key_summary_percentiles(self):
        _, snap = self._snap()
        s = key_summary(snap, "serve.admitted")
        assert s["points"] == 10
        assert s["min"] == 0.0 and s["max"] == 18.0
        assert s["p50"] == 8.0 and s["p99"] == 18.0
        assert s["first"] == 0.0 and s["last"] == 18.0
        assert s["delta"] == 18.0
        assert key_summary(snap, "no.such.key") == {"key": "no.such.key",
                                                    "points": 0}

    def test_query_snapshot_param_shapes(self):
        _, snap = self._snap()
        body = query_snapshot(snap, {"key": "serve.admitted", "rate": "1",
                                     "window": "4"})
        assert len(body["points"]) == 4
        assert body["summary"]["points"] == 4
        assert len(body["rate"]) == 3
        windowed = query_snapshot(snap, {"window": "3"})
        assert len(windowed["frames"]) == 3
        assert "fleet.hosts" in windowed["keys"]
        assert query_snapshot(snap, {}) is snap

    def test_snapshot_keys_union(self):
        plane = _plane()
        plane.sample(serve={"a": 1})
        plane.sample(fleet={"b": 2})
        assert snapshot_keys(plane.snapshot()) == ["fleet.b", "serve.a"]


# ---------------------------------------------------------------------------
# the closed planner loop
# ---------------------------------------------------------------------------


class TestPlannerLoop:
    def test_fused_group_records_occupancy_rows(self):
        """FusedMuxGroup.pump feeds the plane one occupancy row per lane
        per committed window when (and only when) the plane is armed."""
        from peritext_tpu.parallel.codec import encode_frame
        from peritext_tpu.plan import TenantSpec
        from peritext_tpu.serve import FusedMuxGroup, default_lane_factory
        from peritext_tpu.testing.fuzz import generate_workload

        specs = [TenantSpec(tenant="tA", docs=1),
                 TenantSpec(tenant="tB", docs=1)]
        group = FusedMuxGroup(
            specs,
            default_lane_factory(
                ("doc1", "doc2", "doc3"),
                slot_capacity=128, mark_capacity=64, tomb_capacity=96,
                round_insert_capacity=32, round_delete_capacity=16,
                round_mark_capacity=16,
            ),
            host="test",
        )
        plane = _plane()
        group.history = plane
        sids = {}
        for spec in specs:
            sid, verdict = group.open_session(spec.tenant, "client")
            assert verdict.admitted
            sids[spec.tenant] = sid
        workloads = generate_workload(seed=5, num_docs=2, ops_per_doc=12)
        frames = {}
        for spec, w in zip(specs, workloads):
            changes = sorted((ch for log in w.values() for ch in log),
                             key=lambda c: (c.actor, c.seq))
            frames[spec.tenant] = [encode_frame(changes[:6]),
                                   encode_frame(changes[6:])]
        # window 1: both tenants (full); window 2: one tenant (sparse)
        for name in ("tA", "tB"):
            assert group.submit(name, sids[name], frames[name][0]).admitted
        group.flush()
        assert group.submit("tA", sids["tA"], frames["tA"][1]).admitted
        group.flush()
        rows = plane.occupancy_rows()
        assert rows, "armed plane recorded no occupancy rows"
        for row in rows:
            assert set(row) == {"row", "lane", "occupancy", "docs"}
            assert 0.0 <= row["occupancy"] <= 1.0
        # the sparse second window recorded sub-full occupancy
        assert min(r["occupancy"] for r in rows) < 1.0
        dist = plane.snapshot()["occupancy"]["distribution"]
        assert dist["count"] == len(rows)

    def test_propose_history_weighted_differs_and_is_deterministic(self):
        """The acceptance pin: on the committed snapshot, the bimodal
        occupancy history flips the width-shrink gate (p90 utilization
        0.9 vs the ~0.07 point estimate), so the proposal DIFFERS from
        the snapshot-only one; same history -> byte-identical proposal."""
        snap = json.loads(SNAPSHOT.read_text())
        base = propose(snap)
        weighted = propose(snap, history=BIMODAL)
        again = propose(snap, history=list(BIMODAL))
        assert json.dumps(weighted.to_json(), sort_keys=True) == (
            json.dumps(again.to_json(), sort_keys=True))
        assert weighted.to_json() != base.to_json()
        # the point-estimate plan shrinks widths; the history-weighted
        # plan sees p90 occupancy 0.9 and keeps them
        assert weighted.insert_width > base.insert_width
        hist = weighted.modeled["history"]
        assert hist["rows"] == len(BIMODAL)
        assert hist["occupancy"]["p90"] == 0.9
        assert hist["occupancy"]["sparse_frac"] == 0.75
        assert hist["dispatch_weight_factor"] == 1.75
        assert hist["weighted_terms"] == ["dispatch_cost", "utilization"]
        assert weighted.modeled["utilization"] == 0.9
        # the no-history path is untouched: no phantom history block
        assert "history" not in base.modeled

    def test_history_values_normalizes_every_shape(self):
        plane = _plane()
        plane.record_occupancy(0, 0.25)
        plane.record_occupancy(1, 0.75)
        assert history_values(None) == []
        assert history_values(plane) == [0.25, 0.75]
        assert history_values(plane.snapshot()) == [0.25, 0.75]
        assert history_values([{"occupancy": 0.5}, 0.9]) == [0.5, 0.9]

    def test_occupancy_distribution_shape(self):
        assert occupancy_distribution([]) == {"count": 0}
        dist = occupancy_distribution(BIMODAL)
        assert dist["count"] == 16 and dist["p90"] == 0.9
        assert dist["sparse_frac"] == 0.75

    def test_occupancy_ring_is_bounded(self):
        plane = _plane(occupancy_cap=8)
        for i in range(20):
            plane.record_occupancy(0, i / 20.0)
        rows = plane.occupancy_rows()
        assert len(rows) == 8
        assert plane.snapshot()["occupancy"]["total"] == 20
        assert rows[0]["row"] == 13  # oldest rows aged out


# ---------------------------------------------------------------------------
# arming: zero compiles, bounded overhead, off-by-default global
# ---------------------------------------------------------------------------


class TestArming:
    def test_global_plane_is_off_by_default(self):
        assert not GLOBAL_HISTORY.enabled

    def test_arming_compiles_nothing_within_overhead_budget(self):
        """ISSUE acceptance: enabling the plane mid-serve triggers ZERO
        new XLA compiles, and the caller-measured sampling overhead
        stays within the pinned budget."""
        from peritext_tpu.parallel.codec import encode_frame
        from peritext_tpu.parallel.streaming import StreamingMerge
        from peritext_tpu.serve import SessionMux
        from peritext_tpu.testing.fuzz import generate_workload

        def make_mux():
            return SessionMux(
                StreamingMerge(
                    num_docs=1, actors=("doc1", "doc2", "doc3"),
                    slot_capacity=128, mark_capacity=64, tomb_capacity=96,
                    round_insert_capacity=32, round_delete_capacity=16,
                    round_mark_capacity=16, static_rounds=True,
                ),
                host="armed",
            )

        def drive(mux, plane=None):
            sid, verdict = mux.open_session("client")
            assert verdict.admitted
            if plane is not None:
                mux.history_plane = plane  # arming: attribute swap, no jit
            for frame in frames:
                assert mux.submit(sid, frame).admitted
                mux.flush()

        w = generate_workload(seed=9, num_docs=1, ops_per_doc=24)[0]
        changes = sorted((ch for log in w.values() for ch in log),
                         key=lambda c: (c.actor, c.seq))
        frames = [encode_frame(changes[i::6]) for i in range(6)]
        drive(make_mux())  # cold run: every shape variant compiles here
        plane = _plane()
        with RecompileSentinel() as sentinel:
            sentinel.mark()
            t0 = time.perf_counter()
            drive(make_mux(), plane=plane)
            plane.note_overhead(time.perf_counter() - t0)
            sentinel.assert_steady_state(
                "armed history sampling over steady-state serve rounds")
        assert plane.frames_sampled >= 1
        snap = plane.snapshot()
        assert "serve.queue.depth" in snap["keys"]
        # the budget is generous (it covers the serve rounds themselves)
        # — the pin is that overhead is FED IN and bounded, not measured
        # by the merge-scope plane
        assert 0.0 < snap["overhead_seconds"] < 30.0


# ---------------------------------------------------------------------------
# surfaces: health composition, prometheus gauges, the HTTP route
# ---------------------------------------------------------------------------


class TestSurfaces:
    def _active_plane(self):
        plane = _plane()
        _feed_flat(plane, plane.min_frames + 2)
        plane.sample(serve={"shed": 50.0})
        plane.record_occupancy(0, 0.5, docs=2)
        return plane

    def test_health_snapshot_composes_history(self):
        plane = self._active_plane()
        snap = health_snapshot(history=plane)
        assert snap["history"]["rounds"] == plane.rounds
        assert snap["history"]["anomaly"]["active"]
        json.dumps(snap, default=str)

    def test_prometheus_history_gauges(self):
        plane = self._active_plane()
        text = prometheus_text(history=plane)
        for needle in (
            "peritext_history_enabled 1",
            "peritext_history_rounds ",
            "peritext_history_frames_sampled ",
            "peritext_history_frames_retained ",
            'peritext_history_tier_frames{tier="0"} ',
            "peritext_history_segments ",
            "peritext_history_anomalies_active 1",
            "peritext_history_anomalies_total 1",
            'peritext_history_anomaly_by_key{key="serve.shed"} 1',
            "peritext_history_occupancy_rows 1",
            "peritext_history_sample_overhead_seconds ",
        ):
            assert needle in text, needle

    def test_timeseries_route_and_query_params(self):
        import urllib.request

        from peritext_tpu.obs import MetricsServer

        plane = self._active_plane()
        server = MetricsServer(history=plane)
        host, port = server.start()
        base = f"http://{host}:{port}"
        try:
            body = json.loads(urllib.request.urlopen(
                f"{base}/timeseries.json", timeout=5).read())
            assert body["rounds"] == plane.rounds
            assert body["anomaly"]["active"]
            keyed = json.loads(urllib.request.urlopen(
                f"{base}/timeseries.json?key=serve.shed&rate=1&window=4",
                timeout=5).read())
            assert keyed["key"] == "serve.shed"
            assert len(keyed["points"]) == 4
            assert keyed["summary"]["max"] == 50.0
            assert keyed["rate"], "rate=1 produced no derivative"
        finally:
            server.stop()


# ---------------------------------------------------------------------------
# the CLI: obs history / obs top / obs plan --history
# ---------------------------------------------------------------------------


class TestCli:
    def _write_snapshot(self, tmp_path, plane):
        path = tmp_path / "timeseries.json"
        path.write_text(json.dumps(plane.snapshot(), default=str))
        return path

    def test_history_exit_codes(self, tmp_path, capsys):
        from peritext_tpu.obs.__main__ import main as obs_main

        quiet = _plane()
        _feed_flat(quiet, quiet.min_frames + 2, value=3.0)
        self._write_snapshot(tmp_path, quiet)
        assert obs_main(["history", str(tmp_path)]) == 0
        out = capsys.readouterr().out
        assert "serve.shed" in out
        # an active anomaly is exit 1 (the drift-check contract)
        spiked = _plane()
        _feed_flat(spiked, spiked.min_frames + 2)
        spiked.sample(serve={"shed": 50.0})
        hot = tmp_path / "hot"
        hot.mkdir()
        (hot / "timeseries.json").write_text(
            json.dumps(spiked.snapshot(), default=str))
        assert obs_main(["history", str(hot)]) == 1
        err = capsys.readouterr().err
        assert "anomaly: serve.shed [shed-storm]" in err
        # unreadable source / unknown key are exit 2
        assert obs_main(["history", str(tmp_path / "missing")]) == 2
        assert obs_main(["history", str(tmp_path), "--key", "no.such"]) == 2

    def test_history_key_view_with_rate(self, tmp_path, capsys):
        from peritext_tpu.obs.__main__ import main as obs_main

        plane = _plane()
        for i in range(8):
            plane.sample(serve={"admitted": float(i * 2)})
        self._write_snapshot(tmp_path, plane)
        assert obs_main(["history", str(tmp_path), "--key",
                         "serve.admitted", "--rate", "--json"]) == 0
        body = json.loads(capsys.readouterr().out)
        assert len(body["points"]) == 8
        assert body["rate"][-1][1] == 2.0
        assert body["summary"]["delta"] == 14.0

    def test_top_dashboard_over_live_server(self, capsys):
        from peritext_tpu.obs import MetricsServer
        from peritext_tpu.obs.__main__ import main as obs_main

        plane = _plane()
        for i in range(6):
            plane.sample(serve={"admitted": float(i * 5)})
        server = MetricsServer(history=plane)
        host, port = server.start()
        try:
            assert obs_main(["top", f"http://{host}:{port}", "--json"]) == 0
            body = json.loads(capsys.readouterr().out)
        finally:
            server.stop()
        planes = {row["plane"] for row in body["planes"]}
        assert {"health", "timeseries"} <= planes
        assert body["movers"][0]["key"] == "serve.admitted"
        assert body["movers"][0]["delta"] == 25.0

    def test_plan_surfaces_history_weighted_terms(self, tmp_path, capsys):
        from peritext_tpu.obs.__main__ import main as obs_main

        plane = _plane()
        for occ in BIMODAL:
            plane.record_occupancy(0, occ)
        hist_path = tmp_path / "history.json"
        hist_path.write_text(json.dumps(plane.snapshot(), default=str))
        assert obs_main(["plan", str(SNAPSHOT),
                         "--history", str(hist_path)]) in (0, 1)
        out = capsys.readouterr().out
        assert "history-weighted terms: dispatch_cost, utilization" in out
        assert "16 occupancy row(s)" in out
        assert obs_main(["plan", str(SNAPSHOT), "--history",
                         str(tmp_path / "nope.json")]) == 2
