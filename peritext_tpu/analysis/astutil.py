"""Shared AST helpers for graftlint rules (pure stdlib — never imports the
scanned code, never imports jax)."""

from __future__ import annotations

import ast
from typing import Dict, Iterator, NamedTuple, Optional, Set, Tuple

#: names that produce a jit-compiled callable
JIT_NAMES = {"jax.jit", "jit", "pjit", "jax.pjit"}
PARTIAL_NAMES = {"partial", "functools.partial"}

#: attribute reads on a traced array that are static at trace time
STATIC_TRACER_ATTRS = {"shape", "ndim", "dtype", "size", "weak_type"}


def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for a Name/Attribute chain, else None."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = dotted_name(node.value)
        return f"{base}.{node.attr}" if base else None
    return None


def call_name(node: ast.Call) -> Optional[str]:
    return dotted_name(node.func)


class JitSpec(NamedTuple):
    """Static-argument declaration of one jit wrapping."""

    static_argnums: frozenset
    static_argnames: frozenset


def _const_ints(node: ast.AST) -> Set[int]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return {node.value}
    if isinstance(node, (ast.Tuple, ast.List)):
        out: Set[int] = set()
        for elt in node.elts:
            out |= _const_ints(elt)
        return out
    return set()


def _const_strs(node: ast.AST) -> Set[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return {node.value}
    if isinstance(node, (ast.Tuple, ast.List)):
        out: Set[str] = set()
        for elt in node.elts:
            out |= _const_strs(elt)
        return out
    return set()


def jit_call_spec(call: ast.Call) -> Optional[JitSpec]:
    """JitSpec if ``call`` is ``jax.jit(...)`` or ``partial(jax.jit, ...)``."""
    name = dotted_name(call.func)
    if name in JIT_NAMES:
        pass
    elif (
        name in PARTIAL_NAMES
        and call.args
        and dotted_name(call.args[0]) in JIT_NAMES
    ):
        pass
    else:
        return None
    nums: Set[int] = set()
    names: Set[str] = set()
    for kw in call.keywords:
        if kw.arg == "static_argnums":
            nums |= _const_ints(kw.value)
        elif kw.arg == "static_argnames":
            names |= _const_strs(kw.value)
    return JitSpec(frozenset(nums), frozenset(names))


def jit_decoration(fn: ast.AST) -> Optional[JitSpec]:
    """JitSpec if the function def carries a jit decorator."""
    for dec in getattr(fn, "decorator_list", []):
        if dotted_name(dec) in JIT_NAMES:
            return JitSpec(frozenset(), frozenset())
        if isinstance(dec, ast.Call):
            spec = jit_call_spec(dec)
            if spec is not None:
                return spec
    return None


def module_defs(tree: ast.Module) -> Dict[str, ast.AST]:
    """Every function def in the file by bare name (methods included; last
    definition of a name wins — good enough for file-local reachability)."""
    defs: Dict[str, ast.AST] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs[node.name] = node
    return defs


def jit_roots(tree: ast.Module) -> Tuple[Dict[str, JitSpec], Dict[int, JitSpec]]:
    """``(callables, root_defs)``:

    * ``callables`` — names that, when *called*, dispatch a jitted program
      (decorated defs plus ``g = jax.jit(f, ...)`` module assignments);
    * ``root_defs`` — ``id(def-node) -> JitSpec`` for every function body
      that executes under trace (decorated, or wrapped by an assignment).
    """
    defs = module_defs(tree)
    callables: Dict[str, JitSpec] = {}
    root_defs: Dict[int, JitSpec] = {}
    for name, node in defs.items():
        spec = jit_decoration(node)
        if spec is not None:
            callables[name] = spec
            root_defs[id(node)] = spec
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Assign) and isinstance(node.value, ast.Call)):
            continue
        if dotted_name(node.value.func) not in JIT_NAMES:
            continue
        spec = jit_call_spec(node.value)
        if spec is None:
            continue
        for target in node.targets:
            if isinstance(target, ast.Name):
                callables[target.id] = spec
        if node.value.args:
            wrapped = dotted_name(node.value.args[0])
            if wrapped is None:
                wrapped = _shard_map_body(node.value.args[0])
            if wrapped in defs:
                root_defs[id(defs[wrapped])] = spec
    return callables, root_defs


def _shard_map_body(node: ast.AST) -> Optional[str]:
    """The mapped body's name if ``node`` is a ``shard_map(body, ...)``
    call — the body executes under the enclosing trace, so
    ``jit(shard_map(body, ...))`` roots ``body`` exactly like
    ``jit(body)`` would."""
    if not (isinstance(node, ast.Call) and node.args):
        return None
    name = dotted_name(node.func)
    if name is None or not name.endswith("shard_map"):
        return None
    return dotted_name(node.args[0])


def traced_params(fn: ast.AST, spec: JitSpec) -> Set[str]:
    """Parameter names that arrive as tracers (static args excluded)."""
    args = fn.args
    ordered = [a.arg for a in args.posonlyargs + args.args]
    traced: Set[str] = set()
    for i, name in enumerate(ordered):
        if i in spec.static_argnums or name in spec.static_argnames:
            continue
        if name in ("self", "cls"):
            continue
        traced.add(name)
    traced |= {
        a.arg for a in args.kwonlyargs if a.arg not in spec.static_argnames
    }
    return traced


def called_local_names(fn: ast.AST) -> Set[str]:
    """Bare and ``self.x(...)`` callee names inside a function body — the
    edges of the file-local call graph."""
    out: Set[str] = set()
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        if isinstance(func, ast.Name):
            out.add(func.id)
        elif (
            isinstance(func, ast.Attribute)
            and isinstance(func.value, ast.Name)
            and func.value.id in ("self", "cls")
        ):
            out.add(func.attr)
        # shard_map(body, ...) runs body under the caller's trace: an edge
        # to body, not just to shard_map itself
        body = _shard_map_body(node)
        if body is not None:
            out.add(body)
    return out


def import_maps(tree: ast.Module) -> Tuple[Dict[str, str], Dict[str, str]]:
    """``(module_aliases, from_imports)``: ``np -> numpy`` and
    ``perf_counter -> time.perf_counter`` style maps for name resolution."""
    aliases: Dict[str, str] = {}
    from_imports: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                aliases[alias.asname or alias.name.split(".")[0]] = (
                    alias.name if alias.asname else alias.name.split(".")[0]
                )
        elif isinstance(node, ast.ImportFrom) and node.module and not node.level:
            for alias in node.names:
                from_imports[alias.asname or alias.name] = (
                    f"{node.module}.{alias.name}"
                )
    return aliases, from_imports


def resolve_name(name: str, aliases: Dict[str, str], from_imports: Dict[str, str]) -> str:
    """Expand the leading segment of a dotted name through the file's
    imports: ``np.asarray -> numpy.asarray``, ``Random -> random.Random``."""
    head, _, rest = name.partition(".")
    if head in from_imports:
        full = from_imports[head]
        return f"{full}.{rest}" if rest else full
    if head in aliases:
        return f"{aliases[head]}.{rest}" if rest else aliases[head]
    return name


def iteration_sites(tree: ast.Module) -> Iterator[Tuple[ast.AST, ast.AST]]:
    """Yield ``(iter_expr, anchor_node)`` for every for-loop and
    comprehension generator in the file."""
    for node in ast.walk(tree):
        if isinstance(node, (ast.For, ast.AsyncFor)):
            yield node.iter, node
        elif isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp, ast.GeneratorExp)):
            for gen in node.generators:
                yield gen.iter, node
