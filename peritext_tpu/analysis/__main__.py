"""graftlint CLI.

Usage::

    python -m peritext_tpu.analysis [paths...]           # lint (default: peritext_tpu)
    python -m peritext_tpu.analysis --list-rules
    python -m peritext_tpu.analysis --update-baseline    # re-attribute the ledger

Exit codes: 0 clean (modulo baseline), 1 unbaselined findings, 2 usage error.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path
from typing import List, Optional

from .baseline import (
    BASELINE_NAME,
    apply_baseline,
    find_default_baseline,
    load_baseline,
    save_baseline,
    update_baseline,
)
from .engine import all_rule_ids, rule_table, scan_paths


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m peritext_tpu.analysis",
        description="graftlint: determinism & tracer-safety static analysis",
    )
    parser.add_argument("paths", nargs="*", default=["peritext_tpu"],
                        help="files/directories to scan (default: peritext_tpu)")
    parser.add_argument("--baseline", metavar="FILE",
                        help=f"baseline file (default: nearest {BASELINE_NAME} "
                             "above the first scanned path)")
    parser.add_argument("--no-baseline", action="store_true",
                        help="report every finding, ignoring any baseline")
    parser.add_argument("--update-baseline", action="store_true",
                        help="rewrite the baseline from this scan, preserving "
                             "existing justifications")
    parser.add_argument("--rules", metavar="IDS",
                        help="comma-separated rule subset (e.g. PTL001,PTL005)")
    parser.add_argument("--format", choices=("text", "json"), default="text")
    parser.add_argument("--list-rules", action="store_true")
    args = parser.parse_args(argv)

    if args.list_rules:
        for row in rule_table():
            print(f"{row['id']} [{row['scope']}] {row['summary']}")
            print(f"    {row['rationale']}")
        return 0

    rules = None
    if args.rules:
        rules = [r.strip() for r in args.rules.split(",") if r.strip()]
        unknown = set(rules) - set(all_rule_ids())
        if unknown:
            print(f"unknown rule(s): {', '.join(sorted(unknown))}", file=sys.stderr)
            return 2

    baseline_path: Optional[Path] = None
    if args.baseline:
        baseline_path = Path(args.baseline)
        if not baseline_path.is_file() and not args.update_baseline:
            print(f"baseline not found: {baseline_path}", file=sys.stderr)
            return 2
    elif not args.no_baseline:
        baseline_path = find_default_baseline(args.paths)

    root = baseline_path.parent if baseline_path else Path.cwd()
    try:
        findings = scan_paths(args.paths, root=root, rules=rules)
    except (FileNotFoundError, ValueError) as exc:
        print(str(exc), file=sys.stderr)
        return 2

    if args.update_baseline:
        # no pre-existing/explicit baseline: anchor the new ledger at cwd
        # (the scan root), NEVER inside the scanned tree — entries must be
        # rooted where the default discovery walk will later find them
        target = baseline_path or Path.cwd() / BASELINE_NAME
        old = load_baseline(target) if target.is_file() else {}
        entries = update_baseline(findings, old)
        if rules is not None:
            # a --rules-scoped update must not delete other rules' entries
            # (and their hand-written justifications) from the ledger
            selected = set(rules)
            entries.extend(
                e for e in old.values() if e.rule not in selected
            )
        save_baseline(target, entries)
        todo = sum(1 for e in entries if e.justification.startswith("TODO"))
        print(f"{target}: {len(entries)} entries ({todo} needing justification)")
        return 0

    entries = (
        load_baseline(baseline_path)
        if baseline_path and not args.no_baseline
        else {}
    )
    new, stale = apply_baseline(findings, entries)

    if args.format == "json":
        print(json.dumps(
            {
                "findings": [f.to_json() for f in new],
                "baselined": len(findings) - len(new),
                "stale_baseline_entries": [
                    {"rule": e.rule, "path": e.path, "context": e.context}
                    for e in stale
                ],
            },
            indent=2,
        ))
    else:
        for finding in new:
            print(finding.render())
        for entry in stale:
            print(
                f"warning: stale baseline entry {entry.rule} {entry.path} "
                f"({entry.context!r}) — prune with --update-baseline",
                file=sys.stderr,
            )
        summary = (
            f"graftlint: {len(new)} finding(s), "
            f"{len(findings) - len(new)} baselined, {len(stale)} stale"
        )
        print(summary, file=sys.stderr if new else sys.stdout)
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
