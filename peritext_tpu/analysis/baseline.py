"""Attributed baseline: the ledger of known, *justified* findings.

The repo self-scan must be clean — but some violations are intentional
(observability timing in a hot loop, transport jitter that is nondeterministic
by design).  Those live in ``graftlint_baseline.json`` at the repo root, one
entry per finding, each carrying a human justification.  Fingerprints are
``(rule, path, stripped source line, occurrence count)`` — line-number drift
never invalidates an entry; editing or removing the offending line does.

* a scan finding with no baseline budget left → **new** (fails the lint);
* a baseline entry whose finding no longer occurs → **stale** (warned, so
  the ledger gets pruned, but lint stays green — deleting dead suppressions
  must never block a fix).
"""

from __future__ import annotations

import json
from collections import Counter
from dataclasses import dataclass
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from .engine import Finding

BASELINE_NAME = "graftlint_baseline.json"

#: fingerprint key
Key = Tuple[str, str, str]  # (rule, path, context)


@dataclass
class BaselineEntry:
    rule: str
    path: str
    context: str
    count: int
    justification: str


def load_baseline(path: Path) -> Dict[Key, BaselineEntry]:
    data = json.loads(path.read_text(encoding="utf-8"))
    entries: Dict[Key, BaselineEntry] = {}
    for raw in data.get("findings", []):
        entry = BaselineEntry(
            rule=raw["rule"],
            path=raw["path"],
            context=raw["context"],
            count=int(raw.get("count", 1)),
            justification=raw.get("justification", ""),
        )
        key = (entry.rule, entry.path, entry.context)
        if key in entries:  # merge duplicates defensively
            entries[key].count += entry.count
        else:
            entries[key] = entry
    return entries


def save_baseline(path: Path, entries: Iterable[BaselineEntry]) -> None:
    payload = {
        "//": "graftlint attributed baseline — every entry is a known, "
              "justified violation; regenerate with --update-baseline",
        "version": 1,
        "findings": [
            {
                "rule": e.rule,
                "path": e.path,
                "context": e.context,
                "count": e.count,
                "justification": e.justification,
            }
            for e in sorted(entries, key=lambda e: (e.path, e.rule, e.context))
        ],
    }
    path.write_text(json.dumps(payload, indent=2) + "\n", encoding="utf-8")


def apply_baseline(
    findings: Sequence[Finding], entries: Dict[Key, BaselineEntry]
) -> Tuple[List[Finding], List[BaselineEntry]]:
    """``(new, stale)``: findings not covered by the baseline, and baseline
    entries no longer matched by any finding (candidates for pruning)."""
    budget = Counter({key: e.count for key, e in entries.items()})
    new: List[Finding] = []
    for finding in findings:  # findings arrive line-sorted: earlier wins budget
        key = (finding.rule, finding.path, finding.context)
        if budget.get(key, 0) > 0:
            budget[key] -= 1
        else:
            new.append(finding)
    stale = [
        entries[key]
        for key, remaining in sorted(budget.items())
        if remaining > 0
    ]
    return new, stale


def update_baseline(
    findings: Sequence[Finding], old: Dict[Key, BaselineEntry]
) -> List[BaselineEntry]:
    """Rebuild entries from a scan, preserving existing justifications."""
    counts: Counter = Counter(
        (f.rule, f.path, f.context) for f in findings
    )
    out: List[BaselineEntry] = []
    for (rule, path, context), count in sorted(counts.items()):
        prior = old.get((rule, path, context))
        out.append(
            BaselineEntry(
                rule=rule,
                path=path,
                context=context,
                count=count,
                justification=prior.justification if prior else "TODO: justify or fix",
            )
        )
    return out


def find_default_baseline(paths: Sequence[str | Path]) -> Optional[Path]:
    """Walk up from the first scanned path looking for the checked-in
    baseline (the repo root); None if absent."""
    if not paths:
        return None
    start = Path(paths[0]).resolve()
    if start.is_file():
        start = start.parent
    for candidate in [start, *start.parents]:
        hit = candidate / BASELINE_NAME
        if hit.is_file():
            return hit
    return None
