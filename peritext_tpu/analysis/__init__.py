"""graftlint — determinism & tracer-safety static analysis for peritext-tpu.

The north-star contract (byte-equality convergence at TPU speed) rests on
invariants that unit tests only probe after the fact:

* merge/convergence code must never let *iteration order of unordered
  containers* leak into digests or delivery order (PTL001);
* jit-traced code must never branch Python-side on a tracer (PTL002), sync
  to the host mid-program (PTL003), or mint per-doc shapes that recompile
  the session program (PTL004);
* fault handling must use the typed errors from ``core/errors.py`` unless a
  boundary is explicitly declared (PTL005);
* deterministic merge regions must not read wall clocks or unseeded RNGs
  (PTL006).

This package machine-checks those invariants over the AST — no imports of
the scanned code, no jax dependency — and pairs them with a runtime
recompile sentinel (:class:`peritext_tpu.observability.RecompileSentinel`)
that counts per-jit-site XLA compilations so steady-state streaming rounds
can assert **zero** recompiles.

Run it::

    python -m peritext_tpu.analysis peritext_tpu/

Pre-existing, intentional violations are attributed (with a justification
each) in ``graftlint_baseline.json`` at the repo root; anything new fails
``make lint`` and CI.  Inline escapes: ``# graftlint: disable=PTL00X`` on
the offending line, or ``# graftlint: boundary(reason)`` to declare a fault
boundary (satisfies PTL005).
"""

from .engine import (  # noqa: F401
    Finding,
    LintConfig,
    all_rule_ids,
    rule_table,
    scan_paths,
)
from .baseline import (  # noqa: F401
    BASELINE_NAME,
    apply_baseline,
    find_default_baseline,
    load_baseline,
    update_baseline,
)
