"""graftlint engine: file collection, per-file AST context, rule dispatch,
inline suppressions.

The engine is deliberately import-free with respect to the scanned code: it
parses source text with :mod:`ast` only, so it runs anywhere (CI lint jobs,
pre-commit) without jax or device initialization.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass
from pathlib import Path
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Set

from . import astutil

#: ``# graftlint: disable=PTL001,PTL006`` — suppress those rules on this line
_SUPPRESS_RE = re.compile(r"#\s*graftlint:\s*disable=([A-Z0-9_,\s]+)")
#: ``# graftlint: boundary(reason)`` — declares a fault boundary (PTL005)
_BOUNDARY_RE = re.compile(r"#\s*graftlint:\s*boundary\(([^)]*)\)")
#: ruff/flake8 blind-except suppression doubles as a boundary declaration
_NOQA_BLE_RE = re.compile(r"#\s*noqa\b[^#]*\bBLE001\b")


@dataclass(frozen=True)
class LintConfig:
    """Project knobs shared by every rule."""

    #: directory names whose files are "merge/convergence scope" (PTL001,
    #: PTL004's shape checks, PTL006)
    merge_scope_dirs: frozenset = frozenset({"core", "ops", "parallel", "store"})
    #: '/'-joined path suffixes of INDIVIDUAL merge-scope files living in
    #: otherwise out-of-scope directories.  plan/ is the canonical split:
    #: the cost model (plan/model.py, plan/tuner.py) is observability —
    #: wall-clock reads are legal — but plan/fusion.py assembles the
    #: cross-tenant fusion groups that decide device dispatch order, so it
    #: must stay deterministic like the merge kernels it feeds.  obs/ has
    #: the same split: every other obs module reads clocks freely (that's
    #: the design rule — clock reads live THERE), but obs/timeseries.py is
    #: the round-counted history plane whose retention/anomaly scoring
    #: must replay byte-identically, so it joins the merge scope and its
    #: sampling overhead is fed in as data via note_overhead()
    merge_scope_files: frozenset = frozenset(
        {"plan/fusion.py", "obs/timeseries.py"}
    )
    #: functions that route a raw length into the padded-shape tables;
    #: shapes wrapped in one of these never recompile (streaming.py's
    #: ``_width_bucket`` is the canonical instance)
    bucket_fns: frozenset = frozenset({"_width_bucket", "width_bucket", "next_pow2"})


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str  # repo-root-relative (baseline-stable), '/'-separated
    line: int
    col: int
    message: str
    #: stripped source line — the line-number-independent fingerprint basis
    context: str

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}"

    def to_json(self) -> Dict[str, object]:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
            "context": self.context,
        }


class FileContext:
    """Everything a rule needs about one parsed file."""

    def __init__(self, display_path: str, source: str, tree: ast.Module, config: LintConfig):
        self.display_path = display_path
        self.tree = tree
        self.config = config
        self.lines = source.splitlines()
        self._parents: Dict[int, ast.AST] = {}
        for node in ast.walk(tree):
            for child in ast.iter_child_nodes(node):
                self._parents[id(child)] = node
        self.suppressed: Dict[int, Set[str]] = {}
        self.boundaries: Dict[int, str] = {}
        for lineno, text in enumerate(self.lines, 1):
            m = _SUPPRESS_RE.search(text)
            if m:
                rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
                self.suppressed.setdefault(lineno, set()).update(rules)
            m = _BOUNDARY_RE.search(text)
            if m:
                self.boundaries[lineno] = m.group(1).strip()
                self.suppressed.setdefault(lineno, set()).add("PTL005")
            elif _NOQA_BLE_RE.search(text):
                self.suppressed.setdefault(lineno, set()).add("PTL005")
        parts = Path(display_path).parts[:-1]
        posix = Path(display_path).as_posix()
        self.in_merge_scope = (
            any(p in config.merge_scope_dirs for p in parts)
            or any(posix.endswith(f) for f in config.merge_scope_files)
        )
        self.module_aliases, self.from_imports = astutil.import_maps(tree)

    # -- helpers used by rules ------------------------------------------------

    def parent(self, node: ast.AST) -> Optional[ast.AST]:
        return self._parents.get(id(node))

    def ancestors(self, node: ast.AST) -> Iterator[ast.AST]:
        cur = self.parent(node)
        while cur is not None:
            yield cur
            cur = self.parent(cur)

    def resolve(self, name: str) -> str:
        return astutil.resolve_name(name, self.module_aliases, self.from_imports)

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    def finding(self, rule: str, node: ast.AST, message: str) -> Finding:
        lineno = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0)
        return Finding(rule, self.display_path, lineno, col, message, self.line_text(lineno))


class Rule:
    """Base class: subclasses set ``rule_id``/``summary``/``rationale`` and
    implement :meth:`check`."""

    rule_id: str = "PTL000"
    #: "merge" rules only run on files under a merge-scope directory
    scope: str = "all"
    summary: str = ""
    rationale: str = ""

    def check(self, ctx: FileContext) -> Iterator[Finding]:  # pragma: no cover
        raise NotImplementedError


def _registry() -> Dict[str, Rule]:
    from .rules import ALL_RULES

    return ALL_RULES


def all_rule_ids() -> List[str]:
    """Every registered rule id — derived from the registry, so a new rule
    module can never be silently excluded from the default scan."""
    return sorted(_registry())


def rule_table() -> List[Dict[str, str]]:
    """(id, scope, summary, rationale) for docs and ``--list-rules``."""
    return [
        {
            "id": rule.rule_id,
            "scope": rule.scope,
            "summary": rule.summary,
            "rationale": rule.rationale,
        }
        for rule in sorted(_registry().values(), key=lambda r: r.rule_id)
    ]


def collect_files(paths: Sequence[str | Path]) -> List[Path]:
    """Every ``.py`` file under ``paths``.  A nonexistent or non-Python
    path is an error, never an empty result — a typo'd scan target must
    not make lint a silent no-op."""
    files: List[Path] = []
    for p in paths:
        path = Path(p)
        if path.is_dir():
            files.extend(
                f for f in sorted(path.rglob("*.py"))
                if "__pycache__" not in f.parts
            )
        elif path.is_file():
            if path.suffix != ".py":
                raise ValueError(f"not a Python file: {path}")
            files.append(path)
        else:
            raise FileNotFoundError(f"no such file or directory: {path}")
    return files


def scan_file(
    path: Path,
    *,
    root: Optional[Path] = None,
    config: Optional[LintConfig] = None,
    rules: Optional[Iterable[str]] = None,
) -> List[Finding]:
    config = config or LintConfig()
    root = root or Path.cwd()
    try:
        display = path.resolve().relative_to(root.resolve()).as_posix()
    except ValueError:
        display = path.as_posix()
    try:
        source = path.read_text(encoding="utf-8")
        tree = ast.parse(source, filename=str(path))
    except (OSError, SyntaxError, ValueError) as exc:
        return [Finding("PTL000", display, getattr(exc, "lineno", 1) or 1, 0,
                        f"unparseable file: {exc}", "")]
    ctx = FileContext(display, source, tree, config)
    wanted = set(rules) if rules is not None else None
    findings: List[Finding] = []
    for rule in _registry().values():
        if wanted is not None and rule.rule_id not in wanted:
            continue
        if rule.scope == "merge" and not ctx.in_merge_scope:
            continue
        for finding in rule.check(ctx):
            if finding.rule in ctx.suppressed.get(finding.line, ()):
                continue
            findings.append(finding)
    findings.sort(key=lambda f: (f.line, f.col, f.rule))
    return findings


def scan_paths(
    paths: Sequence[str | Path],
    *,
    root: Optional[Path] = None,
    config: Optional[LintConfig] = None,
    rules: Optional[Iterable[str]] = None,
) -> List[Finding]:
    """Lint every ``.py`` file under ``paths``; findings carry paths relative
    to ``root`` (the baseline anchor) and are sorted for stable output."""
    findings: List[Finding] = []
    for path in collect_files(paths):
        findings.extend(scan_file(path, root=root, config=config, rules=rules))
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings
