"""PTL005 — broad ``except`` outside a declared fault boundary.

The fault-domain architecture (DESIGN.md degradation ladder) works because
failures carry *types*: ``DecodeError`` quarantines a doc,
``TransportError`` marks a peer behind, ``DeviceRoundError`` rolls back a
round.  A broad ``except Exception`` erases that information — unless the
site *is* one of the few declared boundaries where "any failure degrades
identically" is the contract.  Boundaries must say so on the line:
``# graftlint: boundary(reason)`` (``# noqa: BLE001`` is honored too);
everything else catches typed errors from ``core/errors.py``.
"""

from __future__ import annotations

import ast
from typing import Iterator

from .. import astutil
from ..engine import FileContext, Finding, Rule

_BROAD = {"Exception", "BaseException"}


def _broad_name(type_node: ast.AST) -> str | None:
    if type_node is None:
        return "bare except"
    name = astutil.dotted_name(type_node)
    if name in _BROAD or (name and name.split(".")[-1] in _BROAD):
        return f"except {name}"
    if isinstance(type_node, ast.Tuple):
        for elt in type_node.elts:
            hit = _broad_name(elt)
            if hit:
                return hit
    return None


class BroadExceptRule(Rule):
    rule_id = "PTL005"
    scope = "all"
    summary = "broad except outside a declared fault boundary"
    rationale = (
        "typed errors drive the degradation ladder (quarantine / behind / "
        "rollback); broad catches erase the fault type and mask real bugs"
    )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            hit = _broad_name(node.type)
            if hit is None:
                continue
            # boundary/noqa annotations are applied by the engine's
            # suppression pass; reaching here means the line is bare
            yield ctx.finding(
                self.rule_id,
                node,
                f"{hit} is not a declared fault boundary — catch typed "
                "errors from core/errors.py or annotate the line with "
                "'# graftlint: boundary(reason)'",
            )
