"""PTL004 — recompile hazards at jit callsites and shape construction.

XLA compiles one executable per (static args, input shapes) signature.  Two
patterns silently turn "compile once, dispatch forever" into
"compile-per-doc" (the hazard `parallel/streaming.py` guards with width
buckets — Ragged Paged Attention makes the same move kernel-side):

* a *static* jit argument fed a per-call shape-derived scalar
  (``len(...)``, ``x.shape[i]``) — every distinct value mints a fresh
  executable;
* a device-array constructor whose shape embeds a raw ``len(...)`` /
  ``.shape`` read instead of routing through the padded-shape tables
  (``_width_bucket``) — every new doc population mints a fresh input shape;
* a variable-length list built inline at a jit callsite — every length is a
  new pytree structure, i.e. a new signature.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional

from .. import astutil
from ..engine import FileContext, Finding, Rule

#: device-array constructors only — host-side np buffers get their shapes
#: managed at the jit boundary (padding/bucketing) and are not themselves
#: compile inputs
_CONSTRUCTORS = {
    "jax.numpy.zeros", "jax.numpy.ones", "jax.numpy.empty", "jax.numpy.full",
}


class RecompileHazardRule(Rule):
    rule_id = "PTL004"
    scope = "all"
    summary = "jit callsite / array shape that recompiles per distinct value"
    rationale = (
        "one compiled program per session is the streaming contract; "
        "per-doc scalars and unbucketed shapes mint executables per doc"
    )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        jitted, _ = astutil.jit_roots(ctx.tree)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = astutil.call_name(node)
            if name is None:
                continue
            spec = jitted.get(name) or jitted.get(name.rpartition(".")[2])
            if spec is not None:
                yield from self._check_jit_callsite(ctx, node, name, spec)
            resolved = ctx.resolve(name)
            if resolved in _CONSTRUCTORS and ctx.in_merge_scope:
                yield from self._check_constructor(ctx, node, resolved)

    # -- jit callsites --------------------------------------------------------

    def _check_jit_callsite(
        self, ctx: FileContext, call: ast.Call, name: str, spec: astutil.JitSpec
    ) -> Iterator[Finding]:
        for i, arg in enumerate(call.args):
            if i in spec.static_argnums:
                culprit = self._shape_derived(ctx, arg)
                if culprit:
                    yield ctx.finding(
                        self.rule_id,
                        arg,
                        f"static arg {i} of jit callsite '{name}' is "
                        f"shape-derived ({culprit}) — every distinct value "
                        "recompiles; route it through the padded-shape tables",
                    )
            if self._varlen_pytree(arg):
                yield ctx.finding(
                    self.rule_id,
                    arg,
                    f"variable-length sequence built inline at jit callsite "
                    f"'{name}' — each length is a new pytree signature; pass "
                    "a padded array",
                )
        for kw in call.keywords:
            if kw.arg in spec.static_argnames:
                culprit = self._shape_derived(ctx, kw.value)
                if culprit:
                    yield ctx.finding(
                        self.rule_id,
                        kw.value,
                        f"static kwarg '{kw.arg}' of jit callsite '{name}' is "
                        f"shape-derived ({culprit}) — every distinct value "
                        "recompiles; route it through the padded-shape tables",
                    )

    # -- array constructors ---------------------------------------------------

    def _check_constructor(
        self, ctx: FileContext, call: ast.Call, resolved: str
    ) -> Iterator[Finding]:
        shape_args = list(call.args[:1]) + [
            kw.value for kw in call.keywords if kw.arg == "shape"
        ]
        for shape in shape_args:
            culprit = self._shape_derived(ctx, shape, stop_at=call)
            if culprit:
                yield ctx.finding(
                    self.rule_id,
                    shape,
                    f"'{resolved}' shape embeds raw {culprit} — per-doc "
                    "sizes must route through a width bucket "
                    f"({'/'.join(sorted(ctx.config.bucket_fns))}) so shapes "
                    "stay stable across rounds",
                )

    # -- helpers --------------------------------------------------------------

    def _varlen_pytree(self, arg: ast.AST) -> bool:
        if isinstance(arg, (ast.ListComp, ast.GeneratorExp)):
            return True
        return isinstance(arg, ast.Call) and astutil.call_name(arg) == "list"

    def _shape_derived(
        self, ctx: FileContext, expr: ast.AST, stop_at: Optional[ast.AST] = None
    ) -> Optional[str]:
        """Raw ``len(...)`` read inside ``expr`` that is not wrapped by a
        bucket function; returns a description or None.  (``x.shape`` reads
        are shape-*preserving* — stable per compiled signature — and stay
        allowed.)"""
        for node in ast.walk(expr):
            if not (isinstance(node, ast.Call) and astutil.call_name(node) == "len"):
                continue
            if self._bucketed(ctx, node, stop_at):
                continue
            return "len(...)"
        return None

    def _bucketed(
        self, ctx: FileContext, node: ast.AST, stop_at: Optional[ast.AST]
    ) -> bool:
        for anc in ctx.ancestors(node):
            if anc is stop_at:
                return False
            if isinstance(anc, ast.Call):
                name = astutil.call_name(anc)
                if name and name.rpartition(".")[2] in ctx.config.bucket_fns:
                    return True
        return False
