"""PTL002 — Python control flow on jit-traced values.

``if``/``while``/``assert`` on a tracer either raises a
ConcretizationTypeError or — worse, via weak shortcuts like
``bool(np.asarray(x))`` — silently burns a host round-trip per call.
Structural reads (``x.shape``, ``x.ndim``, ``x.dtype``, ``len(x)``) are
static at trace time and stay allowed; value branches must go through
``jnp.where`` / ``lax.cond`` / ``lax.fori_loop`` or be declared static.
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Optional, Set

from .. import astutil
from ..engine import FileContext, Finding, Rule

_STATIC_CALLS = {"len", "isinstance", "type", "hasattr", "getattr"}


class TracerControlFlowRule(Rule):
    rule_id = "PTL002"
    scope = "all"
    summary = "Python control flow branching on a jit-traced value"
    rationale = (
        "tracers have no runtime truth value; branch device-side "
        "(jnp.where/lax.cond) or mark the argument static"
    )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        _, root_defs = astutil.jit_roots(ctx.tree)
        for node in ast.walk(ctx.tree):
            spec = root_defs.get(id(node))
            if spec is None:
                continue
            tainted = astutil.traced_params(node, spec)
            yield from self._check_body(ctx, node, node.body, set(tainted))

    def _check_body(
        self, ctx: FileContext, fn: ast.AST, body: List[ast.stmt], tainted: Set[str]
    ) -> Iterator[Finding]:
        for stmt in body:
            yield from self._check_stmt(ctx, fn, stmt, tainted)

    def _check_stmt(
        self, ctx: FileContext, fn: ast.AST, stmt: ast.stmt, tainted: Set[str]
    ) -> Iterator[Finding]:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # nested defs capture the closure; params shadow outer taint
            inner = tainted - {
                a.arg
                for a in stmt.args.posonlyargs + stmt.args.args + stmt.args.kwonlyargs
            }
            yield from self._check_body(ctx, fn, stmt.body, inner)
            return
        if isinstance(stmt, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            value = stmt.value
            if value is not None:
                yield from self._check_ifexp(ctx, fn, value, tainted)
            if value is not None and self._traced_ref(ctx, value, tainted):
                targets = (
                    stmt.targets if isinstance(stmt, ast.Assign) else [stmt.target]
                )
                for target in targets:
                    for name in ast.walk(target):
                        if isinstance(name, ast.Name):
                            tainted.add(name.id)
            return
        if isinstance(stmt, (ast.If, ast.While)):
            name = self._traced_ref(ctx, stmt.test, tainted)
            if name:
                kind = "if" if isinstance(stmt, ast.If) else "while"
                yield ctx.finding(
                    self.rule_id,
                    stmt,
                    f"'{kind}' condition reads traced value '{name}' inside "
                    f"@jax.jit '{getattr(fn, 'name', '<fn>')}' — use "
                    "jnp.where/lax.cond or mark it static",
                )
            yield from self._check_body(ctx, fn, stmt.body, tainted)
            yield from self._check_body(ctx, fn, stmt.orelse, tainted)
            return
        if isinstance(stmt, ast.Assert):
            name = self._traced_ref(ctx, stmt.test, tainted)
            if name:
                yield ctx.finding(
                    self.rule_id,
                    stmt,
                    f"assert on traced value '{name}' inside @jax.jit "
                    f"'{getattr(fn, 'name', '<fn>')}' — use "
                    "checkify or a host-side precondition",
                )
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            it = stmt.iter
            if isinstance(it, ast.Call) and astutil.call_name(it) == "range":
                name = self._traced_ref(ctx, it, tainted)
                if name:
                    yield ctx.finding(
                        self.rule_id,
                        stmt,
                        f"loop bound reads traced value '{name}' inside "
                        f"@jax.jit '{getattr(fn, 'name', '<fn>')}' — use "
                        "lax.fori_loop/lax.scan",
                    )
            yield from self._check_body(ctx, fn, stmt.body, tainted)
            yield from self._check_body(ctx, fn, stmt.orelse, tainted)
            return
        # descend into remaining compound statements (with/try) and pick up
        # IfExp value-branches anywhere in expressions
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.stmt):
                yield from self._check_stmt(ctx, fn, child, tainted)
            elif isinstance(child, ast.ExceptHandler):
                yield from self._check_body(ctx, fn, child.body, tainted)
            elif isinstance(child, ast.expr):
                yield from self._check_ifexp(ctx, fn, child, tainted)

    def _check_ifexp(
        self, ctx: FileContext, fn: ast.AST, expr: ast.expr, tainted: Set[str]
    ) -> Iterator[Finding]:
        for node in ast.walk(expr):
            if isinstance(node, ast.IfExp):
                name = self._traced_ref(ctx, node.test, tainted)
                if name:
                    yield ctx.finding(
                        self.rule_id,
                        node,
                        f"ternary condition reads traced value '{name}' inside "
                        f"@jax.jit '{getattr(fn, 'name', '<fn>')}' — use jnp.where",
                    )

    def _traced_ref(
        self, ctx: FileContext, expr: ast.expr, tainted: Set[str]
    ) -> Optional[str]:
        """Name of a tainted reference in ``expr`` that is NOT behind a
        static read (.shape/.ndim/.dtype/len/isinstance), else None."""
        for node in ast.walk(expr):
            if not (isinstance(node, ast.Name) and node.id in tainted):
                continue
            if self._static_read(ctx, node):
                continue
            return node.id
        return None

    def _static_read(self, ctx: FileContext, node: ast.Name) -> bool:
        """True when the tainted name only feeds a trace-time-static read:
        an attribute chain ending in .shape/.ndim/.dtype, ``len(x)``,
        ``isinstance(x, ...)``, or an ``is (not) None`` structure check."""
        cur: ast.AST = node
        parent = ctx.parent(cur)
        while isinstance(parent, ast.Attribute):
            if parent.attr in astutil.STATIC_TRACER_ATTRS:
                return True
            cur = parent
            parent = ctx.parent(cur)
        if (
            isinstance(parent, ast.Call)
            and astutil.call_name(parent) in _STATIC_CALLS
            and cur in parent.args
        ):
            return True
        if isinstance(parent, ast.Compare):
            operands = [parent.left, *parent.comparators]
            if (
                all(isinstance(op, (ast.Is, ast.IsNot)) for op in parent.ops)
                and any(
                    isinstance(o, ast.Constant) and o.value is None
                    for o in operands
                )
            ):
                return True
        return False
