"""PTL007 — the ragged modules must be bucket-free.

The ragged layout's entire claim (ops/ragged.py, DESIGN.md "Ragged paged
apply") is ONE compiled shape for the whole pool: per-doc true op counts
and true page counts ride in as data, never as shapes.  The moment a
power-of-two rounder or width bucket sneaks into a ragged module, the
layout silently regrows the bucket ladder it exists to kill — and nothing
crashes, the recompile sentinel just starts counting executables again.

So the rule is blunt: inside a ragged module (``ragged.py`` /
``ragged_pallas.py``), CALLING any bucket/pow-2 helper is a finding, and
so is IMPORTING one (an import is a call waiting to happen, and the
cheapest place to catch the regression is the import line the reviewer
actually reads).
"""

from __future__ import annotations

import ast
from pathlib import PurePosixPath
from typing import Iterator

from .. import astutil
from ..engine import FileContext, Finding, Rule

#: the modules that carry the one-shape contract
_RAGGED_BASENAMES = frozenset({"ragged.py", "ragged_pallas.py"})

#: bucket spellings beyond the config's canonical set: the legacy private
#: rounder (store/paged._pow2 delegates to utils.shapes.next_pow2 but old
#: call sites spell it bare) and the cursor-table bucket
_EXTRA_BUCKET_FNS = frozenset({"_pow2", "pow2", "cursor_width_bucket"})


class RaggedBucketFreeRule(Rule):
    rule_id = "PTL007"
    scope = "all"
    summary = "bucket/pow-2 helper used or imported inside a ragged module"
    rationale = (
        "ragged = one compiled shape with true counts as data; any width "
        "bucket in a ragged module regrows the ladder the layout kills"
    )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if PurePosixPath(ctx.display_path).name not in _RAGGED_BASENAMES:
            return
        banned = _EXTRA_BUCKET_FNS | ctx.config.bucket_fns
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call):
                name = astutil.call_name(node)
                if name and name.rpartition(".")[2] in banned:
                    yield ctx.finding(
                        self.rule_id,
                        node,
                        f"bucket helper '{name}' called in a ragged module — "
                        "ragged dispatch takes true counts as data, never "
                        "as rounded shapes",
                    )
            elif isinstance(node, ast.ImportFrom):
                for alias in node.names:
                    if alias.name in banned:
                        yield ctx.finding(
                            self.rule_id,
                            node,
                            f"bucket helper '{alias.name}' imported into a "
                            "ragged module — the one-shape contract bans "
                            "width buckets here outright",
                        )
