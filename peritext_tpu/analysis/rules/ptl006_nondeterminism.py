"""PTL006 — wall-clock / unseeded-RNG reads in deterministic merge regions.

Byte-equality convergence means a merge's output is a pure function of the
change set.  A wall-clock read or a global/unseeded RNG inside ``core/``/
``ops/``/``parallel/`` is entropy leaking into that function — even when it
"only" orders retries, it desynchronizes replicas' observable behavior and
makes fuzz failures unreproducible.  RNG must arrive as an explicitly
seeded ``random.Random(seed)`` / ``np.random.default_rng(seed)`` passed in
by the caller; time belongs to the observability layer.
"""

from __future__ import annotations

import ast
from typing import Iterator

from .. import astutil
from ..engine import FileContext, Finding, Rule

_WALL_CLOCK = {
    "time.time", "time.time_ns", "time.monotonic", "time.monotonic_ns",
    "time.perf_counter", "time.perf_counter_ns", "time.process_time",
    "datetime.datetime.now", "datetime.datetime.utcnow",
    "datetime.datetime.today", "datetime.date.today",
}
#: module-level (global-state) RNG entry points
_GLOBAL_RNG = {
    f"random.{fn}"
    for fn in (
        "random", "randint", "randrange", "uniform", "choice", "choices",
        "sample", "shuffle", "getrandbits", "gauss", "normalvariate",
        "betavariate", "expovariate", "randbytes",
    )
} | {
    f"numpy.random.{fn}"
    for fn in (
        "rand", "randn", "randint", "random", "random_sample", "ranf",
        "shuffle", "permutation", "choice", "normal", "uniform", "bytes",
    )
}
#: RNG constructors that are deterministic ONLY when given a seed
_SEEDABLE = {"random.Random", "numpy.random.default_rng", "numpy.random.RandomState"}
_ENTROPY = {"random.SystemRandom", "secrets.token_bytes", "secrets.token_hex",
            "uuid.uuid4", "os.urandom"}


class NondeterminismRule(Rule):
    rule_id = "PTL006"
    scope = "merge"
    summary = "wall-clock or unseeded RNG in a deterministic merge region"
    rationale = (
        "merge output must be a pure function of the change set; entropy "
        "makes replicas diverge and fuzz failures unreproducible"
    )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = astutil.call_name(node)
            if name is None:
                continue
            resolved = ctx.resolve(name)
            if resolved in _WALL_CLOCK:
                yield ctx.finding(
                    self.rule_id,
                    node,
                    f"wall-clock read '{resolved}()' in a deterministic merge "
                    "region — timing belongs in the observability layer",
                )
            elif resolved in _GLOBAL_RNG:
                yield ctx.finding(
                    self.rule_id,
                    node,
                    f"global-RNG call '{resolved}()' in a deterministic merge "
                    "region — thread a seeded random.Random through instead",
                )
            elif resolved in _SEEDABLE and not node.args and not node.keywords:
                yield ctx.finding(
                    self.rule_id,
                    node,
                    f"unseeded '{resolved}()' in a deterministic merge region "
                    "— construct it from an explicit seed",
                )
            elif resolved in _ENTROPY:
                yield ctx.finding(
                    self.rule_id,
                    node,
                    f"entropy source '{resolved}()' in a deterministic merge "
                    "region — derive ids/jitter from seeded state",
                )
