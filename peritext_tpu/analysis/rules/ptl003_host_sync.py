"""PTL003 — host synchronization reachable from a ``@jax.jit`` function.

``.item()``, ``jax.device_get``, ``np.asarray``, ``.block_until_ready()``
inside traced code either fail outright on a tracer or (when they sneak
through on concrete aux values) serialize the async dispatch pipeline — the
FusionStitching defect class: a fusion-breaking host sync in the middle of
a device program.  Reachability is file-local: a helper called (by bare
name or ``self.method``) from a jit root is scanned too.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, Set

from .. import astutil
from ..engine import FileContext, Finding, Rule

#: fully-resolved call names that force a host sync
_SYNC_CALLS = {
    "jax.device_get",
    "jax.block_until_ready",
    "numpy.asarray",
    "numpy.array",
}
#: method attributes that force a host sync on an array receiver
_SYNC_METHODS = {"item", "tolist", "block_until_ready"}
#: explicit escape hatches — syncs inside these callbacks are intentional
_CALLBACK_HOSTS = {
    "jax.pure_callback",
    "jax.experimental.io_callback",
    "jax.debug.callback",
    "jax.debug.print",
}
_CASTS = {"float", "int", "bool", "complex"}


class HostSyncRule(Rule):
    rule_id = "PTL003"
    scope = "all"
    summary = "host sync reachable from a @jax.jit function"
    rationale = (
        "host syncs break XLA fusion and the async dispatch overlap the "
        "streaming engine depends on; keep device programs pure"
    )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        _, root_defs = astutil.jit_roots(ctx.tree)
        if not root_defs:
            return
        defs = astutil.module_defs(ctx.tree)
        # file-local reachability closure from the jit roots
        reachable: Dict[int, str] = {}  # id(def) -> root chain label
        frontier = [
            (node, getattr(node, "name", "<fn>"))
            for node in defs.values()
            if id(node) in root_defs
        ]
        for node, chain in frontier:
            reachable[id(node)] = chain
        while frontier:
            node, chain = frontier.pop()
            for callee in sorted(astutil.called_local_names(node)):
                target = defs.get(callee)
                if target is None or id(target) in reachable:
                    continue
                label = f"{chain} -> {callee}"
                reachable[id(target)] = label
                frontier.append((target, label))
        for node in defs.values():
            chain = reachable.get(id(node))
            if chain is None:
                continue
            spec = root_defs.get(id(node))
            tainted = astutil.traced_params(node, spec) if spec else set()
            yield from self._scan_fn(ctx, node, chain, tainted)

    def _scan_fn(
        self, ctx: FileContext, fn: ast.AST, chain: str, tainted: Set[str]
    ) -> Iterator[Finding]:
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            if self._inside_callback(ctx, node):
                continue
            name = astutil.call_name(node)
            resolved = ctx.resolve(name) if name else None
            if resolved in _SYNC_CALLS:
                yield ctx.finding(
                    self.rule_id,
                    node,
                    f"host sync '{resolved}' reachable from @jax.jit "
                    f"(via {chain}) — keep the device program pure or move "
                    "the sync outside the jit boundary",
                )
                continue
            func = node.func
            if (
                isinstance(func, ast.Attribute)
                and func.attr in _SYNC_METHODS
                and not node.args
            ):
                yield ctx.finding(
                    self.rule_id,
                    node,
                    f"host sync '.{func.attr}()' reachable from @jax.jit "
                    f"(via {chain}) — device values must stay on device "
                    "inside traced code",
                )
                continue
            if (
                name in _CASTS
                and len(node.args) == 1
                and isinstance(node.args[0], ast.Name)
                and node.args[0].id in tainted
            ):
                yield ctx.finding(
                    self.rule_id,
                    node,
                    f"'{name}()' concretizes traced value "
                    f"'{node.args[0].id}' inside @jax.jit (via {chain}) — "
                    "this is a host sync; keep it as an array",
                )

    def _inside_callback(self, ctx: FileContext, node: ast.AST) -> bool:
        for anc in ctx.ancestors(node):
            if isinstance(anc, ast.Call):
                name = astutil.call_name(anc)
                if name and ctx.resolve(name) in _CALLBACK_HOSTS:
                    return True
        return False
