"""PTL001 — unordered set/dict iteration in merge/convergence modules.

Python dicts iterate in *insertion* order — for long-lived instance state
(subscriber tables, quarantine registries, per-doc side tables) insertion
order is arrival order, which diverges across replicas and sessions.  Sets
hash-order their elements outright.  Anything in ``core/``/``ops/``/
``parallel/`` that fans out deliveries, builds digests, or walks registries
must iterate in an order derived from the *keys* (``sorted(...)``), not
from history.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional, Set

from .. import astutil
from ..engine import FileContext, Finding, Rule

#: wrappers that preserve the inner iterable's (dis)order
_ORDER_NEUTRAL = {"list", "tuple", "enumerate", "reversed", "iter"}
#: wrappers that impose a deterministic order
_ORDERING = {"sorted"}
_DICT_VIEWS = {"keys", "values", "items"}
#: consumers whose result does not depend on generation order — a
#: comprehension feeding one of these directly is order-clean
_ORDER_INSENSITIVE = {"sorted", "set", "frozenset", "sum", "max", "min", "any", "all", "len"}


def _set_bound_names(tree: ast.Module) -> Set[str]:
    """Names assigned from an obvious set expression anywhere in the file."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1):
            continue
        target = node.targets[0]
        if isinstance(target, ast.Name) and _is_set_expr(node.value):
            out.add(target.id)
    return out


def _typed_attr_names(tree: ast.Module) -> tuple[Set[str], Set[str]]:
    """``(set_attrs, dict_attrs)``: attribute names assigned an obvious
    set / dict expression anywhere in the file (``self._pending = set()``,
    ``self._subscribers = {}``) — bare iteration over these is the most
    common spelling of the arrival-order hazard."""
    set_attrs: Set[str] = set()
    dict_attrs: Set[str] = set()
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1):
            continue
        target = node.targets[0]
        if not isinstance(target, ast.Attribute):
            continue
        if _is_set_expr(node.value):
            set_attrs.add(target.attr)
        elif _is_dict_expr(node.value):
            dict_attrs.add(target.attr)
    return set_attrs, dict_attrs


def _is_set_expr(node: ast.AST) -> bool:
    if isinstance(node, (ast.Set, ast.SetComp)):
        return True
    if isinstance(node, ast.Call) and astutil.call_name(node) in ("set", "frozenset"):
        return True
    return False


def _is_dict_expr(node: ast.AST) -> bool:
    if isinstance(node, (ast.Dict, ast.DictComp)):
        return True
    if isinstance(node, ast.Call) and astutil.call_name(node) in (
        "dict", "defaultdict", "collections.defaultdict", "OrderedDict",
        "collections.OrderedDict", "Counter", "collections.Counter",
    ):
        return True
    return False


def _unwrap(expr: ast.AST) -> Optional[ast.AST]:
    """Peel order-neutral wrappers; None means an ordering wrapper was hit."""
    while isinstance(expr, ast.Call):
        name = astutil.call_name(expr)
        if name in _ORDERING:
            return None
        if name in _ORDER_NEUTRAL and expr.args:
            expr = expr.args[0]
            continue
        break
    return expr


class UnorderedIterationRule(Rule):
    rule_id = "PTL001"
    scope = "merge"
    summary = "unordered set/dict iteration in a merge/convergence module"
    rationale = (
        "insertion/hash order is replica-local history; digests and delivery "
        "fan-out must iterate in sorted key order to converge byte-equal"
    )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        set_names = _set_bound_names(ctx.tree)
        set_attrs, dict_attrs = _typed_attr_names(ctx.tree)
        for iter_expr, anchor in astutil.iteration_sites(ctx.tree):
            reason = self._unordered_reason(iter_expr, set_names, set_attrs, dict_attrs)
            if reason is not None and not self._order_insensitive(ctx, anchor):
                yield ctx.finding(
                    self.rule_id,
                    anchor,
                    f"iteration over {reason} — wrap in sorted(...) or "
                    "attribute the site in the graftlint baseline",
                )

    def _order_insensitive(self, ctx: FileContext, anchor: ast.AST) -> bool:
        """A comprehension fed directly to sorted()/set()/sum()/... cannot
        leak generation order into its result."""
        if isinstance(anchor, ast.SetComp):
            return True  # result is itself unordered; any leak is flagged at ITS use
        if not isinstance(anchor, (ast.ListComp, ast.GeneratorExp)):
            return False
        parent = ctx.parent(anchor)
        return (
            isinstance(parent, ast.Call)
            and astutil.call_name(parent) in _ORDER_INSENSITIVE
            and anchor in parent.args
        )

    def _unordered_reason(
        self,
        expr: ast.AST,
        set_names: Set[str],
        set_attrs: Set[str],
        dict_attrs: Set[str],
    ) -> Optional[str]:
        expr = _unwrap(expr)
        if expr is None:
            return None
        if isinstance(expr, (ast.Set, ast.SetComp)):
            return "a set literal/comprehension"
        if isinstance(expr, ast.Call):
            name = astutil.call_name(expr)
            if name in ("set", "frozenset"):
                return f"{name}(...)"
            if (
                isinstance(expr.func, ast.Attribute)
                and expr.func.attr in _DICT_VIEWS
                and isinstance(expr.func.value, ast.Attribute)
            ):
                recv = astutil.dotted_name(expr.func.value) or "<attr>"
                return (
                    f"dict view '{recv}.{expr.func.attr}()' of long-lived "
                    "instance state (insertion order = arrival order)"
                )
            return None
        if isinstance(expr, ast.Name) and expr.id in set_names:
            return f"set-typed name '{expr.id}'"
        if isinstance(expr, ast.Attribute):
            name = astutil.dotted_name(expr) or expr.attr
            if expr.attr in set_attrs:
                return f"set-typed instance state '{name}'"
            if expr.attr in dict_attrs:
                return (
                    f"dict-typed instance state '{name}' "
                    "(insertion order = arrival order)"
                )
        return None
