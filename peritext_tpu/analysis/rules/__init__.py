"""graftlint rule registry — one module per rule, registered by import."""

from __future__ import annotations

from typing import Dict

from ..engine import Rule
from .ptl001_unordered_iteration import UnorderedIterationRule
from .ptl002_tracer_control_flow import TracerControlFlowRule
from .ptl003_host_sync import HostSyncRule
from .ptl004_recompile_hazard import RecompileHazardRule
from .ptl005_broad_except import BroadExceptRule
from .ptl006_nondeterminism import NondeterminismRule
from .ptl007_ragged_bucket_free import RaggedBucketFreeRule

ALL_RULES: Dict[str, Rule] = {
    rule.rule_id: rule
    for rule in (
        UnorderedIterationRule(),
        TracerControlFlowRule(),
        HostSyncRule(),
        RecompileHazardRule(),
        BroadExceptRule(),
        NondeterminismRule(),
        RaggedBucketFreeRule(),
    )
}
