"""Append-only JSONL perf-regression ledger.

BENCH_r01-r05 exist as files nobody reads; this module is the reader and
the memory.  Each ledger line is one :func:`ledger_record`: the bench
ladder rows of one run plus an optional devprof snapshot, keyed by
(git sha, device fingerprint, config).  ``python -m peritext_tpu.obs perf``
renders the LAST record against a ROLLING REFERENCE — the median of each
row's value over the preceding records with a matching device fingerprint
and row identity — and ``--gate`` turns a regression beyond the row's
tolerance band into exit code 1, which is the CI perf-gate job.

Tolerance-band policy (DESIGN.md "Device cost & perf ledger"): direction
comes from the row's unit (``ops/s``/``docs/s`` regress DOWN, ``B/op`` and
seconds regress UP); bands default per unit — tight for deterministic
byte-count rows, loose for wall-clock rows (shared CI runners are noisy) —
and improvements never fail the gate.  Reference matching is per ROW:
deterministic-unit rows compare across any machine of the same platform
(their values don't depend on clock speed — this keeps the gate
non-vacuous on ephemeral CI runners), wall-clock rows require the full
device fingerprint.  A wall-clock row with no same-device reference passes
vacuously and seeds the reference; a SAME-CONFIG reference row the
candidate no longer carries is a ``missing`` verdict that FAILS the gate —
dropping or renaming a bench row must be a deliberate, reference-
regenerating change, never a silent bypass.
"""

from __future__ import annotations

import json
import os
import subprocess
from pathlib import Path
from typing import Any, Dict, List, Optional, Sequence

SCHEMA_VERSION = 1

#: regression direction by unit: +1 = higher is better, -1 = lower is better
DIRECTION_BY_UNIT = {
    "ops/s": +1,
    "docs/s": +1,
    "B/op": -1,
    "s": -1,
    "seconds": -1,
    "bytes": -1,
}

#: default tolerance band by unit (fraction of the reference value).
#: Byte-count rows are deterministic per (workload, codec) and get a tight
#: band; wall-clock-derived rows get a loose one — the gate is meant to
#: catch step regressions (a 2x slower round), not scheduler jitter.
BAND_BY_UNIT = {"B/op": 0.10, "bytes": 0.10}
DEFAULT_BAND = 0.50
#: rolling-reference window: how many prior matching records feed the median
DEFAULT_WINDOW = 5


def git_sha(root: Optional[str] = None) -> Optional[str]:
    """Current commit sha (best-effort: None outside a git checkout)."""
    try:
        out = subprocess.run(
            ["git", "rev-parse", "HEAD"],
            cwd=root or os.path.dirname(os.path.abspath(__file__)),
            capture_output=True, text=True, timeout=10,
        )
        sha = out.stdout.strip()
        return sha if out.returncode == 0 and sha else None
    except (OSError, subprocess.SubprocessError):
        return None


def device_fingerprint() -> Dict[str, Any]:
    """The ledger's device key: jax platform + device kind + host core
    count.  Two records compare only when this matches — a CPU smoke run on
    a 4-core CI runner never gates against a TPU ladder from the bench
    host."""
    platform = kind = None
    try:
        import jax

        dev = jax.devices()[0]
        platform, kind = dev.platform, dev.device_kind
    except Exception:  # graftlint: boundary(fingerprinting must work even where no jax backend initializes — the record is still keyed by cpu count)
        pass
    return {"platform": platform, "kind": kind, "cpus": os.cpu_count()}


def _row_config_key(row: Dict[str, Any]) -> str:
    """Stable per-row config identity: the sizing fields that change what
    the row measures (a smoke row must never gate against a full row)."""
    fields = ("docs", "ops_per_doc", "rounds", "slot_capacity", "hosts")
    return ",".join(f"{k}={row[k]}" for k in fields if row.get(k) is not None)


def ledger_record(
    rows: Sequence[Dict[str, Any]],
    *,
    config: str,
    devprof: Optional[Dict[str, Any]] = None,
    sha: Optional[str] = None,
    device: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    """Build one ledger record from bench result rows (each a bench.py row
    dict: ``row``/``metric``/``value``/``unit`` plus sizing fields)."""
    out_rows = []
    for r in rows:
        entry = {
            "row": r.get("row") or r.get("metric") or "?",
            "metric": r.get("metric"),
            "value": r.get("value"),
            "unit": r.get("unit"),
            "key": _row_config_key(r),
        }
        if r.get("failed"):
            entry["failed"] = True
        if r.get("skipped"):
            entry["skipped"] = True
        if isinstance(r.get("latency"), dict):
            # the latency plane's per-stage decomposition rides along so
            # `obs why` can diff a failing row's stages against its
            # rolling reference (older records simply lack the key)
            entry["latency"] = r["latency"]
        out_rows.append(entry)
    return {
        "schema": SCHEMA_VERSION,
        "sha": sha if sha is not None else git_sha(),
        "device": device if device is not None else device_fingerprint(),
        "config": config,
        "rows": out_rows,
        "devprof": devprof,
    }


def append_record(path: str | Path, record: Dict[str, Any]) -> None:
    """Append one record as a JSONL line (the ledger is append-only)."""
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    with open(path, "a") as fh:
        fh.write(json.dumps(record, sort_keys=True) + "\n")


def load_ledger(path: str | Path) -> List[Dict[str, Any]]:
    """All records, oldest first.  Raises on unreadable/corrupt lines —
    a silently-skipped record would silently weaken the gate."""
    records = []
    for n, line in enumerate(Path(path).read_text().splitlines(), 1):
        line = line.strip()
        if not line:
            continue
        try:
            records.append(json.loads(line))
        except json.JSONDecodeError as exc:
            raise ValueError(f"{path}:{n}: corrupt ledger line: {exc}") from exc
    return records


# -- regression gate ---------------------------------------------------------


#: units whose values are a function of (workload, code), not clock speed —
#: their rows gate across machines of one PLATFORM, which is what keeps the
#: gate non-vacuous on ephemeral CI runners whose core counts never match
#: the committed reference's fingerprint
DETERMINISTIC_UNITS = frozenset(BAND_BY_UNIT)


def _row_identity(config: Optional[str], row: Dict[str, Any]) -> tuple:
    """A row's gate identity: the RECORD's config (a smoke row must never
    gate against a full row — sizing fields alone can be absent, e.g. the
    wire row) plus the row's name/metric/unit/sizing key."""
    return (config, row.get("row"), row.get("metric"), row.get("unit"),
            row.get("key"))


def _device_matches(a: Optional[Dict], b: Optional[Dict], match: str) -> bool:
    if match == "any":
        return True
    a, b = a or {}, b or {}
    if match == "platform":
        return a.get("platform") == b.get("platform")
    return a == b  # "device": the full fingerprint


def _match_level(unit: str, match: str) -> str:
    """Deterministic-unit rows relax a ``device`` match to ``platform``
    (their values don't depend on the machine's clock); explicit
    ``platform``/``any`` requests are honored as given."""
    if match == "device" and unit in DETERMINISTIC_UNITS:
        return "platform"
    return match


def _median(values: List[float]) -> float:
    xs = sorted(values)
    mid = len(xs) // 2
    return xs[mid] if len(xs) % 2 else (xs[mid - 1] + xs[mid]) / 2


def evaluate(
    records: Sequence[Dict[str, Any]],
    *,
    tolerance: Optional[float] = None,
    window: int = DEFAULT_WINDOW,
    match: str = "device",
) -> Dict[str, Any]:
    """Judge the LAST record against the rolling reference built from the
    records before it.  Returns ``{"rows": [verdict...], "regressed": bool,
    "candidate": {...}, "reference_records": n}``; verdict statuses are
    ``ok`` / ``improved`` / ``regressed`` / ``failed`` (the row failed where
    its reference succeeded) / ``new`` (no reference — vacuous pass) /
    ``missing`` (a same-config reference row the candidate no longer
    carries — a renamed or dropped bench row must fail the gate loudly,
    never silently weaken it to a vacuous pass).  Each verdict carries the
    reference median (``ref``), the SIGNED absolute delta (``delta``, in
    the row's own unit) and percentage delta alongside the status, plus
    the candidate row's ``latency`` decomposition when the ledger record
    has one — so ``obs why`` and CI artifacts consume one schema."""
    if not records:
        raise ValueError("empty ledger: nothing to evaluate")
    candidate = records[-1]
    cand_config = candidate.get("config")
    cand_dev = candidate.get("device")
    levels = {"device", "platform"} if match == "device" else {match}
    # device-filtered but NOT window-sliced: the window applies per row
    # identity below — slicing here would let recent OTHER-config records
    # evict a row's true references and quietly turn the gate vacuous
    priors = {
        level: [r for r in records[:-1]
                if _device_matches(r.get("device"), cand_dev, level)]
        for level in levels
    }
    verdicts = []
    regressed = False
    cand_idents = set()
    for row in candidate.get("rows", []):
        unit = row.get("unit") or ""
        ident = _row_identity(cand_config, row)
        cand_idents.add(ident)
        refs = [
            pr["value"]
            for rec in priors[_match_level(unit, match)]
            for pr in rec.get("rows", [])
            if _row_identity(rec.get("config"), pr) == ident
            and isinstance(pr.get("value"), (int, float))
            and not pr.get("failed") and not pr.get("skipped")
        ][-window:]
        band = (
            tolerance if tolerance is not None
            else BAND_BY_UNIT.get(unit, DEFAULT_BAND)
        )
        verdict = {
            "row": row.get("row"),
            "unit": unit,
            "value": row.get("value"),
            "ref": round(_median(refs), 4) if refs else None,
            "refs": len(refs),
            "band_pct": round(band * 100, 1),
            "delta": None,
            "delta_pct": None,
            "status": "new",
        }
        if isinstance(row.get("latency"), dict):
            verdict["latency"] = row["latency"]
        if refs:
            ref = _median(refs)
            value = row.get("value")
            if row.get("failed") or not isinstance(value, (int, float)):
                verdict["status"] = "failed"
                regressed = True
            else:
                direction = DIRECTION_BY_UNIT.get(unit, +1)
                delta = (value - ref) / ref if ref else 0.0
                verdict["delta"] = round(value - ref, 4)
                verdict["delta_pct"] = round(delta * 100, 1)
                shortfall = -delta * direction  # >0 = worse, whatever the unit
                if shortfall > band:
                    verdict["status"] = "regressed"
                    regressed = True
                elif delta * direction > band:
                    verdict["status"] = "improved"
                else:
                    verdict["status"] = "ok"
        verdicts.append(verdict)
    # reference rows the candidate dropped: only SAME-CONFIG references
    # count (a single-mode record appended to a ladder ledger is a new
    # config, not a mass row-drop), each judged at its own unit's level
    missing_seen = set(cand_idents)
    for level in sorted(levels):
        same_config = [r for r in priors[level]
                       if r.get("config") == cand_config][-window:]
        for rec in same_config:
            for pr in rec.get("rows", []):
                unit = pr.get("unit") or ""
                if _match_level(unit, match) != level:
                    continue
                ident = _row_identity(rec.get("config"), pr)
                if ident in missing_seen:
                    continue
                missing_seen.add(ident)
                verdicts.append({
                    "row": pr.get("row"),
                    "unit": unit,
                    "value": None,
                    "ref": pr.get("value"),
                    "refs": 1,
                    "band_pct": round(
                        (tolerance if tolerance is not None
                         else BAND_BY_UNIT.get(unit, DEFAULT_BAND)) * 100, 1),
                    "delta": None,
                    "delta_pct": None,
                    "status": "missing",
                })
                regressed = True
    return {
        "rows": verdicts,
        "regressed": regressed,
        "candidate": {
            "sha": candidate.get("sha"),
            "config": cand_config,
            "device": cand_dev,
        },
        "reference_records": max(len(p) for p in priors.values()),
    }
