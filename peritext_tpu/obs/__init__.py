"""peritext_tpu.obs — the fleet telemetry subsystem.

What grew out of ``peritext_tpu/observability.py`` (which remains as a
re-export shim so no historical import breaks): the instrumentation layer
every streaming-perf PR is judged by.  Four cooperating pieces:

* :mod:`.spans` — structured pipeline spans (:class:`Tracer`): nested,
  monotonic-id spans over the merge pipeline (ingest → encode →
  device-apply → resolve → decode → patch-scatter), serialized as
  Perfetto-compatible Chrome trace-event JSON and correlated ACROSS HOSTS
  by a compact trace-context field carried in the wire codec (frame v5)
  and the anti-entropy frontier.
* :mod:`.histograms` — fixed-bucket latency/size histograms with
  p50/p95/p99 readout; the rolling round-latency window behind the
  supervisor's deadline autotuning.
* :mod:`.recorder` — the flight recorder: a bounded ring of recent
  spans+events per session, dumped as JSONL on quarantine, rollback, or
  transport give-up so chaos-soak failures become post-mortems.
* :mod:`.convergence` — per-peer replication-lag watermarks (ops-behind
  clock-delta sums, staleness) and divergence probes (same frontier +
  different commutative store digest = a first-class incident) fed by
  every anti-entropy frontier exchange; the behind-states the
  ``parallel/gossip.py`` healing scheduler consumes.
* :mod:`.devprof` — the DEVICE-facing layer the host-side telemetry above
  cannot provide: per-jit-site / per-shape-bucket XLA cost and memory
  introspection (``cost_analysis``/``memory_analysis`` of the compiled
  merge executables), bucket-occupancy accounting (real vs padded ops per
  padded-shape bucket) and round-boundary device-memory watermarks.  Off
  by default; ``GLOBAL_DEVPROF.enable()`` arms every hook in the stack.
* :mod:`.ledger` — the append-only JSONL perf history (bench ladder rows +
  devprof snapshots keyed by git sha / device / config) behind
  ``python -m peritext_tpu.obs perf`` and the CI perf-gate job.
* :mod:`.latency` — the time-to-visibility latency plane: per-drain-batch
  stage-watermark records (admit → window → stage → dispatch → commit →
  visibility) fed by the serve tier, per-stage histograms + SLO burn-rate
  gauges (``peritext_latency_*``, ``/latency.json``), and the
  ``python -m peritext_tpu.obs why`` attribution engine that names the
  dominant moved stage when the perf gate fails.  Off by default;
  ``GLOBAL_LATENCY.enable()`` arms the serve-tier hooks.
* :mod:`.incidents` — the fleet incident plane: a deterministic,
  round-counted :class:`IncidentMonitor` that folds every plane above into
  typed incidents (host-death, divergence, quarantine-storm, shed-storm,
  slo-burn, recompile-storm, migration-failure, perf-regression) with a
  two-watermark open→ack→resolve lifecycle, (host, doc, trace)-window
  causal correlation ordered by the ``latency.attribute`` tie-break, and a
  frontier-sentinel summary so two frontends agree on the incident view;
  plus :func:`merge_flight_dumps`, the cross-host black-box timeline
  (``python -m peritext_tpu.obs incidents`` / ``status`` / ``flight``).
* :mod:`.timeseries` — the fleet history plane: a deterministic,
  round-counted :class:`TimeSeriesPlane` that periodically samples every
  plane above into min/max/last frames retained across downsampling
  tiers (recent full-rate, older merged N:1 so spikes survive), persists
  append-only JSONL segments that replay byte-identically, scores a
  rolling-median + MAD anomaly per gauge key (findings feed the incident
  monitor as its ninth signal source), and records the fused serving
  tier's per-window occupancy rows — the ``propose(history=...)``
  feedback loop (``peritext_history_*``, ``/timeseries.json``,
  ``python -m peritext_tpu.obs history`` / ``top``).  Off by default;
  ``GLOBAL_HISTORY.enable()`` arms the serve-tier hooks.
* :mod:`.exporters` — Prometheus text exposition and JSON snapshot
  endpoints (:class:`MetricsServer`, mounted by ``ReplicaServer``:
  ``/metrics`` with ``peritext_convergence_*`` gauges, ``/health.json``,
  ``/convergence.json``, ``/trace.json``), plus the
  ``python -m peritext_tpu.obs`` CLI (:mod:`.__main__`) that renders a
  trace dump into a per-stage/per-host summary table and
  ``/convergence.json`` scrapes into the fleet lag view (``fleet``).

Design rule (DESIGN.md "Telemetry"): timestamps are telemetry, not merge
inputs.  Merge-scope modules (``core/``, ``ops/``, ``parallel/``) never
read the wall clock directly — they open spans and observe histograms, and
the clock reads happen HERE, outside graftlint's PTL006 merge scope, so the
determinism contract stays machine-checkable.
"""

from .convergence import ConvergenceMonitor, DivergenceIncident, PeerLag
from .devprof import (
    DeviceProfiler,
    GLOBAL_DEVPROF,
    note_jit_dispatch,
    occupancy_key,
)
from .events import EventLog, profile_trace
from .histograms import (
    GLOBAL_HISTOGRAMS,
    Histogram,
    HistogramRegistry,
    LATENCY_BUCKETS_S,
    SIZE_BUCKETS,
)
from .incidents import (
    Incident,
    IncidentMonitor,
    TAXONOMY,
    merge_flight_dumps,
)
from .latency import (
    GLOBAL_LATENCY,
    LatencyPlane,
    STAGES,
    attribute,
    check_sum_consistency,
)
from .metrics import Counters, GLOBAL_COUNTERS, health_snapshot
from .recorder import FlightRecorder
from .sentinel import RecompileSentinel
from .spans import (
    GLOBAL_TRACER,
    Span,
    TraceContext,
    Tracer,
    ambient_parent,
    current_span,
    merge_traces,
)
from .stats import MergeStats
from .timeseries import (
    GLOBAL_HISTORY,
    TimeSeriesPlane,
    anomaly_kind,
    replay_segments,
)
from .exporters import MetricsServer, prometheus_text

__all__ = [
    "ConvergenceMonitor",
    "Counters",
    "DeviceProfiler",
    "DivergenceIncident",
    "EventLog",
    "FlightRecorder",
    "GLOBAL_COUNTERS",
    "GLOBAL_DEVPROF",
    "GLOBAL_HISTOGRAMS",
    "GLOBAL_HISTORY",
    "GLOBAL_LATENCY",
    "GLOBAL_TRACER",
    "Histogram",
    "HistogramRegistry",
    "Incident",
    "IncidentMonitor",
    "LATENCY_BUCKETS_S",
    "LatencyPlane",
    "MergeStats",
    "MetricsServer",
    "PeerLag",
    "RecompileSentinel",
    "SIZE_BUCKETS",
    "STAGES",
    "Span",
    "TAXONOMY",
    "TimeSeriesPlane",
    "TraceContext",
    "Tracer",
    "ambient_parent",
    "anomaly_kind",
    "attribute",
    "check_sum_consistency",
    "current_span",
    "health_snapshot",
    "merge_flight_dumps",
    "merge_traces",
    "note_jit_dispatch",
    "occupancy_key",
    "profile_trace",
    "prometheus_text",
    "replay_segments",
]
