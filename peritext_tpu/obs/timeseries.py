"""Fleet history plane: round-counted time-series retention + anomaly scoring.

Every other plane answers "what is happening NOW" — this module retains
those answers over time so drift is visible before the perf gate fails.
It periodically samples any set of plane snapshots (health, convergence,
serve, devprof, latency, incidents, mesh, page-pool — anything that is a
dict or exposes ``snapshot()``) into fixed-interval FRAMES held in a
bounded in-memory ring and optionally persisted as append-only JSONL
segments.

**Retention tiers**: tier 0 holds recent frames at full rate; when it
overflows, its oldest ``merge_factor`` frames merge N:1 into one tier-1
frame, and so on down the cascade.  Every frame — raw or merged — keeps
``min``/``max``/``last`` per gauge, so a one-frame spike survives every
downsampling tier (the min/max envelope never forgets it) while storage
stays O(tiers × tier_capacity).  The last tier drops oldest-first.

**Determinism contract**: the plane is ROUND-counted, never wall-clocked.
``advance_round()``/``sample()`` advance a logical round counter; frames
are stamped with rounds; the anomaly scorer is a pure function of the
ring.  This file sits in graftlint's merge scope (the plan-scope split:
``obs/timeseries.py`` joins ``plan/fusion.py`` in
``LintConfig.merge_scope_files``), so PTL006 bans clock/RNG reads here
outright — sampling overhead is measured by CALLERS and fed in as data
via :meth:`TimeSeriesPlane.note_overhead` ("timestamps are telemetry,
not merge inputs").  Persisted segments replay byte-identically
(:func:`replay_segments`; pinned by test).

**Anomaly scoring**: per gauge key, a rolling-median + MAD z-score over
the tier-0 ring (``z = 0.6745·|x − med| / MAD``).  A zero MAD (flat
baseline) falls back to a relative floor scale so flat-then-spiked
counters still fire while float jitter on drifting gauges stays quiet.
Findings are typed dicts; :func:`anomaly_kind` maps a gauge key's source
prefix onto the EXISTING incident taxonomy (``IncidentMonitor`` consumes
them via ``observe_timeseries`` as its ninth signal source — anomaly
findings are root-cause candidates on existing kinds, never a new latch).

**The closed planner loop**: ``FusedMuxGroup.pump`` records per-window
occupancy rows via :meth:`TimeSeriesPlane.record_occupancy`;
``plan/tuner.propose(history=...)`` weights its cost-model terms by the
observed occupancy DISTRIBUTION (p90 utilization, sparse-window dispatch
weighting) instead of the devprof point estimate — see DESIGN.md
"History plane".

Off by default (the devprof/latency pattern): arming is
``plane.enable()``, every feed site checks ``plane.enabled``, and arming
compiles nothing (recompile-sentinel pin in ``tests/test_timeseries.py``).
"""

from __future__ import annotations

import json
import math
import threading
from collections import deque
from pathlib import Path
from typing import Any, Dict, List, Optional, Sequence

#: when a gauge's rolling MAD is exactly zero (flat baseline), the z-score
#: falls back to ``|x − med| / max(|med| · FRAC, ABS)`` — large enough to
#: fire on a genuine spike from a flat line, forgiving enough that float
#: jitter on a drifting gauge stays quiet
MAD_FLOOR_FRAC = 0.05
MAD_FLOOR_ABS = 1e-6

#: z-scores are capped so a spike over a zero-MAD baseline stays finite
#: and JSON-safe
Z_CAP = 1e9

#: gauge-key prefix -> incident kind for anomaly findings (first match
#: wins; walked in tuple order, so the order IS the contract).  Keys are
#: prefixed by the ``sample(**sources)`` kwarg that produced them.
ANOMALY_KIND_PREFIXES = (
    ("convergence.", "divergence"),
    ("fleet.", "host-death"),
    ("jit.", "recompile-storm"),
    ("latency.", "slo-burn"),
    ("recompiles.", "recompile-storm"),
    ("serve.", "shed-storm"),
    ("session.", "quarantine-storm"),
)

#: anything unmapped (plan., devprof., probe., ...) is a perf concern
ANOMALY_DEFAULT_KIND = "perf-regression"


def anomaly_kind(key: str) -> str:
    """Map a flattened gauge key onto the existing incident taxonomy."""
    for prefix, kind in ANOMALY_KIND_PREFIXES:
        if key.startswith(prefix):
            return kind
    return ANOMALY_DEFAULT_KIND


# -- pure helpers (shared by the plane, the exporter route, and the CLI) -----


def _snap(obj: Any) -> Dict[str, Any]:
    """Normalize a sample source: a plain dict passes through, a live
    plane contributes its ``snapshot()``."""
    if isinstance(obj, dict):
        return obj
    snap = getattr(obj, "snapshot", None)
    if callable(snap):
        body = snap()
        if isinstance(body, dict):
            return body
    raise TypeError(
        f"history source must be a dict or expose snapshot(): {type(obj)!r}"
    )


def _flatten(prefix: str, value: Any, out: Dict[str, float]) -> None:
    """Collapse a snapshot to dotted-key numeric gauges.  Bools become
    0/1, non-finite floats are dropped (JSON safety), strings/lists are
    skipped — gauges are the retained signal, labels are not."""
    if isinstance(value, bool):
        out[prefix] = 1.0 if value else 0.0
    elif isinstance(value, (int, float)):
        v = float(value)
        if math.isfinite(v):
            out[prefix] = v
    elif isinstance(value, dict):
        for k in sorted(value, key=str):
            _flatten(f"{prefix}.{k}", value[k], out)


def flatten_gauges(name: str, source: Any) -> Dict[str, float]:
    """Public flattening entry: ``{name}.{dotted.path}: float``."""
    out: Dict[str, float] = {}
    _flatten(name, _snap(source), out)
    return out


def _median(values: Sequence[float]) -> float:
    vs = sorted(values)
    n = len(vs)
    mid = n // 2
    if n % 2:
        return float(vs[mid])
    return (float(vs[mid - 1]) + float(vs[mid])) / 2.0


def _percentile(sorted_vals: Sequence[float], q: float) -> float:
    """Ceil-rank percentile over an ascending list (deterministic; the
    same convention the cost model uses for occupancy distributions)."""
    if not sorted_vals:
        return 0.0
    idx = max(0, math.ceil(q * len(sorted_vals)) - 1)
    return float(sorted_vals[min(idx, len(sorted_vals) - 1)])


def mad_z(value: float, baseline: Sequence[float]) -> float:
    """The anomaly score: robust z over a rolling baseline (see module
    doc for the zero-MAD floor rule).  Pure — no clock, no RNG."""
    med = _median(baseline)
    mad = _median([abs(v - med) for v in baseline])
    if mad > 0.0:
        scale = mad
    else:
        scale = max(abs(med) * MAD_FLOOR_FRAC, MAD_FLOOR_ABS)
    return min(0.6745 * abs(value - med) / scale, Z_CAP)


def chronological_frames(snap: Dict[str, Any]) -> List[Dict[str, Any]]:
    """All retained frames oldest -> newest: the deepest (most merged)
    tier holds the oldest history, tier 0 the newest."""
    frames: List[Dict[str, Any]] = []
    for tier in reversed(snap.get("tiers") or []):
        frames.extend(tier)
    return frames


def snapshot_keys(snap: Dict[str, Any]) -> List[str]:
    """Sorted union of gauge keys across every retained frame."""
    keys = set()
    for frame in chronological_frames(snap):
        keys.update(frame.get("gauges") or ())
    return sorted(keys)


def series_points(snap: Dict[str, Any], key: str,
                  window: Optional[int] = None) -> List[List[float]]:
    """``[[round, last], ...]`` for one gauge key, oldest -> newest,
    optionally limited to the trailing ``window`` points."""
    points: List[List[float]] = []
    for frame in chronological_frames(snap):
        g = (frame.get("gauges") or {}).get(key)
        if g is not None:
            points.append([frame.get("round_last", frame.get("round", 0)),
                           g["last"]])
    if window is not None and window > 0:
        points = points[-window:]
    return points


def series_rate(points: Sequence[Sequence[float]]) -> List[List[float]]:
    """Per-round derivative between consecutive points: ``[[round,
    (v - v_prev) / (round - round_prev)], ...]`` (the counter-rate view)."""
    rates: List[List[float]] = []
    for prev, cur in zip(points, points[1:]):
        dr = cur[0] - prev[0]
        if dr > 0:
            rates.append([cur[0], round((cur[1] - prev[1]) / dr, 6)])
    return rates


def key_summary(snap: Dict[str, Any], key: str,
                window: Optional[int] = None) -> Dict[str, Any]:
    """Per-key percentile summary.  ``min``/``max`` come from the frame
    ENVELOPES (so spikes merged into deep tiers still count); percentiles
    are over last-values."""
    lasts: List[float] = []
    lo: Optional[float] = None
    hi: Optional[float] = None
    frames = chronological_frames(snap)
    if window is not None and window > 0:
        frames = frames[-window:]
    for frame in frames:
        g = (frame.get("gauges") or {}).get(key)
        if g is None:
            continue
        lasts.append(g["last"])
        lo = g["min"] if lo is None else min(lo, g["min"])
        hi = g["max"] if hi is None else max(hi, g["max"])
    if not lasts:
        return {"key": key, "points": 0}
    ordered = sorted(lasts)
    return {
        "key": key,
        "points": len(lasts),
        "min": lo,
        "max": hi,
        "mean": round(sum(lasts) / len(lasts), 6),
        "p50": _percentile(ordered, 0.50),
        "p95": _percentile(ordered, 0.95),
        "p99": _percentile(ordered, 0.99),
        "first": lasts[0],
        "last": lasts[-1],
        "delta": round(lasts[-1] - lasts[0], 6),
    }


def query_snapshot(snap: Dict[str, Any],
                   params: Dict[str, str]) -> Dict[str, Any]:
    """The ``/timeseries.json?...`` engine, shared with ``obs history``:
    no params -> the full snapshot; ``key=`` -> that gauge's points +
    summary (``rate=1`` adds the derivative); ``window=N`` without a key
    -> the trailing N frames."""
    key = params.get("key")
    raw_window = params.get("window")
    window = int(raw_window) if raw_window else None
    want_rate = str(params.get("rate", "")).lower() in ("1", "true", "yes")
    if key:
        points = series_points(snap, key, window=window)
        body: Dict[str, Any] = {
            "key": key,
            "points": points,
            "summary": key_summary(snap, key, window=window),
        }
        if want_rate:
            body["rate"] = series_rate(points)
        return body
    if window:
        return {
            "window": window,
            "frames": chronological_frames(snap)[-window:],
            "keys": snapshot_keys(snap),
        }
    return snap


def occupancy_distribution(values: Sequence[float]) -> Dict[str, Any]:
    """The distribution body the planner weights by: count, mean, the
    p10/p50/p90 spread, and the sparse-window fraction (occupancy < 0.5
    — windows that under-amortize the dispatch floor)."""
    vals = sorted(float(v) for v in values)
    if not vals:
        return {"count": 0}
    sparse = sum(1 for v in vals if v < 0.5)
    return {
        "count": len(vals),
        "mean": round(sum(vals) / len(vals), 6),
        "p10": _percentile(vals, 0.10),
        "p50": _percentile(vals, 0.50),
        "p90": _percentile(vals, 0.90),
        "sparse_frac": round(sparse / len(vals), 6),
    }


def _merge_frames(chunk: Sequence[Dict[str, Any]]) -> Dict[str, Any]:
    """Merge N chronological frames into one downsampled frame: min of
    mins, max of maxes, last by round order, key union."""
    gauges: Dict[str, Dict[str, float]] = {}
    for frame in chunk:
        fg = frame["gauges"]
        for key in sorted(fg):
            g = fg[key]
            cur = gauges.get(key)
            if cur is None:
                gauges[key] = {"min": g["min"], "max": g["max"],
                               "last": g["last"]}
            else:
                cur["min"] = min(cur["min"], g["min"])
                cur["max"] = max(cur["max"], g["max"])
                cur["last"] = g["last"]
    return {
        "round": chunk[0]["round"],
        "round_last": chunk[-1]["round_last"],
        "frames": sum(int(f["frames"]) for f in chunk),
        "gauges": gauges,
    }


# -- the plane ---------------------------------------------------------------


class TimeSeriesPlane:
    """The history plane (see module doc).  Thread-safe; off by default.

    ``sample_every`` decimates :meth:`advance_round` (the periodic feed);
    :meth:`sample` always samples.  ``dir=`` arms JSONL persistence:
    every raw frame appends to ``history-<seg>.jsonl``, rotating after
    ``segment_frames`` frames — replay with :func:`replay_segments`.
    """

    def __init__(
        self,
        sample_every: int = 1,
        tier_capacity: int = 64,
        tiers: int = 3,
        merge_factor: int = 4,
        anomaly_window: int = 32,
        min_frames: int = 8,
        threshold: float = 6.0,
        segment_frames: int = 256,
        dir: Optional[Any] = None,
        host: str = "local",
        occupancy_cap: int = 1024,
    ) -> None:
        if sample_every < 1:
            raise ValueError(f"sample_every must be >= 1, got {sample_every}")
        if tiers < 1:
            raise ValueError(f"tiers must be >= 1, got {tiers}")
        if merge_factor < 2:
            raise ValueError(f"merge_factor must be >= 2, got {merge_factor}")
        if tier_capacity < merge_factor:
            raise ValueError(
                f"tier_capacity {tier_capacity} < merge_factor {merge_factor}"
            )
        if min_frames < 2:
            raise ValueError(f"min_frames must be >= 2, got {min_frames}")
        if segment_frames < 1:
            raise ValueError(
                f"segment_frames must be >= 1, got {segment_frames}"
            )
        self.enabled = False
        self.host = host
        self.sample_every = int(sample_every)
        self.tier_capacity = int(tier_capacity)
        self.merge_factor = int(merge_factor)
        self.anomaly_window = int(anomaly_window)
        self.min_frames = int(min_frames)
        self.threshold = float(threshold)
        self.segment_frames = int(segment_frames)
        self._dir = Path(dir) if dir is not None else None
        self._lock = threading.Lock()
        self.rounds = 0
        self.frames_sampled = 0
        self._tiers: List[deque] = [deque() for _ in range(int(tiers))]
        self._segment_index = 0
        self._segment_count = 0
        self._active: Dict[str, Dict[str, Any]] = {}
        self._anomaly_counts: Dict[str, int] = {}
        self._anomaly_first_round: Dict[str, int] = {}
        self.anomalies_total = 0
        self._occ_rows: deque = deque(maxlen=int(occupancy_cap))
        self.occupancy_total = 0
        self.overhead_seconds = 0.0

    # -- arming --------------------------------------------------------------

    def enable(self) -> "TimeSeriesPlane":
        self.enabled = True
        return self

    def disable(self) -> None:
        self.enabled = False

    def __enter__(self) -> "TimeSeriesPlane":
        return self.enable()

    def __exit__(self, *exc) -> None:
        self.disable()

    # -- the feed ------------------------------------------------------------

    def advance_round(self, **sources: Any) -> Optional[Dict[str, Any]]:
        """The periodic feed: advance the round counter and, when armed
        and on the sampling cadence, sample ``sources`` into one frame.
        Returns the retained frame or None when decimated/disarmed."""
        with self._lock:
            self.rounds += 1
            if not self.enabled:
                return None
            if (self.rounds - 1) % self.sample_every:
                return None
            return self._sample_locked(sources)

    def sample(self, **sources: Any) -> Optional[Dict[str, Any]]:
        """Force one sample (still advances the round counter)."""
        with self._lock:
            self.rounds += 1
            if not self.enabled:
                return None
            return self._sample_locked(sources)

    def _sample_locked(self, sources: Dict[str, Any]) -> Dict[str, Any]:
        gauges: Dict[str, float] = {}
        for name in sorted(sources):
            _flatten(name, _snap(sources[name]), gauges)
        return self._ingest_locked(self.rounds, gauges)

    def ingest_raw(self, raw: Dict[str, Any]) -> Dict[str, Any]:
        """Re-feed one persisted raw frame through retention — the replay
        path.  The frame's own round stamp becomes the plane's clock."""
        with self._lock:
            self.rounds = int(raw["round"])
            gauges = {k: float(raw["gauges"][k]) for k in sorted(raw["gauges"])}
            return self._ingest_locked(self.rounds, gauges)

    def _ingest_locked(self, rnd: int,
                       gauges: Dict[str, float]) -> Dict[str, Any]:
        self.frames_sampled += 1
        self._persist_locked({"round": rnd, "gauges": gauges})
        frame = {
            "round": rnd,
            "round_last": rnd,
            "frames": 1,
            "gauges": {k: {"min": gauges[k], "max": gauges[k],
                           "last": gauges[k]} for k in sorted(gauges)},
        }
        self._retain_locked(frame)
        self._score_locked(frame)
        return frame

    def _persist_locked(self, raw: Dict[str, Any]) -> None:
        if self._dir is None:
            return
        self._dir.mkdir(parents=True, exist_ok=True)
        path = self._dir / f"history-{self._segment_index:05d}.jsonl"
        with path.open("a", encoding="utf-8") as fh:
            fh.write(json.dumps(raw, sort_keys=True) + "\n")
        self._segment_count += 1
        if self._segment_count >= self.segment_frames:
            self._segment_index += 1
            self._segment_count = 0

    def _retain_locked(self, frame: Dict[str, Any]) -> None:
        self._tiers[0].append(frame)
        for t in range(len(self._tiers) - 1):
            tier = self._tiers[t]
            while (len(tier) > self.tier_capacity
                   and len(tier) >= self.merge_factor):
                chunk = [tier.popleft() for _ in range(self.merge_factor)]
                self._tiers[t + 1].append(_merge_frames(chunk))
        last = self._tiers[-1]
        while len(last) > self.tier_capacity:
            last.popleft()

    def _score_locked(self, frame: Dict[str, Any]) -> None:
        prior = list(self._tiers[0])[:-1][-self.anomaly_window:]
        active: Dict[str, Dict[str, Any]] = {}
        fg = frame["gauges"]
        for key in sorted(fg):
            vals = []
            for fr in prior:
                g = fr["gauges"].get(key)
                if g is not None:
                    vals.append(g["last"])
            if len(vals) < self.min_frames:
                continue
            x = fg[key]["last"]
            z = mad_z(x, vals)
            if z > self.threshold:
                active[key] = {
                    "key": key,
                    "kind": anomaly_kind(key),
                    "round": frame["round"],
                    "value": x,
                    "median": _median(vals),
                    "z": round(z, 4),
                }
                self._anomaly_counts[key] = (
                    self._anomaly_counts.get(key, 0) + 1
                )
                self.anomalies_total += 1
                if key not in self._anomaly_first_round:
                    self._anomaly_first_round[key] = frame["round"]
        self._active = active

    # -- the planner's occupancy channel -------------------------------------

    def record_occupancy(self, lane: int, occupancy: float,
                         docs: int = 0) -> None:
        """One per-window occupancy row from the fused serving tier —
        the raw material for ``propose(history=...)``."""
        if not self.enabled:
            return
        with self._lock:
            self.occupancy_total += 1
            self._occ_rows.append({
                "row": self.occupancy_total,
                "lane": int(lane),
                "occupancy": round(float(occupancy), 6),
                "docs": int(docs),
            })

    def occupancy_rows(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [dict(r) for r in self._occ_rows]

    def occupancy_values(self) -> List[float]:
        with self._lock:
            return [float(r["occupancy"]) for r in self._occ_rows]

    # -- overhead is fed IN, never read here (PTL006 merge scope) ------------

    def note_overhead(self, seconds: float) -> None:
        """Callers measure their own sampling wall and report it — the
        plane cannot read a clock (merge-scope determinism)."""
        with self._lock:
            self.overhead_seconds += max(0.0, float(seconds))

    # -- anomaly readout -----------------------------------------------------

    def active_anomalies(self) -> List[Dict[str, Any]]:
        """Findings active as of the latest frame, sorted by key."""
        with self._lock:
            return [dict(self._active[k]) for k in sorted(self._active)]

    def anomaly_keys(self) -> List[str]:
        with self._lock:
            return sorted(self._active)

    def anomaly_first_round(self, key: str) -> Optional[int]:
        with self._lock:
            return self._anomaly_first_round.get(key)

    # -- query API -----------------------------------------------------------

    def series(self, key: str,
               window: Optional[int] = None) -> List[List[float]]:
        return series_points(self.snapshot(), key, window=window)

    def rate(self, key: str,
             window: Optional[int] = None) -> List[List[float]]:
        return series_rate(self.series(key, window=window))

    def summary(self, key: str,
                window: Optional[int] = None) -> Dict[str, Any]:
        return key_summary(self.snapshot(), key, window=window)

    def query(self, params: Optional[Dict[str, str]] = None) -> Dict[str, Any]:
        return query_snapshot(self.snapshot(), params or {})

    # -- snapshot ------------------------------------------------------------

    def segments(self) -> int:
        with self._lock:
            return self._segment_index + (1 if self._segment_count else 0)

    def frames_json(self) -> str:
        """Canonical JSON of the retained ring — the byte-identity oracle
        the replay test pins."""
        with self._lock:
            return json.dumps([list(t) for t in self._tiers], sort_keys=True)

    def snapshot(self) -> Dict[str, Any]:
        """The ``/timeseries.json`` body (and the ``history`` section of
        ``health_snapshot``)."""
        with self._lock:
            tiers = [list(t) for t in self._tiers]
            active = [dict(self._active[k]) for k in sorted(self._active)]
            counts = {k: self._anomaly_counts[k]
                      for k in sorted(self._anomaly_counts)}
            first = {k: self._anomaly_first_round[k]
                     for k in sorted(self._anomaly_first_round)}
            occ_rows = [dict(r) for r in self._occ_rows]
            segs = self._segment_index + (1 if self._segment_count else 0)
        snap: Dict[str, Any] = {
            "host": self.host,
            "enabled": self.enabled,
            "rounds": self.rounds,
            "sample_every": self.sample_every,
            "frames_sampled": self.frames_sampled,
            "frames_retained": sum(len(t) for t in tiers),
            "tier_capacity": self.tier_capacity,
            "merge_factor": self.merge_factor,
            "tier_frames": [len(t) for t in tiers],
            "tiers": tiers,
            "segments": segs,
            "segment_frames": self.segment_frames,
            "dir": str(self._dir) if self._dir is not None else None,
            "anomaly": {
                "window": self.anomaly_window,
                "min_frames": self.min_frames,
                "threshold": self.threshold,
                "total": self.anomalies_total,
                "active": active,
                "counts": counts,
                "first_round": first,
            },
            "occupancy": {
                "rows": len(occ_rows),
                "total": self.occupancy_total,
                "distribution": occupancy_distribution(
                    [r["occupancy"] for r in occ_rows]
                ),
            },
            "occupancy_rows": occ_rows,
            "overhead_seconds": round(self.overhead_seconds, 6),
        }
        snap["keys"] = snapshot_keys(snap)
        return snap


def replay_segments(dir: Any, **config: Any) -> TimeSeriesPlane:
    """Rebuild a plane from its persisted JSONL segments: every raw frame
    re-feeds through retention in file/line order, reconstructing the
    ring byte-identically (``frames_json()`` equality is the pin).  Pass
    the ORIGINAL plane's retention config for an exact rebuild."""
    plane = TimeSeriesPlane(**config).enable()
    for path in sorted(Path(dir).glob("history-*.jsonl")):
        for line in path.read_text(encoding="utf-8").splitlines():
            line = line.strip()
            if line:
                plane.ingest_raw(json.loads(line))
    return plane


#: default process-wide plane — off until ``GLOBAL_HISTORY.enable()``
#: (the GLOBAL_DEVPROF / GLOBAL_LATENCY pattern)
GLOBAL_HISTORY = TimeSeriesPlane()
