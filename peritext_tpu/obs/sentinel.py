"""Runtime recompile sentinel (the runtime half of graftlint PTL004)."""

from __future__ import annotations

import logging
import re
from typing import Dict, Optional

from .metrics import Counters, GLOBAL_COUNTERS

#: jax's log_compiles emission: "Compiling <site> with global shapes and
#: types ..." (pxla) / "Compiling <site> for ..." (older dispatch paths).
#: Matched with ``search``, anywhere in the record — handlers downstream of
#: other logging layers can receive the message PREFIXED (formatter noise,
#: "%(asctime)s ... Compiling f ...") or MULTI-LINE (a "Finished tracing +
#: transforming <site> ..." line batched ahead of the Compiling line), and
#: an anchored match silently counted zero compiles for those.  The word
#: boundary keeps "XLA compilation"/"Recompiling"-style prose from
#: false-matching.
_COMPILE_MSG_RE = re.compile(r"\bCompiling (\S+)")


class RecompileSentinel(logging.Handler):
    """Runtime guard for the compile-shape discipline (DESIGN.md "compile-
    shape discipline", graftlint PTL004): counts XLA compilations **per jit
    site** so steady-state streaming rounds can assert *zero* recompiles.

    Backed by ``jax_log_compiles``: while active, jax logs one
    ``Compiling <site> ...`` record per executable built, and this handler
    (attached to the ``"jax"`` logger) tallies it — no private APIs, no
    tracing overhead beyond the log call.  Counts land three ways:

    * :attr:`counts` — ``{site: compiles}`` on the sentinel itself;
    * ``jit.compiles.<site>`` / ``jit.compiles_total`` on the target
      :class:`Counters` (default :data:`GLOBAL_COUNTERS`), which
      :func:`~.metrics.health_snapshot` exports;
    * ``health_snapshot(sentinel=s)`` embeds the per-site dict directly.

    Use as a context manager; :meth:`mark` + :meth:`assert_steady_state`
    express the invariant tests care about::

        with RecompileSentinel() as s:
            warmup_rounds(session)
            s.mark()
            steady_rounds(session)
            s.assert_steady_state("steady-state streaming rounds")
    """

    def __init__(self, counters: Optional[Counters] = None, logger: str = "jax"):
        super().__init__(level=logging.DEBUG)
        self.counts: Dict[str, int] = {}
        self._marked: Dict[str, int] = {}
        self._counters = counters if counters is not None else GLOBAL_COUNTERS
        self._logger = logging.getLogger(logger)
        self._prev_log_compiles: Optional[bool] = None
        self._active = False

    # -- logging.Handler ------------------------------------------------------

    def emit(self, record: logging.LogRecord) -> None:
        try:
            message = record.getMessage()
        except Exception:  # graftlint: boundary(malformed foreign log records are ignored, never raised into the workload)
            return
        m = _COMPILE_MSG_RE.search(message)
        if m is None:
            return
        site = m.group(1)
        self.counts[site] = self.counts.get(site, 0) + 1
        self._counters.add(f"jit.compiles.{site}")
        self._counters.add("jit.compiles_total")

    # -- lifecycle ------------------------------------------------------------

    def start(self) -> "RecompileSentinel":
        if self._active:
            return self
        import jax

        self._prev_log_compiles = bool(jax.config.jax_log_compiles)
        jax.config.update("jax_log_compiles", True)
        self._logger.addHandler(self)
        self._active = True
        return self

    def stop(self) -> None:
        if not self._active:
            return
        self._logger.removeHandler(self)
        try:
            import jax

            jax.config.update("jax_log_compiles", self._prev_log_compiles)
        except Exception:  # graftlint: boundary(best-effort config restore on teardown; the counts already collected stay valid)
            pass
        self._active = False

    def __enter__(self) -> "RecompileSentinel":
        return self.start()

    def __exit__(self, *exc_info) -> None:
        self.stop()

    # -- assertions -----------------------------------------------------------

    @property
    def total(self) -> int:
        return sum(self.counts.values())

    def mark(self) -> None:
        """Snapshot the current counts; :meth:`since_mark` and
        :meth:`assert_steady_state` measure growth from here."""
        self._marked = dict(self.counts)

    def since_mark(self) -> Dict[str, int]:
        """Per-site compiles since :meth:`mark` (empty dict = steady state)."""
        return {
            site: n - self._marked.get(site, 0)
            for site, n in sorted(self.counts.items())
            if n > self._marked.get(site, 0)
        }

    def assert_steady_state(self, what: str = "steady-state rounds") -> None:
        fresh = self.since_mark()
        if fresh:
            raise AssertionError(
                f"{what} triggered {sum(fresh.values())} recompile(s): {fresh} "
                "— a per-round shape escaped the padded-shape tables "
                "(see DESIGN.md compile-shape discipline / graftlint PTL004)"
            )
