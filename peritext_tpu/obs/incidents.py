"""Fleet incident plane: typed, round-counted incidents correlated across
every existing observability surface.

The repo's planes each answer one narrow question — the heartbeat ledger
says which host is dead, the convergence monitor says which peer diverged,
the admission queue says what it shed, the latency plane says how much SLO
budget burned, the recompile sentinel says what compiled, the supervisor
says what it rolled back, the perf ledger says what regressed, and the
history plane says which gauge drifted from its own past.  An operator
staring at a sick fleet needs the *correlated* answer: what broke, where,
and what was the first cause.  :class:`IncidentMonitor` is that answer as a
deterministic fold over the planes' own snapshots.

Design rules, inherited from the planes it watches:

* **Deterministic and round-counted.**  Incident state advances only on
  :meth:`IncidentMonitor.advance_round`; nothing in here reads a wall clock
  or RNG.  Two monitors fed the same observations in the same round order
  hold byte-identical incident sets (``incidents_json``) and equal
  ``digest()`` values — the groundwork for the ROADMAP's multi-frontend
  death-verdict gossip, where independent frontends must AGREE on the
  incident view before acting on it.
* **Typed taxonomy.**  Every signal is one of :data:`TAXONOMY`; free-text
  incident kinds would rot into unmatchable strings the way untyped shed
  reasons would have.
* **Two-watermark lifecycle.**  Open → ack → resolve with hysteresis: a
  signal must hold for ``open_after`` consecutive rounds to open an
  incident (the admission controller's high watermark), and an open
  incident resolves only after ``clear_after`` consecutive quiet rounds
  (the low watermark).  A flapping signal therefore re-arms ONE incident
  instead of minting an open/resolve pair per flap — exactly why admission
  backpressure latches between two watermarks instead of toggling at one.
* **Causal correlation.**  Signals sharing a host, doc, or trace id within
  ``correlation_window`` rounds collapse into ONE incident; its root-cause
  candidates are ordered by the same largest-delta / earliest-taxonomy
  tie-break :func:`~.latency.attribute` uses, so ``obs incidents`` and
  ``obs why`` name first causes by one rule.

Cross-host: an incident OPEN fires the attached
:class:`~.recorder.FlightRecorder` (one black-box dump per incident, not
per signal), and :func:`merge_flight_dumps` merges the per-host dump files
— host-attributed by filename since dumps gained the
``flight-<host>-<pid>-<n>-<reason>.jsonl`` spelling — into a single fleet
timeline keyed by trace id.  A compact incident summary also rides the
replication frontier as the ``"\\x00incidents"`` NUL sentinel
(:meth:`IncidentMonitor.wire_summary`): an int, so old peers'
``{actor: seq}`` frontier validation accepts-and-ignores it like every
other sentinel, while new peers record whether their peer's incident view
agrees with their own.

Off by default: nothing arms a monitor implicitly, arming one compiles
nothing (pure-Python bookkeeping), and feeding it costs a few dict walks
per round.
"""

from __future__ import annotations

import json
import re
import zlib
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Dict, Iterable, List, Optional, Tuple

#: The typed incident taxonomy, in FIXED order — the order IS the
#: root-cause tie-break (earlier entries win ties, mirroring the stage
#: order in :data:`~.latency.STAGES`): infrastructure death first, state
#: safety next, control-plane storms, then soft (SLO / perf) degradation.
TAXONOMY = (
    "host-death",
    "divergence",
    "quarantine-storm",
    "shed-storm",
    "slo-burn",
    "recompile-storm",
    "migration-failure",
    "perf-regression",
)

_TAXONOMY_INDEX = {kind: i for i, kind in enumerate(TAXONOMY)}

#: incident lifecycle states (open → ack → resolved; ack is operator-local
#: and excluded from the cross-host digest)
STATUSES = ("open", "ack", "resolved")


def _avalanche(x: int) -> int:
    """The anti-entropy digest's avalanche finisher — reused so incident
    digests and store digests share one mixing idiom."""
    x = (x * 2246822519) & 0xFFFFFFFF
    return x ^ (x >> 15)


def _snap(obj) -> Dict[str, Any]:
    """Feed-normalization: every ``observe_*`` accepts the live plane
    object or its already-scraped ``snapshot()`` dict, so the CLI can feed
    a monitor from files exactly as a process feeds it live objects."""
    if isinstance(obj, dict):
        return obj
    snap = getattr(obj, "snapshot", None)
    if callable(snap):
        return snap()
    raise TypeError(f"expected a dict or an object with snapshot(), got {type(obj).__name__}")


@dataclass
class _Candidate:
    """One signal source attached to an incident: the per-(kind, host, doc)
    accumulation the root-cause ordering ranks."""

    kind: str
    host: str
    doc: Optional[str] = None
    trace: Optional[int] = None
    value: float = 0.0          # max magnitude seen (the ordering delta)
    first_round: int = 0
    last_round: int = 0
    count: int = 0
    detail: Dict[str, Any] = field(default_factory=dict)

    def to_json(self) -> Dict[str, Any]:
        return {
            "kind": self.kind,
            "host": self.host,
            "doc": self.doc,
            "trace": self.trace,
            "value": round(float(self.value), 6),
            "first_round": self.first_round,
            "last_round": self.last_round,
            "count": self.count,
            "detail": dict(sorted(self.detail.items())),
        }


class Incident:
    """One correlated incident: a set of signal sources sharing a
    (host, doc, trace) scope, with a two-watermark lifecycle."""

    def __init__(self, ident: str, opened_round: int) -> None:
        self.id = ident
        self.status = "open"
        self.opened_round = opened_round
        self.acked_round: Optional[int] = None
        self.resolved_round: Optional[int] = None
        self.last_signal_round = opened_round
        self.quiet = 0
        self.signals = 0
        self.dumped = False
        self._candidates: Dict[Tuple[str, str, Optional[str]], _Candidate] = {}

    # -- scope ---------------------------------------------------------------

    @property
    def hosts(self) -> List[str]:
        return sorted({c.host for c in self._candidates.values()})

    @property
    def docs(self) -> List[str]:
        return sorted({c.doc for c in self._candidates.values()
                       if c.doc is not None})

    @property
    def traces(self) -> List[int]:
        return sorted({c.trace for c in self._candidates.values()
                       if c.trace is not None})

    def keys(self) -> Iterable[Tuple[str, str, Optional[str]]]:
        return self._candidates.keys()

    # -- candidates ----------------------------------------------------------

    def attach(self, kind: str, host: str, doc: Optional[str],
               trace: Optional[int], value: float,
               detail: Dict[str, Any], rounds: int) -> None:
        key = (kind, host, doc)
        cand = self._candidates.get(key)
        if cand is None:
            cand = _Candidate(kind=kind, host=host, doc=doc, trace=trace,
                              first_round=rounds)
            self._candidates[key] = cand
        cand.value = max(cand.value, float(value))
        cand.last_round = rounds
        cand.count += 1
        if trace is not None:
            cand.trace = trace
        if detail:
            cand.detail.update(detail)
        self.signals += 1
        self.last_signal_round = rounds

    def candidates(self) -> List[_Candidate]:
        """Root-cause ordering: the same deterministic rule
        :func:`~.latency.attribute` uses — largest delta wins, ties break
        to the EARLIEST taxonomy entry (strict ``>`` while walking taxonomy
        order keeps the first)."""
        ordered = sorted(
            self._candidates.values(),
            key=lambda c: (_TAXONOMY_INDEX[c.kind], c.host, c.doc or ""),
        )
        best: Optional[_Candidate] = None
        best_val = 0.0
        for cand in ordered:
            if best is None or cand.value > best_val:
                best, best_val = cand, cand.value
        rest = [c for c in ordered if c is not best]
        rest.sort(key=lambda c: (-c.value, _TAXONOMY_INDEX[c.kind],
                                 c.host, c.doc or ""))
        return ([best] if best is not None else []) + rest

    @property
    def kind(self) -> str:
        """The incident's primary classification: its root cause's kind."""
        cands = self.candidates()
        return cands[0].kind if cands else "unknown"

    # -- lifecycle -----------------------------------------------------------

    @property
    def resolved(self) -> bool:
        return self.status == "resolved"

    def ack(self, rounds: int) -> bool:
        if self.status != "open":
            return False
        self.status = "ack"
        self.acked_round = rounds
        return True

    def resolve(self, rounds: int) -> None:
        self.status = "resolved"
        self.resolved_round = rounds

    # -- readout -------------------------------------------------------------

    def to_json(self) -> Dict[str, Any]:
        return {
            "id": self.id,
            "kind": self.kind,
            "status": self.status,
            "hosts": self.hosts,
            "docs": self.docs,
            "traces": self.traces,
            "opened_round": self.opened_round,
            "acked_round": self.acked_round,
            "resolved_round": self.resolved_round,
            "last_signal_round": self.last_signal_round,
            "signals": self.signals,
            "candidates": [c.to_json() for c in self.candidates()],
        }


class IncidentMonitor:
    """Deterministic incident fold over the existing planes' snapshots.

    Feed it each monitoring round — any subset of ``observe_*`` calls, then
    ONE :meth:`advance_round` — and read incidents back through
    :meth:`snapshot` (the ``/incidents.json`` body), :meth:`open_incidents`
    or :meth:`incidents_json`.  All thresholds are per-monitor constructor
    state, so two monitors configured alike and fed alike agree exactly.

    ``open_after`` / ``clear_after`` are the two watermarks: consecutive
    active rounds to open, consecutive quiet rounds to resolve.
    ``correlation_window`` bounds how stale an open incident's last signal
    may be while still absorbing a new correlated signal.  ``recorder``
    (optional) gets ONE :meth:`~.recorder.FlightRecorder.fault` per
    incident open — the black-box dump for the post-mortem.
    """

    def __init__(
        self,
        host: str = "local",
        open_after: int = 1,
        clear_after: int = 2,
        correlation_window: int = 4,
        burn_threshold: float = 1.0,
        compile_storm_threshold: int = 3,
        recorder=None,
        counters=None,
    ) -> None:
        if open_after < 1:
            raise ValueError(f"open_after must be >= 1, got {open_after}")
        if clear_after < 1:
            raise ValueError(f"clear_after must be >= 1, got {clear_after}")
        self.host = host
        self.open_after = int(open_after)
        self.clear_after = int(clear_after)
        self.correlation_window = int(correlation_window)
        self.burn_threshold = float(burn_threshold)
        self.compile_storm_threshold = int(compile_storm_threshold)
        self.recorder = recorder
        self.counters = counters
        self.rounds = 0
        self._seq = 0
        self._incidents: List[Incident] = []
        #: (kind, host, doc) -> signals raised THIS round, folded at
        #: advance_round; value/detail keep the largest magnitude seen
        self._raised: Dict[Tuple[str, str, Optional[str]], Dict[str, Any]] = {}
        #: consecutive-active-round streaks per signal key (high watermark)
        self._streaks: Dict[Tuple[str, str, Optional[str]], int] = {}
        #: per-feed cumulative marks for delta detection (rollbacks,
        #: divergence incidents, compiles, migration rollbacks)
        self._marks: Dict[str, int] = {}
        #: hosts whose dead verdict already produced its edge signal — a
        #: latched-dead lease must not re-open a resolved incident forever
        self._dead_seen: set = set()
        #: peer -> parsed wire summary from the frontier sentinel
        self.peer_views: Dict[str, Dict[str, int]] = {}

    # -- raw signal ingestion ------------------------------------------------

    def raise_signal(self, kind: str, host: Optional[str] = None,
                     doc: Optional[str] = None, trace: Optional[int] = None,
                     value: float = 1.0, **detail: Any) -> None:
        """Raise one typed signal for the CURRENT round.  ``value`` is the
        signal's magnitude — the delta the root-cause ordering ranks.
        Re-raising a (kind, host, doc) key within a round keeps the larger
        magnitude; the round's verdicts land at :meth:`advance_round`."""
        if kind not in _TAXONOMY_INDEX:
            raise ValueError(f"unknown incident kind {kind!r}; "
                             f"taxonomy: {', '.join(TAXONOMY)}")
        key = (kind, host or self.host, doc)
        prev = self._raised.get(key)
        if prev is None or float(value) > prev["value"]:
            self._raised[key] = {"value": float(value), "trace": trace,
                                 "detail": dict(detail)}
        elif detail:
            prev["detail"].update(detail)

    # -- typed feeds ---------------------------------------------------------

    def observe_leases(self, ledger) -> None:
        """HeartbeatLedger feed: a ``dead`` verdict is a host-death signal.
        The ledger latches dead, so the signal persists until the host is
        reset (re-admitted) — resolution IS re-admission here."""
        snap = _snap(ledger)
        for name, lease in sorted(snap.get("leases", {}).items()):
            if lease.get("verdict") == "dead":
                self.raise_signal(
                    "host-death", host=name,
                    value=float(lease.get("missed", 1)),
                    dead_at_round=lease.get("dead_at_round"),
                )

    def observe_fleet(self, fleet) -> None:
        """FleetFrontend feed: host-death on the dead-verdict EDGE (and for
        as long as the dead host still owns serving docs or docs sit
        failed), so the incident resolves once failover re-homes everything
        — post-heal, not post-reset; plus migration-failure on
        migration-rollback deltas or failed docs."""
        snap = _snap(fleet)
        leases = snap.get("leases", {}).get("leases", {})
        serving = snap.get("serving", {})
        failed = list(snap.get("failed_docs", ()))
        stranded: Dict[str, int] = {}
        for _doc, owner in serving.items():
            stranded[owner] = stranded.get(owner, 0) + 1
        for name, lease in sorted(leases.items()):
            if lease.get("verdict") != "dead":
                self._dead_seen.discard(name)
                continue
            owned = stranded.get(name, 0)
            if name not in self._dead_seen:
                self._dead_seen.add(name)
            elif owned == 0 and not failed:
                continue  # healed: docs re-homed, nothing failed
            self.raise_signal(
                "host-death", host=name,
                value=float(max(owned, 1)),
                stranded_docs=owned,
                dead_at_round=lease.get("dead_at_round"),
            )
        rollbacks = int(snap.get("migration_rollbacks", 0))
        delta = rollbacks - self._marks.get("migration_rollbacks", 0)
        self._marks["migration_rollbacks"] = rollbacks
        if delta > 0 or failed:
            self.raise_signal(
                "migration-failure", host=self.host,
                value=float(delta + len(failed)),
                rollbacks=delta, failed_docs=failed,
            )

    def observe_convergence(self, monitor) -> None:
        """ConvergenceMonitor feed: NEW divergence incidents (count delta)
        raise a divergence signal per divergent peer.  Delta-triggered, so
        a healed replica that stops probing divergent lets the incident
        resolve even though the convergence monitor's per-peer divergent
        flag stays latched — the latch is its evidence, not ours."""
        snap = _snap(monitor)
        total = int(snap.get("divergence_incidents", 0))
        delta = total - self._marks.get("divergence_incidents", 0)
        self._marks["divergence_incidents"] = total
        if delta <= 0:
            return
        peers = snap.get("divergent_peers") or [self.host]
        for peer in sorted(peers):
            self.raise_signal("divergence", host=peer, value=float(delta),
                              divergence_incidents=total)

    def observe_serve(self, mux) -> None:
        """SessionMux feed: engaged backpressure or sheds since the last
        clean flush raise a shed-storm signal.  ``recent_sheds`` clears on
        the mux's next committed clean round, so redelivery completing IS
        the heal."""
        snap = _snap(mux)
        sheds = int(snap.get("recent_sheds", 0))
        overloaded = bool(snap.get("overloaded", False))
        if sheds > 0 or overloaded:
            self.raise_signal(
                "shed-storm", host=str(snap.get("host", self.host)),
                value=float(max(sheds, 1)),
                recent_sheds=sheds, overloaded=overloaded,
            )

    def observe_latency(self, plane) -> None:
        """LatencyPlane feed: an SLO burn rate above ``burn_threshold``
        (default 1.0 — burning budget faster than it accrues) is an
        slo-burn signal whose magnitude is the burn rate itself."""
        snap = _snap(plane)
        slo = snap.get("slo", {}) or {}
        burn = float(slo.get("burn_rate", 0.0) or 0.0)
        if burn > self.burn_threshold:
            self.raise_signal("slo-burn", host=self.host, value=burn,
                              burn_rate=burn, breaches=slo.get("breaches"))

    def observe_sentinel(self, sentinel) -> None:
        """RecompileSentinel feed: ``compile_storm_threshold`` or more new
        compiles since the previous observation is a recompile-storm — a
        steady-state serving loop should compile NOTHING per round."""
        if isinstance(sentinel, dict):
            total = int(sentinel.get("total", 0))
        else:
            total = int(getattr(sentinel, "total", 0))
        delta = total - self._marks.get("compiles", 0)
        self._marks["compiles"] = total
        if delta >= self.compile_storm_threshold:
            self.raise_signal("recompile-storm", host=self.host,
                              value=float(delta), new_compiles=delta)

    def observe_supervisor(self, supervisor) -> None:
        """GuardedSession / session ``health()`` feed: NEW rollbacks or
        NEWLY quarantined docs (both count deltas) raise quarantine-storm.
        Delta-triggered on purpose: the quarantine registry latches — a
        recovered session keeps benign demotion records as evidence — so
        absolute presence would hold the incident open forever; the latch
        is the session's evidence, not ours, and quiet rounds after the
        last new rollback/quarantine ARE the heal."""
        if isinstance(supervisor, dict):
            health = supervisor
        else:
            fn = getattr(supervisor, "health", None)
            if not callable(fn):
                raise TypeError("observe_supervisor wants a health() object or dict")
            health = fn()
        rollbacks = int(health.get("rollbacks", 0))
        delta = rollbacks - self._marks.get("rollbacks", 0)
        self._marks["rollbacks"] = rollbacks
        quarantined = health.get("quarantined") or {}
        qdelta = len(quarantined) - self._marks.get("quarantined", 0)
        self._marks["quarantined"] = len(quarantined)
        if delta > 0 or qdelta > 0:
            self.raise_signal(
                "quarantine-storm", host=self.host,
                value=float(max(delta, 0) + max(qdelta, 0)),
                rollbacks=delta,
                quarantined_docs=sorted(str(d) for d in quarantined),
            )

    def observe_perf(self, report) -> None:
        """Perf-ledger ``evaluate()`` feed: a regressed gate raises a
        perf-regression signal whose magnitude is the worst regression's
        percentage delta — the same figure ``obs perf`` prints."""
        rep = dict(report)
        if not rep.get("regressed"):
            return
        worst = 0.0
        names: List[str] = []
        for row in rep.get("rows", ()):
            if row.get("status") in ("regressed", "failed", "missing"):
                names.append(str(row.get("name")))
                pct = row.get("delta_pct")
                if pct is not None:
                    worst = max(worst, abs(float(pct)))
        self.raise_signal("perf-regression", host=self.host,
                          value=worst or 1.0, rows=sorted(names))

    def observe_timeseries(self, plane) -> None:
        """TimeSeriesPlane feed (the ninth signal source): every anomaly
        active as of the plane's latest frame raises a signal on the
        EXISTING kind its gauge-key prefix maps to (``anomaly_kind`` —
        ``serve.*`` -> shed-storm, ``fleet.*`` -> host-death, ...), never
        a new latch.  The signal's magnitude is the robust z-score, so a
        correlated incident's root-cause ordering ranks the anomaly
        against the primary plane's own evidence."""
        snap = _snap(plane)
        anomaly = snap.get("anomaly") or {}
        for finding in anomaly.get("active") or ():
            kind = str(finding.get("kind") or "perf-regression")
            if kind not in _TAXONOMY_INDEX:
                kind = "perf-regression"
            self.raise_signal(
                kind, host=str(snap.get("host", self.host)),
                value=float(finding.get("z", 1.0) or 1.0),
                anomaly=True, anomaly_key=str(finding.get("key")),
                anomaly_round=int(finding.get("round", 0) or 0),
            )

    # -- lifecycle fold ------------------------------------------------------

    def advance_round(self) -> List[Incident]:
        """Fold the round's raised signals into incident state: bump
        streaks, open / correlate at the high watermark, resolve at the low
        one.  Returns incidents OPENED this round (the dump trigger)."""
        self.rounds += 1
        raised, self._raised = self._raised, {}
        for key in list(self._streaks):
            if key not in raised:
                del self._streaks[key]
        opened: List[Incident] = []
        for key in sorted(
            raised,
            key=lambda k: (_TAXONOMY_INDEX[k[0]], k[1], k[2] or ""),
        ):
            self._streaks[key] = self._streaks.get(key, 0) + 1
            if self._streaks[key] < self.open_after:
                continue
            kind, host, doc = key
            sig = raised[key]
            inc = self._correlate(host, doc, sig["trace"], key)
            if inc is None:
                self._seq += 1
                inc = Incident(f"INC-{self._seq:04d}", self.rounds)
                self._incidents.append(inc)
                opened.append(inc)
            inc.attach(kind, host, doc, sig["trace"], sig["value"],
                       sig["detail"], self.rounds)
        # the low watermark counts ANY re-fire of an incident's keys as
        # activity — even sub-threshold flaps — so a flapping signal
        # re-arms the open incident instead of letting it resolve and then
        # minting a fresh one (the latch between the two watermarks)
        active_keys = set(raised)
        for inc in self._incidents:
            if inc.resolved:
                continue
            if any(k in active_keys for k in inc.keys()):
                inc.quiet = 0
            else:
                inc.quiet += 1
                if inc.quiet >= self.clear_after:
                    inc.resolve(self.rounds)
        if self.counters is not None:
            for inc in opened:
                self.counters.add("incident.opened")
        for inc in opened:
            self._dump(inc)
        return opened

    def _correlate(self, host: str, doc: Optional[str],
                   trace: Optional[int], key) -> Optional[Incident]:
        """The collapse rule: the EARLIEST-opened unresolved incident whose
        last signal is within the correlation window and which shares the
        signal's host, doc, trace, or exact key."""
        for inc in self._incidents:
            if inc.resolved:
                continue
            if self.rounds - inc.last_signal_round > self.correlation_window:
                continue
            if (key in inc.keys()
                    or host in inc.hosts
                    or (doc is not None and doc in inc.docs)
                    or (trace is not None and trace in inc.traces)):
                return inc
        return None

    def _dump(self, inc: Incident) -> None:
        if self.recorder is None or inc.dumped:
            return
        inc.dumped = True
        try:
            self.recorder.fault(
                f"incident-{inc.kind}", incident=inc.id,
                hosts=",".join(inc.hosts), opened_round=inc.opened_round,
            )
        except Exception:  # graftlint: boundary(a failed black-box dump must not lose the incident that triggered it)
            pass

    def ack(self, ident: str) -> bool:
        """Operator acknowledgement: open → ack.  Local-only state — the
        cross-host digest folds ack back into open so two frontends with
        different operators still agree on the incident view."""
        for inc in self._incidents:
            if inc.id == ident:
                return inc.ack(self.rounds)
        return False

    # -- readout -------------------------------------------------------------

    def incidents(self) -> List[Incident]:
        return list(self._incidents)

    def open_incidents(self) -> List[Incident]:
        return [inc for inc in self._incidents if not inc.resolved]

    def incident_kinds(self) -> List[str]:
        """The DISTINCT primary kinds ever opened — the chaos oracles'
        exact-set assertion surface."""
        return sorted({inc.kind for inc in self._incidents})

    def time_to_detection(self, kind: str,
                          fault_round: int) -> Optional[int]:
        """Monitor rounds from ``fault_round`` to the first open of an
        incident whose primary kind is ``kind`` (None if never opened)."""
        for inc in self._incidents:
            if inc.kind == kind and inc.opened_round >= fault_round:
                return inc.opened_round - fault_round
        return None

    def incidents_json(self) -> str:
        """Canonical JSON of the full incident list — the two-monitor
        determinism contract compares THESE bytes."""
        return json.dumps([inc.to_json() for inc in self._incidents],
                          sort_keys=True, separators=(",", ":"))

    def digest(self) -> int:
        """Order-sensitive 32-bit digest of the observation-derived
        incident view.  Ack state is normalized back to open (operator
        acks are local), so two frontends fed the same observations match
        even when only one operator acked."""
        rows = []
        for inc in self._incidents:
            row = inc.to_json()
            row.pop("acked_round", None)
            if row["status"] == "ack":
                row["status"] = "open"
            rows.append(row)
        blob = json.dumps(rows, sort_keys=True, separators=(",", ":"))
        return _avalanche(zlib.crc32(blob.encode("utf-8")) & 0xFFFFFFFF)

    def wire_summary(self) -> int:
        """The frontier-sentinel payload: ``(open_count << 32) | digest``,
        one int so old peers' ``{actor: seq}`` validation accepts it."""
        return (len(self.open_incidents()) << 32) | self.digest()

    @staticmethod
    def parse_wire_summary(value: int) -> Dict[str, int]:
        return {"open": int(value) >> 32, "digest": int(value) & 0xFFFFFFFF}

    def observe_peer_summary(self, peer: str, value: int) -> None:
        """Record a peer's frontier-carried incident summary; ``snapshot``
        reports per-peer agreement (same digest = same incident view)."""
        self.peer_views[str(peer)] = self.parse_wire_summary(value)

    def snapshot(self) -> Dict[str, Any]:
        """The ``/incidents.json`` body (golden-shape test pins these
        keys): lifecycle tallies, per-kind open counts over the FULL
        taxonomy, the incident list, and the cross-host agreement view."""
        open_incs = self.open_incidents()
        by_kind = {kind: 0 for kind in TAXONOMY}
        for inc in open_incs:
            by_kind[inc.kind] += 1
        digest = self.digest()
        return {
            "host": self.host,
            "rounds": self.rounds,
            "open": len(open_incs),
            "acked": sum(1 for i in open_incs if i.status == "ack"),
            "resolved": sum(1 for i in self._incidents if i.resolved),
            "total": len(self._incidents),
            "by_kind": by_kind,
            "digest": digest,
            "open_after": self.open_after,
            "clear_after": self.clear_after,
            "correlation_window": self.correlation_window,
            "peers": {
                peer: {**view, "agree": view["digest"] == digest}
                for peer, view in sorted(self.peer_views.items())
            },
            "incidents": [inc.to_json() for inc in self._incidents],
        }


# -- merged black-box timeline ------------------------------------------------

#: ``flight-<host>-<pid>-<n>-<reason>.jsonl`` (current) — the pid/counter
#: pair is numeric, which is how the parser tells the host-bearing spelling
#: from the legacy ``flight-<pid>-<n>-<reason>`` one
_DUMP_NAME = re.compile(
    r"^flight-(?:(?P<host>.+?)-)?(?P<pid>\d+)-(?P<n>\d+)-(?P<reason>.+)\.jsonl$"
)


def _dump_host(name: str) -> Optional[str]:
    m = _DUMP_NAME.match(name)
    return m.group("host") if m else None


def merge_flight_dumps(paths: Iterable[str | Path]) -> Dict[str, Any]:
    """Merge per-host flight-recorder dump files into ONE fleet timeline.

    Each record is host-attributed from its dump's filename (the
    ``flight-<host>-...`` spelling; legacy host-less dumps attribute as
    ``"?"``), the merged timeline is ordered by ``(ts, host, seq)``, and
    records carrying a trace id are additionally grouped per trace — the
    cross-host causal chains the wire's trace-context sentinels stitched.
    Successive dumps from one recorder overlap (each carries the whole
    ring), so records are deduplicated by ``(host, pid, seq)`` — the seq
    counter is per-recorder-monotonic, making the triple a stable record
    identity across dumps.  Unreadable files and unparsable lines are
    counted, not fatal: a post-mortem merges what survived the crash.
    """
    timeline: List[Dict[str, Any]] = []
    dumps: List[Dict[str, Any]] = []
    seen: set = set()
    skipped = 0
    for path in sorted(Path(p) for p in paths):
        host = _dump_host(path.name) or "?"
        m = _DUMP_NAME.match(path.name)
        pid = m.group("pid") if m else path.name
        try:
            lines = path.read_text().splitlines()
        except OSError:
            skipped += 1
            continue
        header: Dict[str, Any] = {}
        for line in lines:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                skipped += 1
                continue
            if not isinstance(rec, dict):
                skipped += 1
                continue
            if rec.get("kind") == "dump" and not header:
                header = rec
                dumps.append({"file": path.name, "host": host,
                              "reason": rec.get("reason"),
                              "records": rec.get("records")})
                continue
            seq = rec.get("seq")
            if seq is not None:
                key = (host, pid, int(seq))
                if key in seen:
                    continue
                seen.add(key)
            timeline.append({"host": host, "file": path.name, **rec})
    timeline.sort(key=lambda r: (float(r.get("ts", 0.0) or 0.0),
                                 r.get("host", ""),
                                 int(r.get("seq", 0) or 0)))
    traces: Dict[str, List[Dict[str, Any]]] = {}
    for rec in timeline:
        trace = rec.get("trace_id")
        if trace is None:
            continue
        traces.setdefault(str(trace), []).append(rec)
    return {
        "hosts": sorted({r["host"] for r in timeline} | {d["host"] for d in dumps}),
        "dumps": dumps,
        "records": len(timeline),
        "skipped": skipped,
        "timeline": timeline,
        "traces": traces,
    }
