"""Process-local counters and the composed fleet health snapshot."""

from __future__ import annotations

import contextlib
import threading
import time
from collections import defaultdict
from typing import Any, Dict, Iterator, Optional


class Counters:
    """Thread-safe named counters and accumulated timings."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counts: Dict[str, float] = defaultdict(float)

    def add(self, name: str, value: float = 1.0) -> None:
        with self._lock:
            self._counts[name] += value

    def get(self, name: str) -> float:
        with self._lock:
            return self._counts.get(name, 0.0)

    @contextlib.contextmanager
    def timed(self, name: str) -> Iterator[None]:
        start = time.perf_counter()
        try:
            yield
        finally:
            self.add(name, time.perf_counter() - start)

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._counts)

    def reset(self) -> None:
        with self._lock:
            self._counts.clear()


#: Default process-wide counters.
GLOBAL_COUNTERS = Counters()


#: counter/histogram namespaces that make up the fault-domain health surface
_HEALTH_PREFIXES = ("streaming.", "transport.", "supervisor.", "merge.",
                    "jit.", "convergence.", "serve.", "fleet.", "plan.",
                    "incident.")


def health_snapshot(
    counters: Optional[Counters] = None,
    session=None,
    sentinel=None,
    histograms=None,
    recorder=None,
    convergence=None,
    devprof=None,
    serve=None,
    fleet=None,
    plan=None,
    mesh=None,
    latency=None,
    incidents=None,
    history=None,
) -> Dict[str, Any]:
    """One structured dict for a fleet health endpoint: every fault-domain
    counter (quarantines, corrupt frames, transport retries / behind peers,
    supervisor rollbacks, guarded-merge fallbacks, per-jit-site compile
    counts, convergence exchange/divergence tallies) and the fault-domain
    latency/size histogram percentiles, plus —
    when a streaming session or its
    :class:`~..parallel.supervisor.GuardedSession` is given — that session's
    own ``health()`` (quarantine registry with typed reasons,
    fallback/pending counts, rollback evidence, deadline-autotune state,
    padding efficiency).  With a :class:`~.sentinel.RecompileSentinel`
    attached, its per-site compile counts appear under ``recompiles`` (the
    counter form lands under ``counters`` as ``jit.compiles.*`` either
    way); with a :class:`~.recorder.FlightRecorder`, its ring/dump summary
    appears under ``flight_recorder``; with a
    :class:`~.convergence.ConvergenceMonitor`, its per-peer lag watermarks
    and divergence tallies appear under ``convergence``; with a
    :class:`~.devprof.DeviceProfiler`, its shape-bucket / occupancy /
    memory-watermark snapshot appears under ``devprof``; with a
    :class:`~..serve.SessionMux` (or anything exposing the same
    ``snapshot()``), its session/queue/verdict/window state appears under
    ``serve``; with a planner verdict (a
    :class:`~..plan.tuner.PlanProposal`, anything with ``to_json()``, or
    a plain dict), the proposal/current/modeled body appears under
    ``plan`` — the device-as-OS planner's advice rides the SAME health
    surface the rest of the fleet scrapes; with a mesh-shard stats dict
    (a sharded session's ``_mesh_stats()`` / sharded store's
    ``shard_stats()``), the per-shard load/utilization and ICI page-move
    tallies appear under ``mesh``; with a
    :class:`~.latency.LatencyPlane`, its stage-watermark decomposition
    (per-stage histograms, SLO burn rate, close causes) appears under
    ``latency``; with an
    :class:`~.incidents.IncidentMonitor`, its correlated incident view
    (typed incident list, lifecycle tallies, per-peer agreement) appears
    under ``incidents``; with a
    :class:`~.timeseries.TimeSeriesPlane`, its retention-tier frames,
    anomaly findings, and recorded occupancy rows appear under
    ``history``.  Everything in the snapshot is
    JSON-serializable (the exporter-schema golden test pins this)."""
    from .histograms import GLOBAL_HISTOGRAMS

    counters = counters or GLOBAL_COUNTERS
    histograms = histograms if histograms is not None else GLOBAL_HISTOGRAMS
    out: Dict[str, Any] = {
        "counters": {
            k: v
            for k, v in sorted(counters.snapshot().items())
            if k.startswith(_HEALTH_PREFIXES)
        },
        "histograms": {
            name: snap
            for name, snap in sorted(histograms.snapshot().items())
            if name.startswith(_HEALTH_PREFIXES)
        },
    }
    if session is not None:
        out["session"] = session.health()
    if sentinel is not None:
        out["recompiles"] = {
            "sites": dict(sorted(sentinel.counts.items())),
            "total": sentinel.total,
        }
    if recorder is not None:
        out["flight_recorder"] = recorder.snapshot()
    if convergence is not None:
        out["convergence"] = convergence.snapshot()
    if devprof is not None:
        out["devprof"] = devprof.snapshot()
    if serve is not None:
        out["serve"] = serve.snapshot()
    if fleet is not None:
        out["fleet"] = fleet.snapshot()
    if plan is not None:
        out["plan"] = (
            plan.to_json() if hasattr(plan, "to_json") else dict(plan)
        )
    if mesh is not None:
        out["mesh"] = dict(mesh)
    if latency is not None:
        out["latency"] = latency.snapshot()
    if incidents is not None:
        out["incidents"] = incidents.snapshot()
    if history is not None:
        out["history"] = (
            history.snapshot() if hasattr(history, "snapshot")
            else dict(history)
        )
    return out
