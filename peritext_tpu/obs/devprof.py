"""Device-cost observability: what the compiled merge executables cost.

Every telemetry layer so far (spans, histograms, flight recorder,
convergence monitor) watches the HOST side of the pipeline.  This module is
the device-facing counterpart — :class:`DeviceProfiler` captures, per jit
site and shape bucket:

* **XLA cost/memory introspection** — ``cost_analysis()`` (FLOPs, bytes
  accessed) and ``memory_analysis()`` (argument/output/temp device memory)
  of the actual compiled executables, via the AOT ``lower().compile()``
  path.  Capture is memoized per (site, shape bucket) and gated behind
  ``capture_costs`` because each capture builds one extra executable — a
  warmup-time act, never a steady-state one.  (AOT compiles do NOT emit
  jax's ``Compiling <site>`` log record, so cost capture never perturbs the
  :class:`~.sentinel.RecompileSentinel` counts the bucket table is
  cross-checked against.)
* **Bucket occupancy** — per padded-shape bucket: rounds dispatched, real
  ops vs padded op-stream capacity, and the padding waste ratio.  This
  generalizes the single scalar ``MergeStats.padding_efficiency`` into the
  per-bucket table Ragged Paged Attention treats as the first-class TPU
  ragged-batching signal: a mis-sized round width shows up as one bucket
  with high waste, not as a diluted session average.
* **Device-memory watermarks** — ``Device.memory_stats()`` samples taken at
  round boundaries (streaming commit, guarded supervisor round, batch
  merge).  CPU backends return no stats; the snapshot then reports
  ``available: false`` instead of zeros.
* **Shape-bucket keys** — :meth:`~DeviceProfiler.shape_signature` derives a
  stable key from the dispatch's actual argument shapes/dtypes plus its
  static arguments, i.e. exactly the granularity of jax's compile cache.
  The per-site distinct-shape count therefore equals the sentinel's
  per-site compile count on a fresh-session replay — the cross-check
  tests/test_devprof.py pins.

Profiling is OFF by default (``GLOBAL_DEVPROF.enabled`` is False) and every
hook in the merge stack is behind that one attribute check, so the disabled
cost is a single branch per dispatch.  All host syncs here (AOT compiles,
``memory_stats`` reads) live in ``obs/`` — outside graftlint's merge scope
and outside every jit boundary — which is the scoping that keeps the repo
self-scan clean (DESIGN.md "Device cost & perf ledger").
"""

from __future__ import annotations

import hashlib
import threading
from typing import Any, Callable, Dict, Optional, Tuple


def _describe(obj: Any, out: list) -> None:
    """Flatten a dispatch-argument pytree into a deterministic textual
    descriptor: arrays become ``dtype[shape]``, containers recurse in sorted
    key order, ``None`` is preserved (an absent optional stream changes the
    compiled signature and must change the key too)."""
    if obj is None:
        out.append("none")
    elif isinstance(obj, dict):
        out.append("{")
        for k in sorted(obj):
            out.append(f"{k}:")
            _describe(obj[k], out)
        out.append("}")
    elif isinstance(obj, (tuple, list)):
        out.append("(")
        for item in obj:
            _describe(item, out)
        out.append(")")
    elif hasattr(obj, "shape") and hasattr(obj, "dtype"):
        out.append(f"{obj.dtype}{tuple(obj.shape)}")
    else:
        out.append(repr(obj))


class _ShapeBucket:
    """One (jit site, compiled shape) bucket: dispatch count plus the
    memoized cost/memory analyses of its executable."""

    __slots__ = ("sig", "dispatches", "cost", "memory")

    def __init__(self, sig: str) -> None:
        self.sig = sig
        self.dispatches = 0
        self.cost: Optional[Dict[str, float]] = None
        self.memory: Optional[Dict[str, int]] = None

    def to_json(self) -> Dict[str, Any]:
        return {
            "dispatches": self.dispatches,
            "sig": self.sig,
            "cost": self.cost,
            "memory": self.memory,
        }


class _Occupancy:
    """One padded-shape bucket's occupancy accounting."""

    __slots__ = ("origin", "rounds", "real_ops", "padded_capacity")

    def __init__(self, origin: str) -> None:
        self.origin = origin
        self.rounds = 0
        self.real_ops = 0
        self.padded_capacity = 0

    def to_json(self) -> Dict[str, Any]:
        waste = (
            1.0 - self.real_ops / self.padded_capacity
            if self.padded_capacity else 0.0
        )
        return {
            "origin": self.origin,
            "rounds": self.rounds,
            "real_ops": self.real_ops,
            "padded_capacity": self.padded_capacity,
            "padding_waste": round(waste, 4),
        }


#: cost_analysis keys worth keeping (the rest is per-operand detail)
_COST_KEYS = ("flops", "bytes accessed", "transcendentals", "optimal_seconds")
#: CompiledMemoryStats attributes exported per bucket
_MEMORY_ATTRS = (
    "argument_size_in_bytes",
    "output_size_in_bytes",
    "temp_size_in_bytes",
    "alias_size_in_bytes",
    "generated_code_size_in_bytes",
)


class DeviceProfiler:
    """Per-jit-site / per-shape-bucket device-cost collector (module doc).

    Use :meth:`enable` / :meth:`disable` (or the context-manager form) to
    bound a profiled region; :meth:`snapshot` is the JSON-serializable
    export every surface (``/devprof.json``, ``health_snapshot(devprof=)``,
    the perf ledger, ``peritext_device_*`` gauges) shares.
    """

    def __init__(self, capture_costs: bool = False) -> None:
        self.enabled = False
        self.capture_costs = capture_costs
        self._lock = threading.Lock()
        self._sites: Dict[str, Dict[str, _ShapeBucket]] = {}
        self._occupancy: Dict[str, _Occupancy] = {}
        self._mem_samples = 0
        self._mem_last: Optional[int] = None
        self._mem_peak: Optional[int] = None
        self._mem_backend_peak: Optional[int] = None
        self._page_pool: Optional[Dict[str, Any]] = None
        self._page_pool_peak_util = 0.0
        self._ragged: Optional[Dict[str, int]] = None
        self._mesh: Optional[Dict[str, Any]] = None
        self._mesh_peak_imbalance = 0.0

    # -- lifecycle ----------------------------------------------------------

    def enable(self, capture_costs: Optional[bool] = None) -> "DeviceProfiler":
        if capture_costs is not None:
            self.capture_costs = capture_costs
        self.enabled = True
        return self

    def disable(self) -> None:
        self.enabled = False

    def reset(self) -> None:
        with self._lock:
            self._sites = {}
            self._occupancy = {}
            self._mem_samples = 0
            self._mem_last = None
            self._mem_peak = None
            self._mem_backend_peak = None
            self._page_pool = None
            self._page_pool_peak_util = 0.0
            self._ragged = None
            self._mesh = None
            self._mesh_peak_imbalance = 0.0

    def __enter__(self) -> "DeviceProfiler":
        return self.enable()

    def __exit__(self, *exc_info) -> None:
        self.disable()

    # -- shape-bucket keys --------------------------------------------------

    @staticmethod
    def shape_signature(tree: Any, static: Tuple = ()) -> Tuple[str, str]:
        """``(key, sig)`` for one dispatch: ``sig`` is the readable
        descriptor (argument shapes/dtypes + statics), ``key`` its stable
        hash.  Built from the ACTUAL dispatched arrays so the bucket
        granularity matches jax's compile cache exactly — neither coarser
        (two signatures, one bucket) nor finer (one signature, two)."""
        parts: list = []
        _describe(tree, parts)
        if static:
            parts.append(f"static={static!r}")
        sig = " ".join(parts)
        key = hashlib.sha1(sig.encode()).hexdigest()[:16]
        return key, sig

    # -- dispatch + occupancy accounting ------------------------------------

    def note_dispatch(
        self,
        site: str,
        key: str,
        sig: str = "",
        aot: Optional[Callable[[], Any]] = None,
    ) -> None:
        """Record one dispatch of ``site`` under shape bucket ``key``.

        ``aot`` — a zero-arg callable returning the dispatch's
        ``jax.stages.Lowered`` (i.e. ``lambda: jitted.lower(*args)``) —
        feeds the memoized cost/memory capture the first time a bucket is
        seen, when ``capture_costs`` is on."""
        capture = None
        with self._lock:
            buckets = self._sites.setdefault(site, {})
            bucket = buckets.get(key)
            if bucket is None:
                bucket = buckets[key] = _ShapeBucket(sig)
                if self.capture_costs and aot is not None:
                    capture = bucket
            bucket.dispatches += 1
        if capture is not None:
            cost, memory = self._analyze(aot)
            with self._lock:
                capture.cost, capture.memory = cost, memory

    @staticmethod
    def _analyze(aot: Callable[[], Any]):
        """Best-effort AOT cost/memory introspection of one executable."""
        try:
            compiled = aot().compile()
            raw = compiled.cost_analysis()
            if isinstance(raw, (list, tuple)):
                raw = raw[0] if raw else {}
            cost = {
                k.replace(" ", "_"): float(raw[k])
                for k in _COST_KEYS
                if raw and k in raw
            } or None
            stats = compiled.memory_analysis()
            memory = None
            if stats is not None:
                memory = {
                    a: int(getattr(stats, a))
                    for a in _MEMORY_ATTRS
                    if hasattr(stats, a)
                }
                if memory:
                    # the executable's resident device-memory requirement:
                    # arguments + outputs + XLA temp allocations
                    memory["peak_bytes"] = (
                        memory.get("argument_size_in_bytes", 0)
                        + memory.get("output_size_in_bytes", 0)
                        + memory.get("temp_size_in_bytes", 0)
                    )
            return cost, memory
        except Exception:  # graftlint: boundary(cost introspection is best-effort telemetry; an XLA AOT quirk must never fail the dispatch path being profiled)
            return None, None

    def observe_round(
        self, bucket: str, real_ops: int, padded_capacity: int,
        rounds: int = 1, origin: str = "streaming.round",
    ) -> None:
        """Fold one committed round (or one batch merge) into the
        bucket-occupancy table."""
        with self._lock:
            occ = self._occupancy.get(bucket)
            if occ is None:
                occ = self._occupancy[bucket] = _Occupancy(origin)
            occ.rounds += rounds
            occ.real_ops += int(real_ops)
            occ.padded_capacity += int(padded_capacity)

    def observe_page_pool(self, stats: Dict[str, Any]) -> None:
        """Fold one page-pool snapshot (store/paged.PagedDocStore
        ``pool_stats()``) in: the latest snapshot is kept whole (pool
        utilization, pages in use, internal fragmentation per doc-size
        decile) plus a peak-utilization watermark across the profiled
        region — the paged layout's waste story, sampled at round
        boundaries like the memory watermarks."""
        with self._lock:
            self._page_pool = dict(stats)
            util = float(stats.get("pool_utilization") or 0.0)
            self._page_pool_peak_util = max(self._page_pool_peak_util, util)

    def observe_mesh(self, stats: Dict[str, Any]) -> None:
        """Fold one mesh-shard snapshot (a sharded session's
        ``_mesh_stats()`` / the sharded store's ``shard_stats()``) in:
        latest snapshot kept whole (per-shard load/utilization, the
        cumulative ICI page-move count) plus a peak shard-imbalance
        watermark across the profiled region — the doc-axis analog of the
        page-pool waste story."""
        with self._lock:
            self._mesh = dict(stats)
            ratio = float(stats.get("imbalance_ratio") or 0.0)
            self._mesh_peak_imbalance = max(self._mesh_peak_imbalance, ratio)

    def observe_ragged(self, docs_walked: int, pages_walked: int,
                       real_ops: int, padded_slot_waste: int = 0,
                       dispatches: int = 1) -> None:
        """Fold one ragged apply's plan stats in (ops/ragged callers report
        after each dispatch): docs and pool pages the plan walked, the real
        ops applied, and any padded-slot waste — which the ragged layout
        keeps at ~0 by construction (true counts are loop bounds, not
        shapes), making this section the bucket-occupancy table's
        counterpoint."""
        with self._lock:
            if self._ragged is None:
                self._ragged = {
                    "dispatches": 0, "docs_walked": 0, "pages_walked": 0,
                    "real_ops": 0, "padded_slot_waste": 0,
                }
            r = self._ragged
            r["dispatches"] += int(dispatches)
            r["docs_walked"] += int(docs_walked)
            r["pages_walked"] += int(pages_walked)
            r["real_ops"] += int(real_ops)
            r["padded_slot_waste"] += int(padded_slot_waste)

    # -- device-memory watermarks -------------------------------------------

    def sample_memory(self) -> Optional[int]:
        """Sample the first local device's live memory; returns
        ``bytes_in_use`` (None when the backend exposes no stats — CPU)."""
        try:
            import jax

            stats = jax.local_devices()[0].memory_stats()
        except Exception:  # graftlint: boundary(memory watermarks are best-effort; a backend without memory_stats must not fail the round being sampled)
            stats = None
        with self._lock:
            self._mem_samples += 1
            if not stats:
                return None
            in_use = stats.get("bytes_in_use")
            if in_use is not None:
                self._mem_last = int(in_use)
                self._mem_peak = max(self._mem_peak or 0, int(in_use))
            peak = stats.get("peak_bytes_in_use")
            if peak is not None:
                self._mem_backend_peak = int(peak)
            return self._mem_last

    # -- export -------------------------------------------------------------

    def distinct_shapes(self) -> Dict[str, int]:
        """Per-site distinct compiled-shape counts — the quantity that must
        equal the RecompileSentinel's per-site compile counts on a
        fresh-session replay."""
        with self._lock:
            return {site: len(b) for site, b in sorted(self._sites.items())}

    def snapshot(self) -> Dict[str, Any]:
        """One JSON-serializable document: the shape-bucket table per jit
        site (with any captured cost/memory analyses), the bucket-occupancy
        table, and the device-memory watermarks.  The exporter golden test
        pins this schema."""
        with self._lock:
            sites = {
                site: {
                    "distinct_shapes": len(buckets),
                    "dispatches": sum(b.dispatches for b in buckets.values()),
                    "buckets": {
                        key: b.to_json() for key, b in sorted(buckets.items())
                    },
                }
                for site, buckets in sorted(self._sites.items())
            }
            occupancy = {
                k: o.to_json() for k, o in sorted(self._occupancy.items())
            }
            real = sum(o.real_ops for o in self._occupancy.values())
            padded = sum(o.padded_capacity for o in self._occupancy.values())
            rounds = sum(o.rounds for o in self._occupancy.values())
            memory = {
                "available": self._mem_last is not None,
                "samples": self._mem_samples,
                "bytes_in_use": self._mem_last,
                "peak_bytes_in_use": (
                    self._mem_backend_peak
                    if self._mem_backend_peak is not None
                    else self._mem_peak
                ),
            }
            page_pool = (
                dict(self._page_pool,
                     peak_utilization=round(self._page_pool_peak_util, 4))
                if self._page_pool is not None
                else None
            )
            ragged = dict(self._ragged) if self._ragged is not None else None
            mesh = (
                dict(self._mesh,
                     peak_imbalance=round(self._mesh_peak_imbalance, 4))
                if self._mesh is not None
                else None
            )
        return {
            "enabled": self.enabled,
            "capture_costs": self.capture_costs,
            "sites": sites,
            "occupancy": occupancy,
            "occupancy_totals": {
                "rounds": rounds,
                "real_ops": real,
                "padded_capacity": padded,
                "padding_waste": round(1.0 - real / padded, 4) if padded else 0.0,
            },
            "memory": memory,
            # None until a paged store reports in — padded-only processes
            # export no page section (the golden-shape test pins both forms)
            "page_pool": page_pool,
            # None until a ragged apply reports in (same discipline)
            "ragged": ragged,
            # None until a mesh-sharded session reports in (same discipline)
            "mesh": mesh,
        }


#: Default process-wide device profiler — OFF by default; every hook in the
#: merge stack checks ``GLOBAL_DEVPROF.enabled`` before doing any work.
GLOBAL_DEVPROF = DeviceProfiler()


def occupancy_key(docs: int, ki: int, kd: int, km: int, kp: int) -> str:
    """The ONE spelling of a padded-shape occupancy bucket — every producer
    (streaming rounds, batch merges) must share it, or the occupancy table
    splits into incompatible key namespaces."""
    return f"D{docs}.ki{ki}.kd{kd}.km{km}.kp{kp}"


def note_jit_dispatch(
    site: str,
    jitfn: Any,
    args: Tuple,
    kwargs: Optional[Dict[str, Any]] = None,
    profiler: Optional[DeviceProfiler] = None,
) -> None:
    """Record one dispatch of jit wrapper ``jitfn`` called as
    ``jitfn(*args, **kwargs)``: shape-bucket key from the actual arguments
    (static scalars inside ``args`` are folded by value, matching jax's
    cache granularity) plus the AOT lowering for cost capture.  Callers on
    hot paths guard on ``profiler.enabled`` first; this no-ops regardless
    when profiling is off."""
    p = profiler if profiler is not None else GLOBAL_DEVPROF
    if not p.enabled:
        return
    kwargs = kwargs or {}
    key, sig = p.shape_signature(args, static=tuple(sorted(kwargs.items())))
    p.note_dispatch(site, key, sig, aot=lambda: jitfn.lower(*args, **kwargs))
