"""Fixed-bucket histograms with percentile readout.

The shape every latency/size metric in the fleet shares: a fixed bucket
table (so merging and exporting never depends on the observation stream),
cumulative or ROLLING-WINDOW counts, and p50/p95/p99 readout computed from
the bucket counts.  Percentiles return the matched bucket's UPPER bound
(the overflow bucket returns the observed max), so a percentile-derived
deadline errs high — the safe direction for a watchdog.

The rolling-window mode is what the supervisor's deadline autotuning rides:
a bounded ring of recent observations whose evictions decrement the bucket
counts, so the percentile always describes the last ``window`` rounds.
"""

from __future__ import annotations

import contextlib
import math
import threading
import time
from bisect import bisect_left
from collections import deque
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

#: latency buckets (seconds): sub-ms dispatches through multi-minute compiles
LATENCY_BUCKETS_S = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0,
)
#: size buckets (counts/bytes): frame counts, scheduled changes, op totals
SIZE_BUCKETS = (
    1, 2, 5, 10, 25, 50, 100, 250, 500, 1_000, 2_500, 5_000,
    10_000, 25_000, 50_000, 100_000, 1_000_000,
)


class Histogram:
    """Thread-safe fixed-bucket histogram.

    ``window=None`` (default) accumulates forever; ``window=N`` keeps the
    counts describing only the most recent N observations (the rolling
    percentile the deadline autotuner needs).
    """

    def __init__(
        self,
        buckets: Sequence[float] = LATENCY_BUCKETS_S,
        window: Optional[int] = None,
    ) -> None:
        if window is not None and window <= 0:
            raise ValueError(f"window must be positive or None, got {window}")
        self.bounds: Tuple[float, ...] = tuple(sorted(float(b) for b in buckets))
        if not self.bounds:
            raise ValueError("histogram needs at least one bucket bound")
        self.window = window
        self._lock = threading.Lock()
        # one overflow bucket past the last bound
        self._counts: List[int] = [0] * (len(self.bounds) + 1)
        self._ring: Optional[deque] = deque() if window is not None else None
        self.count = 0
        self.sum = 0.0
        self._max = 0.0

    def _bucket(self, value: float) -> int:
        return bisect_left(self.bounds, value)

    def observe(self, value: float) -> None:
        value = float(value)
        idx = self._bucket(value)
        with self._lock:
            self._counts[idx] += 1
            self.count += 1
            self.sum += value
            self._max = max(self._max, value)
            if self._ring is not None:
                self._ring.append((idx, value))
                if len(self._ring) > self.window:
                    old_idx, old_value = self._ring.popleft()
                    self._counts[old_idx] -= 1
                    self.count -= 1
                    self.sum -= old_value
                    if old_value >= self._max:
                        self._max = max(
                            (v for _, v in self._ring), default=0.0
                        )

    def percentile(self, q: float) -> float:
        """The q-quantile (0 < q <= 1) as the matched bucket's upper bound;
        the overflow bucket reads as the observed max.  0.0 when empty."""
        with self._lock:
            if self.count == 0:
                return 0.0
            rank = max(1, math.ceil(q * self.count))
            cum = 0
            for i, c in enumerate(self._counts):
                cum += c
                if cum >= rank:
                    if i < len(self.bounds):
                        return float(self.bounds[i])
                    return float(self._max)
            return float(self._max)

    @property
    def p50(self) -> float:
        return self.percentile(0.50)

    @property
    def p95(self) -> float:
        return self.percentile(0.95)

    @property
    def p99(self) -> float:
        return self.percentile(0.99)

    @property
    def overflow(self) -> int:
        """Observations past the last bound — a saturated top bucket reads
        as "overflowed", never silently as the top bound.  Window-safe: the
        rolling ring decrements this slot on eviction like any other."""
        with self._lock:
            return self._counts[-1]

    def bucket_counts(self) -> List[Tuple[float, int]]:
        """CUMULATIVE counts per upper bound (Prometheus ``le`` semantics);
        the +Inf bucket is ``count``."""
        with self._lock:
            out = []
            cum = 0
            for bound, c in zip(self.bounds, self._counts):
                cum += c
                out.append((bound, cum))
            return out

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            count, total, mx = self.count, self.sum, self._max
            over = self._counts[-1]
        return {
            "count": count,
            "sum": round(total, 6),
            "max": round(mx, 6),
            "overflow": over,
            "p50": self.percentile(0.50),
            "p95": self.percentile(0.95),
            "p99": self.percentile(0.99),
        }


class HistogramRegistry:
    """Named histograms, created on first observation — the process-wide
    analog of :class:`~.metrics.Counters` for distributions."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._hists: Dict[str, Histogram] = {}

    def get(
        self, name: str, buckets: Sequence[float] = LATENCY_BUCKETS_S
    ) -> Histogram:
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = Histogram(buckets)
            return h

    def observe(
        self, name: str, value: float,
        buckets: Sequence[float] = LATENCY_BUCKETS_S,
    ) -> None:
        self.get(name, buckets).observe(value)

    @contextlib.contextmanager
    def timed(self, name: str) -> Iterator[None]:
        """Observe the enclosed block's wall seconds into ``name``."""
        start = time.perf_counter()
        try:
            yield
        finally:
            self.observe(name, time.perf_counter() - start)

    def items(self) -> List[Tuple[str, Histogram]]:
        with self._lock:
            return sorted(self._hists.items())

    def snapshot(self) -> Dict[str, Dict[str, float]]:
        return {name: h.snapshot() for name, h in self.items()}

    def reset(self) -> None:
        with self._lock:
            self._hists.clear()


#: default process-wide histogram registry (exported by health_snapshot
#: and the Prometheus endpoint)
GLOBAL_HISTOGRAMS = HistogramRegistry()
