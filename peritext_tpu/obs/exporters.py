"""Metrics exporters: Prometheus text exposition and HTTP endpoints.

:func:`prometheus_text` renders the process counters + histograms (and
optionally one session's health) in Prometheus text-exposition format
(version 0.0.4).  :class:`MetricsServer` mounts that plus the JSON health
snapshot and the live Perfetto trace on a tiny threaded HTTP server —
``ReplicaServer(metrics_port=...)`` starts one per host, so a fleet scrape
is ``GET /metrics`` against every replica.

Endpoints:

* ``/metrics``      — Prometheus text exposition
* ``/health.json``  — :func:`~.metrics.health_snapshot` as JSON
* ``/trace.json``   — the attached tracer's Chrome trace-event dump
* ``/devprof.json`` — the attached :class:`~.devprof.DeviceProfiler`
  snapshot (shape buckets, occupancy, memory watermarks)
* ``/serve.json``   — the attached :class:`~..serve.SessionMux` snapshot
  (sessions, bounded-queue + typed-verdict state, autotuned round window)
* ``/fleet.json``   — the attached :class:`~..serve.FleetFrontend` snapshot
  (heartbeat-lease table, router placement, per-host serve summaries,
  failover/migration tallies, fleet-wide verdict accounting)
* ``/latency.json`` — the attached :class:`~.latency.LatencyPlane` snapshot
  (per-stage watermark histograms, SLO burn rate, close causes,
  time-to-visibility)
* ``/incidents.json`` — the attached
  :class:`~.incidents.IncidentMonitor` snapshot (typed incident list,
  lifecycle tallies, cross-host agreement view)
* ``/timeseries.json`` — the attached
  :class:`~.timeseries.TimeSeriesPlane` snapshot (retention tiers,
  anomaly findings, occupancy rows); supports windowed query params
  (``?key=...&window=N&rate=1`` — :func:`~.timeseries.query_snapshot`)

A raising plane snapshot answers 500 with a TYPED JSON body
(``{"error": ..., "plane": ...}``) — one sick plane must not turn a
fleet scrape into an HTML traceback page.
"""

from __future__ import annotations

import json
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional, Tuple
from urllib.parse import parse_qs

from .histograms import GLOBAL_HISTOGRAMS, HistogramRegistry
from .metrics import Counters, GLOBAL_COUNTERS, health_snapshot
from .timeseries import query_snapshot

_NAME_RE = re.compile(r"[^a-zA-Z0-9_]")


def _metric_name(name: str) -> str:
    return "peritext_" + _NAME_RE.sub("_", name)


def _fmt(value: float) -> str:
    return repr(round(float(value), 9)) if value % 1 else str(int(value))


def _quote_label(value: str) -> str:
    """Full exposition-format label escaping: backslash, quote, newline."""
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


#: computed once per process: the sha shells out to git and the fingerprint
#: may touch the jax backend — neither belongs on the per-scrape path
_BUILD_INFO: Optional[Dict[str, str]] = None


def build_info() -> Dict[str, str]:
    """One identity record for this process — the SAME spellings the perf
    ledger stamps into its rows (:func:`~.ledger.git_sha` /
    :func:`~.ledger.device_fingerprint`), plus the wire caps, so a scraped
    fleet and a ledger row can be joined on identity without translation."""
    global _BUILD_INFO
    if _BUILD_INFO is None:
        from ..parallel.codec import WIRE_CAPS
        from .ledger import device_fingerprint, git_sha

        try:
            import jax
            jax_version = getattr(jax, "__version__", "unknown")
        except Exception:  # graftlint: boundary(the identity gauge must render even where jax is absent)
            jax_version = "none"
        fp = device_fingerprint()
        _BUILD_INFO = {
            "sha": git_sha() or "unknown",
            "wire_caps": str(WIRE_CAPS),
            "jax": str(jax_version),
            "device": f"{fp.get('platform')}-{fp.get('kind')}"
                      f"-{fp.get('cpus')}",
        }
    return _BUILD_INFO


def prometheus_text(
    counters: Optional[Counters] = None,
    histograms: Optional[HistogramRegistry] = None,
    session=None,
    sentinel=None,
    convergence=None,
    devprof=None,
    serve=None,
    fleet=None,
    plan=None,
    latency=None,
    incidents=None,
    history=None,
) -> str:
    """Prometheus text exposition of the process telemetry.  Counter names
    sanitize ``.`` → ``_`` under a ``peritext_`` prefix; histograms emit the
    standard ``_bucket{le=...}`` / ``_sum`` / ``_count`` series; a session's
    numeric health fields land as ``peritext_session_*`` gauges; a
    :class:`~.convergence.ConvergenceMonitor` lands as per-peer
    ``peritext_convergence_*`` gauges (lag ops, staleness rounds) plus the
    fleet-level totals; a :class:`~.devprof.DeviceProfiler` lands as
    per-site ``peritext_device_*`` gauges (distinct compiled shapes,
    dispatches, modeled flops/bytes totals, peak executable memory) plus
    the bucket-occupancy and device-memory-watermark totals, and — when a
    mesh-sharded session reported in — ``peritext_mesh_*`` gauges
    (per-shard pool load/utilization, shard-imbalance ratio, cumulative
    ICI page moves); a
    :class:`~..serve.SessionMux` lands as ``peritext_serve_*`` gauges
    (sessions, bounded-queue depth/peak, backpressure flag, autotuned
    window) plus the typed-verdict counters, with sheds labelled by
    reason; a :class:`~..serve.FleetFrontend` lands as
    ``peritext_fleet_*`` gauges (host/lease counts, failover + migration
    tallies, durable-state bookkeeping) plus the fleet-wide verdict
    counters with sheds labelled by reason.  A serve snapshot's
    ``fusion`` section lands as ``peritext_plan_fusion_*`` gauges (group
    membership, dispatch amortization, window occupancy); a planner
    verdict passed as ``plan`` (a :class:`~..plan.tuner.PlanProposal` or
    its ``to_json()`` dict) lands as ``peritext_plan_*`` gauges (modeled
    scores, savings fraction, the proposed statics); a
    :class:`~.latency.LatencyPlane` lands as ``peritext_latency_*``
    families — one histogram per stage watermark plus the end-to-end
    total and time-to-visibility, SLO burn-rate gauges, and the
    window-close cause counters; an
    :class:`~.incidents.IncidentMonitor` lands as ``peritext_incident_*``
    gauges — lifecycle tallies, per-kind open counts over the FULL
    taxonomy (absent kinds at 0, so alert rules never reference a series
    that has yet to exist), the incident-view digest, and per-peer
    agreement flags; a :class:`~.timeseries.TimeSeriesPlane` (live or
    snapshot dict) lands as ``peritext_history_*`` gauges — frames
    sampled/retained, per-tier frame counts, persisted segments, active
    + cumulative anomalies (with the by-key breakdown as its own
    labelled family), recorded occupancy rows, and the caller-reported
    sampling overhead.  Every exposition also carries ONE
    ``peritext_build_info`` info-style gauge (value 1, identity as
    labels: git sha, wire caps, jax version, device fingerprint) — the
    same spellings the perf ledger stamps, so fleet scrapes and ledger
    rows join on identity."""
    counters = counters or GLOBAL_COUNTERS
    histograms = histograms if histograms is not None else GLOBAL_HISTOGRAMS
    lines = []
    info = build_info()
    m = "peritext_build_info"
    lines.append(f"# TYPE {m} gauge")
    lines.append(
        f'{m}{{sha="{_quote_label(info["sha"])}"'
        f',wire_caps="{_quote_label(info["wire_caps"])}"'
        f',jax="{_quote_label(info["jax"])}"'
        f',device="{_quote_label(info["device"])}"}} 1'
    )
    for name, value in sorted(counters.snapshot().items()):
        m = _metric_name(name)
        lines.append(f"# TYPE {m} counter")
        lines.append(f"{m} {_fmt(value)}")
    for name, hist in histograms.items():
        m = _metric_name(name)
        lines.append(f"# TYPE {m} histogram")
        for bound, cum in hist.bucket_counts():
            lines.append(f'{m}_bucket{{le="{bound:g}"}} {cum}')
        lines.append(f'{m}_bucket{{le="+Inf"}} {hist.count}')
        lines.append(f"{m}_sum {_fmt(hist.sum)}")
        lines.append(f"{m}_count {hist.count}")
        lines.append(f"{m}_overflow {hist.overflow}")
    if sentinel is not None:
        m = "peritext_recompiles_total"
        lines.append(f"# TYPE {m} counter")
        lines.append(f"{m} {sentinel.total}")
    if convergence is not None:
        snap = convergence.snapshot()
        per_peer = (
            ("peritext_convergence_lag_ops", "ops_behind"),
            ("peritext_convergence_ahead_ops", "ops_ahead"),
            ("peritext_convergence_staleness_rounds", "staleness_rounds"),
            ("peritext_convergence_peer_failures", "failures"),
        )
        for m, key in per_peer:
            lines.append(f"# TYPE {m} gauge")
            for peer, rec in snap["peers"].items():
                # full exposition-format label escaping: backslash, quote,
                # AND newline — peer names are arbitrary strings (pubsub
                # subscriber keys, logical gossip names), and one raw
                # newline would corrupt the whole scrape page
                quoted = (peer.replace("\\", "\\\\").replace('"', '\\"')
                          .replace("\n", "\\n"))
                lines.append(f'{m}{{peer="{quoted}"}} {_fmt(rec[key])}')
        for m, value in (
            ("peritext_convergence_peers", len(snap["peers"])),
            ("peritext_convergence_total_lag_ops", snap["total_lag_ops"]),
            ("peritext_convergence_rounds", snap["rounds"]),
        ):
            lines.append(f"# TYPE {m} gauge")
            lines.append(f"{m} {_fmt(value)}")
        m = "peritext_convergence_divergence_incidents_total"
        lines.append(f"# TYPE {m} counter")
        lines.append(f"{m} {_fmt(snap['divergence_incidents'])}")
    if devprof is not None:
        dp = devprof.snapshot()
        per_site = []
        for site, rec in dp["sites"].items():
            flops = sum(
                b["cost"]["flops"] * b["dispatches"]
                for b in rec["buckets"].values()
                if b.get("cost") and "flops" in b["cost"]
            )
            bytes_acc = sum(
                b["cost"]["bytes_accessed"] * b["dispatches"]
                for b in rec["buckets"].values()
                if b.get("cost") and "bytes_accessed" in b["cost"]
            )
            peak = max(
                (b["memory"]["peak_bytes"] for b in rec["buckets"].values()
                 if b.get("memory")),
                default=0,
            )
            per_site.append((site, rec, flops, bytes_acc, peak))
        site_gauges = (
            ("peritext_device_distinct_shapes", lambda r, f, ba, p: r["distinct_shapes"]),
            ("peritext_device_dispatches", lambda r, f, ba, p: r["dispatches"]),
            ("peritext_device_flops_total", lambda r, f, ba, p: f),
            ("peritext_device_bytes_accessed_total", lambda r, f, ba, p: ba),
            ("peritext_device_peak_bytes", lambda r, f, ba, p: p),
        )
        for m, value_of in site_gauges:
            lines.append(f"# TYPE {m} gauge")
            for site, rec, flops, bytes_acc, peak in per_site:
                quoted = (site.replace("\\", "\\\\").replace('"', '\\"')
                          .replace("\n", "\\n"))
                lines.append(
                    f'{m}{{site="{quoted}"}} '
                    f"{_fmt(value_of(rec, flops, bytes_acc, peak))}"
                )
        tot = dp["occupancy_totals"]
        for m, value in (
            ("peritext_device_rounds_total", tot["rounds"]),
            ("peritext_device_real_ops_total", tot["real_ops"]),
            ("peritext_device_padded_ops_total", tot["padded_capacity"]),
            ("peritext_device_padding_waste_ratio", tot["padding_waste"]),
        ):
            lines.append(f"# TYPE {m} gauge")
            lines.append(f"{m} {_fmt(value)}")
        pp = dp.get("page_pool")
        if pp:
            # paged-storage gauges (store/paged.PagedDocStore.pool_stats):
            # pool occupancy + internal fragmentation, with the per-decile
            # fragmentation breakdown as a labelled family
            for m, value in (
                ("peritext_page_pool_pages", pp["pool_pages"]),
                ("peritext_page_pages_in_use", pp["pages_in_use"]),
                ("peritext_page_pool_utilization", pp["pool_utilization"]),
                ("peritext_page_pool_peak_utilization",
                 pp.get("peak_utilization", pp["pool_utilization"])),
                ("peritext_page_pool_growths", pp["growths"]),
                ("peritext_page_docs_resident", pp["docs_resident"]),
                ("peritext_page_internal_frag_slots", pp["internal_frag_slots"]),
                ("peritext_page_internal_frag_ratio", pp["internal_frag_ratio"]),
                ("peritext_page_size_slots", pp["page_size"]),
            ):
                lines.append(f"# TYPE {m} gauge")
                lines.append(f"{m} {_fmt(value)}")
            m = "peritext_page_frag_ratio"
            lines.append(f"# TYPE {m} gauge")
            for decile, value in sorted(pp.get("frag_by_decile", {}).items()):
                lines.append(f'{m}{{decile="{decile}"}} {_fmt(value)}')
        rg = dp.get("ragged")
        if rg:
            # ragged-apply gauges (ops/ragged.py dispatches): how much of
            # the pool each one-program round actually walked.  The waste
            # gauge is the layout's headline — identically 0 padded slots
            # dispatched, vs the bucket ladder's pow-2 pad
            for m, value in (
                ("peritext_ragged_dispatches", rg["dispatches"]),
                ("peritext_ragged_docs_walked", rg["docs_walked"]),
                ("peritext_ragged_pages_walked", rg["pages_walked"]),
                ("peritext_ragged_real_ops", rg["real_ops"]),
                ("peritext_ragged_padded_slot_waste", rg["padded_slot_waste"]),
            ):
                lines.append(f"# TYPE {m} gauge")
                lines.append(f"{m} {_fmt(value)}")
        ms = dp.get("mesh")
        if ms:
            # mesh-shard gauges (store/sharded shard_stats via the session's
            # _mesh_stats): doc-axis balance across the sharded page pools
            # plus the cumulative ICI page-move tally from reshards
            for m, value in (
                ("peritext_mesh_shards", ms["shards"]),
                ("peritext_mesh_rows_per_shard", ms["rows_per_shard"]),
                ("peritext_mesh_shard_imbalance_ratio",
                 ms["imbalance_ratio"]),
                ("peritext_mesh_peak_imbalance_ratio",
                 ms.get("peak_imbalance", ms["imbalance_ratio"])),
                ("peritext_mesh_ici_page_moves",
                 ms.get("ici_page_moves", 0)),
            ):
                lines.append(f"# TYPE {m} gauge")
                lines.append(f"{m} {_fmt(value)}")
            m = "peritext_mesh_shard_load"
            lines.append(f"# TYPE {m} gauge")
            for shard, value in enumerate(ms.get("shard_load") or ()):
                lines.append(f'{m}{{shard="{shard}"}} {_fmt(value)}')
            m = "peritext_mesh_shard_pool_utilization"
            lines.append(f"# TYPE {m} gauge")
            for shard, value in enumerate(ms.get("shard_utilization") or ()):
                lines.append(f'{m}{{shard="{shard}"}} {_fmt(value)}')
        mem = dp["memory"]
        if mem["available"]:
            for m, value in (
                ("peritext_device_memory_bytes_in_use", mem["bytes_in_use"]),
                ("peritext_device_memory_peak_bytes", mem["peak_bytes_in_use"]),
            ):
                if value is not None:
                    lines.append(f"# TYPE {m} gauge")
                    lines.append(f"{m} {_fmt(value)}")
    if serve is not None:
        snap = serve.snapshot()
        q = snap["queue"]
        w = snap["window"]
        for m, value in (
            ("peritext_serve_sessions", snap["sessions"]),
            ("peritext_serve_docs", snap["docs"]),
            ("peritext_serve_doc_capacity", snap["doc_capacity"]),
            ("peritext_serve_degraded_docs", snap["degraded_docs"]),
            ("peritext_serve_rounds", snap["rounds"]),
            ("peritext_serve_applied_frames", snap["applied_frames"]),
            ("peritext_serve_buffered_frames", snap["buffered_frames"]),
            ("peritext_serve_overloaded", int(snap["overloaded"])),
            ("peritext_serve_queue_depth", q["depth"]),
            ("peritext_serve_queue_peak", q["peak"]),
            ("peritext_serve_queue_max_depth", q["max_depth"]),
            ("peritext_serve_backpressure", int(q["backpressure"])),
            ("peritext_serve_window_seconds", w["seconds"]),
            ("peritext_serve_window_p99_round_seconds",
             w["p99_round_seconds"]),
        ):
            lines.append(f"# TYPE {m} gauge")
            lines.append(f"{m} {_fmt(value)}")
        verdicts = q["verdicts"]
        for m, key in (
            ("peritext_serve_submitted_total", "submitted"),
            ("peritext_serve_admitted_total", "admitted"),
            ("peritext_serve_delayed_total", "delayed"),
        ):
            lines.append(f"# TYPE {m} counter")
            lines.append(f"{m} {_fmt(verdicts[key])}")
        m = "peritext_serve_shed_total"
        lines.append(f"# TYPE {m} counter")
        lines.append(f"{m} {_fmt(verdicts['shed'])}")
        # the by-reason breakdown is its OWN family: mixing an unlabelled
        # total with labelled samples under one name would make a PromQL
        # sum() double-count every shed
        m = "peritext_serve_shed_reason_total"
        lines.append(f"# TYPE {m} counter")
        for reason, count in verdicts["shed_reasons"].items():
            quoted = (reason.replace("\\", "\\\\").replace('"', '\\"')
                      .replace("\n", "\\n"))
            lines.append(f'{m}{{reason="{quoted}"}} {_fmt(count)}')
        fu = snap.get("fusion")
        if fu:
            # cross-tenant fusion gauges: how many tenants this host's
            # dispatches amortize over (identity report when standalone)
            for m, value in (
                ("peritext_plan_fusion_grouped", int(fu["grouped"])),
                ("peritext_plan_fusion_tenants", fu["tenants"]),
                ("peritext_plan_fusion_lanes", fu["lanes"]),
                ("peritext_plan_fusion_windows", fu["windows"]),
                ("peritext_plan_fusion_dispatches", fu["dispatches"]),
                ("peritext_plan_docs_per_dispatch",
                 fu["docs_per_dispatch"]),
                ("peritext_plan_window_occupancy",
                 fu["window_occupancy"]),
            ):
                lines.append(f"# TYPE {m} gauge")
                lines.append(f"{m} {_fmt(value)}")
    if fleet is not None:
        snap = fleet.snapshot()
        leases = snap["leases"]["leases"]
        live = sum(1 for rec in leases.values() if rec["verdict"] == "live")
        dead = sum(1 for rec in leases.values() if rec["verdict"] == "dead")
        for m, value in (
            ("peritext_fleet_hosts", len(snap["hosts"])),
            ("peritext_fleet_live_hosts", live),
            ("peritext_fleet_dead_hosts", dead),
            ("peritext_fleet_docs", len(snap["serving"])),
            ("peritext_fleet_moving_docs", len(snap["moving"])),
            ("peritext_fleet_failed_docs", len(snap["failed_docs"])),
            ("peritext_fleet_rounds", snap["rounds"]),
            ("peritext_fleet_journal_frames", snap["journal_frames"]),
            ("peritext_fleet_checkpoint_docs", snap["checkpoint_docs"]),
        ):
            lines.append(f"# TYPE {m} gauge")
            lines.append(f"{m} {_fmt(value)}")
        for m, value in (
            ("peritext_fleet_failovers_total", snap["failovers"]),
            ("peritext_fleet_failover_docs_total", snap["failover_docs"]),
            ("peritext_fleet_migrations_total", snap["migrations"]),
            ("peritext_fleet_migration_rollbacks_total",
             snap["migration_rollbacks"]),
            ("peritext_fleet_checkpoint_ships_total",
             snap["checkpoint_ships"]),
        ):
            lines.append(f"# TYPE {m} counter")
            lines.append(f"{m} {_fmt(value)}")
        verdicts = snap["verdicts"]
        for m, key in (
            ("peritext_fleet_submitted_total", "submitted"),
            ("peritext_fleet_admitted_total", "admitted"),
            ("peritext_fleet_delayed_total", "delayed"),
            ("peritext_fleet_shed_total", "shed"),
        ):
            lines.append(f"# TYPE {m} counter")
            lines.append(f"{m} {_fmt(verdicts[key])}")
        # by-reason family, own name (same no-double-count rationale as
        # peritext_serve_shed_reason_total)
        m = "peritext_fleet_shed_reason_total"
        lines.append(f"# TYPE {m} counter")
        for reason, count in verdicts["shed_reasons"].items():
            quoted = (reason.replace("\\", "\\\\").replace('"', '\\"')
                      .replace("\n", "\\n"))
            lines.append(f'{m}{{reason="{quoted}"}} {_fmt(count)}')
    if plan is not None:
        pj = plan.to_json() if hasattr(plan, "to_json") else dict(plan)
        modeled = pj.get("modeled") or {}
        proposal = pj.get("proposal") or {}
        for m, value in (
            ("peritext_plan_current_score", modeled.get("current_score")),
            ("peritext_plan_proposed_score", modeled.get("proposed_score")),
            ("peritext_plan_savings_frac", modeled.get("savings_frac")),
            ("peritext_plan_utilization", modeled.get("utilization")),
            ("peritext_plan_proposed_fused_depth",
             proposal.get("fused_depth")),
            ("peritext_plan_proposed_slot_capacity",
             proposal.get("slot_capacity")),
            ("peritext_plan_proposed_page_size", proposal.get("page_size")),
            ("peritext_plan_proposed_window_seconds",
             proposal.get("window_seconds")),
        ):
            if isinstance(value, (int, float)):
                lines.append(f"# TYPE {m} gauge")
                lines.append(f"{m} {_fmt(value)}")
    if latency is not None:
        # the latency plane owns PRIVATE histograms (arming it for one
        # bench arm must not pollute the process registry), so its
        # families are emitted here from the plane itself
        for name, hist in sorted(latency.hists.items()):
            m = f"peritext_latency_{_NAME_RE.sub('_', name)}_seconds"
            lines.append(f"# TYPE {m} histogram")
            for bound, cum in hist.bucket_counts():
                lines.append(f'{m}_bucket{{le="{bound:g}"}} {cum}')
            lines.append(f'{m}_bucket{{le="+Inf"}} {hist.count}')
            lines.append(f"{m}_sum {_fmt(hist.sum)}")
            lines.append(f"{m}_count {hist.count}")
            lines.append(f"{m}_overflow {hist.overflow}")
        snap = latency.snapshot()
        slo = snap["slo"]
        for m, value in (
            ("peritext_latency_enabled", int(snap["enabled"])),
            ("peritext_latency_sample_every", snap["sample_every"]),
            ("peritext_latency_windows", snap["windows"]),
            ("peritext_latency_records", snap["records"]),
            ("peritext_latency_pending_visibility",
             snap["pending_visibility"]),
            ("peritext_latency_never_read", snap["never_read"]),
            ("peritext_latency_replica_fanout", snap["shards"]),
            ("peritext_latency_slo_seconds", slo["slo_seconds"]),
            ("peritext_latency_slo_target", slo["target"]),
            ("peritext_latency_slo_violating_frac", slo["violating_frac"]),
            ("peritext_latency_slo_burn_rate", slo["burn_rate"]),
        ):
            lines.append(f"# TYPE {m} gauge")
            lines.append(f"{m} {_fmt(value)}")
        m = "peritext_latency_force_close_total"
        lines.append(f"# TYPE {m} counter")
        for cause, count in sorted(snap["force_close"].items()):
            quoted = (cause.replace("\\", "\\\\").replace('"', '\\"')
                      .replace("\n", "\\n"))
            lines.append(f'{m}{{cause="{quoted}"}} {_fmt(count)}')
    if incidents is not None:
        snap = incidents.snapshot()
        for m, value in (
            ("peritext_incident_rounds", snap["rounds"]),
            ("peritext_incident_open", snap["open"]),
            ("peritext_incident_acked", snap["acked"]),
            ("peritext_incident_resolved", snap["resolved"]),
            ("peritext_incident_total", snap["total"]),
            ("peritext_incident_digest", snap["digest"]),
        ):
            lines.append(f"# TYPE {m} gauge")
            lines.append(f"{m} {_fmt(value)}")
        # by-kind family, own name (same no-double-count rationale as
        # peritext_serve_shed_reason_total); the FULL taxonomy is emitted
        # so dashboards can alert on kinds that have never fired
        m = "peritext_incident_open_by_kind"
        lines.append(f"# TYPE {m} gauge")
        for kind, count in snap["by_kind"].items():
            lines.append(f'{m}{{kind="{_quote_label(kind)}"}} {_fmt(count)}')
        m = "peritext_incident_peer_agreement"
        lines.append(f"# TYPE {m} gauge")
        for peer, view in snap["peers"].items():
            lines.append(
                f'{m}{{peer="{_quote_label(peer)}"}} {int(view["agree"])}'
            )
    if history is not None:
        snap = (history.snapshot() if hasattr(history, "snapshot")
                else dict(history))
        anomaly = snap.get("anomaly") or {}
        occ = snap.get("occupancy") or {}
        for m, value in (
            ("peritext_history_enabled", int(bool(snap.get("enabled")))),
            ("peritext_history_rounds", snap.get("rounds", 0)),
            ("peritext_history_sample_every", snap.get("sample_every", 1)),
            ("peritext_history_frames_sampled",
             snap.get("frames_sampled", 0)),
            ("peritext_history_frames_retained",
             snap.get("frames_retained", 0)),
            ("peritext_history_segments", snap.get("segments", 0)),
            ("peritext_history_anomalies_active",
             len(anomaly.get("active") or ())),
            ("peritext_history_anomalies_total", anomaly.get("total", 0)),
            ("peritext_history_occupancy_rows", occ.get("rows", 0)),
            ("peritext_history_sample_overhead_seconds",
             snap.get("overhead_seconds", 0.0)),
        ):
            lines.append(f"# TYPE {m} gauge")
            lines.append(f"{m} {_fmt(value)}")
        m = "peritext_history_tier_frames"
        lines.append(f"# TYPE {m} gauge")
        for tier, count in enumerate(snap.get("tier_frames") or ()):
            lines.append(f'{m}{{tier="{tier}"}} {_fmt(count)}')
        # by-key anomaly family, its OWN name (same no-double-count
        # rationale as peritext_serve_shed_reason_total)
        m = "peritext_history_anomaly_by_key"
        lines.append(f"# TYPE {m} counter")
        counts = anomaly.get("counts") or {}
        for key in sorted(counts):
            lines.append(
                f'{m}{{key="{_quote_label(key)}"}} {_fmt(counts[key])}'
            )
    if session is not None:
        health = session.health()
        for key in sorted(health):
            value = health[key]
            if isinstance(value, bool):
                value = int(value)
            if isinstance(value, (int, float)):
                m = _metric_name(f"session.{key}")
                lines.append(f"# TYPE {m} gauge")
                lines.append(f"{m} {_fmt(value)}")
        quarantined = health.get("quarantined")
        if isinstance(quarantined, dict):
            m = _metric_name("session.quarantined_docs")
            lines.append(f"# TYPE {m} gauge")
            lines.append(f"{m} {len(quarantined)}")
    return "\n".join(lines) + "\n"


class _Handler(BaseHTTPRequestHandler):
    server_version = "peritext-obs"

    def do_GET(self) -> None:  # noqa: N802 - BaseHTTPRequestHandler API
        routes: Dict[str, Tuple[Callable[[], str], str]] = self.server._routes  # type: ignore[attr-defined]
        path, _, query = self.path.partition("?")
        entry = routes.get(path)
        if entry is None:
            self.send_error(404)
            return
        fn, content_type = entry
        try:
            if getattr(fn, "accepts_query", False):
                # last value wins per key, keys visited sorted — a scrape
                # with duplicate params must parse deterministically
                params = {k: v[-1]
                          for k, v in sorted(parse_qs(query).items())}
                body = fn(params).encode("utf-8")
            else:
                body = fn().encode("utf-8")
        except Exception as exc:  # graftlint: boundary(an exporter endpoint answers 500, never kills the serving thread)
            # typed JSON error body: which plane broke + why — a sick
            # plane must not turn a fleet scrape into a traceback page
            stem = path.rsplit("/", 1)[-1]
            if stem.endswith(".json"):
                stem = stem[:-5]
            err = json.dumps({"error": str(exc), "plane": stem or "metrics"})
            body = err.encode("utf-8")
            self.send_response(500)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        self.send_response(200)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *args) -> None:  # scrapes must not spam stderr
        pass


class MetricsServer:
    """Threaded HTTP exporter for one host's telemetry (see module doc)."""

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        counters: Optional[Counters] = None,
        histograms: Optional[HistogramRegistry] = None,
        session=None,
        tracer=None,
        recorder=None,
        sentinel=None,
        convergence=None,
        devprof=None,
        serve=None,
        fleet=None,
        plan=None,
        latency=None,
        incidents=None,
        history=None,
    ) -> None:
        def metrics() -> str:
            return prometheus_text(
                counters=counters, histograms=histograms,
                session=session, sentinel=sentinel, convergence=convergence,
                devprof=devprof, serve=serve, fleet=fleet, plan=plan,
                latency=latency, incidents=incidents, history=history,
            )

        def snapshot() -> str:
            return json.dumps(
                health_snapshot(
                    counters=counters, session=session, sentinel=sentinel,
                    histograms=histograms, recorder=recorder,
                    convergence=convergence, devprof=devprof, serve=serve,
                    fleet=fleet, plan=plan, latency=latency,
                    incidents=incidents, history=history,
                ),
                default=str,
            )

        routes: Dict[str, Tuple[Callable[[], str], str]] = {
            "/metrics": (metrics, "text/plain; version=0.0.4; charset=utf-8"),
            "/health.json": (snapshot, "application/json"),
        }
        if tracer is not None:
            routes["/trace.json"] = (
                lambda: json.dumps(tracer.chrome_trace()),
                "application/json",
            )
        if convergence is not None:
            routes["/convergence.json"] = (
                lambda: json.dumps(convergence.snapshot()),
                "application/json",
            )
        if devprof is not None:
            routes["/devprof.json"] = (
                lambda: json.dumps(devprof.snapshot()),
                "application/json",
            )
        if serve is not None:
            routes["/serve.json"] = (
                lambda: json.dumps(serve.snapshot()),
                "application/json",
            )
        if fleet is not None:
            routes["/fleet.json"] = (
                lambda: json.dumps(fleet.snapshot()),
                "application/json",
            )
        if plan is not None:
            routes["/plan.json"] = (
                lambda: json.dumps(
                    plan.to_json() if hasattr(plan, "to_json")
                    else dict(plan)
                ),
                "application/json",
            )
        if latency is not None:
            routes["/latency.json"] = (
                lambda: json.dumps(latency.snapshot()),
                "application/json",
            )
        if incidents is not None:
            routes["/incidents.json"] = (
                lambda: json.dumps(incidents.snapshot()),
                "application/json",
            )
        if history is not None:
            def timeseries(params: Optional[Dict[str, str]] = None) -> str:
                return json.dumps(
                    query_snapshot(history.snapshot(), params or {}),
                    default=str,
                )

            # opt into the handler's query-string dispatch
            timeseries.accepts_query = True  # type: ignore[attr-defined]
            routes["/timeseries.json"] = (timeseries, "application/json")
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self._httpd._routes = routes  # type: ignore[attr-defined]
        self.address: Tuple[str, int] = self._httpd.server_address[:2]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> Tuple[str, int]:
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True
        )
        self._thread.start()
        return self.address

    def stop(self) -> None:
        if self._thread is None:
            # never started: shutdown() would block forever waiting for a
            # serve_forever() loop that doesn't exist — just release the port
            self._httpd.server_close()
            return
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)
        self._thread = None
