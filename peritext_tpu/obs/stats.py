"""Per-merge / per-round observability report."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict


@dataclass
class MergeStats:
    """Per-merge observability (attached to ``api.batch.MergeReport``, and —
    per streaming commit — to ``StreamingMerge.last_round_stats``)."""

    docs: int = 0
    device_docs: int = 0
    fallback_docs: int = 0
    device_ops: int = 0
    fallback_ops: int = 0
    encode_seconds: float = 0.0
    apply_seconds: float = 0.0
    resolve_seconds: float = 0.0
    decode_seconds: float = 0.0
    #: real ops / padded op-stream capacity across the batch (0..1)
    padding_efficiency: float = 0.0
    extras: Dict[str, float] = field(default_factory=dict)

    @property
    def total_seconds(self) -> float:
        return (
            self.encode_seconds
            + self.apply_seconds
            + self.resolve_seconds
            + self.decode_seconds
        )

    @property
    def device_ops_per_sec(self) -> float:
        wall = self.apply_seconds
        return self.device_ops / wall if wall > 0 else 0.0

    def to_json(self) -> Dict[str, Any]:
        return {
            "docs": self.docs,
            "device_docs": self.device_docs,
            "fallback_docs": self.fallback_docs,
            "device_ops": self.device_ops,
            "fallback_ops": self.fallback_ops,
            "encode_seconds": round(self.encode_seconds, 6),
            "apply_seconds": round(self.apply_seconds, 6),
            "resolve_seconds": round(self.resolve_seconds, 6),
            "decode_seconds": round(self.decode_seconds, 6),
            "padding_efficiency": round(self.padding_efficiency, 4),
            "device_ops_per_sec": round(self.device_ops_per_sec, 1),
            **self.extras,
        }
