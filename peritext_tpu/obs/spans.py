"""Structured pipeline spans with cross-host trace propagation.

A :class:`Tracer` produces NESTED spans with monotonic ids over the merge
pipeline (``ingest → encode → device-apply → resolve → decode →
patch-scatter``, plus anti-entropy and guarded supervisor rounds) and
serializes them as Perfetto-compatible Chrome trace-event JSON
(``chrome://tracing`` / https://ui.perfetto.dev load it directly).

Cross-host correlation: a span's :class:`TraceContext` — a compact
``(trace_id, span_id)`` pair — rides the anti-entropy wire (frontier
sentinels + codec frame v5, see ``parallel/codec.py``), and a receiving
host opens its handler span with ``ctx=`` so both hosts' spans share ONE
trace id in the merged trace (:func:`merge_traces`).

Instrumentation contract: ``tracer.span(...)`` ALWAYS measures (a pair of
clock reads, ~100 ns) so callers can read ``span.duration`` for stats even
when nothing is exporting; spans are only RETAINED when the tracer is
enabled (bounded buffer, for the Perfetto dump) or has sinks (e.g. a
:class:`~.recorder.FlightRecorder` ring).  Merge-scope modules never read
the wall clock themselves — the reads live here, in the observability
layer, keeping graftlint's PTL006 merge scope clean.
"""

from __future__ import annotations

import contextlib
import json
import os
import socket
import threading
import time
import zlib
from collections import deque
from typing import Dict, Iterator, List, NamedTuple, Optional


class TraceContext(NamedTuple):
    """The compact wire-carried correlation pair: which trace a remote
    span belongs to, and which span is its parent."""

    trace_id: int
    span_id: int


class Span:
    """One finished (or in-flight) pipeline stage."""

    __slots__ = ("name", "trace_id", "span_id", "parent_id", "host", "args",
                 "ts", "duration", "tid")

    def __init__(self, name: str, trace_id: int, span_id: int, parent_id: int,
                 host: str, args: Dict, ts: float) -> None:
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.host = host
        self.args = args
        self.ts = ts  # epoch seconds at span start (cross-host alignable)
        self.duration = 0.0  # wall seconds, set at span exit
        self.tid = threading.get_ident()

    @property
    def context(self) -> TraceContext:
        return TraceContext(self.trace_id, self.span_id)

    def to_event(self) -> Dict:
        """One Chrome trace-event (complete event, ``ph: "X"``)."""
        return {
            "name": self.name,
            "cat": "peritext",
            "ph": "X",
            "ts": int(self.ts * 1e6),
            "dur": max(1, int(self.duration * 1e6)),
            "pid": _host_pid(self.host),
            "tid": self.tid & 0xFFFFFFFF,
            "args": {
                "trace_id": f"{self.trace_id:016x}",
                "span_id": self.span_id,
                "parent_id": self.parent_id,
                "host": self.host,
                **_jsonable(self.args),
            },
        }

    def to_json(self) -> Dict:
        """Flat record for the flight-recorder JSONL form."""
        return {
            "name": self.name,
            "host": self.host,
            "trace_id": f"{self.trace_id:016x}",
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "start_ts": self.ts,
            "duration_s": round(self.duration, 6),
            "args": _jsonable(self.args),
        }

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (f"Span({self.name!r}, trace={self.trace_id:#x}, "
                f"id={self.span_id}, dur={self.duration:.6f}s)")


def _jsonable(args: Dict) -> Dict:
    return {k: v if isinstance(v, (str, int, float, bool, type(None))) else str(v)
            for k, v in args.items()}


def _host_pid(host: str) -> int:
    """Stable small int per host label (Chrome's pid field)."""
    return zlib.crc32(host.encode("utf-8")) & 0x7FFFFFFF


def _mint_trace_id() -> int:
    """63-bit trace id.  Entropy is fine here: trace ids are telemetry
    labels, never merge inputs (DESIGN.md "Telemetry")."""
    return (int.from_bytes(os.urandom(8), "big") >> 1) or 1


#: ONE active-span stack per thread, shared across tracer instances, so a
#: span opened by a transport tracer parents the session tracer's ingest
#: spans on the same thread (cross-component linkage)
_ACTIVE = threading.local()


def _stack() -> list:
    stack = getattr(_ACTIVE, "spans", None)
    if stack is None:
        stack = _ACTIVE.spans = []
    return stack


def current_span() -> Optional[Span]:
    """The innermost span open on this thread (any tracer), or None."""
    stack = _stack()
    return stack[-1] if stack else None


@contextlib.contextmanager
def ambient_parent(span: Optional[Span]) -> Iterator[None]:
    """Propagate ``span`` across a thread boundary: while active, spans
    opened on THIS thread parent under it (the thread-local stack does not
    cross threads by itself).  The supervisor uses this so a guarded
    round's stage spans nest under ``supervisor.round`` even though the
    round body runs on the watchdog worker thread.  ``None`` is a no-op."""
    if span is None:
        yield
        return
    stack = _stack()
    stack.append(span)
    try:
        yield
    finally:
        if stack and stack[-1] is span:
            stack.pop()
        else:  # pragma: no cover - unbalanced exit
            try:
                stack.remove(span)
            except ValueError:
                pass


class Tracer:
    """Produces spans; retains them (bounded) when ``enabled``; pushes each
    finished span to registered sinks either way."""

    def __init__(self, host: Optional[str] = None, enabled: bool = False,
                 trace_id: Optional[int] = None, capacity: int = 65536) -> None:
        self.host = host or f"{socket.gethostname()}/{os.getpid()}"
        self.enabled = enabled
        self.trace_id = int(trace_id) if trace_id is not None else _mint_trace_id()
        self._lock = threading.Lock()
        # span ids are monotonic per tracer ABOVE a random 48-bit-shifted
        # base: two hosts whose spans share one trace id (wire-carried
        # context) must not mint colliding ids, or parent links in a merged
        # trace become ambiguous
        self._id_base = int.from_bytes(os.urandom(6), "big") << 14
        self._next_id = 1
        self._spans: deque = deque(maxlen=capacity)
        self._sinks: List = []

    # -- lifecycle / wiring --------------------------------------------------

    def enable(self) -> "Tracer":
        self.enabled = True
        return self

    def disable(self) -> None:
        self.enabled = False

    def active(self) -> bool:
        return self.enabled or bool(self._sinks)

    def add_sink(self, sink) -> None:
        """``sink(span)`` is called with every finished span (e.g. a
        FlightRecorder's ``record_span``)."""
        with self._lock:
            if sink not in self._sinks:
                self._sinks.append(sink)

    def remove_sink(self, sink) -> None:
        with self._lock:
            if sink in self._sinks:
                self._sinks.remove(sink)

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()

    # -- span production -----------------------------------------------------

    @contextlib.contextmanager
    def span(self, name: str, ctx: Optional[TraceContext] = None,
             **args) -> Iterator[Span]:
        """Open one nested span.  ``ctx`` adopts a wire-carried remote
        context (the span joins the REMOTE trace as a child of the remote
        span); otherwise the span nests under this thread's innermost open
        span, or roots a new span under the tracer's own trace id."""
        parent = current_span()
        if ctx is not None:
            trace_id, parent_id = int(ctx[0]), int(ctx[1])
        elif parent is not None:
            trace_id, parent_id = parent.trace_id, parent.span_id
        else:
            trace_id, parent_id = self.trace_id, 0
        with self._lock:
            span_id = self._id_base + self._next_id
            self._next_id += 1
        sp = Span(name, trace_id, span_id, parent_id, self.host,
                  dict(args), time.time())
        t0 = time.perf_counter()
        stack = _stack()
        stack.append(sp)
        try:
            yield sp
        except BaseException as exc:  # graftlint: boundary(annotate the span with the escaping error for the timeline; always re-raised)
            sp.args.setdefault("error", repr(exc))
            raise
        finally:
            sp.duration = time.perf_counter() - t0
            if stack and stack[-1] is sp:
                stack.pop()
            else:  # pragma: no cover - unbalanced exit (generator misuse)
                try:
                    stack.remove(sp)
                except ValueError:
                    pass
            if self.enabled:
                with self._lock:
                    self._spans.append(sp)
            for sink in list(self._sinks):
                try:
                    sink(sp)
                except Exception:  # graftlint: boundary(telemetry sinks must never fail the traced workload)
                    pass

    def current_context(self) -> Optional[TraceContext]:
        """The context of this thread's innermost open span, for stamping
        onto outbound wire frames."""
        sp = current_span()
        return sp.context if sp is not None else None

    # -- export --------------------------------------------------------------

    def spans(self) -> List[Span]:
        with self._lock:
            return list(self._spans)

    def chrome_trace(self) -> Dict:
        """Perfetto/Chrome trace-event JSON for every retained span."""
        spans = self.spans()
        events: List[Dict] = []
        for host in sorted({sp.host for sp in spans}):
            events.append({
                "name": "process_name", "ph": "M", "pid": _host_pid(host),
                "tid": 0, "args": {"name": host},
            })
        events.extend(sp.to_event() for sp in spans)
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def write_chrome_trace(self, path) -> None:
        with open(path, "w") as f:
            json.dump(self.chrome_trace(), f)


def merge_traces(*traces: Dict) -> Dict:
    """Merge several ``chrome_trace()`` dicts (or bare event lists) into one
    trace — the per-host dumps of a cross-host exchange view as a single
    timeline because the wire-carried context gave them one trace id."""
    events: List[Dict] = []
    for t in traces:
        events.extend(t.get("traceEvents", []) if isinstance(t, dict) else t)
    events.sort(key=lambda e: (e.get("ph") != "M", e.get("ts", 0)))
    return {"traceEvents": events, "displayTimeUnit": "ms"}


#: default process-wide tracer: inactive (spans still measure, nothing is
#: retained) until a caller enables it or attaches a sink
GLOBAL_TRACER = Tracer()
