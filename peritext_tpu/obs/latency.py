"""Time-to-visibility latency plane: stage watermarks per drain batch.

The serving tier gates on p99 *apply* latency, but the SLO a client feels
is **time-to-visibility**: submit → admission verdict → window wait →
stage → fused device commit → the first read that exposes the patch.
This module is the low-overhead decomposition of that journey.

**Stage taxonomy** (:data:`STAGES`, telescoping watermark diffs):

* ``admit``      — submit entry → admission verdict + enqueue
  (``serve/admission.py`` verdict time);
* ``window``     — enqueue → round-open window close (the batching dial;
  close cause ∈ {``window``, ``backpressure``, ``flush``});
* ``stage``      — window close → frames bulk-ingested into the session's
  staging buffers (``serve/mux.py`` ``_ingest_batch``);
* ``dispatch``   — staged → host dispatch of the fused device program
  (the drain wall MINUS its measured apply-dispatch span — the schedule /
  upload / program-build half of ``parallel/staging.py`` +
  ``parallel/streaming.py``);
* ``commit``     — the apply-dispatch span itself (streaming's
  ``streaming.apply`` spans accumulated into ``last_drain_marks``);
* ``visibility`` — commit → the first ``patches()``/``read()`` that
  exposes the committed round (the ``prefetch_digest`` readback seam).

**Sampling policy**: one compact :class:`dict` record per DRAIN BATCH
(never per op), anchored on the batch's first-enqueued frame — the op
that waited the whole window, i.e. the worst case an SLO cares about.
``sample_every=N`` decimates further.  Everything is a few clock reads
and one dict per committed window, which keeps the enabled overhead
inside the devprof <2% budget (pinned by ``scripts/latency_smoke.py``).

**Determinism contract**: the plane lives in ``obs/`` and is fed clock
watermarks by the SERVE tier only.  Merge-scope modules
(``parallel/streaming.py``, ``parallel/staging.py``) contribute span
DURATIONS (``last_drain_marks``), never wall-clock reads — graftlint's
PTL006 merge scope stays clean.

Sum-consistency holds by construction: the five server-side stage
durations are telescoping differences of monotonic watermarks, so they
are each nonnegative and sum exactly to ``commit − submit``
(:func:`check_sum_consistency`; asserted in-row by the serve bench rows
and across layouts by the tests).

**Attribution** (:func:`attribute`, ``python -m peritext_tpu.obs why``):
when the perf-ledger gate fails, diff the failing row's latest per-stage
decomposition against its rolling reference (median per stage over the
prior matching records) plus the devprof shape-bucket/occupancy deltas,
and deterministically name the dominant moved stage — largest positive
delta, ties broken by taxonomy order (earliest stage wins).
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Any, Dict, List, Optional, Sequence

from .histograms import Histogram

#: the stage taxonomy, in pipeline order.  Attribution tie-breaks walk
#: this tuple front to back, so the order IS the determinism contract.
STAGES = ("admit", "window", "stage", "dispatch", "commit", "visibility")

#: server-side stages (watermark diffs; sum to ``commit − submit``)
SERVER_STAGES = STAGES[:-1]

#: typed window-close causes — the vocabulary the mux, the fused group
#: and the exporters share
CLOSE_WINDOW = "window"
CLOSE_BACKPRESSURE = "backpressure"
CLOSE_FLUSH = "flush"
CLOSE_CAUSES = (CLOSE_WINDOW, CLOSE_BACKPRESSURE, CLOSE_FLUSH)


def check_sum_consistency(record: Dict[str, Any], *, tol: float = 1e-6,
                          client_wall: Optional[float] = None) -> bool:
    """The plane's core invariant on one sampled record: every stage
    nonnegative, the server-side stages summing to the record's total
    (``commit − submit``) within float tolerance, and — when the client's
    own observed wall is supplied — the server-side sum never exceeding
    what the client saw (plus ``tol`` slack for the clock reads between
    the two measurements)."""
    stages = record.get("stages") or {}
    if any(d < 0 for d in stages.values()):
        return False
    total = record.get("total", 0.0)
    # the server-side stages telescope to commit − submit == total; the
    # visibility stage (present once the record is finalized) sits ON TOP
    # of total (total + visibility == time_to_visibility)
    server_sum = sum(stages.get(s, 0.0) for s in SERVER_STAGES)
    if abs(server_sum - total) > tol:
        return False
    if client_wall is not None:
        # the anchor frame's client-observed latency starts at its
        # enqueue (the admit watermark), so compare against the post-admit
        # portion of the server sum
        if total - stages.get("admit", 0.0) > client_wall + tol:
            return False
    return True


class LatencyPlane:
    """The stage-watermark latency plane (see module doc).

    Off by default — arming is ``plane.enable()`` (the devprof pattern:
    ``GLOBAL_LATENCY.enable()`` arms every serve-tier hook at once).  One
    :meth:`observe_batch` per committed drain window feeds the per-stage
    histograms; :meth:`mark_visible` (called by the mux's read surface)
    finalizes pending records with the visibility stage.  Thread-safe.

    ``slo_seconds``/``slo_target`` parameterize the burn-rate gauge: the
    fraction of the rolling window's commit totals violating
    ``slo_seconds``, divided by the error budget ``1 − slo_target`` —
    burn rate 1.0 = exactly spending the budget, >1 = burning it down.
    """

    def __init__(
        self,
        sample_every: int = 1,
        slo_seconds: float = 0.25,
        slo_target: float = 0.99,
        slo_window: int = 256,
        pending_cap: int = 512,
    ) -> None:
        if sample_every < 1:
            raise ValueError(
                f"sample_every must be >= 1, got {sample_every}"
            )
        if not 0.0 < slo_target < 1.0:
            raise ValueError(
                f"slo_target must be in (0, 1), got {slo_target}"
            )
        self.enabled = False
        self.sample_every = int(sample_every)
        self.slo_seconds = float(slo_seconds)
        self.slo_target = float(slo_target)
        self._lock = threading.Lock()
        #: per-stage duration histograms + the end-to-end families; the
        #: plane owns PRIVATE histograms (not GLOBAL_HISTOGRAMS) so
        #: enabling it for one bench arm never pollutes another's registry
        self.hists: Dict[str, Histogram] = {
            **{stage: Histogram() for stage in STAGES},
            "total": Histogram(),
            "time_to_visibility": Histogram(),
        }
        self._windows_seen = 0
        self.records = 0
        #: sampled records awaiting their first exposing read; bounded —
        #: an unread backlog evicts oldest-first into ``never_read``
        self._pending: deque = deque()
        self._pending_cap = int(pending_cap)
        self.never_read = 0
        self.force_close: Dict[str, int] = {c: 0 for c in CLOSE_CAUSES}
        #: rolling commit totals behind the SLO burn-rate gauge
        self._slo_ring: deque = deque(maxlen=int(slo_window))
        self.max_shards = 1
        self.last: Optional[Dict[str, Any]] = None

    # -- arming ------------------------------------------------------------

    def enable(self) -> "LatencyPlane":
        self.enabled = True
        return self

    def disable(self) -> None:
        self.enabled = False

    def __enter__(self) -> "LatencyPlane":
        return self.enable()

    def __exit__(self, *exc) -> None:
        self.disable()

    def reset(self) -> None:
        with self._lock:
            for h in self.hists:
                self.hists[h] = Histogram()
            self._windows_seen = 0
            self.records = 0
            self._pending.clear()
            self.never_read = 0
            self.force_close = {c: 0 for c in CLOSE_CAUSES}
            self._slo_ring.clear()
            self.max_shards = 1
            self.last = None

    # -- the serve tier's feed ---------------------------------------------

    def observe_batch(
        self,
        *,
        submit: float,
        admit: float,
        close: float,
        staged: float,
        commit: float,
        marks: Optional[Dict[str, float]] = None,
        cause: str = CLOSE_WINDOW,
        batch: int = 1,
        shards: int = 1,
    ) -> Optional[Dict[str, Any]]:
        """Record one committed drain window from its stage watermarks
        (monotonic clock reads, all taken by the serve tier) plus the
        session's span-derived ``last_drain_marks``.  Applies the
        sampling policy; returns the sampled record or None when this
        window was decimated.  The watermarks anchor on the batch's
        FIRST-enqueued frame (worst case — see module doc)."""
        with self._lock:
            self._windows_seen += 1
            self.force_close[cause] = self.force_close.get(cause, 0) + 1
            if (self._windows_seen - 1) % self.sample_every:
                return None
            admit_d = max(0.0, admit - submit)
            window_d = max(0.0, close - admit)
            stage_d = max(0.0, staged - close)
            span = max(0.0, commit - staged)
            apply_s = float((marks or {}).get("apply_seconds", span))
            commit_d = min(max(0.0, apply_s), span)
            dispatch_d = span - commit_d
            stages = {
                "admit": admit_d,
                "window": window_d,
                "stage": stage_d,
                "dispatch": dispatch_d,
                "commit": commit_d,
            }
            total = sum(stages.values())
            self.records += 1
            self.max_shards = max(self.max_shards, int(shards))
            record = {
                "seq": self.records,
                "submit": submit,
                "commit": commit,
                "stages": stages,
                "total": total,
                "cause": cause,
                "batch": int(batch),
                "shards": int(shards),
                "rounds": int((marks or {}).get("rounds", 0)),
                "visible": None,
                "time_to_visibility": None,
            }
            for stage, d in stages.items():
                self.hists[stage].observe(d)
            self.hists["total"].observe(total)
            self._slo_ring.append(total)
            self._pending.append(record)
            while len(self._pending) > self._pending_cap:
                self._pending.popleft()
                self.never_read += 1
            self.last = record
            return record

    def mark_visible(self, now: float) -> int:
        """Finalize every pending record with ``now`` as its visibility
        watermark — called by the mux's read surface (``patches()`` /
        ``read()``) at the FIRST read after a commit, i.e. the moment a
        client could actually observe the committed round.  Returns how
        many records were finalized (0 when none were pending: repeat
        reads between commits are free)."""
        with self._lock:
            n = len(self._pending)
            while self._pending:
                rec = self._pending.popleft()
                vis = max(0.0, now - rec["commit"])
                rec["visible"] = now
                rec["stages"]["visibility"] = vis
                rec["time_to_visibility"] = rec["total"] + vis
                self.hists["visibility"].observe(vis)
                self.hists["time_to_visibility"].observe(
                    rec["time_to_visibility"]
                )
            return n

    # -- readout -----------------------------------------------------------

    def slo(self) -> Dict[str, Any]:
        """The burn-rate gauge body (also a ``peritext_latency_*`` gauge
        family): violations over the rolling window / the error budget."""
        with self._lock:
            ring = list(self._slo_ring)
        violations = sum(1 for t in ring if t > self.slo_seconds)
        frac = violations / len(ring) if ring else 0.0
        budget = 1.0 - self.slo_target
        return {
            "slo_seconds": self.slo_seconds,
            "target": self.slo_target,
            "window": len(ring),
            "violations": violations,
            "violating_frac": round(frac, 6),
            "burn_rate": round(frac / budget, 4) if budget else 0.0,
        }

    def snapshot(self) -> Dict[str, Any]:
        """The ``/latency.json`` body (golden-shape test pins these keys):
        arming + sampling state, the per-stage histogram snapshots, the
        end-to-end families, the SLO burn gauge, close causes, fan-out."""
        with self._lock:
            pending = len(self._pending)
            last = dict(self.last) if self.last is not None else None
        return {
            "enabled": self.enabled,
            "sample_every": self.sample_every,
            "windows": self._windows_seen,
            "records": self.records,
            "pending_visibility": pending,
            "never_read": self.never_read,
            "shards": self.max_shards,
            "force_close": dict(self.force_close),
            "stages": {s: self.hists[s].snapshot() for s in STAGES},
            "total": self.hists["total"].snapshot(),
            "time_to_visibility": self.hists["time_to_visibility"].snapshot(),
            "slo": self.slo(),
            "last": last,
        }

    def decomposition(self) -> Dict[str, Any]:
        """The per-stage decomposition a bench ladder row persists (and
        ``obs why`` diffs): mean milliseconds per stage over the sampled
        records, the end-to-end means, and the consistency evidence."""
        def mean_ms(name: str) -> Optional[float]:
            h = self.hists[name]
            return round(h.sum / h.count * 1e3, 4) if h.count else None

        stages_ms = {
            s: mean_ms(s) for s in STAGES if self.hists[s].count
        }
        with self._lock:
            last = self.last
            consistent = (
                check_sum_consistency(last) if last is not None else True
            )
        return {
            "stages_ms": stages_ms,
            "total_ms": mean_ms("total"),
            "time_to_visibility_ms": mean_ms("time_to_visibility"),
            "records": self.records,
            "never_read": self.never_read,
            "shards": self.max_shards,
            "force_close": dict(self.force_close),
            "slo_burn_rate": self.slo()["burn_rate"],
            "sum_consistent": consistent,
        }


#: default process-wide plane — off until ``GLOBAL_LATENCY.enable()``
#: (the GLOBAL_DEVPROF pattern: every serve-tier hook checks ``enabled``)
GLOBAL_LATENCY = LatencyPlane()


# -- attribution: obs why -----------------------------------------------------


def _devprof_shape(dp: Optional[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
    """Collapse a devprof snapshot to the three deltas attribution cites:
    total distinct compiled shapes, total dispatches, padding waste."""
    if not isinstance(dp, dict):
        return None
    sites = dp.get("sites") or {}
    occ = dp.get("occupancy_totals") or {}
    return {
        "distinct_shapes": sum(
            int(r.get("distinct_shapes", 0)) for r in sites.values()
        ),
        "dispatches": sum(
            int(r.get("dispatches", 0)) for r in sites.values()
        ),
        "padding_waste": occ.get("padding_waste"),
    }


def attribute(
    records: Sequence[Dict[str, Any]],
    *,
    row: Optional[str] = None,
    window: Optional[int] = None,
    match: str = "device",
    tolerance: Optional[float] = None,
) -> Dict[str, Any]:
    """The ``obs why`` engine: judge the ledger's last record with the
    perf gate, then explain WHAT moved.

    Picks the failing row (or ``row`` explicitly), diffs its per-stage
    ``latency`` decomposition against the per-stage MEDIAN over the prior
    matching records, attaches the devprof shape/occupancy deltas, and
    names the dominant moved stage: the largest positive per-stage delta,
    ties broken by :data:`STAGES` order (earliest wins) — same inputs,
    same verdict, always.

    Verdicts: ``clean`` (gate passes), ``regression-attributed`` (a stage
    moved up), ``regression-unattributed`` (a regression whose
    decomposition shows no stage moving — look outside the latency
    plane), ``no-decomposition`` (candidate or reference rows carry no
    ``latency`` — the gate's old exit-1-and-shrug).
    """
    from . import ledger as _ledger

    window = window if window is not None else _ledger.DEFAULT_WINDOW
    report = _ledger.evaluate(
        records, tolerance=tolerance, window=window, match=match,
    )
    verdicts = report["rows"]
    target = None
    if row is not None:
        target = next((v for v in verdicts if v["row"] == row), None)
        if target is None:
            raise ValueError(f"row {row!r} not in the candidate record")
    else:
        bad = [v for v in verdicts
               if v["status"] in ("regressed", "failed", "missing")]
        # prefer a failing row that CAN be decomposed; deterministic:
        # verdict order is the candidate record's row order
        target = next((v for v in bad if v.get("latency")), None) \
            or (bad[0] if bad else None)
    out: Dict[str, Any] = {
        "regressed": bool(report["regressed"]),
        "candidate": report["candidate"],
        "reference_records": report["reference_records"],
        "rows": verdicts,
    }
    if target is None:
        out.update(verdict="clean", row=None)
        return out
    out.update(
        row=target["row"], status=target["status"], unit=target["unit"],
        value=target["value"], ref=target["ref"],
        delta=target.get("delta"), delta_pct=target.get("delta_pct"),
    )

    candidate = records[-1]
    cand_config = candidate.get("config")
    cand_dev = candidate.get("device")
    crow = next(
        (r for r in candidate.get("rows", []) if r.get("row") == target["row"]),
        None,
    )
    cand_lat = (crow or {}).get("latency")
    cand_stages = (
        cand_lat.get("stages_ms") if isinstance(cand_lat, dict) else None
    )
    ident = (
        _ledger._row_identity(cand_config, crow) if crow is not None else None
    )
    level = _ledger._match_level((crow or {}).get("unit") or "", match)
    priors = [r for r in records[:-1]
              if _ledger._device_matches(r.get("device"), cand_dev, level)]
    ref_lats = [
        pr["latency"]
        for rec in priors
        for pr in rec.get("rows", [])
        if _ledger._row_identity(rec.get("config"), pr) == ident
        and isinstance(pr.get("latency"), dict)
        and isinstance(pr["latency"].get("stages_ms"), dict)
    ][-window:]
    ref_stages: Dict[str, float] = {}
    for stage in STAGES:
        vals = [
            float(rl["stages_ms"][stage]) for rl in ref_lats
            if isinstance(rl["stages_ms"].get(stage), (int, float))
        ]
        if vals:
            ref_stages[stage] = round(_ledger._median(vals), 4)
    out["reference_latency_records"] = len(ref_lats)
    out["candidate_stages_ms"] = cand_stages
    out["reference_stages_ms"] = ref_stages or None

    # devprof evidence: candidate snapshot vs the newest prior that has one
    cand_dp = _devprof_shape(candidate.get("devprof"))
    ref_dp = next(
        (_devprof_shape(r.get("devprof")) for r in reversed(priors)
         if _devprof_shape(r.get("devprof")) is not None),
        None,
    )
    if cand_dp is not None and ref_dp is not None:
        delta_dp = {}
        for key in ("distinct_shapes", "dispatches", "padding_waste"):
            a, b = cand_dp.get(key), ref_dp.get(key)
            delta_dp[key] = (
                round(a - b, 6) if isinstance(a, (int, float))
                and isinstance(b, (int, float)) else None
            )
        out["devprof"] = {
            "candidate": cand_dp, "reference": ref_dp, "delta": delta_dp,
        }
    else:
        out["devprof"] = None

    if not cand_stages or not ref_stages:
        out.update(verdict="no-decomposition", dominant_stage=None,
                   stage_deltas_ms=None)
        return out
    deltas = {
        s: round(float(cand_stages[s]) - ref_stages[s], 4)
        for s in STAGES if s in cand_stages and s in ref_stages
    }
    dominant = None
    best = 0.0
    for s in STAGES:  # taxonomy order: strict > keeps the EARLIEST on ties
        d = deltas.get(s)
        if d is not None and d > best:
            best, dominant = d, s
    out["stage_deltas_ms"] = deltas
    out["dominant_stage"] = dominant
    out["verdict"] = (
        "regression-attributed" if dominant is not None
        else "regression-unattributed"
    )
    return out
