"""Structured event logging and JAX profiler hooks."""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from pathlib import Path
from typing import Any, Dict, IO, Iterator, Optional


class EventLog:
    """Append-only structured event stream.

    Events are plain dicts with a ``kind``; every record gets a monotonic
    sequence number and a wall-clock timestamp.  Optionally tees each record
    to a JSON-lines file (``fsync=True`` additionally fsyncs per record —
    the flight-recorder-grade durability mode).  Usable directly as an
    ``Editor.on_event`` sink, and as a context manager (``with EventLog(p)
    as log: ...`` closes the file on exit).

    Construction is leak-safe: the tee file is opened first, and any
    failure in the remainder of ``__init__`` (e.g. an invalid capacity)
    closes it before re-raising — a half-constructed log never strands an
    open handle.
    """

    def __init__(self, path: Optional[str | Path] = None,
                 capacity: Optional[int] = 10000,
                 fsync: bool = False):
        self._file: Optional[IO[str]] = None
        f: Optional[IO[str]] = open(path, "a") if path is not None else None
        try:
            if capacity is not None and capacity <= 0:
                raise ValueError(
                    f"capacity must be positive or None, got {capacity}"
                )
            self._lock = threading.Lock()
            self._events: list = []
            self._seq = 0
            self.capacity = capacity
            self.fsync = bool(fsync)
            self._file = f
        except BaseException:  # graftlint: boundary(close-on-error: the handle must not leak when init fails; always re-raised)
            if f is not None:
                f.close()
            raise

    def emit(self, kind: str, **fields: Any) -> Dict[str, Any]:
        record = {"seq": None, "ts": time.time(), "kind": kind, **fields}
        with self._lock:
            self._seq += 1
            record["seq"] = self._seq
            self._events.append(record)
            if self.capacity is not None and len(self._events) > self.capacity:
                self._events = self._events[-self.capacity :]
            if self._file is not None:
                self._file.write(json.dumps(record, default=str) + "\n")
                self._file.flush()
                if self.fsync:
                    os.fsync(self._file.fileno())
        return record

    # Editor.on_event sink (bridge.EditorEvent)
    def __call__(self, editor_event) -> None:
        self.emit(
            f"editor.{editor_event.kind}", actor=editor_event.actor, **editor_event.detail
        )

    def events(self, kind: Optional[str] = None) -> list:
        with self._lock:
            evs = list(self._events)
        return [e for e in evs if kind is None or e["kind"] == kind] if kind else evs

    def close(self) -> None:
        if self._file is not None:
            self._file.close()
            self._file = None

    def __enter__(self) -> "EventLog":
        return self

    def __exit__(self, *exc_info) -> None:
        self.close()


@contextlib.contextmanager
def profile_trace(log_dir: str | Path, enabled: bool = True) -> Iterator[None]:
    """Capture a JAX profiler trace (viewable in TensorBoard / Perfetto) for
    the enclosed block.  Silently degrades to a no-op if the profiler is
    unavailable on the current platform."""
    if not enabled:
        yield
        return
    try:
        import jax

        jax.profiler.start_trace(str(log_dir))
        started = True
    except Exception:  # graftlint: boundary(profiler availability is platform-defined; tracing must never fail the traced workload)
        started = False
    try:
        yield
    finally:
        if started:
            try:
                jax.profiler.stop_trace()
            except Exception:  # graftlint: boundary(stop mirrors start: a torn trace is dropped, never raised into the workload)
                pass
