"""Convergence observability: per-peer replication-lag watermarks and
divergence probes.

Peritext's correctness story is *convergence* — replicas that have seen the
same changes read back byte-identical documents — but until this module the
fleet could not SEE convergence: ``try_sync_with`` surfaced a peer as
``behind`` and forgot it, and the only divergence check was the offline
chaos oracle.  A :class:`ConvergenceMonitor` ingests every anti-entropy
frontier exchange (hooked into ``multihost.sync_with`` / ``_serve_one`` and
``anti_entropy.sync``) and maintains, per peer:

* **ops-behind** — the clock-delta sum ``Σ max(0, peer_seq - local_seq)``:
  how many changes the local store still lacks from that peer's frontier;
* **ops-ahead** — the mirror sum: how many changes the peer lacks from us;
* **staleness** — monitor rounds since the last CLEAN exchange with the
  peer (a reachable peer resets it every round; a partitioned peer's
  staleness grows until the partition heals);
* **divergence probes** — when two frontiers MATCH, the stores must hold
  identical change sets, so their commutative store digests
  (:meth:`~..parallel.anti_entropy.ChangeStore.digest`) must match too.
  ``same frontier + different digest`` is TRUE divergence — a corrupt
  merge, not mere lag — and is flagged as a first-class incident: a
  ``convergence.divergence_incidents`` counter tick plus a flight-recorder
  dump, never a plain ``behind``.

The monitor is pure telemetry: it never touches merge state, holds only
plain dicts/ints, and is cheap enough to ingest every exchange.  The
healing control loop that CONSUMES these watermarks is
:class:`~..parallel.gossip.GossipScheduler` (most-behind-first anti-entropy
priority after a partition heals).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

from .metrics import Counters, GLOBAL_COUNTERS

#: classification labels returned by :meth:`ConvergenceMonitor.observe_frontier`
CONVERGED = "converged"
LAG = "lag"
DIVERGENCE = "divergence"


def clock_delta_ops(local_clock: Dict[str, int],
                    peer_clock: Dict[str, int]) -> int:
    """Ops the LOCAL store lacks from ``peer_clock``'s frontier:
    ``Σ_actors max(0, peer_seq - local_seq)`` — the ops-behind watermark."""
    return sum(
        max(0, int(seq) - int(local_clock.get(actor, 0)))
        for actor, seq in peer_clock.items()
    )


def clocks_equal(a: Dict[str, int], b: Dict[str, int]) -> bool:
    """Frontier equality modulo zero entries (an actor never heard from is
    the same frontier as that actor at seq 0)."""
    return (
        {k: v for k, v in a.items() if v} == {k: v for k, v in b.items() if v}
    )


@dataclass
class PeerLag:
    """One peer's replication-lag watermarks (all telemetry; see module doc)."""

    peer: str
    #: current ops-behind estimate: the clock-delta sum at the last observed
    #: frontier, zeroed by a clean full exchange (the pull drained it)
    ops_behind: int = 0
    #: the mirror watermark: ops the peer lacked from us at last observation
    ops_ahead: int = 0
    #: high-water mark of ops_behind over the peer's lifetime
    peak_ops_behind: int = 0
    #: monitor round of the last clean (fully merged) exchange; -1 = never
    last_clean_round: int = -1
    #: monitor round of the last frontier observation (clean or not)
    last_seen_round: int = -1
    exchanges: int = 0
    #: consecutive failed exchange attempts (reset by any clean exchange)
    failures: int = 0
    #: the peer has EVER probed divergent (latched: divergence is an
    #: incident to investigate, not a state a later round silently repairs)
    divergent: bool = False
    last_outcome: str = "never"
    #: why the most recent exchange attempt failed (cleared by a clean
    #: exchange) — the fleet view's answer to "stale peer, but WHY"
    last_error: Optional[str] = None

    def staleness(self, rounds: int) -> int:
        """Rounds since the last clean exchange (``rounds`` = monitor now);
        a never-reached peer is stale for the monitor's whole lifetime."""
        if self.last_clean_round < 0:
            return rounds
        return max(0, rounds - self.last_clean_round)

    def to_json(self, rounds: int) -> Dict[str, Any]:
        return {
            "ops_behind": self.ops_behind,
            "ops_ahead": self.ops_ahead,
            "peak_ops_behind": self.peak_ops_behind,
            "staleness_rounds": self.staleness(rounds),
            "exchanges": self.exchanges,
            "failures": self.failures,
            "divergent": self.divergent,
            "last_outcome": self.last_outcome,
            "last_error": self.last_error,
        }


@dataclass
class DivergenceIncident:
    """Evidence of one same-frontier/different-digest probe."""

    peer: str
    round: int
    local_digest: int
    peer_digest: int
    clock_size: int


class ConvergenceMonitor:
    """Per-peer lag watermarks + divergence probes over frontier exchanges.

    Thread-safe: transport handler threads (``_serve_one``), client sync
    threads and the exporter scrape concurrently.  ``recorder`` (a
    :class:`~.recorder.FlightRecorder`) receives a ``fault`` record — and
    therefore an automatic ring dump — on every divergence incident.
    """

    def __init__(self, host: str = "local",
                 recorder=None,
                 counters: Optional[Counters] = None) -> None:
        self.host = host
        self.recorder = recorder
        self.counters = counters if counters is not None else GLOBAL_COUNTERS
        self._lock = threading.Lock()
        self._peers: Dict[str, PeerLag] = {}
        self.rounds = 0
        self.divergence_incidents: List[DivergenceIncident] = []

    # -- ingestion (the transport hooks) ------------------------------------

    def advance_round(self) -> int:
        """Tick the monitor's round clock — the staleness unit.  Called by
        the gossip scheduler once per scheduling round (standalone syncs
        may call it per exchange batch)."""
        with self._lock:
            self.rounds += 1
            return self.rounds

    def peer(self, name: str) -> PeerLag:
        with self._lock:
            return self._peer_locked(name)

    def _peer_locked(self, name: str) -> PeerLag:
        rec = self._peers.get(name)
        if rec is None:
            rec = self._peers[name] = PeerLag(peer=name)
        return rec

    def observe_frontier(
        self,
        peer: str,
        local_clock: Dict[str, int],
        peer_clock: Dict[str, int],
        local_digest: Optional[int] = None,
        peer_digest: Optional[int] = None,
    ) -> str:
        """Ingest one frontier observation (mid-exchange is fine: a slow
        link that dies after the frontier still taught us the peer's
        position).  Returns the classification: ``lag``, ``converged``, or
        ``divergence`` — the last meaning the frontiers MATCH but the
        commutative digests differ, which mere lag can never produce."""
        behind = clock_delta_ops(local_clock, peer_clock)
        ahead = clock_delta_ops(peer_clock, local_clock)
        matched = clocks_equal(local_clock, peer_clock)
        divergent = (
            matched
            and local_digest is not None
            and peer_digest is not None
            and int(local_digest) != int(peer_digest)
        )
        with self._lock:
            rec = self._peer_locked(peer)
            rec.exchanges += 1
            rec.ops_behind = behind
            rec.ops_ahead = ahead
            rec.peak_ops_behind = max(rec.peak_ops_behind, behind)
            rec.last_seen_round = self.rounds
            if divergent:
                rec.divergent = True
                rec.last_outcome = DIVERGENCE
                incident = DivergenceIncident(
                    peer=peer, round=self.rounds,
                    local_digest=int(local_digest),
                    peer_digest=int(peer_digest),
                    clock_size=len(peer_clock),
                )
                self.divergence_incidents.append(incident)
            else:
                rec.last_outcome = CONVERGED if matched else LAG
        self.counters.add("convergence.frontier_exchanges")
        if divergent:
            self.counters.add("convergence.divergence_incidents")
            if self.recorder is not None:
                # first-class incident: the flight recorder turns "digests
                # differ at an equal frontier" into a post-mortem dump
                self.recorder.fault(
                    "divergence", peer=peer, host=self.host,
                    local_digest=int(local_digest),
                    peer_digest=int(peer_digest),
                    round=self.rounds,
                )
            return DIVERGENCE
        return CONVERGED if matched else LAG

    def observe_success(self, peer: str, pulled: int = 0,
                        pushed: int = 0) -> None:
        """One CLEAN bidirectional exchange completed: the pull drained the
        observed lag, so the behind estimate zeroes and staleness resets."""
        with self._lock:
            rec = self._peer_locked(peer)
            rec.ops_behind = 0
            rec.ops_ahead = 0
            rec.failures = 0
            rec.last_error = None
            rec.last_clean_round = self.rounds
            rec.last_seen_round = self.rounds
            if rec.last_outcome != DIVERGENCE:
                rec.last_outcome = CONVERGED
        self.counters.add("convergence.clean_exchanges")
        if pulled:
            self.counters.add("convergence.ops_drained", pulled)
        if pushed:
            self.counters.add("convergence.ops_shipped", pushed)

    def observe_failure(self, peer: str, error: Optional[str] = None) -> None:
        """The exchange attempt failed (behind outcome): the peer keeps its
        last lag estimate, staleness keeps growing, failures count up (the
        gossip scheduler's backoff input)."""
        with self._lock:
            rec = self._peer_locked(peer)
            rec.failures += 1
            rec.last_outcome = "behind"
            rec.last_error = error
        self.counters.add("convergence.failed_exchanges")

    # -- readout (the exporter/scheduler surface) ---------------------------

    def peers(self) -> Dict[str, PeerLag]:
        with self._lock:
            return dict(self._peers)

    def behindness(self, peer: str) -> tuple:
        """The gossip scheduler's priority key for one peer, higher = more
        urgent: (ops_behind estimate, staleness rounds)."""
        with self._lock:
            rec = self._peers.get(peer)
            if rec is None:
                return (0, self.rounds)
            return (rec.ops_behind, rec.staleness(self.rounds))

    def total_lag_ops(self) -> int:
        with self._lock:
            return sum(r.ops_behind for r in self._peers.values())

    def divergent_peers(self) -> List[str]:
        with self._lock:
            return sorted(
                name for name, r in self._peers.items() if r.divergent
            )

    def snapshot(self) -> Dict[str, Any]:
        """JSON-serializable readout — the ``/convergence.json`` body and
        the ``health_snapshot(convergence=...)`` composition (the exporter
        golden-shape test pins these keys)."""
        with self._lock:
            rounds = self.rounds
            peers = {
                name: rec.to_json(rounds)
                for name, rec in sorted(self._peers.items())
            }
            incidents = len(self.divergence_incidents)
        return {
            "host": self.host,
            "rounds": rounds,
            "peers": peers,
            "total_lag_ops": sum(p["ops_behind"] for p in peers.values()),
            "divergence_incidents": incidents,
            "divergent_peers": sorted(
                name for name, p in peers.items() if p["divergent"]
            ),
        }
