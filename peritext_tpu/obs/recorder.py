"""Flight recorder: a bounded ring of recent spans+events per session,
dumped as JSONL when something goes wrong.

Chaos-soak failures used to be shrugs — a digest mismatch with no record of
which round did what.  The recorder keeps the last ``capacity`` telemetry
records (finished spans via :meth:`record_span` — wire it as a
:class:`~.spans.Tracer` sink — plus structured fault events) and writes the
whole ring to a JSONL file on :meth:`fault` (quarantine, rollback,
transport give-up; throttled) or an explicit :meth:`dump`.  Each line is
one JSON record; a ``kind: "dump"`` header line carries the reason, so a
post-mortem starts from ``python -m peritext_tpu.obs summary <dump>``.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
from collections import deque
from pathlib import Path
from typing import Dict, List, Optional

#: process-wide dump numbering: several recorders sharing one dump_dir
#: (e.g. a crash-restored supervisor reusing <ckpt>/flight) must never
#: mint colliding default filenames — an overwritten dump is exactly the
#: post-mortem the recorder exists to preserve
_DUMP_IDS = itertools.count(1)


class FlightRecorder:
    """Bounded telemetry ring with fault-triggered JSONL dumps.

    ``dump_dir`` enables automatic dumps on :meth:`fault` (at most one per
    ``min_dump_interval`` seconds — a burst of quarantines produces one
    post-mortem, not a disk flood).  ``fsync=True`` fsyncs each dump before
    returning: the flight-recorder path exists for crashes, and a dump that
    dies in the page cache recorded nothing.
    """

    def __init__(self, capacity: int = 1024,
                 dump_dir: Optional[str | Path] = None,
                 fsync: bool = False,
                 min_dump_interval: float = 1.0) -> None:
        if capacity <= 0:
            raise ValueError(f"capacity must be positive, got {capacity}")
        self.capacity = capacity
        self.dump_dir = Path(dump_dir) if dump_dir is not None else None
        self.fsync = bool(fsync)
        self.min_dump_interval = float(min_dump_interval)
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=capacity)
        self._seq = 0
        self._last_auto_dump: Optional[float] = None
        self.faults = 0
        self.dumps = 0
        self.last_dump_path: Optional[Path] = None

    # -- recording -----------------------------------------------------------

    def record(self, kind: str, **fields) -> Dict:
        """Append one structured record to the ring."""
        with self._lock:
            self._seq += 1
            rec = {"seq": self._seq, "ts": time.time(), "kind": kind, **fields}
            self._ring.append(rec)
        return rec

    def record_span(self, span) -> None:
        """Tracer-sink form: ``tracer.add_sink(recorder.record_span)``."""
        self.record("span", **span.to_json())

    def fault(self, reason: str, **fields) -> Dict:
        """Record a fault event and (when a ``dump_dir`` is configured)
        dump the ring — the quarantine/rollback/transport-give-up hook."""
        self.faults += 1
        rec = self.record("fault", reason=reason, **fields)
        if self.dump_dir is not None:
            now = time.monotonic()
            if (self._last_auto_dump is None
                    or now - self._last_auto_dump >= self.min_dump_interval):
                self._last_auto_dump = now
                try:
                    self.dump(reason=reason)
                except OSError:
                    # graftlint: boundary(a full/readonly disk must not turn a contained fault into a crash; the ring stays queryable in memory)
                    pass
        return rec

    # -- dumping -------------------------------------------------------------

    def entries(self) -> List[Dict]:
        with self._lock:
            return list(self._ring)

    def dump(self, path: Optional[str | Path] = None,
             reason: Optional[str] = None) -> Path:
        """Write the ring to ``path`` (default: a fresh
        ``flight-<pid>-<n>-<reason>.jsonl`` under ``dump_dir``, where
        ``<n>`` is process-unique so recorders sharing the directory never
        overwrite each other's post-mortems) as JSONL; returns the path
        written."""
        entries = self.entries()
        if path is None:
            if self.dump_dir is None:
                raise ValueError("no dump path given and no dump_dir configured")
            self.dump_dir.mkdir(parents=True, exist_ok=True)
            tag = (reason or "manual").replace("/", "_").replace(" ", "_")
            path = self.dump_dir / (
                f"flight-{os.getpid()}-{next(_DUMP_IDS):06d}-{tag}.jsonl"
            )
        path = Path(path)
        path.parent.mkdir(parents=True, exist_ok=True)
        header = {"kind": "dump", "ts": time.time(), "reason": reason,
                  "records": len(entries), "capacity": self.capacity}
        with open(path, "w") as f:
            f.write(json.dumps(header, default=str) + "\n")
            for rec in entries:
                f.write(json.dumps(rec, default=str) + "\n")
            f.flush()
            if self.fsync:
                os.fsync(f.fileno())
        self.dumps += 1
        self.last_dump_path = path
        return path

    def snapshot(self) -> Dict:
        """Health-endpoint summary (JSON-serializable)."""
        with self._lock:
            size = len(self._ring)
        return {
            "capacity": self.capacity,
            "size": size,
            "faults": self.faults,
            "dumps": self.dumps,
            "last_dump": str(self.last_dump_path) if self.last_dump_path else None,
        }
