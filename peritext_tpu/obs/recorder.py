"""Flight recorder: a bounded ring of recent spans+events per session,
dumped as JSONL when something goes wrong.

Chaos-soak failures used to be shrugs — a digest mismatch with no record of
which round did what.  The recorder keeps the last ``capacity`` telemetry
records (finished spans via :meth:`record_span` — wire it as a
:class:`~.spans.Tracer` sink — plus structured fault events) and writes the
whole ring to a JSONL file on :meth:`fault` (quarantine, rollback,
transport give-up; throttled) or an explicit :meth:`dump`.  Each line is
one JSON record; a ``kind: "dump"`` header line carries the reason, so a
post-mortem starts from ``python -m peritext_tpu.obs summary <dump>``.

Fault dumps can carry INCIDENT CONTEXT beyond the ring: register a
provider with :meth:`add_context_provider` and every fault-triggered dump
appends its output as ``kind: "context"`` records.  The serve mux
registers one mapping a quarantine/rollback fault's ``doc`` to that doc's
recent admission-verdict tail, so a post-mortem sees the backpressure
picture around the incident, not just the span ring.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
from collections import deque
from pathlib import Path
from typing import Callable, Dict, List, Optional

#: process-wide dump numbering: several recorders sharing one dump_dir
#: (e.g. a crash-restored supervisor reusing <ckpt>/flight) must never
#: mint colliding default filenames — an overwritten dump is exactly the
#: post-mortem the recorder exists to preserve
_DUMP_IDS = itertools.count(1)


class FlightRecorder:
    """Bounded telemetry ring with fault-triggered JSONL dumps.

    ``dump_dir`` enables automatic dumps on :meth:`fault` (at most one per
    ``min_dump_interval`` seconds — a burst of quarantines produces one
    post-mortem, not a disk flood).  ``fsync=True`` fsyncs each dump before
    returning: the flight-recorder path exists for crashes, and a dump that
    dies in the page cache recorded nothing.
    """

    def __init__(self, capacity: int = 1024,
                 dump_dir: Optional[str | Path] = None,
                 fsync: bool = False,
                 min_dump_interval: float = 1.0,
                 host: Optional[str] = None) -> None:
        if capacity <= 0:
            raise ValueError(f"capacity must be positive, got {capacity}")
        self.capacity = capacity
        #: host label minted into dump filenames
        #: (``flight-<host>-<pid>-<n>-<reason>.jsonl``) so a cross-host
        #: merge (:func:`~.incidents.merge_flight_dumps`) attributes every
        #: record WITHOUT parsing dump bodies
        self.host = host
        self.dump_dir = Path(dump_dir) if dump_dir is not None else None
        self.fsync = bool(fsync)
        self.min_dump_interval = float(min_dump_interval)
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=capacity)
        self._seq = 0
        self._last_auto_dump: Optional[float] = None
        self.faults = 0
        self.dumps = 0
        self.last_dump_path: Optional[Path] = None
        #: name -> fn(fault_fields) returning a dict, a list of dicts, or
        #: None; outputs land in fault dumps as ``kind: "context"`` records
        self._context_providers: Dict[str, Callable] = {}

    # -- recording -----------------------------------------------------------

    def record(self, kind: str, **fields) -> Dict:
        """Append one structured record to the ring."""
        with self._lock:
            self._seq += 1
            rec = {"seq": self._seq, "ts": time.time(), "kind": kind, **fields}
            self._ring.append(rec)
        return rec

    def record_span(self, span) -> None:
        """Tracer-sink form: ``tracer.add_sink(recorder.record_span)``."""
        self.record("span", **span.to_json())

    def add_context_provider(self, name: str, fn: Callable) -> None:
        """Register ``fn(fault_fields) -> dict | list[dict] | None`` to be
        consulted on every fault-triggered dump; its output is appended to
        the dump as ``kind: "context"`` records labelled ``provider=name``.
        Re-registering a name replaces the provider (a rebuilt mux swaps
        its hook in place)."""
        with self._lock:
            self._context_providers[name] = fn

    def fault(self, reason: str, **fields) -> Dict:
        """Record a fault event and (when a ``dump_dir`` is configured)
        dump the ring — the quarantine/rollback/transport-give-up hook.
        The fault's fields are offered to every context provider, so the
        dump carries the incident's surroundings (e.g. the affected doc's
        admission-verdict tail), not just the telemetry ring."""
        self.faults += 1
        rec = self.record("fault", reason=reason, **fields)
        if self.dump_dir is not None:
            now = time.monotonic()
            if (self._last_auto_dump is None
                    or now - self._last_auto_dump >= self.min_dump_interval):
                self._last_auto_dump = now
                try:
                    self.dump(reason=reason, context=dict(fields))
                except OSError:
                    # graftlint: boundary(a full/readonly disk must not turn a contained fault into a crash; the ring stays queryable in memory)
                    pass
        return rec

    # -- dumping -------------------------------------------------------------

    def entries(self) -> List[Dict]:
        with self._lock:
            return list(self._ring)

    def _context_records(self, fields: Dict) -> List[Dict]:
        """Run every context provider against one fault's fields; cap the
        total so a runaway provider can't flood a dump."""
        with self._lock:
            providers = list(self._context_providers.items())
        out: List[Dict] = []
        for name, fn in providers:
            try:
                got = fn(fields)
            except Exception:  # graftlint: boundary(a broken context provider must not lose the dump it decorates)
                continue
            if got is None:
                continue
            records = got if isinstance(got, list) else [got]
            for rec in records:
                if not isinstance(rec, dict):
                    continue
                # envelope keys WIN: a provider record carrying its own
                # ``kind`` (e.g. an admission verdict) must not break the
                # dump reader's kind=="context" filter
                out.append({**rec, "kind": "context", "provider": name})
                if len(out) >= 128:
                    return out
        return out

    def dump(self, path: Optional[str | Path] = None,
             reason: Optional[str] = None,
             context: Optional[Dict] = None) -> Path:
        """Write the ring to ``path`` (default: a fresh
        ``flight-<host>-<pid>-<n>-<reason>.jsonl`` under ``dump_dir``, where
        ``<n>`` is process-unique so recorders sharing the directory never
        overwrite each other's post-mortems) as JSONL; returns the path
        written.  ``context`` (the triggering fault's fields) activates the
        registered context providers, whose records are appended after the
        ring."""
        entries = self.entries()
        if context is not None:
            entries = entries + self._context_records(context)
        if path is None:
            if self.dump_dir is None:
                raise ValueError("no dump path given and no dump_dir configured")
            self.dump_dir.mkdir(parents=True, exist_ok=True)
            tag = (reason or "manual").replace("/", "_").replace(" ", "_")
            host = (self.host or "local").replace("/", "_").replace(" ", "_")
            path = self.dump_dir / (
                f"flight-{host}-{os.getpid()}-{next(_DUMP_IDS):06d}-{tag}.jsonl"
            )
        path = Path(path)
        path.parent.mkdir(parents=True, exist_ok=True)
        header = {"kind": "dump", "ts": time.time(), "reason": reason,
                  "records": len(entries), "capacity": self.capacity}
        with open(path, "w") as f:
            f.write(json.dumps(header, default=str) + "\n")
            for rec in entries:
                f.write(json.dumps(rec, default=str) + "\n")
            f.flush()
            if self.fsync:
                os.fsync(f.fileno())
        self.dumps += 1
        self.last_dump_path = path
        return path

    def snapshot(self) -> Dict:
        """Health-endpoint summary (JSON-serializable)."""
        with self._lock:
            size = len(self._ring)
        return {
            "capacity": self.capacity,
            "size": size,
            "faults": self.faults,
            "dumps": self.dumps,
            "last_dump": str(self.last_dump_path) if self.last_dump_path else None,
        }
